"""overload-bench — goodput-vs-offered-load curve over a live
3-replica cluster (ISSUE 10; the overload mirror of chaos_bench.py).

The headline question of admission control: when offered load exceeds
capacity, does goodput COLLAPSE (every statement times out together)
or DEGRADE (admitted statements finish near peak rate, the excess is
shed fast with a structured `E_OVERLOAD` + retry-after, and control
statements still answer)?

Method: stand up a LocalCluster (1 metad / 3 storaged / 1 graphd),
calibrate 1× capacity with a closed-loop probe, then sweep offered
load at 1× / 2× / 4× via concurrency multiplication (each level runs
`calibration threads × level` closed-loop workers — the standard way
to push a blocking client past saturation).  Admission is armed for
the sweep (`max_running_queries`, `admission_queue_capacity`,
`rpc_server_inbox_capacity`); a control thread issues SHOW QUERIES
throughout and its latency is reported separately (the priority lane's
proof).  Per level:

  goodput_qps      statements that returned rows, per second
  shed             E_OVERLOAD results + admission/inbox shed counters
  admitted_p99_ms  latency of successful statements
  control_p99_ms   SHOW QUERIES latency DURING the level's saturation
  hints_ok         every observed E_OVERLOAD carried retry_after_ms

Usage:
    python -m nebula_tpu.tools.overload_bench
    python -m nebula_tpu.tools.overload_bench --persons 4000 --duration 5
    python -m nebula_tpu.tools.overload_bench --read-scaleout

Emits one JSON object on stdout; bench.py folds the curve into its
`overload` block (goodput_4x_vs_1x is the acceptance number: ≥ 0.7).

`--read-scaleout` (ISSUE 11) runs the goodput-vs-replica-count sweep
instead — 1 storaged / rf=1 leader-only vs 3 storaged / rf=3 at
follower consistency under the same per-replica read capacity
(`storage_read_capacity_qps`); bench.py folds it into `read_scaleout`
(qps_3r_vs_1r is the acceptance number: ≥ 2.0).

`--fleet` (ISSUE 20) runs the coordinator scale-out + fleet QoS sweep
instead — a 10k-session storm over 3 graphds, then the same mixed
GO/MATCH offered load against 1 coordinator vs the fleet of 3 under
the same per-coordinator statement capacity
(`graph_statement_capacity_qps`), then a scarce-slot DWRR phase with
an aggressor tenant; bench.py folds it into `fleet` (fleet_goodput_x
is the acceptance number: ≥ 2.5, plus dwrr_share_held).
"""
from __future__ import annotations

import argparse
import json
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional


def _percentile(sorted_xs: List[float], p: float) -> float:
    if not sorted_xs:
        return 0.0
    return sorted_xs[min(len(sorted_xs) - 1,
                         int(len(sorted_xs) * p / 100.0))]


def _stat_totals(prefixes) -> Dict[str, float]:
    from nebula_tpu.utils.stats import stats
    snap = stats().snapshot()
    out = {}
    for pfx in prefixes:
        out[pfx] = sum(v for k, v in snap.items()
                       if k.startswith(pfx) and not k.endswith("_us")
                       and ".sum" not in k and ".count" not in k
                       and ".bucket" not in k)
    return out


_SHED_COUNTERS = ("admission_shed", "overload_server_rejections")


def _seed_graph(cluster, space: str, persons: int, degree: int,
                replica_factor: int, rng_seed: int):
    """Shared sweep fixture: create a Person/KNOWS space and load the
    seeded random small-GO graph (one copy of the chunked-INSERT
    recipe for the offered-load, read-scaleout and batching sweeps —
    each keeps its own rng seed so historical bench shapes hold)."""
    import numpy as np
    cl = cluster.client()
    assert cl.execute(
        f"CREATE SPACE {space}(partition_num=8, "
        f"replica_factor={replica_factor}, vid_type=INT64)").error is None
    cluster.reconcile_storage()
    for q in (f"USE {space}", "CREATE TAG Person(age int)",
              "CREATE EDGE KNOWS(w int)"):
        assert cl.execute(q).error is None, q
    rng = np.random.default_rng(rng_seed)
    B = 400
    for lo in range(0, persons, B):
        vals = ", ".join(f"{v}:({v % 90})"
                         for v in range(lo, min(lo + B, persons)))
        assert cl.execute(
            f"INSERT VERTEX Person(age) VALUES {vals}").error is None
    src = rng.integers(0, persons, persons * degree)
    dst = rng.integers(0, persons, persons * degree)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    for lo in range(0, src.size, B):
        vals = ", ".join(f"{s}->{d}:({int(s + d) % 100})"
                         for s, d in zip(src[lo:lo + B].tolist(),
                                         dst[lo:lo + B].tolist()))
        assert cl.execute(
            f"INSERT EDGE KNOWS(w) VALUES {vals}").error is None
    cl.close()


class _LevelResult:
    def __init__(self):
        self.lats: List[float] = []
        self.ok = 0
        self.shed_results = 0
        self.errors: List[str] = []
        self.hints_missing = 0
        self.lock = threading.Lock()


def _worker(cluster, space: str, stmt_of, duration_s: float, wid: int,
            res: _LevelResult):
    from nebula_tpu.utils.admission import is_overload, parse_retry_after
    try:
        cl = cluster.client()
        cl.execute(f"USE {space}")
    except Exception as ex:  # noqa: BLE001 — saturation may refuse conns
        with res.lock:
            res.errors.append(f"connect: {ex!r}")
        return
    end = time.monotonic() + duration_s
    j = 0
    while time.monotonic() < end:
        t0 = time.perf_counter()
        try:
            r = cl.execute(stmt_of(wid, j))
        except Exception as ex:  # noqa: BLE001
            with res.lock:
                res.errors.append(repr(ex))
            break
        dt = time.perf_counter() - t0
        with res.lock:
            if r.error is None:
                res.ok += 1
                res.lats.append(dt)
            elif is_overload(r.error):
                res.shed_results += 1
                if parse_retry_after(r.error) is None:
                    res.hints_missing += 1
            else:
                res.errors.append(r.error)
        j += 1
    try:
        cl.close()
    except Exception:  # noqa: BLE001
        pass


def _control_probe(cluster, stop: threading.Event, out: Dict):
    """SHOW QUERIES every 50ms on its own session — the priority lane
    must answer while the data plane saturates."""
    lats: List[float] = []
    errs = 0
    try:
        cl = cluster.client()
    except Exception:  # noqa: BLE001
        out["control_errors"] = -1
        return
    while not stop.wait(0.05):
        t0 = time.perf_counter()
        try:
            r = cl.execute("SHOW LOCAL QUERIES")
            if r.error is not None:
                errs += 1
            else:
                lats.append(time.perf_counter() - t0)
        except Exception:  # noqa: BLE001
            errs += 1
    try:
        cl.close()
    except Exception:  # noqa: BLE001
        pass
    lats.sort()
    out["control_p50_ms"] = round(_percentile(lats, 50) * 1e3, 2)
    out["control_p99_ms"] = round(_percentile(lats, 99) * 1e3, 2)
    out["control_probes"] = len(lats)
    out["control_errors"] = errs


def run_sweep(persons: int = 1200, degree: int = 5,
              cal_threads: int = 6, duration_s: float = 3.0,
              levels=(1, 2, 4), slots: Optional[int] = None,
              queue_capacity: Optional[int] = None,
              inbox_capacity: int = 0,
              tpu_runtime=None, data_dir: Optional[str] = None) -> dict:
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.admission import admission
    from nebula_tpu.utils.config import get_config

    space = "ovld"
    tmp = data_dir or tempfile.mkdtemp(prefix="nebula_overload_")
    cluster = LocalCluster(n_meta=1, n_storage=3, n_graph=1,
                           data_dir=tmp, tpu_runtime=tpu_runtime)
    cfg = get_config()
    dyn_keys = ("max_running_queries", "admission_queue_capacity",
                "rpc_server_inbox_capacity", "query_timeout_secs")
    try:
        _seed_graph(cluster, space, persons, degree,
                    replica_factor=3, rng_seed=31)
        cl = cluster.client()
        cl.execute(f"USE {space}")

        def stmt_of(wid: int, j: int) -> str:
            seed = (wid * 131 + j * 17) % persons
            return f"GO FROM {seed} OVER KNOWS YIELD dst(edge) AS d"

        # warm the plan cache / device plane before calibrating
        warm = cluster.client()
        warm.execute(f"USE {space}")
        warm.execute(stmt_of(0, 0))
        warm.close()

        # ---- calibrate 1× capacity: closed loop, admission OFF ------
        cal = _LevelResult()
        ths = [threading.Thread(target=_worker,
                                args=(cluster, space, stmt_of,
                                      duration_s, i, cal))
               for i in range(cal_threads)]
        t0 = time.perf_counter()
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        cal_wall = time.perf_counter() - t0
        qps_1x = cal.ok / cal_wall if cal_wall > 0 else 0.0
        assert not cal.errors, cal.errors[:3]

        # ---- arm the overload plane for the sweep -------------------
        n_slots = slots if slots is not None else max(cal_threads, 2)
        n_cap = queue_capacity if queue_capacity is not None \
            else 2 * n_slots
        cfg.set_dynamic_many({
            "max_running_queries": n_slots,
            "admission_queue_capacity": n_cap,
            "rpc_server_inbox_capacity": inbox_capacity,
            # bounded budgets keep a saturated level from running away:
            # queued statements are deadline-evicted, client overload
            # retries stay inside this budget
            "query_timeout_secs": max(duration_s * 2, 5.0),
        })

        out_levels: Dict[str, dict] = {}
        for level in levels:
            res = _LevelResult()
            shed0 = _stat_totals(_SHED_COUNTERS)
            stop = threading.Event()
            ctl: Dict = {}
            ctl_t = threading.Thread(target=_control_probe,
                                     args=(cluster, stop, ctl))
            ctl_t.start()
            n_workers = cal_threads * level
            ths = [threading.Thread(target=_worker,
                                    args=(cluster, space, stmt_of,
                                          duration_s, i, res))
                   for i in range(n_workers)]
            t0 = time.perf_counter()
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            wall = time.perf_counter() - t0
            stop.set()
            ctl_t.join()
            shed1 = _stat_totals(_SHED_COUNTERS)
            res.lats.sort()
            attempts = res.ok + res.shed_results + len(res.errors)
            row = {
                "workers": n_workers,
                "wall_s": round(wall, 2),
                "attempted_qps": round(attempts / wall, 1) if wall else 0,
                "goodput_qps": round(res.ok / wall, 1) if wall else 0,
                "ok": res.ok,
                "shed_results": res.shed_results,
                "shed_counters": {
                    k: int(shed1[k] - shed0[k]) for k in shed1},
                "other_errors": len(res.errors),
                "error_sample": res.errors[:3],
                "admitted_p50_ms": round(
                    _percentile(res.lats, 50) * 1e3, 2),
                "admitted_p99_ms": round(
                    _percentile(res.lats, 99) * 1e3, 2),
                # the E_OVERLOAD contract: every shed carries a hint
                "hints_ok": res.hints_missing == 0,
            }
            row.update(ctl)
            out_levels[f"{level}x"] = row

        g1 = out_levels[f"{levels[0]}x"]["goodput_qps"]
        g4 = out_levels[f"{levels[-1]}x"]["goodput_qps"]
        return {
            "persons": persons,
            "degree": degree,
            "replica_factor": 3,
            "statement": "1-hop GO (small-query admission shape)",
            "calibration": {"threads": cal_threads,
                            "qps": round(qps_1x, 1),
                            "p50_ms": round(
                                _percentile(sorted(cal.lats), 50) * 1e3,
                                2)},
            "slots": n_slots,
            "queue_capacity": n_cap,
            "inbox_capacity": inbox_capacity,
            "duration_per_level_s": duration_s,
            "levels": out_levels,
            # the acceptance number: offered 4×, goodput vs the 1× level
            "goodput_4x_vs_1x": round(g4 / g1, 3) if g1 else None,
        }
    finally:
        with cfg.lock:
            for k in dyn_keys:
                cfg.dynamic_layer.pop(k, None)
        admission().reset()
        cluster.stop()
        if data_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


# -- batched-dispatch A/B sweep (ISSUE 15) ----------------------------------


def _hist_sum(snap: Dict[str, float], name: str) -> float:
    return sum(v for k, v in snap.items()
               if k.startswith(name) and k.endswith(".sum"))


def _hist_count(snap: Dict[str, float], name: str) -> float:
    return sum(v for k, v in snap.items()
               if k.startswith(name) and k.endswith(".count"))


def batch_sweep(persons: int = 1200, degree: int = 5,
                threads: int = 8, duration_s: float = 3.0,
                levels=(1, 2, 4), lanes: int = 16,
                wait_us: int = 8000, tpu_runtime=None,
                data_dir: Optional[str] = None) -> dict:
    """Multi-lane batched dispatch A/B (ISSUE 15 acceptance): the SAME
    small-GO closed-loop offered-load sweep with batching OFF
    (`batch_max_lanes=0`, the byte-identical off switch) and ON, on a
    live 3-replica cluster whose graphd runs the device plane.  Per
    (mode, level):

      goodput_qps           statements that returned rows, per second
      dispatches_per_stmt   Δ tpu_kernel_runs / ok — the sharing proof
                            (< 1 means statements shared launches)
      queue_wait_share      Δ tpu_dispatch_queue_us.sum / Σ statement
                            latency — the PR 7 number batching exists
                            to shrink
      batches / mean_lanes  Δ tpu_batches_formed, mean lanes per batch
      form_wait_p_mean_us   mean batch-forming wait per batched stmt

    Plus a rows-identity probe: a seed sample's rows with batching ON
    under concurrent company must equal the batching-OFF sequential
    truth byte-for-byte.  The headline `queue_wait_share_off_over_on`
    (≥ 2.0 target) and `dispatches_per_stmt_on` (< 0.5 target at the
    top level) land in bench.py's `batching` block."""
    import numpy as np

    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.admission import admission
    from nebula_tpu.utils.config import get_config
    from nebula_tpu.utils.stats import stats

    if tpu_runtime is None:
        try:
            from nebula_tpu.tpu import TpuRuntime, make_mesh
            tpu_runtime = TpuRuntime(make_mesh(1))
        except Exception as ex:  # noqa: BLE001 — no jax/device
            return {"error": f"no device runtime: {ex!r}"}

    space = "batch"
    tmp = data_dir or tempfile.mkdtemp(prefix="nebula_batch_")
    cluster = LocalCluster(n_meta=1, n_storage=3, n_graph=1,
                           data_dir=tmp, tpu_runtime=tpu_runtime)
    cfg = get_config()
    dyn_keys = ("batch_max_lanes", "batch_wait_us", "query_timeout_secs",
                "max_running_queries", "admission_queue_capacity")
    try:
        _seed_graph(cluster, space, persons, degree,
                    replica_factor=3, rng_seed=43)

        def stmt_of(wid: int, j: int) -> str:
            seed = (wid * 131 + j * 17) % persons
            return f"GO FROM {seed} OVER KNOWS YIELD dst(edge) AS d"

        warm = cluster.client()
        warm.execute(f"USE {space}")
        warm.execute(stmt_of(0, 0))
        warm.close()
        # admission armed for BOTH arms (fair A/B): its drain releases
        # queued statements in bursts — exactly the arrival bunching
        # the batch former converts into lanes (the ISSUE 15 hand-off)
        cfg.set_dynamic_many({
            "query_timeout_secs": max(duration_s * 8, 20.0),
            "max_running_queries": threads * 2,
            "admission_queue_capacity": threads * 16,
        })

        modes = {"off": {"batch_max_lanes": 0},
                 "on": {"batch_max_lanes": lanes,
                        "batch_wait_us": wait_us}}
        out_modes: Dict[str, dict] = {}
        for mode, flags in modes.items():
            cfg.set_dynamic_many(flags)
            out_levels: Dict[str, dict] = {}
            for level in levels:
                res = _LevelResult()
                s0 = stats().snapshot()
                n_workers = threads * level
                ths = [threading.Thread(target=_worker,
                                        args=(cluster, space, stmt_of,
                                              duration_s, i, res))
                       for i in range(n_workers)]
                t0 = time.perf_counter()
                for t in ths:
                    t.start()
                for t in ths:
                    t.join()
                wall = time.perf_counter() - t0
                s1 = stats().snapshot()
                res.lats.sort()
                runs = s1.get("tpu_kernel_runs", 0) \
                    - s0.get("tpu_kernel_runs", 0)
                qwait = _hist_sum(s1, "tpu_dispatch_queue_us") \
                    - _hist_sum(s0, "tpu_dispatch_queue_us")
                batches = s1.get("tpu_batches_formed", 0) \
                    - s0.get("tpu_batches_formed", 0)
                lanes_sum = _hist_sum(s1, "tpu_batch_lanes") \
                    - _hist_sum(s0, "tpu_batch_lanes")
                form_sum = _hist_sum(s1, "tpu_batch_form_wait_us") \
                    - _hist_sum(s0, "tpu_batch_form_wait_us")
                form_n = _hist_count(s1, "tpu_batch_form_wait_us") \
                    - _hist_count(s0, "tpu_batch_form_wait_us")
                total_us = sum(res.lats) * 1e6
                out_levels[f"{level}x"] = {
                    "workers": n_workers,
                    "wall_s": round(wall, 2),
                    "ok": res.ok,
                    "goodput_qps": round(res.ok / wall, 1)
                    if wall else 0,
                    "other_errors": len(res.errors),
                    "error_sample": res.errors[:3],
                    "p50_ms": round(_percentile(res.lats, 50) * 1e3, 2),
                    "p99_ms": round(_percentile(res.lats, 99) * 1e3, 2),
                    "device_launches": int(runs),
                    "dispatches_per_stmt": round(
                        runs / res.ok, 3) if res.ok else None,
                    "queue_wait_share": round(qwait / total_us, 4)
                    if total_us else 0.0,
                    "batches_formed": int(batches),
                    "mean_lanes": round(lanes_sum / batches, 2)
                    if batches else 0.0,
                    "form_wait_mean_us": round(form_sum / form_n, 1)
                    if form_n else 0.0,
                }
            out_modes[mode] = out_levels

        # -- rows-identity probe: ON under concurrency == OFF truth ---
        probe_seeds = [3, 7, 11, 13, 17]
        cfg.set_dynamic("batch_max_lanes", 0)
        pcl = cluster.client()
        pcl.execute(f"USE {space}")
        truth = {}
        for sd in probe_seeds:
            r = pcl.execute(f"GO FROM {sd} OVER KNOWS "
                            f"YIELD dst(edge) AS d")
            assert r.error is None, r.error
            truth[sd] = sorted(map(repr, r.data.rows))
        cfg.set_dynamic_many({"batch_max_lanes": lanes,
                              "batch_wait_us": max(wait_us, 20000)})
        got: Dict[int, list] = {}
        errs: List[str] = []

        def probe(sd):
            try:
                c2 = cluster.client()
                c2.execute(f"USE {space}")
                r = c2.execute(f"GO FROM {sd} OVER KNOWS "
                               f"YIELD dst(edge) AS d")
                if r.error is not None:
                    errs.append(r.error)
                else:
                    got[sd] = sorted(map(repr, r.data.rows))
                c2.close()
            except Exception as ex:  # noqa: BLE001
                errs.append(repr(ex))

        ths = [threading.Thread(target=probe, args=(sd,))
               for sd in probe_seeds]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        rows_identical = (not errs
                          and all(got.get(sd) == truth[sd]
                                  for sd in probe_seeds))
        top = f"{levels[-1]}x"
        q_off = out_modes["off"][top]["queue_wait_share"]
        q_on = out_modes["on"][top]["queue_wait_share"]
        g_on = {lv: out_modes["on"][f"{lv}x"]["goodput_qps"]
                for lv in levels}
        return {
            "persons": persons,
            "degree": degree,
            "statement": "1-hop GO (small-query device shape)",
            "threads_1x": threads,
            "duration_per_level_s": duration_s,
            "batch_max_lanes": lanes,
            "batch_wait_us": wait_us,
            "modes": out_modes,
            "rows_identical": rows_identical,
            "rows_probe_errors": errs[:3],
            # headlines: launches shared + queue wait collapsed +
            # goodput rising with offered load
            "dispatches_per_stmt_on":
                out_modes["on"][top]["dispatches_per_stmt"],
            "queue_wait_share_off_over_on": round(q_off / q_on, 2)
            if q_on else None,
            "goodput_rises_with_load": all(
                g_on[levels[i]] <= g_on[levels[i + 1]] * 1.05
                for i in range(len(levels) - 1)),
        }
    finally:
        with cfg.lock:
            for k in dyn_keys:
                cfg.dynamic_layer.pop(k, None)
        admission().reset()
        try:
            from nebula_tpu.tpu.batch import batch_former
            batch_former().reset()
        except Exception:  # noqa: BLE001 — no jax
            pass
        cluster.stop()
        if data_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


# -- read scale-out sweep (ISSUE 11) ----------------------------------------


def _read_level(cluster, space, stmt_of, threads: int,
                duration_s: float) -> _LevelResult:
    """One closed-loop read level: `threads` workers for `duration_s`."""
    res = _LevelResult()
    ths = [threading.Thread(target=_worker,
                            args=(cluster, space, stmt_of, duration_s,
                                  i, res))
           for i in range(threads)]
    t0 = time.perf_counter()
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    res.wall = time.perf_counter() - t0       # type: ignore[attr-defined]
    return res


def _seed_read_graph(cluster, space: str, persons: int, degree: int,
                     replica_factor: int):
    _seed_graph(cluster, space, persons, degree, replica_factor,
                rng_seed=47)


def read_scaleout_sweep(persons: int = 1000, degree: int = 5,
                        threads: int = 12, duration_s: float = 3.0,
                        read_capacity_qps: int = 120,
                        tpu_runtime=None,
                        data_dir: Optional[str] = None) -> dict:
    """Goodput-vs-replica-count on a read-heavy mix (ROADMAP item 5 /
    ISSUE 11 acceptance): the SAME offered read load and the SAME
    per-replica read capacity (`storage_read_capacity_qps` — a token
    bucket per storaged that sheds over-rate reads with the PR 8
    E_OVERLOAD + retry-after contract) against

      * a 1-storaged / replica_factor=1 cluster, leader-only reads —
        one replica's capacity is ALL the read capacity, and a shed
        client can only wait it out;
      * a 3-storaged / replica_factor=3 cluster at `follower`
        consistency — load-aware routing walks a shed read to a
        sibling replica with spare tokens, aggregating 3 replicas'
        capacity.

    The capacity model is explicit and honest: an in-process cluster
    shares one interpreter, so raw CPU throughput cannot scale with
    replica count on a small host — what CAN and does scale is
    admitted capacity, which is what replica scale-out buys a real
    deployment.  The acceptance number is `qps_3r_vs_1r` (bar:
    >= 2.0).  Also measured on the 3-replica cluster: read QPS per
    consistency level (capacity off — the pure CPU view), the
    follower-read share, time-to-first-successful-read after a hard
    leader kill, and the result cache serving a hot repeated read
    byte-identical to uncached execution."""
    import shutil as _shutil

    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.config import get_config
    from nebula_tpu.utils.stats import stats

    cfg = get_config()
    tmp = data_dir or tempfile.mkdtemp(prefix="nebula_readscale_")
    out: Dict[str, dict] = {}
    dyn_keys = ("storage_read_capacity_qps", "read_consistency",
                "result_cache_size", "query_timeout_secs")

    def stmt_of(wid: int, j: int) -> str:
        seed = (wid * 131 + j * 17) % persons
        return f"GO FROM {seed} OVER KNOWS YIELD dst(edge) AS d"

    try:
        for label, (n_storage, rf, level) in {
                "1r_leader": (1, 1, "leader"),
                "3r_follower": (3, 3, "follower")}.items():
            cluster = LocalCluster(n_meta=1, n_storage=n_storage,
                                   n_graph=1, data_dir=f"{tmp}/{label}",
                                   tpu_runtime=tpu_runtime)
            try:
                _seed_read_graph(cluster, "rs", persons, degree, rf)
                warm = cluster.client()
                warm.execute("USE rs")
                warm.execute(stmt_of(0, 0))
                warm.close()
                cfg.set_dynamic_many({
                    "storage_read_capacity_qps": read_capacity_qps,
                    "read_consistency": level,
                    "query_timeout_secs": max(duration_s * 4, 10.0),
                })
                fr0 = sum(v for k, v in stats().snapshot().items()
                          if k.startswith("follower_read_total"))
                res = _read_level(cluster, "rs", stmt_of, threads,
                                  duration_s)
                fr1 = sum(v for k, v in stats().snapshot().items()
                          if k.startswith("follower_read_total"))
                res.lats.sort()
                wall = getattr(res, "wall", duration_s)
                out[label] = {
                    "storageds": n_storage,
                    "replica_factor": rf,
                    "consistency": level,
                    "workers": threads,
                    "goodput_qps": round(res.ok / wall, 1) if wall else 0,
                    "ok": res.ok,
                    "errors": len(res.errors),
                    "error_sample": res.errors[:3],
                    "p50_ms": round(_percentile(res.lats, 50) * 1e3, 2),
                    "p99_ms": round(_percentile(res.lats, 99) * 1e3, 2),
                    "follower_read_share": round(
                        (fr1 - fr0) / max(res.ok, 1), 3),
                }
                if label != "3r_follower":
                    continue
                # -- per-consistency-level QPS on the 3-replica
                # cluster, capacity model OFF (the pure CPU view)
                with cfg.lock:
                    cfg.dynamic_layer.pop("storage_read_capacity_qps",
                                          None)
                per_level = {}
                for lvl in ("leader", "follower", "bounded_stale"):
                    cfg.set_dynamic("read_consistency", lvl)
                    r = _read_level(cluster, "rs", stmt_of,
                                    max(threads // 2, 2),
                                    max(duration_s / 2, 1.0))
                    w = getattr(r, "wall", 1.0)
                    per_level[lvl] = {
                        "qps": round(r.ok / w, 1) if w else 0,
                        "errors": len(r.errors)}
                out["qps_by_consistency"] = per_level
                # -- result cache: hot repeated read, byte-identical --
                cfg.set_dynamic_many({"read_consistency": "follower",
                                      "result_cache_size": 64})
                cl = cluster.client()
                cl.execute("USE rs")
                hot = stmt_of(1, 1)
                h0 = stats().snapshot().get("result_cache_hits", 0)
                r1 = cl.execute(hot)
                r2 = cl.execute(hot)
                h1 = stats().snapshot().get("result_cache_hits", 0)
                out["result_cache"] = {
                    "hits": int(h1 - h0),
                    "rows_identical": (
                        r1.error is None and r2.error is None
                        and sorted(map(tuple, r1.data.rows))
                        == sorted(map(tuple, r2.data.rows))),
                }
                with cfg.lock:
                    cfg.dynamic_layer.pop("result_cache_size", None)
                # -- time-to-first-successful-read after leader kill --
                lead = max(range(len(cluster.storageds)), key=lambda i: sum(
                    1 for pp in cluster.storageds[i].parts.values()
                    if pp.is_leader()))
                cl2 = cluster.client()
                cl2.execute("USE rs")
                cluster.stop_storaged(lead)
                t0 = time.perf_counter()
                ttfr = None
                deadline = time.perf_counter() + 30
                j = 0
                while time.perf_counter() < deadline:
                    r = cl2.execute(stmt_of(3, j))
                    j += 1
                    if r.error is None:
                        ttfr = time.perf_counter() - t0
                        break
                out["leader_kill"] = {
                    "time_to_first_read_ms": round(ttfr * 1e3, 1)
                    if ttfr is not None else None,
                }
                cl.close()
                cl2.close()
            finally:
                with cfg.lock:
                    for k in dyn_keys:
                        cfg.dynamic_layer.pop(k, None)
                cluster.stop()
        g1 = out["1r_leader"]["goodput_qps"]
        g3 = out["3r_follower"]["goodput_qps"]
        out["qps_3r_vs_1r"] = round(g3 / g1, 3) if g1 else None
        out["persons"] = persons
        out["degree"] = degree
        out["read_capacity_qps_per_replica"] = read_capacity_qps
        out["duration_per_level_s"] = duration_s
        return out
    finally:
        from nebula_tpu.utils.admission import admission
        admission().reset()
        if data_dir is None:
            _shutil.rmtree(tmp, ignore_errors=True)


# -- HTAP sweep (ISSUE 19): write storm + read storm A/B --------------------


def _htap_write_worker(cluster, space: str, stop: threading.Event,
                       wid: int, persons: int, res: _LevelResult):
    """Closed-loop writer: a stream of NEW edges (fresh ranks) through
    the graphd's group-commit path — the sustained write storm the
    delta plane exists to absorb."""
    cl = cluster.client()
    cl.execute(f"USE {space}")
    j = 0
    try:
        while not stop.is_set():
            s = (wid * 577 + j * 31) % persons
            d = (s + 7 + j) % persons
            r = cl.execute(f"INSERT EDGE KNOWS(w) VALUES "
                           f"{s}->{d}@{10_000 + wid * 100_000 + j}:"
                           f"({j % 100})")
            with res.lock:
                if r.error is None:
                    res.ok += 1
                else:
                    res.errors.append(r.error)
            j += 1
    finally:
        cl.close()


def _htap_read_worker(cluster, space: str, stop: threading.Event,
                      wid: int, persons: int, res: _LevelResult):
    """Closed-loop reader under the write storm: small device-shaped
    GOs; latency lands in res.lats (its p99 is the equal-staleness
    goodput comparison's denominator)."""
    cl = cluster.client()
    cl.execute(f"USE {space}")
    j = 0
    try:
        while not stop.is_set():
            seed = (wid * 131 + j * 17) % persons
            t0 = time.perf_counter()
            r = cl.execute(f"GO FROM {seed} OVER KNOWS "
                           f"YIELD dst(edge) AS d")
            dt = time.perf_counter() - t0
            with res.lock:
                if r.error is None:
                    res.ok += 1
                    res.lats.append(dt)
                else:
                    res.errors.append(r.error)
            j += 1
    finally:
        cl.close()


def _htap_staleness_probe(cluster, space: str, stop: threading.Event,
                          persons: int, lags: List[float],
                          errors: List[str]):
    """Ack-to-visible staleness: insert a marker edge to a brand-new
    dst vid, then poll a 1-hop GO from its src until the marker shows.
    The lag is ack -> first read that RETURNS the row — exactly the
    read-your-writes floor a fresh-read client experiences."""
    cl = cluster.client()
    cl.execute(f"USE {space}")
    k = 0
    try:
        while not stop.is_set():
            src = (37 * k) % persons
            marker = persons + 100_000 + k     # vid no other writer uses
            r = cl.execute(f"INSERT EDGE KNOWS(w) VALUES "
                           f"{src}->{marker}:(1)")
            if r.error is not None:
                errors.append(r.error)
                time.sleep(0.05)
                continue
            t_ack = time.perf_counter()
            while not stop.is_set():
                rr = cl.execute(f"GO FROM {src} OVER KNOWS "
                                f"YIELD dst(edge) AS d")
                if rr.error is None and any(
                        row[0] == marker for row in rr.data.rows):
                    lags.append(time.perf_counter() - t_ack)
                    break
            k += 1
            time.sleep(0.02)
    finally:
        cl.close()


def htap_sweep(persons: int = 900, degree: int = 4, writers: int = 2,
               readers: int = 6, duration_s: float = 3.0,
               delta_cap: int = 2048, tpu_runtime=None,
               data_dir: Optional[str] = None) -> dict:
    """Mixed write-storm + read-storm A/B (ISSUE 19 acceptance): the
    SAME sustained-write workload against the device plane with the
    delta-CSR OFF (`tpu_delta_max_edges=0` — every fresh read pays a
    graph-sized re-export + re-pin) and ON (write batches append into
    the device-resident delta; reads merge base + delta each hop).
    Per mode:

      read_goodput_qps   fresh reads served per second under the storm
      fresh_read_lag_ms  ack-to-visible staleness p50/p99 — insert a
                         marker edge, poll until a GO returns it
      write_qps          acked write statements per second
      repins / repin_avoided / compactions   Δ device-plane counters

    Headlines for bench.py's `htap` block: `read_goodput_on_over_off`
    (bar: >= 2.0 at comparable staleness — or comparable goodput at
    >= 5x lower `fresh_read_lag_ms`), and `repin_avoided_share` (> 0
    proves the storm rode the delta, not the re-pin path)."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.config import get_config
    from nebula_tpu.utils.stats import stats

    if tpu_runtime is None:
        try:
            from nebula_tpu.tpu import TpuRuntime, make_mesh
            tpu_runtime = TpuRuntime(make_mesh(1))
        except Exception as ex:  # noqa: BLE001 — no jax/device
            return {"error": f"no device runtime: {ex!r}"}

    cfg = get_config()
    tmp = data_dir or tempfile.mkdtemp(prefix="nebula_htap_")
    dyn_keys = ("tpu_delta_max_edges", "query_timeout_secs")
    out_modes: Dict[str, dict] = {}
    modes = {"rebuild": 0, "delta": delta_cap}
    try:
        cfg.set_dynamic("query_timeout_secs", max(duration_s * 8, 20.0))
        for mode, cap in modes.items():
            cfg.set_dynamic("tpu_delta_max_edges", cap)
            # one cluster per mode: both arms start from an identical
            # seeded space (the storm grows the graph, so sharing one
            # space would bias the second arm)
            cluster = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                                   data_dir=f"{tmp}/{mode}",
                                   tpu_runtime=tpu_runtime)
            try:
                _seed_graph(cluster, "htap", persons, degree,
                            replica_factor=1, rng_seed=53)
                warm = cluster.client()
                warm.execute("USE htap")
                warm.execute("GO FROM 1 OVER KNOWS YIELD dst(edge) AS d")
                warm.close()
                s0 = stats().snapshot()
                stop = threading.Event()
                wres, rres = _LevelResult(), _LevelResult()
                lags: List[float] = []
                perrs: List[str] = []
                ths = [threading.Thread(
                    target=_htap_write_worker,
                    args=(cluster, "htap", stop, i, persons, wres),
                    daemon=True) for i in range(writers)]
                ths += [threading.Thread(
                    target=_htap_read_worker,
                    args=(cluster, "htap", stop, i, persons, rres),
                    daemon=True) for i in range(readers)]
                ths += [threading.Thread(
                    target=_htap_staleness_probe,
                    args=(cluster, "htap", stop, persons, lags, perrs),
                    daemon=True)]
                t0 = time.perf_counter()
                for t in ths:
                    t.start()
                time.sleep(duration_s)
                stop.set()
                for t in ths:
                    t.join(30)
                wall = time.perf_counter() - t0
                s1 = stats().snapshot()
                rres.lats.sort()
                lags.sort()
                out_modes[mode] = {
                    "wall_s": round(wall, 2),
                    "writes_ok": wres.ok,
                    "write_qps": round(wres.ok / wall, 1) if wall else 0,
                    "reads_ok": rres.ok,
                    "read_goodput_qps": round(rres.ok / wall, 1)
                    if wall else 0,
                    "read_p50_ms": round(
                        _percentile(rres.lats, 50) * 1e3, 2),
                    "read_p99_ms": round(
                        _percentile(rres.lats, 99) * 1e3, 2),
                    "staleness_probes": len(lags),
                    "fresh_read_lag_p50_ms": round(
                        _percentile(lags, 50) * 1e3, 2),
                    "fresh_read_lag_p99_ms": round(
                        _percentile(lags, 99) * 1e3, 2),
                    "errors": len(wres.errors) + len(rres.errors)
                    + len(perrs),
                    "error_sample": (wres.errors + rres.errors
                                     + perrs)[:3],
                    "pins": s1.get("tpu_pins", 0) - s0.get("tpu_pins", 0),
                    "repin_avoided": s1.get("tpu_repin_avoided", 0)
                    - s0.get("tpu_repin_avoided", 0),
                    "compactions": s1.get("tpu_compactions", 0)
                    - s0.get("tpu_compactions", 0),
                }
            finally:
                cluster.stop()
        off, on = out_modes["rebuild"], out_modes["delta"]
        avoided = on["repin_avoided"]
        share = round(avoided / (avoided + on["pins"]), 4) \
            if (avoided + on["pins"]) else 0.0
        g_ratio = round(on["read_goodput_qps"]
                        / off["read_goodput_qps"], 2) \
            if off["read_goodput_qps"] else None
        lag_ratio = round(off["fresh_read_lag_p50_ms"]
                          / on["fresh_read_lag_p50_ms"], 2) \
            if on["fresh_read_lag_p50_ms"] else None
        return {
            "persons": persons,
            "degree": degree,
            "writers": writers,
            "readers": readers,
            "duration_per_mode_s": duration_s,
            "delta_cap": delta_cap,
            "modes": out_modes,
            # headlines (ISSUE 19 acceptance)
            "read_goodput_on_over_off": g_ratio,
            "fresh_read_lag_ms": on["fresh_read_lag_p50_ms"],
            "fresh_read_lag_off_over_on": lag_ratio,
            "repin_avoided_share": share,
            "tpu_repin_avoided": avoided,
        }
    finally:
        with cfg.lock:
            for k in dyn_keys:
                cfg.dynamic_layer.pop(k, None)
        if data_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


# -- fleet scale-out sweep (ISSUE 20) ---------------------------------------


def _fleet_worker(make_client, space: str, stmt_of, duration_s: float,
                  wid: int, res: _LevelResult):
    """Closed-loop worker over a caller-built client (single-endpoint
    or fleet) — the _worker body with the client factory lifted out."""
    from nebula_tpu.utils.admission import is_overload, parse_retry_after
    try:
        cl = make_client(wid)
        cl.execute(f"USE {space}")
    except Exception as ex:  # noqa: BLE001 — saturation may refuse conns
        with res.lock:
            res.errors.append(f"connect: {ex!r}")
        return
    end = time.monotonic() + duration_s
    j = 0
    while time.monotonic() < end:
        t0 = time.perf_counter()
        try:
            r = cl.execute(stmt_of(wid, j))
        except Exception as ex:  # noqa: BLE001
            with res.lock:
                res.errors.append(repr(ex))
            break
        dt = time.perf_counter() - t0
        with res.lock:
            if r.error is None:
                res.ok += 1
                res.lats.append(dt)
            elif is_overload(r.error):
                res.shed_results += 1
                if parse_retry_after(r.error) is None:
                    res.hints_missing += 1
            else:
                res.errors.append(r.error)
        j += 1
    try:
        cl.close()
    except Exception:  # noqa: BLE001
        pass


def _run_arm(make_client, space, stmt_of, n_workers, duration_s):
    res = _LevelResult()
    ths = [threading.Thread(target=_fleet_worker,
                            args=(make_client, space, stmt_of,
                                  duration_s, i, res))
           for i in range(n_workers)]
    t0 = time.perf_counter()
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    res.wall = time.perf_counter() - t0        # type: ignore[attr-defined]
    return res


def fleet_sweep(persons: int = 1200, degree: int = 5, workers: int = 18,
                duration_s: float = 3.0,
                capacity_qps: Optional[int] = None,
                n_sessions: int = 10_000, session_workers: int = 48,
                qos_workers: int = 6, tpu_runtime=None,
                data_dir: Optional[str] = None) -> dict:
    """Coordinator scale-out + fleet QoS sweep (ISSUE 20 acceptance) on
    a 1 metad / 3 storaged / 3 graphd cluster:

      1. SESSION STORM — `n_sessions` (default 10k+) short sessions
         spread over the 3 graphds, each authenticating, running one
         mixed GO/MATCH statement and signing out: the session-scale
         proof (sessions_per_s, zero errors).
      2. CAPACITY ARMS — the SAME closed-loop mixed GO/MATCH offered
         load against ONE coordinator vs the FLEET of 3, under the
         same per-coordinator statement capacity
         (`graph_statement_capacity_qps` — a token bucket per graphd
         that sheds over-rate statements with the PR 8 E_OVERLOAD +
         retry-after contract; a fleet client walks a shed statement
         to a sibling with spare tokens).  The capacity model is
         explicit and honest, exactly as the ISSUE 11 read sweep: an
         in-process cluster shares one interpreter, so raw CPU
         throughput cannot scale with coordinator count on a small
         host — what CAN and does scale is admitted per-coordinator
         capacity, which is what graphd scale-out buys a real
         deployment.  The capacity level is CALIBRATED below the
         host's raw throughput (an uncapped closed-loop probe, then
         cap = raw/5) so the fleet arm measures the capacity model,
         not the calibration host's cores.  Headline
         `fleet_goodput_x` (bar: >= 2.5).
      3. QOS PHASE — capacity off, admission slots scarce, two-level
         DWRR armed (`admission_tenant_weights` vip:3,agg:1) with an
         AGGRESSOR: `agg` offers 2x the closed-loop workers of `vip`.
         The admitted share must still track the weights —
         `dwrr_share_held`: |vip_share - 0.75| <= 0.15.
    """
    from nebula_tpu.cluster.client import GraphClient
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.admission import admission
    from nebula_tpu.utils.config import get_config

    space = "fleet"
    tmp = data_dir or tempfile.mkdtemp(prefix="nebula_fleet_")
    cluster = LocalCluster(n_meta=1, n_storage=3, n_graph=3,
                           data_dir=tmp, tpu_runtime=tpu_runtime)
    cfg = get_config()
    dyn_keys = ("graph_statement_capacity_qps", "query_timeout_secs",
                "max_running_queries", "admission_queue_capacity",
                "admission_tenant_weights")
    try:
        _seed_graph(cluster, space, persons, degree,
                    replica_factor=3, rng_seed=61)

        def stmt_of(wid: int, j: int) -> str:
            seed = (wid * 131 + j * 17) % persons
            if j % 4 == 3:
                return (f"MATCH (a:Person)-[e:KNOWS]->(b) "
                        f"WHERE id(a) == {seed} RETURN id(b)")
            return f"GO FROM {seed} OVER KNOWS YIELD dst(edge) AS d"

        # warm EVERY coordinator (catalog propagation + plan cache):
        # the arms must measure capacity, not first-touch compilation
        dl = time.monotonic() + 20.0
        for g in range(len(cluster.graph_servers)):
            while True:
                w = cluster.client(graphd=g)
                r = w.execute(f"USE {space}")
                if r.error is None:
                    r = w.execute(stmt_of(0, 3))
                if r.error is None:
                    r = w.execute(stmt_of(0, 0))
                w.close()
                if r.error is None:
                    break
                if time.monotonic() > dl:
                    raise AssertionError(
                        f"graphd {g} never warmed: {r.error}")
                time.sleep(0.1)

        addrs = cluster.graph_addrs

        def _fleet(wid):
            rot = addrs[wid % len(addrs):] + addrs[:wid % len(addrs)]
            c = GraphClient(rot)
            c.authenticate()
            return c

        # ---- calibrate raw mixed-load throughput (capacity OFF):
        # the capacity level must sit BELOW what the host can execute,
        # or the fleet arm measures cores, not the capacity model
        cal = _run_arm(_fleet, space, stmt_of, workers,
                       min(duration_s, 2.0))
        cal_wall = getattr(cal, "wall", 1.0)
        raw_qps = cal.ok / cal_wall if cal_wall else 0.0
        # raw/5: the fleet arm's 3x cap lands at ~60% of raw, far
        # enough below the CPU ceiling that walk overhead and GIL
        # contention don't eat the scale-out ratio
        cap = capacity_qps if capacity_qps is not None \
            else max(int(raw_qps / 5), 15)

        # ---- 1. session storm (capacity DISARMED) -------------------
        # each session is fully created and destroyed SERVER-SIDE
        # (metad-replicated row, graphd + engine registries, reaped
        # gauge) — but over kept-alive connections, the way a real
        # driver multiplexes sessions; per-session TCP setup is not
        # the thing being proven
        from nebula_tpu.cluster.rpc import RpcClient
        storm = _LevelResult()
        counter = {"n": 0}
        clock = threading.Lock()

        def _storm_worker(wid: int):
            conns: Dict[int, RpcClient] = {}

            def conn(g: int) -> RpcClient:
                c = conns.get(g)
                if c is None:
                    host, port = addrs[g].rsplit(":", 1)
                    c = conns[g] = RpcClient(host, int(port), retries=0)
                return c
            try:
                while True:
                    with clock:
                        k = counter["n"]
                        if k >= n_sessions:
                            return
                        counter["n"] = k + 1
                    try:
                        rc = conn(k % len(addrs))
                        sid = rc.call("graph.authenticate", user="root",
                                      password="nebula")["session_id"]
                        r1 = rc.call("graph.execute", session_id=sid,
                                     stmt=f"USE {space}")
                        # the cheap GO shape: the storm proves SESSION
                        # lifecycle scale; the mixed GO/MATCH load is
                        # the capacity arms' job
                        r2 = rc.call("graph.execute", session_id=sid,
                                     stmt=stmt_of(wid, 4 * k))
                        rc.call("graph.signout", session_id=sid)
                        err = r1["error"] or r2["error"]
                        with storm.lock:
                            if err is None:
                                storm.ok += 1
                            else:
                                storm.errors.append(err)
                    except Exception as ex:  # noqa: BLE001
                        with storm.lock:
                            storm.errors.append(repr(ex))
            finally:
                for c in conns.values():
                    c.close()

        ths = [threading.Thread(target=_storm_worker, args=(i,))
               for i in range(session_workers)]
        t0 = time.perf_counter()
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        storm_wall = time.perf_counter() - t0
        session_storm = {
            "sessions": n_sessions,
            "workers": session_workers,
            "wall_s": round(storm_wall, 2),
            "sessions_per_s": round(storm.ok / storm_wall, 1)
            if storm_wall else 0,
            "ok": storm.ok,
            "errors": len(storm.errors),
            "error_sample": storm.errors[:3],
        }

        # ---- 2. capacity arms: 1 coordinator vs the fleet of 3 ------
        cfg.set_dynamic_many({
            "graph_statement_capacity_qps": cap,
            "query_timeout_secs": max(duration_s * 2, 8.0),
        })
        shed0 = _stat_totals(_SHED_COUNTERS)

        def _single(wid):
            return cluster.client(graphd=0)

        arms = {}
        for label, mk in (("single", _single), ("fleet", _fleet)):
            res = _run_arm(mk, space, stmt_of, workers, duration_s)
            res.lats.sort()
            wall = getattr(res, "wall", duration_s)
            arms[label] = {
                "coordinators": 1 if label == "single" else len(addrs),
                "workers": workers,
                "wall_s": round(wall, 2),
                "goodput_qps": round(res.ok / wall, 1) if wall else 0,
                "ok": res.ok,
                "shed_results": res.shed_results,
                "other_errors": len(res.errors),
                "error_sample": res.errors[:3],
                "p50_ms": round(_percentile(res.lats, 50) * 1e3, 2),
                "p99_ms": round(_percentile(res.lats, 99) * 1e3, 2),
                "hints_ok": res.hints_missing == 0,
            }
        shed1 = _stat_totals(_SHED_COUNTERS)
        with cfg.lock:
            cfg.dynamic_layer.pop("graph_statement_capacity_qps", None)

        # ---- 3. QoS: DWRR shares hold under an aggressor tenant -----
        cfg.set_dynamic_many({
            "max_running_queries": 2,
            "admission_queue_capacity": 256,
            "admission_tenant_weights": "vip:3,agg:1",
            "query_timeout_secs": max(duration_s * 4, 15.0),
        })
        tenants = {"vip": _LevelResult(), "agg": _LevelResult()}

        def _tenant(user, wid):
            rot = addrs[wid % len(addrs):] + addrs[:wid % len(addrs)]
            c = GraphClient(rot)
            c.authenticate(user, "x")
            return c

        ths = []
        for user, n in (("vip", qos_workers), ("agg", qos_workers * 2)):
            ths += [threading.Thread(
                target=_fleet_worker,
                args=(lambda w, u=user: _tenant(u, w), space, stmt_of,
                      duration_s, i, tenants[user]))
                for i in range(n)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        vip_ok, agg_ok = tenants["vip"].ok, tenants["agg"].ok
        vip_share = vip_ok / (vip_ok + agg_ok) if vip_ok + agg_ok else 0.0
        qos = {
            "weights": "vip:3,agg:1",
            "vip_workers": qos_workers,
            "agg_workers": qos_workers * 2,
            "vip_ok": vip_ok,
            "agg_ok": agg_ok,
            "errors": len(tenants["vip"].errors)
            + len(tenants["agg"].errors),
            "error_sample": (tenants["vip"].errors
                             + tenants["agg"].errors)[:3],
            "vip_share": round(vip_share, 3),
            "expected_share": 0.75,
            "bound": 0.15,
            "dwrr_share_held": abs(vip_share - 0.75) <= 0.15,
            "tenants": admission().tenant_snapshot(),
        }

        g1 = arms["single"]["goodput_qps"]
        g3 = arms["fleet"]["goodput_qps"]
        return {
            "persons": persons,
            "degree": degree,
            "graphds": len(addrs),
            "statement": "mixed 1-hop GO / 1-hop MATCH (3:1)",
            "calibration": {"workers": workers,
                            "raw_qps": round(raw_qps, 1)},
            "capacity_qps_per_graphd": cap,
            "duration_per_arm_s": duration_s,
            "session_storm": session_storm,
            "arms": arms,
            "shed_counters": {k: int(shed1[k] - shed0[k])
                              for k in shed1},
            "qos": qos,
            # the acceptance numbers (ISSUE 20)
            "fleet_goodput_x": round(g3 / g1, 3) if g1 else None,
            "dwrr_share_held": qos["dwrr_share_held"],
        }
    finally:
        with cfg.lock:
            for k in dyn_keys:
                cfg.dynamic_layer.pop(k, None)
        admission().reset()
        cluster.stop()
        if data_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--persons", type=int, default=1200)
    ap.add_argument("--degree", type=int, default=5)
    ap.add_argument("--threads", type=int, default=6,
                    help="calibration (1×) closed-loop threads")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds per load level")
    ap.add_argument("--slots", type=int, default=None,
                    help="max_running_queries for the sweep")
    ap.add_argument("--queue-capacity", type=int, default=None)
    ap.add_argument("--inbox-capacity", type=int, default=0)
    ap.add_argument("--read-scaleout", action="store_true",
                    help="run the replica-count read sweep instead of "
                         "the offered-load sweep")
    ap.add_argument("--batch", action="store_true",
                    help="run the batched-dispatch A/B sweep "
                         "(batching off vs on) instead of the "
                         "offered-load sweep")
    ap.add_argument("--lanes", type=int, default=16,
                    help="batch_max_lanes for the --batch ON arm")
    ap.add_argument("--batch-wait-us", type=int, default=3000,
                    help="batch_wait_us forming window for --batch")
    ap.add_argument("--fleet", action="store_true",
                    help="run the coordinator scale-out + fleet QoS "
                         "sweep (10k-session storm, 1-vs-3 graphd "
                         "goodput under per-coordinator capacity, "
                         "DWRR aggressor shares) instead of the "
                         "offered-load sweep")
    ap.add_argument("--capacity-qps", type=int, default=None,
                    help="graph_statement_capacity_qps per graphd for "
                         "the --fleet capacity arms (default: "
                         "calibrated to raw_qps/5)")
    ap.add_argument("--sessions", type=int, default=10_000,
                    help="session-storm size for --fleet")
    ap.add_argument("--htap", action="store_true",
                    help="run the write-storm + read-storm delta-CSR "
                         "A/B (delta off vs on) instead of the "
                         "offered-load sweep")
    ap.add_argument("--writers", type=int, default=2,
                    help="closed-loop write workers for --htap")
    ap.add_argument("--delta-cap", type=int, default=2048,
                    help="tpu_delta_max_edges for the --htap ON arm")
    args = ap.parse_args(argv)
    if args.fleet:
        print(json.dumps(fleet_sweep(
            persons=args.persons, degree=args.degree,
            workers=args.threads * 3, duration_s=args.duration,
            capacity_qps=args.capacity_qps,
            n_sessions=args.sessions), indent=1))
        return 0
    if args.htap:
        print(json.dumps(htap_sweep(
            persons=args.persons, degree=args.degree,
            writers=args.writers, readers=args.threads,
            duration_s=args.duration,
            delta_cap=args.delta_cap), indent=1))
        return 0
    if args.batch:
        print(json.dumps(batch_sweep(
            persons=args.persons, degree=args.degree,
            threads=args.threads, duration_s=args.duration,
            lanes=args.lanes, wait_us=args.batch_wait_us), indent=1))
        return 0
    if args.read_scaleout:
        print(json.dumps(read_scaleout_sweep(
            persons=args.persons, degree=args.degree,
            threads=max(args.threads * 2, 8),
            duration_s=args.duration), indent=1))
        return 0
    print(json.dumps(run_sweep(
        persons=args.persons, degree=args.degree,
        cal_threads=args.threads, duration_s=args.duration,
        slots=args.slots, queue_capacity=args.queue_capacity,
        inbox_capacity=args.inbox_capacity), indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
