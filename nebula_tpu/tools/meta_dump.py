"""meta-dump — decode and print the cluster catalog / meta state.

The reference's meta-dump walks metad's RocksDB and prints the catalog
keys (src/tools/meta-dump [UNVERIFIED — empty mount, SURVEY §0]); ours
reads either a live metad (--addr host:port, any quorum member) or a
standalone store's durable data-dir, and prints the full meta plane:
spaces, schemas (with versions), indexes (secondary + fulltext),
listeners, users/roles, zones, and the partition map.

    python -m nebula_tpu.tools.meta_dump --addr 127.0.0.1:9559
    python -m nebula_tpu.tools.meta_dump --data-dir /var/lib/nebula-tpu
"""
from __future__ import annotations

import argparse
import sys


def _dump_catalog(cat, part_map=None, zones=None):
    for name in sorted(cat.spaces):
        sp = cat.spaces[name]
        print(f"space `{name}' id={sp.space_id} parts={sp.partition_num} "
              f"replicas={sp.replica_factor} vid_type={sp.vid_type}")
        for t in cat.tags(name):
            for sv in t.versions:
                props = ", ".join(
                    f"{p.name}:{p.ptype.value}"
                    f"{'' if p.nullable else ' NOT NULL'}"
                    for p in sv.props)
                print(f"  tag {t.name} v{sv.version}: [{props}]"
                      + (f" ttl={sv.ttl_col}/{sv.ttl_duration}"
                         if sv.ttl_col else ""))
        for e in cat.edges(name):
            for sv in e.versions:
                props = ", ".join(f"{p.name}:{p.ptype.value}"
                                  for p in sv.props)
                print(f"  edge {e.name} v{sv.version} "
                      f"type={e.edge_type}: [{props}]")
        for d in cat.indexes(name):
            kind = "edge" if d.is_edge else "tag"
            print(f"  {kind} index {d.name} ON "
                  f"{d.schema_name}({', '.join(d.fields)}) id={d.index_id}")
        for d in cat.fulltext_indexes(name):
            kind = "edge" if d.is_edge else "tag"
            print(f"  fulltext {kind} index {d.name} ON "
                  f"{d.schema_name}({d.fields[0]}) id={d.index_id}")
        for ltype, ep in cat.listeners(name):
            print(f"  listener {ltype} @ {ep}")
        if part_map and name in part_map:
            for pid, reps in enumerate(part_map[name]):
                print(f"  part {pid}: {reps}")
    for uname, u in sorted(cat.users.items()):
        roles = ", ".join(f"{sp or '*'}:{r}" for sp, r in
                          sorted(u.roles.items())) or "-"
        print(f"user `{uname}' roles=[{roles}]")
    for zname in sorted(zones or {}):
        print(f"zone `{zname}': {zones[zname]}")


def main(argv=None):
    ap = argparse.ArgumentParser(prog="nebula-tpu-meta-dump")
    ap.add_argument("--addr", help="a metad host:port (live cluster)")
    ap.add_argument("--data-dir",
                    help="standalone durable store's data directory")
    args = ap.parse_args(argv)
    if bool(args.addr) == bool(args.data_dir):
        ap.error("exactly one of --addr / --data-dir is required")

    if args.addr:
        from ..cluster.meta_client import MetaClient
        mc = MetaClient([args.addr], my_addr="meta-dump", role="tool")
        mc.refresh(force=True)
        zones = {}
        try:
            zones = mc.list_zones()
        except Exception:  # noqa: BLE001 — older metad without zones
            pass
        _dump_catalog(mc.catalog, part_map=dict(mc.part_map), zones=zones)
        return 0

    from ..graphstore.store import GraphStore
    store = GraphStore(data_dir=args.data_dir)
    try:
        # JournalingCatalog proxies reads to the recovered catalog
        _dump_catalog(store.catalog)
    finally:
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
