"""algo-bench — device vs numpy-host A/B per algorithm on the
north-star social array graph (ISSUE 13; the analytics mirror of
write_bench/overload_bench).

The headline question of the algo plane: does the vertex-program
engine's one-jitted-kernel-per-iteration form beat the numpy host
oracles on the same graph?  Per algorithm:

  device_s    median end-to-end device run (prep cached, kernels warm)
  host_s      median numpy-oracle run (power iteration / union-find /
              Dijkstra — genuinely different algorithm families)
  speedup     host_s / device_s (the acceptance number: > 1.0)
  iterations  device iterations to convergence/cap
  iter_ms     per-iteration device wall ms (p50 over the timed runs)
  rows_match  device rows == oracle rows (exact for wcc/sssp;
              pagerank max |Δrank| reported, bar 1e-9)

PageRank runs a FIXED iteration count on both sides (tol=0) so the
A/B compares identical work.  WCC/SSSP run to convergence.

Usage:
    python -m nebula_tpu.tools.algo_bench
    python -m nebula_tpu.tools.algo_bench --persons 300000 --degree 12

Emits one JSON object on stdout; bench.py folds it into its `algo`
block (speedups + rows_match are the acceptance evidence).
"""
from __future__ import annotations

import argparse
import json
import time
from typing import Dict, List, Optional

PAGERANK_TOL = 1e-8        # documented rank parity bar (abs, per vid)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2] if s else 0.0


def _build_graph(persons: int, degree: int, parts: int, seed: int):
    from nebula_tpu.bench.datagen import (SnapshotStore,
                                          make_social_arrays,
                                          snapshot_from_arrays)
    arrs = make_social_arrays(persons, degree, seed=seed)
    snap = snapshot_from_arrays(arrs, parts=parts, space="algo_ns")
    snap.space = "algo_ns"
    return SnapshotStore(snap), snap


def run_suite(persons: int = 120_000, degree: int = 12,
              parts: int = 8, seed: int = 7, repeats: int = 3,
              tpu_runtime=None,
              algos=("pagerank", "wcc", "sssp")) -> Dict:
    """Device-vs-host A/B per algorithm on one social array graph."""
    from nebula_tpu.algo.engine import run_algorithm
    store, snap = _build_graph(persons, degree, parts, seed)
    sd = store.space("algo_ns")
    rt = tpu_runtime
    if rt is None:
        from nebula_tpu.tpu import TpuRuntime, make_mesh
        rt = TpuRuntime(make_mesh(1))

    base_params: Dict[str, Dict] = {
        # fixed work on both sides: tol=0 never converges early
        "pagerank": {"max_iter": 20, "tol": 0.0},
        "wcc": {},
        "sssp": {"src": 0, "weight": "w"},
    }
    out: Dict = {"graph": {"persons": persons, "degree": degree,
                           "parts": parts,
                           "edges": int(snap.block("KNOWS", "out")
                                        .indptr[:, -1].sum())}}
    for func in algos:
        params = dict(base_params[func])
        # warmup: kernel compile + edge-array upload settle
        run_algorithm(func, {**params, "mode": "device"}, snap, sd,
                      rt=rt)
        dev_lat, host_lat, iter_all = [], [], []
        dev_rows = host_rows = None
        iters = 0
        for _ in range(repeats):
            iter_us: List[int] = []
            t0 = time.perf_counter()
            dev_rows, info = run_algorithm(
                func, {**params, "mode": "device"}, snap, sd, rt=rt,
                iter_us=iter_us)
            dev_lat.append(time.perf_counter() - t0)
            iters = info["iterations"]
            iter_all.extend(iter_us)
            t0 = time.perf_counter()
            host_rows, _ = run_algorithm(
                func, {**params, "mode": "host"}, snap, sd)
            host_lat.append(time.perf_counter() - t0)
        if func == "pagerank":
            dv = {r[0]: r[1] for r in dev_rows}
            hv = {r[0]: r[1] for r in host_rows}
            same_vids = set(dv) == set(hv)
            # diff over the intersection so a vid-domain parity bug
            # reports rows_match=False with the diff intact instead of
            # blowing up the whole suite with a KeyError
            max_diff = max((abs(dv[k] - hv[k]) for k in dv
                            if k in hv), default=0.0)
            rows_match = same_vids and max_diff <= PAGERANK_TOL
        else:
            max_diff = 0.0
            rows_match = dev_rows == host_rows
        dev_s, host_s = _median(dev_lat), _median(host_lat)
        out[func] = {
            "device_s": round(dev_s, 6),
            "host_s": round(host_s, 6),
            "speedup": round(host_s / dev_s, 3) if dev_s > 0 else 0.0,
            "iterations": iters,
            "iter_ms_p50": round(_median(iter_all) / 1000.0, 3)
            if iter_all else 0.0,
            "rows": len(dev_rows),
            "rows_match": bool(rows_match),
            "pagerank_max_abs_diff": max_diff
            if func == "pagerank" else None,
        }
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--persons", type=int, default=120_000)
    ap.add_argument("--degree", type=int, default=12)
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)
    res = run_suite(persons=args.persons, degree=args.degree,
                    parts=args.parts, seed=args.seed,
                    repeats=args.repeats)
    print(json.dumps(res, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
