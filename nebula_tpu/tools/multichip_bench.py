"""Multi-chip sharded-execution A/B bench (ISSUE 17 tentpole proof).

Measures what the mesh-native sharded plane actually buys:

  * **HBM scale-out** — with the per-DEVICE budget flag set to 1/4 of
    the snapshot, the single-chip pin REFUSES (the graph does not fit
    one chip) while the N-shard pin accepts (each shard parks ~1/N of
    the bytes); the per-shard ledger gauges are reported and must sum
    to the pinned total.
  * **Parity** — GO-3-step rows from the sharded runtime are
    byte-identical to the numpy CSR oracle (host_csr_traverse) AND to
    the single-chip runtime (the 1-vs-N A/B is an apples comparison).
  * **Goodput + exchange** — edges/s for 1-shard vs N-shard on the
    same snapshot, per-shard HBM bytes, and the bit-packed frontier
    all_to_all payload per hop (TraverseStats.exchange_bytes).

The sweep runs the measurement in a THROWAWAY subprocess with a hard
deadline (the same wedge-containment contract as probe_device): the
virtual arm forces `JAX_PLATFORMS=cpu` + 8 host devices so the A/B
always lands in the bench JSON even with no accelerator attached, and
a real-device arm runs additionally when the structured probe verdict
is "ok" — bench.py embeds the verdict verbatim as `probe_status`, so a
missing device arm is always attributable (ok / no_devices / timeout).

CLI:
  python -m nebula_tpu.tools.multichip_bench            # parent sweep
  python -m nebula_tpu.tools.multichip_bench --child    # one arm
"""
from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

_SENTINEL = "NEBULA_MULTICHIP:"


# -- child: one bounded in-process measurement ------------------------------

def _run_measurement(persons: int, degree: int, steps: int,
                     repeats: int) -> dict:
    import numpy as np

    from ..bench.datagen import (SnapshotStore, host_csr_traverse,
                                 make_social_arrays, snapshot_from_arrays)
    from ..tpu import TpuRuntime, make_mesh
    from ..tpu.device import TpuUnavailable
    from ..utils.config import get_config
    from ..utils.stats import stats

    import jax
    devs = jax.devices()
    N = min(8, len(devs))
    out: dict = {"platform": devs[0].platform, "n_devices": len(devs),
                 "shards": N, "persons": persons, "degree": degree,
                 "steps": steps}
    if N < 2:
        out["error"] = "need >= 2 devices for a sharded arm"
        return out

    arrs = make_social_arrays(persons, degree, seed=7)
    snap = snapshot_from_arrays(arrs, parts=N, space="mc")
    sstore = SnapshotStore(snap)
    rt1 = TpuRuntime(make_mesh(1))
    rtN = TpuRuntime(make_mesh(N))
    snap_bytes = rtN.pin_prebuilt(snap).hbm_bytes()
    rtN.unpin("mc")
    out["snapshot_bytes"] = snap_bytes

    # ---- HBM scale-out proof: budget = snapshot/4 per device ----------
    limit = max(snap_bytes // 4, 1)
    get_config().set_dynamic("tpu_hbm_limit_bytes", limit)
    try:
        proof: dict = {"per_device_limit_bytes": limit,
                       "graph_over_budget_x": round(snap_bytes / limit, 2)}
        try:
            rt1.pin_prebuilt(snap)
            proof["single_chip_refused"] = False    # should NOT happen
        except TpuUnavailable as ex:
            proof["single_chip_refused"] = True
            proof["refusal"] = str(ex)[:200]
        dev = rtN.pin_prebuilt(snap)                # must fit: bytes/N
        shard_bytes = dev.shard_hbm_bytes()
        proof["sharded_pin_ok"] = True
        proof["shard_hbm_bytes"] = {str(k): int(v)
                                    for k, v in shard_bytes.items()}
        proof["shard_sum_matches_total"] = \
            sum(shard_bytes.values()) == dev.hbm_bytes()
        out["hbm_scaleout"] = proof
    finally:
        get_config().set_dynamic("tpu_hbm_limit_bytes", 0)

    # ---- parity + goodput A/B ----------------------------------------
    seeds = np.unique(arrs["src"][:64])[:16].tolist()
    rt1.pin_prebuilt(snap)

    def one_arm(rt, label):
        rows, st = rt.traverse(sstore, "mc", seeds, ["KNOWS"], "out",
                               steps)                  # warm + escalate
        lat = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            rows, st = rt.traverse(sstore, "mc", seeds, ["KNOWS"],
                                   "out", steps)
            lat.append(time.perf_counter() - t0)
        edges = st.edges_traversed()
        xhops = max(steps - 1, 0)
        arm = {"shards": st.shards,
               "edges_traversed": edges,
               "median_s": round(statistics.median(lat), 4),
               "edges_per_s": int(edges / statistics.median(lat)),
               "exchange_bytes": st.exchange_bytes,
               "exchange_bytes_per_hop":
                   st.exchange_bytes // xhops if xhops else 0,
               "device_s": round(st.device_s, 4)}
        key = sorted((int(e.src), e.name, int(e.ranking), int(e.dst))
                     for _, e, _ in rows)
        return arm, key

    armN, keyN = one_arm(rtN, "sharded")
    arm1, key1 = one_arm(rt1, "single")
    out["single_chip"] = arm1
    out["sharded"] = armN
    out["rows_identical_1_vs_N"] = key1 == keyN

    # numpy oracle: same CSR arrays, vectorized host expansion
    total, kept, dst, w = host_csr_traverse(snap, seeds, steps,
                                            materialize=True)
    devd = np.asarray(sorted(k[3] for k in keyN), np.int64)
    out["rows_identical_vs_numpy"] = (
        kept == len(keyN) and
        bool((np.sort(dst.astype(np.int64)) == devd).all()))
    out["numpy_edges_traversed"] = total

    # the mesh gauges the run left behind
    snapm = stats().snapshot()
    out["tpu_shards_gauge"] = snapm.get("tpu_shards")
    out["tpu_all_to_all_bytes"] = snapm.get("tpu_all_to_all_bytes", 0)
    return out


def _child_main(args) -> int:
    try:
        res = _run_measurement(args.persons, args.degree, args.steps,
                               args.repeats)
    except Exception as ex:  # noqa: BLE001 — verdict, not traceback
        res = {"error": repr(ex)[:400]}
    print(_SENTINEL + json.dumps(res))
    return 0 if "error" not in res else 1


# -- parent: bounded subprocess arms + probe verdict ------------------------

def _run_child(force_cpu: bool, persons: int, degree: int, steps: int,
               repeats: int, timeout_s: float) -> dict:
    env = dict(os.environ)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
    cmd = [sys.executable, "-m", "nebula_tpu.tools.multichip_bench",
           "--child", "--persons", str(persons), "--degree", str(degree),
           "--steps", str(steps), "--repeats", str(repeats)]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return {"status": "timeout", "timeout_s": timeout_s}
    for line in out.stdout.splitlines():
        if line.startswith(_SENTINEL):
            try:
                res = json.loads(line[len(_SENTINEL):])
                res["status"] = "ok" if "error" not in res else "error"
                return res
            except ValueError:
                pass
    return {"status": "error", "rc": out.returncode,
            "stderr": (out.stderr or "").strip()[-400:]}


def multichip_sweep(persons: int = 120_000, degree: int = 6,
                    steps: int = 3, repeats: int = 5,
                    timeout_s: float = 600.0) -> dict:
    """The bench.py `multichip` block: structured probe verdict + the
    always-available virtual-mesh A/B + a real-device A/B when the
    probe lands ok.  Never raises, never hangs past its deadlines."""
    from .probe_device import probe
    verdict = probe()
    result = {"probe_status": verdict["probe_status"],
              "probe": verdict,
              "virtual": _run_child(True, persons, degree, steps,
                                    repeats, timeout_s)}
    if verdict["probe_status"] == "ok" and verdict["n_devices"] >= 2:
        result["device"] = _run_child(False, persons, degree, steps,
                                      repeats, timeout_s)
    v = result["virtual"]
    if v.get("status") == "ok":
        result["speedup_Nshard_vs_1"] = round(
            v["sharded"]["edges_per_s"]
            / max(v["single_chip"]["edges_per_s"], 1), 3)
    return result


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="1-vs-N-shard mesh execution A/B")
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--persons", type=int,
                    default=int(os.environ.get(
                        "NEBULA_BENCH_MULTICHIP_PERSONS", 120_000)))
    ap.add_argument("--degree", type=int, default=6)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get(
                        "NEBULA_BENCH_MULTICHIP_TIMEOUT", 600)))
    args = ap.parse_args(argv)
    if args.child:
        return _child_main(args)
    res = multichip_sweep(args.persons, args.degree, args.steps,
                          args.repeats, args.timeout)
    print(json.dumps(res, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
