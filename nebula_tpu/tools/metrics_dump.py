"""metrics-dump — scrape one daemon or a whole cluster's telemetry.

Observability CLI (ISSUE 1, grown cluster-wide in ISSUE 8): fetch
Prometheus exposition text / recent traces / flight-recorder entries
from daemon webservice ports, pretty-print a chosen trace as an
indented span tree, and diff counters over time.

    # one daemon
    python -m nebula_tpu.tools.metrics_dump --addr 127.0.0.1:10669
    python -m nebula_tpu.tools.metrics_dump --addr ... --traces
    python -m nebula_tpu.tools.metrics_dump --addr ... --trace <tid|latest>
    python -m nebula_tpu.tools.metrics_dump --addr ... --grep rpc_
    python -m nebula_tpu.tools.metrics_dump --addr ... --flight

    # whole cluster: per-host sections + a merged (counters summed) view
    python -m nebula_tpu.tools.metrics_dump \
        --addrs 127.0.0.1:10669,127.0.0.1:10779,127.0.0.1:10559

    # delta mode: re-scrape every N seconds, print only changed counters
    python -m nebula_tpu.tools.metrics_dump --addrs ... --watch 5

A metad's federated view (`/cluster_metrics`) can be scraped like any
single target with `--addr <metad-ws> --path /cluster_metrics`.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Dict, List, Tuple


def _fetch(addr: str, path: str) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return r.read().decode()


def _parse_samples(text: str) -> Dict[str, float]:
    """name{labels} → value for every sample line (comments skipped)."""
    out: Dict[str, float] = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        head, _, val = ln.rpartition(" ")
        try:
            out[head] = float(val)
        except ValueError:
            continue
    return out


def dump_metrics(addr: str, grep: str = "", path: str = "/metrics") -> int:
    text = _fetch(addr, path)
    n = 0
    for ln in text.splitlines():
        if grep and grep not in ln:
            continue
        print(ln)
        if not ln.startswith("#"):
            n += 1
    return n


def scrape_cluster(addrs: List[str], path: str = "/metrics"
                   ) -> Tuple[Dict[str, Dict[str, float]],
                              Dict[str, float]]:
    """-> (per-host samples, merged samples).  Merging SUMS values per
    sample key — correct for counters and histogram rows (the common
    cross-host question is 'how much in total'); gauges are better read
    per host, which the per-host map preserves.  Unreachable hosts are
    reported on stderr and skipped."""
    per_host: Dict[str, Dict[str, float]] = {}
    merged: Dict[str, float] = {}
    for addr in addrs:
        try:
            samples = _parse_samples(_fetch(addr, path))
        except OSError as ex:
            print(f"scrape of {addr} failed: {ex}", file=sys.stderr)
            continue
        per_host[addr] = samples
        for k, v in samples.items():
            merged[k] = merged.get(k, 0.0) + v
    return per_host, merged


def _fmt_val(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(v)


def dump_cluster(addrs: List[str], grep: str = "",
                 path: str = "/metrics") -> int:
    per_host, merged = scrape_cluster(addrs, path)
    for addr in sorted(per_host):
        print(f"== {addr} ({len(per_host[addr])} samples)")
        for k in sorted(per_host[addr]):
            if grep and grep not in k:
                continue
            print(f"  {k} {_fmt_val(per_host[addr][k])}")
    print(f"== merged ({len(per_host)}/{len(addrs)} hosts)")
    n = 0
    for k in sorted(merged):
        if grep and grep not in k:
            continue
        print(f"  {k} {_fmt_val(merged[k])}")
        n += 1
    return n


def watch_cluster(addrs: List[str], interval: float, grep: str = "",
                  iterations: int = 0, path: str = "/metrics") -> int:
    """Delta mode: print only samples whose MERGED value changed since
    the previous scrape (plus the first full baseline count).
    iterations=0 runs until interrupted."""
    _, prev = scrape_cluster(addrs, path)
    print(f"baseline: {len(prev)} samples from {len(addrs)} target(s)")
    i = 0
    while iterations <= 0 or i < iterations:
        i += 1
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            break
        _, cur = scrape_cluster(addrs, path)
        changed = [(k, prev.get(k, 0.0), v) for k, v in sorted(cur.items())
                   if v != prev.get(k, 0.0) and (not grep or grep in k)]
        stamp = time.strftime("%H:%M:%S")
        if not changed:
            print(f"[{stamp}] no change")
        for k, old, new in changed:
            print(f"[{stamp}] {k} {_fmt_val(old)} -> {_fmt_val(new)} "
                  f"(+{_fmt_val(new - old)})")
        prev = cur
    return 0


def dump_trace_list(addr: str) -> int:
    traces = json.loads(_fetch(addr, "/traces"))
    for t in traces:
        print(f"{t['tid']}  {t['name']:<28} spans={t['spans']:<4} "
              f"{t['dur_us']}us")
    return len(traces)


def dump_trace(addr: str, tid: str):
    print(_fetch(addr, f"/traces?id={tid}&format=text"))


def dump_flight(addr: str, entry_id: str = "") -> int:
    if entry_id:
        print(_fetch(addr, f"/flight?id={entry_id}"))
        return 1
    entries = json.loads(_fetch(addr, "/flight"))
    for e in entries:
        print(f"#{e['id']:<5} {e['status']:<9} {e['kind']:<10} "
              f"{e['latency_us']}us ops={e['operators']:<3} "
              f"{e['stmt'][:60]}")
    return len(entries)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="metrics-dump")
    ap.add_argument("--addr", default="",
                    help="webservice host:port of one daemon")
    ap.add_argument("--addrs", default="",
                    help="comma-separated webservice addrs of the whole "
                         "cluster (per-host + merged output)")
    ap.add_argument("--path", default="/metrics",
                    help="metrics path to scrape (e.g. /cluster_metrics "
                         "on a metad)")
    ap.add_argument("--traces", action="store_true",
                    help="list recent traces instead of metrics")
    ap.add_argument("--trace", default="",
                    help="print one trace's span tree by id "
                         "('latest' = newest recorded trace)")
    ap.add_argument("--flight", action="store_true",
                    help="list flight-recorder entries")
    ap.add_argument("--flight-id", default="",
                    help="print one flight entry's full per-operator "
                         "breakdown")
    ap.add_argument("--grep", default="",
                    help="only metric lines containing this substring")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="re-scrape every N seconds and print only "
                         "counters that changed (delta mode)")
    ap.add_argument("--iterations", type=int, default=0,
                    help="watch iterations before exiting (0 = forever; "
                         "for scripted use)")
    args = ap.parse_args(argv)
    addrs = [a for a in args.addrs.split(",") if a]
    if not addrs and args.addr:
        addrs = [args.addr]
    if not addrs:
        ap.error("need --addr or --addrs")
    one = addrs[0]
    if len(addrs) > 1 and (args.trace or args.traces or args.flight
                           or args.flight_id):
        # traces/flight entries are per-process state, not mergeable
        # samples — be explicit about which host answers
        print(f"note: --traces/--trace/--flight query a single host; "
              f"using {one}", file=sys.stderr)
    try:
        if args.trace:
            tid = args.trace
            if tid == "latest":
                traces = json.loads(_fetch(one, "/traces"))
                if not traces:
                    print("no traces recorded", file=sys.stderr)
                    return 1
                tid = traces[0]["tid"]
            dump_trace(one, tid)
        elif args.traces:
            dump_trace_list(one)
        elif args.flight or args.flight_id:
            dump_flight(one, args.flight_id)
        elif args.watch > 0:
            watch_cluster(addrs, args.watch, args.grep,
                          args.iterations, args.path)
        elif len(addrs) > 1:
            dump_cluster(addrs, args.grep, args.path)
        else:
            dump_metrics(one, args.grep, args.path)
    except OSError as ex:
        print(f"scrape failed: {ex}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
