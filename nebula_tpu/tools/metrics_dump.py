"""metrics-dump — scrape a running daemon's /metrics + /traces.

Observability CLI (ISSUE 1): fetch the Prometheus exposition text and
the recent-trace list from a daemon's webservice port, pretty-print a
chosen trace as an indented span tree.  Useful both interactively and
as the round-over-round diff source (work counters + counter metrics
are deterministic where timings are not; docs/OBSERVABILITY.md).

    python -m nebula_tpu.tools.metrics_dump --addr 127.0.0.1:10669
    python -m nebula_tpu.tools.metrics_dump --addr ... --traces
    python -m nebula_tpu.tools.metrics_dump --addr ... --trace <tid>
    python -m nebula_tpu.tools.metrics_dump --addr ... --grep rpc_
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _fetch(addr: str, path: str) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return r.read().decode()


def dump_metrics(addr: str, grep: str = "") -> int:
    text = _fetch(addr, "/metrics")
    n = 0
    for ln in text.splitlines():
        if grep and grep not in ln:
            continue
        print(ln)
        if not ln.startswith("#"):
            n += 1
    return n


def dump_trace_list(addr: str) -> int:
    traces = json.loads(_fetch(addr, "/traces"))
    for t in traces:
        print(f"{t['tid']}  {t['name']:<28} spans={t['spans']:<4} "
              f"{t['dur_us']}us")
    return len(traces)


def dump_trace(addr: str, tid: str):
    print(_fetch(addr, f"/traces?id={tid}&format=text"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="metrics-dump")
    ap.add_argument("--addr", required=True,
                    help="webservice host:port of any daemon")
    ap.add_argument("--traces", action="store_true",
                    help="list recent traces instead of metrics")
    ap.add_argument("--trace", default="",
                    help="print one trace's span tree by id "
                         "('latest' = newest recorded trace)")
    ap.add_argument("--grep", default="",
                    help="only metric lines containing this substring")
    args = ap.parse_args(argv)
    try:
        if args.trace:
            tid = args.trace
            if tid == "latest":
                traces = json.loads(_fetch(args.addr, "/traces"))
                if not traces:
                    print("no traces recorded", file=sys.stderr)
                    return 1
                tid = traces[0]["tid"]
            dump_trace(args.addr, tid)
        elif args.traces:
            dump_trace_list(args.addr)
        else:
            dump_metrics(args.addr, args.grep)
    except OSError as ex:
        print(f"scrape failed: {ex}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
