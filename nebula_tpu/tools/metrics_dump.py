"""metrics-dump — scrape one daemon or a whole cluster's telemetry.

Observability CLI (ISSUE 1, grown cluster-wide in ISSUE 8): fetch
Prometheus exposition text / recent traces / flight-recorder entries
from daemon webservice ports, pretty-print a chosen trace as an
indented span tree, and diff counters over time.

    # one daemon
    python -m nebula_tpu.tools.metrics_dump --addr 127.0.0.1:10669
    python -m nebula_tpu.tools.metrics_dump --addr ... --traces
    python -m nebula_tpu.tools.metrics_dump --addr ... --trace <tid|latest>
    python -m nebula_tpu.tools.metrics_dump --addr ... --grep rpc_
    python -m nebula_tpu.tools.metrics_dump --addr ... --flight

    # whole cluster: per-host sections + a merged (counters summed) view
    python -m nebula_tpu.tools.metrics_dump \
        --addrs 127.0.0.1:10669,127.0.0.1:10779,127.0.0.1:10559

    # delta mode: re-scrape every N seconds, print only changed counters
    python -m nebula_tpu.tools.metrics_dump --addrs ... --watch 5

    # live workload + stall dumps (ISSUE 9)
    python -m nebula_tpu.tools.metrics_dump --addr ... --queries
    python -m nebula_tpu.tools.metrics_dump --addr ... --stalls

    # auto-repair plans from a metad (ISSUE 14)
    python -m nebula_tpu.tools.metrics_dump --addr <metad-ws> --repairs

    # workload insights (ISSUE 16): fingerprint tables + partition heat
    python -m nebula_tpu.tools.metrics_dump --addrs <graphd-ws>,... \
        --statements [--watch 5]
    python -m nebula_tpu.tools.metrics_dump --addr <metad-ws> --hotspots

    # sharded mesh execution (ISSUE 17): per-device HBM residency +
    # frontier-exchange bytes, per host and cluster-merged
    python -m nebula_tpu.tools.metrics_dump --addrs <graphd-ws>,... \
        --shards [--watch 5]

    # delta-CSR plane (ISSUE 19): per-shard delta fill, repin-avoided
    # share and recent compaction swaps, per host and cluster-merged
    python -m nebula_tpu.tools.metrics_dump --addrs <graphd-ws>,... \
        --deltas [--watch 5]

    # fleet plane (ISSUE 20): per-coordinator sessions / statement
    # goodput / epoch-propagation lag / failover counters, per host
    # and cluster-merged; --watch shows per-interval deltas
    python -m nebula_tpu.tools.metrics_dump --addrs <graphd-ws>,... \
        --fleet [--watch 5]

    # Perfetto: every trace tree (+ stall captures) as Chrome
    # trace-event JSON, one track per daemon/service, device spans
    # included — open the file at https://ui.perfetto.dev
    python -m nebula_tpu.tools.metrics_dump --addrs a,b,c \
        --perfetto /tmp/cluster.trace.json

A metad's federated view (`/cluster_metrics`) can be scraped like any
single target with `--addr <metad-ws> --path /cluster_metrics`.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.request
from typing import Dict, List, Tuple


def _fetch(addr: str, path: str) -> str:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return r.read().decode()


def _parse_samples(text: str) -> Dict[str, float]:
    """name{labels} → value for every sample line (comments skipped)."""
    out: Dict[str, float] = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        head, _, val = ln.rpartition(" ")
        try:
            out[head] = float(val)
        except ValueError:
            continue
    return out


def dump_metrics(addr: str, grep: str = "", path: str = "/metrics") -> int:
    text = _fetch(addr, path)
    n = 0
    for ln in text.splitlines():
        if grep and grep not in ln:
            continue
        print(ln)
        if not ln.startswith("#"):
            n += 1
    return n


def scrape_cluster(addrs: List[str], path: str = "/metrics"
                   ) -> Tuple[Dict[str, Dict[str, float]],
                              Dict[str, float]]:
    """-> (per-host samples, merged samples).  Merging SUMS values per
    sample key — correct for counters and histogram rows (the common
    cross-host question is 'how much in total'); gauges are better read
    per host, which the per-host map preserves.  Unreachable hosts are
    reported on stderr and skipped."""
    per_host: Dict[str, Dict[str, float]] = {}
    merged: Dict[str, float] = {}
    for addr in addrs:
        try:
            samples = _parse_samples(_fetch(addr, path))
        except OSError as ex:
            print(f"scrape of {addr} failed: {ex}", file=sys.stderr)
            continue
        per_host[addr] = samples
        for k, v in samples.items():
            merged[k] = merged.get(k, 0.0) + v
    return per_host, merged


def _fmt_val(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(v)


def dump_cluster(addrs: List[str], grep: str = "",
                 path: str = "/metrics") -> int:
    per_host, merged = scrape_cluster(addrs, path)
    for addr in sorted(per_host):
        print(f"== {addr} ({len(per_host[addr])} samples)")
        for k in sorted(per_host[addr]):
            if grep and grep not in k:
                continue
            print(f"  {k} {_fmt_val(per_host[addr][k])}")
    print(f"== merged ({len(per_host)}/{len(addrs)} hosts)")
    n = 0
    for k in sorted(merged):
        if grep and grep not in k:
            continue
        print(f"  {k} {_fmt_val(merged[k])}")
        n += 1
    return n


def watch_cluster(addrs: List[str], interval: float, grep: str = "",
                  iterations: int = 0, path: str = "/metrics",
                  scrape_fn=None) -> int:
    """Delta mode: print only samples whose MERGED value changed since
    the previous scrape (plus the first full baseline count).
    iterations=0 runs until interrupted.  scrape_fn overrides the
    default /metrics scrape (the --statements/--hotspots views plug in
    here) — it must return scrape_cluster's (per_host, merged) shape."""
    scrape = scrape_fn or (lambda: scrape_cluster(addrs, path))
    _, prev = scrape()
    print(f"baseline: {len(prev)} samples from {len(addrs)} target(s)")
    i = 0
    while iterations <= 0 or i < iterations:
        i += 1
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            break
        _, cur = scrape()
        changed = [(k, prev.get(k, 0.0), v) for k, v in sorted(cur.items())
                   if v != prev.get(k, 0.0) and (not grep or grep in k)]
        stamp = time.strftime("%H:%M:%S")
        if not changed:
            print(f"[{stamp}] no change")
        for k, old, new in changed:
            print(f"[{stamp}] {k} {_fmt_val(old)} -> {_fmt_val(new)} "
                  f"(+{_fmt_val(new - old)})")
        prev = cur
    return 0


def _fetch_json(addr: str, path: str):
    return json.loads(_fetch(addr, path))


# -- workload insights views (ISSUE 16) -------------------------------------


def _insights():
    """utils.insights, importable BOTH ways this tool is launched:
    `python -m nebula_tpu.tools.metrics_dump` (package-relative) and
    `tools/metrics_dump.py` as a plain script (repo root on sys.path)."""
    try:
        from ..utils import insights
    except ImportError:
        import os
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        from nebula_tpu.utils import insights
    return insights


def _print_statement_rows(rows: List[dict]):
    statement_columns = _insights().statement_columns
    for (fp, sample, calls, errs, p50, p95, nrows, dev, plan, chg,
         reg) in statement_columns(rows):
        flag = "  REGRESSED" if reg else ""
        print(f"  {fp}  calls={calls:<7} errs={errs:<5} "
              f"p50={p50:<9} p95={p95:<9} rows={nrows:<8} "
              f"dev={dev:<5} plan={(plan or '-'):<12} chg={chg}{flag}  "
              f"{str(sample)[:48]}")


def dump_statements(addrs: List[str]) -> int:
    """Statement fingerprint tables (GET /statements on each graphd):
    per-host sections plus ONE exactly-merged view (shared fixed
    latency buckets sum losslessly).  A metad serves the already-merged
    cluster view at /cluster_statements (scrape with --path)."""
    merge_statement_snapshots = _insights().merge_statement_snapshots
    snaps = []
    for addr in addrs:
        try:
            rows = _fetch_json(addr, "/statements")
        except (OSError, ValueError) as ex:
            print(f"scrape of {addr} failed: {ex}", file=sys.stderr)
            continue
        snaps.append(rows)
        print(f"== {addr} ({len(rows)} fingerprints)")
        _print_statement_rows(rows)
    if len(snaps) > 1:
        merged = merge_statement_snapshots(snaps)
        print(f"== merged ({len(snaps)}/{len(addrs)} hosts)")
        _print_statement_rows(merged)
    return sum(len(s) for s in snaps)


def _print_heat_rows(rows: List[dict]):
    for r in rows:
        where = ""
        if r.get("leader"):
            where = f"  leader={r['leader']}"
        elif r.get("hosts"):
            where = f"  hosts={','.join(r['hosts'])}"
        print(f"  {r['space']}/{r['part']:<4} score={r['score']:<10} "
              f"rqps={r['read_qps']:<8} wqps={r['write_qps']:<8} "
              f"reads={r['reads']:<8} writes={r['writes']:<8} "
              f"rlat={r['read_lat_us']}us wlat={r['write_lat_us']}us"
              f"{where}")


def dump_hotspots(addrs: List[str]) -> int:
    """Per-partition heat rows (GET /hotspots): a storaged answers
    with its local parts, a metad with the heartbeat-merged cluster
    ranking (leader/replicas attached).  Multiple storaged addrs are
    merged locally the same way metad merges heartbeats."""
    merge_heat_snapshots = _insights().merge_heat_snapshots
    per_host: Dict[str, List[dict]] = {}
    for addr in addrs:
        try:
            rows = _fetch_json(addr, "/hotspots")
        except (OSError, ValueError) as ex:
            print(f"scrape of {addr} failed: {ex}", file=sys.stderr)
            continue
        per_host[addr] = rows
        print(f"== {addr} ({len(rows)} parts)")
        _print_heat_rows(rows)
    if len(per_host) > 1:
        merged = merge_heat_snapshots(per_host)
        print(f"== merged ({len(per_host)}/{len(addrs)} hosts)")
        _print_heat_rows(merged)
    return sum(len(r) for r in per_host.values())


def _statement_samples(rows: List[dict]) -> Dict[str, float]:
    """Flatten fingerprint rows into the sample-dict shape the watch
    loop diffs — counters only (monotone, so deltas read cleanly)."""
    out: Dict[str, float] = {}
    for r in rows:
        fp = r.get("fingerprint", "?")
        for k in ("calls", "errors", "kills", "sheds", "rows",
                  "plan_changed", "plan_cache_hits",
                  "result_cache_hits"):
            out[f'statement_{k}{{fp="{fp}"}}'] = float(r.get(k, 0))
    return out


def _heat_samples(rows: List[dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for r in rows:
        key = f'space="{r["space"]}",part="{r["part"]}"'
        for k in ("reads", "writes", "read_rows", "write_rows",
                  "read_bytes", "write_bytes"):
            out[f"part_{k}{{{key}}}"] = float(r.get(k, 0))
    return out


def scrape_cluster_view(addrs: List[str], path: str, flatten
                        ) -> Tuple[Dict[str, Dict[str, float]],
                                   Dict[str, float]]:
    """scrape_cluster's shape for a JSON view: per-host flattened
    samples + the counter-summed merge — this is what lets --watch
    reuse the ONE snapshot-diff loop for statements and hotspots."""
    per_host: Dict[str, Dict[str, float]] = {}
    merged: Dict[str, float] = {}
    for addr in addrs:
        try:
            samples = flatten(_fetch_json(addr, path))
        except (OSError, ValueError) as ex:
            print(f"scrape of {addr} failed: {ex}", file=sys.stderr)
            continue
        per_host[addr] = samples
        for k, v in samples.items():
            merged[k] = merged.get(k, 0.0) + v
    return per_host, merged


# -- sharded-execution view (ISSUE 17) --------------------------------------

_SHARD_HBM_PAT = re.compile(r'^tpu_shard_hbm_bytes\{shard="?(\d+)"?\}$')
_SHARD_KEYS = ("tpu_shards", "tpu_hbm_bytes_pinned",
               "tpu_all_to_all_bytes")


def _is_shard_sample(name: str) -> bool:
    return name in _SHARD_KEYS or bool(_SHARD_HBM_PAT.match(name))


def _shard_filter(samples: Dict[str, float]) -> Dict[str, float]:
    return {k: v for k, v in samples.items() if _is_shard_sample(k)}


def _print_shard_rows(samples: Dict[str, float]):
    per_shard = {int(m.group(1)): v for k, v in samples.items()
                 for m in [_SHARD_HBM_PAT.match(k)] if m}
    width = samples.get("tpu_shards")
    pinned = samples.get("tpu_hbm_bytes_pinned", 0.0)
    a2a = samples.get("tpu_all_to_all_bytes", 0.0)
    print(f"  mesh width: {int(width) if width else '?'} shard(s)")
    for pn in sorted(per_shard):
        share = per_shard[pn] / pinned if pinned else 0.0
        print(f"  shard {pn:<3} hbm={int(per_shard[pn]):<12} "
              f"({share:.1%} of pinned)")
    ledger = sum(per_shard.values())
    ok = "OK" if ledger == pinned else "MISMATCH"
    print(f"  ledger sum={int(ledger)} vs tpu_hbm_bytes_pinned="
          f"{int(pinned)} -> {ok}")
    print(f"  all_to_all exchanged: {int(a2a)} bytes")


def dump_shards(addrs: List[str], path: str = "/metrics") -> int:
    """Sharded-mesh residency view (ISSUE 17): each host's per-device
    HBM ledger (`tpu_shard_hbm_bytes{shard}`), its sum checked against
    `tpu_hbm_bytes_pinned`, the mesh width and the cumulative frontier
    all_to_all bytes — plus one cluster-merged section.  Combine with
    --watch for exchange-byte deltas per interval."""
    per_host, merged = scrape_cluster(addrs, path)
    n = 0
    for addr in sorted(per_host):
        samples = _shard_filter(per_host[addr])
        print(f"== {addr} ({len(samples)} shard samples)")
        if samples:
            _print_shard_rows(samples)
            n += len(samples)
    if len(per_host) > 1:
        print(f"== merged ({len(per_host)}/{len(addrs)} hosts)")
        _print_shard_rows(_shard_filter(merged))
    return n


def _scrape_shard_view(addrs: List[str], path: str = "/metrics"
                       ) -> Tuple[Dict[str, Dict[str, float]],
                                  Dict[str, float]]:
    per_host, merged = scrape_cluster(addrs, path)
    return ({a: _shard_filter(s) for a, s in per_host.items()},
            _shard_filter(merged))


# -- delta-CSR view (ISSUE 19) ----------------------------------------------

_DELTA_SHARD_PAT = re.compile(r'^tpu_shard_delta_edges\{shard="?(\d+)"?\}$')
_DELTA_KEYS = ("tpu_delta_edges", "tpu_delta_bytes", "tpu_compactions",
               "tpu_repin_avoided", "tpu_pins", "tpu_batch_gate_rearms")


def _is_delta_sample(name: str) -> bool:
    return name in _DELTA_KEYS or bool(_DELTA_SHARD_PAT.match(name))


def _delta_filter(samples: Dict[str, float]) -> Dict[str, float]:
    return {k: v for k, v in samples.items() if _is_delta_sample(k)}


def _print_delta_rows(samples: Dict[str, float]):
    per_shard = {int(m.group(1)): v for k, v in samples.items()
                 for m in [_DELTA_SHARD_PAT.match(k)] if m}
    edges = samples.get("tpu_delta_edges", 0.0)
    nbytes = samples.get("tpu_delta_bytes", 0.0)
    avoided = samples.get("tpu_repin_avoided", 0.0)
    pins = samples.get("tpu_pins", 0.0)
    comps = samples.get("tpu_compactions", 0.0)
    rearms = samples.get("tpu_batch_gate_rearms", 0.0)
    print(f"  delta plane: {int(edges)} rows, {int(nbytes)} bytes "
          f"resident")
    worst = max(per_shard.values()) if per_shard else 0.0
    for pn in sorted(per_shard):
        bar = "#" * int(30 * per_shard[pn] / worst) if worst else ""
        print(f"  shard {pn:<3} delta_rows={int(per_shard[pn]):<8} "
              f"{bar}")
    share = avoided / (avoided + pins) if (avoided + pins) else 0.0
    print(f"  repins avoided: {int(avoided)} vs pins {int(pins)} "
          f"({share:.1%} of epoch advances rode the delta)")
    print(f"  compactions: {int(comps)}   "
          f"forming-window gate re-arms: {int(rearms)}")


def _compaction_history(addr: str) -> List[str]:
    """tpu:compaction spans from the host's trace ring — the recent
    swap history (space + duration), newest first."""
    rows: List[str] = []
    try:
        for t in _collect_traces(addr):
            for sp in t.get("spans", []):
                if sp.get("name") != "tpu:compaction":
                    continue
                attrs = sp.get("attrs") or {}
                rows.append(f"    space={attrs.get('space', '?')} "
                            f"dur={int(sp.get('dur_us', 0))}us")
    except Exception:  # noqa: BLE001 — tracing may be disabled
        pass
    return rows[:10]


def dump_deltas(addrs: List[str], path: str = "/metrics") -> int:
    """Delta-CSR residency view (ISSUE 19): per-shard delta fill
    (`tpu_shard_delta_edges{shard}`), total delta rows/bytes, the
    repin-avoided share, compaction count and recent `tpu:compaction`
    swap history — per host plus one cluster-merged section.  Combine
    with --watch for apply/compaction deltas per interval."""
    per_host, merged = scrape_cluster(addrs, path)
    n = 0
    for addr in sorted(per_host):
        samples = _delta_filter(per_host[addr])
        print(f"== {addr} ({len(samples)} delta samples)")
        if samples:
            _print_delta_rows(samples)
            n += len(samples)
        hist = _compaction_history(addr)
        if hist:
            print("  recent compactions:")
            for row in hist:
                print(row)
    if len(per_host) > 1:
        print(f"== merged ({len(per_host)}/{len(addrs)} hosts)")
        _print_delta_rows(_delta_filter(merged))
    return n


def _scrape_delta_view(addrs: List[str], path: str = "/metrics"
                       ) -> Tuple[Dict[str, Dict[str, float]],
                                  Dict[str, float]]:
    per_host, merged = scrape_cluster(addrs, path)
    return ({a: _delta_filter(s) for a, s in per_host.items()},
            _delta_filter(merged))


# -- fleet view (ISSUE 20) --------------------------------------------------

_FLEET_STMT_PAT = re.compile(
    r'^query_latency_us_hist_(count|sum)\{[^}]*kind="?([^"},]+)"?[^}]*\}$')
_FLEET_EPOCH_PAT = re.compile(
    r'^epoch_propagation_lag_ms_(count|sum)(\{[^}]*\})?$')
_FLEET_SHED_PAT = re.compile(
    r'^overload_server_rejections\{[^}]*graph\.statement_capacity[^}]*\}$')
_FLEET_KEYS = ("graph_sessions", "cluster_epoch_folds", "session_moves",
               "coordinator_failovers", "graphd_drains", "kill_owner_dead")


def _is_fleet_sample(name: str) -> bool:
    return (name in _FLEET_KEYS
            or bool(_FLEET_STMT_PAT.match(name))
            or bool(_FLEET_EPOCH_PAT.match(name))
            or bool(_FLEET_SHED_PAT.match(name)))


def _fleet_filter(samples: Dict[str, float]) -> Dict[str, float]:
    return {k: v for k, v in samples.items() if _is_fleet_sample(k)}


def _print_fleet_rows(samples: Dict[str, float]):
    per_kind: Dict[str, float] = {}
    lag_sum = lag_n = 0.0
    for k, v in samples.items():
        m = _FLEET_STMT_PAT.match(k)
        if m and m.group(1) == "count":
            per_kind[m.group(2)] = per_kind.get(m.group(2), 0.0) + v
        m = _FLEET_EPOCH_PAT.match(k)
        if m:
            if m.group(1) == "sum":
                lag_sum += v
            else:
                lag_n += v
    total = sum(per_kind.values())
    kinds = ", ".join(f"{kk}={int(per_kind[kk])}"
                      for kk in sorted(per_kind, key=per_kind.get,
                                       reverse=True)[:4])
    print(f"  sessions: {int(samples.get('graph_sessions', 0))}")
    print(f"  statements served: {int(total)}"
          + (f"  ({kinds})" if kinds else ""))
    lag = f"{lag_sum / lag_n:.2f}ms mean of {int(lag_n)}" if lag_n \
        else "none observed"
    print(f"  epoch folds: "
          f"{int(samples.get('cluster_epoch_folds', 0))}   "
          f"propagation lag: {lag}")
    sheds = sum(v for k, v in samples.items()
                if _FLEET_SHED_PAT.match(k))
    print(f"  session moves: {int(samples.get('session_moves', 0))}   "
          f"failovers: "
          f"{int(samples.get('coordinator_failovers', 0))}   "
          f"drains: {int(samples.get('graphd_drains', 0))}   "
          f"kill owner-dead: "
          f"{int(samples.get('kill_owner_dead', 0))}")
    print(f"  capacity sheds: {int(sheds)}")


def dump_fleet(addrs: List[str], path: str = "/metrics") -> int:
    """Fleet coordination view (ISSUE 20): each graphd's live session
    count (`graph_sessions`), statements served by kind (the goodput
    ledger — `query_latency_us_hist_count{kind}`), epoch-propagation
    lag mean, and the failover-plane counters (session moves,
    coordinator failovers, drains, owner-dead kills, capacity sheds)
    — per host plus one cluster-merged section.  Combine with --watch
    for per-interval goodput/lag deltas per coordinator."""
    per_host, merged = scrape_cluster(addrs, path)
    n = 0
    for addr in sorted(per_host):
        samples = _fleet_filter(per_host[addr])
        print(f"== {addr} ({len(samples)} fleet samples)")
        if samples:
            _print_fleet_rows(samples)
            n += len(samples)
    if len(per_host) > 1:
        print(f"== merged ({len(per_host)}/{len(addrs)} hosts)")
        _print_fleet_rows(_fleet_filter(merged))
    return n


def _scrape_fleet_view(addrs: List[str], path: str = "/metrics"
                       ) -> Tuple[Dict[str, Dict[str, float]],
                                  Dict[str, float]]:
    per_host, merged = scrape_cluster(addrs, path)
    return ({a: _fleet_filter(s) for a, s in per_host.items()},
            _fleet_filter(merged))


def dump_trace_list(addr: str) -> int:
    traces = json.loads(_fetch(addr, "/traces"))
    for t in traces:
        print(f"{t['tid']}  {t['name']:<28} spans={t['spans']:<4} "
              f"{t['dur_us']}us")
    return len(traces)


def dump_trace(addr: str, tid: str):
    print(_fetch(addr, f"/traces?id={tid}&format=text"))


def dump_flight(addr: str, entry_id: str = "") -> int:
    if entry_id:
        print(_fetch(addr, f"/flight?id={entry_id}"))
        return 1
    entries = json.loads(_fetch(addr, "/flight"))
    for e in entries:
        print(f"#{e['id']:<5} {e['status']:<9} {e['kind']:<10} "
              f"{e['latency_us']}us ops={e['operators']:<3} "
              f"{e['stmt'][:60]}")
    return len(entries)


def dump_queries(addr: str) -> int:
    """Live workload rows (GET /queries): in-flight statements with
    per-operator progress, then the device dispatch table."""
    got = json.loads(_fetch(addr, "/queries"))
    qs = got.get("queries", [])
    for e in qs:
        print(f"q{e['qid']:<5} sess={e['session']:<4} "
              f"{e['status']:<8} {e['operator']:<24} "
              f"rows={e['rows']:<8} dur={e['duration_us']}us "
              f"queue={e['queue_us']}us dev={e['device_us']}us "
              f"host={e['host_us']}us  {e['stmt'][:50]}")
    for d in got.get("dispatches", []):
        print(f"dispatch#{d['seq']} {d['kernel']:<10} {d['state']:<8} "
              f"wait={d['wait_us']}us run={d['run_us']}us "
              f"qid={d.get('qid')}")
    return len(qs)


def dump_repairs(addr: str) -> int:
    """Auto-repair plans (GET /repairs on a metad, ISSUE 14): the
    raft-persisted RepairPlan table the PartSupervisor drives — one
    line per plan with its phase/status, newest last."""
    entries = json.loads(_fetch(addr, "/repairs"))
    for r in entries:
        err = f"  err={r['error']}" if r.get("error") else ""
        # target is None for remove-only plans (live members already
        # satisfy rf; only the dead replica needs dropping)
        tgt = r["target"] if r.get("target") else "-"
        print(f"#{r['rid']:<4} {r['space']}/{r['part']:<3} "
              f"dead={r['dead']:<22} target={tgt:<22} "
              f"{r['phase']:<12} {r['status']:<8}{err}")
    return len(entries)


def dump_stalls(addr: str, entry_id: str = "") -> int:
    if entry_id:
        print(_fetch(addr, f"/stalls?id={entry_id}"))
        return 1
    entries = json.loads(_fetch(addr, "/stalls"))
    for e in entries:
        subj = e.get("subject", {})
        what = subj.get("stmt") or subj.get("kernel") or ""
        print(f"#{e['id']:<4} {e['kind']:<10} "
              f"elapsed={e['elapsed_s']}s thr={e['threshold_s']}s "
              f"threads={e['threads']:<3} {str(what)[:60]}")
    return len(entries)


# -- Perfetto / Chrome trace-event export (ISSUE 9 satellite) ---------------


def to_perfetto(per_addr_traces: Dict[str, List[dict]],
                per_addr_stalls: "Dict[str, List[dict]] | None" = None
                ) -> dict:
    """Convert trace-store entries (each `{tid, name, spans}`) and
    stall captures into the Chrome trace-event JSON Perfetto loads.

    Track layout: one PROCESS per scraped daemon (its webservice addr)
    and one THREAD per service role that emitted spans there — so a
    stitched cluster trace renders graphd / storaged / metad / device
    spans on separate tracks, remote spans under the daemon that
    produced them.  Span attrs ride in `args`; stall captures become
    global instant events carrying their thread-stack summary."""
    events: List[dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}

    def pid_of(addr: str) -> int:
        if addr not in pids:
            pids[addr] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[addr],
                           "args": {"name": addr}})
        return pids[addr]

    def tid_of(addr: str, svc: str) -> int:
        key = (addr, svc)
        if key not in tids:
            tids[key] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid_of(addr), "tid": tids[key],
                           "args": {"name": svc}})
        return tids[key]

    for addr, traces in sorted(per_addr_traces.items()):
        for entry in traces:
            for s in entry.get("spans", []):
                svc = str(s.get("svc") or "unknown")
                if s.get("remote"):
                    svc += " [remote]"
                ev = {"name": s.get("name", "?"), "cat": svc,
                      "ph": "X",
                      "ts": float(s.get("t0", 0.0)) * 1e6,
                      "dur": int(s.get("dur_us", 0)),
                      "pid": pid_of(addr), "tid": tid_of(addr, svc),
                      "args": {"trace": s.get("tid"),
                               **(s.get("attrs") or {})}}
                events.append(ev)
    for addr, stalls in sorted((per_addr_stalls or {}).items()):
        for e in stalls:
            subj = e.get("subject", {})
            events.append({
                "name": f"stall:{e.get('kind', '?')}",
                "cat": "stall", "ph": "i", "s": "g",
                "ts": float(e.get("ts", 0.0)) * 1e6,
                "pid": pid_of(addr), "tid": tid_of(addr, "watchdog"),
                "args": {"elapsed_s": e.get("elapsed_s"),
                         "threshold_s": e.get("threshold_s"),
                         "subject": {k: v for k, v in subj.items()
                                     if k != "stacks"},
                         "threads": sorted(e.get("stacks", {}))}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _collect_traces(addr: str) -> List[dict]:
    out = []
    for t in json.loads(_fetch(addr, "/traces")):
        try:
            out.append(json.loads(_fetch(addr,
                                         f"/traces?id={t['tid']}")))
        except (OSError, ValueError):
            continue
    return out


def _collect_stalls(addr: str) -> List[dict]:
    out = []
    try:
        summaries = json.loads(_fetch(addr, "/stalls"))
    except (OSError, ValueError):
        return out
    for s in summaries:
        try:
            out.append(json.loads(_fetch(addr,
                                         f"/stalls?id={s['id']}")))
        except (OSError, ValueError):
            continue
    return out


def dump_perfetto(addrs: List[str], out_path: str) -> int:
    """Scrape every addr's traces + stall captures and write one
    Perfetto-loadable trace-event file.  Returns the event count."""
    traces: Dict[str, List[dict]] = {}
    stalls: Dict[str, List[dict]] = {}
    for addr in addrs:
        try:
            traces[addr] = _collect_traces(addr)
        except OSError as ex:
            print(f"scrape of {addr} failed: {ex}", file=sys.stderr)
            continue
        stalls[addr] = _collect_stalls(addr)
    doc = to_perfetto(traces, stalls)
    with open(out_path, "w") as f:
        json.dump(doc, f)
    n = len(doc["traceEvents"])
    print(f"wrote {n} events from {len(traces)} host(s) to {out_path}")
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="metrics-dump")
    ap.add_argument("--addr", default="",
                    help="webservice host:port of one daemon")
    ap.add_argument("--addrs", default="",
                    help="comma-separated webservice addrs of the whole "
                         "cluster (per-host + merged output)")
    ap.add_argument("--path", default="/metrics",
                    help="metrics path to scrape (e.g. /cluster_metrics "
                         "on a metad)")
    ap.add_argument("--traces", action="store_true",
                    help="list recent traces instead of metrics")
    ap.add_argument("--trace", default="",
                    help="print one trace's span tree by id "
                         "('latest' = newest recorded trace)")
    ap.add_argument("--flight", action="store_true",
                    help="list flight-recorder entries")
    ap.add_argument("--flight-id", default="",
                    help="print one flight entry's full per-operator "
                         "breakdown")
    ap.add_argument("--queries", action="store_true",
                    help="live workload rows: in-flight statements "
                         "with per-operator progress + the device "
                         "dispatch table (GET /queries)")
    ap.add_argument("--stalls", action="store_true",
                    help="stall-watchdog captures (GET /stalls)")
    ap.add_argument("--repairs", action="store_true",
                    help="auto-repair plans from a metad "
                         "(GET /repairs): phase/status per plan")
    ap.add_argument("--statements", action="store_true",
                    help="statement fingerprint tables "
                         "(GET /statements on graphds): per-host + "
                         "exactly-merged; combine with --watch for "
                         "call/error deltas")
    ap.add_argument("--hotspots", action="store_true",
                    help="per-partition heat rows (GET /hotspots on "
                         "storageds, or a metad for the cluster-ranked "
                         "view); combine with --watch for read/write "
                         "deltas")
    ap.add_argument("--shards", action="store_true",
                    help="sharded mesh execution view (ISSUE 17): "
                         "per-device HBM ledger + frontier-exchange "
                         "bytes per host and merged; combine with "
                         "--watch for exchange deltas")
    ap.add_argument("--deltas", action="store_true",
                    help="delta-CSR view (ISSUE 19): per-shard delta "
                         "fill, repin-avoided share and recent "
                         "compaction swaps per host and merged; "
                         "combine with --watch for apply/compaction "
                         "deltas")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet coordination view (ISSUE 20): "
                         "per-coordinator sessions / statements by "
                         "kind / epoch-propagation lag / failover "
                         "counters per host and merged; combine with "
                         "--watch for goodput deltas")
    ap.add_argument("--stall-id", default="",
                    help="print one stall capture in full (thread "
                         "stacks, dispatch table, kernel ledger)")
    ap.add_argument("--perfetto", default="",
                    help="write every scraped trace tree (+ stall "
                         "captures) to FILE as Chrome trace-event "
                         "JSON loadable in Perfetto")
    ap.add_argument("--grep", default="",
                    help="only metric lines containing this substring")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="re-scrape every N seconds and print only "
                         "counters that changed (delta mode)")
    ap.add_argument("--iterations", type=int, default=0,
                    help="watch iterations before exiting (0 = forever; "
                         "for scripted use)")
    args = ap.parse_args(argv)
    addrs = [a for a in args.addrs.split(",") if a]
    if not addrs and args.addr:
        addrs = [args.addr]
    if not addrs:
        ap.error("need --addr or --addrs")
    one = addrs[0]
    if len(addrs) > 1 and (args.trace or args.traces or args.flight
                           or args.flight_id or args.queries
                           or args.stalls or args.stall_id
                           or args.repairs):
        # traces/flight/workload entries are per-process state, not
        # mergeable samples — be explicit about which host answers
        print(f"note: --traces/--trace/--flight/--queries/--stalls "
              f"query a single host; using {one}", file=sys.stderr)
    try:
        if args.perfetto:
            dump_perfetto(addrs, args.perfetto)
        elif args.statements:
            if args.watch > 0:
                watch_cluster(addrs, args.watch, args.grep,
                              args.iterations,
                              scrape_fn=lambda: scrape_cluster_view(
                                  addrs, "/statements",
                                  _statement_samples))
            else:
                dump_statements(addrs)
        elif args.shards:
            if args.watch > 0:
                watch_cluster(addrs, args.watch, args.grep,
                              args.iterations,
                              scrape_fn=lambda: _scrape_shard_view(
                                  addrs, args.path))
            else:
                dump_shards(addrs, args.path)
        elif args.deltas:
            if args.watch > 0:
                watch_cluster(addrs, args.watch, args.grep,
                              args.iterations,
                              scrape_fn=lambda: _scrape_delta_view(
                                  addrs, args.path))
            else:
                dump_deltas(addrs, args.path)
        elif args.fleet:
            if args.watch > 0:
                watch_cluster(addrs, args.watch, args.grep,
                              args.iterations,
                              scrape_fn=lambda: _scrape_fleet_view(
                                  addrs, args.path))
            else:
                dump_fleet(addrs, args.path)
        elif args.hotspots:
            if args.watch > 0:
                watch_cluster(addrs, args.watch, args.grep,
                              args.iterations,
                              scrape_fn=lambda: scrape_cluster_view(
                                  addrs, "/hotspots", _heat_samples))
            else:
                dump_hotspots(addrs)
        elif args.queries:
            dump_queries(one)
        elif args.repairs:
            dump_repairs(one)
        elif args.stalls or args.stall_id:
            dump_stalls(one, args.stall_id)
        elif args.trace:
            tid = args.trace
            if tid == "latest":
                traces = json.loads(_fetch(one, "/traces"))
                if not traces:
                    print("no traces recorded", file=sys.stderr)
                    return 1
                tid = traces[0]["tid"]
            dump_trace(one, tid)
        elif args.traces:
            dump_trace_list(one)
        elif args.flight or args.flight_id:
            dump_flight(one, args.flight_id)
        elif args.watch > 0:
            watch_cluster(addrs, args.watch, args.grep,
                          args.iterations, args.path)
        elif len(addrs) > 1:
            dump_cluster(addrs, args.grep, args.path)
        else:
            dump_metrics(one, args.grep, args.path)
    except OSError as ex:
        print(f"scrape failed: {ex}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
