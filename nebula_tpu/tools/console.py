"""Interactive nGQL console — the nebula-console analog.

Usage:
    python -m nebula_tpu.tools.console            # REPL
    python -m nebula_tpu.tools.console -e 'STMT'  # one-shot
    python -m nebula_tpu.tools.console -f file.ngql
    python -m nebula_tpu.tools.console --addr host:port   # cluster graphd

Without --addr it runs an in-process engine (single-process mode).
"""
from __future__ import annotations

import argparse
import sys
import time

from ..core.value import value_to_string
from ..exec.engine import QueryEngine, Session


def format_result(r) -> str:
    if not r.ok:
        return f"[ERROR] {r.error}"
    ds = r.data
    if not ds.column_names:
        return f"Execution succeeded (time spent {r.latency_us}us)"
    widths = [len(c) for c in ds.column_names]
    srows = []
    for row in ds.rows:
        sr = [value_to_string(c) for c in row]
        for i, s in enumerate(sr):
            widths[i] = max(widths[i], min(len(s), 60))
        srows.append(sr)
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep,
           "|" + "|".join(f" {c:<{widths[i]}} " for i, c in
                          enumerate(ds.column_names)) + "|",
           sep]
    for sr in srows:
        out.append("|" + "|".join(
            f" {s[:60]:<{widths[i]}} " for i, s in enumerate(sr)) + "|")
    out.append(sep)
    out.append(f"Got {len(ds.rows)} rows (time spent {r.latency_us}us)")
    return "\n".join(out)


def split_statements(text: str) -> list:
    """Split on top-level `;` (quote- and escape-aware, matching the
    tokenizer's string rules) so each statement's result prints
    separately; the engine also accepts the unsplit compound form."""
    out, buf, q, esc = [], [], None, False
    for ch in text:
        if q:
            buf.append(ch)
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == q:
                q = None
        elif ch in "'\"`":
            q = ch
            buf.append(ch)
        elif ch == ";":
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    out.append("".join(buf))
    return [s for s in (x.strip() for x in out) if s]


def main(argv=None):
    ap = argparse.ArgumentParser(prog="nebula-tpu-console")
    ap.add_argument("-e", "--execute", help="run one statement and exit")
    ap.add_argument("-f", "--file", help="run statements from a file")
    ap.add_argument("--addr", help="connect to a cluster graphd host:port")
    ap.add_argument("--user", default="root")
    ap.add_argument("--password", default="nebula")
    ap.add_argument("--data-dir",
                    help="durable standalone store (journal + checkpoint "
                         "recovery); default is in-memory")
    args = ap.parse_args(argv)

    if args.addr:
        from ..cluster.client import GraphClient
        host, port = args.addr.rsplit(":", 1)
        client = GraphClient(host, int(port))
        client.authenticate(args.user, args.password)
        execute = client.execute
    else:
        if args.data_dir:
            from ..graphstore.store import GraphStore
            eng = QueryEngine(GraphStore(data_dir=args.data_dir))
        else:
            eng = QueryEngine()
        sess = eng.new_session(args.user)
        execute = lambda text: eng.execute(sess, text)  # noqa: E731

    def run_one(text: str) -> int:
        text = text.strip()
        if not text:
            return 0
        r = execute(text)
        print(format_result(r))
        return 0 if r.ok else 1

    if args.execute:
        rc = 0
        for stmt in split_statements(args.execute):
            rc |= run_one(stmt)
        return rc
    if args.file:
        with open(args.file) as f:
            buf = f.read()
        rc = 0
        for stmt in split_statements(buf):
            rc |= run_one(stmt)
        return rc

    print("Welcome to nebula-tpu console. Type `:quit' to exit.")
    buf = ""
    while True:
        try:
            prompt = "nebula-tpu> " if not buf else "          -> "
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            break
        if line.strip() in (":quit", ":exit", "quit", "exit"):
            break
        buf += line + "\n"
        if ";" in line or not line.endswith("\\"):
            for stmt in split_statements(buf):
                run_one(stmt)
            buf = ""
    return 0


if __name__ == "__main__":
    sys.exit(main())
