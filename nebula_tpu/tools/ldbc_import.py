"""ldbc-import — bulk CSV loader (the nebula-importer analog, in-tree
per SURVEY §2 row 31 because the benchmarks need it).

Loads vertex and edge CSVs into a space, using the native csv_ingest
parser when available (falling back to csv.reader), and optionally
writes a checkpoint for later restore.

    python -m nebula_tpu.tools.ldbc_import --space snb \
        --vid-type INT64 --parts 8 \
        --vertices Person:person.csv:id,firstName:string,age:int \
        --edges KNOWS:knows.csv:src,dst,since:int \
        [--checkpoint DIR] [--delimiter '|']

Spec grammar:  TAG:file:idcol[,prop:type...]   (vertices)
               ETYPE:file:srccol,dstcol[,prop:type...]  (edges)
Types: int, float, string.  Column order must match the file.
"""
from __future__ import annotations

import argparse
import csv
import sys
import time
from typing import List, Optional, Tuple

from ..graphstore.schema import PropDef, PropType
from ..graphstore.store import GraphStore

_PT = {"int": PropType.INT64, "float": PropType.DOUBLE,
       "string": PropType.STRING}


def _conv(t: str, raw: str):
    return int(raw) if t == "int" else float(raw) if t == "float" else raw


def _parse_props(parts: List[str]) -> List[Tuple[str, str]]:
    out = []
    for p in parts:
        if ":" not in p:
            raise SystemExit(f"bad prop spec `{p}' (want name:type)")
        n, t = p.split(":", 1)
        if t not in _PT:
            raise SystemExit(f"bad prop type `{t}' in `{p}'")
        out.append((n, t))
    return out


def _read_rows(path: str, delim: str, header: bool):
    with open(path, newline="") as f:
        r = csv.reader(f, delimiter=delim)
        if header:
            next(r, None)
        yield from r


def _native_columns(path: str, delim: str, header: bool, n_keys: int,
                    props) -> Optional[list]:
    """Typed columns via the native parser when every column is numeric;
    None → caller uses the csv.reader path."""
    if not all(t in ("int", "float") for _, t in props):
        return None
    from ..native.kernels import csv_ingest
    types = ["int"] * n_keys + [t for _, t in props]
    return csv_ingest(path, types, delim=delim, skip_header=header)


def import_vertices(store: GraphStore, space: str, spec: str, delim: str,
                    vid_is_int: bool, header: bool) -> int:
    tag, path, cols = spec.split(":", 2)
    colspecs = cols.split(",")
    props = _parse_props(colspecs[1:])
    store.catalog.create_tag(space, tag,
                             [PropDef(n, _PT[t]) for n, t in props],
                             if_not_exists=True)
    if vid_is_int:
        got = _native_columns(path, delim, header, 1, props)
        if got is not None:
            vids, pcols = got[0], got[1:]
            for i in range(len(vids)):
                pv = {name: _conv(t, pcols[j][i])
                      for j, (name, t) in enumerate(props)}
                store.insert_vertex(space, int(vids[i]), tag, pv)
            return len(vids)
    n = 0
    for row in _read_rows(path, delim, header):
        vid = int(row[0]) if vid_is_int else row[0]
        pv = {name: _conv(t, row[i])
              for i, (name, t) in enumerate(props, start=1)}
        store.insert_vertex(space, vid, tag, pv)
        n += 1
    return n


def import_edges(store: GraphStore, space: str, spec: str, delim: str,
                 vid_is_int: bool, header: bool) -> int:
    etype, path, cols = spec.split(":", 2)
    colspecs = cols.split(",")
    props = _parse_props(colspecs[2:])
    store.catalog.create_edge(space, etype,
                              [PropDef(n, _PT[t]) for n, t in props],
                              if_not_exists=True)
    if vid_is_int:
        got = _native_columns(path, delim, header, 2, props)
        if got is not None:
            srcs, dsts = got[0], got[1]
            pcols = got[2:]
            for i in range(len(srcs)):
                pv = {name: _conv(t, pcols[j][i])
                      for j, (name, t) in enumerate(props)}
                store.insert_edge(space, int(srcs[i]), etype,
                                  int(dsts[i]), 0, pv)
            return len(srcs)
    n = 0
    for row in _read_rows(path, delim, header):
        src = int(row[0]) if vid_is_int else row[0]
        dst = int(row[1]) if vid_is_int else row[1]
        pv = {name: _conv(t, row[i])
              for i, (name, t) in enumerate(props, start=2)}
        store.insert_edge(space, src, etype, dst, 0, pv)
        n += 1
    return n


def main(argv=None):
    ap = argparse.ArgumentParser(prog="nebula-tpu-ldbc-import")
    ap.add_argument("--space", required=True)
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--vid-type", default="INT64",
                    choices=["INT64", "FIXED_STRING(32)"])
    ap.add_argument("--vertices", action="append", default=[],
                    help="TAG:file:idcol[,prop:type...]")
    ap.add_argument("--edges", action="append", default=[],
                    help="ETYPE:file:src,dst[,prop:type...]")
    ap.add_argument("--delimiter", default=",")
    ap.add_argument("--header", dest="header", action="store_true",
                    default=True, help="first CSV row is a header (default)")
    ap.add_argument("--no-header", dest="header", action="store_false")
    ap.add_argument("--checkpoint", default=None,
                    help="write a restorable checkpoint here when done")
    args = ap.parse_args(argv)

    store = GraphStore()
    store.create_space(args.space, partition_num=args.parts,
                       vid_type=args.vid_type, if_not_exists=True)
    vid_is_int = args.vid_type == "INT64"
    t0 = time.perf_counter()
    total_v = total_e = 0
    for spec in args.vertices:
        n = import_vertices(store, args.space, spec, args.delimiter,
                            vid_is_int, args.header)
        total_v += n
        print(f"imported {n} vertices from {spec.split(':')[1]}")
    for spec in args.edges:
        n = import_edges(store, args.space, spec, args.delimiter,
                         vid_is_int, args.header)
        total_e += n
        print(f"imported {n} edges from {spec.split(':')[1]}")
    dt = time.perf_counter() - t0
    print(f"total: {total_v} vertices, {total_e} edges in {dt:.2f}s "
          f"({(total_v + total_e) / max(dt, 1e-9):,.0f} rows/s)")
    if args.checkpoint:
        store.checkpoint(args.checkpoint)
        print(f"checkpoint written to {args.checkpoint}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
