"""storage-perf — storage stress tool (the reference's storage_perf).

Hammers the storage op set (insert/getNeighbors/point-get mixes) against
an in-process store or a live cluster graphd, reporting ops/sec.

    python -m nebula_tpu.tools.storage_perf [--addr host:port]
        [--vertices N] [--edges N] [--reads N] [--batch B]
"""
from __future__ import annotations

import argparse
import random
import sys
import time


def main(argv=None):
    ap = argparse.ArgumentParser(prog="nebula-tpu-storage-perf")
    ap.add_argument("--addr", help="cluster graphd host:port (default: "
                                   "in-process store)")
    ap.add_argument("--vertices", type=int, default=10_000)
    ap.add_argument("--edges", type=int, default=50_000)
    ap.add_argument("--reads", type=int, default=10_000)
    ap.add_argument("--batch", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    rng = random.Random(args.seed)

    if args.addr:
        from ..cluster.client import GraphClient
        host, port = args.addr.rsplit(":", 1)
        cli = GraphClient(host, int(port))
        cli.authenticate()

        def run(q):
            rs = cli.execute(q)
            if rs.error:
                raise RuntimeError(rs.error)
            return rs
    else:
        from ..exec.engine import QueryEngine
        eng = QueryEngine()
        sess = eng.new_session()

        def run(q):
            rs = eng.execute(sess, q)
            if rs.error:
                raise RuntimeError(rs.error)
            return rs

    run("CREATE SPACE IF NOT EXISTS perf(partition_num=8, vid_type=INT64)")
    time.sleep(0.2 if args.addr else 0)
    run("USE perf")
    run("CREATE TAG IF NOT EXISTS node(a int)")
    run("CREATE EDGE IF NOT EXISTS rel(w int)")

    def timed(label, n_ops, fn):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        print(f"{label}: {n_ops} ops in {dt:.2f}s = {n_ops / dt:,.0f} op/s")

    V, E, B = args.vertices, args.edges, args.batch

    def insert_vertices():
        for lo in range(0, V, B):
            vals = ", ".join(f"{i}:({i})" for i in range(lo, min(lo + B, V)))
            run(f"INSERT VERTEX node(a) VALUES {vals}")
    timed("insert vertex", V, insert_vertices)

    def insert_edges():
        for lo in range(0, E, B):
            vals = ", ".join(
                f"{rng.randrange(V)}->{rng.randrange(V)}:({i})"
                for i in range(lo, min(lo + B, E)))
            run(f"INSERT EDGE rel(w) VALUES {vals}")
    timed("insert edge", E, insert_edges)

    read_iters = max(1, args.reads // B)
    read_ops = read_iters * B           # report ONLY work actually done

    def point_reads():
        for _ in range(read_iters):
            ids = ", ".join(str(rng.randrange(V)) for _ in range(B))
            run(f"FETCH PROP ON node {ids} YIELD node.a")
    timed("point fetch", read_ops, point_reads)

    def neighbors():
        for _ in range(read_iters):
            ids = ", ".join(str(rng.randrange(V)) for _ in range(B))
            run(f"GO FROM {ids} OVER rel YIELD dst(edge)")
    timed("getNeighbors", read_ops, neighbors)
    return 0


if __name__ == "__main__":
    sys.exit(main())
