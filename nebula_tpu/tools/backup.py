"""Offline backup/restore tool — the `br` binary analog.

The statement surface (CREATE BACKUP / SHOW BACKUPS / DROP BACKUP /
RESTORE BACKUP) covers the online standalone store; this tool covers
the offline legs the reference handles with its br binary
(reference: the br repo's backup/restore against stopped services
[UNVERIFIED — empty mount, SURVEY §0]):

    python -m nebula_tpu.tools.backup create  --data-dir D --out B
    python -m nebula_tpu.tools.backup list    --dir BACKUPS_DIR
    python -m nebula_tpu.tools.backup restore --data-dir D --backup B

`create` opens the durable store (recovering checkpoint + journal),
writes a restorable checkpoint to --out, and exits.  `restore` opens
the store, swaps in the backup's state, and compacts so the data dir
boots the restored world.  For a cluster, run restore against each
storaged's data dir with the services stopped — the same contract the
reference's br imposes.
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def _open(data_dir: str):
    from ..graphstore.store import GraphStore
    return GraphStore(data_dir=data_dir)


def cmd_create(args) -> int:
    from ..exec.jobs import write_backup_meta
    st = _open(args.data_dir)
    try:
        manifest = st.checkpoint(args.out)
        write_backup_meta(args.out, manifest)
        print(f"backup written to {args.out} "
              f"({len(manifest['spaces'])} spaces)")
    finally:
        st.close()
    return 0


def cmd_list(args) -> int:
    from ..exec.jobs import iter_backups
    n = 0
    for name, info in iter_backups(args.dir):
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                           time.gmtime(info.get("created", 0)))
        print(f"{name}\t{ts}\t{','.join(info.get('spaces') or [])}")
        n += 1
    if n == 0:
        print("(no backups)")
    return 0


def cmd_restore(args) -> int:
    if not os.path.isfile(os.path.join(args.backup, "manifest.json")):
        print(f"not a backup dir: {args.backup}", file=sys.stderr)
        return 1
    st = _open(args.data_dir)
    try:
        out = st.restore_backup(args.backup)
        print(f"restored spaces: {', '.join(out['spaces'])}")
    finally:
        st.close()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nebula_tpu.tools.backup")
    sub = ap.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("create", help="checkpoint a data dir to a backup")
    c.add_argument("--data-dir", required=True)
    c.add_argument("--out", required=True)
    c.set_defaults(fn=cmd_create)
    l = sub.add_parser("list", help="list backups under a directory")
    l.add_argument("--dir", required=True)
    l.set_defaults(fn=cmd_list)
    r = sub.add_parser("restore", help="restore a backup into a data dir")
    r.add_argument("--data-dir", required=True)
    r.add_argument("--backup", required=True)
    r.set_defaults(fn=cmd_restore)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
