"""csr-dump — inspect the device snapshot layout of a space.

Shows what would be pinned into HBM: per-(edge type, direction) block
shapes, per-part edge counts, property columns, padding overhead, and
total bytes — the capacity-planning view of the device plane.

    python -m nebula_tpu.tools.csr_dump <checkpoint_dir> --space NAME
"""
from __future__ import annotations

import argparse
import sys


def human(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.1f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


def main(argv=None):
    ap = argparse.ArgumentParser(prog="nebula-tpu-csr-dump")
    ap.add_argument("checkpoint", help="checkpoint directory")
    ap.add_argument("--space", required=True)
    args = ap.parse_args(argv)

    from ..graphstore.csr import build_snapshot
    from ..graphstore.store import GraphStore
    store = GraphStore.from_checkpoint(args.checkpoint)
    snap = build_snapshot(store, args.space)
    print(f"space `{args.space}': epoch={snap.epoch} "
          f"parts={snap.num_parts} vmax={snap.vmax} "
          f"total={human(snap.hbm_bytes())}")
    for (et, dirn), b in sorted(snap.blocks.items()):
        per_part = [b.edges_of_part(p) for p in range(b.num_parts)]
        emax = b.nbr.shape[1]
        used = sum(per_part)
        pad = b.num_parts * emax - used
        nbytes = b.indptr.nbytes + b.nbr.nbytes + b.rank.nbytes + \
            sum(a.nbytes for a in b.props.values())
        print(f"  block ({et}, {dirn}): edges={used} emax={emax} "
              f"pad={pad} ({human(nbytes)})")
        print(f"    per-part: {per_part}")
        for name, a in sorted(b.props.items()):
            print(f"    prop {name}: {a.dtype} {human(a.nbytes)}")
    for name, t in sorted(snap.tags.items()):
        nbytes = t.present.nbytes + sum(a.nbytes for a in t.props.values())
        print(f"  tag table {name}: present={int(t.present.sum())} "
              f"({human(nbytes)})")
    print(f"string pool: {len(snap.pool)} entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
