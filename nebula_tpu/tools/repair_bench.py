"""repair-bench — time-to-full-redundancy under a permanent host kill
(ISSUE 14; the self-healing mirror of overload_bench.py).

The headline question of auto-repair: when one of a part's three
replicas dies for good under live read/write load, how long until the
cluster is back at FULL redundancy (every part rf-replicated on live
hosts, `under_replicated_parts` == 0) with NO operator action — and
how deep does goodput dip while the repair plane snapshot-installs the
replacement replicas?

Method: stand up a LocalCluster (1 metad / 4 storaged / 1 graphd),
create an rf=3 space (each part: three replicas, one spare host), run
closed-loop mixed INSERT/FETCH workers, hard-kill one storaged
mid-run, and poll the meta part map + repair table until every part is
healed.  Reported:

  time_to_full_redundancy_s   kill → part map fully rf=3 on live hosts
                              (includes the liveness horizon + grace —
                              the honest operator-visible number)
  goodput_before/during/after statements/s in each phase
  goodput_dip_ratio           worst during-repair rate vs before-kill
  acked_lost / wrong_rows     acked writes missing / wrong after heal
                              (must both be 0)
  repairs_done / failed       plan outcomes from the repair table

Usage:
    python -m nebula_tpu.tools.repair_bench
    python -m nebula_tpu.tools.repair_bench --rows 400 --duration 6

Emits one JSON object on stdout; bench.py folds it into the
`self_heal` block (acceptance: acked_lost == wrong_rows == 0 and the
part map reaches full redundancy unattended).
"""
from __future__ import annotations

import argparse
import json
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List


def run_self_heal(rows: int = 300, parts: int = 4, duration_s: float = 8.0,
                  workers: int = 4, heal_timeout_s: float = 60.0,
                  data_dir: str = "") -> Dict[str, Any]:
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.config import get_config
    from nebula_tpu.utils.stats import stats

    cfg = get_config()
    saved = {k: cfg.get(k) for k in
             ("host_hb_expire_secs", "repair_grace_secs",
              "repair_scan_interval_secs")}
    cfg.set_dynamic_many({"host_hb_expire_secs": 0.6,
                          "repair_grace_secs": 0.8,
                          "repair_scan_interval_secs": 0.1})
    tmp = data_dir or tempfile.mkdtemp(prefix="repair_bench_")
    cluster = LocalCluster(n_meta=1, n_storage=4, n_graph=1,
                           data_dir=tmp)
    acked: Dict[int, int] = {}
    acked_mu = threading.Lock()
    ok_times: List[float] = []
    stop = threading.Event()
    try:
        cl = cluster.client()
        for q in (f"CREATE SPACE heal(partition_num={parts}, "
                  f"replica_factor=3, vid_type=INT64)",):
            r = cl.execute(q)
            assert r.error is None, r.error
        cluster.reconcile_storage()
        cl.execute("USE heal")
        r = cl.execute("CREATE TAG item(x int)")
        assert r.error is None, r.error
        vals = ", ".join(f"{i}:({i})" for i in range(rows))
        r = cl.execute(f"INSERT VERTEX item(x) VALUES {vals}")
        assert r.error is None, r.error

        def worker(wid: int):
            c = cluster.client()
            c.execute("USE heal")
            j = 0
            while not stop.is_set():
                vid = 10_000 + wid * 100_000 + j
                r = c.execute(f"INSERT VERTEX item(x) VALUES "
                              f"{vid}:({vid % 997})")
                now = time.monotonic()
                if r.error is None:
                    with acked_mu:
                        acked[vid] = vid % 997
                        ok_times.append(now)
                r = c.execute(f"FETCH PROP ON item {j % rows} "
                              f"YIELD item.x AS x")
                if r.error is None:
                    with acked_mu:
                        ok_times.append(time.monotonic())
                j += 1
            c.close()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(workers)]
        for t in threads:
            t.start()
        warm_s = max(duration_s / 4.0, 1.0)
        time.sleep(warm_s)
        dead = cluster.storage_servers[0].addr
        t_kill = time.monotonic()
        cluster.stop_storaged(0)

        meta = cluster.graphds[0].meta
        healed_at = None
        deadline = time.monotonic() + heal_timeout_s
        while time.monotonic() < deadline:
            meta.refresh(force=True)
            pm = meta.parts_of("heal")
            if all(dead not in reps and len(reps) == 3 for reps in pm):
                healed_at = time.monotonic()
                break
            time.sleep(0.2)
        time.sleep(max(duration_s - warm_s, 1.0))
        stop.set()
        for t in threads:
            t.join(timeout=10)

        # verify every acked write against the healed replica set
        lost = wrong = 0
        with acked_mu:
            sample = sorted(acked.items())
        for i in range(0, len(sample), 64):
            chunk = sample[i:i + 64]
            r = cl.execute("FETCH PROP ON item " +
                           ", ".join(str(v) for v, _ in chunk) +
                           " YIELD id(vertex) AS v, item.x AS x")
            assert r.error is None, r.error
            got = {int(v): int(x) for v, x in r.data.rows}
            for vid, want in chunk:
                if vid not in got:
                    lost += 1
                elif got[vid] != want:
                    wrong += 1

        def rate(lo: float, hi: float) -> float:
            n = sum(1 for t in ok_times if lo <= t < hi)
            return round(n / max(hi - lo, 1e-9), 1)

        t_end = max(ok_times) if ok_times else t_kill
        before = rate(t_kill - warm_s, t_kill)
        during_hi = healed_at if healed_at is not None else t_end
        during = rate(t_kill, max(during_hi, t_kill + 1e-3))
        after = rate(during_hi, max(t_end, during_hi + 1e-3))
        repairs = meta.list_repairs()
        snap = stats().snapshot()
        return {
            "rows_seeded": rows, "workers": workers,
            "dead_host": dead,
            "healed": healed_at is not None,
            "time_to_full_redundancy_s":
                round(healed_at - t_kill, 2) if healed_at else None,
            "goodput_before_qps": before,
            "goodput_during_repair_qps": during,
            "goodput_after_qps": after,
            "goodput_dip_ratio":
                round(during / before, 3) if before else None,
            "acked_writes": len(sample),
            "acked_lost": lost, "wrong_rows": wrong,
            "repairs_done": sum(1 for r in repairs
                                if r["status"] == "DONE"),
            "repairs_failed": sum(1 for r in repairs
                                  if r["status"] == "FAILED"),
            "under_replicated_parts_final":
                snap.get("under_replicated_parts"),
        }
    finally:
        stop.set()
        cfg.set_dynamic_many(saved)
        cluster.stop()
        if not data_dir:
            shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="repair-bench")
    ap.add_argument("--rows", type=int, default=300)
    ap.add_argument("--parts", type=int, default=4)
    ap.add_argument("--duration", type=float, default=8.0)
    ap.add_argument("--workers", type=int, default=4)
    args = ap.parse_args(argv)
    out = run_self_heal(rows=args.rows, parts=args.parts,
                        duration_s=args.duration, workers=args.workers)
    print(json.dumps(out, indent=2))
    return 0 if out["healed"] and not out["acked_lost"] \
        and not out["wrong_rows"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
