"""chaos-bench — seeded fault-schedule runner over a live LocalCluster
(ISSUE 5; the fault-tolerance mirror of write_bench.py).

Each schedule arms a deterministic `FaultSchedule` (utils/failpoints:
every trigger decision is drawn from `random.Random(f"{seed}:{site}")`)
over a live 3-replica cluster, drives a seeded workload through the
public client, then measures what the robustness layer actually paid:

  recovery_s            faults stop → every part's live replicas export
                        byte-identical state and all TOSS journals drain
  retry_amplification   internal re-sends per acked statement
                        (replica-walk + RPC-client retries + meta leader
                        walks, from the deterministic counters)
  dedup_hits            re-sent writes answered from the exactly-once
                        window instead of double-applying

and re-asserts the chaos invariants (acked writes exactly once,
replicas converged) — a schedule that breaks them FAILS and prints a
one-line reproducer:

    REPRODUCE: python -m nebula_tpu.tools.chaos_bench --schedule <name> --seed <n>

The pytest twin of any failure is `tests/chaos/test_schedules.py` with
the same seed.  Usage:

    python -m nebula_tpu.tools.chaos_bench                 # all schedules
    python -m nebula_tpu.tools.chaos_bench --schedule reply_loss --seed 606

Emits one JSON object on stdout (CI-diffable, like write_bench);
bench.py folds recovery-time + amplification into its `fault_recovery`
block.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

# the harness lives with the chaos tests (it IS test infrastructure —
# this tool is its headless runner); resolve it relative to the repo
_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_CHAOS_DIR = os.path.join(_REPO, "tests", "chaos")
if _CHAOS_DIR not in sys.path:
    sys.path.insert(0, _CHAOS_DIR)

#: schedule → default seed (the ones the pytest twins pin)
DEFAULT_SEEDS = {
    "leader_kill": 101,
    "fsync_stall": 202,
    "torn_toss": 303,
    "meta_partition": 404,
    "reply_loss": 606,
}


def _counters():
    from nebula_tpu.utils.stats import stats
    snap = stats().snapshot()

    def total(prefix):
        return sum(v for k, v in snap.items() if k.startswith(prefix))

    return {
        "replica_walk_retries": total("storage_replica_walk_retries"),
        "rpc_client_retries": total("rpc_client_retries"),
        "meta_leader_walk_retries": snap.get("meta_leader_walk_retries", 0),
        "breaker_trips": snap.get("rpc_breaker_trips", 0),
        "breaker_short_circuits": snap.get("rpc_breaker_short_circuits", 0),
        "dedup_hits": snap.get("storage_write_dedup_hits", 0)
        + snap.get("storage_write_dedup_apply_skips", 0),
        "failpoints_fired": total("failpoint_fired"),
    }


def _settle(cc, require: int) -> float:
    """Seconds for the cluster to prove itself healthy again: replicas
    byte-identical + TOSS journals drained."""
    t0 = time.perf_counter()
    cc.wait_no_pending_chains()
    cc.wait_replicas_converged(require=require)
    return time.perf_counter() - t0


def _finish(cc, led, seed, fired, require: int) -> dict:
    from harness import assert_acked_exactly_once
    recovery_s = _settle(cc, require)
    assert_acked_exactly_once(cc, led)
    c = _counters()
    acked = len(led.acked)
    retries = (c["replica_walk_retries"] + c["rpc_client_retries"]
               + c["meta_leader_walk_retries"])
    return {
        "seed": seed,
        "acked": acked,
        "failed": len(led.failed),
        "faults_fired": fired,
        "recovery_s": round(recovery_s, 3),
        "retries": retries,
        "retry_amplification": round(retries / acked, 3) if acked else None,
        "counters": c,
        "invariants_ok": True,
    }


# -- schedules --------------------------------------------------------------


def sched_leader_kill(seed: int, writes: int) -> dict:
    """Hard-kill the storaged leading the most parts mid-workload; the
    tokened replica-walk retry must carry every statement through."""
    from harness import ChaosCluster
    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    cc = ChaosCluster(data_dir=tmp)
    try:
        half = threading.Event()
        led_box = {}

        def drive():
            # the workload thread flags the halfway point itself (vid
            # order is the seeded schedule, so "halfway" is data-
            # deterministic even though the kill lands asynchronously)
            from harness import WriteLedger
            led = WriteLedger()
            import random as _r
            rng = _r.Random(seed)
            for k in range(writes):
                vid = 1000 + k
                age = rng.randint(1, 99)
                r = cc.run(f'INSERT VERTEX Person(name, age) VALUES '
                           f'{vid}:("p{vid}",{age})')
                (led.ack(vid, {"age": age}) if r.error is None
                 else led.fail(vid, r.error))
                if k == writes // 2:
                    half.set()
            led_box["led"] = led

        t = threading.Thread(target=drive)
        t.start()
        half.wait(60.0)
        t_kill = time.perf_counter()
        cc.kill_storaged(cc.leader_of_most_parts())
        t.join()
        res = _finish(cc, led_box["led"], seed, 1, require=2)
        res["kill_to_drained_s"] = round(time.perf_counter() - t_kill, 3)
        return res
    finally:
        cc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def sched_fsync_stall(seed: int, writes: int) -> dict:
    """Random 80ms WAL fsync stalls on the storage plane."""
    from nebula_tpu.utils.failpoints import FaultSchedule, fail
    from harness import ChaosCluster, mixed_workload
    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    cc = ChaosCluster(data_dir=tmp)
    try:
        sched = FaultSchedule(seed, [
            {"fp": "wal:pre_fsync", "action": "delay", "arg": 0.08,
             "p": 0.35, "key": "storage", "max": 25},
        ]).arm(fail)
        led = mixed_workload(cc, seed=seed, n_writes=writes)
        sched.disarm(fail)
        return _finish(cc, led, seed, sum(sched.fired.values()), require=3)
    finally:
        cc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def sched_torn_toss(seed: int, writes: int) -> dict:
    """Tear TOSS chains between the journaled out-half and the in-half;
    the janitor must re-drive every journal (failed statements allowed,
    torn state not)."""
    from nebula_tpu.utils.failpoints import FaultSchedule, fail
    from harness import ChaosCluster, WriteLedger
    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    cc = ChaosCluster(data_dir=tmp)
    try:
        n = max(writes // 2, 10)
        for k in range(n):
            cc.ok(f'INSERT VERTEX Person(name, age) VALUES '
                  f'{9000 + k}:("t{k}",{k % 90 + 1})')
        sched = FaultSchedule(seed, [
            {"fp": "toss:pre_in", "action": "raise", "p": 0.5, "max": 4},
        ]).arm(fail)
        led = WriteLedger()
        for k in range(n):
            s, d = 9000 + k, 9000 + (k + 1) % n
            r = cc.run(f"INSERT EDGE KNOWS(w) VALUES {s}->{d}:({k})")
            # edge acks ride the same exactly-once invariant through the
            # ledger's vertex probe; torn statements may legally fail
            if r.error is not None:
                led.fail(s, r.error)
        sched.disarm(fail)
        for k in range(n):
            led.ack(9000 + k, {"age": k % 90 + 1})
        return _finish(cc, led, seed, sum(sched.fired.values()), require=3)
    finally:
        cc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def sched_meta_partition(seed: int, writes: int) -> dict:
    """3-metad quorum with half its replication rounds dropped."""
    from nebula_tpu.utils.failpoints import FaultSchedule, fail
    from harness import ChaosCluster, mixed_workload
    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    cc = ChaosCluster(n_meta=3, data_dir=tmp)
    try:
        sched = FaultSchedule(seed, [
            {"fp": "raft:replicate", "action": "raise", "p": 0.5,
             "key": "meta", "max": 60},
        ]).arm(fail)
        led = mixed_workload(cc, seed=seed, n_writes=writes,
                             vid_base=2000)
        sched.disarm(fail)
        return _finish(cc, led, seed, sum(sched.fired.values()), require=3)
    finally:
        cc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def sched_reply_loss(seed: int, writes: int) -> dict:
    """Kill acked storage.write replies at random — the dedup window's
    home turf; re-sends must land exactly once."""
    from nebula_tpu.utils.failpoints import FaultSchedule, fail
    from harness import ChaosCluster, mixed_workload
    tmp = tempfile.mkdtemp(prefix="chaos_bench_")
    cc = ChaosCluster(data_dir=tmp)
    try:
        sched = FaultSchedule(seed, [
            {"fp": "rpc:server_reply", "action": "raise", "p": 0.4,
             "key": "storage.write|ok", "max": 8},
        ]).arm(fail)
        led = mixed_workload(cc, seed=seed, n_writes=writes,
                             vid_base=3000)
        sched.disarm(fail)
        res = _finish(cc, led, seed, sum(sched.fired.values()), require=3)
        if sum(sched.fired.values()) and not res["counters"]["dedup_hits"]:
            raise AssertionError("replies were killed but no re-send "
                                 "was deduplicated")
        return res
    finally:
        cc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


SCHEDULES = {
    "leader_kill": sched_leader_kill,
    "fsync_stall": sched_fsync_stall,
    "torn_toss": sched_torn_toss,
    "meta_partition": sched_meta_partition,
    "reply_loss": sched_reply_loss,
}


def run(schedules=None, seed=None, writes: int = 40) -> dict:
    """Run the named schedules (default: all); returns per-schedule
    metrics plus the aggregate bench.py folds into `fault_recovery`.
    A broken invariant raises AFTER printing its reproducer line."""
    names = list(schedules or SCHEDULES)
    out = {"writes_per_schedule": writes, "schedules": {}}
    worst_recovery = 0.0
    total_retries = total_acked = 0
    for name in names:
        s = seed if seed is not None else DEFAULT_SEEDS[name]
        try:
            r = SCHEDULES[name](s, writes)
        except Exception:
            print(f"REPRODUCE: python -m nebula_tpu.tools.chaos_bench "
                  f"--schedule {name} --seed {s}", file=sys.stderr,
                  flush=True)
            raise
        out["schedules"][name] = r
        worst_recovery = max(worst_recovery, r["recovery_s"])
        total_retries += r["retries"]
        total_acked += r["acked"]
    out["worst_recovery_s"] = round(worst_recovery, 3)
    out["retry_amplification"] = (round(total_retries / total_acked, 3)
                                  if total_acked else None)
    out["invariants_ok"] = True
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--schedule", action="append",
                    choices=sorted(SCHEDULES),
                    help="schedule(s) to run (default: all)")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the schedule's pinned seed")
    ap.add_argument("--writes", type=int, default=40,
                    help="workload statements per schedule")
    args = ap.parse_args(argv)
    print(json.dumps(run(args.schedule, args.seed, args.writes),
                     indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
