"""Tools: console, csr-dump, db-dump analogs."""
