"""Synthetic LDBC-SNB-shaped graph generator.

The real LDBC datasets aren't available offline (zero egress), so the
bench harness generates a Person/KNOWS social graph with the properties
that matter for traversal benchmarking: heavy-tailed degree distribution
(supernodes — SURVEY §7 hard-part #4), string + int + float edge props
(predicate mask coverage), and hash partitioning across P parts.
"""
from __future__ import annotations

import numpy as np

from ..core.value import NULL
from ..graphstore.schema import PropDef, PropType
from ..graphstore.store import GraphStore

_NAMES = ["ada", "bob", "cid", "dee", "eve", "fay", "gus", "hal",
          "ivy", "joe", "kim", "lee", "mia", "ned", "oda", "pam"]


def make_social_graph(n_persons: int = 20_000, avg_degree: int = 25,
                      parts: int = 8, seed: int = 7, space: str = "snb",
                      store: GraphStore | None = None,
                      edge_props: bool = True) -> GraphStore:
    """Person vertices + KNOWS edges with a Zipf-ish degree tail.

    Vertex ids are ints 0..n-1.  Edge props: w INT64 (the benchmark's
    filter column), f DOUBLE, city STRING (dict-encodable).
    """
    rng = np.random.default_rng(seed)
    st = store if store is not None else GraphStore()
    st.create_space(space, partition_num=parts, vid_type="INT64")
    st.catalog.create_tag(space, "Person", [
        PropDef("age", PropType.INT64),
        PropDef("name", PropType.STRING)])
    eprops = [PropDef("w", PropType.INT64),
              PropDef("f", PropType.DOUBLE),
              PropDef("city", PropType.STRING)] if edge_props else []
    st.catalog.create_edge(space, "KNOWS", eprops)

    ages = rng.integers(13, 90, n_persons)
    name_ix = rng.integers(0, len(_NAMES), n_persons)
    for v in range(n_persons):
        st.insert_vertex(space, int(v), "Person",
                         {"age": int(ages[v]), "name": _NAMES[name_ix[v]]})

    n_edges = n_persons * avg_degree
    src = rng.integers(0, n_persons, n_edges)
    # dst mixture: mostly uniform (frontier growth under traversal) with a
    # Zipf tail (supernode destinations, like follower graphs)
    dst = rng.integers(0, n_persons, n_edges)
    hot = rng.random(n_edges) < 0.15
    dst[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
    w = rng.integers(0, 100, n_edges)
    f = rng.random(n_edges)
    city_ix = rng.integers(0, len(_NAMES), n_edges)
    for i in range(n_edges):
        s, d = int(src[i]), int(dst[i])
        if s == d:
            continue
        props = ({"w": int(w[i]), "f": float(f[i]),
                  "city": _NAMES[city_ix[i]]} if edge_props else {})
        st.insert_edge(space, s, "KNOWS", d, 0, props)
    return st


def write_snb_csvs(outdir: str, n_persons: int, avg_degree: int,
                   seed: int = 7):
    """LDBC-SNB-interactive-shaped CSV dumps ('|' delimited, header row)
    for the bulk import bench leg (VERDICT r3 item 6: the bench must
    build its graph THROUGH tools/ldbc_import, not around it).

    person.csv: id|age|name          (string column → csv.reader path)
    knows.csv:  src|dst|w|f          (all numeric → native csv_ingest)
    likes.csv:  src|dst|w|f          (second edge type: OVER * configs)

    Same degree distribution as make_social_graph (uniform dsts with a
    Zipf supernode tail, self-loops dropped); LIKES carries ~20% of
    KNOWS' volume.  Returns (person_path, knows_path, likes_path,
    n_person_rows, n_knows_rows, n_likes_rows)."""
    import os
    rng = np.random.default_rng(seed)
    ages = rng.integers(13, 90, n_persons)
    name_ix = rng.integers(0, len(_NAMES), n_persons)
    ppath = os.path.join(outdir, "person.csv")
    with open(ppath, "w") as f:
        f.write("id|age|name\n")
        f.writelines(f"{v}|{ages[v]}|{_NAMES[name_ix[v]]}\n"
                     for v in range(n_persons))

    def edge_file(name, n_edges):
        src = rng.integers(0, n_persons, n_edges)
        dst = rng.integers(0, n_persons, n_edges)
        hot = rng.random(n_edges) < 0.15
        dst[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
        keep = src != dst
        src, dst = src[keep], dst[keep]
        w = rng.integers(0, 100, src.size)
        fv = rng.random(src.size)
        path = os.path.join(outdir, name)
        with open(path, "w") as f:
            f.write("src|dst|w|f\n")
            f.writelines(f"{s}|{d}|{ww}|{ff!r}\n"
                         for s, d, ww, ff in zip(src.tolist(),
                                                 dst.tolist(),
                                                 w.tolist(), fv.tolist()))
        return path, int(src.size)

    kpath, nk = edge_file("knows.csv", n_persons * avg_degree)
    lpath, nl = edge_file("likes.csv", max(n_persons * avg_degree // 5, 1))
    return ppath, kpath, lpath, n_persons, nk, nl


def pick_seeds(store: GraphStore, space: str, k: int,
               min_degree: int = 1) -> list:
    """k vertex ids that actually have out-edges (traversal seeds)."""
    sd = store.space(space)
    seeds = []
    for p in sd.parts:
        for vid, per in p.out_edges.items():
            if sum(len(m) for m in per.values()) >= min_degree:
                seeds.append(vid)
                if len(seeds) >= k:
                    return seeds
    return seeds


# ---------------------------------------------------------------------------
# Array-native generation for north-star-scale graphs (tens of millions
# of edges).  The dict store can't hold SF100-shaped data in RAM, and
# the benchmark needs the CSR itself, so this path builds the
# CsrSnapshot directly from numpy arrays — same layout as
# graphstore.csr.build_snapshot (dense = vid, owner = vid % P).
# ---------------------------------------------------------------------------


def make_social_arrays(n_persons: int, avg_degree: int, seed: int = 7,
                       hot_frac: float = 0.15, src_hot_frac: float = 0.05):
    """Edge arrays with the same distribution as make_social_graph, PLUS
    an out-degree Zipf tail: frontier expansion follows OUT edges, so
    supernode pressure on the kernel's edge buckets only exists if some
    SOURCES are celebrities (a fan-out graph's follower lists).  In-tail
    alone (hot destinations) exercises only frontier dedup."""
    rng = np.random.default_rng(seed)
    n_edges = n_persons * avg_degree
    src = rng.integers(0, n_persons, n_edges, dtype=np.int64)
    dst = rng.integers(0, n_persons, n_edges, dtype=np.int64)
    hot = rng.random(n_edges) < hot_frac
    dst[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
    shot = rng.random(n_edges) < src_hot_frac
    src[shot] = (rng.zipf(1.5, int(shot.sum())) - 1) % n_persons
    keep = src != dst
    src, dst = src[keep], dst[keep]
    n_edges = src.size
    return {
        "n": n_persons,
        "src": src,
        "dst": dst,
        "w": rng.integers(0, 100, n_edges, dtype=np.int64),
        "f": rng.random(n_edges),
        "city": rng.integers(0, len(_NAMES), n_edges, dtype=np.int64),
    }


def _coo_to_padded_csr(owner, local, nbr_dense, vmax, P):
    """Vectorized COO → (P, ...) padded CSR.  Inputs must already be
    sorted by (owner, local, tiebreak).  Returns (indptr, nbr, order
    positions within part)."""
    counts = np.bincount(owner, minlength=P)
    emax = max(int(counts.max()), 1)
    row_id = owner * (vmax) + np.minimum(local, vmax - 1)
    per_vertex = np.bincount(row_id, minlength=P * vmax).reshape(P, vmax)
    indptr = np.zeros((P, vmax + 1), np.int64)
    np.cumsum(per_vertex, axis=1, out=indptr[:, 1:])
    starts = np.zeros(P + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(owner.size, dtype=np.int64) - starts[owner]
    nbr = np.full((P, emax), -1, np.int32)
    nbr[owner, pos] = nbr_dense.astype(np.int32)
    return indptr.astype(np.int32), nbr, pos, emax


def snapshot_from_arrays(arrs, parts: int = 8, space: str = "snb"):
    """Build a CsrSnapshot (out + in KNOWS blocks, w/f/city props)
    directly from edge arrays — the bulk-ingest path for benchmark-scale
    graphs."""
    from ..graphstore.csr import CsrSnapshot, StringPool
    from ..graphstore.csr import CsrBlock
    from ..graphstore.schema import PropType

    n, P = int(arrs["n"]), parts
    src, dst = arrs["src"], arrs["dst"]
    pool = StringPool()
    city_codes = np.asarray([pool.encode(s) for s in _NAMES],
                            np.int64)[arrs["city"]]
    counts = np.bincount(np.arange(n, dtype=np.int64) % P, minlength=P)
    vmax = max(int(counts.max()), 1)
    snap = CsrSnapshot(space=space, epoch=0, num_parts=P, vmax=vmax,
                       num_vertices=counts.astype(np.int32),
                       pool=pool,
                       dense_to_vid=list(range(n)))

    for direction in ("out", "in"):
        a, b = (src, dst) if direction == "out" else (dst, src)
        owner = a % P
        local = a // P
        order = np.lexsort((b, local, owner))
        ow, lo, nb = owner[order], local[order], b[order]
        indptr, nbr, pos, emax = _coo_to_padded_csr(ow, lo, nb, vmax, P)
        rank = np.zeros_like(nbr)
        props = {}
        for name, col, pt in (("w", arrs["w"], PropType.INT64),
                              ("f", arrs["f"], PropType.DOUBLE),
                              ("city", city_codes, PropType.STRING)):
            dt = np.float64 if pt == PropType.DOUBLE else np.int64
            padded = np.full((P, emax),
                             np.nan if dt == np.float64 else -2, dt)
            padded[ow, pos] = col[order].astype(dt)
            props[name] = padded
        snap.blocks[("KNOWS", direction)] = CsrBlock(
            etype="KNOWS", direction=direction, indptr=indptr, nbr=nbr,
            rank=rank, props=props,
            prop_types={"w": PropType.INT64, "f": PropType.DOUBLE,
                        "city": PropType.STRING})
    return snap


def host_csr_traverse(snap, seeds, steps: int, w_gt=None,
                      materialize: bool = False,
                      etypes=("KNOWS",)):
    """Vectorized numpy host baseline over the same CSR: per hop, gather
    neighbor ranges with repeat, dedup with np.unique.  This is the
    strongest honest CPU single-core baseline available here (a C++
    row-at-a-time engine does strictly more work per edge).

    `etypes` expands through multiple out-blocks per hop (the OVER *
    comparator).  Returns (edges_traversed, final_kept_edge_count) —
    and with materialize=True, also (dst_vids, w) numpy arrays of the
    final-hop result so the baseline pays the same output cost class
    the device E2E path does (VERDICT r1 weak #2: no flattering
    asymmetries).
    """
    P = snap.num_parts
    blks = [snap.block(et, "out") for et in etypes]
    frontier = np.unique(np.asarray(seeds, np.int64))
    total = 0
    for hop in range(steps):
        owner = frontier % P
        local = frontier // P
        nxts, ws = [], []
        for blk in blks:
            s = blk.indptr[owner, local].astype(np.int64)
            e = blk.indptr[owner, local + 1].astype(np.int64)
            deg = e - s
            total += int(deg.sum())
            if deg.sum() == 0:
                nxts.append(np.empty(0, np.int64))
                ws.append(np.empty(0, blk.props["w"].dtype))
                continue
            rows = np.repeat(np.arange(frontier.size), deg)
            offs = np.arange(deg.sum(), dtype=np.int64) - \
                np.repeat(np.cumsum(deg) - deg, deg)
            idx = s[rows] + offs
            nxts.append(blk.nbr[owner[rows], idx].astype(np.int64))
            if hop == steps - 1:
                ws.append(blk.props["w"][owner[rows], idx])
        nxt = np.concatenate(nxts) if len(nxts) > 1 else nxts[0]
        if nxt.size == 0:
            return (total, 0, None, None) if materialize else (total, 0)
        if hop == steps - 1:
            w = np.concatenate(ws) if len(ws) > 1 else ws[0]
            if w_gt is not None:
                keep = w > w_gt
                nxt, w = nxt[keep], w[keep]
            if materialize:
                return total, int(nxt.size), nxt, w
            return total, int(nxt.size)
        frontier = np.unique(nxt)
    return (total, 0, None, None) if materialize else (total, 0)


def host_bfs(snap, src_dense, steps: int, etype: str = "KNOWS"):
    """Numpy BFS comparator for config 5 (VERDICT r3 weak #5: BFS had no
    content oracle): level-synchronous BFS over the out-CSR, returning
    the full dense-id distance array (-1 unreached, 0..steps otherwise).
    The device BFS kernel's distance output must match element-for-
    element."""
    P = snap.num_parts
    blk = snap.block(etype, "out")
    n = len(snap.dense_to_vid)
    dist = np.full(n, -1, np.int32)
    fr = np.unique(np.asarray(src_dense, np.int64))
    dist[fr] = 0
    for hop in range(1, steps + 1):
        if fr.size == 0:
            break
        owner = fr % P
        local = fr // P
        s = blk.indptr[owner, local].astype(np.int64)
        e = blk.indptr[owner, local + 1].astype(np.int64)
        deg = e - s
        tot = int(deg.sum())
        if tot == 0:
            break
        rows = np.repeat(np.arange(fr.size), deg)
        offs = np.arange(tot, dtype=np.int64) - \
            np.repeat(np.cumsum(deg) - deg, deg)
        idx = s[rows] + offs
        nxt = np.unique(blk.nbr[owner[rows], idx].astype(np.int64))
        nxt = nxt[dist[nxt] < 0]
        dist[nxt] = hop
        fr = nxt
    return dist


def _expand_paths(blk, P, fr):
    """One path-expansion hop over an out-CSR block: (parent, dst, eid)
    for EVERY edge out of fr's entries — no dedup; this is path currency,
    not frontier currency.  eid is a globally-unique physical edge id
    (part-major slot index), the trail-dedup key."""
    owner = fr % P
    local = fr // P
    s = blk.indptr[owner, local].astype(np.int64)
    e = blk.indptr[owner, local + 1].astype(np.int64)
    deg = e - s
    tot = int(deg.sum())
    if tot == 0:
        z = np.empty(0, np.int64)
        return z, z, z
    parent = np.repeat(np.arange(fr.size, dtype=np.int64), deg)
    offs = np.arange(tot, dtype=np.int64) \
        - np.repeat(np.cumsum(deg) - deg, deg)
    idx = s[parent] + offs
    emax = blk.nbr.shape[1]
    eid = owner[parent] * emax + idx
    dst = blk.nbr[owner[parent], idx].astype(np.int64)
    return parent, dst, eid


def host_match_agg(snap, seeds_dense, min_age):
    """Numpy comparator for the IC-shaped config 3 (VERDICT r2 item 2:
    the honest CPU baseline): 2-hop path join p→f→ff with trail
    (distinct-edge) semantics, vertex-prop filter ff.age > min_age, and
    a group-count by ff.  Returns (ff_dense sorted, counts)."""
    P = snap.num_parts
    blk = snap.block("KNOWS", "out")
    fr = np.asarray(sorted(set(int(s) for s in seeds_dense)), np.int64)
    if fr.size == 0:
        z = np.empty(0, np.int64)
        return z, z
    r1, f, e1 = _expand_paths(blk, P, fr)
    r2, ff, e2 = _expand_paths(blk, P, f)
    keep = e2 != e1[r2]
    ff = ff[keep]
    age = snap.tags["Person"].props["age"][ff % P, ff // P]
    ff = ff[age > min_age]
    u, c = np.unique(ff, return_counts=True)
    return u, c


def host_trail_paths(snap, seeds_dense, max_hop):
    """Numpy comparator for config 4: count of variable-length *1..N
    trail paths (distinct edges within one path) from the seed set —
    level-joins with pairwise edge-id comparison, the same algorithm
    class the device frame assembly uses."""
    P = snap.num_parts
    blk = snap.block("KNOWS", "out")
    last = np.asarray(sorted(set(int(s) for s in seeds_dense)), np.int64)
    eids = []
    total = 0
    for _h in range(max_hop):
        if last.size == 0:
            break
        parent, dst, eid = _expand_paths(blk, P, last)
        if dst.size == 0:
            break
        keep = np.ones(dst.size, bool)
        for pe in eids:
            keep &= pe[parent] != eid
        total += int(keep.sum())
        sel = np.flatnonzero(keep)
        last = dst[sel]
        eids = [pe[parent[sel]] for pe in eids] + [eid[sel]]
    return total


class SnapshotStore:
    """Duck-typed GraphStore stand-in backed by a prebuilt CsrSnapshot —
    just enough surface for TpuRuntime.traverse/bfs (dense_id, epoch,
    edge-type catalog)."""

    class _SD:
        def __init__(self, n, epoch):
            self._n = n
            self.epoch = epoch

        def dense_id(self, v):
            v = int(v)
            return v if 0 <= v < self._n else -1

    class _Edge:
        edge_type = 1

    class _Catalog:
        def get_edge(self, space, et):
            return SnapshotStore._Edge()

    def __init__(self, snap):
        self.snap = snap
        self._sd = SnapshotStore._SD(len(snap.dense_to_vid), snap.epoch)
        self.catalog = SnapshotStore._Catalog()

    def space(self, name):
        return self._sd
