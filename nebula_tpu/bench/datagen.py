"""Synthetic LDBC-SNB-shaped graph generator.

The real LDBC datasets aren't available offline (zero egress), so the
bench harness generates a Person/KNOWS social graph with the properties
that matter for traversal benchmarking: heavy-tailed degree distribution
(supernodes — SURVEY §7 hard-part #4), string + int + float edge props
(predicate mask coverage), and hash partitioning across P parts.
"""
from __future__ import annotations

import numpy as np

from ..core.value import NULL
from ..graphstore.schema import PropDef, PropType
from ..graphstore.store import GraphStore

_NAMES = ["ada", "bob", "cid", "dee", "eve", "fay", "gus", "hal",
          "ivy", "joe", "kim", "lee", "mia", "ned", "oda", "pam"]


def make_social_graph(n_persons: int = 20_000, avg_degree: int = 25,
                      parts: int = 8, seed: int = 7, space: str = "snb",
                      store: GraphStore | None = None,
                      edge_props: bool = True) -> GraphStore:
    """Person vertices + KNOWS edges with a Zipf-ish degree tail.

    Vertex ids are ints 0..n-1.  Edge props: w INT64 (the benchmark's
    filter column), f DOUBLE, city STRING (dict-encodable).
    """
    rng = np.random.default_rng(seed)
    st = store if store is not None else GraphStore()
    st.create_space(space, partition_num=parts, vid_type="INT64")
    st.catalog.create_tag(space, "Person", [
        PropDef("age", PropType.INT64),
        PropDef("name", PropType.STRING)])
    eprops = [PropDef("w", PropType.INT64),
              PropDef("f", PropType.DOUBLE),
              PropDef("city", PropType.STRING)] if edge_props else []
    st.catalog.create_edge(space, "KNOWS", eprops)

    ages = rng.integers(13, 90, n_persons)
    name_ix = rng.integers(0, len(_NAMES), n_persons)
    for v in range(n_persons):
        st.insert_vertex(space, int(v), "Person",
                         {"age": int(ages[v]), "name": _NAMES[name_ix[v]]})

    n_edges = n_persons * avg_degree
    src = rng.integers(0, n_persons, n_edges)
    # dst mixture: mostly uniform (frontier growth under traversal) with a
    # Zipf tail (supernode destinations, like follower graphs)
    dst = rng.integers(0, n_persons, n_edges)
    hot = rng.random(n_edges) < 0.15
    dst[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
    w = rng.integers(0, 100, n_edges)
    f = rng.random(n_edges)
    city_ix = rng.integers(0, len(_NAMES), n_edges)
    for i in range(n_edges):
        s, d = int(src[i]), int(dst[i])
        if s == d:
            continue
        props = ({"w": int(w[i]), "f": float(f[i]),
                  "city": _NAMES[city_ix[i]]} if edge_props else {})
        st.insert_edge(space, s, "KNOWS", d, 0, props)
    return st


def write_snb_csvs(outdir: str, n_persons: int, avg_degree: int,
                   seed: int = 7):
    """LDBC-SNB-interactive-shaped CSV dumps ('|' delimited, header row)
    for the bulk import bench leg (VERDICT r3 item 6: the bench must
    build its graph THROUGH tools/ldbc_import, not around it).

    person.csv: id|age|name          (string column → csv.reader path)
    knows.csv:  src|dst|w|f          (all numeric → native csv_ingest)
    likes.csv:  src|dst|w|f          (second edge type: OVER * configs)

    Same degree distribution as make_social_graph (uniform dsts with a
    Zipf supernode tail, self-loops dropped); LIKES carries ~20% of
    KNOWS' volume.  Returns (person_path, knows_path, likes_path,
    n_person_rows, n_knows_rows, n_likes_rows)."""
    import os
    rng = np.random.default_rng(seed)
    ages = rng.integers(13, 90, n_persons)
    name_ix = rng.integers(0, len(_NAMES), n_persons)
    ppath = os.path.join(outdir, "person.csv")
    with open(ppath, "w") as f:
        f.write("id|age|name\n")
        f.writelines(f"{v}|{ages[v]}|{_NAMES[name_ix[v]]}\n"
                     for v in range(n_persons))

    def edge_file(name, n_edges):
        src = rng.integers(0, n_persons, n_edges)
        dst = rng.integers(0, n_persons, n_edges)
        hot = rng.random(n_edges) < 0.15
        dst[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
        keep = src != dst
        src, dst = src[keep], dst[keep]
        w = rng.integers(0, 100, src.size)
        fv = rng.random(src.size)
        path = os.path.join(outdir, name)
        with open(path, "w") as f:
            f.write("src|dst|w|f\n")
            f.writelines(f"{s}|{d}|{ww}|{ff!r}\n"
                         for s, d, ww, ff in zip(src.tolist(),
                                                 dst.tolist(),
                                                 w.tolist(), fv.tolist()))
        return path, int(src.size)

    kpath, nk = edge_file("knows.csv", n_persons * avg_degree)
    lpath, nl = edge_file("likes.csv", max(n_persons * avg_degree // 5, 1))
    return ppath, kpath, lpath, n_persons, nk, nl


def pick_seeds(store: GraphStore, space: str, k: int,
               min_degree: int = 1) -> list:
    """k vertex ids that actually have out-edges (traversal seeds)."""
    sd = store.space(space)
    seeds = []
    for p in sd.parts:
        for vid, per in p.out_edges.items():
            if sum(len(m) for m in per.values()) >= min_degree:
                seeds.append(vid)
                if len(seeds) >= k:
                    return seeds
    return seeds


# ---------------------------------------------------------------------------
# Array-native generation for north-star-scale graphs (tens of millions
# of edges).  The dict store can't hold SF100-shaped data in RAM, and
# the benchmark needs the CSR itself, so this path builds the
# CsrSnapshot directly from numpy arrays — same layout as
# graphstore.csr.build_snapshot (dense = vid, owner = vid % P).
# ---------------------------------------------------------------------------


def make_social_arrays(n_persons: int, avg_degree: int, seed: int = 7,
                       hot_frac: float = 0.15, src_hot_frac: float = 0.05):
    """Edge arrays with the same distribution as make_social_graph, PLUS
    an out-degree Zipf tail: frontier expansion follows OUT edges, so
    supernode pressure on the kernel's edge buckets only exists if some
    SOURCES are celebrities (a fan-out graph's follower lists).  In-tail
    alone (hot destinations) exercises only frontier dedup."""
    rng = np.random.default_rng(seed)
    n_edges = n_persons * avg_degree
    src = rng.integers(0, n_persons, n_edges, dtype=np.int64)
    dst = rng.integers(0, n_persons, n_edges, dtype=np.int64)
    hot = rng.random(n_edges) < hot_frac
    dst[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
    shot = rng.random(n_edges) < src_hot_frac
    src[shot] = (rng.zipf(1.5, int(shot.sum())) - 1) % n_persons
    keep = src != dst
    src, dst = src[keep], dst[keep]
    n_edges = src.size
    return {
        "n": n_persons,
        "src": src,
        "dst": dst,
        "w": rng.integers(0, 100, n_edges, dtype=np.int64),
        "f": rng.random(n_edges),
        "city": rng.integers(0, len(_NAMES), n_edges, dtype=np.int64),
    }


def _coo_to_padded_csr(owner, local, nbr_dense, vmax, P):
    """Vectorized COO → (P, ...) padded CSR.  Inputs must already be
    sorted by (owner, local, tiebreak).  Returns (indptr, nbr, order
    positions within part)."""
    counts = np.bincount(owner, minlength=P)
    emax = max(int(counts.max()), 1)
    row_id = owner * (vmax) + np.minimum(local, vmax - 1)
    per_vertex = np.bincount(row_id, minlength=P * vmax).reshape(P, vmax)
    indptr = np.zeros((P, vmax + 1), np.int64)
    np.cumsum(per_vertex, axis=1, out=indptr[:, 1:])
    starts = np.zeros(P + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(owner.size, dtype=np.int64) - starts[owner]
    nbr = np.full((P, emax), -1, np.int32)
    nbr[owner, pos] = nbr_dense.astype(np.int32)
    return indptr.astype(np.int32), nbr, pos, emax


def snapshot_from_arrays(arrs, parts: int = 8, space: str = "snb"):
    """Build a CsrSnapshot (out + in KNOWS blocks, w/f/city props)
    directly from edge arrays — the bulk-ingest path for benchmark-scale
    graphs."""
    from ..graphstore.csr import CsrSnapshot, StringPool
    from ..graphstore.csr import CsrBlock
    from ..graphstore.schema import PropType

    n, P = int(arrs["n"]), parts
    src, dst = arrs["src"], arrs["dst"]
    pool = StringPool()
    city_codes = np.asarray([pool.encode(s) for s in _NAMES],
                            np.int64)[arrs["city"]]
    counts = np.bincount(np.arange(n, dtype=np.int64) % P, minlength=P)
    vmax = max(int(counts.max()), 1)
    snap = CsrSnapshot(space=space, epoch=0, num_parts=P, vmax=vmax,
                       num_vertices=counts.astype(np.int32),
                       pool=pool,
                       dense_to_vid=list(range(n)))

    for direction in ("out", "in"):
        a, b = (src, dst) if direction == "out" else (dst, src)
        owner = a % P
        local = a // P
        order = np.lexsort((b, local, owner))
        ow, lo, nb = owner[order], local[order], b[order]
        indptr, nbr, pos, emax = _coo_to_padded_csr(ow, lo, nb, vmax, P)
        rank = np.zeros_like(nbr)
        props = {}
        for name, col, pt in (("w", arrs["w"], PropType.INT64),
                              ("f", arrs["f"], PropType.DOUBLE),
                              ("city", city_codes, PropType.STRING)):
            dt = np.float64 if pt == PropType.DOUBLE else np.int64
            padded = np.full((P, emax),
                             np.nan if dt == np.float64 else -2, dt)
            padded[ow, pos] = col[order].astype(dt)
            props[name] = padded
        snap.blocks[("KNOWS", direction)] = CsrBlock(
            etype="KNOWS", direction=direction, indptr=indptr, nbr=nbr,
            rank=rank, props=props,
            prop_types={"w": PropType.INT64, "f": PropType.DOUBLE,
                        "city": PropType.STRING})
    return snap


def host_csr_traverse(snap, seeds, steps: int, w_gt=None,
                      materialize: bool = False,
                      etypes=("KNOWS",)):
    """Vectorized numpy host baseline over the same CSR: per hop, gather
    neighbor ranges with repeat, dedup with np.unique.  This is the
    strongest honest CPU single-core baseline available here (a C++
    row-at-a-time engine does strictly more work per edge).

    `etypes` expands through multiple out-blocks per hop (the OVER *
    comparator).  Returns (edges_traversed, final_kept_edge_count) —
    and with materialize=True, also (dst_vids, w) numpy arrays of the
    final-hop result so the baseline pays the same output cost class
    the device E2E path does (VERDICT r1 weak #2: no flattering
    asymmetries).
    """
    P = snap.num_parts
    blks = [snap.block(et, "out") for et in etypes]
    frontier = np.unique(np.asarray(seeds, np.int64))
    total = 0
    for hop in range(steps):
        owner = frontier % P
        local = frontier // P
        nxts, ws = [], []
        for blk in blks:
            s = blk.indptr[owner, local].astype(np.int64)
            e = blk.indptr[owner, local + 1].astype(np.int64)
            deg = e - s
            total += int(deg.sum())
            if deg.sum() == 0:
                nxts.append(np.empty(0, np.int64))
                ws.append(np.empty(0, blk.props["w"].dtype))
                continue
            rows = np.repeat(np.arange(frontier.size), deg)
            offs = np.arange(deg.sum(), dtype=np.int64) - \
                np.repeat(np.cumsum(deg) - deg, deg)
            idx = s[rows] + offs
            nxts.append(blk.nbr[owner[rows], idx].astype(np.int64))
            if hop == steps - 1:
                ws.append(blk.props["w"][owner[rows], idx])
        nxt = np.concatenate(nxts) if len(nxts) > 1 else nxts[0]
        if nxt.size == 0:
            return (total, 0, None, None) if materialize else (total, 0)
        if hop == steps - 1:
            w = np.concatenate(ws) if len(ws) > 1 else ws[0]
            if w_gt is not None:
                keep = w > w_gt
                nxt, w = nxt[keep], w[keep]
            if materialize:
                return total, int(nxt.size), nxt, w
            return total, int(nxt.size)
        frontier = np.unique(nxt)
    return (total, 0, None, None) if materialize else (total, 0)


def host_bfs(snap, src_dense, steps: int, etype: str = "KNOWS"):
    """Numpy BFS comparator for config 5 (VERDICT r3 weak #5: BFS had no
    content oracle): level-synchronous BFS over the out-CSR, returning
    the full dense-id distance array (-1 unreached, 0..steps otherwise).
    The device BFS kernel's distance output must match element-for-
    element."""
    P = snap.num_parts
    blk = snap.block(etype, "out")
    n = len(snap.dense_to_vid)
    dist = np.full(n, -1, np.int32)
    fr = np.unique(np.asarray(src_dense, np.int64))
    dist[fr] = 0
    for hop in range(1, steps + 1):
        if fr.size == 0:
            break
        owner = fr % P
        local = fr // P
        s = blk.indptr[owner, local].astype(np.int64)
        e = blk.indptr[owner, local + 1].astype(np.int64)
        deg = e - s
        tot = int(deg.sum())
        if tot == 0:
            break
        rows = np.repeat(np.arange(fr.size), deg)
        offs = np.arange(tot, dtype=np.int64) - \
            np.repeat(np.cumsum(deg) - deg, deg)
        idx = s[rows] + offs
        nxt = np.unique(blk.nbr[owner[rows], idx].astype(np.int64))
        nxt = nxt[dist[nxt] < 0]
        dist[nxt] = hop
        fr = nxt
    return dist


def _expand_paths(blk, P, fr):
    """One path-expansion hop over an out-CSR block: (parent, dst, eid)
    for EVERY edge out of fr's entries — no dedup; this is path currency,
    not frontier currency.  eid is a globally-unique physical edge id
    (part-major slot index), the trail-dedup key."""
    owner = fr % P
    local = fr // P
    s = blk.indptr[owner, local].astype(np.int64)
    e = blk.indptr[owner, local + 1].astype(np.int64)
    deg = e - s
    tot = int(deg.sum())
    if tot == 0:
        z = np.empty(0, np.int64)
        return z, z, z
    parent = np.repeat(np.arange(fr.size, dtype=np.int64), deg)
    offs = np.arange(tot, dtype=np.int64) \
        - np.repeat(np.cumsum(deg) - deg, deg)
    idx = s[parent] + offs
    emax = blk.nbr.shape[1]
    eid = owner[parent] * emax + idx
    dst = blk.nbr[owner[parent], idx].astype(np.int64)
    return parent, dst, eid


def host_match_agg(snap, seeds_dense, min_age):
    """Numpy comparator for the IC-shaped config 3 (VERDICT r2 item 2:
    the honest CPU baseline): 2-hop path join p→f→ff with trail
    (distinct-edge) semantics, vertex-prop filter ff.age > min_age, and
    a group-count by ff.  Returns (ff_dense sorted, counts)."""
    P = snap.num_parts
    blk = snap.block("KNOWS", "out")
    fr = np.asarray(sorted(set(int(s) for s in seeds_dense)), np.int64)
    if fr.size == 0:
        z = np.empty(0, np.int64)
        return z, z
    r1, f, e1 = _expand_paths(blk, P, fr)
    r2, ff, e2 = _expand_paths(blk, P, f)
    keep = e2 != e1[r2]
    ff = ff[keep]
    age = snap.tags["Person"].props["age"][ff % P, ff // P]
    ff = ff[age > min_age]
    u, c = np.unique(ff, return_counts=True)
    return u, c


def host_trail_paths(snap, seeds_dense, max_hop):
    """Numpy comparator for config 4: count of variable-length *1..N
    trail paths (distinct edges within one path) from the seed set —
    level-joins with pairwise edge-id comparison, the same algorithm
    class the device frame assembly uses."""
    P = snap.num_parts
    blk = snap.block("KNOWS", "out")
    last = np.asarray(sorted(set(int(s) for s in seeds_dense)), np.int64)
    eids = []
    total = 0
    for _h in range(max_hop):
        if last.size == 0:
            break
        parent, dst, eid = _expand_paths(blk, P, last)
        if dst.size == 0:
            break
        keep = np.ones(dst.size, bool)
        for pe in eids:
            keep &= pe[parent] != eid
        total += int(keep.sum())
        sel = np.flatnonzero(keep)
        last = dst[sel]
        eids = [pe[parent[sel]] for pe in eids] + [eid[sel]]
    return total


class SnapshotStore:
    """Duck-typed GraphStore stand-in backed by a prebuilt CsrSnapshot —
    just enough surface for TpuRuntime.traverse/bfs (dense_id, epoch,
    edge-type catalog)."""

    class _SD:
        def __init__(self, n, epoch):
            self._n = n
            self.epoch = epoch

        def dense_id(self, v):
            v = int(v)
            return v if 0 <= v < self._n else -1

    class _Edge:
        edge_type = 1

    class _Catalog:
        def get_edge(self, space, et):
            return SnapshotStore._Edge()

    def __init__(self, snap):
        self.snap = snap
        self._sd = SnapshotStore._SD(len(snap.dense_to_vid), snap.epoch)
        self.catalog = SnapshotStore._Catalog()

    def space(self, name):
        return self._sd


# ---------------------------------------------------------------------------
# LDBC-SNB interactive slice (VERDICT r4 weak #1 / item 6): enough of the
# datagen schema — Person/Forum/Post/Comment with KNOWS / HAS_MEMBER /
# CONTAINER_OF / HAS_CREATOR and datagen-like skew — to run IC5 and IC9
# with their published query text, plus numpy oracles for both.
# ---------------------------------------------------------------------------


def make_snb_interactive(n_persons: int = 4_000, parts: int = 8,
                         seed: int = 19, space: str = "ic",
                         store: GraphStore | None = None):
    """Person/Forum/Post/Comment graph with LDBC-interactive shape.

    Vid layout (INT64, one space): persons [0, P), forums [P, P+F),
    posts [P+F, P+F+M), comments [P+F+M, ...).  Distributions follow the
    datagen spirit: Zipf-tailed KNOWS degree, power-law forum sizes and
    posts-per-forum, post creators drawn from the forum's members,
    comments replying to (and created near) existing posts.  Dates are
    epoch-day ints (the queries only compare/order them).

    Returns (store, arrays) where arrays carries the raw numpy columns
    the IC5/IC9 oracles run over.
    """
    rng = np.random.default_rng(seed)
    st = store if store is not None else GraphStore()
    st.create_space(space, partition_num=parts, vid_type="INT64")
    st.catalog.create_tag(space, "Person", [
        PropDef("firstName", PropType.STRING),
        PropDef("lastName", PropType.STRING)])
    st.catalog.create_tag(space, "Forum", [
        PropDef("title", PropType.STRING)])
    st.catalog.create_tag(space, "Post", [
        PropDef("creationDate", PropType.INT64),
        PropDef("content", PropType.STRING)])
    st.catalog.create_tag(space, "Comment", [
        PropDef("creationDate", PropType.INT64),
        PropDef("content", PropType.STRING)])
    st.catalog.create_edge(space, "KNOWS", [
        PropDef("creationDate", PropType.INT64)])
    st.catalog.create_edge(space, "HAS_MEMBER", [
        PropDef("joinDate", PropType.INT64)])
    st.catalog.create_edge(space, "CONTAINER_OF", [])
    st.catalog.create_edge(space, "HAS_CREATOR", [])
    st.catalog.create_edge(space, "REPLY_OF", [])

    n_forums = max(n_persons // 10, 4)
    for v in range(n_persons):
        st.insert_vertex(space, v, "Person",
                         {"firstName": _NAMES[v % len(_NAMES)],
                          "lastName": _NAMES[(v * 7 + 3) % len(_NAMES)]})

    # KNOWS: undirected in LDBC — insert BOTH directions so `-[:KNOWS]-`
    # and the directed planes agree on the friendship set
    n_k = n_persons * 8
    ks = rng.integers(0, n_persons, n_k)
    kd = rng.integers(0, n_persons, n_k)
    hot = rng.random(n_k) < 0.15
    kd[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
    kdate = rng.integers(15_000, 20_000, n_k)
    keep = ks != kd
    ks, kd, kdate = ks[keep], kd[keep], kdate[keep]
    pairs = {}
    for s, d, dt in zip(ks.tolist(), kd.tolist(), kdate.tolist()):
        pairs[(min(s, d), max(s, d))] = dt
    know_pairs = np.array(sorted(pairs), np.int64).reshape(-1, 2)
    know_dates = np.array([pairs[tuple(p)] for p in know_pairs.tolist()],
                          np.int64)
    for (a, b), dt in zip(know_pairs.tolist(), know_dates.tolist()):
        st.insert_edge(space, a, "KNOWS", b, 0, {"creationDate": int(dt)})
        st.insert_edge(space, b, "KNOWS", a, 0, {"creationDate": int(dt)})

    f0 = n_persons
    for i in range(n_forums):
        st.insert_vertex(space, f0 + i, "Forum",
                         {"title": f"forum{i}"})
    # memberships: forum sizes power-law; joinDate uniform
    mem_f, mem_p, mem_d = [], [], []
    sizes = np.minimum((rng.zipf(1.4, n_forums) * 3) % (n_persons // 2) + 2,
                       n_persons)
    for i in range(n_forums):
        members = rng.choice(n_persons, size=int(sizes[i]), replace=False)
        dates = rng.integers(15_000, 20_000, members.size)
        for p, dt in zip(members.tolist(), dates.tolist()):
            st.insert_edge(space, f0 + i, "HAS_MEMBER", p, 0,
                           {"joinDate": int(dt)})
        mem_f.extend([i] * members.size)
        mem_p.extend(members.tolist())
        mem_d.extend(dates.tolist())
    mem_f = np.array(mem_f, np.int64)
    mem_p = np.array(mem_p, np.int64)
    mem_d = np.array(mem_d, np.int64)

    # posts: per-forum volume power-law, creator drawn from members
    p0 = f0 + n_forums
    post_forum, post_creator, post_date = [], [], []
    vol = (rng.zipf(1.3, n_forums) * 2) % 40 + 1
    for i in range(n_forums):
        m = mem_p[mem_f == i]
        if m.size == 0:
            continue
        creators = rng.choice(m, size=int(vol[i]))
        dates = rng.integers(15_000, 20_000, creators.size)
        post_forum.extend([i] * creators.size)
        post_creator.extend(creators.tolist())
        post_date.extend(dates.tolist())
    n_posts = len(post_forum)
    post_forum = np.array(post_forum, np.int64)
    post_creator = np.array(post_creator, np.int64)
    post_date = np.array(post_date, np.int64)
    for j in range(n_posts):
        st.insert_vertex(space, p0 + j, "Post",
                         {"creationDate": int(post_date[j]),
                          "content": f"post{j}"})
        st.insert_edge(space, f0 + int(post_forum[j]), "CONTAINER_OF",
                       p0 + j, 0, {})
        st.insert_edge(space, p0 + j, "HAS_CREATOR",
                       int(post_creator[j]), 0, {})

    # comments: reply to a random post, creator any person
    c0 = p0 + n_posts
    n_comments = n_posts * 2
    cmt_post = rng.integers(0, max(n_posts, 1), n_comments)
    cmt_creator = rng.integers(0, n_persons, n_comments)
    cmt_date = rng.integers(15_000, 20_100, n_comments)
    if n_posts == 0:
        n_comments = 0
    for j in range(n_comments):
        st.insert_vertex(space, c0 + j, "Comment",
                         {"creationDate": int(cmt_date[j]),
                          "content": f"cmt{j}"})
        st.insert_edge(space, c0 + j, "REPLY_OF",
                       p0 + int(cmt_post[j]), 0, {})
        st.insert_edge(space, c0 + j, "HAS_CREATOR",
                       int(cmt_creator[j]), 0, {})

    arrays = {
        "n_persons": n_persons, "n_forums": n_forums,
        "n_posts": n_posts, "n_comments": n_comments,
        "f0": f0, "p0": p0, "c0": c0,
        "know_pairs": know_pairs, "know_dates": know_dates,
        "mem_f": mem_f, "mem_p": mem_p, "mem_d": mem_d,
        "post_forum": post_forum, "post_creator": post_creator,
        "post_date": post_date,
        "cmt_post": cmt_post[:n_comments],
        "cmt_creator": cmt_creator[:n_comments],
        "cmt_date": cmt_date[:n_comments],
    }
    return st, arrays


def _friends_1_2(arrays, root: int) -> np.ndarray:
    """Dense person ids within 1..2 undirected KNOWS hops, root excluded."""
    kp = arrays["know_pairs"]
    adj = {}
    for a, b in kp.tolist():
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, []).append(a)
    l1 = set(adj.get(root, []))
    l2 = set()
    for f in l1:
        l2.update(adj.get(f, []))
    out = (l1 | l2) - {root}
    return np.array(sorted(out), np.int64)


def ic5_numpy(arrays, root: int, min_date: int):
    """Oracle for IC5: forums a 1..2-hop friend joined after min_date,
    scored by posts created in that forum by friends whose OWN
    membership qualifies (the official query counts over the
    (friend, forum) membership pairs, so a post by a friend who is not
    a qualifying member of that forum does not score)."""
    fr = set(_friends_1_2(arrays, root).tolist())
    mf, mp, md = arrays["mem_f"], arrays["mem_p"], arrays["mem_d"]
    qual_pairs = {(int(f), int(p)) for f, p, d in zip(mf, mp, md)
                  if int(p) in fr and int(d) > min_date}
    qual_forums = {f for f, _ in qual_pairs}
    pf, pc = arrays["post_forum"], arrays["post_creator"]
    counts = {f: 0 for f in qual_forums}
    for f, c in zip(pf.tolist(), pc.tolist()):
        if (f, c) in qual_pairs:
            counts[f] += 1
    # ORDER BY postCount DESC, forum title ASC; LIMIT 20
    out = sorted(((f"forum{f}", n) for f, n in counts.items()),
                 key=lambda t: (-t[1], t[0]))[:20]
    return out


def ic9_numpy(arrays, root: int, max_date: int):
    """Oracle for IC9: most recent messages (posts or comments) created
    by 1..2-hop friends before max_date."""
    fr = set(_friends_1_2(arrays, root).tolist())
    p0, c0 = arrays["p0"], arrays["c0"]
    msgs = []
    for j, (c, d) in enumerate(zip(arrays["post_creator"].tolist(),
                                   arrays["post_date"].tolist())):
        if c in fr and d < max_date:
            msgs.append((int(c), p0 + j, int(d)))
    for j, (c, d) in enumerate(zip(arrays["cmt_creator"].tolist(),
                                   arrays["cmt_date"].tolist())):
        if c in fr and d < max_date:
            msgs.append((int(c), c0 + j, int(d)))
    msgs.sort(key=lambda t: (-t[2], t[1]))
    return msgs[:20]
