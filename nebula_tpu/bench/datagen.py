"""Synthetic LDBC-SNB-shaped graph generator.

The real LDBC datasets aren't available offline (zero egress), so the
bench harness generates a Person/KNOWS social graph with the properties
that matter for traversal benchmarking: heavy-tailed degree distribution
(supernodes — SURVEY §7 hard-part #4), string + int + float edge props
(predicate mask coverage), and hash partitioning across P parts.
"""
from __future__ import annotations

import numpy as np

from ..core.value import NULL
from ..graphstore.schema import PropDef, PropType
from ..graphstore.store import GraphStore

_NAMES = ["ada", "bob", "cid", "dee", "eve", "fay", "gus", "hal",
          "ivy", "joe", "kim", "lee", "mia", "ned", "oda", "pam"]


def make_social_graph(n_persons: int = 20_000, avg_degree: int = 25,
                      parts: int = 8, seed: int = 7, space: str = "snb",
                      store: GraphStore | None = None,
                      edge_props: bool = True) -> GraphStore:
    """Person vertices + KNOWS edges with a Zipf-ish degree tail.

    Vertex ids are ints 0..n-1.  Edge props: w INT64 (the benchmark's
    filter column), f DOUBLE, city STRING (dict-encodable).
    """
    rng = np.random.default_rng(seed)
    st = store if store is not None else GraphStore()
    st.create_space(space, partition_num=parts, vid_type="INT64")
    st.catalog.create_tag(space, "Person", [
        PropDef("age", PropType.INT64),
        PropDef("name", PropType.STRING)])
    eprops = [PropDef("w", PropType.INT64),
              PropDef("f", PropType.DOUBLE),
              PropDef("city", PropType.STRING)] if edge_props else []
    st.catalog.create_edge(space, "KNOWS", eprops)

    ages = rng.integers(13, 90, n_persons)
    name_ix = rng.integers(0, len(_NAMES), n_persons)
    for v in range(n_persons):
        st.insert_vertex(space, int(v), "Person",
                         {"age": int(ages[v]), "name": _NAMES[name_ix[v]]})

    n_edges = n_persons * avg_degree
    src = rng.integers(0, n_persons, n_edges)
    # dst mixture: mostly uniform (frontier growth under traversal) with a
    # Zipf tail (supernode destinations, like follower graphs)
    dst = rng.integers(0, n_persons, n_edges)
    hot = rng.random(n_edges) < 0.15
    dst[hot] = (rng.zipf(1.6, int(hot.sum())) - 1) % n_persons
    w = rng.integers(0, 100, n_edges)
    f = rng.random(n_edges)
    city_ix = rng.integers(0, len(_NAMES), n_edges)
    for i in range(n_edges):
        s, d = int(src[i]), int(dst[i])
        if s == d:
            continue
        props = ({"w": int(w[i]), "f": float(f[i]),
                  "city": _NAMES[city_ix[i]]} if edge_props else {})
        st.insert_edge(space, s, "KNOWS", d, 0, props)
    return st


def pick_seeds(store: GraphStore, space: str, k: int,
               min_degree: int = 1) -> list:
    """k vertex ids that actually have out-edges (traversal seeds)."""
    sd = store.space(space)
    seeds = []
    for p in sd.parts:
        for vid, per in p.out_edges.items():
            if sum(len(m) for m in per.values()) >= min_degree:
                seeds.append(vid)
                if len(seeds) >= k:
                    return seeds
    return seeds
