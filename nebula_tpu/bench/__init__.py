"""Benchmark harness (SURVEY §7 step 8): synthetic LDBC-SNB-shaped data
generation + the CPU-vs-TPU measurement loop behind the repo-root bench.py."""
from .datagen import make_social_graph  # noqa: F401
