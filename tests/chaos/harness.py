"""Chaos-test harness (ISSUE 5 tentpole, part 4).

Seeded fault schedules drive mixed read/write workloads over a LIVE
multi-replica LocalCluster and assert the system invariants:

  * every ACKED write survives and appears exactly once;
  * replicas of every part re-converge BYTE-IDENTICALLY after the
    faults stop (export_part_state compared across live replicas);
  * no torn TOSS chain is left behind (pending journals drain);
  * queries don't overshoot their deadline budget beyond grace.

Everything here is deterministic modulo thread scheduling: the fault
schedules draw from `random.Random(f"{seed}:{site}")` (utils/failpoints),
the workloads from `random.Random(seed)`, so a failure reproduces from
its seed — tools/chaos_bench.py prints the reproducer line.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from nebula_tpu.cluster.launcher import LocalCluster
from nebula_tpu.cluster.rpc import reset_breakers
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import stats


class ChaosCluster:
    """A LocalCluster plus the probes the invariants need."""

    def __init__(self, n_meta=1, n_storage=3, n_graph=1, parts=4,
                 replica_factor=3, space="cx", tpu_runtime=None,
                 data_dir=None):
        fail.reset()
        reset_breakers()
        stats().reset()
        self.space = space
        self.cluster = LocalCluster(n_meta=n_meta, n_storage=n_storage,
                                    n_graph=n_graph, data_dir=data_dir,
                                    tpu_runtime=tpu_runtime)
        self.client = self.cluster.client()
        self.dead: set = set()          # indexes of killed storageds
        self.dead_graphds: set = set()  # indexes of killed graphds
        r = self.client.execute(
            f"CREATE SPACE {space}(partition_num={parts}, "
            f"replica_factor={replica_factor}, vid_type=INT64)")
        assert r.error is None, r.error
        self.cluster.reconcile_storage()
        for q in (f"USE {space}",
                  "CREATE TAG Person(name string, age int)",
                  "CREATE TAG Counter(n int)",
                  "CREATE EDGE KNOWS(w int)"):
            r = self.client.execute(q)
            assert r.error is None, f"{q} -> {r.error}"
        self.wait_part_leaders()

    def wait_part_leaders(self, timeout: float = 15.0):
        """Block until every part has an elected leader — chaos starts
        from a HEALTHY cluster, not a half-elected one."""
        pm = self.cluster.meta_clients[0].parts_of(self.space)
        dl = time.monotonic() + timeout
        for pid in range(len(pm)):
            while not any(ss.parts[k].is_leader()
                          for _, ss in self._live_replicas(pid)
                          for k in ss.parts
                          if k[1] == pid and
                          k[0] == ss.meta.catalog.get_space(
                              self.space).space_id):
                if time.monotonic() > dl:
                    raise AssertionError(f"part {pid}: no leader elected")
                time.sleep(0.05)

    # -- lifecycle --------------------------------------------------------

    def stop(self):
        fail.reset()
        reset_breakers()
        self.cluster.stop()

    def kill_storaged(self, i: int):
        self.dead.add(i)
        self.cluster.stop_storaged(i)

    def kill_graphd(self, i: int):
        """Hard-kill coordinator `i` — no drain, in-flight statements
        die with it (ISSUE 20 failover chaos)."""
        self.dead_graphds.add(i)
        self.cluster.stop_graphd(i)

    def fleet_client(self):
        """A client holding EVERY graphd endpoint — the failover-aware
        session the ISSUE 20 invariants drive."""
        return self.cluster.fleet_client()

    def leader_of_most_parts(self) -> int:
        """Index of the live storaged leading the most parts of the
        space — the highest-impact crash target."""
        best, best_n = -1, -1
        for i, ss in enumerate(self.cluster.storageds):
            if i in self.dead:
                continue
            n = sum(1 for p in ss.parts.values() if p.is_leader())
            if n > best_n:
                best, best_n = i, n
        assert best >= 0, "no live storaged"
        return best

    # -- statement driver -------------------------------------------------

    def run(self, q: str):
        return self.client.execute(q)

    def ok(self, q: str):
        r = self.client.execute(q)
        assert r.error is None, f"{q} -> {r.error}"
        return r

    # -- invariants -------------------------------------------------------

    def _live_replicas(self, pid: int):
        sid = None
        out = []
        for i, ss in enumerate(self.cluster.storageds):
            if i in self.dead:
                continue
            if sid is None:
                sid = ss.meta.catalog.get_space(self.space).space_id
            if (sid, pid) in ss.parts:
                out.append((i, ss))
        return out

    def wait_replicas_converged(self, timeout: float = 20.0,
                                require: int = 2) -> Dict[int, bytes]:
        """Poll until every part's LIVE replicas export byte-identical
        state; returns {pid: payload}.  `require`: minimum live replica
        count per part (sanity that the check compares something)."""
        pm = self.cluster.meta_clients[0].parts_of(self.space)
        dl = time.monotonic() + timeout
        last_diff: Dict[int, List[int]] = {}
        out: Dict[int, bytes] = {}
        for pid in range(len(pm)):
            while True:
                reps = self._live_replicas(pid)
                assert len(reps) >= require, \
                    f"part {pid}: only {len(reps)} live replicas"
                blobs = {}
                for i, ss in reps:
                    try:
                        blobs[i] = ss.store.export_part_state(
                            self.space, pid)
                    except Exception as ex:  # noqa: BLE001 — mid-apply
                        blobs[i] = repr(ex).encode()
                if len(set(blobs.values())) == 1:
                    out[pid] = next(iter(blobs.values()))
                    break
                last_diff[pid] = sorted(blobs)
                if time.monotonic() > dl:
                    sizes = {i: len(b) for i, b in blobs.items()}
                    raise AssertionError(
                        f"part {pid} replicas never converged "
                        f"(replica sizes {sizes})")
                time.sleep(0.1)
        return out

    def wait_no_pending_chains(self, timeout: float = 20.0):
        """Every TOSS journal drains (the janitor re-drove or retired
        every chain) on every live replica."""
        pm = self.cluster.meta_clients[0].parts_of(self.space)
        dl = time.monotonic() + timeout
        while True:
            left = {}
            for pid in range(len(pm)):
                for i, ss in self._live_replicas(pid):
                    ch = ss.store.pending_chains(self.space, pid)
                    if ch:
                        left[(pid, i)] = list(ch)
            if not left:
                return
            if time.monotonic() > dl:
                raise AssertionError(f"pending TOSS chains left: {left}")
            time.sleep(0.2)

    def fetch_ages(self, vids: List[int]) -> Dict[int, int]:
        """{vid: age} for the vids that exist (chunked FETCH)."""
        out: Dict[int, int] = {}
        for i in range(0, len(vids), 64):
            chunk = vids[i:i + 64]
            r = self.ok("FETCH PROP ON Person " +
                        ", ".join(map(str, chunk)) +
                        " YIELD id(vertex) AS v, Person.age AS a")
            for v, a in r.data.rows:
                out[int(v)] = int(a)
        return out

    def logical_state(self) -> Dict[int, Dict[str, Any]]:
        """Per-part {vertices, out_edges, in_edges, part_count} from a
        live replica — the cross-CLUSTER comparable form.  Excludes the
        dense-id map (allocation order varies with retry interleaving)
        and the dedup window / chain journal (fault-history artifacts,
        not logical content)."""
        pm = self.cluster.meta_clients[0].parts_of(self.space)
        out: Dict[int, Dict[str, Any]] = {}
        for pid in range(len(pm)):
            _, ss = self._live_replicas(pid)[0]
            st = ss.store.part_state_payload(self.space, pid)
            out[pid] = {"vertices": st["vertices"],
                        "out_edges": st["out_edges"],
                        "in_edges": st["in_edges"],
                        "part_count": st["part_count"]}
        return out


class WriteLedger:
    """Records every write the workload ACKED (and every failure) so
    the invariants can be checked against ground truth."""

    def __init__(self):
        self.acked: Dict[int, Dict[str, Any]] = {}    # vid → props
        self.failed: List[Tuple[int, str]] = []
        self.lock = threading.Lock()

    def ack(self, vid: int, props: Dict[str, Any]):
        with self.lock:
            self.acked[vid] = props

    def fail(self, vid: int, err: str):
        with self.lock:
            self.failed.append((vid, err))


def mixed_workload(cc: ChaosCluster, seed: int, n_writes: int = 80,
                   read_every: int = 5,
                   vid_base: int = 1000) -> WriteLedger:
    """Seeded sequence of single-vertex INSERTs interleaved with reads.
    Returns the ledger of acked/failed statements."""
    rng = random.Random(seed)
    led = WriteLedger()
    for k in range(n_writes):
        vid = vid_base + k
        age = rng.randint(1, 99)
        r = cc.run(f'INSERT VERTEX Person(name, age) VALUES '
                   f'{vid}:("p{vid}",{age})')
        if r.error is None:
            led.ack(vid, {"age": age})
        else:
            led.fail(vid, r.error)
        if k % read_every == 0:
            cc.run(f"FETCH PROP ON Person {vid} YIELD Person.age AS a")
    return led


def assert_acked_exactly_once(cc: ChaosCluster, led: WriteLedger):
    """Every acked write is present with its acked value.  (Presence
    with the right value == applied; the dedup window + raft ordering
    make a duplicate apply impossible — the companion counters prove
    re-sends actually happened in the schedules that inject them.)"""
    got = cc.fetch_ages(sorted(led.acked))
    missing = {v: p for v, p in led.acked.items() if v not in got}
    assert not missing, f"ACKED writes lost: {missing}"
    wrong = {v: (got[v], p["age"]) for v, p in led.acked.items()
             if got[v] != p["age"]}
    assert not wrong, f"ACKED writes corrupted (got, want): {wrong}"


def counter_workload(cc: ChaosCluster, seed: int, vid: int = 777,
                     n: int = 30) -> Tuple[int, int]:
    """Sequential read-modify-write increments of one Counter vertex;
    returns (acked, failed).  Exactly-once detector: with dedup, a
    statement acked after internal re-sends still bumps the counter
    by EXACTLY one."""
    cc.ok(f"INSERT VERTEX Counter(n) VALUES {vid}:(0)")
    acked = failed = 0
    for _ in range(n):
        r = cc.run(f"UPDATE VERTEX ON Counter {vid} SET n = n + 1")
        if r.error is None:
            acked += 1
        else:
            failed += 1
    return acked, failed


def counter_value(cc: ChaosCluster, vid: int = 777) -> int:
    r = cc.ok(f"FETCH PROP ON Counter {vid} YIELD Counter.n AS n")
    return int(r.data.rows[0][0])
