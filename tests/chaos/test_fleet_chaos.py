"""Fleet failover chaos (ISSUE 20): kill 1-of-3 graphds under mixed
read/write load with result caches armed fleet-wide.

The acceptance claims under test:

  * ZERO wrong rows — every value a reader observes is one a writer
    actually wrote, and per-coordinator observations never regress
    (cluster cache epochs: retired keys are unreachable, a coordinator
    never re-serves an older cached value for a key it already
    advanced past);
  * acked-exactly-once through the crash — every acked write is
    present with its acked value afterwards; an unknown-outcome
    E_COORDINATOR_LOST write is resolved by read-then-retry, never by
    a blind re-send;
  * ZERO stale cross-coordinator cache hits once the bounded
    propagation window closes — cached reads on EVERY surviving
    coordinator converge to the final acked values, and the
    time-to-coherence is measured and bounded;
  * failover recovery is bounded — the client homed on the killed
    coordinator completes its next statement within seconds, not
    deadline-timeouts.

Marked `chaos` + `slow`: NOT part of the tier-1 gate.  The fault-free
fleet goodput curve lives in tools/overload_bench.py --fleet (bench.py
`fleet` block), including the aggressor-tenant DWRR share proof.
"""
import threading
import time

import pytest

from nebula_tpu.cluster.client import GraphClient
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.stats import stats

from harness import ChaosCluster

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

_FLAGS = ("result_cache_size", "result_cache_strict_epoch")


def _pop_flags():
    cfg = get_config()
    for k in _FLAGS:
        cfg.dynamic_layer.pop(k, None)


def _fleet_client(cc, home: int) -> GraphClient:
    """A failover client HOMED on graphd `home` (endpoint rotation puts
    it first) — so killing that graphd exercises this client's
    failover, not just its siblings'."""
    addrs = cc.cluster.graph_addrs
    c = GraphClient(addrs[home:] + addrs[:home])
    c.authenticate("root", "nebula")
    r = c.execute(f"USE {cc.space}")
    assert r.error is None, r.error
    return c


def _resolve_write(client, vid: int, val: int) -> bool:
    """Drive one UPDATE to a definite outcome: an unknown-outcome
    E_COORDINATOR_LOST is resolved by reading back (reads retry
    safely) and re-sending ONLY when provably not applied.  Returns
    whether the write is acked-with-val."""
    for _ in range(6):
        r = client.execute(f"UPDATE VERTEX ON Person {vid} SET age = {val}")
        if r.error is None:
            return True
        if "E_COORDINATOR_LOST" not in r.error:
            return False
        rr = client.execute(
            f"FETCH PROP ON Person {vid} YIELD Person.age AS a")
        if rr.error is None and rr.data.rows \
                and int(rr.data.rows[0][0]) >= val:
            return True                    # it DID land before the crash
        # provably behind: safe to drive again
    return False


def test_kill_one_of_three_graphds_under_load():
    cc = ChaosCluster(n_meta=1, n_storage=3, n_graph=3, parts=4,
                      replica_factor=3)
    get_config().set_dynamic("result_cache_size", 128)
    get_config().set_dynamic("result_cache_strict_epoch", True)
    victim = 2                      # graphd 0 stays up for the harness
    rounds, per_writer = 5, 20
    ranges = {w: list(range(2000 + w * 100, 2000 + w * 100 + per_writer))
              for w in range(3)}
    acked = {}                      # vid -> highest acked val
    acked_lock = threading.Lock()
    wrong = []                      # (who, vid, saw, context)
    recovery = {}                   # box for the victim writer's measure
    stop_readers = threading.Event()
    kill_at = threading.Barrier(3 + 1, timeout=60)   # 3 writers + main
    try:
        # seed every vid through the stable coordinator
        for vids in ranges.values():
            for v in vids:
                cc.ok(f'INSERT VERTEX Person(name, age) VALUES '
                      f'{v}:("p{v}",0)')
                with acked_lock:
                    acked[v] = 0

        def writer(w):
            client = _fleet_client(cc, home=w)
            for rnd in range(1, rounds + 1):
                if rnd == 3:
                    kill_at.wait()          # main kills graphd `victim`
                    if w == victim:
                        t0 = time.monotonic()
                for v in ranges[w]:
                    if _resolve_write(client, v, rnd):
                        with acked_lock:
                            acked[v] = max(acked[v], rnd)
                    else:
                        wrong.append(("writer", v, rnd, "unresolved"))
                if rnd == 3 and w == victim:
                    recovery["failover_s"] = time.monotonic() - t0
            client.close()

        def reader(rid):
            client = _fleet_client(cc, home=rid)   # homed 0 and 1
            last = {}                   # (coordinator, vid) -> last seen
            while not stop_readers.is_set():
                for v in list(acked)[rid::2][:30]:
                    with acked_lock:
                        floor = 0 if v not in acked else -1
                    r = client.execute(
                        f"FETCH PROP ON Person {v} YIELD Person.age AS a")
                    if r.error is not None or not r.data.rows:
                        continue        # structured failure: allowed
                    saw = int(r.data.rows[0][0])
                    if saw > rounds or saw < 0:
                        wrong.append(("reader", v, saw, "never written"))
                    key = (client.addr, v)
                    if saw < last.get(key, floor):
                        # a coordinator re-served an OLDER cached value
                        # for a vid it had already served newer — the
                        # stale-cache-resurrection bug
                        wrong.append(("reader", v, saw,
                                      f"regressed below {last[key]} "
                                      f"on {client.addr}"))
                    last[key] = saw
                time.sleep(0.005)
            client.close()

        writers = [threading.Thread(target=writer, args=(w,), daemon=True)
                   for w in range(3)]
        readers = [threading.Thread(target=reader, args=(i,), daemon=True)
                   for i in range(2)]
        for t in writers + readers:
            t.start()

        kill_at.wait()                  # everyone parked at round 3
        cc.kill_graphd(victim)

        for t in writers:
            t.join(120)
            assert not t.is_alive(), "writer wedged"
        stop_readers.set()
        for t in readers:
            t.join(30)
            assert not t.is_alive(), "reader wedged"

        assert not wrong, wrong[:10]
        assert recovery.get("failover_s") is not None
        assert recovery["failover_s"] < 15.0, recovery
        # every vid's final acked value is the last round a writer got
        # acked — through a coordinator crash, nothing lost
        missing = {v: a for v, a in acked.items() if a < 1}
        assert not missing, f"writes never acked: {missing}"

        # -- zero stale cross-coordinator cache hits ----------------------
        # after the storm, every SURVIVING coordinator's CACHED read
        # must converge to the final acked value within the bounded
        # propagation window; time-to-coherence is the recovery report
        t0 = time.monotonic()
        survivors = [i for i in range(3) if i != victim]
        clients = {i: _fleet_client(cc, home=i) for i in survivors}
        sample = sorted(acked)[::5]
        deadline = t0 + 10.0
        for v in sample:
            want = [[acked[v]]]
            for i, cl in clients.items():
                q = f"FETCH PROP ON Person {v} YIELD Person.age AS a"
                while True:
                    r1, r2 = cl.execute(q), cl.execute(q)   # 2nd: cached
                    if r1.error is None and r2.error is None \
                            and r1.data.rows == want \
                            and r2.data.rows == want:
                        break
                    if time.monotonic() > deadline:
                        raise AssertionError(
                            f"coordinator {i} stale for vid {v}: "
                            f"{r1.error or r1.data.rows} / "
                            f"{r2.error or r2.data.rows}, want {want}")
                    time.sleep(0.05)
        coherence_s = time.monotonic() - t0
        for cl in clients.values():
            cl.close()
        snap = stats().snapshot()
        print(f"\nfleet chaos: failover_s={recovery['failover_s']:.2f} "
              f"coherence_s={coherence_s:.2f} "
              f"failovers={snap.get('coordinator_failovers', 0):.0f} "
              f"session_moves={snap.get('session_moves', 0):.0f} "
              f"epoch_lag_p95_ms="
              f"{snap.get('epoch_propagation_lag_ms.p95', 0):.1f}")
        assert coherence_s < 10.0
    finally:
        _pop_flags()
        cc.stop()


def test_graceful_drain_under_load_sheds_nothing():
    """Planned-restart half of the same proof: DRAIN (not kill) a
    coordinator mid-storm — every statement still acks (drain refusals
    precede execution and retry transparently), zero errors of any
    kind surface to the workload."""
    cc = ChaosCluster(n_meta=1, n_storage=3, n_graph=3, parts=4,
                      replica_factor=3)
    try:
        victim = 2
        client = _fleet_client(cc, home=victim)
        results = []

        def writer():
            for k in range(120):
                results.append(client.execute(
                    f'INSERT VERTEX Person(name, age) VALUES '
                    f'{4000 + k}:("d{k}",{k % 90})'))

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        while len(results) < 20:
            time.sleep(0.005)
        cc.cluster.drain_graphd(victim)
        cc.dead_graphds.add(victim)
        t.join(60)
        assert not t.is_alive()
        errs = [r.error for r in results if r.error is not None]
        assert not errs, errs[:5]
        assert client.addr != cc.cluster.graph_addrs[victim]
        for k in range(120):
            r = cc.ok(f"FETCH PROP ON Person {4000 + k} "
                      f"YIELD Person.age AS a")
            assert r.data.rows == [[k % 90]]
        client.close()
    finally:
        cc.stop()
