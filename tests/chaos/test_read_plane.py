"""Read-plane chaos (ISSUE 11): a mixed-consistency read storm rides
through a leader kill and a replication partition/heal while writes
run concurrently.  Asserts:

  * follower + bounded_stale reads keep succeeding (>= 99%) during the
    election the leader kill forces — with ZERO wrong rows vs the
    static oracle;
  * `leader`-consistency reads are never stale vs a sequential oracle
    (a monotonic counter: a read started after the k-th ack must
    observe >= k);
  * PR 5 acked-exactly-once still holds for the concurrent writes.

Marked `chaos` + `slow`: NOT part of the tier-1 gate.  Reproduce with
the seed in the test (the storm's vid choices and the fault schedule
draw from it).
"""
import random
import threading
import time

import pytest

from nebula_tpu.utils.consistency import use_consistency
from nebula_tpu.utils.failpoints import FaultSchedule, fail
from nebula_tpu.utils.stats import stats

from harness import (ChaosCluster, assert_acked_exactly_once,
                     counter_value, mixed_workload)

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

SEED = 707


class _ReadStorm:
    """Reader threads at one consistency level against the graphd's
    DistributedStore (the thread-local override scopes the level to
    each thread)."""

    def __init__(self, ds, space, level, oracle, stop):
        self.ds = ds
        self.space = space
        self.level = level
        self.oracle = oracle            # vid → age (static during storm)
        self.stop = stop
        self.ok = 0
        self.failed = 0
        self.wrong = []
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        rng = random.Random(f"{SEED}:{self.level}")
        vids = sorted(self.oracle)
        with use_consistency(self.level):
            while not self.stop.is_set():
                vid = rng.choice(vids)
                try:
                    tv = self.ds.get_vertex(self.space, vid)
                except Exception:  # noqa: BLE001 — counted, not raised
                    self.failed += 1
                    continue
                age = (tv or {}).get("Person", {}).get("age")
                if age == self.oracle[vid]:
                    self.ok += 1
                else:
                    self.wrong.append((vid, age))


def test_read_storm_survives_leader_kill_and_partition(tmp_path):
    cc = ChaosCluster(data_dir=str(tmp_path / "c"), n_storage=3,
                      parts=4, replica_factor=3)
    try:
        # static oracle rows: never touched during the storm
        oracle = {}
        vals = []
        for k in range(48):
            vid = 100 + k
            age = (k * 13) % 97 + 1
            oracle[vid] = age
            vals.append(f'{vid}:("p{vid}",{age})')
        cc.ok("INSERT VERTEX Person(name, age) VALUES " + ", ".join(vals))
        cc.ok("INSERT VERTEX Counter(n) VALUES 900:(0)")
        cc.wait_replicas_converged(require=3)

        ds = cc.cluster.graphds[0].store
        stop = threading.Event()
        storms = [_ReadStorm(ds, cc.space, lvl, oracle, stop)
                  for lvl in ("follower", "bounded_stale")]
        for st in storms:
            st.thread.start()

        # sequential oracle: a leader read started after the k-th acked
        # increment must observe >= k (never stale)
        seq = {"acked": 0, "viol": [], "reads": 0, "werrs": 0}
        wstop = threading.Event()

        def writer():
            while not wstop.is_set():
                r = cc.run("UPDATE VERTEX ON Counter 900 SET n = n + 1")
                if r.error is None:
                    seq["acked"] += 1
                else:
                    seq["werrs"] += 1

        def leader_reader():
            while not wstop.is_set():
                floor = seq["acked"]        # acked BEFORE the read began
                r = cc.run("FETCH PROP ON Counter 900 "
                           "YIELD Counter.n AS n")
                if r.error is None and r.data.rows:
                    seq["reads"] += 1
                    n = int(r.data.rows[0][0])
                    if n < floor:
                        seq["viol"].append((n, floor))
                time.sleep(0.01)

        wt = threading.Thread(target=writer, daemon=True)
        lt = threading.Thread(target=leader_reader, daemon=True)
        wt.start()
        lt.start()

        # concurrent PR 5 ledgered writes for the exactly-once check
        led_box = {}

        def ledger_writes():
            led_box["led"] = mixed_workload(cc, seed=SEED, n_writes=40,
                                            vid_base=4000)
        mt = threading.Thread(target=ledger_writes, daemon=True)
        mt.start()

        time.sleep(1.0)                 # storm reaches steady state
        # -- fault 1: kill the storaged leading the most parts --------
        victim = cc.leader_of_most_parts()
        cc.kill_storaged(victim)
        time.sleep(2.0)                 # election + walk window
        # -- fault 2: replication partition, then heal ----------------
        sched = FaultSchedule(SEED, [
            {"fp": "raft:replicate", "action": "raise", "p": 0.4,
             "key": "p", "max": 40},
        ]).arm(fail)
        time.sleep(1.5)
        sched.disarm(fail)              # heal
        time.sleep(1.5)

        stop.set()
        wstop.set()
        for st in storms:
            st.thread.join(10)
        wt.join(20)
        lt.join(10)
        mt.join(30)

        # -- invariants ----------------------------------------------
        for st in storms:
            total = st.ok + st.failed + len(st.wrong)
            assert total >= 20, f"{st.level}: storm too weak ({total})"
            assert not st.wrong, f"{st.level}: WRONG rows: {st.wrong[:5]}"
            rate = st.ok / total
            assert rate >= 0.99, \
                f"{st.level}: success {st.ok}/{total} = {rate:.3f} < 99%"
        assert seq["reads"] >= 10, "leader-read oracle starved"
        assert not seq["viol"], \
            f"leader reads served STALE values: {seq['viol'][:5]}"
        # follower machinery demonstrably engaged
        snap = stats().snapshot()
        fr = sum(v for k, v in snap.items()
                 if k.startswith("follower_read_total"))
        assert fr >= 20, f"follower read path barely used ({fr})"
        # exactly-once for the concurrent ledgered writes
        assert_acked_exactly_once(cc, led_box["led"])
        # the sequential counter converged to its acked count exactly
        # (failed UPDATEs may or may not have landed — bound both ways)
        n = counter_value(cc, 900)
        assert seq["acked"] <= n <= seq["acked"] + seq["werrs"], \
            (n, seq["acked"], seq["werrs"])
        cc.wait_replicas_converged(require=2)
    finally:
        cc.stop()
