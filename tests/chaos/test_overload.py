"""Overload chaos schedules (ISSUE 10): saturation + faults over a
live 3-replica cluster, with the admission plane armed.

The acceptance claims under test:

  * shedding NEVER drops acked work — the PR5 invariants (acked writes
    exactly-once, byte-identical replica convergence, drained TOSS
    journals) hold through an overload storm, with and without real
    faults underneath;
  * every E_OVERLOAD that surfaces to the client carries a
    machine-parseable retry-after hint;
  * control statements (SHOW QUERIES) keep answering during
    saturation — the priority lane's proof.

Marked `chaos` + `slow`: NOT part of the tier-1 gate.  The fault-free
goodput curve lives in tools/overload_bench.py (bench.py `overload`
block); the deadline-eviction and kill-eviction contracts are unit
tests (tests/unit/test_admission.py).
"""
import threading
import time

import pytest

from nebula_tpu.utils.admission import (admission, is_overload,
                                        parse_retry_after)
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import FaultSchedule, fail
from nebula_tpu.utils.stats import stats

from harness import ChaosCluster, WriteLedger, assert_acked_exactly_once

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

_FLAGS = ("max_running_queries", "admission_queue_capacity",
          "rpc_server_inbox_capacity", "query_timeout_secs")


def _arm_admission(slots=3, capacity=4, timeout_s=15.0):
    get_config().set_dynamic_many({
        "max_running_queries": slots,
        "admission_queue_capacity": capacity,
        "query_timeout_secs": timeout_s,
    })


def _disarm_admission():
    cfg = get_config()
    with cfg.lock:
        for k in _FLAGS:
            cfg.dynamic_layer.pop(k, None)
    admission().reset()


def _overload_storm(cc, n_writers=10, writes_each=10, vid_base=1000):
    """Concurrent single-vertex INSERT storm (each writer on its own
    client/session) + a control probe issuing SHOW QUERIES throughout.
    Returns (ledger, sheds, control_errors, control_count)."""
    led = WriteLedger()
    sheds, shed_lock = [], threading.Lock()

    def writer(wid):
        cl = cc.cluster.client()
        try:
            cl.execute(f"USE {cc.space}")
            for k in range(writes_each):
                vid = vid_base + wid * 1000 + k
                age = (wid * 7 + k) % 90 + 1
                r = cl.execute(
                    f'INSERT VERTEX Person(name, age) VALUES '
                    f'{vid}:("p{vid}",{age})')
                if r.error is None:
                    led.ack(vid, {"age": age})
                elif is_overload(r.error):
                    with shed_lock:
                        sheds.append(r.error)
                else:
                    led.fail(vid, r.error)
        finally:
            cl.close()

    ctl_errs, ctl_n = [], [0]
    stop = threading.Event()

    def control():
        cl = cc.cluster.client()
        try:
            cl.execute(f"USE {cc.space}")
            while not stop.wait(0.05):
                r = cl.execute("SHOW QUERIES")
                ctl_n[0] += 1
                if r.error is not None:
                    ctl_errs.append(r.error)
        finally:
            cl.close()

    ths = [threading.Thread(target=writer, args=(i,), daemon=True)
           for i in range(n_writers)]
    ctl_t = threading.Thread(target=control, daemon=True)
    ctl_t.start()
    for t in ths:
        t.start()
    for t in ths:
        t.join(120)
    stop.set()
    ctl_t.join(10)
    return led, sheds, ctl_errs, ctl_n[0]


def test_overload_storm_invariants(tmp_path):
    """Pure saturation (no injected faults): 10 writers against 3
    admission slots / queue of 4.  The plane must ENGAGE (statements
    queued), control statements must answer throughout, surfaced sheds
    must carry hints, and the acked set must survive exactly-once with
    replicas byte-identical."""
    cc = ChaosCluster(data_dir=str(tmp_path))
    try:
        _arm_admission(slots=3, capacity=4)
        enq0 = stats().snapshot().get("admission_enqueued", 0)
        led, sheds, ctl_errs, ctl_n = _overload_storm(cc)
        assert ctl_n > 0 and not ctl_errs, \
            f"control lane failed during saturation: {ctl_errs[:3]}"
        for e in sheds:
            assert parse_retry_after(e) is not None, e
        assert stats().snapshot().get("admission_enqueued", 0) > enq0, \
            "the storm never engaged the admission queue"
        assert led.acked, "nothing acked — storm misconfigured"
        _disarm_admission()
        cc.wait_no_pending_chains()
        cc.wait_replicas_converged(require=3)
        assert_acked_exactly_once(cc, led)
    finally:
        _disarm_admission()
        cc.stop()


def test_overload_storm_with_faults_keeps_acked_writes(tmp_path):
    """Saturation + real faults underneath (WAL fsync stalls slowing
    the data plane, acked-write replies killed at random): shedding and
    the exactly-once machinery must compose — every acked write
    survives exactly once, replicas converge byte-identically."""
    cc = ChaosCluster(data_dir=str(tmp_path))
    try:
        _arm_admission(slots=3, capacity=4, timeout_s=25.0)
        sched = FaultSchedule(707, [
            {"fp": "wal:pre_fsync", "action": "delay", "arg": 0.06,
             "p": 0.3, "key": "storage", "max": 30},
            {"fp": "rpc:server_reply", "action": "raise", "p": 0.25,
             "key": "storage.write|ok", "max": 5},
        ]).arm(fail)
        led, sheds, ctl_errs, ctl_n = _overload_storm(
            cc, n_writers=8, writes_each=8, vid_base=50_000)
        sched.disarm(fail)
        assert ctl_n > 0 and not ctl_errs, \
            f"control lane failed during saturation: {ctl_errs[:3]}"
        for e in sheds:
            assert parse_retry_after(e) is not None, e
        assert led.acked
        _disarm_admission()
        cc.wait_no_pending_chains()
        cc.wait_replicas_converged(require=3)
        assert_acked_exactly_once(cc, led)
        # faults demonstrably fired — the run exercised overload UNDER
        # failure, not beside it (the seed pins the trigger stream)
        assert sum(sched.fired.values()) > 0, sched.fired
    finally:
        fail.reset()
        _disarm_admission()
        cc.stop()


def test_overload_storm_with_leader_kill(tmp_path):
    """Saturation + a hard storaged kill mid-storm: the replica walk
    re-homes writes while admission keeps the herd bounded; acked
    writes survive exactly once on the remaining replicas."""
    cc = ChaosCluster(data_dir=str(tmp_path))
    try:
        _arm_admission(slots=3, capacity=6, timeout_s=30.0)
        killed = threading.Event()

        def killer():
            time.sleep(1.0)       # let the storm saturate first
            cc.kill_storaged(cc.leader_of_most_parts())
            killed.set()

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        led, sheds, ctl_errs, ctl_n = _overload_storm(
            cc, n_writers=8, writes_each=8, vid_base=80_000)
        kt.join(30)
        assert killed.is_set()
        for e in sheds:
            assert parse_retry_after(e) is not None, e
        assert led.acked
        assert ctl_n > 0, "control probe never ran"
        _disarm_admission()
        cc.wait_no_pending_chains()
        cc.wait_replicas_converged(require=2)
        assert_acked_exactly_once(cc, led)
    finally:
        _disarm_admission()
        cc.stop()
