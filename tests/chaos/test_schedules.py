"""Seeded fault schedules over a live 3-replica cluster (ISSUE 5).

Five distinct schedules — leader kill mid-batch, fsync stall storm,
torn TOSS chains, partitioned metad, device dispatch failure — plus a
reply-loss storm, each asserting the acked-write-exactly-once and
replica-convergence invariants.  Marked `chaos` + `slow`: NOT part of
the tier-1 gate.  Reproduce any failure with the seed in its header:

    python -m nebula_tpu.tools.chaos_bench --schedule <name> --seed <n>
"""
import threading
import time

import pytest

from nebula_tpu.utils.failpoints import FaultSchedule, fail
from nebula_tpu.utils.stats import stats

from harness import (ChaosCluster, assert_acked_exactly_once,
                     counter_value, counter_workload, mixed_workload)

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


def _batched_insert(n: int, base: int = 5000) -> str:
    vals = ", ".join(f'{base + i}:("b{base + i}",{(i * 7) % 97 + 1})'
                     for i in range(n))
    return f"INSERT VERTEX Person(name, age) VALUES {vals}"


# -- schedule 1: leader kill mid-batch (the acceptance scenario) ------------


def test_leader_kill_mid_batch(tmp_path):
    """SEED=101.  One batched INSERT; the storaged leading the most
    parts is hard-killed after the schedule's chosen propose.  The
    statement must still ack (tokened replica-walk retry), and the
    final store state must equal the fault-free twin's."""
    ref = ChaosCluster(data_dir=str(tmp_path / "ref"))
    try:
        ref.ok(_batched_insert(120))
        ref.wait_replicas_converged(require=3)
        want = ref.logical_state()
    finally:
        ref.stop()

    cc = ChaosCluster(data_dir=str(tmp_path / "chaos"))
    try:
        kill_at = 2                     # the schedule: 3rd propose dies
        trigger = threading.Event()
        done = threading.Event()

        def decide(idx, key):
            if idx == kill_at:
                trigger.set()
                done.wait(5.0)          # hold THIS propose till the kill
            return None

        def killer():
            trigger.wait(30.0)
            cc.kill_storaged(cc.leader_of_most_parts())
            done.set()

        fail.arm_callable("storage:pre_propose", decide)
        kt = threading.Thread(target=killer)
        kt.start()
        r = cc.run(_batched_insert(120))
        kt.join()
        fail.disarm("storage:pre_propose")
        assert trigger.is_set(), "kill never triggered — nothing proven"
        assert r.error is None, f"batched INSERT died with the leader: " \
                                f"{r.error}"
        retries = sum(v for k, v in stats().snapshot().items()
                      if k.startswith("storage_replica_walk_retries"))
        assert retries >= 1, "no replica-walk retry happened"
        cc.wait_replicas_converged(require=2)
        assert cc.logical_state() == want, \
            "chaos run diverged from the fault-free twin"
    finally:
        cc.stop()


# -- schedule 2: fsync stall storm ------------------------------------------


def test_fsync_stall_storm(tmp_path):
    """SEED=202.  Random 80ms WAL fsync stalls on the storage plane
    under a mixed workload: every acked write survives, replicas
    re-converge byte-identically."""
    cc = ChaosCluster(data_dir=str(tmp_path / "c"))
    try:
        sched = FaultSchedule(202, [
            {"fp": "wal:pre_fsync", "action": "delay", "arg": 0.08,
             "p": 0.35, "key": "storage", "max": 25},
        ]).arm(fail)
        led = mixed_workload(cc, seed=202, n_writes=60)
        sched.disarm(fail)
        assert sched.fired.get("wal:pre_fsync", 0) >= 5, \
            f"storm too weak: {sched.fired}"
        assert not led.failed, f"writes failed under stalls: {led.failed}"
        assert_acked_exactly_once(cc, led)
        cc.wait_replicas_converged(require=3)
    finally:
        cc.stop()


# -- schedule 3: torn TOSS chains -------------------------------------------


def test_torn_toss_chain(tmp_path):
    """SEED=303.  Edge inserts with the chain torn between the
    journaled out-half and the in-half: failed statements are allowed,
    but the janitor must re-drive every journaled chain — no pending
    journals, both halves present, replicas converged."""
    cc = ChaosCluster(data_dir=str(tmp_path / "c"))
    try:
        cc.ok(_batched_insert(40, base=9000))
        sched = FaultSchedule(303, [
            {"fp": "toss:pre_in", "action": "raise", "p": 0.5, "max": 4},
        ]).arm(fail)
        acked_edges = []
        for k in range(24):
            s, d = 9000 + k, 9000 + (k + 1) % 40
            r = cc.run(f"INSERT EDGE KNOWS(w) VALUES {s}->{d}:({k})")
            if r.error is None:
                acked_edges.append((s, d, k))
        sched.disarm(fail)
        assert sched.fired.get("toss:pre_in", 0) >= 1, "no chain torn"
        # janitor drains every journaled chain, replicas converge
        cc.wait_no_pending_chains()
        cc.wait_replicas_converged(require=3)
        # every ACKED edge serves from BOTH planes (out-half + in-half)
        for s, d, w in acked_edges:
            r = cc.ok(f"GO FROM {s} OVER KNOWS YIELD dst(edge) AS d, "
                      f"KNOWS.w AS w")
            assert [d, w] in r.data.rows, f"out-half lost {s}->{d}"
            r = cc.ok(f"GO FROM {d} OVER KNOWS REVERSELY YIELD "
                      f"src(edge) AS s, KNOWS.w AS w")
            assert [s, w] in r.data.rows, f"in-half lost {s}->{d}"
    finally:
        cc.stop()


# -- schedule 4: partitioned metad ------------------------------------------


def test_partitioned_metad(tmp_path):
    """SEED=404.  A 3-metad quorum with half its replication rounds
    dropped: writes (which heartbeat/refresh through metad) keep
    acking via the jittered leader walk, and the data plane converges."""
    cc = ChaosCluster(n_meta=3, data_dir=str(tmp_path / "c"))
    try:
        sched = FaultSchedule(404, [
            {"fp": "raft:replicate", "action": "raise", "p": 0.5,
             "key": "meta", "max": 60},
        ]).arm(fail)
        led = mixed_workload(cc, seed=404, n_writes=40, vid_base=2000)
        sched.disarm(fail)
        assert sched.fired.get("raft:replicate", 0) >= 10, \
            f"partition too weak: {sched.fired}"
        assert not led.failed, f"writes failed: {led.failed}"
        assert_acked_exactly_once(cc, led)
        cc.wait_replicas_converged(require=3)
    finally:
        cc.stop()


# -- schedule 5: device dispatch failure ------------------------------------


def test_device_dispatch_failure(tmp_path):
    """SEED=505.  Fused MATCH pipelines with half their device
    dispatches failing: every query answers with the host plane's
    exact rows (stashed-subplan fallback — never wrong, only absent)."""
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime
    cc = ChaosCluster(data_dir=str(tmp_path / "c"), parts=8,
                      tpu_runtime=TpuRuntime(make_mesh()))
    try:
        cc.ok(_batched_insert(40, base=100))
        for k in range(60):
            s, d = 100 + (k * 3) % 40, 100 + (k * 7 + 1) % 40
            if s != d:
                cc.run(f"INSERT EDGE KNOWS(w) VALUES {s}->{d}:({k})")
        q = ("MATCH (a:Person)-[:KNOWS]->(b:Person) "
             "WHERE id(a) IN [100,101,102,103,104,105] "
             "WITH DISTINCT b MATCH (b)-[:KNOWS]->(c:Person) "
             "RETURN id(b) AS x, id(c) AS y ORDER BY x, y")
        want = cc.ok(q).data.rows       # warm, fault-free answer
        sched = FaultSchedule(505, [
            {"fp": "tpu:dispatch", "action": "raise", "p": 0.5},
        ]).arm(fail)
        for _ in range(10):
            r = cc.ok(q)
            assert r.data.rows == want, "fallback changed the answer"
        sched.disarm(fail)
        assert sched.fired.get("tpu:dispatch", 0) >= 2, \
            f"dispatch faults never fired: {sched.fired}"
    finally:
        cc.stop()


# -- schedule 6: reply-loss storm (the dedup machinery under fire) ----------


def test_reply_loss_storm(tmp_path):
    """SEED=606.  Acked storage.write replies killed at random under a
    sequential counter workload: every acked increment lands exactly
    once (final value == acked count when nothing failed), and the
    dedup machinery demonstrably engaged."""
    cc = ChaosCluster(data_dir=str(tmp_path / "c"))
    try:
        sched = FaultSchedule(606, [
            {"fp": "rpc:server_reply", "action": "raise", "p": 0.4,
             "key": "storage.write|ok", "max": 8},
        ]).arm(fail)
        acked, failed = counter_workload(cc, seed=606, n=30)
        led = mixed_workload(cc, seed=606, n_writes=30, vid_base=3000)
        sched.disarm(fail)
        assert sched.fired.get("rpc:server_reply", 0) >= 3, \
            f"storm too weak: {sched.fired}"
        snap = stats().snapshot()
        dedup = snap.get("storage_write_dedup_hits", 0) + \
            snap.get("storage_write_dedup_apply_skips", 0)
        assert dedup >= 1, "re-sends were never deduplicated"
        n = counter_value(cc)
        if failed == 0:
            assert n == acked, \
                f"exactly-once violated: {n} != {acked} acked"
        else:
            assert acked <= n <= acked + failed, (n, acked, failed)
        assert_acked_exactly_once(cc, led)
        cc.wait_replicas_converged(require=3)
    finally:
        cc.stop()
