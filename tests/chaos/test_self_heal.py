"""Cluster self-healing chaos proof (ISSUE 14 tentpole).

A storaged dies PERMANENTLY under mixed read/write load and the
cluster restores full replication with NO operator action:

  * every acked write survives exactly once, zero wrong rows;
  * `under_replicated_parts` returns to 0 unattended;
  * the NEW replica set (repair targets included) converges
    byte-identically;
  * a repair plan survives a metad leader kill mid-plan (the
    raft-persisted phase resumes on the successor);
  * a flapping host (heartbeats pause < grace, then resume) triggers
    NO repair — the hysteresis against data-move thrash;
  * `UPDATE CONFIGS repair_enabled=false` is an effective kill switch.

Marked `chaos` + `slow`: NOT part of the tier-1 gate.
"""
import threading
import time

import pytest

from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import stats

from harness import (ChaosCluster, assert_acked_exactly_once,
                     mixed_workload)

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

_FAST_REPAIR = {"host_hb_expire_secs": 0.6,
                "repair_grace_secs": 0.8,
                "repair_scan_interval_secs": 0.1}
_DEFAULTS = {"host_hb_expire_secs": 10.0,
             "repair_grace_secs": 60.0,
             "repair_scan_interval_secs": 0.5,
             "repair_enabled": True}


def _set_flags(d):
    get_config().set_dynamic_many(d)


def _meta(cc: ChaosCluster):
    return cc.cluster.meta_clients[0]


def _wait_healed(cc: ChaosCluster, dead_addr: str, rf: int = 3,
                 timeout: float = 60.0):
    """Poll until every part's replica set is rf live hosts with the
    dead one gone, and the supervisor's gauge agrees."""
    meta = _meta(cc)
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        meta.refresh(force=True)
        pm = meta.parts_of(cc.space)
        if all(dead_addr not in reps and len(reps) == rf
               for reps in pm):
            snap = stats().snapshot()
            if snap.get("under_replicated_parts") == 0:
                return
        time.sleep(0.3)
    raise AssertionError(
        f"never healed: part map {meta.parts_of(cc.space)}, "
        f"repairs {meta.list_repairs()}")


def test_permanent_storaged_kill_self_heals_under_load(tmp_path):
    """The acceptance scenario: 4 storageds, rf=3, one killed for good
    under mixed load.  Acked-exactly-once holds throughout, the part
    map returns to full redundancy with zero operator statements, and
    the promoted replica set converges byte-identically."""
    _set_flags(_FAST_REPAIR)
    cc = ChaosCluster(n_storage=4, replica_factor=3,
                      data_dir=str(tmp_path))
    try:
        leds = []

        def load(seed, base):
            leds.append(mixed_workload(cc, seed, n_writes=120,
                                       vid_base=base))

        threads = [threading.Thread(target=load, args=(7 + i,
                                                       1000 + 1000 * i),
                                    daemon=True) for i in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.5)                 # writes in flight
        victim = cc.leader_of_most_parts()
        dead_addr = cc.cluster.storage_servers[victim].addr
        cc.kill_storaged(victim)
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)

        _wait_healed(cc, dead_addr)
        # acked exactly once, zero wrong rows — against the healed set
        for led in leds:
            assert led.acked, "workload acked nothing"
            assert_acked_exactly_once(cc, led)
        # the NEW replica set (repair targets included) converges
        # byte-identically: 3 live hosts now hold every part
        cc.wait_replicas_converged(require=3)
        # the plans that did it are visible and DONE
        repairs = _meta(cc).list_repairs()
        done = [r for r in repairs if r["status"] == "DONE"]
        assert done, repairs
        assert all(r["dead"] == dead_addr for r in repairs), repairs
        snap = stats().snapshot()
        assert snap.get("repair_tasks_done", 0) >= len(done)
    finally:
        _set_flags(_DEFAULTS)
        cc.stop()


def test_repair_resumes_across_metad_leader_kill_mid_plan(tmp_path):
    """A RepairPlan is raft state: kill the metad leader while its
    supervisor is mid-plan (held at a meta:repair_step failpoint) and
    the successor's supervisor re-drives it from the recorded phase to
    completion."""
    _set_flags(_FAST_REPAIR)
    cc = ChaosCluster(n_meta=3, n_storage=4, replica_factor=3,
                      data_dir=str(tmp_path))
    try:
        led = mixed_workload(cc, seed=42, n_writes=60)
        # hold the FIRST repair phases long enough to kill the leader
        # mid-plan (every plan's first few steps stall 1.5s)
        fail.arm("meta:repair_step", "4*delay(1.5)")
        victim = cc.leader_of_most_parts()
        dead_addr = cc.cluster.storage_servers[victim].addr
        cc.kill_storaged(victim)
        # wait for a plan row to exist (raft-persisted, still RUNNING)
        dl = time.monotonic() + 30
        while time.monotonic() < dl:
            reps = _meta(cc).list_repairs()
            if any(r["status"] == "RUNNING" for r in reps):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("no repair plan materialized")
        old = cc.cluster.meta_leader_index()
        assert old >= 0
        cc.cluster.stop_metad(old)
        fail.disarm("meta:repair_step")
        _wait_healed(cc, dead_addr)
        assert_acked_exactly_once(cc, led)
        cc.wait_replicas_converged(require=3)
        repairs = _meta(cc).list_repairs()
        assert any(r["status"] == "DONE" for r in repairs), repairs
        assert not any(r["status"] == "RUNNING" for r in repairs), repairs
    finally:
        fail.disarm("meta:repair_step")
        _set_flags(_DEFAULTS)
        cc.stop()


def test_flapping_host_triggers_no_repair(tmp_path):
    """Hysteresis: a host whose heartbeats pause for less than
    `repair_grace_secs` (twice) never becomes a repair target — the
    dead-clock requires CONTINUOUS death, so a flapping host cannot
    thrash part moves."""
    get_config().set_dynamic_many({"host_hb_expire_secs": 0.4,
                                   "repair_grace_secs": 1.2,
                                   "repair_scan_interval_secs": 0.05})
    cc = ChaosCluster(n_storage=3, replica_factor=3,
                      data_dir=str(tmp_path))
    try:
        cc.ok('INSERT VERTEX Person(name, age) VALUES 1:("p1",11)')
        mc = cc.cluster.meta_clients[2]      # storaged #2's heartbeat
        for _ in range(2):
            mc.stop_heartbeat()
            time.sleep(0.9)     # dead ~0.5s — inside the grace
            mc.start_heartbeat(parts_fn=mc._hb_parts_fn)
            time.sleep(0.6)     # recovers, clock resets
        time.sleep(1.0)
        assert _meta(cc).list_repairs() == []
        snap = stats().snapshot()
        assert snap.get("repair_tasks_done", 0) == 0
        assert snap.get("repair_tasks_failed", 0) == 0
        # and the cluster is back to fully healthy in the gauge
        dl = time.monotonic() + 10
        while time.monotonic() < dl:
            if stats().snapshot().get("under_replicated_parts") == 0:
                break
            time.sleep(0.1)
        assert stats().snapshot().get("under_replicated_parts") == 0
    finally:
        _set_flags(_DEFAULTS)
        cc.stop()


def test_kill_switch_pauses_a_mid_flight_plan(tmp_path):
    """Flipping `repair_enabled=false` while a plan is MID-FLIGHT stops
    it at the next phase boundary; the plan stays RUNNING (not FAILED)
    and resumes from its recorded phase when re-enabled."""
    _set_flags(_FAST_REPAIR)
    cc = ChaosCluster(n_storage=4, replica_factor=3,
                      data_dir=str(tmp_path))
    try:
        cc.ok('INSERT VERTEX Person(name, age) VALUES 1:("p1",11)')
        # hold every phase so the disable lands mid-plan
        fail.arm("meta:repair_step", "-1*delay(0.4)")
        victim = cc.leader_of_most_parts()
        dead_addr = cc.cluster.storage_servers[victim].addr
        cc.kill_storaged(victim)
        dl = time.monotonic() + 30
        while time.monotonic() < dl:
            if any(r["status"] == "RUNNING"
                   for r in _meta(cc).list_repairs()):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("no repair plan materialized")
        get_config().set_dynamic("repair_enabled", False)
        fail.disarm("meta:repair_step")
        # drivers die at the next phase boundary; nothing re-spawns
        time.sleep(2.0)
        before = {r["rid"]: (r["phase"], r["status"])
                  for r in _meta(cc).list_repairs()}
        assert any(st == "RUNNING" for _, st in before.values()), before
        assert not any(st == "FAILED" for _, st in before.values()), \
            before
        time.sleep(2.0)
        after = {r["rid"]: (r["phase"], r["status"])
                 for r in _meta(cc).list_repairs()}
        assert after == before, (before, after)   # frozen, not driven
        get_config().set_dynamic("repair_enabled", True)
        _wait_healed(cc, dead_addr)
    finally:
        fail.disarm("meta:repair_step")
        _set_flags(_DEFAULTS)
        cc.stop()


def test_repair_enabled_false_is_a_kill_switch(tmp_path):
    """`UPDATE CONFIGS repair_enabled=false`: a permanently dead host
    past the grace creates NO plan; re-enabling heals unattended."""
    get_config().set_dynamic_many({**_FAST_REPAIR,
                                   "repair_enabled": False})
    cc = ChaosCluster(n_storage=4, replica_factor=3,
                      data_dir=str(tmp_path))
    try:
        cc.ok('INSERT VERTEX Person(name, age) VALUES 1:("p1",11)')
        victim = cc.leader_of_most_parts()
        dead_addr = cc.cluster.storage_servers[victim].addr
        cc.kill_storaged(victim)
        time.sleep(3.0)                 # way past expire + grace
        assert _meta(cc).list_repairs() == []
        # the degradation IS visible while repair is off
        assert stats().snapshot().get("under_replicated_parts", 0) > 0
        # flip the switch back on — the same dynamic path UPDATE
        # CONFIGS uses — and the cluster heals
        get_config().set_dynamic("repair_enabled", True)
        _wait_healed(cc, dead_addr)
    finally:
        _set_flags(_DEFAULTS)
        cc.stop()
