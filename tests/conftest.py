import os
import sys

# Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
# exercised without TPU hardware (the driver separately dry-runs the
# multi-chip path; bench.py uses the real chip).
os.environ["JAX_PLATFORMS"] = "cpu"   # force: the session env may point at a real chip
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# jax may have been imported already (site hooks) with the env's platform
# baked in — override through the live config too.
try:
    import jax
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: seeded fault-schedule tests over a live "
        "cluster (tests/chaos/; always also marked slow)")
    config.addinivalue_line(
        "markers", "lint: fast drift checks (catalogue lints, "
        "fingerprint goldens) — tools/ci_lint.sh runs `-m lint` as a "
        "pre-merge gate without the full suite")
