"""Graph-analytics plane (ISSUE 13): `CALL algo.*` on the shared
vertex-program engine.

Covers: statement surface (parse/validate/plan), seeded oracle parity
(device PageRank/WCC/SSSP vs the independent numpy oracles — exact for
WCC/SSSP, documented tolerance + deterministic order for PageRank),
kill/deadline landing BETWEEN iterations, admission behavior (below-
interactive band, queued-statement deadline eviction), flight-recorder
forced capture for killed/shed algo statements, live SHOW QUERIES
per-iteration progress, the BFS refactor regression (device FIND
SHORTEST PATH rows still byte-identical to the host oracle through the
shared frontier steps), and the algo_bench tool.
"""
import random
import threading
import time

import numpy as np
import pytest

from nebula_tpu.core.value import NULL
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.schema import PropDef, PropType
from nebula_tpu.graphstore.store import GraphStore
from nebula_tpu.utils.admission import (admission, is_analytic_stmt,
                                        is_control_stmt)
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.flight import flight_recorder
from nebula_tpu.utils.stats import stats

tpu = pytest.importorskip("nebula_tpu.tpu")
from nebula_tpu.tpu import TpuRuntime, make_mesh  # noqa: E402

P = 4

PAGERANK_TOL = 1e-8     # documented |Δrank| bar vs the oracle


def algo_store(seed=0, n=80, avg_deg=4, spacename="ag",
               neg_weight=False):
    """Seeded random graph with a non-negative int weight prop (w),
    occasionally-NULL weights, a second edge type, and an isolated
    + dangling vertex so the corner paths (no out-edges, no edges at
    all) are always exercised."""
    rng = random.Random(seed)
    st = GraphStore()
    st.create_space(spacename, partition_num=P, vid_type="INT64")
    st.catalog.create_tag(spacename, "person", [
        PropDef("age", PropType.INT64)])
    st.catalog.create_edge(spacename, "knows", [
        PropDef("w", PropType.INT64)])
    st.catalog.create_edge(spacename, "likes", [
        PropDef("w", PropType.INT64)])
    for v in range(n):
        st.insert_vertex(spacename, v, "person", {"age": v})
    lo = -5 if neg_weight else 0
    for v in range(n - 2):          # n-2: dangling, n-1: isolated
        for _ in range(rng.randint(0, avg_deg * 2)):
            d = rng.randrange(n - 1)
            w = rng.randint(lo, 9) if rng.random() > 0.1 else NULL
            st.insert_edge(spacename, v, "knows", d, rng.randint(0, 1),
                           {"w": w})
        if rng.random() > 0.6:
            st.insert_edge(spacename, v, "likes", rng.randrange(n - 1),
                           0, {"w": rng.randint(0, 9)})
    return st


@pytest.fixture(scope="module")
def rt():
    return TpuRuntime(make_mesh(P))


@pytest.fixture(scope="module")
def eng(rt):
    st = algo_store(1)
    e = QueryEngine(st, tpu_runtime=rt)
    s = e.new_session()
    assert e.execute(s, "USE ag").ok
    return e


@pytest.fixture()
def sess(eng):
    s = eng.new_session()
    eng.execute(s, "USE ag")
    return s


@pytest.fixture()
def clean():
    fail.reset()
    admission().reset()
    yield
    fail.reset()
    admission().reset()
    for k in ("max_running_queries", "admission_queue_capacity",
              "query_timeout_secs"):
        get_config().dynamic_layer.pop(k, None)


def q(eng, sess, text):
    rs = eng.execute(sess, text)
    assert rs.error is None, f"{text} -> {rs.error}"
    return rs


# -- statement surface ------------------------------------------------------


def test_parse_plan_explain(eng, sess):
    rs = q(eng, sess, "EXPLAIN CALL algo.pagerank(max_iter=5) "
                      "YIELD vid, rank AS r")
    assert "CallAlgo" in rs.data.rows[0][0]


def test_yield_aliases_and_projection(eng, sess):
    rs = q(eng, sess, "CALL algo.pagerank(max_iter=2) "
                      "YIELD rank AS r")
    assert rs.data.column_names == ["r"]
    assert all(isinstance(v[0], float) for v in rs.data.rows)


def test_default_yield_is_full_width(eng, sess):
    rs = q(eng, sess, "CALL algo.wcc()")
    assert rs.data.column_names == ["vid", "component"]


@pytest.mark.parametrize("text,frag", [
    ("CALL algo.nope()", "unknown algorithm"),
    ("CALL algo.pagerank(bogus=1)", "unknown parameter"),
    ("CALL algo.sssp()", "requires parameter `src'"),
    ("CALL algo.pagerank() YIELD nope", "cannot YIELD"),
    ("CALL notalgo.pagerank()", "unknown procedure module"),
    ('CALL algo.pagerank(edge_types="nosuch")', "not found"),
    ("CALL algo.pagerank() YIELD rank + 1", "bare output column"),
])
def test_validation_errors(eng, sess, text, frag):
    rs = eng.execute(sess, text)
    assert rs.error is not None and frag in rs.error, (text, rs.error)


def test_duplicate_param_is_syntax_error(eng, sess):
    rs = eng.execute(sess, "CALL algo.pagerank(max_iter=1, max_iter=2)")
    assert rs.error is not None and "duplicate parameter" in rs.error


def test_bad_param_values(eng, sess):
    for text, frag in [
        ("CALL algo.pagerank(damping=2.0)", "damping"),
        ("CALL algo.pagerank(max_iter=-1)", "max_iter"),
        ('CALL algo.pagerank(mode="wat")', "mode"),
        ('CALL algo.sssp(src=0, direction="up")', "direction"),
    ]:
        rs = eng.execute(sess, text)
        assert rs.error is not None and frag in rs.error, (text,
                                                          rs.error)


def test_negative_weights_refused(rt):
    st = algo_store(9, neg_weight=True)
    e = QueryEngine(st, tpu_runtime=rt)
    s = e.new_session()
    e.execute(s, "USE ag")
    rs = e.execute(s, 'CALL algo.sssp(src=0, weight="w")')
    assert rs.error is not None and "non-negative" in rs.error


def test_sssp_unknown_source_is_empty(eng, sess):
    rs = q(eng, sess, "CALL algo.sssp(src=987654)")
    assert rs.data.rows == []


# -- oracle parity (the tentpole contract) ----------------------------------


def _rows(eng, sess, text):
    return q(eng, sess, text).data.rows


@pytest.mark.parametrize("seed", [2, 3, 4])
def test_wcc_device_matches_oracle(rt, seed):
    st = algo_store(seed)
    e = QueryEngine(st, tpu_runtime=rt)
    s = e.new_session()
    e.execute(s, "USE ag")
    dev = _rows(e, s, 'CALL algo.wcc(mode="device")')
    host = _rows(e, s, 'CALL algo.wcc(mode="host")')
    assert dev == host                      # union-find vs label prop
    assert len(dev) == 80                   # every vertex reported
    # the isolated vertex is its own component
    comp = dict(dev)
    assert comp[79] == 79


@pytest.mark.parametrize("seed", [2, 3, 4])
@pytest.mark.parametrize("weight", [None, "w"])
def test_sssp_device_matches_oracle(rt, seed, weight):
    st = algo_store(seed)
    e = QueryEngine(st, tpu_runtime=rt)
    s = e.new_session()
    e.execute(s, "USE ag")
    warg = f', weight="{weight}"' if weight else ""
    dev = _rows(e, s, f'CALL algo.sssp(src=0{warg}, mode="device")')
    host = _rows(e, s, f'CALL algo.sssp(src=0{warg}, mode="host")')
    assert dev == host                      # Bellman frontier vs Dijkstra
    d = dict(dev)
    assert d[0] == 0.0
    assert 79 not in d                      # isolated: unreached


@pytest.mark.parametrize("seed", [2, 3])
def test_pagerank_device_matches_oracle(rt, seed):
    st = algo_store(seed)
    e = QueryEngine(st, tpu_runtime=rt)
    s = e.new_session()
    e.execute(s, "USE ag")
    dev = _rows(e, s, 'CALL algo.pagerank(max_iter=30, tol=0.0, '
                      'mode="device")')
    host = _rows(e, s, 'CALL algo.pagerank(max_iter=30, tol=0.0, '
                       'mode="host")')
    assert [r[0] for r in dev] == [r[0] for r in host]   # same vid order
    diffs = [abs(a[1] - b[1]) for a, b in zip(dev, host)]
    assert max(diffs) <= PAGERANK_TOL
    # deterministic ranking order: rounding inside the tolerance, the
    # two sides rank vertices identically (ties broken by vid)
    def ranking(rows):
        return [v for v, _ in sorted(rows,
                                     key=lambda r: (-round(r[1], 6),
                                                    r[0]))]
    assert ranking(dev) == ranking(host)
    # ranks form a probability distribution over the real vertices
    assert abs(sum(r[1] for r in dev) - 1.0) < 1e-6


def test_pagerank_deterministic_across_runs(eng, sess):
    a = _rows(eng, sess, "CALL algo.pagerank(max_iter=10, tol=0.0)")
    b = _rows(eng, sess, "CALL algo.pagerank(max_iter=10, tol=0.0)")
    assert a == b                           # bit-identical run-to-run


def test_edge_types_restriction(rt):
    st = algo_store(5)
    e = QueryEngine(st, tpu_runtime=rt)
    s = e.new_session()
    e.execute(s, "USE ag")
    both = _rows(e, s, 'CALL algo.wcc(mode="device")')
    only = _rows(e, s, 'CALL algo.wcc(edge_types="knows", '
                       'mode="device")')
    host = _rows(e, s, 'CALL algo.wcc(edge_types="knows", '
                       'mode="host")')
    assert only == host
    # dropping `likes` can only split components, never merge them
    nc = lambda rows: len({c for _, c in rows})
    assert nc(only) >= nc(both)


def test_deleted_vertex_excluded(rt):
    st = algo_store(6)
    e = QueryEngine(st, tpu_runtime=rt)
    s = e.new_session()
    e.execute(s, "USE ag")
    q(e, s, "DELETE VERTEX 5")
    rows = _rows(e, s, "CALL algo.wcc()")
    assert 5 not in {r[0] for r in rows}
    assert 5 not in {r[1] for r in rows}    # nor as a component id


def test_host_mode_without_runtime():
    """No device runtime at all: auto mode runs the oracles."""
    st = algo_store(7)
    e = QueryEngine(st)                      # no tpu_runtime
    s = e.new_session()
    e.execute(s, "USE ag")
    rows = _rows(e, s, "CALL algo.wcc()")
    assert len(rows) == 80
    rs = e.execute(s, 'CALL algo.wcc(mode="device")')
    assert rs.error is not None and "no device runtime" in rs.error


# -- long-running statement contract (kill / deadline / progress) ----------


def _run_async(eng, sess, text):
    box = {}

    def run():
        box["rs"] = eng.execute(sess, text)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, box


def _wait_for(pred, timeout=10.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        got = pred()
        if got:
            return got
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {msg}")


def test_kill_lands_between_iterations(eng, clean):
    s = eng.new_session()
    eng.execute(s, "USE ag")
    fail.arm("algo:iter", "1000000*delay(0.05)")
    flight_recorder().clear()
    t, box = _run_async(
        eng, s, "CALL algo.pagerank(max_iter=10000, tol=0.0)")
    from nebula_tpu.utils.workload import live_registry
    lq = _wait_for(
        lambda: next((x for x in live_registry().snapshot()
                      if "algo.pagerank[iter" in x["operator"]), None),
        msg="live iteration progress")
    assert "active_frontier=" in lq["operator"]
    assert eng.kill_running(qid=lq["qid"])
    t.join(timeout=10)
    assert not t.is_alive()
    assert box["rs"].error == "ExecutionError: query was killed"
    # forced flight capture, classified `killed`, kind CallAlgo
    ent = next(e for e in flight_recorder().list(limit=10)
               if e["kind"] == "CallAlgo")
    assert ent["status"] == "killed"


def test_deadline_lands_between_iterations(eng, clean):
    get_config().set_dynamic("query_timeout_secs", 0.3)
    s = eng.new_session()
    eng.execute(s, "USE ag")
    fail.arm("algo:iter", "1000000*delay(0.05)")
    before = stats().snapshot().get("query_deadline_exceeded", 0)
    rs = eng.execute(s, "CALL algo.pagerank(max_iter=10000, tol=0.0)")
    assert rs.error is not None and rs.error.startswith(
        "E_QUERY_TIMEOUT")
    assert stats().snapshot()["query_deadline_exceeded"] == before + 1


def test_deadline_lands_in_host_oracle_pagerank(eng, clean):
    """The iterative HOST oracle honors the cancel contract too: the
    console path (no device runtime) must not hang a KILL/timeout
    until 10M power iterations finish."""
    get_config().set_dynamic("query_timeout_secs", 0.3)
    s = eng.new_session()
    eng.execute(s, "USE ag")
    t0 = time.monotonic()
    rs = eng.execute(s, 'CALL algo.pagerank(max_iter=10000000, '
                        'tol=0.0, mode="host")')
    assert rs.error is not None and rs.error.startswith(
        "E_QUERY_TIMEOUT")
    assert time.monotonic() - t0 < 5.0


def test_show_queries_displays_iteration_progress(eng, clean):
    s = eng.new_session()
    eng.execute(s, "USE ag")
    s2 = eng.new_session()
    fail.arm("algo:iter", "1000000*delay(0.05)")
    t, box = _run_async(
        eng, s, "CALL algo.wcc(max_iter=10000)")

    def probe():
        rs = eng.execute(s2, "SHOW QUERIES")
        for r in rs.data.rows:
            if "algo.wcc[iter" in r[5]:
                return r
        return None
    row = _wait_for(probe, msg="SHOW QUERIES algo progress")
    assert "active_frontier=" in row[5]
    assert row[4] == "RUNNING"
    fail.reset()                 # let it finish quickly
    t.join(timeout=20)
    assert box["rs"].error is None


# -- admission: below-interactive band --------------------------------------


def test_callalgo_is_analytic_not_control():
    assert is_analytic_stmt("CallAlgo")
    assert not is_control_stmt("CallAlgo")
    assert not is_analytic_stmt("Go")


def test_analytic_queues_below_interactive(clean):
    """slots=1 busy; a queued CALL algo.* must NOT be admitted while
    an interactive statement waits, even though it enqueued first."""
    from nebula_tpu.utils import cancel as _cancel
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 10)
    ctl = admission()
    blocker = ctl.acquire(qid=1, session=1, kind="Go")
    assert blocker is not None and blocker.mode == "admitted"
    order = []

    def waiter(qid, sid, kind):
        with _cancel.use_cancel(kill=threading.Event()):
            tk = ctl.acquire(qid=qid, session=sid, kind=kind)
        order.append(kind)
        tk.release()

    ta = threading.Thread(target=waiter, args=(2, 2, "CallAlgo"),
                          daemon=True)
    ta.start()
    _wait_for(lambda: ctl.snapshot()["analytic_queued"] == 1,
              msg="analytic queued")
    tb = threading.Thread(target=waiter, args=(3, 3, "Go"),
                          daemon=True)
    tb.start()
    _wait_for(lambda: ctl.snapshot()["queued"] == 2,
              msg="both queued")
    blocker.release()
    ta.join(timeout=5)
    tb.join(timeout=5)
    assert order == ["Go", "CallAlgo"]


def test_queued_algo_deadline_evicted(eng, clean):
    """PR 8 deadline-aware eviction applies to the analytic band: a
    CALL algo.* whose budget expires while QUEUED fails
    E_QUERY_TIMEOUT without ever taking a slot."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 10)
    s1 = eng.new_session()
    eng.execute(s1, "USE ag")
    s2 = eng.new_session()
    eng.execute(s2, "USE ag")
    fail.arm_callable(
        "exec:node",
        lambda i, key: ("delay", 0.8) if key == "Project" else None)
    t1, b1 = _run_async(eng, s1, "YIELD 1 AS x")   # occupies the slot
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="slot taken")
    cfg.set_dynamic("query_timeout_secs", 0.2)
    before = stats().snapshot().get("admission_deadline_evictions", 0)
    rs = eng.execute(s2, "CALL algo.pagerank(max_iter=10000, tol=0.0)")
    cfg.dynamic_layer.pop("query_timeout_secs", None)
    assert rs.error is not None and rs.error.startswith(
        "E_QUERY_TIMEOUT")
    assert stats().snapshot()["admission_deadline_evictions"] \
        == before + 1
    fail.reset()
    t1.join(timeout=20)
    assert b1["rs"].error is None


def test_kill_evicts_queued_algo(eng, clean):
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 10)
    s1 = eng.new_session()
    eng.execute(s1, "USE ag")
    s2 = eng.new_session()
    eng.execute(s2, "USE ag")
    fail.arm_callable(
        "exec:node",
        lambda i, key: ("delay", 0.8) if key == "Project" else None)
    t1, b1 = _run_async(eng, s1, "YIELD 1 AS x")
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="slot taken")
    t2, b2 = _run_async(eng, s2,
                        "CALL algo.pagerank(max_iter=10000, tol=0.0)")
    _wait_for(lambda: admission().snapshot()["analytic_queued"] == 1,
              msg="algo queued")
    assert eng.kill_running(sid=s2.id)
    t2.join(timeout=10)
    assert b2["rs"].error == "ExecutionError: query was killed"
    fail.reset()
    t1.join(timeout=20)
    assert b1["rs"].error is None


def test_shed_algo_forces_flight_capture(eng, clean):
    """Queue full → E_OVERLOAD; the flight recorder classifies the
    shed CALL algo.* like any other statement kind (ISSUE 13
    satellite)."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 0)
    s1 = eng.new_session()
    eng.execute(s1, "USE ag")
    s2 = eng.new_session()
    eng.execute(s2, "USE ag")
    fail.arm_callable(
        "exec:node",
        lambda i, key: ("delay", 0.8) if key == "Project" else None)
    t1, b1 = _run_async(eng, s1, "YIELD 1 AS x")
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="slot taken")
    flight_recorder().clear()
    rs = eng.execute(s2, "CALL algo.wcc()")
    assert rs.error is not None and rs.error.startswith("E_OVERLOAD")
    assert "retry_after_ms=" in rs.error
    ent = next(e for e in flight_recorder().list(limit=10)
               if e["kind"] == "CallAlgo")
    assert ent["status"] == "shed"
    fail.reset()
    t1.join(timeout=20)
    assert b1["rs"].error is None


# -- metrics ---------------------------------------------------------------


def test_algo_metrics_emitted(eng, sess):
    snap0 = stats().snapshot()
    q(eng, sess, "CALL algo.pagerank(max_iter=3, tol=0.0)")
    snap = stats().snapshot()
    runs = {k: v for k, v in snap.items() if k.startswith("algo_runs")}
    assert any("pagerank" in k and "device" in k for k in runs)
    it_key = next(k for k in snap
                  if k.startswith("algo_iterations")
                  and "pagerank" in k)
    assert snap[it_key] - snap0.get(it_key, 0) == 3


# -- BFS refactor regression (shared frontier steps) ------------------------


def _bfs_store(seed=11, n=60):
    rng = random.Random(seed)
    st = GraphStore()
    st.create_space("bg", partition_num=P, vid_type="INT64")
    st.catalog.create_tag("bg", "t", [PropDef("x", PropType.INT64)])
    st.catalog.create_edge("bg", "e", [PropDef("w", PropType.INT64)])
    for v in range(n):
        st.insert_vertex("bg", v, "t", {"x": v})
    for v in range(n):
        for _ in range(rng.randint(3, 7)):
            st.insert_edge("bg", v, "e", rng.randrange(n),
                           rng.randint(0, 1), {"w": rng.randint(0, 9)})
    return st


@pytest.mark.parametrize("mesh_n", [P, 1])
@pytest.mark.parametrize("where", [None, "e.w > 3"])
def test_find_shortest_path_regression(mesh_n, where):
    """Byte-identical-rows regression for the BFS refactor onto the
    shared frontier steps: device FIND SHORTEST PATH rows must equal
    the host oracle's rows exactly on both kernels (sharded P-way and
    the single-chip direction-optimizing variant), filtered and
    unfiltered."""
    st = _bfs_store()
    rt = TpuRuntime(make_mesh(mesh_n))
    w = f" WHERE {where}" if where else ""
    text = (f"FIND SHORTEST PATH FROM 1, 7 TO 13, 29 OVER e{w} "
            f"UPTO 6 STEPS YIELD path AS p")
    dev_eng = QueryEngine(st, tpu_runtime=rt)
    s = dev_eng.new_session()
    dev_eng.execute(s, "USE bg")
    dev = dev_eng.execute(s, text)
    assert dev.error is None
    host_eng = QueryEngine(st)              # host oracle (no runtime)
    hs = host_eng.new_session()
    host_eng.execute(hs, "USE bg")
    host = host_eng.execute(hs, text)
    assert host.error is None
    assert list(map(repr, dev.data.rows)) == \
        list(map(repr, host.data.rows))
    if where is None:           # the filtered variant may prune to 0
        assert len(host.data.rows) > 0


# -- bench tool -------------------------------------------------------------


def test_algo_bench_suite_small(rt):
    from nebula_tpu.tools.algo_bench import run_suite
    res = run_suite(persons=400, degree=4, parts=P, repeats=1,
                    tpu_runtime=rt)
    for algo in ("pagerank", "wcc", "sssp"):
        blk = res[algo]
        assert blk["rows_match"], (algo, blk)
        assert blk["device_s"] > 0 and blk["host_s"] > 0
        assert blk["iterations"] >= 1
    assert res["graph"]["persons"] == 400


@pytest.mark.slow
def test_oracle_parity_larger_sweep():
    """Slow variant: more seeds, bigger graphs, all three algorithms
    (tier-1 keeps the 3-seed small sweep above)."""
    rt = TpuRuntime(make_mesh(P))
    for seed in range(20, 24):
        st = algo_store(seed, n=400, avg_deg=6)
        e = QueryEngine(st, tpu_runtime=rt)
        s = e.new_session()
        e.execute(s, "USE ag")
        assert _rows(e, s, 'CALL algo.wcc(mode="device")') == \
            _rows(e, s, 'CALL algo.wcc(mode="host")')
        assert _rows(e, s, 'CALL algo.sssp(src=0, weight="w", '
                           'mode="device")') == \
            _rows(e, s, 'CALL algo.sssp(src=0, weight="w", '
                        'mode="host")')
        dev = _rows(e, s, 'CALL algo.pagerank(max_iter=40, tol=0.0, '
                          'mode="device")')
        host = _rows(e, s, 'CALL algo.pagerank(max_iter=40, tol=0.0, '
                           'mode="host")')
        assert max(abs(a[1] - b[1]) for a, b in zip(dev, host)) \
            <= PAGERANK_TOL
