"""Secondary index tests: maintenance on writes, prefix/range scans,
rebuild backfill, planner hint extraction, cluster-mode LOOKUP."""
import pytest

from nebula_tpu.exec import QueryEngine


@pytest.fixture()
def eng():
    e = QueryEngine()
    s = e.new_session()

    def run(q):
        r = e.execute(s, q)
        assert r.ok, f"{q} -> {r.error}"
        if "REBUILD" in q.upper():
            from nebula_tpu.exec.jobs import job_manager
            assert job_manager(e.qctx.store).wait()   # jobs are async (r4)
        return r

    run('CREATE SPACE ix(partition_num=4, vid_type=INT64)')
    run('USE ix')
    run('CREATE TAG p(city string, age int64)')
    run('CREATE EDGE e(w int64)')
    run('CREATE TAG INDEX i_city_age ON p(city, age)')
    run('CREATE EDGE INDEX i_w ON e(w)')
    run('INSERT VERTEX p(city, age) VALUES 1:("sf", 30), 2:("sf", 25), '
        '3:("nyc", 41), 4:("sf", 19), 5:("nyc", 30)')
    run('INSERT EDGE e(w) VALUES 1->2:(5), 2->3:(50), 3->4:(9)')
    e._run = run
    return e


def rows(eng, q):
    return eng._run(q).data.rows


def ids(eng, q):
    return sorted(r[0] for r in rows(eng, q))


def test_eq_prefix_and_range(eng):
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" YIELD id(vertex)') \
        == [1, 2, 4]
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age > 20 '
                    'YIELD id(vertex)') == [1, 2]
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age >= 19 '
                    'AND p.age < 30 YIELD id(vertex)') == [2, 4]


def test_residual_filter(eng):
    # age alone is not an index prefix of (city, age) → residual filter
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') \
        == [1, 5]


def test_edge_index_range(eng):
    got = rows(eng, 'LOOKUP ON e WHERE e.w >= 9 YIELD src(edge) AS s, '
                    'rank(edge) AS r, dst(edge) AS d')
    assert sorted(map(tuple, got)) == [(2, 0, 3), (3, 0, 4)]


def test_index_tracks_update_and_delete(eng):
    eng._run('UPDATE VERTEX ON p 2 SET age = 66')
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age > 60 '
                    'YIELD id(vertex)') == [2]
    eng._run('DELETE VERTEX 2')
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" YIELD id(vertex)') \
        == [1, 4]
    eng._run('DELETE EDGE e 2->3')
    assert rows(eng, 'LOOKUP ON e WHERE e.w == 50 YIELD src(edge)') == []


def test_rebuild_backfills(eng):
    # a new index sees only post-creation writes until REBUILD
    # (reference semantics); age==41 picks the fresh i_age index (eq
    # beats the no-prefix i_city_age), which is empty pre-rebuild
    eng._run('CREATE TAG INDEX i_age ON p(age)')
    assert rows(eng, 'LOOKUP ON p WHERE p.age == 41 YIELD id(vertex)') == []
    eng._run('REBUILD TAG INDEX i_age')
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 41 YIELD id(vertex)') == [3]


def test_duplicate_range_bounds_keep_tightest(eng):
    # both bounds consumed by the index; the tighter one must win
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age > 20 '
                    'AND p.age > 10 YIELD id(vertex)') == [1, 2]
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age < 26 '
                    'AND p.age < 100 YIELD id(vertex)') == [2, 4]


def test_drop_and_recreate_index_starts_empty(eng):
    eng._run('CREATE TAG INDEX i_age2 ON p(age)')
    eng._run('REBUILD TAG INDEX i_age2')
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') \
        == [1, 5]
    eng._run('DROP TAG INDEX i_age2')
    # mutate while the index is dropped — no maintenance happens
    eng._run('UPDATE VERTEX ON p 1 SET age = 99')
    eng._run('CREATE TAG INDEX i_age2 ON p(age)')
    # stale entry (30 → vid 1) must NOT resurrect
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') == []
    eng._run('REBUILD TAG INDEX i_age2')
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') == [5]
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 99 YIELD id(vertex)') == [1]


def test_lookup_without_index_errors():
    e = QueryEngine()
    s = e.new_session()
    for q in ['CREATE SPACE noix(partition_num=2, vid_type=INT64)',
              'USE noix', 'CREATE TAG t(a int64)']:
        assert e.execute(s, q).ok
    r = e.execute(s, 'LOOKUP ON t WHERE t.a > 0 YIELD id(vertex)')
    assert not r.ok and "index" in r.error.lower()


def test_lookup_plan_has_hints(eng):
    r = eng._run('EXPLAIN LOOKUP ON p WHERE p.city == "sf" AND p.age > 20 '
                 'YIELD id(vertex)')
    desc = r.data.rows[0][0]
    assert "IndexScan" in desc


def test_cluster_lookup_uses_index():
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    try:
        cl = c.client()
        assert cl.execute(
            "CREATE SPACE cix(partition_num=4, vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ["USE cix", "CREATE TAG t(a int)",
                  "CREATE TAG INDEX i_a ON t(a)",
                  "INSERT VERTEX t(a) VALUES 1:(10), 2:(20), 3:(30)"]:
            rs = cl.execute(q)
            assert rs.error is None, (q, rs.error)
        rs = cl.execute("LOOKUP ON t WHERE t.a >= 20 YIELD id(vertex)")
        assert rs.error is None and \
            sorted(r[0] for r in rs.data.rows) == [2, 3]
        # rebuild on live cluster (index created before data here, so it
        # must be a no-op that still reports entries)
        rs = cl.execute("REBUILD TAG INDEX i_a")
        assert rs.error is None
        from nebula_tpu.exec.jobs import job_manager
        for g in c.graphds:                      # jobs are async (r4)
            mgr = getattr(g.engine.qctx.store, "_job_manager", None)
            assert mgr is None or mgr.wait()
        rs = cl.execute("LOOKUP ON t WHERE t.a == 10 YIELD id(vertex)")
        assert rs.error is None and rs.data.rows == [[1]]
    finally:
        c.stop()


def test_index_on_alter_added_default_column(eng):
    """Rows stored before ALTER ... ADD are indexed under the filled
    default — the index path and the fill_row'd scan path must return
    the same rows (review regression)."""
    eng._run('CREATE TAG q(name string)')
    eng._run('INSERT VERTEX q(name) VALUES 10:("old1"), 11:("old2")')
    eng._run('ALTER TAG q ADD (score int DEFAULT 5)')
    eng._run('INSERT VERTEX q(name, score) VALUES 12:("new", 7)')
    eng._run('CREATE TAG INDEX iq ON q(score)')
    eng._run('REBUILD TAG INDEX iq')
    assert ids(eng, 'LOOKUP ON q WHERE q.score == 5 YIELD id(vertex)') \
        == [10, 11]
    assert ids(eng, 'LOOKUP ON q WHERE q.score >= 5 YIELD id(vertex)') \
        == [10, 11, 12]
    # incremental maintenance on a pre-ALTER row keys consistently too
    eng._run('UPDATE VERTEX ON q 10 SET name = "renamed"')
    assert ids(eng, 'LOOKUP ON q WHERE q.score == 5 YIELD id(vertex)') \
        == [10, 11]
