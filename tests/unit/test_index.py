"""Secondary index tests: maintenance on writes, prefix/range scans,
rebuild backfill, planner hint extraction, cluster-mode LOOKUP."""
import pytest

from nebula_tpu.exec import QueryEngine


@pytest.fixture()
def eng():
    e = QueryEngine()
    s = e.new_session()

    def run(q):
        r = e.execute(s, q)
        assert r.ok, f"{q} -> {r.error}"
        if "REBUILD" in q.upper():
            from nebula_tpu.exec.jobs import job_manager
            assert job_manager(e.qctx.store).wait()   # jobs are async (r4)
        return r

    run('CREATE SPACE ix(partition_num=4, vid_type=INT64)')
    run('USE ix')
    run('CREATE TAG p(city string, age int64)')
    run('CREATE EDGE e(w int64)')
    run('CREATE TAG INDEX i_city_age ON p(city, age)')
    run('CREATE EDGE INDEX i_w ON e(w)')
    run('INSERT VERTEX p(city, age) VALUES 1:("sf", 30), 2:("sf", 25), '
        '3:("nyc", 41), 4:("sf", 19), 5:("nyc", 30)')
    run('INSERT EDGE e(w) VALUES 1->2:(5), 2->3:(50), 3->4:(9)')
    e._run = run
    return e


def rows(eng, q):
    return eng._run(q).data.rows


def ids(eng, q):
    return sorted(r[0] for r in rows(eng, q))


def test_eq_prefix_and_range(eng):
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" YIELD id(vertex)') \
        == [1, 2, 4]
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age > 20 '
                    'YIELD id(vertex)') == [1, 2]
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age >= 19 '
                    'AND p.age < 30 YIELD id(vertex)') == [2, 4]


def test_residual_filter(eng):
    # age alone is not an index prefix of (city, age) → residual filter
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') \
        == [1, 5]


def test_edge_index_range(eng):
    got = rows(eng, 'LOOKUP ON e WHERE e.w >= 9 YIELD src(edge) AS s, '
                    'rank(edge) AS r, dst(edge) AS d')
    assert sorted(map(tuple, got)) == [(2, 0, 3), (3, 0, 4)]


def test_index_tracks_update_and_delete(eng):
    eng._run('UPDATE VERTEX ON p 2 SET age = 66')
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age > 60 '
                    'YIELD id(vertex)') == [2]
    eng._run('DELETE VERTEX 2')
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" YIELD id(vertex)') \
        == [1, 4]
    eng._run('DELETE EDGE e 2->3')
    assert rows(eng, 'LOOKUP ON e WHERE e.w == 50 YIELD src(edge)') == []


def test_rebuild_backfills(eng):
    # a new index sees only post-creation writes until REBUILD
    # (reference semantics); age==41 picks the fresh i_age index (eq
    # beats the no-prefix i_city_age), which is empty pre-rebuild
    eng._run('CREATE TAG INDEX i_age ON p(age)')
    assert rows(eng, 'LOOKUP ON p WHERE p.age == 41 YIELD id(vertex)') == []
    eng._run('REBUILD TAG INDEX i_age')
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 41 YIELD id(vertex)') == [3]


def test_duplicate_range_bounds_keep_tightest(eng):
    # both bounds consumed by the index; the tighter one must win
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age > 20 '
                    'AND p.age > 10 YIELD id(vertex)') == [1, 2]
    assert ids(eng, 'LOOKUP ON p WHERE p.city == "sf" AND p.age < 26 '
                    'AND p.age < 100 YIELD id(vertex)') == [2, 4]


def test_drop_and_recreate_index_starts_empty(eng):
    eng._run('CREATE TAG INDEX i_age2 ON p(age)')
    eng._run('REBUILD TAG INDEX i_age2')
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') \
        == [1, 5]
    eng._run('DROP TAG INDEX i_age2')
    # mutate while the index is dropped — no maintenance happens
    eng._run('UPDATE VERTEX ON p 1 SET age = 99')
    eng._run('CREATE TAG INDEX i_age2 ON p(age)')
    # stale entry (30 → vid 1) must NOT resurrect
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') == []
    eng._run('REBUILD TAG INDEX i_age2')
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 30 YIELD id(vertex)') == [5]
    assert ids(eng, 'LOOKUP ON p WHERE p.age == 99 YIELD id(vertex)') == [1]


def test_lookup_without_index_errors():
    e = QueryEngine()
    s = e.new_session()
    for q in ['CREATE SPACE noix(partition_num=2, vid_type=INT64)',
              'USE noix', 'CREATE TAG t(a int64)']:
        assert e.execute(s, q).ok
    r = e.execute(s, 'LOOKUP ON t WHERE t.a > 0 YIELD id(vertex)')
    assert not r.ok and "index" in r.error.lower()


def test_lookup_plan_has_hints(eng):
    r = eng._run('EXPLAIN LOOKUP ON p WHERE p.city == "sf" AND p.age > 20 '
                 'YIELD id(vertex)')
    desc = r.data.rows[0][0]
    assert "IndexScan" in desc


def test_cluster_lookup_uses_index():
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    try:
        cl = c.client()
        assert cl.execute(
            "CREATE SPACE cix(partition_num=4, vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ["USE cix", "CREATE TAG t(a int)",
                  "CREATE TAG INDEX i_a ON t(a)",
                  "INSERT VERTEX t(a) VALUES 1:(10), 2:(20), 3:(30)"]:
            rs = cl.execute(q)
            assert rs.error is None, (q, rs.error)
        rs = cl.execute("LOOKUP ON t WHERE t.a >= 20 YIELD id(vertex)")
        assert rs.error is None and \
            sorted(r[0] for r in rs.data.rows) == [2, 3]
        # rebuild on live cluster (index created before data here, so it
        # must be a no-op that still reports entries)
        rs = cl.execute("REBUILD TAG INDEX i_a")
        assert rs.error is None
        from nebula_tpu.exec.jobs import job_manager
        for g in c.graphds:                      # jobs are async (r4)
            mgr = getattr(g.engine.qctx.store, "_job_manager", None)
            assert mgr is None or mgr.wait()
        rs = cl.execute("LOOKUP ON t WHERE t.a == 10 YIELD id(vertex)")
        assert rs.error is None and rs.data.rows == [[1]]
    finally:
        c.stop()


def test_index_on_alter_added_default_column(eng):
    """Rows stored before ALTER ... ADD are indexed under the filled
    default — the index path and the fill_row'd scan path must return
    the same rows (review regression)."""
    eng._run('CREATE TAG q(name string)')
    eng._run('INSERT VERTEX q(name) VALUES 10:("old1"), 11:("old2")')
    eng._run('ALTER TAG q ADD (score int DEFAULT 5)')
    eng._run('INSERT VERTEX q(name, score) VALUES 12:("new", 7)')
    eng._run('CREATE TAG INDEX iq ON q(score)')
    eng._run('REBUILD TAG INDEX iq')
    assert ids(eng, 'LOOKUP ON q WHERE q.score == 5 YIELD id(vertex)') \
        == [10, 11]
    assert ids(eng, 'LOOKUP ON q WHERE q.score >= 5 YIELD id(vertex)') \
        == [10, 11, 12]
    # incremental maintenance on a pre-ALTER row keys consistently too
    eng._run('UPDATE VERTEX ON q 10 SET name = "renamed"')
    assert ids(eng, 'LOOKUP ON q WHERE q.score == 5 YIELD id(vertex)') \
        == [10, 11]


# ---- geo index (VERDICT r4 item 4: cell_token → covering-cell index) ----


def test_covering_ranges_contains_cell_tokens():
    """Property: every point inside a region's bbox has its cell token
    inside the region's covering ranges (the geo index's correctness
    contract — the cover may over-approximate, never under)."""
    import random
    from nebula_tpu.core.geo import (Geography, cell_token,
                                     covering_ranges, from_wkt)
    rnd = random.Random(7)
    poly = from_wkt("POLYGON((-3 -2, 9 -2, 9 7, -3 7, -3 -2))")
    rs = covering_ranges(poly)
    assert rs == sorted(rs) and all(lo <= hi for lo, hi in rs)
    for _ in range(500):
        p = Geography("point", (rnd.uniform(-3, 9), rnd.uniform(-2, 7)))
        t = cell_token(p)
        assert any(lo <= t <= hi for lo, hi in rs), p.coords
    # distance pad: points within r meters stay covered
    ctr = Geography("point", (20.0, 40.0))
    rs2 = covering_ranges(ctr, pad_m=50_000)
    import math
    for _ in range(300):
        ang = rnd.uniform(0, 2 * math.pi)
        d_deg = rnd.uniform(0, 50_000 / 111_320.0)
        p = Geography("point", (20.0 + d_deg * math.cos(ang) /
                                math.cos(math.radians(40.0)),
                                40.0 + d_deg * math.sin(ang)))
        t = cell_token(p)
        assert any(lo <= t <= hi for lo, hi in rs2), p.coords


def _gc_dest(lng: float, lat: float, bearing_deg: float, d_m: float):
    """Great-circle destination point (sphere, EARTH_RADIUS_M) — the
    exact inverse of the haversine distance_m uses."""
    import math
    from nebula_tpu.core.geo import EARTH_RADIUS_M
    br = math.radians(bearing_deg)
    la1 = math.radians(lat)
    lo1 = math.radians(lng)
    dr = d_m / EARTH_RADIUS_M
    la2 = math.asin(math.sin(la1) * math.cos(dr)
                    + math.cos(la1) * math.sin(dr) * math.cos(br))
    lo2 = lo1 + math.atan2(math.sin(br) * math.sin(dr) * math.cos(la1),
                           math.cos(dr) - math.sin(la1) * math.sin(la2))
    lng2 = math.degrees(lo2)
    if lng2 > 180.0:
        lng2 -= 360.0
    if lng2 < -180.0:
        lng2 += 360.0
    return lng2, math.degrees(la2)


def test_geo_pad_boundary_shell():
    """Regression for the geo pad under-coverage (ADVICE high,
    core/geo.py): the old 111320 m/deg conversion exceeded the
    EARTH_RADIUS_M-derived ~111195 m/deg, so the padded bbox was ~0.11%
    too small and points at distance just under r fell OUTSIDE the
    covering ranges (44/3000 fuzz misses, e.g. dist 299997 m for
    r=300000).  Walk a shell of points at 0.9990r..0.9999r around
    centers at several latitudes and assert every one lands inside the
    cover — the geo index must never under-approximate ST_DWithin."""
    from nebula_tpu.core.geo import (Geography, _pad_boxes, cell_token,
                                     covering_ranges, distance_m)
    for (clng, clat) in [(0.0, 0.0), (20.0, 40.0), (-70.0, -33.0),
                         (150.0, 60.0)]:
        ctr = Geography("point", (clng, clat))
        for r in (5_000.0, 300_000.0):
            boxes = _pad_boxes(ctr, r)
            rs = covering_ranges(ctr, pad_m=r)
            for bearing in range(0, 360, 15):
                for frac in (0.9990, 0.9999):
                    p = Geography("point",
                                  _gc_dest(clng, clat, bearing, r * frac))
                    assert distance_m(ctr, p) <= r, (ctr, p)
                    # the RAW padded box must contain the point — cell
                    # rounding usually masked the old under-coverage,
                    # so assert below the quantization too
                    px, py = p.coords
                    assert any(lo <= px <= hi and la <= py <= lb
                               for (lo, hi, la, lb) in boxes), \
                        (ctr.coords, r, bearing, frac, p.coords, boxes)
                    t = cell_token(p)
                    assert any(lo <= t <= hi for lo, hi in rs), \
                        (ctr.coords, r, bearing, frac, p.coords)


def test_geo_index_lookup_and_maintenance(eng):
    eng._run('CREATE TAG place(name string, loc geography)')
    eng._run('CREATE TAG INDEX ploc ON place(loc)')
    eng._run('INSERT VERTEX place(name, loc) VALUES '
             '20:("a", ST_Point(1.0, 1.0)), 21:("b", ST_Point(5.0, 5.0)), '
             '22:("c", ST_Point(50.0, 50.0)), 23:("n", NULL)')
    q = ('LOOKUP ON place WHERE ST_Intersects(place.loc, '
         'ST_GeogFromText("POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))")) '
         'YIELD id(vertex)')
    assert ids(eng, q) == [20, 21]
    # update moves the entry between cells
    eng._run('UPDATE VERTEX ON place 22 SET loc = ST_Point(2.0, 2.0)')
    assert ids(eng, q) == [20, 21, 22]
    # delete removes it
    eng._run('DELETE VERTEX 21')
    assert ids(eng, q) == [20, 22]
    # distance predicates (both spellings) ride the same index
    assert ids(eng, 'LOOKUP ON place WHERE ST_Distance(place.loc, '
                    'ST_Point(1.0, 1.0)) < 1000 YIELD id(vertex)') == [20]
    assert ids(eng, 'LOOKUP ON place WHERE ST_DWithin(place.loc, '
                    'ST_Point(2.0, 2.0), 1000) YIELD id(vertex)') == [22]


def test_geo_index_is_cell_keyed(eng):
    """The index object is the GeoIndexData subclass (cell-token keys),
    and REBUILD backfills it for rows written before CREATE INDEX."""
    from nebula_tpu.graphstore.index import GeoIndexData
    eng._run('CREATE TAG spot(loc geography)')
    eng._run('INSERT VERTEX spot(loc) VALUES 30:(ST_Point(2.0, 2.0)), '
             '31:(ST_Point(80.0, 10.0))')
    eng._run('CREATE TAG INDEX sloc ON spot(loc)')
    eng._run('REBUILD TAG INDEX sloc')
    st = eng.qctx.store
    idx = st.space("ix").index_data["sloc"]
    assert isinstance(idx, GeoIndexData)
    assert idx.count() == 2
    assert ids(eng, 'LOOKUP ON spot WHERE ST_DWithin(spot.loc, '
                    'ST_Point(2.0, 2.0), 5000) YIELD id(vertex)') == [30]


def test_geo_plan_uses_covering_ranges(eng):
    eng._run('CREATE TAG park(loc geography)')
    eng._run('CREATE TAG INDEX parkloc ON park(loc)')
    r = eng._run('EXPLAIN LOOKUP ON park WHERE ST_Intersects(park.loc, '
                 'ST_Point(1.0, 1.0)) YIELD id(vertex)')
    txt = "\n".join(str(c) for row in r.data.rows for c in row)
    assert "geo_ranges" in txt and "IndexScan" in txt
    # MATCH seeds from the geo index through the exploration rule
    r = eng._run('EXPLAIN MATCH (a:park) WHERE ST_DWithin(a.park.loc, '
                 'ST_Point(1.0, 1.0), 500) RETURN id(a)')
    txt = "\n".join(str(c) for row in r.data.rows for c in row)
    assert "geo_ranges" in txt


def test_geo_index_non_point_shapes(eng):
    """LINESTRING/POLYGON values are keyed by EVERY covering cell —
    single-centroid keying silently dropped shapes whose centroid falls
    outside the query cover (code-review repro: creating the index
    changed query results)."""
    eng._run('CREATE TAG road(loc geography)')
    eng._run('CREATE TAG INDEX rloc ON road(loc)')
    eng._run('INSERT VERTEX road(loc) VALUES '
             '40:(ST_GeogFromText("LINESTRING(0 0, 100 0)")), '
             '41:(ST_Point(2.0, 2.0))')
    # centroid of 40 is (50, 0) — outside this region; the line itself
    # crosses it
    q = ('LOOKUP ON road WHERE ST_Intersects(road.loc, '
         'ST_GeogFromText("POLYGON((-1 -1, 5 -1, 5 5, -1 5, -1 -1))")) '
         'YIELD id(vertex)')
    assert ids(eng, q) == [40, 41]
    # no duplicate rows from the multi-cell entries
    assert len(rows(eng, q)) == 2
    # maintenance removes every cell entry
    eng._run('DELETE VERTEX 40')
    assert ids(eng, q) == [41]


def test_covering_ranges_antimeridian_and_pole():
    """Distance pads that cross the antimeridian or degenerate near a
    pole must stay supersets of the true disc (code-review repro)."""
    from nebula_tpu.core.geo import Geography, cell_token, covering_ranges

    def covered(rs, lng, lat):
        t = cell_token(Geography("point", (lng, lat)))
        return any(lo <= t <= hi for lo, hi in rs)

    rs = covering_ranges(Geography("point", (179.9, 0.0)), pad_m=50_000)
    assert covered(rs, -179.9, 0.0)        # 22 km across the seam
    rs = covering_ranges(Geography("point", (-179.95, 10.0)), pad_m=30_000)
    assert covered(rs, 179.9, 10.0)
    rs = covering_ranges(Geography("point", (0.0, 89.5)), pad_m=50_000)
    assert covered(rs, 30.0, 89.5)         # 29 km around the pole cap
    rs = covering_ranges(Geography("point", (0.0, 89.98)), pad_m=50_000)
    assert covered(rs, 180.0, 89.99)       # pad crosses the pole


def test_lookup_prefers_eq_index_over_geo(eng):
    """An equality binding on a B-tree index is more selective than the
    bbox cover; the geo branch must not preempt it (code-review)."""
    eng._run('CREATE TAG shop(city string, loc geography)')
    eng._run('CREATE TAG INDEX shopcity ON shop(city)')
    eng._run('CREATE TAG INDEX shoploc ON shop(loc)')
    eng._run('INSERT VERTEX shop(city, loc) VALUES '
             '50:("sf", ST_Point(1.0, 1.0)), 51:("nyc", ST_Point(1.0, 1.0))')
    r = eng._run('EXPLAIN LOOKUP ON shop WHERE shop.city == "sf" AND '
                 'ST_Intersects(shop.loc, ST_Point(1.0, 1.0)) '
                 'YIELD id(vertex)')
    txt = "\n".join(str(c) for row in r.data.rows for c in row)
    assert "shopcity" in txt and "geo_ranges" not in txt
    # and the rows are still exact
    assert ids(eng, 'LOOKUP ON shop WHERE shop.city == "sf" AND '
                    'ST_Intersects(shop.loc, ST_Point(1.0, 1.0)) '
                    'YIELD id(vertex)') == [50]


def test_string_prefix_index(eng):
    """CREATE TAG INDEX i ON t(name(4)) — reference string-prefix
    spelling: keys truncate, probes truncate to match, bounds widen to
    inclusive, and the full predicate stays residual so shared prefixes
    never surface wrong rows."""
    eng._run('CREATE TAG u(name string, age int)')
    eng._run('CREATE TAG INDEX uname ON u(name(4))')
    eng._run('INSERT VERTEX u(name, age) VALUES 60:("alexander", 30), '
             '61:("alexis", 25), 62:("bob", 40), 63:("alex", 20)')
    assert ids(eng, 'LOOKUP ON u WHERE u.name == "alexander" '
                    'YIELD id(vertex)') == [60]
    assert ids(eng, 'LOOKUP ON u WHERE u.name == "alex" '
                    'YIELD id(vertex)') == [63]
    assert ids(eng, 'LOOKUP ON u WHERE u.name > "alexb" '
                    'YIELD id(vertex)') == [61, 62]
    # exclusive lo exactly at the prefix length collides with truncated
    # keys — must widen to inclusive + residual (code-review repro)
    assert ids(eng, 'LOOKUP ON u WHERE u.name > "alex" '
                    'YIELD id(vertex)') == [60, 61, 62]
    assert ids(eng, 'LOOKUP ON u WHERE u.name >= "alexander" '
                    'YIELD id(vertex)') == [60, 61, 62]
    # maintenance respects truncation
    eng._run('UPDATE VERTEX ON u 62 SET name = "alexzzz"')
    assert ids(eng, 'LOOKUP ON u WHERE u.name == "alexzzz" '
                    'YIELD id(vertex)') == [62]
    # rebuild keeps the prefix keys
    eng._run('REBUILD TAG INDEX uname')
    assert ids(eng, 'LOOKUP ON u WHERE u.name == "alexis" '
                    'YIELD id(vertex)') == [61]
    # introspection shows the prefix length
    r = eng._run('DESC TAG INDEX uname')
    assert r.data.rows[0][0] == "name(4)"
    # non-string prop with a length / zero length are errors
    s2 = eng.new_session()
    assert eng.execute(s2, 'USE ix').ok
    rs = eng.execute(s2, 'CREATE TAG INDEX bad ON u(age(4))')
    assert not rs.ok and "string" in rs.error.lower()
    rs = eng.execute(s2, 'CREATE TAG INDEX bad2 ON u(name(0))')
    assert not rs.ok
