"""Value-model semantics: null kinds, 3-valued logic, compare, arithmetic."""
import math

from nebula_tpu.core import (EMPTY, NULL, NULL_BAD_TYPE, NULL_DIV_BY_ZERO,
                             NULL_OVERFLOW, DataSet, Date, DateTime, Duration,
                             Edge, Path, Step, Tag, Time, Vertex, is_null,
                             total_order_key, type_name)
from nebula_tpu.core.value import (INT64_MAX, logical_and, logical_not,
                                   logical_or, logical_xor, v_add, v_div,
                                   v_eq, v_lt, v_mod, v_mul, v_ne, v_sub)


def test_null_kinds_interned():
    assert NULL is not NULL_BAD_TYPE
    assert NULL == NULL_BAD_TYPE  # all nulls equal for dedup
    assert hash(NULL) == hash(NULL_DIV_BY_ZERO)
    assert repr(NULL_DIV_BY_ZERO) == "__DIV_BY_ZERO__"


def test_arithmetic_null_propagation():
    assert is_null(v_add(NULL, 1))
    assert is_null(v_mul(2, NULL))
    assert v_add(NULL_BAD_TYPE, 1) is NULL_BAD_TYPE


def test_division():
    assert v_div(7, 2) == 3
    assert v_div(-7, 2) == -3  # trunc toward zero, not floor
    assert v_div(7.0, 2) == 3.5
    assert v_div(1, 0) is NULL_DIV_BY_ZERO
    assert v_div(1.0, 0.0) is NULL_DIV_BY_ZERO
    assert v_mod(7, 3) == 1
    assert v_mod(-7, 3) == -1  # C-style remainder
    assert v_mod(5, 0) is NULL_DIV_BY_ZERO


def test_overflow():
    assert v_add(INT64_MAX, 1) is NULL_OVERFLOW
    assert v_mul(INT64_MAX, 2) is NULL_OVERFLOW
    assert v_add(INT64_MAX, 0) == INT64_MAX


def test_string_concat():
    assert v_add("a", "b") == "ab"
    assert v_add("a", 1) == "a1"
    assert v_add(1, "a") == "1a"
    assert v_add("x", True) == "xtrue"


def test_list_concat():
    assert v_add([1], [2, 3]) == [1, 2, 3]
    assert v_add([1], 2) == [1, 2]
    assert v_add(0, [1]) == [0, 1]


def test_bad_type_arith():
    assert v_sub("a", 1) is NULL_BAD_TYPE
    assert v_mul(True, 2) is NULL_BAD_TYPE  # bool is not numeric


def test_three_valued_logic():
    assert logical_and(True, NULL) is NULL
    assert logical_and(False, NULL) is False
    assert logical_or(True, NULL) is True
    assert logical_or(False, NULL) is NULL
    assert logical_not(NULL) is NULL
    assert logical_xor(True, NULL) is NULL
    assert logical_and(True, True) is True


def test_eq_semantics():
    assert v_eq(1, 1.0) is True
    assert v_eq(1, "1") is False  # cross-type == is false, not null
    assert is_null(v_eq(NULL, 1))
    assert is_null(v_eq(NULL, NULL))
    assert v_ne(1, 2) is True
    assert v_eq([1, 2], [1, 2]) is True
    assert v_eq([1, NULL], [1, 2]) is NULL


def test_lt_semantics():
    assert v_lt(1, 2.5) is True
    assert v_lt("a", "b") is True
    assert v_lt(1, "a") is NULL_BAD_TYPE
    assert is_null(v_lt(NULL, 1))
    assert v_lt([1, 2], [1, 3]) is True
    assert v_lt([1], [1, 0]) is True


def test_total_order():
    vals = [NULL, "b", 2, EMPTY, 1.5, "a", True]
    s = sorted(vals, key=total_order_key)
    assert s[0] is EMPTY
    assert s[-1] is NULL
    assert s[1] is True
    assert s[2:4] == [1.5, 2]
    assert s[4:6] == ["a", "b"]


def test_date_time_compare():
    assert v_lt(Date(2020, 1, 1), Date(2020, 1, 2)) is True
    assert v_eq(Time(1, 2, 3), Time(1, 2, 3)) is True
    assert v_lt(DateTime(2020, 1, 1), DateTime(2021, 1, 1)) is True


def test_date_plus_duration():
    d = v_add(Date(2020, 1, 31), Duration(months=1))
    assert d == Date(2020, 2, 29)  # clamped to month end (leap year)
    d2 = v_add(Date(2020, 1, 1), Duration(seconds=86400))
    assert d2 == Date(2020, 1, 2)


def test_vertex_edge_path():
    v1 = Vertex("a", [Tag("person", {"name": "Ann", "age": 30})])
    v2 = Vertex("b", [Tag("person", {"name": "Bob"})])
    assert v1.prop("person", "age") == 30
    assert is_null(v1.prop("person", "nope"))
    e = Edge("a", "b", "knows", 0, {"since": 2010})
    er = Edge("b", "a", "knows", 0, {"since": 2010}, etype=-1)
    assert e.key() == er.key()  # direction-insensitive identity
    p = Path(v1, [Step(v2, "knows", 0, {"since": 2010})])
    assert p.length() == 1
    assert [n.vid for n in p.nodes()] == ["a", "b"]
    assert p.relationships()[0].src == "a"
    assert not p.has_duplicate_vertices()


def test_dataset():
    ds = DataSet(["a", "b"], [[1, 2], [3, 4]])
    assert ds.column("b") == [2, 4]
    assert len(ds) == 2
    assert type_name(ds) == "dataset"


def test_columnar_wire_roundtrip():
    """Device-plane results ship columnar through the wire (SURVEY §2
    row 25 / VERDICT r4 item 2): numeric columns as raw buffers hoisted
    into binary RPC frames, base64 when serialized to a file/raft entry,
    object columns per-value; materialized sets fall back to row form."""
    import numpy as np

    from nebula_tpu.core import wire
    from nebula_tpu.core.value import ColumnarDataSet

    d = np.arange(1000, dtype=np.int64) * 7
    w = np.linspace(0, 1, 1000)
    s = np.array([f"s{i}" for i in range(1000)], dtype=object)
    ds = ColumnarDataSet(["d", "w", "s"], [d, w, s])
    # file/raft serialization: base64 fallback
    back = wire.loads(wire.dumps(ds))
    assert isinstance(back, ColumnarDataSet)
    assert np.array_equal(np.asarray(back._cols[0]), d)
    assert np.allclose(np.asarray(back._cols[1]), w)
    assert list(back._cols[2]) == list(s)
    # rpc framing: raw buffers ride out-of-band binary frames
    from nebula_tpu.cluster.rpc import RpcClient, RpcServer
    srv = RpcServer()
    srv.register("q", lambda p: {"data": wire.to_wire(
        ColumnarDataSet(["d", "w"], [d, w])), "note": "x"})
    srv.start()
    try:
        cl = RpcClient(srv.host, srv.port)
        r = cl.call("q")
        assert r["note"] == "x"          # plain JSON fields intact
        got = wire.from_wire(r["data"])
        assert isinstance(got, ColumnarDataSet)
        assert np.array_equal(np.asarray(got._cols[0]), d)
        assert np.allclose(np.asarray(got._cols[1]), w)
        # non-blob calls still use the plain JSON frame
        srv.register("plain", lambda p: {"v": [1, 2, 3]})
        assert cl.call("plain") == {"v": [1, 2, 3]}
    finally:
        srv.stop()
    # materialized → plain dataset tag (rows already exist)
    ds2 = ColumnarDataSet(["v"], [np.arange(3)])
    _ = ds2.rows
    assert wire.to_wire(ds2)["@t"] == "dataset"
