"""Read-path fault tolerance (ISSUE 11): read-index / lease follower
reads, the lease clock-skew margin, load-aware replica routing, and the
leader-hint write-back into the cached part map."""
import time

import pytest

from nebula_tpu.cluster.launcher import LocalCluster
from nebula_tpu.cluster.raft import LoopbackTransport, RaftPart
from nebula_tpu.cluster.rpc import RpcClient, RpcError, reset_breakers
from nebula_tpu.cluster.storage_client import (
    note_peer_latency, note_peer_overload, peer_score, reset_peer_stats)
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.consistency import use_consistency
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import stats


@pytest.fixture(autouse=True)
def _clean():
    fail.reset()
    reset_breakers()
    reset_peer_stats()
    yield
    fail.reset()
    reset_breakers()
    reset_peer_stats()
    for k in ("read_consistency", "read_max_stale_ms",
              "raft_lease_margin_ms", "result_cache_size"):
        get_config().dynamic_layer.pop(k, None)


# -- raft-level read_index ---------------------------------------------------


def _loopback_group(tmp_path, n=3, group="ri"):
    tr = LoopbackTransport()
    nodes = {}
    ids = ["a", "b", "c", "d", "e"][:n]
    for nid in ids:
        nodes[nid] = RaftPart(group, nid, list(ids), tr,
                              str(tmp_path / nid),
                              apply_cb=lambda i, d: None, wal_sync=False)
    for node in nodes.values():
        node.start()
    deadline = time.time() + 5
    leader = None
    while time.time() < deadline and leader is None:
        leader = next((x for x in nodes.values() if x.is_leader()), None)
        time.sleep(0.05)
    assert leader is not None, "no leader elected"
    return tr, nodes, leader


def test_read_index_leader_lease_fast_path(tmp_path):
    tr, nodes, leader = _loopback_group(tmp_path)
    try:
        time.sleep(0.3)                      # settle heartbeat acks
        assert leader.propose(b"x") is not None
        before = stats().snapshot().get(
            'raft_read_index{path=lease}', 0)
        idx = leader.read_index()
        assert idx is not None and idx >= leader.commit_index - 1
        # the barrier covers everything committed before the call
        assert idx >= 1
        after = stats().snapshot().get('raft_read_index{path=lease}', 0)
        assert after == before + 1, "lease fast path not taken"
    finally:
        for n in nodes.values():
            n.stop()


def test_read_index_follower_forwards_and_waits(tmp_path):
    applied = {nid: [] for nid in ("a", "b", "c")}
    tr = LoopbackTransport()
    nodes = {}
    for nid in ("a", "b", "c"):
        nodes[nid] = RaftPart(
            "rif", nid, ["a", "b", "c"], tr, str(tmp_path / nid),
            apply_cb=(lambda i, d, _n=nid: applied[_n].append(d)),
            wal_sync=False)
    for n in nodes.values():
        n.start()
    deadline = time.time() + 5
    leader = None
    while time.time() < deadline and leader is None:
        leader = next((x for x in nodes.values() if x.is_leader()), None)
        time.sleep(0.05)
    assert leader is not None
    try:
        assert leader.propose(b"w1") is not None
        follower = next(n for n in nodes.values() if n is not leader)
        idx = follower.read_index()
        assert idx is not None and idx >= 1
        # a follower read observes everything committed before it began
        assert follower.wait_applied(idx, timeout=5.0)
        assert b"w1" in applied[follower.node_id]
    finally:
        for n in nodes.values():
            n.stop()


def test_read_index_quorum_fallback_without_lease(tmp_path):
    """With the lease margin >= the election timeout the lease fast
    path is disabled — read_index must still answer via a live quorum
    round."""
    tr, nodes, leader = _loopback_group(tmp_path, group="riq")
    try:
        assert leader.propose(b"q1") is not None
        get_config().set_dynamic("raft_lease_margin_ms", 10_000.0)
        assert not leader.has_lease(), \
            "margin >= election timeout must kill the lease"
        before = stats().snapshot().get(
            'raft_read_index{path=quorum}', 0)
        idx = leader.read_index()
        assert idx is not None and idx >= 1
        after = stats().snapshot().get(
            'raft_read_index{path=quorum}', 0)
        assert after == before + 1, "quorum confirm path not taken"
    finally:
        get_config().dynamic_layer.pop("raft_lease_margin_ms", None)
        for n in nodes.values():
            n.stop()


def test_deposed_leader_rejects_lease_and_read_index(tmp_path):
    """ISSUE 11 satellite: a minority-side ex-leader must refuse lease
    reads within the margined window AND fail read_index (its quorum
    confirm cannot complete), while the majority side elects a leader
    that serves."""
    tr, nodes, leader = _loopback_group(tmp_path, group="rid")
    try:
        others = [n for n in nodes.values() if n is not leader]
        tr.partition(leader.node_id, others[0].node_id)
        tr.partition(leader.node_id, others[1].node_id)
        # the margined lease window is eto_min - margin: the ex-leader
        # must stop serving lease reads no later than that
        margin_s = leader._lease_margin_s()
        deadline = time.time() + 5
        while time.time() < deadline and leader.has_lease():
            time.sleep(0.01)
        assert not leader.has_lease()
        # ... and read_index on the deposed side must NOT answer (no
        # lease, no quorum)
        assert leader.read_index(timeout=0.5) is None
        # the majority side elects a new leader that serves read_index
        deadline = time.time() + 5
        new_leader = None
        while time.time() < deadline and new_leader is None:
            new_leader = next((n for n in others if n.is_leader()), None)
            time.sleep(0.05)
        assert new_leader is not None, "majority never re-elected"
        assert new_leader.read_index() is not None
        assert margin_s > 0, "default lease margin must be non-zero"
    finally:
        for n in nodes.values():
            n.stop()


def test_read_index_failpoint_site(tmp_path):
    tr, nodes, leader = _loopback_group(tmp_path, group="rfp")
    try:
        fail.arm("raft:read_index", "raise(down)")
        assert leader.read_index() is None
        fail.disarm("raft:read_index")
        time.sleep(0.2)
        assert leader.read_index() is not None
    finally:
        for n in nodes.values():
            n.stop()


# -- replica routing scores --------------------------------------------------


def test_peer_scores_steer_away_from_overload_and_latency():
    note_peer_latency("h1:1", 0.002)
    note_peer_latency("h2:1", 0.200)
    assert peer_score("h1:1") < peer_score("h2:1")
    # an E_OVERLOAD hint penalizes the peer for its retry-after window
    note_peer_overload("h1:1", 2.0)
    assert peer_score("h1:1") > peer_score("h2:1")
    # the penalty decays with the window
    note_peer_overload("h3:1", 0.0)
    time.sleep(0.01)
    assert peer_score("h3:1") < peer_score("h1:1")


def test_route_orders_follower_reads_by_score():
    from nebula_tpu.cluster.storage_client import StorageClient

    class _Meta:
        pass
    sc = StorageClient.__new__(StorageClient)
    note_peer_latency("r1:1", 0.5)
    note_peer_latency("r2:1", 0.001)
    replicas = ["r1:1", "r2:1", "r3:1"]
    assert sc._route(replicas, follower_ok=False) == replicas
    ranked = sc._route(replicas, follower_ok=True)
    assert ranked[0] in ("r2:1", "r3:1") and ranked[-1] == "r1:1"


# -- leader-hint write-back --------------------------------------------------


def test_parts_of_applies_leader_hint_overlay():
    from nebula_tpu.cluster.meta_client import MetaClient
    mc = MetaClient(["never:1"], heartbeat_interval=999.0)
    mc.part_map = {"sp": [["a:1", "b:1", "c:1"], ["a:1", "b:1", "c:1"]]}
    mc.note_part_leader("sp", 1, "c:1")
    pm = mc.parts_of("sp")
    assert pm[0] == ["a:1", "b:1", "c:1"]
    assert pm[1] == ["c:1", "a:1", "b:1"]
    # a hint whose addr left the replica set is ignored
    mc.note_part_leader("sp", 0, "gone:9")
    mc.part_map["sp"][0] = ["a:1", "b:1"]
    assert mc.parts_of("sp")[0] == ["a:1", "b:1"]
    # garbage hints never land
    mc2 = MetaClient(["never:1"], heartbeat_interval=999.0)
    mc2.note_part_leader("sp", 0, "")
    mc2.note_part_leader("sp", 0, "noport")
    assert ("sp", 0) not in mc2._part_hints


def _part_and_leader(cluster, space, pid):
    sid = cluster.storageds[0].meta.catalog.get_space(space).space_id
    for ss in cluster.storageds:
        part = ss.parts.get((sid, pid))
        if part is not None and part.is_leader():
            return ss, part
    return None, None


@pytest.mark.slow
def test_leader_hint_write_back_one_walk_per_failover(tmp_path):
    """The regression the satellite names: after a leadership move the
    FIRST statement pays the replica walk and writes the hint back;
    the next statement routes straight to the new leader — one walk
    total, not one per call."""
    c = LocalCluster(n_meta=1, n_storage=3, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        cl = c.client()
        assert cl.execute("CREATE SPACE hint(partition_num=1, "
                          "replica_factor=3, vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ("USE hint", "CREATE TAG P(x int)",
                  "INSERT VERTEX P(x) VALUES 1:(7)"):
            r = cl.execute(q)
            assert r.error is None, (q, r.error)

        meta = c.graphds[0].meta
        deadline = time.time() + 10
        ss = part = None
        while time.time() < deadline and part is None:
            ss, part = _part_and_leader(c, "hint", 0)
            if part is None:
                time.sleep(0.05)
        assert part is not None, "part 0 never elected a leader"
        # move leadership to a replica that is neither the current
        # leader nor the address the client would try FIRST (the hint
        # overlay / map front) — so the next read must walk exactly once
        first_tried = meta.parts_of("hint")[0][0]
        candidates = [a for a in meta.parts_of("hint")[0]
                      if a not in (first_tried, ss.my_addr)]
        assert candidates, "need a third replica to transfer to"
        target = candidates[0]
        assert part.transfer_leadership(target), "transfer failed"
        deadline = time.time() + 10
        while time.time() < deadline:
            ss2, p2 = _part_and_leader(c, "hint", 0)
            if ss2 is not None and ss2.my_addr == target:
                break
            time.sleep(0.05)
        assert ss2 is not None and ss2.my_addr == target

        def walks():
            return sum(v for k, v in stats().snapshot().items()
                       if k.startswith("storage_replica_walk_retries"))

        q = "FETCH PROP ON P 1 YIELD P.x AS x"
        w0 = walks()
        r = cl.execute(q)
        assert r.error is None and r.data.rows == [[7]]
        w1 = walks()
        assert w1 > w0, "failover read should have walked once"
        # the hint is written back: the NEXT statement goes straight
        r = cl.execute(q)
        assert r.error is None and r.data.rows == [[7]]
        w2 = walks()
        assert w2 == w1, \
            f"second statement re-walked ({w2 - w1} extra walks) — " \
            f"leader hint was not written back"
        assert meta.parts_of("hint")[0][0] == target
    finally:
        c.stop()


# -- storaged consistency levels over a live cluster -------------------------


@pytest.fixture(scope="module")
def rcluster(tmp_path_factory):
    fail.reset()
    reset_breakers()
    c = LocalCluster(n_meta=1, n_storage=3, n_graph=1,
                     data_dir=str(tmp_path_factory.mktemp("rp")))
    cl = c.client()
    assert cl.execute("CREATE SPACE rp(partition_num=2, "
                      "replica_factor=3, vid_type=INT64)").error is None
    c.reconcile_storage()
    for q in ("USE rp", "CREATE TAG Person(age int)",
              "INSERT VERTEX Person(age) VALUES 1:(11), 2:(22), 3:(33)"):
        r = cl.execute(q)
        assert r.error is None, (q, r.error)
    yield c, cl
    c.stop()


def test_follower_reads_serve_and_count(rcluster):
    c, cl = rcluster
    ds = c.graphds[0].store
    before = sum(v for k, v in stats().snapshot().items()
                 if k.startswith("follower_read_total"))
    with use_consistency("follower"):
        tv = ds.get_vertex("rp", 1)
    assert tv == {"Person": {"age": 11}}
    after = sum(v for k, v in stats().snapshot().items()
                if k.startswith("follower_read_total"))
    assert after > before, "follower read did not take the read path"
    # read-your-writes floors recorded from write acks
    assert ds._applied_floor, "write acks did not record applied floors"
    with use_consistency("bounded_stale"):
        tv = ds.get_vertex("rp", 2)
    assert tv == {"Person": {"age": 22}}


def test_bounded_stale_rejects_with_structured_lag(rcluster):
    """A replica over the staleness bound rejects with E_STALE + a
    machine-readable lag hint (bound forced impossible so EVERY
    replica, leader included, must reject)."""
    c, cl = rcluster
    get_config().set_dynamic("read_max_stale_ms", -1.0)
    try:
        addr = c.storage_servers[0].addr
        rc = RpcClient.from_addr(addr, timeout=5.0, retries=0)
        before = stats().snapshot().get("stale_read_rejects", 0)
        with pytest.raises(RpcError, match=r"E_STALE.*lag_ms=\d+"):
            rc.call("storage.get_vertex", space="rp", part=0, vid=1,
                    consistency="bounded_stale")
        rc.close()
        assert stats().snapshot().get("stale_read_rejects", 0) > before
    finally:
        get_config().dynamic_layer.pop("read_max_stale_ms", None)


def test_bounded_stale_min_applied_gate(rcluster):
    """A bounded_stale read whose read-your-writes floor outruns the
    replica's apply must reject (the client walks to a fresher one)."""
    c, cl = rcluster
    addr = c.storage_servers[1].addr
    rc = RpcClient.from_addr(addr, timeout=5.0, retries=0)
    with pytest.raises(RpcError, match="E_STALE"):
        rc.call("storage.get_vertex", space="rp", part=0, vid=1,
                consistency="bounded_stale", min_applied=10 ** 9)
    rc.close()


def test_unknown_consistency_rejected(rcluster):
    c, cl = rcluster
    addr = c.storage_servers[0].addr
    rc = RpcClient.from_addr(addr, timeout=5.0, retries=0)
    with pytest.raises(RpcError, match="unknown consistency"):
        rc.call("storage.get_vertex", space="rp", part=0, vid=1,
                consistency="snapshot")
    rc.close()


def test_follower_reads_through_flag_and_nqgl(rcluster):
    """The read_consistency flag routes whole statements; SHOW QUERIES
    grows a Consistency column."""
    c, cl = rcluster
    get_config().set_dynamic("read_consistency", "follower")
    try:
        r = cl.execute("FETCH PROP ON Person 3 YIELD Person.age AS a")
        assert r.error is None and r.data.rows == [[33]]
    finally:
        get_config().dynamic_layer.pop("read_consistency", None)
    r = cl.execute("SHOW QUERIES")
    assert r.error is None
    assert "Consistency" in r.data.column_names
