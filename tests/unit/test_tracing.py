"""Distributed tracing + Prometheus export + work counters (ISSUE 1).

Covers the acceptance criteria:
  * a GO query through a socket-real LocalCluster produces ONE trace
    whose tree holds graphd-side executor spans, storaged-side spans
    delivered over the RPC envelope, and the device-plane
    put/dispatch/fetch phase spans;
  * GET /metrics is valid Prometheus text (histogram bucket
    monotonicity, label escaping);
  * work counters are deterministic across repeat runs;
  * the metrics_dump scraper works against a live webservice.
"""
import json
import urllib.request

import pytest

from nebula_tpu.utils import trace
from nebula_tpu.utils.stats import (StatsManager, WorkCounters,
                                    current_work, use_work)


# ---- trace primitives -----------------------------------------------------


def test_span_is_noop_without_trace():
    assert trace.current_ctx() is None
    with trace.span("orphan") as rec:
        assert rec is None
    assert trace.wire_context() is None


def test_trace_nesting_and_store():
    store = trace.trace_store()
    with trace.start_trace("t-root", service="svc", tag="x") as tg:
        tid = tg.trace_id
        with trace.span("child-a"):
            with trace.span("grandchild"):
                pass
        with trace.span("child-b", k=1):
            pass
        trace.record_phase("phase", 0.001, eb=4)
    entry = store.get(tid)
    assert entry is not None
    names = {s["name"] for s in entry["spans"]}
    assert names == {"t-root", "child-a", "grandchild", "child-b",
                     "phase"}
    by_name = {s["name"]: s for s in entry["spans"]}
    root = by_name["t-root"]
    assert root["psid"] == "" and root["attrs"]["tag"] == "x"
    assert by_name["child-a"]["psid"] == root["sid"]
    assert by_name["grandchild"]["psid"] == by_name["child-a"]["sid"]
    assert by_name["child-b"]["psid"] == root["sid"]
    assert by_name["phase"]["psid"] == root["sid"]
    tree = trace.render_tree(entry)
    assert tree.splitlines()[0].startswith("t-root")
    assert "    grandchild" in tree
    # after the trace closed, the thread has no context again
    assert trace.current_ctx() is None


def test_trace_ctx_cross_thread_isolated_parents():
    """use_ctx installs a per-thread COPY: concurrent spans share the
    sink but not the parent-slot (scheduler parallel branches)."""
    import threading
    with trace.start_trace("par", service="s") as tg:
        snap = trace.current_ctx()
        root_sid = snap.sid
        done = []

        def worker(i):
            with trace.use_ctx(snap):
                with trace.span(f"w{i}"):
                    pass
            done.append(i)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(done) == 4
    entry = trace.trace_store().get(tg.trace_id)
    workers = [s for s in entry["spans"] if s["name"].startswith("w")]
    assert len(workers) == 4
    assert all(s["psid"] == root_sid for s in workers)


def test_trace_store_bounded():
    store = trace.TraceStore(capacity=3)
    for i in range(10):
        store.add(f"t{i}", f"n{i}", [])
    assert len(store.list(limit=50)) == 3
    assert store.get("t0") is None and store.get("t9") is not None


# ---- work counters --------------------------------------------------------


def test_work_counters_thread_local_and_dict():
    assert current_work() is None
    wc = WorkCounters()
    with use_work(wc):
        assert current_work() is wc
        current_work().add("edges_traversed", 5)
        current_work().add_rpc(100, 200)
        current_work().extend_frontier([1, 4])
    assert current_work() is None
    d = wc.as_dict()
    assert d == {"edges_traversed": 5, "frontier_sizes": [1, 4],
                 "rpc_calls": 1, "wire_bytes_sent": 100,
                 "wire_bytes_recv": 200, "device_dispatches": 0,
                 "storage_rows": 0}


def test_engine_query_attaches_work_and_trace():
    """Every statement produces a trace; SHOW TRACES lists it; device
    work counters land on the statement's ExecutionContext."""
    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime

    eng = QueryEngine(tpu_runtime=TpuRuntime(make_mesh()))
    s = eng.new_session()
    for q in ["CREATE SPACE wk(partition_num=8, vid_type=INT64)",
              "USE wk", "CREATE EDGE e(w int)",
              "INSERT EDGE e(w) VALUES 1->2:(1), 2->3:(2), 1->3:(3)"]:
        r = eng.execute(s, q)
        assert r.error is None, f"{q} -> {r.error}"
    r = eng.execute(s, "GO 2 STEPS FROM 1 OVER e YIELD dst(edge) AS d")
    assert r.error is None
    r = eng.execute(s, "SHOW TRACES")
    assert r.error is None
    names = [row[1] for row in r.data.rows]
    assert "query:Go" in names
    tid = next(row[0] for row in r.data.rows if row[1] == "query:Go")
    entry = trace.trace_store().get(tid)
    span_names = {sp["name"] for sp in entry["spans"]}
    assert any(n.startswith("exec:") for n in span_names)
    # device phases present when the GO fused onto the device plane
    assert {"device:put", "device:dispatch", "device:fetch"} <= span_names


def test_device_work_counters_deterministic():
    """Two identical post-warmup runs produce byte-identical work
    counters (the bench regression signal)."""
    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime

    rt = TpuRuntime(make_mesh())
    eng = QueryEngine(tpu_runtime=rt)
    s = eng.new_session()
    for q in ["CREATE SPACE dwk(partition_num=8, vid_type=INT64)",
              "USE dwk", "CREATE EDGE e(w int)",
              "INSERT EDGE e(w) VALUES 1->2:(1), 2->3:(2), 1->3:(3), "
              "3->4:(4), 2->4:(5)"]:
        assert eng.execute(s, q).error is None

    def probe():
        wc = WorkCounters()
        with use_work(wc):
            rows, st = rt.traverse(eng.store, "dwk", [1], ["e"], "out", 2)
        return wc.as_dict()

    probe()                      # warmup: escalation settles buckets
    w1, w2 = probe(), probe()
    assert json.dumps(w1) == json.dumps(w2)
    assert w1["edges_traversed"] > 0
    assert w1["frontier_sizes"][0] == 1      # the single seed
    assert w1["device_dispatches"] >= 1


# ---- Prometheus exposition ------------------------------------------------


def test_prometheus_histogram_monotone_and_escaping():
    sm = StatsManager()
    sm.inc("plain_total", 3)
    sm.inc_labeled("ops_total", {"op": 'quo"te\\back\nline'}, 2)
    sm.gauge("hbm_bytes", 12.5)
    for v in (50, 700, 700, 99_000, 2_000_000_000):
        sm.observe("lat_us", v, {"op": "go"})
    text = sm.to_prometheus()
    lines = text.splitlines()
    assert "# TYPE plain_total counter" in lines
    assert "plain_total 3" in lines
    # label escaping per the exposition format
    assert 'ops_total{op="quo\\"te\\\\back\\nline"} 2' in lines
    assert "hbm_bytes 12.5" in lines
    # histogram: cumulative buckets ending at +Inf == count
    buckets = [ln for ln in lines if ln.startswith("lat_us_bucket")]
    vals = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert vals == sorted(vals), "bucket counts must be cumulative"
    assert 'le="+Inf"' in buckets[-1]
    assert vals[-1] == 5
    assert 'lat_us_count{op="go"} 5' in lines
    # the 2e9 observation only lands in +Inf
    assert vals[-1] - vals[-2] == 1


def test_metrics_endpoint_serves_prometheus():
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.utils.stats import stats

    stats().observe("ws_scrape_lat_us", 1234, {"op": "x"})
    stats().inc("ws_scrape_counter", 9)
    ws = WebService(role="graphd")
    ws.start()
    try:
        body = urllib.request.urlopen(
            f"http://{ws.addr}/metrics").read().decode()
        assert "# TYPE ws_scrape_counter counter" in body
        assert "ws_scrape_counter 9" in body
        assert 'ws_scrape_lat_us_bucket{op="x",le="5000"} 1' in body
        assert 'ws_scrape_lat_us_bucket{op="x",le="+Inf"} 1' in body
    finally:
        ws.stop()


# ---- the cluster acceptance test -----------------------------------------


@pytest.fixture(scope="module")
def traced_cluster(tmp_path_factory):
    """LocalCluster with a device runtime + one GO query already run."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime

    rt = TpuRuntime(make_mesh())
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path_factory.mktemp("traced")),
                     tpu_runtime=rt)
    try:
        cl = c.client()
        r = cl.execute("CREATE SPACE tr(partition_num=8, "
                       "vid_type=INT64)")
        assert r.error is None, r.error
        c.reconcile_storage()
        for q in ["USE tr", "CREATE TAG P(a int)", "CREATE EDGE E(w int)",
                  "INSERT VERTEX P(a) VALUES 1:(1), 2:(2), 3:(3)",
                  "INSERT EDGE E(w) VALUES 1->2:(5), 2->3:(7)"]:
            r = cl.execute(q)
            assert r.error is None, f"{q} -> {r.error}"
        r = cl.execute("GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d")
        assert r.error is None, r.error
        assert sorted(x[0] for x in r.data.rows) == [3]
        yield c, cl
    finally:
        c.stop()


def _go_trace_entry():
    for t in trace.trace_store().list():
        if t["name"] == "query:Go":
            return trace.trace_store().get(t["tid"])
    raise AssertionError("no query:Go trace recorded")


def test_cluster_trace_stitches_services_and_device(traced_cluster):
    """ONE trace id covers graphd executors, storaged spans delivered
    over the RPC envelope, and the device put/dispatch/fetch phases."""
    entry = _go_trace_entry()
    spans = entry["spans"]
    # single trace: every span carries the same tid
    assert {s["tid"] for s in spans} == {entry["tid"]}
    names = {s["name"] for s in spans}
    # graphd-side executor spans
    assert any(n.startswith("exec:") for n in names)
    # storaged-side spans, shipped back over the RPC envelope
    remote_storaged = [s for s in spans
                      if s.get("svc") == "storaged" and s.get("remote")]
    assert remote_storaged, "no storaged span came back in a reply"
    # the remote span's parent chain reaches this trace's spans
    by_id = {s["sid"]: s for s in spans}
    assert any(s["psid"] in by_id for s in remote_storaged), \
        "remote spans are not stitched into the tree"
    # device-plane phase spans (the GO fused to TpuTraverse)
    assert {"device:put", "device:dispatch", "device:fetch"} <= names, \
        sorted(names)
    # the rendered tree nests a storaged span under a graphd rpc span
    tree = trace.render_tree(entry)
    assert "rpc.server:storage.get_neighbors (storaged [remote])" \
        in tree or "storaged" in tree


def test_cluster_insert_trace_has_raft_span(traced_cluster):
    """Write path: the storaged-side raft propose span rides back too
    (group commit renamed it raft:propose_batch; the `entries` attr
    carries the batch size)."""
    for t in trace.trace_store().list():
        if t["name"] in ("query:Insert", "query:InsertEdge",
                         "query:InsertVertex", "query:InsertEdges",
                         "query:InsertVertices"):
            entry = trace.trace_store().get(t["tid"])
            for s in entry["spans"]:
                if s["name"] == "raft:propose_batch":
                    assert s.get("attrs", {}).get("entries", 0) >= 1
                    return
    raise AssertionError(
        "no insert trace carries a raft:propose_batch span")


def test_traces_endpoint_and_metrics_dump(traced_cluster, capsys):
    """GET /traces serves the stitched trace; the metrics_dump scraper
    renders it and the /metrics text from a live webservice."""
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump

    ws = WebService(role="graphd")
    ws.start()
    try:
        listing = json.loads(urllib.request.urlopen(
            f"http://{ws.addr}/traces").read())
        go = next(t for t in listing if t["name"] == "query:Go")
        full = json.loads(urllib.request.urlopen(
            f"http://{ws.addr}/traces?id={go['tid']}").read())
        assert full["tid"] == go["tid"] and full["spans"]
        txt = urllib.request.urlopen(
            f"http://{ws.addr}/traces?id={go['tid']}&format=text"
        ).read().decode()
        assert txt.startswith("query:Go")
        # the scraper CLI against the same endpoint
        assert metrics_dump.main(["--addr", ws.addr, "--traces"]) == 0
        assert go["tid"] in capsys.readouterr().out
        assert metrics_dump.main(
            ["--addr", ws.addr, "--trace", go["tid"]]) == 0
        assert "query:Go" in capsys.readouterr().out
        assert metrics_dump.main(
            ["--addr", ws.addr, "--grep", "num_queries"]) == 0
        assert "num_queries" in capsys.readouterr().out
    finally:
        ws.stop()


def test_cluster_query_work_counters(traced_cluster):
    """Cluster host-path work counters: RPC calls and wire bytes are
    counted and deterministic across identical repeat queries."""
    c, cl = traced_cluster
    eng = c.graphds[0].engine
    sess = eng.new_session()
    from nebula_tpu.utils.config import get_config
    get_config().set_dynamic("tpu_enable", False)   # force host path
    # tracing off: span payloads in RPC replies carry timing digits,
    # which would make wire-byte counts vary run-to-run (this is the
    # documented regression-probe mode; docs/OBSERVABILITY.md)
    get_config().set_dynamic("enable_query_tracing", False)
    try:
        def probe():
            wc = WorkCounters()
            with use_work(wc):
                r = eng.execute(sess, "USE tr")
                assert r.error is None
                r = eng.execute(sess,
                                "GO 2 STEPS FROM 1 OVER E "
                                "YIELD dst(edge) AS d")
                assert r.error is None, r.error
            return wc.as_dict()

        w1, w2 = probe(), probe()
    finally:
        get_config().dynamic_layer.pop("tpu_enable", None)
        get_config().dynamic_layer.pop("enable_query_tracing", None)
    assert w1["rpc_calls"] > 0 and w1["wire_bytes_sent"] > 0
    assert w1["edges_traversed"] >= 2      # 1->2, 2->3
    assert json.dumps(w1) == json.dumps(w2)
