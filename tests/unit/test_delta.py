"""Device-resident delta-CSR (ISSUE 19): merged base+delta traversal
vs full-rebuild vs the host oracle at every fill level, across
insert/delete/tombstone-resurrect interleavings and 1/2/4-part meshes;
compaction swap under concurrent traversal; KILL-during-compaction;
the `tpu_delta_max_edges=0` off switch; the group-commit ack →
read-your-writes floor; and the batch-former gate re-arm."""
import random
import threading
import time

import numpy as np
import pytest

from nebula_tpu.core.value import NULL
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.schema import PropDef, PropType
from nebula_tpu.graphstore.store import GraphStore
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import stats

tpu = pytest.importorskip("nebula_tpu.tpu")
from nebula_tpu.tpu import TpuRuntime, make_mesh           # noqa: E402

from test_tpu import norm_edge                             # noqa: E402

DELTA_KEYS = ("tpu_delta_max_edges", "tpu_delta_compact_watermark",
              "tpu_delta_vmax_slack")


@pytest.fixture()
def delta_cfg():
    """Delta plane ON with compaction parked (watermark 2.0 — tests
    that want compaction lower it themselves); restores every flag."""
    fail.reset()
    get_config().set_dynamic_many({"tpu_delta_max_edges": 64,
                                   "tpu_delta_compact_watermark": 2.0})
    yield get_config()
    fail.reset()
    cfg = get_config()
    with cfg.lock:
        for k in DELTA_KEYS:
            cfg.dynamic_layer.pop(k, None)


def store_p(parts, seed=3, n=90, avg_deg=4, spacename="g"):
    rng = random.Random(seed)
    st = GraphStore()
    st.create_space(spacename, partition_num=parts, vid_type="INT64")
    st.catalog.create_tag(spacename, "person", [
        PropDef("age", PropType.INT64), PropDef("name", PropType.STRING)])
    st.catalog.create_edge(spacename, "knows", [
        PropDef("w", PropType.INT64), PropDef("f", PropType.DOUBLE),
        PropDef("tag", PropType.STRING)])
    names = ["ann", "bob", "cid", "dee"]
    for v in range(n):
        st.insert_vertex(spacename, v, "person",
                         {"age": rng.randint(0, 80),
                          "name": rng.choice(names)})
    for v in range(n):
        for _ in range(rng.randint(0, avg_deg * 2)):
            props = {"w": rng.randint(-5, 100) if rng.random() > .1
                     else NULL,
                     "f": rng.uniform(0, 1), "tag": rng.choice(names)}
            st.insert_edge(spacename, v, "knows", rng.randrange(n),
                           rng.randint(0, 2), props)
    return st


def host_rows(st, space, vids, steps=2, direction="out"):
    """Numpy/host oracle: the engine's pure-host GO rows."""
    eng = QueryEngine(st)
    s = eng.new_session()
    eng.execute(s, f"USE {space}")
    q = (f"GO {steps} STEPS FROM {', '.join(map(str, vids))} OVER knows"
         + (" REVERSELY" if direction == "in" else
            " BIDIRECT" if direction == "both" else "")
         + " YIELD src(edge), type(edge), rank(edge), dst(edge)")
    rs = eng.execute(s, q)
    assert rs.error is None, f"{q} -> {rs.error}"
    return sorted(map(repr, rs.data.rows))


def dev_rows(rt, st, vids, steps=2, direction="out"):
    rows, _ = rt.traverse(st, "g", list(vids), ["knows"], direction,
                          steps)
    return sorted(norm_edge(e) for (_, e, _) in rows)


def rebuild_rows(parts, st, vids, steps=2, direction="out"):
    """Full-rebuild oracle: a FRESH runtime with the delta off pins a
    from-scratch snapshot of the current store state."""
    cfg = get_config()
    with cfg.lock:
        saved = cfg.dynamic_layer.get("tpu_delta_max_edges")
    cfg.set_dynamic("tpu_delta_max_edges", 0)
    try:
        rt = TpuRuntime(make_mesh(parts))
        return dev_rows(rt, st, vids, steps, direction)
    finally:
        cfg.set_dynamic("tpu_delta_max_edges",
                        saved if saved is not None else 0)


def three_way(rt, st, parts, vids, tag, steps=2, direction="out"):
    got = dev_rows(rt, st, vids, steps, direction)
    want_rebuild = rebuild_rows(parts, st, vids, steps, direction)
    want_host = host_rows(st, "g", vids, steps, direction)
    assert got == want_rebuild, \
        f"[{tag}] merged kernel != full rebuild ({len(got)} vs " \
        f"{len(want_rebuild)} rows)"
    assert got == want_host, f"[{tag}] merged kernel != host oracle"
    return got


def live_edges(st, limit=None):
    out = [(s, r, d) for (s, _et, r, d, _p) in st.scan_edges("g", "knows")]
    return out if limit is None else out[:limit]


# -- parity across interleavings, fill levels, mesh widths ------------------


@pytest.mark.parametrize("parts", [1, 2, 4])
def test_interleaved_writes_parity(delta_cfg, parts):
    """Insert / delete / tombstone-resurrect interleavings on a P-part
    mesh: the merged kernel's rows equal a full rebuild AND the host
    oracle after every phase, without a single re-pin."""
    st = store_p(parts, seed=20 + parts)
    rt = TpuRuntime(make_mesh(parts))
    seeds = [1, 5, 9]
    dev = rt.pin(st, "g")
    assert dev.delta is not None, "delta plane not armed"
    three_way(rt, st, parts, seeds, "empty fill")      # fill level 0

    # phase 1: fresh inserts (new rank space so they never collide)
    for i in range(12):
        st.insert_edge("g", seeds[i % 3], "knows", (7 * i) % 90, 50 + i,
                       {"w": 1000 + i, "f": .5, "tag": "zz"})
    three_way(rt, st, parts, seeds, "inserts")
    assert rt.pin(st, "g") is dev, "insert burst forced a re-pin"

    # phase 2: delete a mix of base edges and fresh delta edges
    for s, r, d in live_edges(st, 8):
        st.delete_edge("g", s, "knows", d, r)
    st.delete_edge("g", seeds[0], "knows", 0, 50)      # delta-resident
    three_way(rt, st, parts, seeds, "deletes")

    # phase 3: tombstone-resurrect — identical re-insert unmasks the
    # base row; changed re-insert overrides it
    resurrect = live_edges(st, 2)
    for s, r, d in resurrect:
        st.delete_edge("g", s, "knows", d, r)
    for s, r, d in resurrect:
        st.insert_edge("g", s, "knows", d, r,
                       {"w": 77, "f": .25, "tag": "rz"})
    three_way(rt, st, parts, seeds, "resurrect")

    # phase 4: endpoints with no prior vertex row + a brand-new vertex
    st.insert_vertex("g", 5000, "person", {"age": 1, "name": "new"})
    st.insert_edge("g", seeds[0], "knows", 5000, 0,
                   {"w": 5, "f": .1, "tag": "nv"})
    st.insert_edge("g", 5000, "knows", seeds[1], 0,
                   {"w": 6, "f": .2, "tag": "nv"})
    three_way(rt, st, parts, [seeds[0], 5000], "new vertex")

    # phase 5: vertex tag update rides the delta too
    st.update_vertex("g", seeds[1], "person", {"age": 99})
    assert rt.pin(st, "g") is dev
    three_way(rt, st, parts, seeds, "tag update")

    three_way(rt, st, parts, seeds, "reverse", direction="in")
    three_way(rt, st, parts, seeds, "bidirect", direction="both")
    assert rt.pin(st, "g") is dev, \
        "the whole interleaving should ride one pinned snapshot"
    assert stats().snapshot().get("tpu_repin_avoided", 0) > 0


def test_full_fill_and_overflow_fall_back(delta_cfg):
    """Fill one (block, part) row to the padded cap — parity holds at
    fill_ratio 1.0 — then overflow it: the runtime falls back to a
    full rebuild (fresh snapshot object) and rows stay correct."""
    get_config().set_dynamic("tpu_delta_max_edges", 8)
    st = store_p(1, seed=31, n=40, avg_deg=2)
    rt = TpuRuntime(make_mesh(1))
    dev = rt.pin(st, "g")
    dcap = dev.delta.host.dcap
    for i in range(dcap):
        st.insert_edge("g", 1, "knows", (i * 3) % 40, 60 + i,
                       {"w": i, "f": .5, "tag": "x"})
    three_way(rt, st, 1, [1], "full fill")
    assert rt.pin(st, "g") is dev
    assert dev.delta.host.fill_ratio() == 1.0
    # one more insert into the same (block, part): DeltaOverflow →
    # rebuild path (new snapshot, delta drained into the base)
    st.insert_edge("g", 1, "knows", 39, 999, {"w": -1, "f": 0, "tag": "o"})
    dev2 = rt.pin(st, "g")
    assert dev2 is not dev, "overflow must force a full rebuild"
    assert dev2.delta is not None and \
        dev2.delta.host.total_edges() == 0, "rebuild drains the delta"
    three_way(rt, st, 1, [1], "post overflow")


def test_off_switch_is_byte_identical(delta_cfg):
    """`tpu_delta_max_edges=0`: no delta plane is armed, every epoch
    bump re-pins (the pre-delta behavior), and rows match the delta-on
    runtime exactly."""
    get_config().set_dynamic("tpu_delta_max_edges", 0)
    st = store_p(2, seed=40)
    rt = TpuRuntime(make_mesh(2))
    dev = rt.pin(st, "g")
    assert dev.delta is None
    st.insert_edge("g", 1, "knows", 2, 77, {"w": 1, "f": .1, "tag": "t"})
    dev2 = rt.pin(st, "g")
    assert dev2 is not dev, "off switch must re-pin on every write"
    assert dev2.delta is None
    # steps=1 so the fresh edge is IN the row set (GO N STEPS yields
    # only the edges at step N)
    off = dev_rows(rt, st, [1, 5, 9], steps=1)
    get_config().set_dynamic("tpu_delta_max_edges", 64)
    rt_on = TpuRuntime(make_mesh(2))
    rt_on.pin(st, "g")
    st.insert_edge("g", 1, "knows", 3, 78, {"w": 2, "f": .2, "tag": "t"})
    on = dev_rows(rt_on, st, [1, 5, 9], steps=1)
    get_config().set_dynamic("tpu_delta_max_edges", 0)
    off2 = dev_rows(rt, st, [1, 5, 9], steps=1)
    assert on == off2 and off != on  # the new edge is visible both ways
    assert host_rows(st, "g", [1, 5, 9], steps=1) == on


# -- compaction -------------------------------------------------------------


def wait_for(pred, timeout=20.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    pytest.fail(f"timed out waiting for {msg}")


def test_compaction_swap_under_concurrent_traversal(delta_cfg):
    """Past the watermark the background job rebuilds the base OFF the
    gate and swaps it under a short hold while traversals keep
    running; afterwards the new snapshot serves a drained delta and
    parity holds."""
    get_config().set_dynamic_many({"tpu_delta_max_edges": 8,
                                   "tpu_delta_compact_watermark": 0.5})
    st = store_p(2, seed=50)
    rt = TpuRuntime(make_mesh(2))
    dev = rt.pin(st, "g")
    c0 = stats().snapshot().get("tpu_compactions", 0)

    stop = threading.Event()
    errs = []

    from nebula_tpu.tpu.device import TpuUnavailable

    def churn():
        while not stop.is_set():
            try:
                rows, _ = rt.traverse(st, "g", [1, 5], ["knows"],
                                      "out", 2)
            except TpuUnavailable:
                # the swap retired our snapshot mid-flight — the
                # engine-level contract is "caller re-pins / falls
                # back"; the next loop iteration re-pins
                continue
            except Exception as ex:  # noqa: BLE001
                errs.append(repr(ex))
                return

    ths = [threading.Thread(target=churn, daemon=True) for _ in range(2)]
    for t in ths:
        t.start()
    try:
        for i in range(6):      # past 0.5 * dcap(8) in one part row
            st.insert_edge("g", 1, "knows", (i * 7) % 90, 60 + i,
                           {"w": i, "f": .5, "tag": "c"})
        rt.pin(st, "g")          # apply → watermark check → kick job
        wait_for(lambda: stats().snapshot().get("tpu_compactions", 0)
                 > c0, msg="background compaction")
    finally:
        stop.set()
        for t in ths:
            t.join(30)
    assert not errs, errs[:3]
    new = rt.snapshots["g"]
    assert new is not dev, "compaction must swap in a fresh base"
    assert new.delta is not None and new.delta.host.total_edges() == 0, \
        "compaction folds the delta into the base"
    three_way(rt, st, 2, [1, 5], "post compaction")
    # the swap re-armed the watch: writes keep riding the delta
    st.insert_edge("g", 5, "knows", 9, 61, {"w": 7, "f": .7, "tag": "c"})
    assert rt.pin(st, "g") is new


def test_kill_during_compaction_aborts_cleanly(delta_cfg):
    """The `tpu:compact_swap` failpoint fires between the off-gate
    build and the swap: the job aborts, the serving snapshot and its
    delta stay intact, reads stay correct, and the NEXT compaction
    (failpoint disarmed) succeeds."""
    get_config().set_dynamic_many({"tpu_delta_max_edges": 8,
                                   "tpu_delta_compact_watermark": 0.5})
    st = store_p(1, seed=60, n=50)
    rt = TpuRuntime(make_mesh(1))
    dev = rt.pin(st, "g")
    c0 = stats().snapshot().get("tpu_compactions", 0)
    fail.arm("tpu:compact_swap", "raise")
    for i in range(6):
        st.insert_edge("g", 1, "knows", (i * 3) % 50, 70 + i,
                       {"w": i, "f": .5, "tag": "k"})
    rt.pin(st, "g")
    wait_for(lambda: not getattr(dev, "_compacting", False),
             msg="aborted compaction thread")
    assert stats().snapshot().get("tpu_compactions", 0) == c0, \
        "killed compaction must not count as one"
    assert rt.snapshots["g"] is dev, "killed compaction must not swap"
    assert dev.delta.host.total_edges() > 0, \
        "killed compaction must leave the delta intact"
    three_way(rt, st, 1, [1], "after killed compaction")
    # disarm and write again: the retry compacts for real
    fail.reset()
    st.insert_edge("g", 1, "knows", 2, 90, {"w": 1, "f": .1, "tag": "k"})
    rt.pin(st, "g")
    wait_for(lambda: stats().snapshot().get("tpu_compactions", 0) > c0,
             msg="retry compaction")
    wait_for(lambda: rt.snapshots["g"] is not dev, msg="swap")
    three_way(rt, st, 1, [1], "after retry compaction")


# -- freshness: the group-commit ack path -----------------------------------


def test_read_your_writes_through_engine(delta_cfg):
    """Engine-level INSERT → GO on the device plane: the ack'd write is
    visible to the next statement via the delta fast path (no re-pin),
    holding the PR 9 read-your-writes floor."""
    st = store_p(2, seed=70)
    rt = TpuRuntime(make_mesh(2))
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    assert eng.execute(s, "USE g").error is None
    rs = eng.execute(s, "GO FROM 1 OVER knows YIELD dst(edge) AS d")
    assert rs.error is None
    dev = rt.snapshots["g"]
    assert dev.delta is not None
    r0 = stats().snapshot().get("tpu_repin_avoided", 0)
    assert eng.execute(
        s, 'INSERT EDGE knows(w, f, tag) VALUES 1->77@55:(9, 0.5, "x")'
    ).error is None
    rs = eng.execute(s, "GO FROM 1 OVER knows YIELD dst(edge) AS d")
    assert rs.error is None
    assert [77] == sorted(x[0] for x in rs.data.rows
                          if x[0] == 77), "ack'd write not visible"
    assert rt.snapshots["g"] is dev, "fresh read must not re-pin"
    assert stats().snapshot().get("tpu_repin_avoided", 0) > r0
    # gauges follow the plane
    snap = stats().snapshot()
    assert snap.get("tpu_delta_edges", 0) >= 1
    assert snap.get("tpu_delta_bytes", 0) > 0


# -- cluster feed -----------------------------------------------------------


@pytest.mark.slow
def test_cluster_delta_fast_path(tmp_path, delta_cfg):
    """DistributedStore feeds the delta: a write through the graphd's
    own store rides the dirty-key log (census-covered) into the pinned
    snapshot without a re-export; rows match the host path."""
    from nebula_tpu.cluster.launcher import LocalCluster

    rt = TpuRuntime(make_mesh())
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path), tpu_runtime=rt)
    try:
        cl = c.client()
        r = cl.execute("CREATE SPACE dd(partition_num=8, "
                       "replica_factor=1, vid_type=INT64)")
        assert r.error is None, r.error
        c.reconcile_storage()
        for q in ["USE dd", "CREATE TAG T()", "CREATE EDGE E(w int)",
                  "INSERT VERTEX T() VALUES 1:(), 2:(), 3:(), 4:()",
                  "INSERT EDGE E(w) VALUES 1->2:(1), 2->3:(2)"]:
            assert cl.execute(q).error is None, q
        r = cl.execute("GO FROM 1 OVER E YIELD dst(edge) AS d")
        assert r.error is None
        assert sorted(x[0] for x in r.data.rows) == [2]
        dev = rt.snapshots.get("dd")
        assert dev is not None and dev.delta is not None, \
            "cluster pin did not arm the delta plane"
        r0 = stats().snapshot().get("tpu_repin_avoided", 0)
        assert cl.execute("INSERT EDGE E(w) VALUES 1->3:(3), 1->4:(4)"
                          ).error is None
        r = cl.execute("GO FROM 1 OVER E YIELD dst(edge) AS d")
        assert r.error is None
        assert sorted(x[0] for x in r.data.rows) == [2, 3, 4]
        assert rt.snapshots["dd"] is dev, \
            "cluster write should ride the delta, not re-export"
        assert stats().snapshot().get("tpu_repin_avoided", 0) > r0
        # delete through the cluster write path → tombstone
        assert cl.execute("DELETE EDGE E 1->2@0").error is None
        r = cl.execute("GO FROM 1 OVER E YIELD dst(edge) AS d")
        assert r.error is None
        assert sorted(x[0] for x in r.data.rows) == [3, 4]
        assert rt.snapshots["dd"] is dev
    finally:
        c.stop()
