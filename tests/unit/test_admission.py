"""Admission control + overload survival (ISSUE 10): bounded slots,
DWRR fairness, deadline/kill eviction of queued statements, structured
E_OVERLOAD shedding with retry-after, the bounded RPC-server inbox,
client-side overload retry inside the deadline budget, the dispatch-
queue cap, and runtime-updatable admission flags (atomic multi-key
UPDATE CONFIGS draining a waiting queue without restart)."""
import threading
import time

import pytest

from nebula_tpu.cluster.rpc import (RpcClient, RpcError, RpcServer,
                                    reset_breakers)
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils import cancel as _cancel
from nebula_tpu.utils.admission import (admission, is_overload,
                                        overload_error,
                                        parse_retry_after)
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.flight import flight_recorder
from nebula_tpu.utils.stats import stats

_ADMISSION_FLAGS = (
    "max_running_queries", "admission_queue_capacity",
    "admission_memory_watermark_bytes", "admission_session_weights",
    "rpc_server_inbox_capacity", "tpu_dispatch_queue_cap",
    "query_timeout_secs",
)


@pytest.fixture()
def clean():
    fail.reset()
    reset_breakers()
    admission().reset()
    yield
    fail.reset()
    reset_breakers()
    admission().reset()
    for k in _ADMISSION_FLAGS:
        get_config().dynamic_layer.pop(k, None)


def _delay_nodes(kind, secs):
    """Delay only plan nodes of `kind` (YIELD plans carry Project;
    SHOW / KILL / UPDATE CONFIGS statements don't), so control
    statements run undelayed."""
    fail.arm_callable(
        "exec:node",
        lambda i, key: ("delay", secs) if key == kind else None)


def _run_async(eng, sess, stmt):
    box = {}

    def run():
        box["rs"] = eng.execute(sess, stmt)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, box


def _wait_for(pred, timeout=5.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        got = pred()
        if got:
            return got
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {msg}")


def _counter(name) -> float:
    return stats().snapshot().get(name, 0)


# -- disabled sentinel ------------------------------------------------------


def test_disabled_sentinel_is_noop(clean):
    """max_running_queries=0 (the default): no ticket is taken, nothing
    queues, nothing sheds — today's behavior."""
    eng = QueryEngine()
    s = eng.new_session()
    before = _counter("admission_enqueued"), _counter("admission_shed")
    for _ in range(5):
        assert eng.execute(s, "YIELD 1 AS x").ok
    snap = admission().snapshot()
    assert snap["running"] == 0 and snap["queued"] == 0
    assert (_counter("admission_enqueued"),
            _counter("admission_shed")) == before


# -- queueing + shedding (engine level) -------------------------------------


def test_queueing_drains_in_bounded_slots(clean):
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 10)
    eng = QueryEngine()
    enq0 = _counter("admission_enqueued")
    _delay_nodes("Project", 0.15)
    runs = [_run_async(eng, eng.new_session(), f"YIELD {i} AS x")
            for i in range(3)]
    for t, box in runs:
        t.join(10)
        assert box["rs"].error is None, box["rs"].error
    assert _counter("admission_enqueued") - enq0 >= 2


def test_shed_is_structured_and_flight_captured(clean):
    """Queue capacity 0: the second statement sheds immediately with a
    parseable retry-after, a forced flight-recorder entry (status
    `shed`), and the control lane (SHOW QUERIES) still answers."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 0)
    eng = QueryEngine()
    s1, s2 = eng.new_session(), eng.new_session()
    _delay_nodes("Project", 0.4)
    t, box = _run_async(eng, s1, "YIELD 1 AS x")
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="slot holder running")
    shed0 = _counter("admission_shed")
    rs = eng.execute(s2, "YIELD 2 AS x")
    assert rs.error is not None and is_overload(rs.error), rs.error
    assert parse_retry_after(rs.error) is not None, rs.error
    assert _counter("admission_shed") - shed0 == 1
    # forced flight capture under status `shed`
    ent = next(e for e in flight_recorder().list(limit=10)
               if e["stmt"] == "YIELD 2 AS x")
    assert ent["status"] == "shed"
    # control lane: SHOW QUERIES bypasses the full queue
    rs = eng.execute(s2, "SHOW QUERIES")
    assert rs.error is None, rs.error
    t.join(10)
    assert box["rs"].error is None


def test_queued_statement_visible_in_show_queries(clean):
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 5)
    eng = QueryEngine()
    s1, s2 = eng.new_session(), eng.new_session()
    _delay_nodes("Project", 0.5)
    t1, b1 = _run_async(eng, s1, "YIELD 1 AS x")
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="holder running")
    t2, b2 = _run_async(eng, s2, "YIELD 2 AS x")
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[4] == "QUEUED"), None),
        msg="QUEUED row in SHOW QUERIES")
    assert row[3] == "YIELD 2 AS x"
    t1.join(10)
    t2.join(10)
    assert b1["rs"].ok and b2["rs"].ok
    # the admission wait fed the statement's queue_us accounting
    assert stats().snapshot().get("admission_queue_wait_us.count", 0) >= 1


# -- eviction of queued statements ------------------------------------------


def test_kill_query_removes_queued_statement(clean):
    """ISSUE 10 satellite: KILL QUERY of a still-QUEUED statement
    removes it from the admission queue immediately — clean killed
    error, slot never consumed."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 5)
    eng = QueryEngine()
    s1, s2, sc = eng.new_session(), eng.new_session(), eng.new_session()
    _delay_nodes("Project", 0.8)
    t1, b1 = _run_async(eng, s1, "YIELD 1 AS x")
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="holder running")
    t2, b2 = _run_async(eng, s2, "YIELD 2 AS x")
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[4] == "QUEUED"), None),
        msg="QUEUED victim")
    qid = row[1]
    ev0 = _counter("admission_kill_evictions")
    t_kill = time.monotonic()
    rs = eng.execute(sc, f"KILL QUERY (session={s2.id}, plan={qid})")
    assert rs.error is None, rs.error
    t2.join(5)
    assert time.monotonic() - t_kill < 2.0, \
        "queued kill must land immediately, not wait for a slot"
    assert b2["rs"].error == "ExecutionError: query was killed"
    assert _counter("admission_kill_evictions") - ev0 == 1
    snap = admission().snapshot()
    assert snap["queued"] == 0
    assert snap["running"] == 1, "victim must never have taken a slot"
    t1.join(10)
    assert b1["rs"].ok


def test_kill_session_evicts_queued_statement(clean):
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 5)
    eng = QueryEngine()
    s1, s2 = eng.new_session(), eng.new_session()
    _delay_nodes("Project", 0.8)
    t1, b1 = _run_async(eng, s1, "YIELD 1 AS x")
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="holder running")
    t2, b2 = _run_async(eng, s2, "YIELD 2 AS x")
    _wait_for(lambda: admission().snapshot()["queued"] == 1,
              msg="victim queued")
    assert eng.kill_session(s2.id)
    t2.join(5)
    assert b2["rs"].error == "ExecutionError: query was killed"
    assert admission().snapshot()["running"] == 1
    t1.join(10)
    assert b1["rs"].ok


def test_deadline_expired_queued_statement_never_takes_slot(clean):
    """Acceptance: a statement whose budget expires while QUEUED is
    rejected with DeadlineExceeded (→ E_QUERY_TIMEOUT) without ever
    consuming a concurrency slot."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 5)
    ctl = admission()
    holder = ctl.acquire(qid=9001, session=1, kind="Go")
    assert holder is not None and holder.mode == "admitted"
    ev0 = _counter("admission_deadline_evictions")
    box = {}

    def waiter():
        try:
            with _cancel.use_cancel(
                    deadline=time.monotonic() + 0.2):
                ctl.acquire(qid=9002, session=2, kind="Go")
            box["err"] = None
        except _cancel.DeadlineExceeded as ex:
            box["err"] = ex

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    t.join(5)
    assert isinstance(box["err"], _cancel.DeadlineExceeded)
    assert _counter("admission_deadline_evictions") - ev0 == 1
    snap = ctl.snapshot()
    assert snap["running"] == 1 and snap["queued"] == 0
    holder.release()


def test_engine_deadline_in_queue_reports_query_timeout(clean):
    """End-to-end: the queued statement surfaces E_QUERY_TIMEOUT at the
    engine boundary, same as any other budget exhaustion."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 5)
    cfg.set_dynamic("query_timeout_secs", 0.25)
    eng = QueryEngine()
    s1, s2 = eng.new_session(), eng.new_session()
    _delay_nodes("Project", 0.6)
    t1, b1 = _run_async(eng, s1, "YIELD 1 AS x")
    _wait_for(lambda: admission().snapshot()["running"] == 1,
              msg="holder running")
    t2, b2 = _run_async(eng, s2, "YIELD 2 AS x")
    t2.join(5)
    assert b2["rs"].error is not None \
        and b2["rs"].error.startswith("E_QUERY_TIMEOUT"), b2["rs"].error
    t1.join(10)


# -- runtime-updatable flags (satellite) ------------------------------------


def test_capacity_bump_drains_queue_without_restart(clean):
    """UPDATE CONFIGS (multi-key, atomic, control lane) raising
    max_running_queries drains the waiting queue live — the saturated
    cluster stays recoverable."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 5)
    eng = QueryEngine()
    sc = eng.new_session()
    _delay_nodes("Project", 0.5)
    runs = [_run_async(eng, eng.new_session(), f"YIELD {i} AS x")
            for i in range(3)]
    _wait_for(lambda: admission().snapshot()["queued"] == 2,
              msg="two statements queued")
    rs = eng.execute(sc, "UPDATE CONFIGS max_running_queries = 3, "
                         "admission_queue_capacity = 16")
    assert rs.error is None, rs.error
    assert cfg.get("max_running_queries") == 3
    assert cfg.get("admission_queue_capacity") == 16
    _wait_for(lambda: admission().snapshot()["queued"] == 0,
              msg="queue drained by the capacity bump")
    assert admission().snapshot()["running"] >= 2
    for t, box in runs:
        t.join(10)
        assert box["rs"].error is None, box["rs"].error


def test_update_configs_multikey_is_atomic(clean):
    """One bad key in the batch → NOTHING changes."""
    cfg = get_config()
    eng = QueryEngine()
    s = eng.new_session()
    rs = eng.execute(s, "UPDATE CONFIGS max_running_queries = 7, "
                        "never_a_flag = 1")
    assert rs.error is not None
    assert cfg.get("max_running_queries") == 0, \
        "a rejected multi-key batch must not half-apply"
    rs = eng.execute(s, "UPDATE CONFIGS admission_session_weights = "
                        "\"7:3,9:1\"")
    assert rs.error is None, rs.error
    assert cfg.get("admission_session_weights") == "7:3,9:1"


# -- fairness (satellite) ---------------------------------------------------


def _spawn_waiters(ctl, sessions, order, olock, hold_s=0.0):
    """One thread per (session, count) waiter; each admitted ticket is
    recorded and released, cascading the drain."""
    threads = []
    qid = [100]

    def waiter(q, sid):
        try:
            tk = ctl.acquire(qid=q, session=sid, kind="Go")
        except Exception as ex:  # noqa: BLE001 — recorded for asserts
            with olock:
                order.append((sid, repr(ex)))
            return
        with olock:
            order.append(sid)
        if hold_s:
            time.sleep(hold_s)
        tk.release()

    for sid, n in sessions:
        for _ in range(n):
            qid[0] += 1
            threads.append(threading.Thread(
                target=waiter, args=(qid[0], sid), daemon=True))
    return threads


def test_dwrr_fairness_weighted_shares(clean):
    """Three sessions with skewed offered load and weights 1:2:1 —
    while every session stays backlogged, admitted shares track the
    weights (no session starves)."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 1000)
    cfg.set_dynamic("admission_session_weights", "102:2")
    ctl = admission()
    holder = ctl.acquire(qid=1, session=999, kind="Go")
    order, olock = [], threading.Lock()
    threads = _spawn_waiters(
        ctl, [(101, 20), (102, 20), (103, 20)], order, olock)
    for t in threads:
        t.start()
    _wait_for(lambda: ctl.snapshot()["queued"] == 60,
              msg="all 60 waiters queued")
    holder.release()
    for t in threads:
        t.join(10)
    assert len(order) == 60 and not any(
        isinstance(x, tuple) for x in order), order[:5]
    # first 16 admissions: all sessions still backlogged, so DWRR
    # shares must track weights 1:2:1 (102 ≈ half, others ≈ quarter,
    # ±rotation-boundary slack)
    head = order[:16]
    assert 6 <= head.count(102) <= 10, head
    assert head.count(101) >= 2, head
    assert head.count(103) >= 2, head


def test_fairness_survives_concurrent_kill_session(clean):
    """A KILL SESSION mid-drain evicts that session's queued waiters;
    every other session's waiters are still admitted (no stall, no
    starvation)."""
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 1000)
    ctl = admission()
    holder = ctl.acquire(qid=1, session=999, kind="Go")
    order, olock = [], threading.Lock()
    killed = []
    kill_ev = threading.Event()
    threads = _spawn_waiters(
        ctl, [(201, 15), (202, 15)], order, olock, hold_s=0.005)
    qid = [500]

    def doomed_waiter(q):
        try:
            with _cancel.use_cancel(kill=kill_ev):
                tk = ctl.acquire(qid=q, session=204, kind="Go")
                with olock:
                    order.append(204)
                tk.release()
        except _cancel.QueryKilled:
            with olock:
                killed.append(q)

    for _ in range(10):
        qid[0] += 1
        threads.append(threading.Thread(
            target=doomed_waiter, args=(qid[0],), daemon=True))
    for t in threads:
        t.start()
    _wait_for(lambda: ctl.snapshot()["queued"] == 40,
              msg="all 40 waiters queued")
    holder.release()
    _wait_for(lambda: len(order) + len(killed) >= 6,
              msg="drain started")
    kill_ev.set()               # KILL SESSION lands mid-drain
    for t in threads:
        t.join(10)
    with olock:
        admitted_204 = order.count(204)
    assert admitted_204 + len(killed) == 10
    assert order.count(201) == 15 and order.count(202) == 15, \
        "surviving sessions must fully drain"
    assert ctl.snapshot()["queued"] == 0


# -- memory watermark -------------------------------------------------------


class _FakeTracker:
    def __init__(self, used):
        self.used = used


def test_memory_watermark_gates_admission(clean):
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 4)
    cfg.set_dynamic("admission_queue_capacity", 10)
    cfg.set_dynamic("admission_memory_watermark_bytes", 1000)
    ctl = admission()
    # first statement admits even though it will exceed the watermark
    # (nothing is running: the gate must never wedge the drain)
    fat = ctl.acquire(qid=1, session=1, kind="Go",
                      tracker=_FakeTracker(2000))
    assert fat.mode == "admitted"
    box = {}

    def second():
        box["t"] = ctl.acquire(qid=2, session=2, kind="Go",
                               tracker=_FakeTracker(10))
        box["at"] = time.monotonic()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    _wait_for(lambda: ctl.snapshot()["queued"] == 1,
              msg="second statement gated by the watermark")
    time.sleep(0.1)
    assert ctl.snapshot()["queued"] == 1, \
        "must stay queued while memory is above the watermark"
    t_rel = time.monotonic()
    fat.release()
    t.join(5)
    assert box["t"].mode == "admitted"
    assert box["at"] >= t_rel
    box["t"].release()


# -- client-side E_OVERLOAD handling (satellite) ----------------------------


def _graphd_stub(replies):
    """RpcServer speaking just enough graph.* for GraphClient: each
    execute pops the next scripted reply."""
    srv = RpcServer()
    calls = {"n": 0}

    def auth(p):
        return {"session_id": 1}

    def execute(p):
        calls["n"] += 1
        return replies.pop(0)

    srv.register("graph.authenticate", auth)
    srv.register("graph.execute", execute)
    srv.register("graph.signout", lambda p: True)
    srv.start()
    return srv, calls


def _ok_reply(val=1):
    return {"error": None, "space": None, "latency_us": 0,
            "data": None, "plan_desc": None}


def _overload_reply(retry_ms=50):
    return {"error": overload_error(retry_ms / 1000.0,
                                    "graphd:admission", "test shed"),
            "space": None, "latency_us": 0, "data": None,
            "plan_desc": None}


def test_client_honors_retry_after_hint(clean):
    from nebula_tpu.cluster.client import GraphClient
    srv, calls = _graphd_stub(
        [_overload_reply(50), _overload_reply(50), _ok_reply()])
    try:
        cl = GraphClient(srv.host, srv.port)
        cl.authenticate()
        t0 = time.monotonic()
        rs = cl.execute("YIELD 1")
        waited = time.monotonic() - t0
        assert rs.error is None
        assert calls["n"] == 3
        # two 50ms hints, each jittered into [25ms, 75ms]
        assert waited >= 0.05, "both hints must be honored"
        cl.close()
    finally:
        srv.stop()


def test_client_overload_budget_exhausted_is_structured(clean):
    """When the deadline budget runs out the client stops retrying and
    returns the STRUCTURED overload: error text + parsed
    retry_after_ms, in bounded wall time."""
    from nebula_tpu.cluster.client import GraphClient
    get_config().set_dynamic("query_timeout_secs", 0.4)
    srv, calls = _graphd_stub([_overload_reply(80) for _ in range(64)])
    try:
        cl = GraphClient(srv.host, srv.port)
        cl.authenticate()
        t0 = time.monotonic()
        rs = cl.execute("YIELD 1")
        waited = time.monotonic() - t0
        assert rs.error is not None and is_overload(rs.error)
        assert rs.retry_after_ms == 80
        assert waited < 3.0, "retries must stay inside the budget"
        # 80ms hints jittered into [40ms, 120ms] against a 0.4s budget
        assert 1 <= calls["n"] < 15
        cl.close()
    finally:
        srv.stop()


# -- bounded RPC-server inbox -----------------------------------------------


def test_rpc_inbox_sheds_with_retry_after(clean):
    """Capacity-1 inbox + a slow handler: concurrent pipelined calls
    are rejected with E_OVERLOAD (+hint); a retrying client rides the
    hint to success; exempt methods are never shed."""
    cfg = get_config()
    cfg.set_dynamic("rpc_server_inbox_capacity", 1)
    srv = RpcServer()
    srv.service_role = "storaged"
    srv.register("test.slow", lambda p: (time.sleep(0.3), "done")[1])
    srv.register("meta.ping", lambda p: "pong")
    srv.start()
    try:
        cl = RpcClient(srv.host, srv.port, retries=0)
        results, errors = [], []

        def call():
            try:
                results.append(cl.call("test.slow"))
            except RpcError as ex:
                errors.append(str(ex))

        ths = [threading.Thread(target=call, daemon=True)
               for _ in range(3)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(10)
        assert errors, "concurrent calls beyond capacity must shed"
        for e in errors:
            assert is_overload(e) and parse_retry_after(e) is not None, e
        # exempt method answers even while the inbox is saturated
        t_busy = threading.Thread(
            target=lambda: cl.call("test.slow"), daemon=True)
        t_busy.start()
        time.sleep(0.05)
        assert cl.call("meta.ping") == "pong"
        t_busy.join(10)
        # a client WITH retries honors the hint and lands the call
        rcl = RpcClient(srv.host, srv.port, retries=4)
        t_busy2 = threading.Thread(
            target=lambda: cl.call("test.slow"), daemon=True)
        t_busy2.start()
        time.sleep(0.05)
        assert rcl.call("test.slow") == "done"
        t_busy2.join(10)
        rcl.close()
        cl.close()
    finally:
        srv.stop()


def test_rpc_inbox_failpoint_force_shed(clean):
    cfg = get_config()
    cfg.set_dynamic("rpc_server_inbox_capacity", 100)
    srv = RpcServer()
    srv.register("test.fast", lambda p: "ok")
    srv.start()
    try:
        fail.arm("rpc:server_inbox", "1*raise")
        cl = RpcClient(srv.host, srv.port, retries=0)
        with pytest.raises(RpcError) as ei:
            cl.call("test.fast")
        assert is_overload(str(ei.value))
        assert cl.call("test.fast") == "ok"    # site disarmed
        cl.close()
    finally:
        srv.stop()


# -- device dispatch-queue cap ----------------------------------------------


def test_dispatch_queue_cap_degrades_to_host(clean):
    from nebula_tpu.tpu.pipeline import _dispatch_overloaded
    from nebula_tpu.utils.workload import dispatch_table
    cfg = get_config()
    assert not _dispatch_overloaded(), "cap=0 must never shed"
    cfg.set_dynamic("tpu_dispatch_queue_cap", 2)
    assert not _dispatch_overloaded(), "empty queue under cap"
    toks = [dispatch_table().enter(f"k{i}") for i in range(2)]
    try:
        shed0 = _counter("tpu_dispatch_queue_shed")
        assert _dispatch_overloaded(), "queued depth at cap must shed"
        assert _counter("tpu_dispatch_queue_shed") - shed0 == 1
    finally:
        for tok in toks:
            dispatch_table().exit(tok)
    assert not _dispatch_overloaded()
