"""Flight recorder + device kernel ledger (ISSUE 8): forced capture on
error/slow, deterministic sampling, bounded rings, the /flight and
/kernels endpoints, SHOW FLIGHT RECORDER, and the bounded slow log."""
import json
import urllib.request

import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.flight import (FlightRecorder, KernelLedger,
                                     flight_recorder, kernel_ledger)


@pytest.fixture()
def recorder():
    fr = flight_recorder()
    fr.clear()
    yield fr
    fr.clear()
    get_config().dynamic_layer.pop("flight_sample_rate", None)
    get_config().dynamic_layer.pop("flight_recorder_capacity", None)


def _mk(fr, error=None, latency_us=10, slow_us=0, stmt="YIELD 1",
        ops=()):
    return fr.record(stmt=stmt, kind="Yield", latency_us=latency_us,
                     error=error, trace_id=None, session=1,
                     operators=list(ops), slow_us=slow_us)


def test_forced_capture_reasons():
    fr = FlightRecorder()
    assert _mk(fr, error="ExecutionError: boom")["status"] == "error"
    assert _mk(fr, error="ExecutionError: query was killed"
               )["status"] == "killed"
    assert _mk(fr, error="E_QUERY_TIMEOUT: statement exceeded"
               )["status"] == "timeout"
    assert _mk(fr, error="FailpointError: rpc:send"
               )["status"] == "failpoint"
    assert _mk(fr, latency_us=900, slow_us=500)["status"] == "slow"
    # structured matching: statement fragments quoted in ordinary
    # errors must not trigger the killed/timeout/failpoint statuses
    assert _mk(fr, error="SemanticError: unknown prop `killed'"
               )["status"] == "error"
    assert _mk(fr, error='SyntaxError: near "E_QUERY_TIMEOUT"'
               )["status"] == "error"


def test_sampling_is_deterministic(recorder):
    get_config().set_dynamic("flight_sample_rate", 0.5)
    fr = FlightRecorder()
    kept = [e for e in (_mk(fr) for _ in range(10)) if e is not None]
    assert len(kept) == 5, "rate 0.5 must retain exactly every 2nd"
    get_config().set_dynamic("flight_sample_rate", 0.0)
    fr2 = FlightRecorder()
    assert all(_mk(fr2) is None for _ in range(5))
    # forced capture ignores the rate
    assert _mk(fr2, error="x") is not None


def test_ring_is_bounded(recorder):
    get_config().set_dynamic("flight_recorder_capacity", 4)
    fr = FlightRecorder()
    for i in range(10):
        _mk(fr, error=f"e{i}")
    lst = fr.list()
    assert len(lst) == 4
    # newest first, oldest evicted
    assert lst[0]["id"] == 10 and lst[-1]["id"] == 7


def test_lazy_operator_materialization(recorder):
    """Dropped statements must not pay operator-list construction."""
    get_config().set_dynamic("flight_sample_rate", 0.0)
    fr = FlightRecorder()
    calls = {"n": 0}

    def ops():
        calls["n"] += 1
        return [{"kind": "Start"}]

    assert fr.record(stmt="q", kind="Yield", latency_us=1, error=None,
                     trace_id=None, session=1, operators=ops) is None
    assert calls["n"] == 0
    e = fr.record(stmt="q", kind="Yield", latency_us=1, error="x",
                  trace_id=None, session=1, operators=ops)
    assert calls["n"] == 1 and e["operators"] == [{"kind": "Start"}]


def test_engine_failed_statement_forced_into_recorder(recorder):
    get_config().set_dynamic("flight_sample_rate", 0.0)
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "USE nosuchspace")        # semantic error → forced
    entries = recorder.list()
    assert entries and entries[0]["status"] == "error"
    assert "nosuchspace" in entries[0]["stmt"]


def test_parse_error_forced_into_recorder(recorder):
    """Syntax errors burn SLO budget — they must leave flight evidence
    like every other error, despite never reaching the scheduler."""
    get_config().set_dynamic("flight_sample_rate", 0.0)
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "GOGO 1 NONSENSE")
    entries = recorder.list()
    assert entries and entries[0]["status"] == "error"
    assert entries[0]["kind"] == "Parse"
    assert "GOGO 1 NONSENSE" in entries[0]["stmt"]


def test_engine_sampled_entry_has_operator_breakdown(recorder):
    get_config().set_dynamic("flight_sample_rate", 1.0)
    eng = QueryEngine()
    s = eng.new_session()
    for q in ['CREATE SPACE fl(partition_num=2, vid_type=INT64)',
              'USE fl', 'CREATE EDGE e(w int)',
              'INSERT EDGE e(w) VALUES 1->2:(1), 2->3:(2)']:
        r = eng.execute(s, q)
        assert r.error is None, f"{q} -> {r.error}"
    r = eng.execute(s, "GO FROM 1 OVER e YIELD dst(edge) AS d")
    assert r.ok
    newest = recorder.list()[0]
    full = recorder.get(newest["id"])
    assert full["status"] == "sampled"
    kinds = {op["kind"] for op in full["operators"]}
    assert kinds, "no per-operator breakdown recorded"
    assert all("exec_us" in op and "rows" in op
               for op in full["operators"])
    assert "work" in full and "rpc_calls" in full["work"]


def test_show_flight_recorder(recorder):
    get_config().set_dynamic("flight_sample_rate", 0.0)
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "USE nosuch1")
    eng.execute(s, "USE nosuch2")
    r = eng.execute(s, "SHOW FLIGHT RECORDER")
    assert r.ok, r.error
    assert r.data.column_names[0] == "Id"
    stmts = [row[6] for row in r.data.rows]
    assert any("nosuch2" in t for t in stmts)
    assert any("nosuch1" in t for t in stmts)
    statuses = {row[1] for row in r.data.rows}
    assert statuses == {"error"}


def test_flight_endpoint(recorder):
    from nebula_tpu.cluster.webservice import WebService
    get_config().set_dynamic("flight_sample_rate", 0.0)
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "USE nosuchspace")
    ws = WebService(role="graphd")
    ws.start()
    try:
        lst = json.loads(urllib.request.urlopen(
            f"http://{ws.addr}/flight").read())
        assert lst and lst[0]["status"] == "error"
        full = json.loads(urllib.request.urlopen(
            f"http://{ws.addr}/flight?id={lst[0]['id']}").read())
        assert full["error"] and "operators" in full
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://{ws.addr}/flight?id=999999")
    finally:
        ws.stop()


# -- kernel ledger ----------------------------------------------------------


def test_kernel_ledger_bounded_and_served():
    from nebula_tpu.cluster.webservice import WebService
    led = KernelLedger()
    for i in range(5):
        led.record(kernel="traverse", shape=[2048], steps=3,
                   compiled=(i == 0), dispatch_us=100 + i,
                   hbm_bytes=1 << 20)
    lst = led.list()
    assert len(lst) == 5 and lst[0]["dispatch_us"] == 104
    assert lst[-1]["compiled"] and not lst[0]["compiled"]
    # the process-wide ledger is what /kernels serves
    kernel_ledger().record(kernel="bfs", shape=[4096, 4096], steps=5,
                           compiled=True, dispatch_us=777,
                           hbm_bytes=123)
    ws = WebService(role="graphd")
    ws.start()
    try:
        rows = json.loads(urllib.request.urlopen(
            f"http://{ws.addr}/kernels").read())
        assert any(r["kernel"] == "bfs" and r["dispatch_us"] == 777
                   for r in rows)
    finally:
        ws.stop()


def test_device_dispatch_feeds_ledger_and_profile():
    """A device GO records its dispatches in the kernel ledger (shape
    bucket, compile-vs-cache, HBM) and its PROFILE row carries the
    compile/HBM fields."""
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime

    kernel_ledger().clear()
    eng = QueryEngine(tpu_runtime=TpuRuntime(make_mesh()))
    s = eng.new_session()
    for q in ["CREATE SPACE kl(partition_num=8, vid_type=INT64)",
              "USE kl", "CREATE EDGE e(w int)",
              "INSERT EDGE e(w) VALUES 1->2:(1), 2->3:(2), 1->3:(3)"]:
        r = eng.execute(s, q)
        assert r.error is None, f"{q} -> {r.error}"
    r = eng.execute(s, "PROFILE GO 2 STEPS FROM 1 OVER e "
                       "YIELD dst(edge) AS d")
    assert r.error is None
    recs = kernel_ledger().list()
    assert recs, "device dispatch left no ledger record"
    assert recs[0]["kernel"] == "traverse"
    assert recs[0]["shape"] and recs[0]["hbm_bytes"] > 0
    assert "'compiles':" in r.plan_desc \
        and "'hbm_bytes':" in r.plan_desc, r.plan_desc
    snap = stats_snapshot()
    assert any(k.startswith("tpu_dispatch_us") for k in snap)
    assert snap.get("tpu_hbm_high_water_bytes", 0) > 0


def stats_snapshot():
    from nebula_tpu.utils.stats import stats
    return stats().snapshot()


# -- bounded slow log -------------------------------------------------------


def test_slow_log_is_bounded():
    get_config().set_dynamic("slow_log_capacity", 3)
    get_config().set_dynamic("slow_query_threshold_us", 0)
    try:
        eng = QueryEngine()
        s = eng.new_session()
        for i in range(8):
            eng.execute(s, f"YIELD {i}")
        assert len(eng.slow_log) == 3
        # newest retained
        assert eng.slow_log[-1]["stmt"] == "YIELD 7"
    finally:
        get_config().dynamic_layer.pop("slow_log_capacity", None)
        get_config().dynamic_layer.pop("slow_query_threshold_us", None)
