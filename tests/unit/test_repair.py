"""Self-healing plane (ISSUE 14), tier-1 half: raft learner semantics
(a learner can NEVER vote or count toward quorum), the resumable
membership task engine (kill between every phase, re-drive converges),
the metad-failover false-dead window, and the dynamic catch-up flag.
The live-load chaos proofs ride in tests/chaos/test_self_heal.py."""
import time

import pytest

from nebula_tpu.cluster.raft import LEADER, LoopbackTransport, RaftPart
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import FailpointError, fail


# ---------------------------------------------------------------------------
# raft learners (LoopbackTransport, in-process groups)
# ---------------------------------------------------------------------------


class Applied:
    def __init__(self):
        import threading
        self.entries = []
        self.lock = threading.Lock()

    def cb(self, idx, data):
        with self.lock:
            self.entries.append((idx, data))

    def data(self):
        with self.lock:
            return [d for _, d in self.entries]


def _mixed_group(tmp_path, n_voters=2, n_learners=1, group="lg",
                 snapshot=False, snapshot_threshold=10_000):
    """n_voters voting members + n_learners learner members."""
    tr = LoopbackTransport()
    voters = [f"v{i}" for i in range(n_voters)]
    learners = [f"l{i}" for i in range(n_learners)]
    parts, apps = [], []
    for nid in voters + learners:
        app = Applied()
        snap_cb = rest_cb = None
        if snapshot:
            def snap_cb(a=app):
                return b"|".join(a.data())

            def rest_cb(b, a=app):
                with a.lock:
                    a.entries = [(0, d) for d in b.split(b"|") if d]
        part = RaftPart(group, nid, voters, tr,
                        str(tmp_path / nid), app.cb,
                        snapshot_cb=snap_cb, restore_cb=rest_cb,
                        election_timeout=(0.05, 0.12),
                        heartbeat_interval=0.02,
                        snapshot_threshold=snapshot_threshold,
                        learners=learners)
        parts.append(part)
        apps.append(app)
    for p in parts:
        p.start()
    return tr, parts, apps


def _wait_leader(parts, timeout=20.0):
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        leaders = [p for p in parts if p.is_leader() and p.alive]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.01)
    raise AssertionError("no unique leader elected")


def _wait_data(app, want, timeout=20.0):
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        if app.data() == want:
            return
        time.sleep(0.01)
    raise AssertionError(f"want {want}, got {app.data()}")


def test_learner_replicates_but_never_counts_toward_quorum(tmp_path):
    """2 voters + 1 learner: entries reach the learner, but with one
    voter dead the group must NOT commit — the learner's ack can never
    substitute for a voter (quorum stays 2-of-2 voters)."""
    tr, parts, apps = _mixed_group(tmp_path, n_voters=2, n_learners=1)
    v0, v1, lrn = parts
    try:
        leader = _wait_leader([v0, v1])
        assert leader.propose(b"a", timeout=20)
        # the learner received and applied the entry (replication works)
        _wait_data(apps[2], [b"a"])
        # kill the OTHER voter: voter quorum is gone; the live learner
        # must not let the leader commit
        other = v1 if leader is v0 else v0
        other.alive = False
        assert leader.propose(b"b", timeout=0.6) is None
        assert b"b" not in apps[0].data() + apps[1].data()
    finally:
        for p in parts:
            p.stop()


def test_learner_never_votes_or_campaigns(tmp_path):
    tr, parts, apps = _mixed_group(tmp_path, n_voters=2, n_learners=1)
    v0, v1, lrn = parts
    try:
        leader = _wait_leader([v0, v1])
        # a learner refuses any vote request, even a well-formed one
        # from a candidate whose log it trails
        r = lrn.handle("request_vote", {
            "_from": leader.node_id, "term": leader.current_term + 1,
            "candidate": leader.node_id,
            "last_log_index": 1 << 30, "last_log_term": 1 << 30})
        assert r["granted"] is False
        # and it never campaigns: both voters die, the learner's
        # election deadline keeps lapsing, it stays a follower forever
        v0.alive = False
        v1.alive = False
        time.sleep(0.5)                 # >> election timeout
        assert lrn.state != LEADER
        assert lrn.current_term <= leader.current_term + 1
    finally:
        for p in parts:
            p.stop()


def test_learner_promote_then_counts_and_votes(tmp_path):
    """After promotion the ex-learner is a full voter: with one
    original voter dead, leader + promoted member form a 2-of-3
    quorum and commits flow again."""
    tr, parts, apps = _mixed_group(tmp_path, n_voters=2, n_learners=1)
    v0, v1, lrn = parts
    try:
        leader = _wait_leader([v0, v1])
        assert leader.propose(b"a", timeout=20)
        _wait_data(apps[2], [b"a"])     # caught up
        fail.reset()
        for p in parts:
            p.update_peers(["v0", "v1", "l0"], [])
        other = v1 if leader is v0 else v0
        other.alive = False
        # retry against the current leader like a real client: the
        # config change may race a heartbeat round
        dl = time.monotonic() + 15
        while True:
            live = [p for p in (v0, v1, lrn) if p.alive]
            ld = next((p for p in live if p.is_leader()), None)
            if ld is not None and ld.propose(b"b", timeout=2):
                break
            assert time.monotonic() < dl, "promoted group never committed"
            time.sleep(0.05)
        _wait_data(apps[2], [b"a", b"b"])
    finally:
        for p in parts:
            p.stop()


def test_learner_snapshot_install_catchup(tmp_path):
    """A learner added AFTER log compaction catches up via snapshot
    install (the repair path for a part with a compacted WAL)."""
    tr, parts, apps = _mixed_group(tmp_path, n_voters=2, n_learners=0,
                                   snapshot=True, snapshot_threshold=10)
    v0, v1 = parts
    try:
        leader = _wait_leader(parts)
        want = []
        for i in range(25):             # > snapshot_threshold
            d = f"e{i}".encode()
            assert leader.propose(d, timeout=20)
            want.append(d)
        dl = time.monotonic() + 10
        while leader.snap_index == 0 and time.monotonic() < dl:
            time.sleep(0.02)
        assert leader.snap_index > 0, "log never compacted"
        # join the learner now — its WAL is empty, the leader's log
        # starts past the snapshot, so catch-up MUST go through
        # install_snapshot
        app = Applied()

        def rest_cb(b, a=app):
            with a.lock:
                a.entries = [(0, d) for d in b.split(b"|") if d]
        lrn = RaftPart("lg", "l0", ["v0", "v1"], tr,
                       str(tmp_path / "l0"), app.cb,
                       snapshot_cb=lambda: b"", restore_cb=rest_cb,
                       election_timeout=(0.05, 0.12),
                       heartbeat_interval=0.02, learners=["l0"])
        lrn.start()
        for p in parts:
            p.update_peers(["v0", "v1"], ["l0"])
        dl = time.monotonic() + 15
        while time.monotonic() < dl:
            got = app.data()
            if got and got == want[-len(got):] and \
                    lrn.applied_index() >= leader.applied_index():
                break
            time.sleep(0.02)
        assert lrn.snap_index > 0, "learner never snapshot-installed"
        parts.append(lrn)
    finally:
        for p in parts:
            p.stop()


# ---------------------------------------------------------------------------
# resumable membership changes (satellite: kill between every phase)
# ---------------------------------------------------------------------------


def _setup_moving_space(client, cluster, parts=4):
    rs = client.execute(
        f"CREATE SPACE mv(partition_num={parts}, replica_factor=1, "
        f"vid_type=INT64)")
    assert rs.error is None, rs.error
    cluster.reconcile_storage()
    for q in ["USE mv", "CREATE TAG item(x int)"]:
        rs = client.execute(q)
        assert rs.error is None, (q, rs.error)
    vals = ", ".join(f"{i}:({i * 10})" for i in range(40))
    rs = client.execute(f"INSERT VERTEX item(x) VALUES {vals}")
    assert rs.error is None, rs.error


def test_membership_change_resumes_after_each_phase_kill(tmp_path):
    """Kill the task at EVERY phase boundary (failpoints at
    add/catch-up/promote/remove) and re-drive: the part converges to
    the target replica set with no orphaned state on the removed
    host."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.cluster.repair import (ClientPartOps,
                                           run_membership_change)
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        _setup_moving_space(client, c, parts=4)
        store = c.graphds[0].store
        ops = ClientPartOps(store.meta, store.sc)
        addrs = [s.addr for s in c.storage_servers]
        alive = list(addrs)
        sites = ["repair:add_learner", "repair:catchup",
                 "repair:promote", "repair:remove"]
        moved = {}                      # pid → (src, dst)
        for pid, site in enumerate(sites):
            # move each part to the OTHER host, dying at a different
            # phase each time
            src = store.meta.parts_of("mv")[pid][0]
            dst = next(a for a in addrs if a != src)
            moved[pid] = (src, dst)
            with fail.scoped():
                fail.arm(site, "raise(killed-mid-task)")
                with pytest.raises(FailpointError):
                    run_membership_change(ops, "mv", pid, add=dst,
                                          remove=src, alive=alive)
            # re-drive the SAME change from scratch: every phase is
            # idempotent, so the converged result is identical no
            # matter where the first attempt died
            run_membership_change(ops, "mv", pid, add=dst,
                                  remove=src, alive=alive)
            store.meta.refresh(force=True)
            assert store.meta.parts_of("mv")[pid] == [dst]
            assert store.meta.learners_of("mv")[pid] == []
        # no orphaned part state on any removed host
        sid = c.storageds[0].meta.catalog.get_space("mv").space_id
        for pid, (src, dst) in moved.items():
            ss_src = c.storageds[addrs.index(src)]
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if (sid, pid) not in ss_src.parts:
                    break
                ss_src.reconcile_parts()
                time.sleep(0.1)
            assert (sid, pid) not in ss_src.parts
            assert not ss_src.store.space("mv").parts[pid].vertices
        # data survived the four phase-killed moves
        rs = client.execute("USE mv")
        assert rs.error is None
        rs = client.execute(
            "FETCH PROP ON item 7, 23, 39 YIELD item.x AS x "
            "| ORDER BY $-.x")
        assert rs.error is None, rs.error
        assert rs.data.rows == [[70], [230], [390]]
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# metad-failover false-dead window (satellite)
# ---------------------------------------------------------------------------


def test_fresh_meta_leader_reports_unknown_not_dead(tmp_path):
    """Liveness is leader-local: a fresh metad leader has seen no
    heartbeats, so without the post-election grace every host would
    read dead the instant it takes over.  With heartbeats silenced
    entirely, the new leader must report UNKNOWN (not OFFLINE) until
    one full heartbeat interval of leadership has elapsed — and the
    supervisor must not create any repair plan inside that window."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=3, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    get_config().set_dynamic_many({"heartbeat_interval_secs": 3.0,
                                   "host_hb_expire_secs": 0.4,
                                   "repair_scan_interval_secs": 0.05})
    try:
        client = c.client()
        rs = client.execute(
            "CREATE SPACE fd(partition_num=2, replica_factor=2, "
            "vid_type=INT64)")
        assert rs.error is None, rs.error
        c.reconcile_storage()
        # silence every heartbeat, then depose the leader: the new one
        # must judge the part-map hosts without ANY heartbeat history
        for mc in c.meta_clients:
            mc.stop_heartbeat()
        old = c.meta_leader_index()
        assert old >= 0
        c.stop_metad(old)
        deadline = time.monotonic() + 15
        new_leader = None
        while time.monotonic() < deadline:
            idx = c.meta_leader_index()
            if idx >= 0 and idx != old:
                new_leader = c.metads[idx]
                break
            time.sleep(0.02)
        assert new_leader is not None, "no successor elected"
        # the new leader may still be applying its log backlog; the
        # part-map hosts must surface (as UNKNOWN) within the grace
        deadline = time.monotonic() + 2.0
        storage = []
        while time.monotonic() < deadline:
            storage = [h for h in new_leader.rpc_list_hosts({})
                       if h["role"] == "storage"]
            if len(storage) == 2:
                break
            time.sleep(0.02)
        assert len(storage) == 2, storage
        assert all(h["status"] == "UNKNOWN" for h in storage), storage
        assert all(not h["alive"] for h in storage), hosts
        assert new_leader.rpc_list_repairs({}) == []
        # SHOW HOSTS renders the same verdict through the client
        rs = client.execute("SHOW HOSTS STORAGE")
        assert rs.error is None, rs.error
        assert {row[2] for row in rs.data.rows} == {"UNKNOWN"}, \
            rs.data.rows
        # after the grace (one heartbeat interval) + expiry with still
        # no heartbeats, the verdict hardens to OFFLINE
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            hosts = [h for h in new_leader.rpc_list_hosts({})
                     if h["role"] == "storage"]
            if all(h["status"] == "OFFLINE" for h in hosts):
                break
            time.sleep(0.1)
        assert all(h["status"] == "OFFLINE" for h in hosts), hosts
    finally:
        get_config().set_dynamic_many({"heartbeat_interval_secs": 1.0,
                                       "host_hb_expire_secs": 10.0,
                                       "repair_scan_interval_secs": 0.5})
        c.stop()


# ---------------------------------------------------------------------------
# dynamic catch-up timeout flag (satellite)
# ---------------------------------------------------------------------------


def test_catchup_timeout_flag_is_dynamic():
    """`balance_catchup_timeout_secs` replaced the hardcoded 30s: both
    BALANCE DATA and auto-repair read it per call, and the UPDATE
    CONFIGS multi-key path (set_dynamic_many) retunes it live."""
    from nebula_tpu.cluster.repair import (MembershipError, PartOps,
                                           catchup_timeout_s,
                                           wait_caught_up)
    assert catchup_timeout_s() == 30.0          # the default
    get_config().set_dynamic_many({"balance_catchup_timeout_secs": 0.3})
    try:
        assert catchup_timeout_s() == 0.3

        class DeadOps(PartOps):
            def call_host(self, addr, method, **kw):
                raise ConnectionError("down")
        t0 = time.monotonic()
        with pytest.raises(MembershipError):
            wait_caught_up(DeadOps(), "h1", "sp", 0, ["h0"])
        # honored the dynamic value, not the old 30s hardcode
        assert time.monotonic() - t0 < 5.0
    finally:
        get_config().set_dynamic_many(
            {"balance_catchup_timeout_secs": 30.0})


def test_show_repairs_parses_standalone():
    """SHOW REPAIRS is a first-class statement: parses everywhere,
    empty table on a standalone (cluster-less) store."""
    from nebula_tpu.query.parser import parse
    assert parse("SHOW REPAIRS").kind == "repairs"
