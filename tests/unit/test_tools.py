"""Tool entrypoints: ldbc_import, db_dump, csr_dump, storage_perf —
each driven through its main() like a user would."""
import pytest

from nebula_tpu.tools import csr_dump, db_dump, ldbc_import, storage_perf


@pytest.fixture()
def csvs(tmp_path):
    people = tmp_path / "person.csv"
    people.write_text("id|name|age\n1|ann|30\n2|bob|25\n3|cid|41\n")
    knows = tmp_path / "knows.csv"
    knows.write_text("src|dst|since\n1|2|2010\n2|3|2015\n1|3|2012\n")
    return people, knows


def test_ldbc_import_and_dumps(tmp_path, csvs, capsys):
    people, knows = csvs
    cp = tmp_path / "cp"
    rc = ldbc_import.main([
        "--space", "ld", "--parts", "4", "--vid-type", "INT64",
        "--vertices", f"Person:{people}:id,name:string,age:int",
        "--edges", f"KNOWS:{knows}:src,dst,since:int",
        "--delimiter", "|", "--checkpoint", str(cp)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "3 vertices" in out and "3 edges" in out

    # restored checkpoint serves queries
    from nebula_tpu.exec import QueryEngine
    from nebula_tpu.graphstore.store import GraphStore
    st = GraphStore.from_checkpoint(str(cp))
    eng = QueryEngine(st)
    s = eng.new_session()
    eng.execute(s, "USE ld")
    r = eng.execute(s, "GO FROM 1 OVER KNOWS YIELD dst(edge), KNOWS.since")
    assert r.ok and sorted(map(tuple, r.data.rows)) == [(2, 2010), (3, 2012)]

    # db_dump over the checkpoint
    assert db_dump.main([str(cp)]) == 0
    out = capsys.readouterr().out
    assert "vertices=3" in out and "edges=3" in out
    assert db_dump.main([str(cp), "--mode", "edge", "--limit", "2"]) == 0
    out = capsys.readouterr().out
    assert "-[:KNOWS@0]->" in out

    # csr_dump over the checkpoint
    assert csr_dump.main([str(cp), "--space", "ld"]) == 0
    out = capsys.readouterr().out
    assert "block (KNOWS, out): edges=3" in out
    assert "tag table Person: present=3" in out


def test_ldbc_import_string_vids(tmp_path, capsys):
    pf = tmp_path / "v.csv"
    pf.write_text("id,score\na,1.5\nb,2.5\n")
    ef = tmp_path / "e.csv"
    ef.write_text("src,dst\na,b\n")
    rc = ldbc_import.main([
        "--space", "lds", "--parts", "2",
        "--vid-type", "FIXED_STRING(32)",
        "--vertices", f"T:{pf}:id,score:float",
        "--edges", f"E:{ef}:src,dst"])
    assert rc == 0
    assert "2 vertices" in capsys.readouterr().out


def test_storage_perf_smoke(capsys):
    rc = storage_perf.main(["--vertices", "50", "--edges", "100",
                            "--reads", "40", "--batch", "20"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "getNeighbors" in out and "op/s" in out


def test_metrics_dump_cluster_scrape(capsys):
    """--addrs scrapes every host, prints per-host sections and a
    merged (counters summed) view (ISSUE 8 satellite)."""
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump
    from nebula_tpu.utils.stats import stats

    stats().inc("md_cluster_probe", 3)
    ws1 = WebService(role="graphd")
    ws2 = WebService(role="storaged")
    ws1.start()
    ws2.start()
    try:
        rc = metrics_dump.main(
            ["--addrs", f"{ws1.addr},{ws2.addr}",
             "--grep", "md_cluster_probe"])
        assert rc == 0
        out = capsys.readouterr().out
        assert f"== {ws1.addr}" in out and f"== {ws2.addr}" in out
        # both webservices front the same in-process registry, so the
        # merged view sums the sample across hosts: 3 + 3
        assert "== merged (2/2 hosts)" in out
        assert "md_cluster_probe 6" in out
    finally:
        ws1.stop()
        ws2.stop()


def test_metrics_dump_watch_deltas(capsys):
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump
    from nebula_tpu.utils.stats import stats

    ws = WebService(role="graphd")
    ws.start()
    try:
        import threading

        def bump():
            stats().inc("md_watch_probe", 5)
        t = threading.Timer(0.1, bump)
        t.start()
        rc = metrics_dump.main(["--addrs", ws.addr, "--watch", "0.3",
                                "--iterations", "1",
                                "--grep", "md_watch_probe"])
        t.join()
        assert rc == 0
        out = capsys.readouterr().out
        assert "baseline:" in out
        assert "md_watch_probe" in out and "(+5)" in out
    finally:
        ws.stop()


def test_metrics_dump_shards_view(capsys):
    """--shards (ISSUE 17): per-device HBM ledger rows, the
    ledger-vs-pinned sum check and exchange bytes, scraped from the
    prometheus exposition (quoted label values)."""
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump
    from nebula_tpu.utils.stats import stats

    st = stats()
    with st.lock:
        # earlier sharded-runtime tests leave their own ledger rows in
        # the process-global registry — start from a clean ledger
        st.labeled_gauges.pop("tpu_shard_hbm_bytes", None)
    st.gauge("tpu_shards", 4.0)
    for p in range(4):
        st.gauge_labeled("tpu_shard_hbm_bytes", {"shard": p},
                         float(1000 + p))
    st.gauge("tpu_hbm_bytes_pinned", float(sum(
        1000 + p for p in range(4))))
    st.inc("tpu_all_to_all_bytes", 2048)
    a2a_total = int(st.snapshot().get("tpu_all_to_all_bytes", 0))
    ws = WebService(role="graphd")
    ws.start()
    try:
        rc = metrics_dump.main(["--addr", ws.addr, "--shards"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "mesh width: 4 shard(s)" in out
        assert "shard 0" in out and "shard 3" in out
        assert "hbm=1003" in out
        assert "-> OK" in out and "MISMATCH" not in out
        assert f"all_to_all exchanged: {a2a_total} bytes" in out

        # a stale pinned total is called out, not silently summed over
        st.gauge("tpu_hbm_bytes_pinned", 1.0)
        rc = metrics_dump.main(["--addr", ws.addr, "--shards"])
        assert rc == 0
        assert "MISMATCH" in capsys.readouterr().out
    finally:
        ws.stop()
        st.gauge("tpu_hbm_bytes_pinned", 0.0)


def test_metrics_dump_fleet_view(capsys):
    """--fleet (ISSUE 20): per-coordinator session gauge, goodput
    ledger by statement kind, epoch-propagation lag mean and the
    failover-plane counters, scraped from the prometheus exposition."""
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump
    from nebula_tpu.utils.stats import stats

    st = stats()
    with st.lock:
        # earlier engine/epoch tests leave observations in the
        # process-global registry — start from known totals
        st.histograms.pop("query_latency_us_hist", None)
        st.histograms.pop("epoch_propagation_lag_ms", None)
        st.labeled.pop("overload_server_rejections", None)
        st.counters["cluster_epoch_folds"] = 3
        st.counters["session_moves"] = 2
        st.counters["coordinator_failovers"] = 1
        st.counters["graphd_drains"] = 0
        st.counters["kill_owner_dead"] = 0
    st.gauge("graph_sessions", 7.0)
    for _ in range(3):
        st.observe("query_latency_us_hist", 900.0, {"kind": "go"})
    st.observe("query_latency_us_hist", 4000.0, {"kind": "match"})
    st.observe("epoch_propagation_lag_ms", 4.0)
    st.observe("epoch_propagation_lag_ms", 8.0)
    st.inc_labeled("overload_server_rejections",
                   {"op": "graph.statement_capacity", "role": "graphd"},
                   4)
    ws = WebService(role="graphd")
    ws.start()
    try:
        rc = metrics_dump.main(["--addr", ws.addr, "--fleet"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "fleet samples" in out
        assert "sessions: 7" in out
        assert "statements served: 4" in out
        assert "go=3" in out and "match=1" in out
        assert "epoch folds: 3" in out
        assert "propagation lag: 6.00ms mean of 2" in out
        assert "session moves: 2" in out and "failovers: 1" in out
        assert "capacity sheds: 4" in out
    finally:
        ws.stop()
        st.gauge("graph_sessions", 0.0)


def test_metrics_dump_perfetto_export(tmp_path, capsys):
    """--perfetto exports scraped trace trees + stall captures as
    Chrome trace-event JSON (ISSUE 9 satellite): one process track per
    daemon, one thread track per service, device spans included,
    stalls as instant events."""
    import json

    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump
    from nebula_tpu.utils import trace
    from nebula_tpu.utils.workload import stall_watchdog

    # a stitched trace with host + device + remote-ish spans
    with trace.start_trace("query:Go", service="graphd", stmt="GO ..."):
        with trace.span("exec:ExpandAll", node=7):
            trace.record_phase("device:dispatch", 0.003, eb=[256])
        trace.graft([{"tid": "t1", "sid": "r1", "psid": "x",
                      "name": "store:get_neighbors", "svc": "storaged",
                      "t0": 1.0, "dur_us": 42}])
    stall_watchdog().clear()
    stall_watchdog()._capture(
        "dispatch", {"kernel": "traverse", "state": "queued"},
        1.5, 0.5)
    ws = WebService(role="graphd")
    ws.start()
    out_path = tmp_path / "cluster.trace.json"
    try:
        rc = metrics_dump.main(["--addr", ws.addr,
                                "--perfetto", str(out_path)])
        assert rc == 0
        doc = json.loads(out_path.read_text())
        evs = doc["traceEvents"]
        spans = [e for e in evs if e["ph"] == "X"]
        names = {e["name"] for e in spans}
        assert {"query:Go", "exec:ExpandAll",
                "device:dispatch"} <= names
        # remote span rides on its own service track
        remote = next(e for e in spans
                      if e["name"] == "store:get_neighbors")
        assert "[remote]" in remote["cat"]
        for e in spans:
            assert e["pid"] and e["tid"] and "ts" in e and "dur" in e
        # process/thread metadata names the tracks
        meta = {e["name"] for e in evs if e["ph"] == "M"}
        assert {"process_name", "thread_name"} <= meta
        # the stall capture lands as a global instant event
        stall = next(e for e in evs if e["ph"] == "i")
        assert stall["name"] == "stall:dispatch"
        assert stall["args"]["subject"]["kernel"] == "traverse"
        # --stalls lists the capture too
        rc = metrics_dump.main(["--addr", ws.addr, "--stalls"])
        assert rc == 0
        assert "dispatch" in capsys.readouterr().out
    finally:
        ws.stop()
        stall_watchdog().clear()


def test_metrics_dump_queries_listing(capsys):
    """--queries prints the live workload rows from GET /queries."""
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump
    from nebula_tpu.utils.workload import live_registry

    lq = live_registry().register(
        qid=990001, session=7, user="root",
        stmt="GO FROM 1 OVER E", kind="Go")
    assert lq is not None
    lq.node_start("ExpandAll", 3)
    ws = WebService(role="graphd")
    ws.start()
    try:
        rc = metrics_dump.main(["--addr", ws.addr, "--queries"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "q990001" in out and "ExpandAll#3" in out
    finally:
        ws.stop()
        live_registry().deregister(990001)


def test_metrics_dump_unreachable_host(capsys):
    """In cluster mode a dead host is reported and skipped — the rest
    of the scrape still merges (single-addr mode stays fatal)."""
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump

    ws = WebService(role="graphd")
    ws.start()
    try:
        rc = metrics_dump.main(["--addrs", f"127.0.0.1:1,{ws.addr}"])
        assert rc == 0
        cap = capsys.readouterr()
        assert "scrape of 127.0.0.1:1 failed" in cap.err
        assert "== merged (1/2 hosts)" in cap.out
    finally:
        ws.stop()


def test_meta_dump_data_dir(tmp_path, capsys):
    from nebula_tpu.exec import QueryEngine
    from nebula_tpu.graphstore.store import GraphStore
    from nebula_tpu.tools import meta_dump

    st = GraphStore(data_dir=str(tmp_path))
    e = QueryEngine(st)
    s = e.new_session()
    for q in ['CREATE SPACE md(partition_num=2, vid_type=INT64)', 'USE md',
              'CREATE TAG t(name string)', 'CREATE EDGE e(w int)',
              'CREATE TAG INDEX i_n ON t(name)',
              'CREATE FULLTEXT TAG INDEX ft_n ON t(name)',
              'ADD LISTENER ELASTICSEARCH "127.0.0.1:9200"',
              'CREATE USER reader WITH PASSWORD "x"',
              'GRANT ROLE USER ON md TO reader']:
        r = e.execute(s, q)
        assert r.ok, f"{q} -> {r.error}"
    st.close()

    assert meta_dump.main(["--data-dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    for needle in ["space `md'", "tag t v", "edge e v", "tag index i_n",
                   "fulltext tag index ft_n", "listener ELASTICSEARCH",
                   "user `reader'", "md:USER"]:
        assert needle in out, (needle, out)


def test_meta_dump_live_cluster(tmp_path, capsys):
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.tools import meta_dump

    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        cl = c.client()
        assert cl.execute("CREATE SPACE lv(partition_num=4, "
                          "replica_factor=1, vid_type=INT64)").error is None
        c.reconcile_storage()
        assert cl.execute("USE lv").error is None
        assert cl.execute("CREATE TAG n(x int)").error is None
        assert meta_dump.main(["--addr", c.meta_addrs[0]]) == 0
        out = capsys.readouterr().out
        assert "space `lv'" in out and "tag n v" in out \
            and "part 0:" in out
    finally:
        c.stop()


def test_metrics_dump_deltas_view(capsys):
    """--deltas (ISSUE 19): per-shard delta fill rows, the
    repin-avoided share and compaction count, scraped from the
    prometheus exposition."""
    from nebula_tpu.cluster.webservice import WebService
    from nebula_tpu.tools import metrics_dump
    from nebula_tpu.utils.stats import stats

    st = stats()
    with st.lock:
        st.labeled_gauges.pop("tpu_shard_delta_edges", None)
    st.gauge("tpu_delta_edges", 30.0)
    st.gauge("tpu_delta_bytes", 4096.0)
    for p in range(4):
        st.gauge_labeled("tpu_shard_delta_edges", {"shard": p},
                         float(10 - p))
    pins0 = st.snapshot().get("tpu_pins", 0)
    avoided0 = st.snapshot().get("tpu_repin_avoided", 0)
    comps0 = st.snapshot().get("tpu_compactions", 0)
    st.inc("tpu_repin_avoided", 3)
    st.inc("tpu_compactions", 1)
    ws = WebService(role="graphd")
    ws.start()
    try:
        rc = metrics_dump.main(["--addr", ws.addr, "--deltas"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "delta plane: 30 rows, 4096 bytes" in out
        assert "shard 0" in out and "shard 3" in out
        assert "delta_rows=10" in out
        assert f"repins avoided: {int(avoided0) + 3} " \
               f"vs pins {int(pins0)}" in out
        assert f"compactions: {int(comps0) + 1}" in out
    finally:
        ws.stop()
        st.gauge("tpu_delta_edges", 0.0)
        st.gauge("tpu_delta_bytes", 0.0)
