"""Graph store + schema + CSR snapshot tests."""
import numpy as np
import pytest

from nebula_tpu.core import NULL, is_null
from nebula_tpu.graphstore import (Catalog, GraphStore, PropDef, PropType,
                                   SchemaError, build_snapshot,
                                   expand_frontier_host, neighbors_of,
                                   stable_vid_hash)


def mk_store(parts=4):
    st = GraphStore()
    st.create_space("test", partition_num=parts, vid_type="FIXED_STRING(32)")
    st.catalog.create_tag("test", "person", [
        PropDef("name", PropType.STRING),
        PropDef("age", PropType.INT64),
    ])
    st.catalog.create_edge("test", "knows", [
        PropDef("since", PropType.INT64),
        PropDef("weight", PropType.DOUBLE),
    ])
    return st


def seed(st):
    people = [("a", "Ann", 30), ("b", "Bob", 25), ("c", "Cat", 41),
              ("d", "Dan", 19), ("e", "Eve", 33)]
    for vid, name, age in people:
        st.insert_vertex("test", vid, "person", {"name": name, "age": age})
    edges = [("a", "b", 2010, 1.0), ("a", "c", 2012, 0.5), ("b", "c", 2015, 2.0),
             ("c", "d", 2018, 1.5), ("d", "e", 2020, 3.0), ("e", "a", 2021, 0.1)]
    for s, d, y, w in edges:
        st.insert_edge("test", s, "knows", d, 0, {"since": y, "weight": w})
    return st


def test_schema_ddl():
    c = Catalog()
    c.create_space("s1", partition_num=2)
    c.create_tag("s1", "t", [PropDef("x", PropType.INT64)])
    with pytest.raises(SchemaError):
        c.create_tag("s1", "t", [])
    c.create_tag("s1", "t", [], if_not_exists=True)
    with pytest.raises(SchemaError):
        c.create_edge("s1", "t", [])  # name conflict with tag
    c.alter_tag("s1", "t", [PropDef("x", PropType.INT64), PropDef("y", PropType.STRING)])
    assert c.get_tag("s1", "t").latest.version == 1
    assert len(c.get_tag("s1", "t").versions) == 2
    c.create_index("s1", "idx_x", "t", ["x"], is_edge=False)
    with pytest.raises(SchemaError):
        c.create_index("s1", "bad", "t", ["nope"], is_edge=False)


def test_defaults_and_nullability():
    st = GraphStore()
    st.create_space("s", partition_num=2)
    st.catalog.create_tag("s", "t", [
        PropDef("a", PropType.INT64, nullable=False, default=7, has_default=True),
        PropDef("b", PropType.STRING, nullable=True),
        PropDef("c", PropType.INT64, nullable=False),
    ])
    with pytest.raises(SchemaError):
        st.insert_vertex("s", "v1", "t", {})  # c not null, no default
    st.insert_vertex("s", "v1", "t", {"c": 1})
    row = st.get_vertex("s", "v1")["t"]
    assert row["a"] == 7 and is_null(row["b"]) and row["c"] == 1
    with pytest.raises(SchemaError):
        st.insert_vertex("s", "v2", "t", {"c": "wrong type"})


def test_insert_and_get_neighbors():
    st = seed(mk_store())
    out = list(st.get_neighbors("test", ["a"], ["knows"], "out"))
    assert [(r[0], r[3]) for r in out] == [("a", "b"), ("a", "c")]
    assert out[0][4]["since"] == 2010
    inn = list(st.get_neighbors("test", ["c"], ["knows"], "in"))
    assert sorted((r[3]) for r in inn) == ["a", "b"]
    assert all(r[5] == -1 for r in inn)
    both = list(st.get_neighbors("test", ["c"], None, "both"))
    assert len(both) == 3  # out: d; in: a, b


def test_delete_vertex_cascades():
    st = seed(mk_store())
    st.delete_vertex("test", "c")
    assert st.get_vertex("test", "c") is None
    assert list(st.get_neighbors("test", ["a"], ["knows"], "out")) == [
        ("a", "knows", 0, "b", {"since": 2010, "weight": 1.0}, 1)]
    assert list(st.get_neighbors("test", ["d"], ["knows"], "in")) == []


def test_update():
    st = seed(mk_store())
    assert st.update_vertex("test", "a", "person", {"age": 31})
    assert st.get_vertex("test", "a")["person"]["age"] == 31
    assert st.update_edge("test", "a", "knows", "b", 0, {"since": 1999})
    assert st.get_edge("test", "a", "knows", "b")["since"] == 1999
    # in-plane mirror also updated
    inn = list(st.get_neighbors("test", ["b"], ["knows"], "in"))
    assert inn[0][4]["since"] == 1999
    assert not st.update_edge("test", "x", "knows", "y", 0, {"since": 1})


def test_dense_ids_encode_partition():
    st = seed(mk_store(parts=4))
    sd = st.space("test")
    for vid, d in sd.vid_to_dense.items():
        assert d % 4 == sd.part_of(vid)
        assert sd.dense_to_vid[d] == vid


def test_stable_hash():
    assert stable_vid_hash("abc") == stable_vid_hash("abc")
    assert stable_vid_hash(42) == 42


def test_csr_snapshot_matches_store():
    st = seed(mk_store(parts=4))
    snap = build_snapshot(st, "test")
    sd = st.space("test")
    blk = snap.block("knows", "out")
    assert blk.total_edges() == 6
    # every vertex's CSR neighbors == store's get_neighbors dsts
    for vid, dense in sd.vid_to_dense.items():
        want = [sd.vid_to_dense[r[3]]
                for r in st.get_neighbors("test", [vid], ["knows"], "out")]
        got = list(neighbors_of(snap, blk, dense))
        assert got == want, (vid, got, want)
    # reversed block
    blk_in = snap.block("knows", "in")
    for vid, dense in sd.vid_to_dense.items():
        want = sorted(sd.vid_to_dense[r[3]]
                      for r in st.get_neighbors("test", [vid], ["knows"], "in"))
        got = sorted(neighbors_of(snap, blk_in, dense))
        assert got == want


def test_csr_props_and_strings():
    st = seed(mk_store(parts=2))
    st.insert_vertex("test", "f", "person", {"name": "Fox", "age": NULL})
    snap = build_snapshot(st, "test")
    tt = snap.tags["person"]
    sd = st.space("test")
    d = sd.vid_to_dense["f"]
    p, li = snap.owner(d), snap.local(d)
    assert tt.present[p, li]
    from nebula_tpu.graphstore import INT_NULL
    assert tt.props["age"][p, li] == INT_NULL  # null sentinel
    code = tt.props["name"][p, li]
    assert snap.pool.decode(int(code)) == "Fox"
    assert snap.pool.lookup("Fox") == code
    assert snap.pool.lookup("NotThere") == -2
    # edge prop column
    blk = snap.block("knows", "out")
    a = sd.vid_to_dense["a"]
    pa, la = snap.owner(a), snap.local(a)
    lo = int(blk.indptr[pa, la])
    assert blk.props["since"][pa, lo] == 2010
    assert blk.props["weight"][pa, lo] == 1.0


def test_expand_frontier_host():
    st = seed(mk_store(parts=4))
    snap = build_snapshot(st, "test")
    sd = st.space("test")
    blk = snap.block("knows", "out")
    f0 = np.array([sd.vid_to_dense["a"]], np.int32)
    f1 = expand_frontier_host(snap, blk, f0)
    assert sorted(sd.dense_to_vid[d] for d in f1) == ["b", "c"]
    f2 = expand_frontier_host(snap, blk, f1)
    assert sorted(sd.dense_to_vid[d] for d in f2) == ["c", "d"]


def test_epoch_bumps():
    st = mk_store()
    e0 = st.space("test").epoch
    st.insert_vertex("test", "z", "person", {"name": "Z", "age": 1})
    assert st.space("test").epoch > e0


def test_scan():
    st = seed(mk_store())
    assert len(list(st.scan_vertices("test"))) == 5
    assert len(list(st.scan_edges("test", "knows"))) == 6
    assert len(list(st.scan_vertices("test", tag="person"))) == 5


def test_repartition_preserves_rows_and_indexes(tmp_path):
    """SUBMIT JOB REPARTITION (the part split/merge task): rows, GO
    results, index lookups, and durability must all survive a 2->8
    re-home, and a cancelled run must leave the space untouched."""
    import threading

    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.graphstore.store import GraphStore

    store = GraphStore(data_dir=str(tmp_path / "db"))
    eng = QueryEngine(store)
    s = eng.new_session()
    for t in ["CREATE SPACE rp(partition_num=2, vid_type=INT64)",
              "USE rp", "CREATE TAG P(a int)", "CREATE EDGE E(w int)",
              "CREATE TAG INDEX pa ON P(a)"]:
        assert eng.execute(s, t).error is None, t
    for v in range(30):
        eng.execute(s, f"INSERT VERTEX P(a) VALUES {v}:({v})")
        eng.execute(s, f"INSERT EDGE E(w) VALUES {v}->{(v + 1) % 30}:({v})")
    rs = eng.execute(s, "GO 2 STEPS FROM 0 OVER E YIELD dst(edge) AS d")
    before = sorted(map(repr, rs.data.rows))

    # cancelled BEFORE the swap: -1, space untouched
    tok = threading.Event()
    tok.set()
    assert store.repartition("rp", 4, cancel=tok) == -1
    assert store.space("rp").num_parts == 2

    rs = eng.execute(s, "SUBMIT JOB REPARTITION 8")
    assert rs.error is None
    jid = rs.data.rows[0][0]
    from nebula_tpu.exec.jobs import job_manager
    assert job_manager(store).wait(jid)     # jobs are async (r4)
    rs = eng.execute(s, f"SHOW JOB {jid}")
    assert rs.data.rows[0][2] == "FINISHED"
    assert store.space("rp").num_parts == 8

    rs = eng.execute(s, "GO 2 STEPS FROM 0 OVER E YIELD dst(edge) AS d")
    assert sorted(map(repr, rs.data.rows)) == before
    rs = eng.execute(s, "LOOKUP ON P WHERE P.a > 25 YIELD id(vertex) AS v")
    assert sorted(r[0] for r in rs.data.rows) == [26, 27, 28, 29]

    # durability: replay reproduces the new layout
    store.close()
    store2 = GraphStore(data_dir=str(tmp_path / "db"))
    eng2 = QueryEngine(store2)
    s2 = eng2.new_session()
    eng2.execute(s2, "USE rp")
    assert store2.space("rp").num_parts == 8
    rs = eng2.execute(s2, "GO 2 STEPS FROM 0 OVER E YIELD dst(edge) AS d")
    assert sorted(map(repr, rs.data.rows)) == before
    store2.close()
