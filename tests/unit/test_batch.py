"""Multi-lane batched device execution (ISSUE 15): batch forming,
per-lane de-mux parity, the one-dispatch-slot contract with the PR 8
shed plane, KILL/deadline lane detach (mid-form and mid-flight), the
SHOW QUERIES Batch column, and UPDATE CONFIGS-updatable flags."""
import random
import threading
import time

import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.schema import PropDef, PropType
from nebula_tpu.graphstore.store import GraphStore
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import WorkCounters, stats, use_work
from nebula_tpu.utils.workload import dispatch_table, live_registry

tpu = pytest.importorskip("nebula_tpu.tpu")
from nebula_tpu.tpu import TpuRuntime, make_mesh          # noqa: E402
from nebula_tpu.tpu.batch import batch_former             # noqa: E402

GO_TMPL = "GO 2 STEPS FROM {seed} OVER E YIELD dst(edge) AS d"


def batched_store(n=60, deg=4):
    rng = random.Random(11)
    st = GraphStore()
    st.create_space("bt", partition_num=4, vid_type="INT64")
    st.catalog.create_tag("bt", "P", [PropDef("x", PropType.INT64)])
    st.catalog.create_edge("bt", "E", [PropDef("w", PropType.INT64)])
    for v in range(n):
        st.insert_vertex("bt", v, "P", {"x": v})
    for v in range(n):
        for _ in range(deg):
            st.insert_edge("bt", v, "E", rng.randrange(n), 0, {"w": v})
    return st


@pytest.fixture(scope="module")
def rt():
    # single-chip mesh: the lane axis is a local_mode program
    return TpuRuntime(make_mesh(1))


@pytest.fixture()
def clean():
    fail.reset()
    batch_former().reset()
    yield
    fail.reset()
    batch_former().reset()
    cfg = get_config()
    with cfg.lock:
        for k in ("batch_max_lanes", "batch_wait_us",
                  "query_timeout_secs", "flight_sample_rate"):
            cfg.dynamic_layer.pop(k, None)


def device_engine(rt, **kw):
    eng = QueryEngine(batched_store(**kw), tpu_runtime=rt)
    s = eng.new_session()
    assert eng.execute(s, "USE bt").error is None
    return eng


@pytest.fixture()
def company():
    """Two dummy live registrations so the batch former's concurrency
    hint is deterministically TRUE regardless of thread arrival order
    (in production the hint comes from real concurrent statements or
    the admission drain burst)."""
    a = live_registry().register(qid=-101, session=0, user="t",
                                 stmt="dummy", kind="Go")
    b = live_registry().register(qid=-102, session=0, user="t",
                                 stmt="dummy", kind="Go")
    yield
    if a is not None:
        live_registry().deregister(-101)
    if b is not None:
        live_registry().deregister(-102)


def _run_stmt(eng, stmt, out, key, errs):
    try:
        s = eng.new_session()
        eng.execute(s, "USE bt")
        wc = WorkCounters()
        with use_work(wc):
            rs = eng.execute(s, stmt)
        out[key] = (rs, wc.as_dict())
    except Exception as ex:  # noqa: BLE001
        errs.append(repr(ex))


def _concurrent(eng, stmts):
    out, errs = {}, []
    ths = [threading.Thread(target=_run_stmt,
                            args=(eng, stmt, out, key, errs),
                            daemon=True)
           for key, stmt in stmts.items()]
    for t in ths:
        t.start()
    for t in ths:
        t.join(60)
    assert not errs, errs[:3]
    return out


def _wait_for(pred, timeout=10.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        got = pred()
        if got:
            return got
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {msg}")


# -- forming + de-mux parity ------------------------------------------------


def test_batched_launch_shares_and_demuxes(rt, clean, company):
    """K compatible concurrent GO statements form ONE multi-lane
    launch; each statement's rows and deterministic WorkCounters equal
    its own solo run (per-lane de-mux through the per-statement
    attribution machinery)."""
    eng = device_engine(rt)
    seeds = [1, 2, 3, 5]
    truth = {}
    for sd in seeds:
        out = {}
        _run_stmt(eng, GO_TMPL.format(seed=sd), out, sd, [])
        rs, wc = out[sd]
        assert rs.error is None, rs.error
        truth[sd] = (sorted(map(repr, rs.data.rows)), wc)
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 300_000})
    s0 = stats().snapshot()
    out = _concurrent(eng, {sd: GO_TMPL.format(seed=sd)
                            for sd in seeds})
    s1 = stats().snapshot()
    for sd in seeds:
        rs, wc = out[sd]
        assert rs.error is None, rs.error
        assert sorted(map(repr, rs.data.rows)) == truth[sd][0], \
            f"seed {sd}: batched rows differ from solo truth"
        assert wc == truth[sd][1], \
            f"seed {sd}: batched work counters differ from solo truth"
    formed = s1.get("tpu_batches_formed", 0) \
        - s0.get("tpu_batches_formed", 0)
    runs = s1.get("tpu_kernel_runs", 0) - s0.get("tpu_kernel_runs", 0)
    assert formed >= 1, "no batch formed under concurrent load"
    # sharing is real: fewer launches than statements (ledger proof)
    assert runs < len(seeds), (runs, len(seeds))


def test_solo_statement_skips_the_window(rt, clean):
    """Batching ON with no concurrent company: the statement takes the
    solo dispatch path — no group, no forming wait, no batch metrics
    (single-query latency unchanged)."""
    eng = device_engine(rt)
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 500_000})
    s0 = stats().snapshot()
    out = {}
    _run_stmt(eng, GO_TMPL.format(seed=7), out, 7, [])
    rs, _ = out[7]
    assert rs.error is None, rs.error
    s1 = stats().snapshot()
    assert s1.get("tpu_batches_formed", 0) == \
        s0.get("tpu_batches_formed", 0)
    assert not batch_former().forming()


def test_batch_form_failpoint_raise_dispatches_solo(rt, clean, company):
    """`tpu:batch_form` armed with raise: enrollment is rejected and
    the statement dispatches SOLO (rows still correct — never host
    fallback, never an error)."""
    eng = device_engine(rt)
    out = {}
    _run_stmt(eng, GO_TMPL.format(seed=9), out, "truth", [])
    truth = sorted(map(repr, out["truth"][0].data.rows))
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 300_000})
    fail.arm("tpu:batch_form", "raise")
    s0 = stats().snapshot()
    out = {}
    _run_stmt(eng, GO_TMPL.format(seed=9), out, 9, [])
    rs, _ = out[9]
    assert rs.error is None, rs.error
    assert sorted(map(repr, rs.data.rows)) == truth
    s1 = stats().snapshot()
    assert s1.get("tpu_batches_formed", 0) == \
        s0.get("tpu_batches_formed", 0)


# -- PR 8 shed interaction: one dispatch-queue slot per batch ---------------


def test_batch_consumes_one_dispatch_slot(rt, clean, company):
    """ISSUE 15 satellite, amended by the ISSUE 19 re-arm fix: with
    the dispatch gate write-held, K batched statements occupy ZERO
    dispatch slots — the non-full forming group keeps re-arming its
    window instead of queueing behind the hold (batching off shows
    depth K), so turning batching on can never increase the
    `tpu_dispatch_queue_cap` shed rate."""
    eng = device_engine(rt)
    seeds = [1, 2, 3]
    # warm: pin + compile outside the gate-held window
    out = {}
    for sd in seeds:
        _run_stmt(eng, GO_TMPL.format(seed=sd), out, sd, [])
        assert out[sd][0].error is None

    def run_held(batching: bool):
        if batching:
            get_config().set_dynamic_many({"batch_max_lanes": 8,
                                           "batch_wait_us": 150_000})
        else:
            get_config().set_dynamic("batch_max_lanes", 0)
        rt._gate.acquire_write()
        depth = None
        try:
            res, errs = {}, []
            ths = [threading.Thread(
                target=_run_stmt,
                args=(eng, GO_TMPL.format(seed=sd), res, sd, errs),
                daemon=True) for sd in seeds]
            r0 = stats().snapshot().get("tpu_batch_gate_rearms", 0)
            for t in ths:
                t.start()
            if batching:
                # the group's window must EXPIRE under the hold at
                # least twice (proof all three enrolled and are
                # re-arming rather than sitting in the dispatch queue)
                _wait_for(lambda: stats().snapshot().get(
                    "tpu_batch_gate_rearms", 0) >= r0 + 2,
                    msg="forming window re-arms behind held gate")
            else:
                _wait_for(lambda: dispatch_table().queued_depth()
                          >= len(seeds),
                          msg=f"queued depth {len(seeds)}")
            # settle: ALL statements are past forming/enqueue before
            # the depth is judged (the batched case must stay at 0)
            time.sleep(0.4)
            depth = dispatch_table().queued_depth()
        finally:
            rt._gate.release_write()
        for t in ths:
            t.join(30)
        assert not errs, errs
        for sd in seeds:
            assert res[sd][0].error is None, res[sd][0].error
        return depth

    assert run_held(batching=False) == len(seeds)
    assert run_held(batching=True) == 0


# -- cancellation detaches one lane -----------------------------------------


def test_kill_mid_form_detaches_lane(rt, clean, company):
    """KILL QUERY of a statement waiting in a forming group evicts
    only that lane: the victim dies promptly (well before the window
    closes), the batchmate completes with correct rows."""
    eng = device_engine(rt)
    out = {}
    _run_stmt(eng, GO_TMPL.format(seed=2), out, "truth", [])
    truth = sorted(map(repr, out["truth"][0].data.rows))
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 3_000_000})
    res, errs = {}, []
    t_victim = threading.Thread(
        target=_run_stmt,
        args=(eng, GO_TMPL.format(seed=1), res, "victim", errs),
        daemon=True)
    t_mate = threading.Thread(
        target=_run_stmt,
        args=(eng, GO_TMPL.format(seed=2), res, "mate", errs),
        daemon=True)
    t_victim.start()
    t_mate.start()
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[3] == GO_TMPL.format(seed=1)), None),
        msg="victim visible")
    _wait_for(lambda: batch_former().forming(), msg="group forming")
    t0 = time.monotonic()
    assert eng.kill_running(sid=row[0], qid=row[1])
    t_victim.join(30)
    killed_after = time.monotonic() - t0
    assert res["victim"][0].error == "ExecutionError: query was killed"
    # the victim left the group long before the 3 s window closed
    assert killed_after < 1.5, killed_after
    t_mate.join(30)
    assert not errs, errs
    assert res["mate"][0].error is None, res["mate"][0].error
    assert sorted(map(repr, res["mate"][0].data.rows)) == truth


def test_kill_mid_flight_discards_only_that_lane(rt, clean, company):
    """KILL QUERY after the batch launched: the victim's lane result
    is discarded at de-mux, the batchmate's rows are exact."""
    eng = device_engine(rt)
    out = {}
    _run_stmt(eng, GO_TMPL.format(seed=3), out, "truth", [])
    truth = sorted(map(repr, out["truth"][0].data.rows))
    get_config().set_dynamic_many({"batch_max_lanes": 2,
                                   "batch_wait_us": 400_000})
    # hold the LAUNCH at the dispatch gate so the kill lands mid-flight
    fail.arm("tpu:dispatch_gate", "delay(0.6)")
    s0 = stats().snapshot()
    res, errs = {}, []
    t_victim = threading.Thread(
        target=_run_stmt,
        args=(eng, GO_TMPL.format(seed=5), res, "victim", errs),
        daemon=True)
    t_mate = threading.Thread(
        target=_run_stmt,
        args=(eng, GO_TMPL.format(seed=3), res, "mate", errs),
        daemon=True)
    t_victim.start()
    t_mate.start()
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[3] == GO_TMPL.format(seed=5)), None),
        msg="victim visible")
    # a 2-lane group fills and claims its launch immediately; the gate
    # failpoint then holds the LAUNCHED batch queued in the dispatch
    # table — the kill below provably lands mid-flight
    _wait_for(lambda: dispatch_table().queued_depth() >= 1,
              msg="batched launch queued at the gate")
    assert eng.kill_running(sid=row[0], qid=row[1])
    t_victim.join(30)
    t_mate.join(30)
    fail.reset()
    assert not errs, errs
    assert res["victim"][0].error == "ExecutionError: query was killed"
    assert res["mate"][0].error is None, res["mate"][0].error
    assert sorted(map(repr, res["mate"][0].data.rows)) == truth
    s1 = stats().snapshot()
    assert s1.get("tpu_batches_formed", 0) \
        - s0.get("tpu_batches_formed", 0) == 1


def test_deadline_mid_form_evicts_lane(rt, clean, company):
    """A statement whose deadline budget expires while batch-forming
    fails E_QUERY_TIMEOUT without a launch (the lane withdrew)."""
    eng = device_engine(rt)
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 5_000_000,
                                   "query_timeout_secs": 0.4})
    s0 = stats().snapshot()
    out = {}
    _run_stmt(eng, GO_TMPL.format(seed=4), out, 4, [])
    rs, _ = out[4]
    assert rs.error is not None and "E_QUERY_TIMEOUT" in rs.error, rs
    s1 = stats().snapshot()
    assert s1.get("tpu_batches_formed", 0) == \
        s0.get("tpu_batches_formed", 0)
    # the all-withdrawn group was REMOVED from the forming map — a
    # later compatible statement opens a fresh group instead of
    # joining an expired husk (code-review regression)
    assert not batch_former().forming()


# -- SHOW QUERIES surface ---------------------------------------------------


def test_show_queries_batch_column(rt, clean, company):
    """An enrolled statement shows BatchId/lane in SHOW QUERIES while
    forming/in flight; the column clears after completion."""
    eng = device_engine(rt)
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 1_500_000})
    res, errs = {}, []
    t = threading.Thread(
        target=_run_stmt,
        args=(eng, GO_TMPL.format(seed=6), res, 6, errs), daemon=True)
    t.start()

    def batched_row():
        r = next((r for r in eng.list_running_queries()
                  if r[3] == GO_TMPL.format(seed=6)), None)
        return r if r is not None and r[13] else None

    row = _wait_for(batched_row, msg="Batch column populated")
    bid, lane = row[13].split("/")
    assert int(bid) >= 1 and int(lane) >= 0
    # the statement surface carries the same column
    s2 = eng.new_session()
    rs = eng.execute(s2, "SHOW QUERIES")
    assert rs.ok
    assert rs.data.column_names[-3:] == ["Batch", "Fingerprint",
                                         "GraphAddr"]
    srow = next(r for r in rs.data.rows
                if r[3] == GO_TMPL.format(seed=6))
    assert srow[13] == row[13]
    t.join(30)
    assert not errs, errs
    assert res[6][0].error is None, res[6][0].error
    assert not any(r[13] for r in eng.list_running_queries())


# -- flags ------------------------------------------------------------------


def test_batch_flags_update_configs(rt, clean):
    """batch_max_lanes / batch_wait_us are runtime-updatable via the
    UPDATE CONFIGS multi-key path and read LIVE by the former."""
    eng = device_engine(rt)
    s = eng.new_session()
    rs = eng.execute(s, "UPDATE CONFIGS batch_max_lanes=4, "
                        "batch_wait_us=123")
    assert rs.error is None, rs.error
    assert get_config().get("batch_max_lanes") == 4
    assert get_config().get("batch_wait_us") == 123
    assert batch_former().max_lanes() == 4
    assert batch_former().enabled()
    rs = eng.execute(s, "UPDATE CONFIGS batch_max_lanes=0")
    assert rs.error is None, rs.error
    assert not batch_former().enabled()

# -- mesh composition (ISSUE 17) --------------------------------------------


def test_repin_to_wider_mesh_mid_form_splits_group(clean, company):
    """The compatibility key carries the mesh shape + epoch: statements
    enrolled BEFORE a set_mesh re-shard and statements enrolled AFTER
    it land in DIFFERENT groups (two 2-lane launches, never one merged
    4-lane launch spanning two launch grids), and the pre-repin group —
    whose snapshot the re-pin retired — still yields correct rows via
    the TpuUnavailable host fallback."""
    from nebula_tpu.tpu import make_mesh2

    rt = TpuRuntime(make_mesh(1))        # private runtime: set_mesh below
    eng = device_engine(rt)
    seeds = [1, 2, 3, 5]
    truth = {}
    for sd in seeds:
        out = {}
        _run_stmt(eng, GO_TMPL.format(seed=sd), out, sd, [])
        rs, _ = out[sd]
        assert rs.error is None, rs.error
        truth[sd] = sorted(map(repr, rs.data.rows))

    # max_lanes=3: a pair never fills a group, so the pre-repin pair
    # keeps FORMING for the whole window while set_mesh runs
    get_config().set_dynamic_many({"batch_max_lanes": 3,
                                   "batch_wait_us": 500_000})
    s0 = stats().snapshot()
    out, errs = {}, []
    pre = [threading.Thread(target=_run_stmt,
                            args=(eng, GO_TMPL.format(seed=sd), out, sd,
                                  errs), daemon=True)
           for sd in seeds[:2]]
    for t in pre:
        t.start()
    # wait until both pre-repin statements are enrolled in one group
    _wait_for(lambda: any(len(g.members) == 2
                          for g in batch_former()._groups.values()),
              msg="pre-repin group of 2")
    # re-shard 1 -> 4 parts mid-form: the enrolled group's snapshot is
    # retired (donated buffers) and the mesh epoch bumps
    rt.set_mesh(make_mesh(4))
    post = [threading.Thread(target=_run_stmt,
                             args=(eng, GO_TMPL.format(seed=sd), out, sd,
                                   errs), daemon=True)
            for sd in seeds[2:]]
    for t in post:
        t.start()
    for t in pre + post:
        t.join(60)
    assert not errs, errs[:3]
    s1 = stats().snapshot()
    for sd in seeds:
        rs, _ = out[sd]
        assert rs.error is None, rs.error
        assert sorted(map(repr, rs.data.rows)) == truth[sd], \
            f"seed {sd}: rows wrong across the mid-form re-shard"
    formed = s1.get("tpu_batches_formed", 0) \
        - s0.get("tpu_batches_formed", 0)
    # without the mesh-shape/epoch key the post pair would JOIN the
    # still-forming pre group (3rd member fills it -> one merged
    # 3-lane launch, formed == 1); the epoch key keeps the grids apart
    # as two 2-lane groups
    assert formed == 2, f"expected two 2-lane groups, saw {formed}"


def test_forming_window_rearms_behind_write_gate(rt, clean, company):
    """ISSUE 19 satellite: with the dispatch gate write-held (a repin
    or compaction swap in flight), a partially-formed group whose
    forming window expires RE-ARMS the window instead of sealing and
    queueing a fully-FORMED batch behind the gate with its
    batch_wait_us already spent.  While the hold lasts the group keeps
    re-arming (`tpu_batch_gate_rearms` grows, `tpu_batches_formed`
    stays flat); on release the group launches once, fully formed."""
    eng = device_engine(rt)
    out = {}
    for sd in (1, 2):       # warm: pin + compile outside the hold
        _run_stmt(eng, GO_TMPL.format(seed=sd), out, sd, [])
        assert out[sd][0].error is None
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 20_000})
    r0 = stats().snapshot().get("tpu_batch_gate_rearms", 0)
    f0 = stats().snapshot().get("tpu_batches_formed", 0)
    res, errs = {}, []
    ths = [threading.Thread(target=_run_stmt,
                            args=(eng, GO_TMPL.format(seed=sd),
                                  res, sd, errs),
                            daemon=True) for sd in (1, 2)]
    rt._gate.acquire_write()
    try:
        for t in ths:
            t.start()
        # several expiries come and go under the hold — each one
        # re-arms instead of sealing the 2-lane group
        _wait_for(lambda: stats().snapshot().get(
            "tpu_batch_gate_rearms", 0) >= r0 + 3,
            msg="forming window re-arms behind the write gate")
        assert stats().snapshot().get("tpu_batches_formed", 0) == f0, \
            "group sealed while the dispatch gate was write-held"
    finally:
        rt._gate.release_write()
    for t in ths:
        t.join(30)
    assert not errs, errs[:3]
    for sd in (1, 2):
        assert res[sd][0].error is None, res[sd][0].error
    # the held statements still launched as ONE shared batch
    assert stats().snapshot().get("tpu_batches_formed", 0) == f0 + 1
