"""ISSUE 11 result cache: read-only statements keyed like the plan
cache plus the engine's write epoch — DDL and mutating statements
invalidate structurally, a dedup-window-replayed write (PR 5 retry)
bumps exactly once, and cached rows are byte-identical to uncached
execution (the entry IS the wire form)."""
import json

import pytest

from nebula_tpu.exec.engine import quick_engine
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.stats import stats


def _counts():
    snap = stats().snapshot()
    return (snap.get("result_cache_hits", 0),
            snap.get("result_cache_misses", 0),
            snap.get("result_cache_invalidations", 0))


def _wire_bytes(data) -> bytes:
    """Canonical byte form of a result for identity checks (buffers
    hex-encoded so columnar blobs compare by content)."""
    from nebula_tpu.core.wire import to_wire

    def default(o):
        if isinstance(o, (bytes, bytearray, memoryview)):
            return bytes(o).hex()
        raise TypeError(type(o).__name__)
    return json.dumps(to_wire(data), sort_keys=True,
                      default=default).encode()


@pytest.fixture()
def eng_sess():
    get_config().set_dynamic("result_cache_size", 64)
    eng, s = quick_engine()
    for q in ("CREATE SPACE rc(partition_num=2, vid_type=INT64)",
              "USE rc", "CREATE TAG Person(age int)",
              "CREATE EDGE KNOWS(w int)"):
        r = eng.execute(s, q)
        assert r.error is None, (q, r.error)
    r = eng.execute(s, "INSERT VERTEX Person(age) VALUES "
                       "1:(30), 2:(25), 3:(41)")
    assert r.error is None, r.error
    r = eng.execute(s, "INSERT EDGE KNOWS(w) VALUES 1->2:(5), 2->3:(50)")
    assert r.error is None, r.error
    yield eng, s
    get_config().dynamic_layer.pop("result_cache_size", None)


def test_hit_skips_execution_entirely(eng_sess, monkeypatch):
    eng, s = eng_sess
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d, KNOWS.w AS w"
    r1 = eng.execute(s, q)
    assert r1.error is None
    h0, m0, _ = _counts()

    # a result-cache hit must not parse, plan OR schedule anything
    import nebula_tpu.exec.engine as E

    def bomb(*a, **kw):
        raise AssertionError("executed on a result-cache hit")

    monkeypatch.setattr(E, "parse", bomb)
    monkeypatch.setattr(eng.scheduler, "run", bomb)
    r2 = eng.execute(s, q)
    h1, m1, _ = _counts()
    assert r2.error is None
    assert h1 == h0 + 1 and m1 == m0
    assert r2.data.rows == r1.data.rows
    assert r2.data.column_names == r1.data.column_names


def test_rows_byte_identical_to_uncached(eng_sess):
    eng, s = eng_sess
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d, KNOWS.w AS w"
    r1 = eng.execute(s, q)        # uncached execution (the put)
    r2 = eng.execute(s, q)        # cache hit
    assert r2.comment == "served from result cache"
    assert _wire_bytes(r2.data) == _wire_bytes(r1.data)


def test_write_invalidates(eng_sess):
    eng, s = eng_sess
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d"
    assert eng.execute(s, q).error is None
    h0, _, inv0 = _counts()
    ep0 = eng.qctx.write_epoch
    r = eng.execute(s, "INSERT EDGE KNOWS(w) VALUES 1->3:(9)")
    assert r.error is None
    assert eng.qctx.write_epoch == ep0 + 1
    _, _, inv1 = _counts()
    assert inv1 == inv0 + 1, "write did not invalidate the cache"
    r = eng.execute(s, q)          # must MISS and see the new edge
    h1, _, _ = _counts()
    assert h1 == h0
    assert [3] in r.data.rows
    # and the fresh entry hits again
    eng.execute(s, q)
    h2, _, _ = _counts()
    assert h2 == h1 + 1


def test_ddl_invalidates(eng_sess):
    eng, s = eng_sess
    q = "FETCH PROP ON Person 1 YIELD Person.age AS a"
    eng.execute(s, q)
    eng.execute(s, q)
    h0, _, _ = _counts()
    r = eng.execute(s, "ALTER TAG Person ADD (name string)")
    assert r.error is None
    eng.execute(s, q)              # stale result unreachable: replan+rerun
    h1, _, _ = _counts()
    assert h1 == h0, "stale result served after DDL"


def test_reads_and_control_statements_do_not_bump(eng_sess):
    eng, s = eng_sess
    ep0 = eng.qctx.write_epoch
    for q in ("GO FROM 1 OVER KNOWS YIELD dst(edge) AS d",
              "SHOW TAGS", "DESCRIBE TAG Person", "YIELD 1 AS x"):
        r = eng.execute(s, q)
        assert r.error is None, (q, r.error)
    assert eng.qctx.write_epoch == ep0, \
        "read/control statements must not bump the write epoch"


def test_cache_is_per_user(eng_sess):
    """A hit never re-runs the permission check (there is no parsed
    stmt to check), so entries must be unreachable across users — the
    user is part of the key."""
    eng, s = eng_sess
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d"
    eng.execute(s, q)
    h0, m0, _ = _counts()
    s2 = eng.new_session(user="carol")
    s2.space = s.space
    r = eng.execute(s2, q)
    assert r.error is None
    h1, m1, _ = _counts()
    assert h1 == h0, "another user's cached rows were served"
    assert m1 == m0 + 1
    # same user, same text: now a hit
    eng.execute(s2, q)
    h2, _, _ = _counts()
    assert h2 == h1 + 1


def test_failed_mutating_statement_still_invalidates(eng_sess):
    """A failed multi-part write may have committed SOME parts (the
    fan-out is not atomic) — the epoch bumps on any mutating attempt,
    success or failure."""
    eng, s = eng_sess
    ep0 = eng.qctx.write_epoch
    r = eng.execute(s, "INSERT VERTEX Nope(x) VALUES 1:(1)")
    assert r.error is not None
    assert eng.qctx.write_epoch == ep0 + 1


def test_disabled_by_default_flag(eng_sess):
    eng, s = eng_sess
    get_config().set_dynamic("result_cache_size", 0)
    try:
        q = "GO FROM 2 OVER KNOWS YIELD dst(edge) AS d"
        h0, _, _ = _counts()
        eng.execute(s, q)
        eng.execute(s, q)
        h1, _, _ = _counts()
        assert h1 == h0
        assert len(eng.result_cache) == 0
    finally:
        get_config().set_dynamic("result_cache_size", 64)


def test_profile_and_vars_never_cached(eng_sess):
    eng, s = eng_sess
    n0 = len(eng.result_cache)
    assert eng.execute(
        s, "PROFILE GO FROM 1 OVER KNOWS YIELD dst(edge)").error is None
    assert eng.execute(
        s, "EXPLAIN GO FROM 1 OVER KNOWS YIELD dst(edge)").error is None
    assert len(eng.result_cache) == n0
    # $var session state bypasses both caches
    r = eng.execute(s, "$v = GO FROM 1 OVER KNOWS YIELD dst(edge) AS d; "
                       "GO FROM $v.d OVER KNOWS YIELD dst(edge) AS d2")
    assert r.error is None
    h0, _, _ = _counts()
    q = "GO FROM 2 OVER KNOWS YIELD dst(edge) AS d"
    eng.execute(s, q)
    eng.execute(s, q)
    h1, _, _ = _counts()
    assert h1 == h0, "cached despite live $var session state"


# -- cluster: dedup-replayed write bumps exactly once; outage survival ------


@pytest.mark.slow
def test_dedup_replayed_write_bumps_epoch_once(tmp_path):
    """A PR 5 reply-loss retry acks ONE statement through the dedup
    window — the result cache must see exactly one invalidation, not
    one per internal re-send."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.cluster.rpc import reset_breakers
    from nebula_tpu.utils.failpoints import fail
    fail.reset()
    reset_breakers()
    c = LocalCluster(n_meta=1, n_storage=3, n_graph=1,
                     data_dir=str(tmp_path))
    get_config().set_dynamic("result_cache_size", 32)
    try:
        cl = c.client()
        assert cl.execute("CREATE SPACE dz(partition_num=1, "
                          "replica_factor=3, vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ("USE dz", "CREATE TAG P(x int)",
                  "INSERT VERTEX P(x) VALUES 1:(1)"):
            r = cl.execute(q)
            assert r.error is None, (q, r.error)
        eng = c.graphds[0].engine
        # populate the cache so the invalidation counter can move
        q = "FETCH PROP ON P 1 YIELD P.x AS x"
        assert cl.execute(q).error is None

        state = {"fired": False}

        def decide(idx, k):
            if state["fired"] or k != "storage.write|ok":
                return None
            state["fired"] = True
            return ("raise", "reply dropped")
        fail.arm_callable("rpc:server_reply", decide)
        ep0 = eng.qctx.write_epoch
        inv0 = stats().snapshot().get("result_cache_invalidations", 0)
        r = cl.execute("INSERT VERTEX P(x) VALUES 2:(2)")
        fail.disarm("rpc:server_reply")
        assert r.error is None, r.error
        assert state["fired"], "reply-loss failpoint never fired"
        snap = stats().snapshot()
        dedup = snap.get("storage_write_dedup_hits", 0) + \
            snap.get("storage_write_dedup_apply_skips", 0)
        assert dedup >= 1, "re-send was not deduplicated"
        assert eng.qctx.write_epoch == ep0 + 1, \
            "dedup-replayed write must bump the epoch exactly once"
        inv1 = stats().snapshot().get("result_cache_invalidations", 0)
        assert inv1 == inv0 + 1
    finally:
        fail.reset()
        get_config().dynamic_layer.pop("result_cache_size", None)
        c.stop()


@pytest.mark.slow
def test_cached_hot_read_survives_storage_outage(tmp_path):
    """The headline scenario: within an epoch, a hot repeated read
    keeps answering from graphd memory even with EVERY storaged down."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.cluster.rpc import reset_breakers
    from nebula_tpu.utils.failpoints import fail
    fail.reset()
    reset_breakers()
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    get_config().set_dynamic("result_cache_size", 32)
    try:
        cl = c.client()
        assert cl.execute("CREATE SPACE oz(partition_num=1, "
                          "vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ("USE oz", "CREATE TAG P(x int)",
                  "INSERT VERTEX P(x) VALUES 1:(42)"):
            r = cl.execute(q)
            assert r.error is None, (q, r.error)
        q = "FETCH PROP ON P 1 YIELD P.x AS x"
        r1 = cl.execute(q)
        assert r1.error is None and r1.data.rows == [[42]]
        c.stop_storaged(0)             # total storage unavailability
        r2 = cl.execute(q)
        assert r2.error is None and r2.data.rows == [[42]], \
            f"hot read died with storage: {r2.error}"
        # a DIFFERENT read (cache miss) must fail — the cache serves
        # exactly what it holds, it is not a stale-data oracle
        r3 = cl.execute("FETCH PROP ON P 9 YIELD P.x AS x")
        assert r3.error is not None
    finally:
        fail.reset()
        reset_breakers()
        get_config().dynamic_layer.pop("result_cache_size", None)
        c.stop()
