"""Cluster-mode integration: a full metad+storaged×2+graphd cluster in
one process over real localhost sockets, driven through GraphClient —
the MockCluster strategy of SURVEY §4."""
import pytest

from nebula_tpu.cluster.launcher import LocalCluster


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def conn(cluster):
    client = cluster.client()

    def run(q, expect_ok=True):
        rs = client.execute(q)
        if expect_ok:
            assert rs.error is None, f"{q} -> {rs.error}"
        return rs

    run("CREATE SPACE cs(partition_num=4, replica_factor=1, vid_type=INT64)")
    cluster.reconcile_storage()
    run("USE cs")
    run("CREATE TAG Person(name string, age int)")
    run("CREATE EDGE KNOWS(w int)")
    run("CREATE TAG INDEX i_person_age ON Person(age)")
    run('INSERT VERTEX Person(name, age) VALUES '
        '1:("ann",30), 2:("bob",25), 3:("cid",41), 4:("dee",19)')
    run("INSERT EDGE KNOWS(w) VALUES 1->2:(5), 2->3:(50), 3->4:(9), "
        "1->3:(80), 4->1:(7)")
    return run


def test_cluster_go(conn):
    rs = conn("GO FROM 1 OVER KNOWS YIELD dst(edge) AS d, KNOWS.w AS w")
    assert sorted(map(tuple, rs.data.rows)) == [(2, 5), (3, 80)]


def test_cluster_multi_hop_filter(conn):
    rs = conn("GO 2 STEPS FROM 1 OVER KNOWS WHERE KNOWS.w > 8 "
              "YIELD src(edge), dst(edge), KNOWS.w")
    assert sorted(map(tuple, rs.data.rows)) == [(2, 3, 50), (3, 4, 9)]


def test_cluster_fetch_and_lookup(conn):
    rs = conn("FETCH PROP ON Person 3 YIELD Person.name, Person.age")
    assert rs.data.rows == [["cid", 41]]
    rs = conn("LOOKUP ON Person WHERE Person.age > 24 YIELD Person.name")
    assert sorted(r[0] for r in rs.data.rows) == ["ann", "bob", "cid"]


def test_cluster_match(conn):
    rs = conn("MATCH (a:Person)-[e:KNOWS]->(b) WHERE e.w >= 50 "
              "RETURN a.Person.name, b.Person.name ORDER BY a.Person.name")
    assert rs.data.rows == [["ann", "cid"], ["bob", "cid"]]


def test_cluster_update_delete(conn):
    conn("UPDATE VERTEX ON Person 4 SET age = 20")
    rs = conn("FETCH PROP ON Person 4 YIELD Person.age")
    assert rs.data.rows == [[20]]
    conn("DELETE EDGE KNOWS 4->1")
    rs = conn("GO FROM 4 OVER KNOWS YIELD dst(edge)")
    assert rs.data.rows == []
    # reverse plane is consistent too
    rs = conn("GO FROM 1 OVER KNOWS REVERSELY YIELD src(edge)")
    assert rs.data.rows == []


def test_cluster_sessions_and_hosts(cluster, conn):
    hosts = cluster.meta_clients[0].list_hosts()
    roles = sorted(h["role"] for h in hosts if h["alive"])
    assert roles == ["graph", "storage", "storage"]
    sess = cluster.meta_clients[0].list_sessions()
    assert any(s["user"] == "root" for s in sess)


def test_cluster_data_is_sharded(cluster, conn):
    """Both storageds hold some parts; the union serves the space."""
    per_host = [sum(p.edge_count()
                    for (sid, pid), rp in ss.parts.items()
                    for p in [ss.store.space("cs").parts[pid]])
                for ss in cluster.storageds]
    assert all(n > 0 for n in per_host), per_host


def test_cluster_second_client_shares_state(cluster):
    c2 = cluster.client()
    rs = c2.execute("USE cs")
    assert rs.error is None
    rs = c2.execute("GO FROM 2 OVER KNOWS YIELD dst(edge)")
    assert rs.data.rows == [[3]]
    c2.close()


def test_toss_chain_resume(cluster, conn):
    """A graphd that dies between the two TOSS halves leaves a journal
    entry on the out-half part; the part leader's resume loop re-drives
    the in-half so the reverse plane converges."""
    import time

    from nebula_tpu.core.wire import to_wire

    # simulate the orphaned chain: propose chain_mark + out-half to the
    # src part directly (what dstore does first), then DON'T send the
    # in-half or the chain_done — exactly the crash window.
    from nebula_tpu.cluster.storage_client import StorageClient
    sc = StorageClient(cluster.meta_clients[0])
    row = {"w": 99}
    src, dst = 2, 4
    src_pid = sc.part_of("cs", src)
    dst_pid = sc.part_of("cs", dst)
    cmd = ("batch", [
        ["chain_mark", src_pid, "orphan-1", dst_pid,
         ["edge_half", src, "KNOWS", dst, 0, row, "in"], time.time() - 10],
        ["edge_half", src, "KNOWS", dst, 0, row, "out"],
    ])
    sc._call_part("cs", src_pid, "storage.write",
                  {"cmds": [to_wire(list(cmd))]})

    # out-plane sees the edge immediately; in-plane only after resume
    rs = conn("GO FROM 2 OVER KNOWS YIELD dst(edge), KNOWS.w")
    assert [4, 99] in rs.data.rows

    deadline = time.time() + 10
    while time.time() < deadline:
        rs = conn("GO FROM 4 OVER KNOWS REVERSELY YIELD src(edge), KNOWS.w")
        if [2, 99] in rs.data.rows:
            break
        time.sleep(0.3)
    assert [2, 99] in rs.data.rows, "resume loop never drove the in-half"

    # journal entry retired on every replica of the src part
    def journals():
        out = []
        for ss in cluster.storageds:
            sid = ss.meta.catalog.get_space("cs").space_id
            if (sid, src_pid) in ss.parts:
                out.append(ss.store.pending_chains("cs", src_pid))
        return out

    deadline = time.time() + 8
    while time.time() < deadline and \
            any("orphan-1" in d for d in journals()):
        time.sleep(0.2)
    assert all("orphan-1" not in d for d in journals()), journals()


def test_leader_lease_blocks_minority_reads(tmp_path):
    """A deposed leader that lost quorum contact must refuse reads."""
    import time

    from nebula_tpu.cluster.raft import LoopbackTransport, RaftPart

    tr = LoopbackTransport()
    nodes = {}
    for nid in ("a", "b", "c"):
        nodes[nid] = RaftPart("lease", nid, ["a", "b", "c"], tr,
                              str(tmp_path / nid), apply_cb=lambda i, d: None,
                              wal_sync=False)
    for n in nodes.values():
        n.start()
    deadline = time.time() + 5
    leader = None
    while time.time() < deadline and leader is None:
        leader = next((n for n in nodes.values() if n.is_leader()), None)
        time.sleep(0.05)
    assert leader is not None
    # settled leader with quorum heartbeats → lease held
    time.sleep(0.3)
    assert leader.has_lease()
    # cut the leader off from both followers: lease must lapse even
    # while it still believes it is leader
    others = [n for n in nodes.values() if n is not leader]
    tr.partition(leader.node_id, others[0].node_id)
    tr.partition(leader.node_id, others[1].node_id)
    deadline = time.time() + 5
    while time.time() < deadline and leader.has_lease():
        time.sleep(0.05)
    assert not leader.has_lease()
    for n in nodes.values():
        n.stop()


def test_storage_side_filter_pushdown(tmp_path):
    """A pushable WHERE executes inside storaged: only surviving rows
    cross the RPC (SURVEY §2 row 12; VERDICT r1 missing #7)."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.utils.stats import stats
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        rs = client.execute(
            "CREATE SPACE pd(partition_num=4, vid_type=INT64)")
        assert rs.error is None, rs.error
        c.reconcile_storage()
        for q in ["USE pd", "CREATE TAG n(x int)", "CREATE EDGE rel(w int)"]:
            assert client.execute(q).error is None
        assert client.execute(
            "INSERT VERTEX n(x) VALUES " +
            ", ".join(f"{i}:({i})" for i in range(60))).error is None
        assert client.execute(
            "INSERT EDGE rel(w) VALUES " +
            ", ".join(f"1->{i}:({i})" for i in range(2, 52))).error is None

        before = stats().snapshot()
        rs = client.execute(
            "GO FROM 1 OVER rel WHERE rel.w >= 45 YIELD dst(edge) AS d")
        assert rs.error is None, rs.error
        assert sorted(r[0] for r in rs.data.rows) == list(range(45, 52))
        after = stats().snapshot()
        scanned = after.get("storage_pushdown_scanned", 0) \
            - before.get("storage_pushdown_scanned", 0)
        shipped = after.get("storage_pushdown_shipped", 0) \
            - before.get("storage_pushdown_shipped", 0)
        assert scanned == 50, (scanned, shipped)
        assert shipped == 7, (scanned, shipped)

        # non-pushable predicates still work (graphd-side re-check)
        rs = client.execute(
            "GO FROM 1 OVER rel WHERE rel.w >= 45 AND $$.n.x < 48 "
            "YIELD dst(edge) AS d")
        assert rs.error is None, rs.error
        assert sorted(r[0] for r in rs.data.rows) == [45, 46, 47]
    finally:
        c.stop()


def test_pushdown_string_filter_roundtrip(tmp_path):
    """String predicates with quotes/backslashes survive the text wire
    format of pushed-down filters."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        assert client.execute(
            "CREATE SPACE ps(partition_num=2, vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ["USE ps", "CREATE TAG n(x int)",
                  "CREATE EDGE rel(tag string)"]:
            assert client.execute(q).error is None
        assert client.execute(
            "INSERT VERTEX n(x) VALUES 1:(1), 2:(2), 3:(3), 4:(4)"
        ).error is None
        assert client.execute(
            'INSERT EDGE rel(tag) VALUES 1->2:("a\\"b"), 1->3:("a\\\\nb"), '
            '1->4:("plain")').error is None
        rs = client.execute(
            'GO FROM 1 OVER rel WHERE rel.tag == "a\\"b" '
            'YIELD dst(edge) AS d')
        assert rs.error is None, rs.error
        assert [r[0] for r in rs.data.rows] == [2]
        rs = client.execute(
            'GO FROM 1 OVER rel WHERE rel.tag == "a\\\\nb" '
            'YIELD dst(edge) AS d')
        assert rs.error is None, rs.error
        assert [r[0] for r in rs.data.rows] == [3]
    finally:
        c.stop()


def test_zones_and_id_allocation(tmp_path):
    """Placement zones (SURVEY §2 row 17): replicas of a part land in
    distinct zones; metad allocates cluster-unique id segments."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=4, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        addrs = [s.addr for s in c.storage_servers]
        rs = client.execute(
            f'ADD HOSTS "{addrs[0]}", "{addrs[1]}" INTO ZONE east')
        assert rs.error is None, rs.error
        rs = client.execute(
            f'ADD HOSTS "{addrs[2]}", "{addrs[3]}" INTO ZONE west')
        assert rs.error is None, rs.error
        rs = client.execute("SHOW ZONES")
        assert rs.error is None
        assert sorted({r[0] for r in rs.data.rows}) == ["east", "west"]
        assert len(rs.data.rows) == 4

        rs = client.execute(
            "CREATE SPACE zoned(partition_num=6, replica_factor=2, "
            "vid_type=INT64)")
        assert rs.error is None, rs.error
        meta = c.graphds[0].meta
        meta.refresh(force=True)
        east, west = set(addrs[:2]), set(addrs[2:])
        for reps in meta.parts_of("zoned"):
            zones_hit = {("east" if r in east else "west") for r in reps}
            assert len(zones_hit) == 2, reps   # one replica per zone

        # moving a host between zones removes it from the old one
        rs = client.execute(f'ADD HOSTS "{addrs[0]}" INTO ZONE west')
        assert rs.error is None
        zones = meta.list_zones()
        assert addrs[0] in zones["west"] and addrs[0] not in zones["east"]

        # id allocation: monotonic, disjoint segments
        a = meta.allocate_ids(10)
        b = meta.allocate_ids(5)
        c2 = meta.allocate_ids(1)
        assert a + 10 <= b and b + 5 <= c2

        rs = client.execute("DROP ZONE east")
        assert rs.error is None
        rs = client.execute("SHOW ZONES")
        assert sorted({r[0] for r in rs.data.rows}) == ["west"]

        # zone admin verbs (round 4): DESC, RENAME, MERGE
        # east was dropped while holding addrs[1], so west holds the rest
        west_set = {addrs[0], addrs[2], addrs[3]}
        rs = client.execute("DESC ZONE west")
        assert rs.error is None
        assert {r[0] for r in rs.data.rows} == west_set
        rs = client.execute("RENAME ZONE west TO coast")
        assert rs.error is None
        zones = meta.list_zones()
        assert "coast" in zones and "west" not in zones
        rs = client.execute("RENAME ZONE nope TO x")
        assert rs.error is not None
        rs = client.execute(
            f'ADD HOSTS "{addrs[0]}" INTO ZONE solo')
        assert rs.error is None, rs.error
        rs = client.execute("MERGE ZONE solo, coast INTO merged")
        assert rs.error is None, rs.error
        zones = meta.list_zones()
        assert set(zones) == {"merged"}
        assert set(zones["merged"]) == west_set

        # DIVIDE ZONE: host lists must partition the source exactly;
        # reference spellings (quoted zone names, "host":port literals)
        rs = client.execute(
            f'DIVIDE ZONE "merged" INTO "m1" ("{addrs[0]}") '
            f'"m2" ("{addrs[2]}", "{addrs[3]}")')
        assert rs.error is None, rs.error
        zones = meta.list_zones()
        assert set(zones) == {"m1", "m2"}
        assert set(zones["m2"]) == {addrs[2], addrs[3]}
        rs = client.execute(
            f'DIVIDE ZONE "m2" INTO "x" ("{addrs[2]}") "y" ("{addrs[0]}")')
        assert rs.error is not None and "partition" in rs.error
        # ADD HOSTS with no zone clause registers into "default",
        # "host":port two-token spelling included
        host, port = addrs[1].rsplit(":", 1)
        rs = client.execute(f'ADD HOSTS "{host}":{port}')
        assert rs.error is None, rs.error
        assert addrs[1] in meta.list_zones().get("default", [])

        # DROP HOSTS refuses while replicas live on the host
        rs = client.execute(f'DROP HOSTS "{addrs[0]}"')
        assert rs.error is not None and "BALANCE" in rs.error, rs.error
    finally:
        c.stop()


def test_zone_leader_spread_and_host_validation(tmp_path):
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=4, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        addrs = [s.addr for s in c.storage_servers]
        client.execute(f'ADD HOSTS "{addrs[0]}", "{addrs[1]}" INTO ZONE a')
        client.execute(f'ADD HOSTS "{addrs[2]}", "{addrs[3]}" INTO ZONE b')
        rs = client.execute(
            "CREATE SPACE zl(partition_num=8, replica_factor=2, "
            "vid_type=INT64)")
        assert rs.error is None, rs.error
        meta = c.graphds[0].meta
        meta.refresh(force=True)
        leaders = {reps[0] for reps in meta.parts_of("zl")}
        assert len(leaders) == 4, leaders   # every host leads something

        rs = client.execute('ADD HOSTS "noport" INTO ZONE a')
        assert rs.error is not None and "bad host" in rs.error
        assert client.execute("SHOW ZONES").error is None
    finally:
        c.stop()


def test_cluster_device_plane(tmp_path):
    """The cluster graphd's TpuRuntime pins a DistributedStore space via
    per-part storage.export_part bulk CSR exports (the north-star
    storage addition) and serves GO / MATCH / GET SUBGRAPH from the
    device with rows identical to the cluster host path."""
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime

    rt = TpuRuntime(make_mesh())
    qs = [
        "GO 2 STEPS FROM 1 OVER KNOWS YIELD dst(edge) AS d, KNOWS.w AS w",
        "GO FROM 1, 4 OVER KNOWS WHERE KNOWS.w > 6 YIELD dst(edge) AS d",
        "MATCH (a:Person)-[e:KNOWS*1..2]->(b) WHERE id(a) == 1 "
        "RETURN id(b), size(e)",
        "GET SUBGRAPH 2 STEPS FROM 1 OUT KNOWS YIELD VERTICES AS v, "
        "EDGES AS e",
        "FIND ALL PATH FROM 1 TO 4 OVER KNOWS UPTO 3 STEPS YIELD path AS p",
    ]
    out = {}
    for mode, runtime in (("host", None), ("device", rt)):
        c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                         data_dir=str(tmp_path / mode),
                         tpu_runtime=runtime)
        try:
            cl = c.client()
            r = cl.execute("CREATE SPACE dv(partition_num=8, "
                           "replica_factor=1, vid_type=INT64)")
            assert r.error is None, r.error
            c.reconcile_storage()
            for q in ["USE dv",
                      "CREATE TAG Person(name string)",
                      "CREATE EDGE KNOWS(w int)",
                      'INSERT VERTEX Person(name) VALUES 1:("a"), '
                      '2:("b"), 3:("c"), 4:("d"), 5:("e")',
                      "INSERT EDGE KNOWS(w) VALUES 1->2:(5), 2->3:(50), "
                      "3->4:(9), 1->3:(80), 4->1:(7), 2->5:(11)"]:
                r = cl.execute(q)
                assert r.error is None, f"{q} -> {r.error}"
            rows = []
            for q in qs:
                r = cl.execute(q)
                assert r.error is None, f"[{mode}] {q} -> {r.error}"
                rows.append(sorted(repr(x) for x in r.data.rows))
            out[mode] = rows
            if runtime is not None:
                # breadcrumb stats are thread-local to the RPC handler;
                # assert engagement via the pinned snapshot (the export
                # really happened) and the global kernel counter
                assert "dv" in runtime.snapshots, \
                    "device plane never pinned the cluster space"
                from nebula_tpu.utils.stats import stats as _metrics
                assert _metrics().snapshot().get("tpu_kernel_runs", 0) > 0
        finally:
            c.stop()
    assert out["host"] == out["device"]


def test_cluster_device_sees_writes(tmp_path):
    """Epoch-based re-pin in cluster mode: a write bumps part epochs and
    the next device query re-exports."""
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime

    rt = TpuRuntime(make_mesh())
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path), tpu_runtime=rt)
    try:
        cl = c.client()
        r = cl.execute("CREATE SPACE dw(partition_num=8, "
                       "replica_factor=1, vid_type=INT64)")
        assert r.error is None, r.error
        c.reconcile_storage()
        for q in ["USE dw", "CREATE TAG T()", "CREATE EDGE E(w int)",
                  "INSERT VERTEX T() VALUES 1:(), 2:(), 3:()",
                  "INSERT EDGE E(w) VALUES 1->2:(1)"]:
            assert cl.execute(q).error is None
        r = cl.execute("GO FROM 1 OVER E YIELD dst(edge) AS d")
        assert r.error is None and sorted(x[0] for x in r.data.rows) == [2]
        assert cl.execute("INSERT EDGE E(w) VALUES 1->3:(2)").error is None
        r = cl.execute("GO FROM 1 OVER E YIELD dst(edge) AS d")
        assert r.error is None
        assert sorted(x[0] for x in r.data.rows) == [2, 3]
    finally:
        c.stop()


def test_show_parts_cluster_real_map(tmp_path):
    """SHOW PARTS in cluster mode reports the meta part map's replica
    sets, not the standalone stub."""
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        cl = c.client()
        r = cl.execute("CREATE SPACE sp(partition_num=4, "
                       "replica_factor=1, vid_type=INT64)")
        assert r.error is None, r.error
        c.reconcile_storage()
        assert cl.execute("USE sp").error is None
        r = cl.execute("SHOW PARTS")
        assert r.error is None, r.error
        assert len(r.data.rows) == 4
        addrs = {s.my_addr for s in c.storageds}
        for pid, leader, peers in r.data.rows:
            assert leader in addrs
            assert set(peers) <= addrs
    finally:
        c.stop()


def test_show_and_kill_queries_cross_graphd(tmp_path):
    """SHOW [ALL] QUERIES fans out over every graphd in metad's session
    table, and KILL QUERY routes to the OWNING graphd (the registry
    holding the kill event lives there)."""
    import threading
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=2,
                     data_dir=str(tmp_path))
    try:
        from nebula_tpu.cluster.client import GraphClient
        addr_a = c.graph_servers[0].addr
        addr_b = c.graph_servers[1].addr
        ha, pa = addr_a.rsplit(":", 1)
        hb, pb = addr_b.rsplit(":", 1)
        ca = GraphClient(ha, int(pa)); ca.authenticate("root", "nebula")
        cb = GraphClient(hb, int(pb)); cb.authenticate("root", "nebula")

        # plant a RUNNING query in graphd B's engine registry (the
        # execute path does exactly this around scheduler.run)
        sess_b = c.graphds[1].engine.sessions[cb.session_id]
        ev = threading.Event()
        sess_b.queries[777] = "stall-on-b"
        sess_b.running_kill[777] = ev
        try:
            rs = ca.execute("SHOW QUERIES")
            assert rs.error is None, rs.error
            hit = [r for r in rs.data.rows if r[3] == "stall-on-b"]
            # GraphAddr is the LAST column (live-progress columns ride
            # in between since ISSUE 9)
            assert hit and hit[0][-1] == addr_b, rs.data.rows
            rs = ca.execute("SHOW LOCAL QUERIES")
            assert rs.error is None
            assert not any(r[3] == "stall-on-b" for r in rs.data.rows)

            rs = ca.execute(
                f"KILL QUERY (session={cb.session_id}, plan=777)")
            assert rs.error is None, rs.error
            assert ev.is_set()
        finally:
            sess_b.queries.pop(777, None)
            sess_b.running_kill.pop(777, None)
        rs = ca.execute("KILL QUERY (session=999999, plan=1)")
        assert rs.error is not None
    finally:
        c.stop()


def test_cluster_jobs_visible_and_recoverable_across_graphds(tmp_path):
    """Jobs live in metad's raft-replicated table (the reference's
    metad JobManager): SUBMIT on graphd A is visible from graphd B,
    terminal status mirrors back, and RECOVER from B re-homes a
    stopped job onto B as the new executor."""
    from nebula_tpu.cluster.client import GraphClient
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.exec.jobs import job_manager
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=2,
                     data_dir=str(tmp_path))
    try:
        addr_a = c.graph_servers[0].addr
        addr_b = c.graph_servers[1].addr
        ha, pa = addr_a.rsplit(":", 1)
        hb, pb = addr_b.rsplit(":", 1)
        ca = GraphClient(ha, int(pa)); ca.authenticate("root", "nebula")
        cb = GraphClient(hb, int(pb)); cb.authenticate("root", "nebula")
        rs = ca.execute("CREATE SPACE cj(partition_num=2, "
                        "replica_factor=1, vid_type=INT64)")
        assert rs.error is None, rs.error
        c.reconcile_storage()
        ca.execute("USE cj"); cb.execute("USE cj")

        rs = ca.execute("SUBMIT JOB STATS")
        assert rs.error is None, rs.error
        jid = rs.data.rows[0][0]

        def poll_status(client, want, timeout=10.0):
            # the metad mirror is written by the worker AFTER the local
            # status flips (eventually consistent) — poll the statement
            # surface like an operator would
            import time as _t
            deadline = _t.time() + timeout
            while _t.time() < deadline:
                r = client.execute(f"SHOW JOB {jid}")
                assert r.error is None, r.error
                if r.data.rows and r.data.rows[0][2] == want:
                    return r
                _t.sleep(0.02)
            raise AssertionError(f"job {jid} never reached {want}: "
                                 f"{r.data.rows}")

        # visible (with terminal status) from the OTHER graphd
        rs = poll_status(cb, "FINISHED")
        assert rs.data.rows[0][0] == jid

        # a job stopped on A recovers on B (B becomes the executor)
        mgr_a = job_manager(c.graphds[0].engine.qctx.store)
        meta = c.graphds[0].meta
        meta.update_job(jid, status="STOPPED")
        rs = cb.execute(f"RECOVER JOB {jid}")
        assert rs.error is None, rs.error
        assert rs.data.rows[0][0] == 1
        mgr_b = job_manager(c.graphds[1].engine.qctx.store)
        assert mgr_b.wait()
        poll_status(ca, "FINISHED")
        assert jid in mgr_b.jobs          # B executed the re-run
        # bogus ids error from any graphd
        rs = cb.execute("STOP JOB 999999")
        assert rs.error is not None
    finally:
        c.stop()


def test_metad_quorum_survives_leader_kill(tmp_path):
    """3-metad quorum: killing the metad LEADER mid-flight must elect a
    new one; DDL, session creation, and queries keep working through
    the surviving majority (the client follows leader hints)."""
    import time
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=3, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        rs = client.execute("CREATE SPACE mq(partition_num=2, "
                            "replica_factor=1, vid_type=INT64)")
        assert rs.error is None, rs.error
        c.reconcile_storage()
        for q in ["USE mq", "CREATE TAG t(x int)",
                  "INSERT VERTEX t(x) VALUES 1:(5)"]:
            rs = client.execute(q)
            assert rs.error is None, (q, rs.error)

        leader_i = next(i for i, ms in enumerate(c.metads)
                        if ms.raft.is_leader())
        c.metads[leader_i].stop()
        c.meta_servers[leader_i].stop()

        deadline = time.time() + 15
        new_leader = None
        while time.time() < deadline and new_leader is None:
            new_leader = next(
                (i for i, ms in enumerate(c.metads)
                 if i != leader_i and ms.raft.is_leader()), None)
            time.sleep(0.05)
        assert new_leader is not None, "no new metad leader elected"

        # DDL through the new leader (client re-discovers it)
        deadline = time.time() + 20
        ok = False
        while time.time() < deadline and not ok:
            rs = client.execute("CREATE TAG t2(y int)")
            ok = rs.error is None
            if not ok:
                time.sleep(0.3)
        assert ok, f"DDL never succeeded after failover: {rs.error}"
        rs = client.execute("FETCH PROP ON t 1 YIELD t.x AS x")
        assert rs.error is None and rs.data.rows == [[5]], rs.error
        # a FRESH session authenticates against the survivors too
        c2 = c.client()
        rs = c2.execute("USE mq; FETCH PROP ON t 1 YIELD t.x AS x")
        assert rs.error is None and rs.data.rows == [[5]], rs.error
    finally:
        c.stop()
