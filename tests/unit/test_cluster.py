"""Cluster-mode integration: a full metad+storaged×2+graphd cluster in
one process over real localhost sockets, driven through GraphClient —
the MockCluster strategy of SURVEY §4."""
import pytest

from nebula_tpu.cluster.launcher import LocalCluster


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    yield c
    c.stop()


@pytest.fixture(scope="module")
def conn(cluster):
    client = cluster.client()

    def run(q, expect_ok=True):
        rs = client.execute(q)
        if expect_ok:
            assert rs.error is None, f"{q} -> {rs.error}"
        return rs

    run("CREATE SPACE cs(partition_num=4, replica_factor=1, vid_type=INT64)")
    cluster.reconcile_storage()
    run("USE cs")
    run("CREATE TAG Person(name string, age int)")
    run("CREATE EDGE KNOWS(w int)")
    run("CREATE TAG INDEX i_person_age ON Person(age)")
    run('INSERT VERTEX Person(name, age) VALUES '
        '1:("ann",30), 2:("bob",25), 3:("cid",41), 4:("dee",19)')
    run("INSERT EDGE KNOWS(w) VALUES 1->2:(5), 2->3:(50), 3->4:(9), "
        "1->3:(80), 4->1:(7)")
    return run


def test_cluster_go(conn):
    rs = conn("GO FROM 1 OVER KNOWS YIELD dst(edge) AS d, KNOWS.w AS w")
    assert sorted(map(tuple, rs.data.rows)) == [(2, 5), (3, 80)]


def test_cluster_multi_hop_filter(conn):
    rs = conn("GO 2 STEPS FROM 1 OVER KNOWS WHERE KNOWS.w > 8 "
              "YIELD src(edge), dst(edge), KNOWS.w")
    assert sorted(map(tuple, rs.data.rows)) == [(2, 3, 50), (3, 4, 9)]


def test_cluster_fetch_and_lookup(conn):
    rs = conn("FETCH PROP ON Person 3 YIELD Person.name, Person.age")
    assert rs.data.rows == [["cid", 41]]
    rs = conn("LOOKUP ON Person WHERE Person.age > 24 YIELD Person.name")
    assert sorted(r[0] for r in rs.data.rows) == ["ann", "bob", "cid"]


def test_cluster_match(conn):
    rs = conn("MATCH (a:Person)-[e:KNOWS]->(b) WHERE e.w >= 50 "
              "RETURN a.Person.name, b.Person.name ORDER BY a.Person.name")
    assert rs.data.rows == [["ann", "cid"], ["bob", "cid"]]


def test_cluster_update_delete(conn):
    conn("UPDATE VERTEX ON Person 4 SET age = 20")
    rs = conn("FETCH PROP ON Person 4 YIELD Person.age")
    assert rs.data.rows == [[20]]
    conn("DELETE EDGE KNOWS 4->1")
    rs = conn("GO FROM 4 OVER KNOWS YIELD dst(edge)")
    assert rs.data.rows == []
    # reverse plane is consistent too
    rs = conn("GO FROM 1 OVER KNOWS REVERSELY YIELD src(edge)")
    assert rs.data.rows == []


def test_cluster_sessions_and_hosts(cluster, conn):
    hosts = cluster.meta_clients[0].list_hosts()
    roles = sorted(h["role"] for h in hosts if h["alive"])
    assert roles == ["graph", "storage", "storage"]
    sess = cluster.meta_clients[0].list_sessions()
    assert any(s["user"] == "root" for s in sess)


def test_cluster_data_is_sharded(cluster, conn):
    """Both storageds hold some parts; the union serves the space."""
    per_host = [sum(p.edge_count()
                    for (sid, pid), rp in ss.parts.items()
                    for p in [ss.store.space("cs").parts[pid]])
                for ss in cluster.storageds]
    assert all(n > 0 for n in per_host), per_host


def test_cluster_second_client_shares_state(cluster):
    c2 = cluster.client()
    rs = c2.execute("USE cs")
    assert rs.error is None
    rs = c2.execute("GO FROM 2 OVER KNOWS YIELD dst(edge)")
    assert rs.data.rows == [[3]]
    c2.close()
