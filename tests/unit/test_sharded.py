"""Mesh-native sharded execution (ISSUE 17): per-device CSR residency,
1/2/4-part parity against the single-chip oracle for GO / MATCH
traverse / BFS, the (1,1) degrade path, the per-shard HBM ledger, the
per-DEVICE budget scale-out proof, and batched lanes on a sharded mesh.

Everything here runs on the 8-device virtual CPU mesh the conftest
forces — the same programs (shard_map, all_to_all) that run on a real
multi-chip mesh, minus the ICI."""
import random
import threading

import numpy as np
import pytest

from nebula_tpu.core.value import NULL
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.csr import build_snapshot
from nebula_tpu.graphstore.schema import PropDef, PropType
from nebula_tpu.graphstore.store import GraphStore
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.stats import stats

tpu = pytest.importorskip("nebula_tpu.tpu")
from nebula_tpu.tpu import (TpuRuntime, make_mesh, make_mesh2,  # noqa: E402
                            mesh_lanes, mesh_parts)
from nebula_tpu.tpu.device import TpuUnavailable           # noqa: E402

from test_tpu import norm_edge                             # noqa: E402


def store_p(parts: int, seed=3, n=90, avg_deg=4, spacename="g"):
    """random_store with a configurable partition count — a sharded
    pin requires partition_num == mesh parts."""
    rng = random.Random(seed)
    st = GraphStore()
    st.create_space(spacename, partition_num=parts, vid_type="INT64")
    st.catalog.create_tag(spacename, "person", [
        PropDef("age", PropType.INT64)])
    st.catalog.create_edge(spacename, "knows", [
        PropDef("w", PropType.INT64), PropDef("f", PropType.DOUBLE)])
    for v in range(n):
        st.insert_vertex(spacename, v, "person", {"age": rng.randint(0, 80)})
    for v in range(n):
        for _ in range(rng.randint(0, avg_deg * 2)):
            props = {"w": rng.randint(-5, 100) if rng.random() > .1
                     else NULL, "f": rng.uniform(0, 1)}
            st.insert_edge(spacename, v, "knows", rng.randrange(n),
                           rng.randint(0, 2), props)
    return st


def go_key(rows):
    return sorted(norm_edge(e) for (_, e, _) in rows)


# -- GO / MATCH / BFS parity: sharded mesh vs single-chip oracle ------------


@pytest.mark.parametrize("parts", [1, 2, 4])
def test_go_parity_sharded_vs_single_chip(parts):
    """GO-3-step rows on a P-part mesh are byte-identical to the
    make_mesh(1) single-chip oracle AND to the host engine."""
    st = store_p(parts, seed=10 + parts)
    rt_shard = TpuRuntime(make_mesh(parts))
    rt_solo = TpuRuntime(make_mesh(1))
    assert rt_shard.mesh_size == parts
    seeds = [1, 5, 9, 23]
    r_sh, s_sh = rt_shard.traverse(st, "g", seeds, ["knows"], "out", 3)
    r_so, s_so = rt_solo.traverse(st, "g", seeds, ["knows"], "out", 3)
    assert go_key(r_sh) == go_key(r_so)
    assert s_sh.shards == parts
    assert s_so.shards == 1
    if parts > 1:
        # 2 exchanges for a 3-hop traverse (the last hop ships no
        # frontier), each a bit-packed (P, P, W) uint32 all_to_all
        from nebula_tpu.tpu.hop import a2a_payload_bytes
        dev = rt_shard.snapshots["g"]
        assert s_sh.exchange_bytes == 2 * a2a_payload_bytes(
            parts, dev.vmax)
    else:
        assert s_sh.exchange_bytes == 0
    # engine-level rows: device plane vs pure-host execution
    q = ("GO 3 STEPS FROM 1, 5, 9, 23 OVER knows "
         "YIELD src(edge), rank(edge), dst(edge)")
    eng_host = QueryEngine(st)
    eng_dev = QueryEngine(st, tpu_runtime=rt_shard)
    sh = eng_host.new_session()
    sdv = eng_dev.new_session()
    eng_host.execute(sh, "USE g")
    eng_dev.execute(sdv, "USE g")
    rs_h = eng_host.execute(sh, q)
    rs_d = eng_dev.execute(sdv, q)
    assert rs_h.error is None and rs_d.error is None
    assert sorted(map(repr, rs_h.data.rows)) == \
        sorted(map(repr, rs_d.data.rows))


@pytest.mark.parametrize("parts", [2, 4])
def test_match_traverse_hops_parity(parts):
    """MATCH's layered expansion (traverse_hops) on a sharded mesh
    yields the same per-hop edge frames as the single-chip program."""
    st = store_p(parts, seed=20 + parts)
    rt_shard = TpuRuntime(make_mesh(parts))
    rt_solo = TpuRuntime(make_mesh(1))
    fr_sh, s_sh = rt_shard.traverse_hops(st, "g", [1, 2, 7], ["knows"],
                                         "out", 3)
    fr_so, _ = rt_solo.traverse_hops(st, "g", [1, 2, 7], ["knows"],
                                     "out", 3)
    assert len(fr_sh) == len(fr_so) == 3
    for hs, ho in zip(fr_sh, fr_so):
        assert sorted(norm_edge(e) for e in hs.edges) == \
            sorted(norm_edge(e) for e in ho.edges)
    assert s_sh.shards == parts


@pytest.mark.parametrize("parts", [2, 4])
def test_bfs_parity_sharded(parts):
    """Sharded BFS dist == single-chip dist == numpy oracle; BFS
    exchanges EVERY level (traverse skips the last hop's)."""
    from nebula_tpu.bench.datagen import host_bfs
    from nebula_tpu.tpu.bfs import bfs_exchange_bytes

    st = store_p(parts, seed=30 + parts, n=120, avg_deg=5)
    rt_shard = TpuRuntime(make_mesh(parts))
    rt_solo = TpuRuntime(make_mesh(1))
    snap = build_snapshot(st, "g")
    sd = st.space("g")
    srcs = [1, 4, 11]
    dist_sh, s_sh = rt_shard.bfs(st, "g", srcs, ["knows"], "out", 5)
    dist_so, _ = rt_solo.bfs(st, "g", srcs, ["knows"], "out", 5)
    assert np.array_equal(np.asarray(dist_sh), np.asarray(dist_so))
    dense = [sd.dense_id(v) for v in srcs]
    want = host_bfs(snap, dense, 5, etype="knows")
    got = np.asarray(dist_sh, np.int32)
    vv = np.arange(want.shape[0])
    assert np.array_equal(got[vv % parts, vv // parts], want)
    dev = rt_shard.snapshots["g"]
    assert s_sh.exchange_bytes == bfs_exchange_bytes(parts, dev.vmax, 5)


# -- mesh construction + degrade --------------------------------------------


def test_mesh2_grid_and_degrade():
    """make_mesh2 builds the ('lane', 'part') grid; oversubscription
    degrades (lane axis first) instead of refusing; one device always
    yields the (1, 1) mesh and the runtime serves in local mode."""
    m = make_mesh2(2, 4)
    assert mesh_lanes(m) == 2 and mesh_parts(m) == 4
    # degrade: 4x16 > 8 devices -> lane axis collapses first
    m2 = make_mesh2(4, 8)
    assert mesh_lanes(m2) == 1 and mesh_parts(m2) == 8
    # explicit devices + insufficient is a hard error (no silent grid)
    import jax
    with pytest.raises(ValueError):
        make_mesh2(2, 8, devices=jax.devices()[:4])
    # (1, 1): the single-device degrade still serves correct rows
    m11 = make_mesh2(1, 1, devices=jax.devices()[:1])
    assert mesh_lanes(m11) == 1 and mesh_parts(m11) == 1
    rt11 = TpuRuntime(m11)
    assert rt11.local_mode
    st = store_p(4, seed=44)
    rt_solo = TpuRuntime(make_mesh(1))
    r11, s11 = rt11.traverse(st, "g", [1, 5], ["knows"], "out", 2)
    rso, _ = rt_solo.traverse(st, "g", [1, 5], ["knows"], "out", 2)
    assert go_key(r11) == go_key(rso)
    assert s11.shards == 1 and s11.exchange_bytes == 0


def test_runtime_on_two_axis_mesh_parity():
    """A TpuRuntime on the full 2-axis (2 lanes x 4 parts) grid serves
    the same rows as the single-chip oracle — the lane rows replicate
    the CSR, the part columns shard it."""
    st = store_p(4, seed=55)
    rt_grid = TpuRuntime(make_mesh2(2, 4))
    assert rt_grid.mesh_lanes == 2 and rt_grid.mesh_size == 4
    rt_solo = TpuRuntime(make_mesh(1))
    rg, sg = rt_grid.traverse(st, "g", [2, 3, 8], ["knows"], "out", 3)
    rs, _ = rt_solo.traverse(st, "g", [2, 3, 8], ["knows"], "out", 3)
    assert go_key(rg) == go_key(rs)
    assert sg.shards == 4


# -- per-shard HBM ledger + budget scale-out --------------------------------


def test_shard_hbm_ledger_accounting():
    """The per-shard ledger: shard_hbm_bytes() sums to hbm_bytes(), and
    the tpu_shard_hbm_bytes{shard} gauges the pin emitted sum to the
    tpu_hbm_bytes_pinned total with tpu_shards == mesh width."""
    st = store_p(4, seed=66)
    rt = TpuRuntime(make_mesh(4))
    dev = rt.pin(st, "g")
    per = dev.shard_hbm_bytes()
    assert set(per) == {0, 1, 2, 3}
    assert sum(per.values()) == dev.hbm_bytes()
    snap = stats().snapshot()
    assert snap.get("tpu_shards") == 4.0
    gauges = [snap.get(f"tpu_shard_hbm_bytes{{shard={p}}}")
              for p in range(4)]
    assert all(g is not None for g in gauges)
    assert sum(gauges) == float(snap.get("tpu_hbm_bytes_pinned"))
    rt.unpin("g")


def test_hbm_budget_is_per_device():
    """The scale-out contract: with the per-DEVICE budget below the
    snapshot total, the single-chip pin REFUSES while a 4-way sharded
    pin accepts (each shard parks ~1/4 of the bytes) and serves rows
    byte-identical to the host engine — a mesh provably holds a graph
    the single chip cannot."""
    st = store_p(4, seed=77, n=150, avg_deg=5)
    rt_solo = TpuRuntime(make_mesh(1))
    rt4 = TpuRuntime(make_mesh(4))
    total = build_snapshot(st, "g").hbm_bytes()
    get_config().set_dynamic("tpu_hbm_limit_bytes", total // 2)
    try:
        with pytest.raises(TpuUnavailable):
            rt_solo.pin(st, "g")
        dev = rt4.pin(st, "g")              # total/4 per device: fits
        assert max(dev.shard_hbm_bytes().values()) <= total // 2
        r4, s4 = rt4.traverse(st, "g", [1, 5, 9], ["knows"], "out", 3)
        host = QueryEngine(st)
        s = host.new_session()
        host.execute(s, "USE g")
        rs = host.execute(
            s, "GO 3 STEPS FROM 1, 5, 9 OVER knows "
               "YIELD src(edge), rank(edge), dst(edge)")
        assert rs.error is None
        assert len(r4) == len(rs.data.rows)
        assert s4.shards == 4
    finally:
        get_config().set_dynamic("tpu_hbm_limit_bytes", 0)
        rt4.unpin("g")


def test_partition_mesh_mismatch_is_unavailable():
    """A snapshot whose partition count differs from the mesh width
    cannot be sharded across it: pin raises TpuUnavailable (the
    executor host-falls-back) instead of mis-sharding."""
    st = store_p(8, seed=88)
    rt4 = TpuRuntime(make_mesh(4))
    with pytest.raises(TpuUnavailable):
        rt4.pin(st, "g")


# -- batched lanes on a sharded mesh ----------------------------------------


def test_sharded_batched_lanes_parity():
    """Concurrent GO statements on a 4-part mesh form ONE lanes x
    shards launch and every statement's rows equal its solo run —
    PR 12's lane axis composed with the part axis."""
    from nebula_tpu.tpu.batch import batch_former
    from nebula_tpu.utils.workload import live_registry

    st = store_p(4, seed=99, n=60)
    rt = TpuRuntime(make_mesh(4))
    eng = QueryEngine(st, tpu_runtime=rt)
    q = "GO 2 STEPS FROM {seed} OVER knows YIELD dst(edge) AS d"

    def run(seed, out):
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q.format(seed=seed))
        out[seed] = rs

    seeds = [1, 2, 3, 5]
    truth = {}
    for sd in seeds:
        run(sd, truth)
        assert truth[sd].error is None
        truth[sd] = sorted(map(repr, truth[sd].data.rows))
    batch_former().reset()
    regs = [live_registry().register(qid=-(200 + i), session=0, user="t",
                                     stmt="d", kind="Go")
            for i in range(2)]
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 300_000})
    s0 = stats().snapshot()
    try:
        out, ths = {}, []
        for sd in seeds:
            t = threading.Thread(target=run, args=(sd, out), daemon=True)
            t.start()
            ths.append(t)
        for t in ths:
            t.join(60)
        s1 = stats().snapshot()
        for sd in seeds:
            assert out[sd].error is None, out[sd].error
            assert sorted(map(repr, out[sd].data.rows)) == truth[sd]
        assert s1.get("tpu_batches_formed", 0) \
            - s0.get("tpu_batches_formed", 0) >= 1
        assert s1.get("tpu_all_to_all_bytes", 0) \
            > s0.get("tpu_all_to_all_bytes", 0)
    finally:
        get_config().set_dynamic_many({"batch_max_lanes": 0,
                                       "batch_wait_us": 1500})
        for i in range(2):
            live_registry().deregister(-(200 + i))
        batch_former().reset()
