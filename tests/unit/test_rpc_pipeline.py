"""ISSUE 2 wire layer: blob codec round-trips, symmetric MAX_FRAME
enforcement, pipelined multiplexing (overlap + no frame interleaving),
and idempotency-gated retry."""
import socket
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from nebula_tpu.cluster import rpc as R
from nebula_tpu.cluster.rpc import (FrameTooLarge, RpcClient, RpcConnError,
                                    RpcError, RpcServer, is_idempotent)


@pytest.fixture()
def server():
    srv = RpcServer()
    srv.start()
    yield srv
    srv.stop()


def _client(srv, **kw):
    kw.setdefault("timeout", 10.0)
    return RpcClient(srv.host, srv.port, **kw)


# -- codec round-trips ------------------------------------------------------


def test_blob_roundtrip_zero_one_many(server):
    """0 blobs = plain JSON frame; 1 and many blobs ride out-of-band."""
    server.register("echo", lambda p: p)
    cl = _client(server)
    try:
        assert cl.call("echo", a=1, b="x") == {"a": 1, "b": "x"}
        one = cl.call("echo", b=b"\x00\x01payload")
        assert bytes(one["b"]) == b"\x00\x01payload"
        many = cl.call("echo", blobs=[bytes([i]) * (i + 1)
                                      for i in range(17)])
        assert [bytes(x) for x in many["blobs"]] == \
            [bytes([i]) * (i + 1) for i in range(17)]
        # empty blob is a legal zero-length out-of-band buffer
        assert bytes(cl.call("echo", e=b"")["e"]) == b""
    finally:
        cl.close()


def test_empty_columns_roundtrip(server):
    """A zero-row columnar result ships and decodes (empty columns)."""
    import numpy as np

    from nebula_tpu.core import wire
    from nebula_tpu.core.value import ColumnarDataSet
    empty = ColumnarDataSet(["d", "w"], [np.empty(0, np.int64),
                                         np.empty(0, np.float64)])
    server.register("q", lambda p: {"data": wire.to_wire(
        ColumnarDataSet(["d", "w"], [np.empty(0, np.int64),
                                     np.empty(0, np.float64)]))})
    cl = _client(server)
    try:
        got = wire.from_wire(cl.call("q")["data"])
        assert isinstance(got, ColumnarDataSet)
        assert len(got) == 0 and got.rows == [] == empty.rows
        assert got.column_names == ["d", "w"]
    finally:
        cl.close()


def test_dataset_columnar_wire_exactness():
    """Row-form DataSets take the typed-blob path only when it is
    lossless: int/float/bool identity survives; mixed columns stay
    per-cell."""
    from nebula_tpu.core import wire
    from nebula_tpu.core.value import NULL, DataSet
    rows = [[i, float(i) / 3, i % 2 == 0, f"s{i}",
             NULL if i % 9 == 0 else i] for i in range(200)]
    back = wire.from_wire(wire.to_wire(DataSet(list("abcde"), rows)))
    assert back.rows == rows
    for ra, rb in zip(back.rows, rows):
        assert [type(x) for x in ra] == [type(x) for x in rb]


# -- symmetric MAX_FRAME ----------------------------------------------------


def test_send_path_rejects_oversized_frame(server, monkeypatch):
    server.register("big", lambda p: {"b": b"y" * 4096})
    server.register("ok", lambda p: "fine")
    cl = _client(server)
    try:
        monkeypatch.setattr(R, "MAX_FRAME", 1024)
        # client side: the oversized REQUEST dies before any byte is
        # sent — the connection stays usable
        with pytest.raises(FrameTooLarge, match="frame too large"):
            cl.call("ok", b=b"x" * 4096)
        assert cl.call("ok") == "fine"
        # server side: the oversized REPLY becomes a diagnosable error
        # reply, not an opaque peer disconnect
        with pytest.raises(RpcError, match="frame too large"):
            cl.call("big")
        assert cl.call("ok") == "fine"
    finally:
        cl.close()


def test_receive_rejects_malformed_blob_header():
    # blob-count field claims more blobs than the frame can hold
    body = b"\x00" + struct.pack("<I", 1 << 20) + b"\x00" * 16
    with pytest.raises(RpcConnError, match="cannot fit"):
        R._decode_body(memoryview(body))
    # declared sizes don't tile the frame exactly
    bad = b"\x00" + struct.pack("<III", 1, 4, 2) + b"{}" + b"abcd" + b"x"
    with pytest.raises(RpcConnError, match="tile"):
        R._decode_body(memoryview(bad))


# -- pipelining: overlap + no interleaving ----------------------------------


def test_concurrent_calls_overlap_wall_time(server):
    """The fanout shape: N concurrent slow calls to ONE peer through one
    pooled client finish in ≈ max, not sum."""
    server.register("slow", lambda p: (time.sleep(0.25), p["i"])[1])
    cl = _client(server)
    try:
        t0 = time.perf_counter()
        with ThreadPoolExecutor(max_workers=6) as pool:
            got = list(pool.map(lambda i: cl.call("slow", i=i), range(6)))
        wall = time.perf_counter() - t0
        assert got == list(range(6))
        assert wall < 3 * 0.25, f"calls serialized: wall={wall:.2f}s"
    finally:
        cl.close()


def test_shared_client_frames_never_interleave(server):
    """Two threads push large distinct blob payloads through ONE pooled
    connection while a slow handler keeps both calls in flight; each
    reply must carry its own request's checksum — a torn/interleaved
    frame could not survive the length-prefixed send-lock discipline."""
    import hashlib

    def handler(p):
        time.sleep(0.1)        # hold both calls in flight concurrently
        return {"tag": p["tag"],
                "digest": hashlib.sha256(bytes(p["blob"])).hexdigest()}

    server.register("sum", handler)
    cl = _client(server, pool_size=1)    # force ONE shared socket
    payloads = {t: bytes([t]) * (1 << 20) for t in (1, 2, 3, 4)}
    windows = {}

    def run(tag):
        import hashlib as h
        t0 = time.perf_counter()
        r = cl.call("sum", tag=tag, blob=payloads[tag])
        windows[tag] = (t0, time.perf_counter())
        assert r["tag"] == tag
        assert r["digest"] == h.sha256(payloads[tag]).hexdigest()

    try:
        threads = [threading.Thread(target=run, args=(t,))
                   for t in payloads]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(windows) == 4
        # the calls genuinely overlapped in time (pipelined, one socket)
        starts = [w[0] for w in windows.values()]
        ends = [w[1] for w in windows.values()]
        assert max(starts) < min(ends), "calls never overlapped"
    finally:
        cl.close()


# -- idempotency-gated retry ------------------------------------------------


class _FlakyServer:
    """Accepts one connection, reads one frame, drops the connection
    (reply lost mid-call); subsequent connections serve normally."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.host, self.port = self.sock.getsockname()
        self.dropped = 0
        self.served = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        first = True
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            if first:
                first = False
                # read the request, then kill the connection: the peer
                # cannot know whether the handler ran
                try:
                    R._recv_frame(conn)
                except RpcConnError:
                    pass
                self.dropped += 1
                conn.close()
                continue
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                req, _, rid = R._recv_frame(conn)
                self.served += 1
                R._send_frame(conn, {"ok": True, "result": "done"}, rid)
        except (RpcConnError, OSError):
            conn.close()

    def close(self):
        self.sock.close()


def test_retry_gated_on_idempotency():
    assert is_idempotent("storage.get_neighbors")
    assert is_idempotent("raft")
    assert not is_idempotent("storage.write")
    assert not is_idempotent("graph.execute")
    assert not is_idempotent("meta.ddl")

    # idempotent read: auto-retried through a fresh connection
    flaky = _FlakyServer()
    cl = RpcClient(flaky.host, flaky.port, timeout=5.0, retries=2)
    try:
        assert cl.call("storage.get_vertex") == "done"
        assert flaky.dropped == 1 and flaky.served >= 1
    finally:
        cl.close()
        flaky.close()

    # non-idempotent write: surfaced to the caller, NOT re-sent
    flaky = _FlakyServer()
    cl = RpcClient(flaky.host, flaky.port, timeout=5.0, retries=2)
    try:
        with pytest.raises(RpcConnError, match="not idempotent"):
            cl.call("storage.write")
        time.sleep(0.1)
        assert flaky.served == 0, "write was re-sent after a mid-call " \
                                  "connection death"
    finally:
        cl.close()
        flaky.close()


def test_call_part_replica_walk_respects_idempotency():
    """The replica walk in StorageClient._call_part must not re-drive a
    non-idempotent call that died mid-reply (double-apply hazard one
    layer above RpcClient's own gate); idempotent reads keep walking."""
    from nebula_tpu.cluster.storage_client import StorageClient, StorageError

    class _Meta:
        def __init__(self, addr):
            self._addr = addr

        def parts_of(self, space):
            return [[self._addr]]

        def refresh(self, force=False):
            pass

    # read: first connection drops mid-reply, walk retries and succeeds
    flaky = _FlakyServer()
    sc = StorageClient(_Meta(f"{flaky.host}:{flaky.port}"))
    try:
        assert sc._call_part("s", 0, "storage.get_vertex", {}) == "done"
        assert flaky.dropped == 1
    finally:
        sc.close()
        flaky.close()

    # write: surfaced as StorageError, never re-sent
    flaky = _FlakyServer()
    sc = StorageClient(_Meta(f"{flaky.host}:{flaky.port}"))
    try:
        with pytest.raises(StorageError, match="non-idempotent"):
            sc._call_part("s", 0, "storage.write", {"cmds": []})
        time.sleep(0.1)
        assert flaky.served == 0
    finally:
        sc.close()
        flaky.close()


def test_pool_gauges_exported(server):
    from nebula_tpu.utils.stats import stats
    server.register("ping", lambda p: "pong")
    cl = _client(server)
    try:
        cl.call("ping")
        snap = stats().snapshot()
        assert "rpc_pool_size" in snap and "rpc_inflight" in snap
        assert snap["rpc_inflight"] >= 0
        assert "rpc_pool_size" in stats().to_prometheus()
    finally:
        cl.close()


def test_cluster_columnar_neighbors_parity():
    """Bulk GO through the cluster takes the columnar get_neighbors
    wire path (≥64 rows/part, single etype, int vids) and must return
    exactly what the row path returns — including schema-upgrade
    defaults for rows written before an ALTER."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.cluster.storage_service import _neighbors_columnar
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1)
    try:
        cl = c.client()
        for q in ("CREATE SPACE nc(partition_num=2, vid_type=INT64)",):
            assert cl.execute(q).error is None
        c.reconcile_storage()
        for q in ("USE nc", "CREATE TAG P()", "CREATE EDGE E(w int)"):
            assert cl.execute(q).error is None
        vals = ", ".join(f"{v}:()" for v in range(200))
        assert cl.execute(f"INSERT VERTEX P() VALUES {vals}").error is None
        edges = ", ".join(f"0->{d}:({d % 97})" for d in range(1, 161))
        assert cl.execute(f"INSERT EDGE E(w) VALUES {edges}").error is None
        # encoder engages on a bulk single-etype reply (direct probe)
        store = c.graphds[0].store
        raw = list(store.get_neighbors("nc", [0], ["E"], "out"))
        assert len(raw) == 160
        enc = _neighbors_columnar([(s, et, r, o, p, sd) for
                                   (s, et, r, o, p, sd) in raw])
        assert enc is not None and enc["n"] == 160 and enc["et"] == "E"
        # end-to-end parity through the engine
        rs = cl.execute("GO FROM 0 OVER E YIELD dst(edge) AS d, "
                        "E.w AS w")
        assert rs.error is None
        assert sorted(map(tuple, rs.data.rows)) == \
            [(d, d % 97) for d in range(1, 161)]
        # schema upgrade: rows written BEFORE the ALTER serve the new
        # prop's default through the columnar decode too
        assert cl.execute("ALTER EDGE E ADD (tag2 int DEFAULT 7)"
                          ).error is None
        rs = cl.execute("GO FROM 0 OVER E YIELD dst(edge) AS d, "
                        "E.tag2 AS t2")
        assert rs.error is None
        assert sorted(map(tuple, rs.data.rows)) == \
            [(d, 7) for d in range(1, 161)]
    finally:
        c.stop()


def test_cluster_fanout_one_host_overlaps():
    """Acceptance: concurrent fanout to N partitions hosted on ONE
    storaged is wall-time ≈ max(partition), not sum — the per-part
    calls multiplex over the pooled per-peer client."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1)
    try:
        cl = c.client()
        assert cl.execute("CREATE SPACE fo(partition_num=6, "
                          "vid_type=INT64)").error is None
        c.reconcile_storage()
        delay = 0.2

        def slow_hook(method):
            if method == "storage.part_stats":
                time.sleep(delay)

        c.storage_servers[0].hooks.append(slow_hook)
        store = c.graphds[0].store
        t0 = time.perf_counter()
        st = store.stats("fo")       # part_stats fanout over 6 parts
        wall = time.perf_counter() - t0
        assert st["partition_num"] == 6
        assert wall < 3.5 * delay, \
            f"fanout serialized on one host: wall={wall:.2f}s " \
            f"(serial would be {6 * delay:.1f}s)"
    finally:
        c.stop()
