"""Property-based tests (SURVEY §7: 'every kernel vs CPU oracle on
random graphs (hypothesis)') — hypothesis drives the input spaces and
shrinks failures; each property states an invariant two independent
implementations must share."""
import numpy as np
import pytest

pytest.importorskip("hypothesis")        # container without it: skip module
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

_slow = settings(max_examples=25, deadline=None,
                 suppress_health_check=[HealthCheck.too_slow])


# -- wire encoding round-trips ----------------------------------------------

_scalars = st.one_of(
    st.integers(min_value=-2**62, max_value=2**62),
    st.floats(allow_nan=False, allow_infinity=False, width=64),
    st.text(max_size=12), st.booleans(), st.none())
_values = st.recursive(
    _scalars,
    lambda inner: st.one_of(
        st.lists(inner, max_size=4),
        st.dictionaries(st.text(min_size=1, max_size=6), inner,
                        max_size=4)),
    max_leaves=12)


@_slow
@given(_values)
def test_wire_roundtrip(v):
    from nebula_tpu.graphstore import schema_wire as w
    assert w.loads(w.dumps(v)) == v


# -- native CSR builder vs the numpy fallback -------------------------------

@_slow
@given(st.integers(1, 6), st.integers(0, 120), st.integers(2, 40),
       st.integers(0, 2**31 - 1))
def test_native_coo_csr_matches_numpy(P, n_edges, n_vertices, seed):
    from nebula_tpu.native import get_lib
    from nebula_tpu.native.kernels import build_coo_csr, _numpy_coo_csr
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_vertices, n_edges, dtype=np.int64)
    dst = rng.integers(0, n_vertices, n_edges, dtype=np.int64)
    rank = rng.integers(0, 3, n_edges, dtype=np.int64)
    vmax = -(-n_vertices // P)
    src_dense = (src % vmax) * P + (src % P)     # any valid dense layout
    out_native = build_coo_csr(src_dense, dst, rank, dst, P, vmax)
    if get_lib() is None or n_edges == 0:
        return                                   # numpy-only env / trivial
    emax = out_native[-1]
    out_np = _numpy_coo_csr(src_dense.astype(np.int64),
                            dst.astype(np.int64), rank.astype(np.int64),
                            dst.astype(np.int64), P, vmax, emax)
    for a, b in zip(out_native, out_np):
        if isinstance(a, np.ndarray):
            assert np.array_equal(a, b)
        else:
            assert a == b


# -- null propagation over scalar builtins ----------------------------------

@_slow
@given(st.sampled_from(["abs", "floor", "ceil", "sqrt", "exp", "log",
                        "sign", "lower", "upper", "trim", "reverse",
                        "length", "tostring"]))
def test_scalar_functions_propagate_null(name):
    from nebula_tpu.core.functions import FUNCTIONS
    from nebula_tpu.core.value import NULL, is_null
    out = FUNCTIONS[name](None, [NULL])
    assert is_null(out), (name, out)


# -- total order over mixed values ------------------------------------------

@_slow
@given(st.lists(_scalars, max_size=12))
def test_total_order_key_sorts_consistently(vals):
    from nebula_tpu.core.value import total_order_key
    keys = [total_order_key(v) for v in vals]
    s1 = sorted(keys)
    s2 = sorted(sorted(keys, reverse=True))
    assert s1 == s2                              # deterministic total order


# -- conjunct split/join round-trip -----------------------------------------

@_slow
@given(st.integers(1, 5), st.integers(0, 2**31 - 1))
def test_split_join_conjuncts_roundtrip(n, seed):
    from nebula_tpu.core.expr import (Binary, Literal, join_conjuncts,
                                      split_conjuncts, to_text)
    rng = np.random.default_rng(seed)
    parts = [Binary(">", Literal(int(rng.integers(0, 50))),
                    Literal(int(rng.integers(0, 50)))) for _ in range(n)]
    joined = join_conjuncts(parts)
    back = split_conjuncts(joined)
    assert [to_text(p) for p in parts] == [to_text(b) for b in back]


# -- device GO vs host engine on random graphs ------------------------------

@settings(max_examples=6, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.integers(0, 2**31 - 1), st.integers(1, 3),
       st.sampled_from(["out", "in", "both"]))
def test_device_go_matches_host_on_random_graphs(seed, steps, direction):
    from test_tpu import host_go, norm_edge, random_store
    from nebula_tpu.tpu import TpuRuntime, make_mesh
    rt = _shared_rt()
    st_ = random_store(seed % 1000, n=60, avg_deg=3)
    rows, _ = rt.traverse(st_, "g", [1, 5, 9], ["knows"], direction,
                          steps)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    want = host_go(st_, "g", [1, 5, 9], ["knows"], direction, steps)
    assert got == want


_rt_box = []


def _shared_rt():
    if not _rt_box:
        from nebula_tpu.tpu import TpuRuntime, make_mesh
        _rt_box.append(TpuRuntime(make_mesh(8)))
    return _rt_box[0]


@settings(max_examples=6, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.integers(0, 2**31 - 1), st.integers(1, 3),
       st.sampled_from(["out", "in", "both"]),
       st.integers(2, 24))
def test_degree_split_go_matches_host_on_random_graphs(
        seed, steps, direction, threshold):
    """Device GO with a RANDOM degree-split threshold == host rows:
    hub sets of every size (including empty and nearly-everything)
    preserve exact results."""
    from test_tpu import host_go, norm_edge, random_store
    from nebula_tpu.utils.config import get_config
    get_config().set_dynamic("tpu_degree_split_threshold", threshold)
    try:
        rt = _shared_rt()
        st_ = random_store(seed % 1000, n=60, avg_deg=3)
        rt.pin(st_, "g", force=True)
        rows, _ = rt.traverse(st_, "g", [1, 5, 9], ["knows"], direction,
                              steps)
        got = sorted(norm_edge(e) for (_, e, _) in rows)
        want = host_go(st_, "g", [1, 5, 9], ["knows"], direction, steps)
        assert got == want
    finally:
        get_config().set_dynamic("tpu_degree_split_threshold", 0)


@settings(max_examples=5, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.integers(0, 2**31 - 1), st.integers(0, 1), st.integers(1, 3))
def test_var_len_match_device_parity_on_random_graphs(seed, m_off, span):
    """MATCH *m..n trail counting: device layered-frame assembly ==
    host DFS on random graphs over random hop windows (the subtlest
    device path — per-depth emission gates + edge distinctness)."""
    from test_tpu import random_store
    from nebula_tpu.exec.engine import QueryEngine
    rt = _shared_rt()
    st_ = random_store(seed % 1000, n=40, avg_deg=3)
    m = m_off + 1
    n = m + span - 1
    q = (f"MATCH (a:person)-[e:knows*{m}..{n}]->(b) "
         f"WHERE id(a) IN [1, 5, 9] RETURN count(*) AS c")
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st_, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, rs.error
        out.append(rs.data.rows)
    assert out[0] == out[1], (m, n, out)


# -- pattern predicates: host/device parity + brute-force oracle ------------

@settings(max_examples=8, deadline=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(st.integers(0, 2**31 - 1), st.integers(1, 2),
       st.booleans(), st.booleans())
def test_pattern_predicate_matches_bruteforce(seed, plen, negate, incoming):
    """WHERE (a)-[:knows*1..k]->() (optionally negated / incoming) on a
    random graph agrees with a brute-force adjacency oracle, and the
    host and device planes agree with each other (r5 feature)."""
    from test_tpu import random_store
    from nebula_tpu.exec.engine import QueryEngine

    st_ = random_store(seed % 1000, n=50, avg_deg=3)
    arrow = "<-[:knows*1..%d]-" % plen if incoming \
        else "-[:knows*1..%d]->" % plen
    pred = f"(a){arrow}()"
    if negate:
        pred = f"NOT {pred}"
    q = f"MATCH (a:person) WHERE {pred} RETURN id(a) AS v"

    # brute-force oracle over the raw adjacency
    sd = st_.space("g")
    adj = {}
    for p in sd.parts:
        for src, per in p.out_edges.items():
            for (rank, dst) in per.get("knows", {}):
                adj.setdefault(src, set()).add(dst)
    radj = {}
    for s_, ds_ in adj.items():
        for d_ in ds_:
            radj.setdefault(d_, set()).add(s_)
    step = radj if incoming else adj
    all_persons = {vid for p in sd.parts for vid in p.vertices}
    reach = set()
    for v in all_persons:
        frontier = {v}
        for _ in range(plen):
            frontier = set().union(*(step.get(x, set())
                                     for x in frontier)) if frontier \
                else set()
            if frontier:
                reach.add(v)
                break
    want = sorted(all_persons - reach) if negate else sorted(reach)

    outs = []
    for rt in (None, _shared_rt()):
        eng = QueryEngine(st_, tpu_runtime=rt)
        ss = eng.new_session()
        eng.execute(ss, "USE g")
        r = eng.execute(ss, q)
        assert r.error is None, (q, r.error)
        outs.append(sorted(x[0] for x in r.data.rows))
    assert outs[0] == want, f"host diverges from oracle for {q}"
    assert outs[1] == want, f"device diverges from oracle for {q}"
