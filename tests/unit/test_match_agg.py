"""TpuMatchAgg: fused fixed-length MATCH → aggregate (tpu/match_agg.py).

Parity contract: for every fusable shape, the fused device node, its
host fallback, and the general (unfused) executor chain must agree on
the multiset of result rows (MATCH aggregates are unordered).
"""
import numpy as np
import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils.config import get_config

from test_tpu import P, random_store  # noqa: E402

from nebula_tpu.tpu import TpuRuntime, make_mesh  # noqa: E402


@pytest.fixture(scope="module")
def rt():
    return TpuRuntime(make_mesh(P))


def _run(eng, s, q):
    r = eng.execute(s, q)
    assert r.error is None, f"{q} -> {r.error}"
    return sorted(map(repr, r.data.rows))


def _engines(seed, rt):
    st = random_store(seed, n=150, avg_deg=4)
    host = QueryEngine(st)
    hs = host.new_session()
    host.execute(hs, "USE g")
    dev = QueryEngine(st, tpu_runtime=rt)
    ds = dev.new_session()
    dev.execute(ds, "USE g")
    return host, hs, dev, ds


QUERIES = [
    # IC-shaped: terminal label + prop filter, group by terminal id
    ("MATCH (p:person)-[:knows]->(f)-[:knows]->(ff:person) "
     "WHERE id(p) IN [1,2,3,4] AND ff.person.age > 30 "
     "RETURN id(ff) AS v, count(*) AS c"),
    # global aggregate: plain + DISTINCT counts over two positions
    ("MATCH (p:person)-[:knows]->(f)-[:knows]->(ff) "
     "WHERE id(p) IN [0,5,6] "
     "RETURN count(*) AS c, count(DISTINCT id(ff)) AS d, "
     "count(DISTINCT id(f)) AS m"),
    # single hop
    ("MATCH (p:person)-[:knows]->(q:person) WHERE id(p) IN [2,7] "
     "RETURN id(q) AS v, count(*) AS c"),
    # 3 hops, group by a MID alias
    ("MATCH (a:person)-[:knows]->(b)-[:knows]->(c)-[:knows]->(d:person) "
     "WHERE id(a) IN [3] RETURN id(c) AS v, count(*) AS c"),
    # string predicate on the terminal
    ("MATCH (p:person)-[:knows]->(f)-[:knows]->(ff:person) "
     "WHERE id(p) IN [1,2,3,4,5] AND ff.person.name == \"ann\" "
     "RETURN id(ff) AS v, count(*) AS c"),
    # predicate on the source beyond the seed list
    ("MATCH (p:person)-[:knows]->(f)-[:knows]->(ff:person) "
     "WHERE id(p) IN [1,2,3,4,5] AND p.person.age < 50 "
     "RETURN id(ff) AS v, count(*) AS c"),
    # variable-length: global count (the config-4 benchmark shape)
    ("MATCH (a:person)-[e:knows*1..3]->(b) WHERE id(a) IN [1,2] "
     "RETURN count(*) AS c"),
    # variable-length: terminal label + grouping across depths
    ("MATCH (a:person)-[e:knows*1..3]->(b:person) WHERE id(a) IN [3] "
     "RETURN id(b) AS v, count(*) AS c"),
    # zero-hop lower bound + DISTINCT terminal
    ("MATCH (a:person)-[e:knows*0..2]->(b) WHERE id(a) IN [1] "
     "RETURN count(*) AS c, count(DISTINCT id(b)) AS d"),
    # fixed m==M spelled as a var-len pattern + terminal predicate
    ("MATCH (a:person)-[e:knows*2..2]->(b:person) "
     "WHERE id(a) IN [1,4] AND b.person.age > 30 "
     "RETURN id(b) AS v, count(*) AS c"),
]


def test_fused_plan_shape(rt):
    _, _, dev, ds = _engines(11, rt)
    r = dev.execute(ds, "EXPLAIN " + QUERIES[0])
    txt = r.data.rows[0][0]
    assert "TpuMatchAgg" in txt
    assert "steps=2" in txt
    assert "Traverse" not in txt.replace("TpuMatchAgg", "")
    # 3-hop chain fuses as steps=3
    r = dev.execute(ds, "EXPLAIN " + QUERIES[3])
    assert "steps=3" in r.data.rows[0][0]
    # var-len fuses with min_hop/var_len recorded
    r = dev.execute(ds, "EXPLAIN " + QUERIES[6])
    txt = r.data.rows[0][0]
    assert "TpuMatchAgg" in txt and "var_len=True" in txt
    # unbounded upper bound stays on the general path
    r = dev.execute(ds, "EXPLAIN MATCH (a:person)-[e:knows*1..]->(b) "
                    "WHERE id(a) IN [1] RETURN count(*) AS c")
    assert "TpuMatchAgg" not in r.data.rows[0][0]


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_device_matches_host(rt, seed, qi):
    host, hs, dev, ds = _engines(seed, rt)
    q = QUERIES[qi]
    assert _run(dev, ds, q) == _run(host, hs, q)


def test_host_fallback_matches_host(rt):
    """Flag off: the fused node's host fallback must match the unfused
    executor chain exactly."""
    host, hs, dev, ds = _engines(4, rt)
    cfg = get_config()
    old = cfg.get("tpu_match_device")
    try:
        cfg.set_dynamic("tpu_match_device", False)
        for q in QUERIES:
            assert _run(dev, ds, q) == _run(host, hs, q)
    finally:
        cfg.set_dynamic("tpu_match_device", old)


def test_unfusable_shapes_still_run(rt):
    host, hs, dev, ds = _engines(6, rt)
    qs = [
        # group key is a prop, not id() — stays on the general chain
        ("MATCH (p:person)-[:knows]->(f)-[:knows]->(ff:person) "
         "WHERE id(p) IN [1,2] RETURN ff.person.age AS a, count(*) AS c"),
        # per-hop edge predicate — stays on the general chain
        ("MATCH (p:person)-[e:knows]->(ff) WHERE id(p) IN [1,2] "
         "AND e.w > 3 RETURN id(ff) AS v, count(*) AS c"),
        # aggregate beyond count — stays on the general chain
        ("MATCH (p:person)-[:knows]->(ff:person) WHERE id(p) IN [1,2] "
         "RETURN id(ff) AS v, sum(ff.person.age) AS s"),
    ]
    for q in qs:
        r = dev.execute(ds, "EXPLAIN " + q)
        assert "TpuMatchAgg" not in r.data.rows[0][0], q
        assert _run(dev, ds, q) == _run(host, hs, q)


def test_null_id_literal_not_fused(rt):
    """id(x) != NULL answers NULL on the host (drops every row); the
    dense compare can't express that, so the shape must stay unfused —
    on BOTH planes (code-review r4 finding)."""
    from nebula_tpu.tpu.exprjit import compilable, vertex_compilable
    host, hs, dev, ds = _engines(8, rt)
    q = ("MATCH (p:person)-[:knows]->(ff) WHERE id(p) IN [1,2] "
         "AND id(ff) != NULL RETURN id(ff) AS v, count(*) AS c")
    r = dev.execute(ds, "EXPLAIN " + q)
    assert "TpuMatchAgg" not in r.data.rows[0][0]
    assert _run(dev, ds, q) == _run(host, hs, q) == []
    # edge plane: the GO endpoint-id gate refuses the same shape
    from nebula_tpu.core import expr as E
    ef = E.Binary("!=", E.FunctionCall("id", [E.VertexExpr("$$")]),
                  E.Literal(None))
    assert not compilable(ef, ["knows"])
    assert not vertex_compilable(
        E.Binary("!=", E.FunctionCall("id", [E.LabelExpr("v")]),
                 E.Literal(None)), "v")


def test_trail_semantics_with_self_loop(rt):
    """A self-loop edge may appear once per trail, not twice — the
    absorbed _edges_distinct conjunct."""
    from nebula_tpu.graphstore.schema import PropDef, PropType
    from nebula_tpu.graphstore.store import GraphStore
    st = GraphStore()
    st.create_space("g", partition_num=P, vid_type="INT64")
    st.catalog.create_tag("g", "person", [PropDef("age", PropType.INT64)])
    st.catalog.create_edge("g", "knows", [PropDef("w", PropType.INT64)])
    for v in (1, 2):
        st.insert_vertex("g", v, "person", {"age": 40})
    st.insert_edge("g", 1, "knows", 1, 0, {"w": 1})   # self loop
    st.insert_edge("g", 1, "knows", 2, 0, {"w": 1})
    st.insert_edge("g", 2, "knows", 1, 0, {"w": 1})
    q = ("MATCH (a:person)-[:knows]->(b)-[:knows]->(c) WHERE id(a) IN [1] "
         "RETURN id(c) AS v, count(*) AS c")
    host = QueryEngine(st)
    hs = host.new_session()
    host.execute(hs, "USE g")
    dev = QueryEngine(st, tpu_runtime=rt)
    ds = dev.new_session()
    dev.execute(ds, "USE g")
    assert _run(dev, ds, q) == _run(host, hs, q)


def test_vertex_predicate_compiler_matches_host_eval():
    """compile_vertex_predicate_np vs per-vertex host Expr.eval."""
    from nebula_tpu.core import expr as E
    from nebula_tpu.core.expr import to_bool3
    from nebula_tpu.exec.context import RowContext
    from nebula_tpu.graphstore.csr import build_snapshot
    from nebula_tpu.tpu.exprjit import compile_vertex_predicate_np

    st = random_store(9, n=80, avg_deg=3)
    snap = build_snapshot(st, "g")
    sd = st.space("g")
    eng = QueryEngine(st)
    s = eng.new_session()
    eng.execute(s, "USE g")
    qctx = eng.qctx

    exprs = [
        E.Binary(">", E.LabelTagProp("v", "person", "age"), E.Literal(40)),
        E.Binary("==", E.LabelTagProp("v", "person", "name"),
                 E.Literal("ann")),
        E.Binary("AND",
                 E.FunctionCall("_hastag", [E.LabelExpr("v"),
                                            E.Literal("person")]),
                 E.Binary("<=", E.LabelTagProp("v", "person", "age"),
                          E.Literal(25))),
        E.Unary("IS_NULL", E.LabelTagProp("v", "nosuch", "p")),
        E.Binary("IN", E.LabelTagProp("v", "person", "name"),
                 E.ListExpr([E.Literal("bob"), E.Literal("dee")])),
    ]
    dense = np.arange(60, dtype=np.int64)
    d2v = {d: sd.dense_to_vid[d] for d in dense.tolist()}
    for ex in exprs:
        mask = compile_vertex_predicate_np(ex, "v", snap, sd)(dense)
        for i, d in enumerate(dense.tolist()):
            full = qctx.build_vertex("g", d2v[d])
            want = False
            if full is not None:
                rc = RowContext(qctx, "g", {"v": full})
                want = to_bool3(ex.eval(rc)) is True
            assert bool(mask[i]) == want, (E.to_text(ex), d2v[d])
