"""TPU device-plane tests: every kernel against its host oracle on the
8-device virtual CPU mesh (conftest sets XLA_FLAGS / JAX_PLATFORMS), per
SURVEY §4's CPU-oracle strategy."""
import random

import numpy as np
import pytest

from nebula_tpu.core.value import NULL
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.csr import build_snapshot, expand_frontier_host
from nebula_tpu.graphstore.schema import PropDef, PropType
from nebula_tpu.graphstore.store import GraphStore

tpu = pytest.importorskip("nebula_tpu.tpu")
from nebula_tpu.tpu import TpuRuntime, make_mesh, pin_snapshot  # noqa: E402
from nebula_tpu.tpu.exprjit import compilable, compile_predicate  # noqa: E402

P = 8


def random_store(seed=0, n=120, avg_deg=5, spacename="g",
                 extra_edge_type=False):
    rng = random.Random(seed)
    st = GraphStore()
    st.create_space(spacename, partition_num=P, vid_type="INT64")
    st.catalog.create_tag(spacename, "person", [
        PropDef("age", PropType.INT64), PropDef("name", PropType.STRING)])
    st.catalog.create_edge(spacename, "knows", [
        PropDef("w", PropType.INT64), PropDef("f", PropType.DOUBLE),
        PropDef("tag", PropType.STRING)])
    if extra_edge_type:
        st.catalog.create_edge(spacename, "likes", [
            PropDef("w", PropType.INT64)])
    names = ["ann", "bob", "cid", "dee"]
    for v in range(n):
        st.insert_vertex(spacename, v, "person",
                         {"age": rng.randint(0, 80), "name": rng.choice(names)})
    for v in range(n):
        for _ in range(rng.randint(0, avg_deg * 2)):
            d = rng.randrange(n)
            props = {"w": rng.randint(-5, 100) if rng.random() > .1 else NULL,
                     "f": rng.uniform(0, 1), "tag": rng.choice(names)}
            st.insert_edge(spacename, v, "knows", d, rng.randint(0, 2), props)
        if extra_edge_type and rng.random() > .5:
            st.insert_edge(spacename, v, "likes", rng.randrange(n), 0,
                           {"w": rng.randint(0, 10)})
    return st


def norm_edge(e):
    """Same normalization as the src()/dst() builtins: reversed edges
    (etype<0) report their stored orientation."""
    if e.etype >= 0:
        return repr([e.src, e.name, e.ranking, e.dst])
    return repr([e.dst, e.name, e.ranking, e.src])


def host_go(st, space, vids, etypes, direction, steps, where_text=None):
    """Host-truth GO result as a sorted list of (src, etype, rank, dst)."""
    eng = QueryEngine(st)
    s = eng.new_session()
    eng.execute(s, f"USE {space}")
    w = f" WHERE {where_text}" if where_text else ""
    q = (f"GO {steps} STEPS FROM {', '.join(map(str, vids))} "
         f"OVER {', '.join(etypes)}"
         + (" REVERSELY" if direction == "in" else
            " BIDIRECT" if direction == "both" else "")
         + w + " YIELD src(edge), type(edge), rank(edge), dst(edge)")
    rs = eng.execute(s, q)
    assert rs.error is None, f"{q} -> {rs.error}"
    return sorted(map(repr, rs.data.rows))


@pytest.fixture(scope="module")
def rt():
    return TpuRuntime(make_mesh(P))


def test_pin_and_hbm(rt):
    st = random_store(1)
    dev = rt.pin(st, "g")
    assert dev.num_parts == P
    assert dev.hbm_bytes() > 0
    # same epoch → cached object
    assert rt.pin(st, "g") is dev
    # write bumps epoch → re-pin
    st.insert_edge("g", 0, "knows", 1, 9, {"w": 1, "f": .5, "tag": "x"})
    dev2 = rt.pin(st, "g")
    assert dev2 is not dev and dev2.epoch != dev.epoch


@pytest.mark.parametrize("steps", [1, 2, 3])
@pytest.mark.parametrize("direction", ["out", "in", "both"])
def test_traverse_matches_host(rt, steps, direction):
    st = random_store(2)
    sources = [3, 17, 44]
    rows, stats = rt.traverse(st, "g", sources, ["knows"], direction, steps)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    want = host_go(st, "g", sources, ["knows"], direction, steps)
    assert got == want
    assert stats.edges_traversed() >= len(rows)


def test_traverse_multi_etype(rt):
    st = random_store(3, extra_edge_type=True)
    rows, _ = rt.traverse(st, "g", [1, 2, 3], ["knows", "likes"], "out", 2)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    want = host_go(st, "g", [1, 2, 3], ["knows", "likes"], "out", 2)
    assert got == want


def test_frontier_oracle(rt):
    """One-hop device frontier == expand_frontier_host on the raw CSR."""
    st = random_store(4)
    snap = build_snapshot(st, "g")
    blk = snap.block("knows", "out")
    sd = st.space("g")
    dense = [sd.dense_id(v) for v in [5, 9]]
    want = expand_frontier_host(snap, blk, np.asarray(dense, np.int32))
    # run a 2-step traverse and recover its intermediate frontier from the
    # final hop's sources
    rows, _ = rt.traverse(st, "g", [5, 9], ["knows"], "out", 2)
    springs = sorted({sd.dense_id(e.src) for (_, e, _) in rows})
    # sources of hop 2 ⊆ hop-1 frontier; vertices with no out-edges appear
    # in `want` but not as hop-2 sources
    assert set(springs) <= set(int(x) for x in want)


@pytest.mark.parametrize("where", [
    "knows.w > 30",
    "knows.w >= 10 AND knows.w < 60",
    "knows.f < 0.5 OR knows.w == 7",
    "knows.tag == \"ann\"",
    "knows.tag != \"bob\" AND knows.w % 2 == 0",
    "knows.w IS NOT NULL AND knows.w * 2 + 1 > 21",
    "knows.w IN [1, 2, 3, 40, 41, 42, 43, 44]",
    "rank(edge) == 1",
    "id($$) == 9",
    "id($$) != 9 AND knows.w > 20",
    "id($$) IN [5, 9, 14, 999999]",
    "id($$) NOT IN [5, 9]",
    "id($^) == 3",
    "NOT (knows.w > 10)",
    "knows.w / 3 > 5",
    "(knows.w & 1) == 0",
    "(knows.w ^ 3) > 40",
    "(knows.w | 8) < 60",
])
def test_predicate_parity(rt, where):
    st = random_store(5)
    from nebula_tpu.query.parser import parse
    stmt = parse(f"GO 2 STEPS FROM 3, 17 OVER knows WHERE {where} "
                 f"YIELD src(edge), type(edge), rank(edge), dst(edge)")
    cond = stmt.where.filter if stmt.where else None
    assert cond is not None
    assert compilable(cond, ["knows"]), where
    rows, _ = rt.traverse(st, "g", [3, 17], ["knows"], "out", 2,
                          edge_filter=cond)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    want = host_go(st, "g", [3, 17], ["knows"], "out", 2, where)
    assert got == want, where


def test_not_compilable():
    from nebula_tpu.query.parser import parse
    for w in ["knows.tag CONTAINS \"a\"",
              "knows.tag =~ \"a.*\"",
              "id($$) + 1 == 3",
              "id($$) == id($^)"]:
        stmt = parse(f"GO FROM 1 OVER knows WHERE {w} YIELD dst(edge)")
        assert not compilable(stmt.where.filter, ["knows"]), w


def test_string_ordering_falls_back(rt):
    """String ordering passes the structural gate but fails typed compile;
    the executor must fall back to the host path with identical rows."""
    st = random_store(5)
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    q = ('GO 2 STEPS FROM 3, 17 OVER knows WHERE knows.tag < "m" '
         'YIELD src(edge), rank(edge), dst(edge)')
    rs = eng.execute(s, q)
    assert rs.error is None, rs.error
    want = QueryEngine(st)
    s2 = want.new_session()
    want.execute(s2, "USE g")
    rs2 = want.execute(s2, q)
    assert sorted(map(repr, rs.data.rows)) == sorted(map(repr, rs2.data.rows))


def test_bucket_escalation(rt):
    """Tiny initial buckets must converge via doubling, same answer."""
    st = random_store(6, n=200, avg_deg=8)
    small = TpuRuntime(make_mesh(P))
    small.init_eb = 4
    rows, stats = small.traverse(st, "g", [1, 2, 3, 4], ["knows"], "out", 3)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    want = host_go(st, "g", [1, 2, 3, 4], ["knows"], "out", 3)
    assert got == want
    assert stats.retries > 0


@pytest.mark.parametrize("direction", ["", " REVERSELY", " BIDIRECT"])
def test_find_shortest_path_device_parity(rt, direction):
    """Device BFS + host reconstruction must yield the exact path rows of
    the host multi-parent BFS, for every direction."""
    st = random_store(11, n=80, avg_deg=4)
    eng_tpu = QueryEngine(st, tpu_runtime=rt)
    eng_cpu = QueryEngine(st)
    pairs = [(1, 40), (3, 9), (17, 2), (5, 77)]
    for (a, b) in pairs:
        q = (f"FIND SHORTEST PATH FROM {a} TO {b} OVER knows{direction} "
             f"UPTO 5 STEPS YIELD path AS p")
        got = {}
        for eng in (eng_tpu, eng_cpu):
            s = eng.new_session()
            eng.execute(s, "USE g")
            rs = eng.execute(s, q)
            assert rs.error is None, (q, rs.error)
            got[id(eng)] = sorted(map(repr, rs.data.rows))
        assert got[id(eng_tpu)] == got[id(eng_cpu)], q
    # the device plane actually served — no silent host fallback
    assert getattr(eng_tpu.qctx, "last_tpu_fallback", None) is None


def test_find_shortest_multi_src_dst_device_parity(rt):
    st = random_store(12, n=60, avg_deg=4)
    q = ("FIND SHORTEST PATH FROM 1, 2, 3 TO 30, 31 OVER knows "
         "UPTO 4 STEPS YIELD path AS p")
    res = {}
    for tpu_on in (True, False):
        eng = QueryEngine(st, tpu_runtime=rt if tpu_on else None)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, rs.error
        res[tpu_on] = sorted(map(repr, rs.data.rows))
    assert res[True] == res[False]


def test_engine_fusion_end_to_end(rt):
    """Same query, optimizer TPU rule ON vs OFF → identical row multisets,
    and the fused plan actually contains TpuTraverse."""
    st = random_store(7)
    eng_cpu = QueryEngine(st)
    eng_tpu = QueryEngine(st, tpu_runtime=rt)
    q = ("GO 3 STEPS FROM 3, 17, 44 OVER knows WHERE knows.w > 10 "
         "YIELD src(edge) AS s, dst(edge) AS d, knows.w AS w")
    for eng in (eng_cpu, eng_tpu):
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, rs.error
        eng._last = sorted(map(repr, rs.data.rows))
    assert eng_cpu._last == eng_tpu._last

    s = eng_tpu.new_session()
    eng_tpu.execute(s, "USE g")
    rs = eng_tpu.execute(s, "EXPLAIN " + q)
    assert "TpuTraverse" in rs.data.rows[0][0]
    s2 = eng_cpu.new_session()
    eng_cpu.execute(s2, "USE g")
    rs = eng_cpu.execute(s2, "EXPLAIN " + q)
    assert "TpuTraverse" not in rs.data.rows[0][0]


def test_mton_and_piped_go_parity(rt):
    """m-TO-n GO and $- piped GO may fuse sub-chains (single-use 1-step
    heads) but must keep exact row parity with the host path."""
    st = random_store(8)
    qs = ["GO 1 TO 3 STEPS FROM 3 OVER knows YIELD src(edge), dst(edge)",
          "GO FROM 3 OVER knows YIELD dst(edge) AS d "
          "| GO FROM $-.d OVER knows YIELD $-.d, dst(edge)"]
    for q in qs:
        out = []
        for tpu_rt in (None, rt):
            eng = QueryEngine(st, tpu_runtime=tpu_rt)
            s = eng.new_session()
            eng.execute(s, "USE g")
            rs = eng.execute(s, q)
            assert rs.error is None, f"{q} -> {rs.error}"
            out.append(sorted(map(repr, rs.data.rows)))
        assert out[0] == out[1], q


def test_write_invalidates_snapshot(rt):
    st = random_store(9)
    rows1, _ = rt.traverse(st, "g", [3], ["knows"], "out", 1)
    st.insert_edge("g", 3, "knows", 99, 7, {"w": 50, "f": .1, "tag": "zz"})
    rows2, _ = rt.traverse(st, "g", [3], ["knows"], "out", 1)
    assert len(rows2) == len(rows1) + 1


def test_single_chip_local_mode():
    """Mesh of 1 device serves an 8-partition space via the vmap driver —
    the real-TPU bench configuration."""
    st = random_store(11)
    rt1 = TpuRuntime(make_mesh(1))
    assert rt1.local_mode
    rows, stats = rt1.traverse(st, "g", [3, 17, 44], ["knows"], "out", 3)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    want = host_go(st, "g", [3, 17, 44], ["knows"], "out", 3)
    assert got == want


def test_temporal_and_overflow_predicates_fall_back(rt):
    """Code-review regressions: DATETIME-vs-int compares and out-of-int64
    literals must produce host-identical results (via fallback)."""
    st = GraphStore()
    st.create_space("t", partition_num=P, vid_type="INT64")
    st.catalog.create_edge("t", "e", [PropDef("ts", PropType.DATETIME),
                                      PropDef("w", PropType.INT64)])
    from nebula_tpu.core.value import DateTime
    st.insert_edge("t", 1, "e", 2, 0, {"ts": DateTime(2020, 5, 1, 12), "w": 3})
    st.insert_edge("t", 2, "e", 3, 0, {"ts": DateTime(2021, 6, 2, 13), "w": 4})
    for q in [
        # datetime-vs-datetime compares refuse device compilation (the
        # encodings are order-isomorphic but the mask compiler keeps
        # temporal kinds distinct); datetime-vs-INT is now rejected
        # upstream by the validator's type deduction
        'GO 2 STEPS FROM 1 OVER e WHERE e.ts > datetime("2020-12-01T00:00:00") '
        "YIELD src(edge), dst(edge)",
        "GO 2 STEPS FROM 1 OVER e WHERE e.w < 99999999999999999999999 "
        "YIELD src(edge), dst(edge)",
        "GO 2 STEPS FROM 1 OVER e WHERE e.w IN [\"x\", 3] "
        "YIELD src(edge), dst(edge)",
    ]:
        out = []
        for tr in (None, rt):
            eng = QueryEngine(st, tpu_runtime=tr)
            s = eng.new_session()
            eng.execute(s, "USE t")
            r = eng.execute(s, q)
            assert r.error is None, (q, r.error)
            out.append(sorted(map(repr, r.data.rows)))
        assert out[0] == out[1], q


def test_pre_epoch_datetime_roundtrip():
    """Encoding must be monotonic and lossless across the 1970 epoch."""
    from nebula_tpu.core.value import DateTime
    from nebula_tpu.graphstore.csr import (StringPool, decode_prop,
                                           encode_prop)
    pool = StringPool()
    vals = [DateTime(1944, 6, 6, 6, 30, 0, 1),
            DateTime(1969, 12, 31, 23, 59, 59, 500000),
            DateTime(1970, 1, 1, 0, 0, 0, 0),
            DateTime(1970, 1, 1, 0, 0, 0, 250000),
            DateTime(2024, 2, 29, 23, 59, 59, 999999)]
    enc = [encode_prop(PropType.DATETIME, v, pool) for v in vals]
    assert enc == sorted(enc)
    for v, e in zip(vals, enc):
        assert decode_prop(PropType.DATETIME, e, pool) == v


def test_yield_fusion_columnar_parity(rt):
    """Project(go_row) absorbed into TpuTraverse: all yieldable column
    shapes (src/dst/rank/type/typeid, edge props incl. strings, literal,
    reverse direction) match the host path row-for-row."""
    st = random_store(13)
    qs = [
        "GO 2 STEPS FROM 3, 17 OVER knows "
        "YIELD src(edge) AS s, dst(edge) AS d, rank(edge) AS r, "
        "type(edge) AS t, knows.w AS w, knows.tag AS g, 7 AS c",
        "GO 2 STEPS FROM 3, 17 OVER knows REVERSELY "
        "YIELD src(edge), dst(edge), knows.tag",
        "GO 3 STEPS FROM 3 OVER knows WHERE knows.w > 20 "
        "YIELD dst(edge), knows.w, knows.f",
    ]
    for q in qs:
        out = []
        for tpu_rt in (None, rt):
            eng = QueryEngine(st, tpu_runtime=tpu_rt)
            s = eng.new_session()
            eng.execute(s, "USE g")
            rs = eng.execute(s, q)
            assert rs.error is None, f"{q} -> {rs.error}"
            out.append(sorted(map(repr, rs.data.rows)))
        assert out[0] == out[1], q

    # the fused plan carries the yields (no separate Project above)
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    rs = eng.execute(s, "EXPLAIN " + qs[0])
    desc = rs.data.rows[0][0]
    assert "TpuTraverse" in desc and "yields" in desc
    assert desc.strip().startswith("TpuTraverse"), desc


def test_non_yieldable_keeps_project(rt):
    """$$-prop yields can't be columnar: Project survives, the chain
    below still fuses, and parity holds."""
    st = random_store(14)
    q = ("GO 2 STEPS FROM 3 OVER knows "
         "YIELD dst(edge) AS d, $$.person.age AS a")
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, rs.error
        out.append(sorted(map(repr, rs.data.rows)))
    assert out[0] == out[1]
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    rs = eng.execute(s, "EXPLAIN " + q)
    desc = rs.data.rows[0][0]
    assert "Project" in desc and "TpuTraverse" in desc


# ---------------------------------------------------------------------------
# MATCH device plane (Traverse via layered hop frames)
# ---------------------------------------------------------------------------


MATCH_QS = [
    # fixed 1-hop with edge alias + props
    "MATCH (a:person)-[e:knows]->(b) WHERE id(a) IN [3, 17, 44] "
    "RETURN id(a), e.w, rank(e), id(b)",
    # reverse and undirected
    "MATCH (a:person)<-[e:knows]-(b) WHERE id(a) == 7 RETURN id(b), e.w",
    "MATCH (a:person)-[e:knows]-(b) WHERE id(a) == 7 RETURN id(b), rank(e)",
    # variable-length: *1..3, *0..2, exact *2
    "MATCH (a:person)-[e:knows*1..3]->(b) WHERE id(a) == 5 "
    "RETURN id(b), size(e)",
    "MATCH (a:person)-[e:knows*0..2]->(b) WHERE id(a) IN [3, 9] "
    "RETURN id(a), id(b)",
    "MATCH (a:person)-[e:knows*2]->(b) WHERE id(a) IN [1, 2] "
    "RETURN id(b)",
    # inline edge-prop predicate (device-compiled per-hop mask)
    "MATCH (a:person)-[e:knows*1..2 {tag: 'ann'}]->(b) WHERE id(a) IN "
    "[3, 17] RETURN id(b), size(e)",
    # longer pattern: two fixed hops + node filter
    "MATCH (a:person)-[e1:knows]->(m)-[e2:knows]->(b:person) "
    "WHERE id(a) == 5 AND b.person.age > 30 RETURN id(m), id(b)",
]


@pytest.mark.parametrize("q", MATCH_QS)
def test_match_traverse_device_parity(rt, q):
    """MATCH Traverse runs on the device plane (layered hop frames +
    host trail assembly) with identical result rows to the host DFS."""
    st = random_store(21)
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, f"{q} -> {rs.error}"
        out.append(sorted(map(repr, rs.data.rows)))
    assert out[0] == out[1], q


def test_match_device_engages(rt):
    """The device plane actually runs (stats recorded), and the flag
    turns it off."""
    from nebula_tpu.utils.config import get_config
    st = random_store(22)
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    q = "MATCH (a:person)-[e:knows*1..3]->(b) WHERE id(a) == 5 RETURN id(b)"
    rs = eng.execute(s, q)
    assert rs.error is None
    st_stats = eng.qctx.last_tpu_stats
    assert st_stats is not None and st_stats.steps == 3
    assert st_stats.edges_traversed() > 0
    want = sorted(map(repr, rs.data.rows))

    get_config().set_dynamic("tpu_match_device", False)
    try:
        eng2 = QueryEngine(st, tpu_runtime=rt)
        s2 = eng2.new_session()
        eng2.execute(s2, "USE g")
        rs2 = eng2.execute(s2, q)
        assert eng2.qctx.last_tpu_stats is None
        assert sorted(map(repr, rs2.data.rows)) == want
    finally:
        get_config().set_dynamic("tpu_match_device", True)


def test_match_multi_etype_prop_pred_hybrid(rt):
    """Multi-etype pattern with an inline prop predicate can't compile a
    device mask — frames come back unfiltered and edge_ok re-checks on
    host during assembly.  Rows must still match the pure host path."""
    st = random_store(23, extra_edge_type=True)
    q = ("MATCH (a:person)-[e:knows|likes*1..2 {w: 1}]->(b) "
         "WHERE id(a) IN [1, 2, 3, 4, 5] RETURN id(b), size(e)")
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, rs.error
        out.append(sorted(map(repr, rs.data.rows)))
    assert out[0] == out[1]


def test_serve_while_repin_stress(rt):
    """Systematic epoch-fencing check (SURVEY §5 race detection): query
    threads traverse while a writer mutates the store (each write bumps
    the epoch and forces a re-pin).  Every result must be internally
    consistent — a traversal may serve the pre- or post-write snapshot,
    but never a torn mix, and the final settled result must equal the
    host oracle.

    The jaxlib CPU race this used to flake on (CHANGES.md PR 6 note:
    concurrent jitted dispatches deadlocking against a device_put,
    2/20 runs) is closed by TpuRuntime's dispatch-vs-repin read-write
    gate (ISSUE 9): dispatches share, a re-pin drains and excludes
    them.  ALARM-GUARDED: the workers are daemon threads joined with a
    timeout, so a regression fails in seconds with the live thread
    stacks instead of wedging the whole 870 s tier-1 budget."""
    import threading
    import time as _time

    st = random_store(31)
    errs = []
    baseline = len(rt.traverse(st, "g", [3], ["knows"], "out", 2)[0])

    def writer():
        for i in range(12):
            st.insert_edge("g", 3, "knows", 200 + i, 0,
                           {"w": 5, "f": .5, "tag": "zz"})

    def reader():
        try:
            prev = baseline
            for _ in range(10):
                rows, _ = rt.traverse(st, "g", [3], ["knows"], "out", 2)
                # monotone: writer only ADDS edges reachable from the
                # seed, so a consistent snapshot can never shrink
                assert len(rows) >= prev, (len(rows), prev)
                prev = len(rows)
        except Exception as ex:  # noqa: BLE001
            errs.append(ex)

    ts = [threading.Thread(target=writer, daemon=True)] + \
        [threading.Thread(target=reader, daemon=True) for _ in range(2)]
    for t in ts:
        t.start()
    deadline = _time.monotonic() + 120.0
    stuck = []
    for t in ts:
        t.join(timeout=max(deadline - _time.monotonic(), 0.1))
        if t.is_alive():
            stuck.append(t.name)
    if stuck:
        from nebula_tpu.utils.workload import _thread_stacks
        dump = "\n".join(f"--- {k}\n" + "\n".join(v[-4:])
                         for k, v in _thread_stacks().items())
        pytest.fail(f"serve-while-repin deadlock: {stuck} still alive "
                    f"after 120s\n{dump}")
    assert not errs, errs
    # settled: device result equals host oracle exactly
    rows, _ = rt.traverse(st, "g", [3], ["knows"], "out", 2)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    assert got == host_go(st, "g", [3], ["knows"], "out", 2)


def test_dispatch_gate_semantics(rt):
    """The dispatch-vs-repin gate (ISSUE 9): readers share; a writer
    excludes readers AND blocks new ones while waiting (writer
    preference, so a dispatch stream cannot starve an epoch bump)."""
    import threading
    import time as _time

    from nebula_tpu.tpu.runtime import _DispatchGate
    g = _DispatchGate()
    log = []
    r1_in = threading.Event()
    release_r1 = threading.Event()

    def reader1():
        g.acquire_read()
        log.append("r1+")
        r1_in.set()
        release_r1.wait(5)
        log.append("r1-")
        g.release_read()

    def writer():
        r1_in.wait(5)
        log.append("w?")
        g.acquire_write()          # blocks until r1 releases
        log.append("w+")
        g.release_write()

    t1 = threading.Thread(target=reader1, daemon=True)
    tw = threading.Thread(target=writer, daemon=True)
    t1.start()
    tw.start()
    r1_in.wait(5)
    # wait until the writer is REGISTERED as waiting (polling the
    # gate's own counter — a blind sleep races thread scheduling on a
    # loaded test VM)
    t0 = _time.monotonic()
    while g._writers_waiting == 0 and _time.monotonic() - t0 < 5.0:
        _time.sleep(0.005)
    assert g._writers_waiting == 1, "writer never queued"
    got2 = []

    def reader2():
        g.acquire_read()           # writer waiting → must block
        got2.append(True)
        g.release_read()

    t2 = threading.Thread(target=reader2, daemon=True)
    t2.start()
    _time.sleep(0.1)
    assert not got2, "reader overtook a waiting writer"
    release_r1.set()
    tw.join(5)
    t2.join(5)
    assert log[-1] == "w+" or "w+" in log
    assert got2 == [True]
    t1.join(5)


def test_failpoint_delayed_dispatch_stall_dump(rt):
    """Acceptance shape (ISSUE 9): a failpoint-delayed device dispatch
    produces a stall capture — thread stacks + the in-flight dispatch
    table + the kernel-ledger tail — while the query's rows stay
    byte-identical to an uninstrumented run (the watchdog observes,
    never touches)."""
    import threading
    import time as _time

    from nebula_tpu.utils.config import get_config
    from nebula_tpu.utils.failpoints import fail
    from nebula_tpu.utils.workload import stall_watchdog

    st = random_store(62)
    want, _ = rt.traverse(st, "g", [3], ["knows"], "out", 2)
    want = sorted(norm_edge(e) for (_, e, _) in want)
    stall_watchdog().clear()
    get_config().set_dynamic("stall_threshold_secs", 0.05)
    fail.arm("tpu:dispatch_gate", "1*delay(0.4)")
    try:
        box = {}

        def run():
            box["rows"], _ = rt.traverse(st, "g", [3], ["knows"],
                                         "out", 2)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t0 = _time.monotonic()
        found = []
        while _time.monotonic() - t0 < 5.0 and not found:
            # poll the RING, not scan_once()'s return — the engine's
            # background watchdog may win the capture race
            stall_watchdog().scan_once()
            found = [e for e in stall_watchdog().list()
                     if e["kind"] == "dispatch"]
            _time.sleep(0.02)
        t.join(30)
        assert len(found) == 1, "delayed dispatch was never captured"
        summ = found[0]
        full = stall_watchdog().get(summ["id"])
        assert full["stacks"], "no thread stacks in the stall dump"
        assert isinstance(full["kernels"], list)
        assert full["subject"]["state"] == "queued"
        got = sorted(norm_edge(e) for (_, e, _) in box["rows"])
        assert got == want, "stall capture perturbed the result rows"
    finally:
        fail.reset()
        stall_watchdog().clear()
        get_config().dynamic_layer.pop("stall_threshold_secs", None)


def test_dispatch_queue_accounting(rt):
    """Every device dispatch reports its wait-vs-run decomposition:
    tpu_dispatch_queue_us{kernel} moves, TraverseStats carries queue_s,
    the queue-depth gauge settles back to zero, and the dispatch table
    is empty once the statement finishes (ISSUE 9)."""
    from nebula_tpu.utils.stats import stats as _stats
    from nebula_tpu.utils.workload import dispatch_table

    st = random_store(61)
    before = _stats().snapshot().get(
        "tpu_dispatch_queue_us{kernel=traverse}.count", 0)
    rows, tstats = rt.traverse(st, "g", [3], ["knows"], "out", 2)
    assert rows
    assert tstats.queue_s >= 0.0
    snap = _stats().snapshot()
    assert snap.get("tpu_dispatch_queue_us{kernel=traverse}.count",
                    0) > before
    assert snap.get("tpu_dispatch_queue_depth", 0) == 0
    assert len(dispatch_table()) == 0


SUBGRAPH_QS = [
    'GET SUBGRAPH 2 STEPS FROM 3 YIELD VERTICES AS v, EDGES AS e',
    'GET SUBGRAPH 3 STEPS FROM 3, 17 BOTH knows YIELD VERTICES AS v, '
    'EDGES AS e',
    'GET SUBGRAPH 2 STEPS FROM 5 OUT knows YIELD VERTICES AS v, EDGES AS e',
    'GET SUBGRAPH 2 STEPS FROM 5 IN knows YIELD VERTICES AS v, EDGES AS e',
    'GET SUBGRAPH WITH PROP 2 STEPS FROM 3 OUT knows YIELD VERTICES AS v, '
    'EDGES AS e',
    'GET SUBGRAPH 2 STEPS FROM 3 OUT knows WHERE knows.w > 30 '
    'YIELD VERTICES AS v, EDGES AS e',
    'GET SUBGRAPH 1 STEPS FROM 44 YIELD EDGES AS e',
    # non-compilable predicate: frames come back unfiltered and the
    # shared assembler's edge_ok host re-check prunes during replay
    'GET SUBGRAPH 2 STEPS FROM 3 OUT knows WHERE knows.tag CONTAINS "a" '
    'YIELD VERTICES AS v, EDGES AS e',
]


@pytest.mark.parametrize("q", SUBGRAPH_QS)
def test_subgraph_device_parity(rt, q):
    """GET SUBGRAPH rides the device hop-frame plane with rows
    byte-identical (including intra-cell list order) to the host BFS."""
    st = random_store(41)
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, f"{q} -> {rs.error}"
        out.append([[repr(c) for c in row] for row in rs.data.rows])
    assert out[0] == out[1], q


def test_subgraph_device_engages(rt):
    st = random_store(42)
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    rs = eng.execute(s, 'GET SUBGRAPH 2 STEPS FROM 3 OUT knows '
                        'YIELD VERTICES AS v, EDGES AS e')
    assert rs.error is None
    assert eng.qctx.last_tpu_stats is not None
    assert eng.qctx.last_tpu_stats.edges_traversed() > 0


PATH_QS = [
    'FIND ALL PATH FROM 3 TO 44 OVER knows UPTO 3 STEPS YIELD path AS p',
    'FIND ALL PATH FROM 3, 17 TO 44, 5 OVER knows UPTO 4 STEPS '
    'YIELD path AS p',
    'FIND NOLOOP PATH FROM 3 TO 44 OVER knows UPTO 4 STEPS YIELD path AS p',
    'FIND ALL PATH WITH PROP FROM 3 TO 44 OVER knows UPTO 3 STEPS '
    'YIELD path AS p',
    'FIND ALL PATH FROM 3 TO 3 OVER knows UPTO 3 STEPS YIELD path AS p',
]


@pytest.mark.parametrize("q", PATH_QS)
def test_find_path_device_parity(rt, q):
    """FIND ALL/NOLOOP PATH rides the device hop-frame plane with rows
    identical to the host DFS."""
    st = random_store(51)
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, f"{q} -> {rs.error}"
        out.append([[repr(c) for c in row] for row in rs.data.rows])
    assert out[0] == out[1], q


def test_find_path_device_engages(rt):
    st = random_store(52)
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    rs = eng.execute(s, 'FIND ALL PATH FROM 3 TO 44 OVER knows '
                        'UPTO 3 STEPS YIELD path AS p')
    assert rs.error is None
    assert eng.qctx.last_tpu_stats is not None


SHORTEST_FILTER_QS = [
    'FIND SHORTEST PATH FROM 3 TO 44 OVER knows WHERE knows.w > 20 '
    'UPTO 5 STEPS YIELD path AS p',
    'FIND SHORTEST PATH FROM 3 TO 44, 17 OVER knows WHERE knows.w >= 10 '
    'UPTO 4 STEPS YIELD path AS p',
    # non-compilable predicate → CannotCompile → host fallback, same rows
    'FIND SHORTEST PATH FROM 3 TO 44 OVER knows '
    'WHERE knows.tag CONTAINS "a" UPTO 5 STEPS YIELD path AS p',
]


@pytest.mark.parametrize("q", SHORTEST_FILTER_QS)
def test_filtered_shortest_path_device_parity(rt, q):
    """FIND SHORTEST PATH WHERE <pred> runs the masked device BFS (or
    falls back for non-compilable predicates) with host-identical
    rows."""
    st = random_store(61)
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, f"{q} -> {rs.error}"
        out.append([[repr(c) for c in row] for row in rs.data.rows])
    assert out[0] == out[1], q


def test_filtered_shortest_path_multi_etype_falls_back(rt):
    """A prop predicate over multiple edge types can't compile one mask
    (exprjit forbids it); filtered shortest path must fall back to the
    host with identical rows, not KeyError."""
    st = random_store(62, extra_edge_type=True)
    q = ('FIND SHORTEST PATH FROM 3 TO 44 OVER knows, likes '
         'WHERE knows.w > 1 UPTO 4 STEPS YIELD path AS p')
    out = []
    for tpu_rt in (None, rt):
        eng = QueryEngine(st, tpu_runtime=tpu_rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, f"{q} -> {rs.error}"
        out.append([[repr(c) for c in row] for row in rs.data.rows])
    assert out[0] == out[1]


def test_bfs_single_compile_at_static_bounds(rt):
    """BFS buckets derive from static bounds (frontier <= vmax, hop
    edges <= padded Emax) so even a 1-seed BFS over a larger graph
    converges with ZERO escalation retries — the recompile ladder is
    the dominant first-run cost on a tunneled chip."""
    st = random_store(71, n=600, avg_deg=8)
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    rs = eng.execute(s, 'FIND SHORTEST PATH FROM 3 TO 599 OVER knows '
                        'UPTO 6 STEPS YIELD path AS p')
    assert rs.error is None, rs.error
    stats = eng.qctx.last_tpu_stats
    assert stats is not None
    assert stats.retries == 0, f"BFS escalated {stats.retries}x"


@pytest.mark.parametrize("seed", [101, 202, 303, 404, 505])
def test_device_parity_fuzz(rt, seed):
    """Randomized cross-surface parity sweep: for each random graph, a
    battery of GO / MATCH / SUBGRAPH / PATH / shortest queries must
    produce byte-identical rows host vs device (the 'identical result
    rows' north-star criterion, exercised beyond the hand-picked
    cases)."""
    import random as _r
    rng = _r.Random(seed)
    st = random_store(seed, n=rng.randint(60, 200),
                      avg_deg=rng.randint(3, 9))
    a, b = rng.randint(0, 59), rng.randint(0, 59)
    w = rng.randint(5, 60)
    qs = [
        f'GO {rng.randint(1, 3)} STEPS FROM {a} OVER knows '
        f'YIELD dst(edge) AS d, knows.w AS w',
        f'GO 2 STEPS FROM {a}, {b} OVER knows WHERE knows.w > {w} '
        f'YIELD src(edge) AS s, dst(edge) AS d',
        f'MATCH (x:person)-[e:knows*1..{rng.randint(2, 3)}]->(y) '
        f'WHERE id(x) == {a} RETURN id(y), size(e)',
        f'GET SUBGRAPH {rng.randint(1, 2)} STEPS FROM {a} OUT knows '
        f'YIELD VERTICES AS v, EDGES AS e',
        f'FIND ALL PATH FROM {a} TO {b} OVER knows UPTO 3 STEPS '
        f'YIELD path AS p',
        f'FIND SHORTEST PATH FROM {a} TO {b} OVER knows '
        f'WHERE knows.w > {w // 2} UPTO 4 STEPS YIELD path AS p',
    ]
    for q in qs:
        out = []
        for tpu_rt in (None, rt):
            eng = QueryEngine(st, tpu_runtime=tpu_rt)
            s = eng.new_session()
            eng.execute(s, "USE g")
            rs = eng.execute(s, q)
            assert rs.error is None, f"[seed {seed}] {q} -> {rs.error}"
            out.append(sorted(
                [[repr(c) for c in row] for row in rs.data.rows]))
        assert out[0] == out[1], f"[seed {seed}] {q}"


def test_pack_unpack_exchange_roundtrip():
    """The bit-packed frontier exchange: pack → OR → unpack must equal
    the bool OR for arbitrary mark matrices (incl. non-multiple-of-32
    vmax, empty, and full rows)."""
    import numpy as np
    from nebula_tpu.tpu.hop import _pack_bits, _unpack_or

    rng = np.random.default_rng(3)
    for vmax in (1, 31, 32, 33, 100, 257):
        for density in (0.0, 0.03, 0.5, 1.0):
            m = rng.random((4, vmax)) < density
            packed = _pack_bits(jnp_asarray(m))
            got = np.asarray(_unpack_or(packed, vmax))
            want = m.any(axis=0)
            assert (got == want).all(), (vmax, density)


def jnp_asarray(x):
    import jax.numpy as jnp
    return jnp.asarray(x)


def test_direction_optimizing_bfs_parity_local():
    """Single-chip BFS (the bench path) switches bottom-up on dense
    levels; distances must equal the numpy level-synchronous BFS, and
    the FIND SHORTEST PATH rows must equal the host engine's."""
    from nebula_tpu.bench.datagen import host_bfs
    from nebula_tpu.graphstore.csr import build_snapshot

    st = random_store(21, n=400, avg_deg=6)
    rt1 = TpuRuntime(make_mesh(1))          # local mode: have_rev leg
    assert rt1.local_mode
    snap = build_snapshot(st, "g")
    sd = st.space("g")
    for srcs in ([1], [2, 3, 5], list(range(40))):
        dist, stats = rt1.bfs(st, "g", srcs, ["knows"], "out", 6)
        dense = [sd.dense_id(v) for v in srcs]
        want = host_bfs(snap, dense, 6, etype="knows")
        got = np.asarray(dist, np.int32)
        nv = want.shape[0]
        vv = np.arange(nv)
        assert np.array_equal(got[vv % 8, vv // 8], want), srcs
    # engine-level rows: local runtime vs host path
    eng_dev = QueryEngine(st, tpu_runtime=rt1)
    eng_cpu = QueryEngine(st)
    q = ("FIND SHORTEST PATH FROM 1 TO 250 OVER knows UPTO 6 STEPS "
         "YIELD path AS p")
    got = {}
    for eng in (eng_dev, eng_cpu):
        s = eng.new_session()
        eng.execute(s, "USE g")
        rs = eng.execute(s, q)
        assert rs.error is None, rs.error
        got[id(eng)] = sorted(map(repr, rs.data.rows))
    assert got[id(eng_dev)] == got[id(eng_cpu)]


def test_bottom_up_bfs_endpoint_predicate_parity():
    """A filtered shortest path on a graph dense enough to flip the
    direction-optimizing kernel bottom-up must still evaluate
    id($^)/id($$) on TRAVERSAL orientation (the bottom-up expansion is
    reversed — endpoints swap inside the kernel)."""
    from nebula_tpu.query.parser import parse
    st = random_store(23, n=200, avg_deg=8)
    rt1 = TpuRuntime(make_mesh(1))
    assert rt1.local_mode
    for w in ("id($$) != 7", "id($^) NOT IN [3, 9]"):
        stmt = parse(f"GO FROM 1 OVER knows WHERE {w} YIELD dst(edge)")
        cond = stmt.where.filter
        dist, _ = rt1.bfs(st, "g", [1, 2, 3, 4, 5, 6, 7, 8], ["knows"],
                          "out", 5, edge_filter=cond)
        # host oracle: level BFS honoring the same edge filter
        import numpy as np
        eng = QueryEngine(st)
        s = eng.new_session()
        eng.execute(s, "USE g")
        frontier = {1, 2, 3, 4, 5, 6, 7, 8}
        want = {v: 0 for v in frontier}
        for lvl in range(1, 6):
            nxt = set()
            for (sv, et, rank, dv, props, sgn) in st.get_neighbors(
                    "g", sorted(frontier), ["knows"], "out"):
                if w == "id($$) != 7" and dv == 7:
                    continue
                if w == "id($^) NOT IN [3, 9]" and sv in (3, 9):
                    continue
                if dv not in want:
                    nxt.add(dv)
            for v in nxt:
                want[v] = lvl
            frontier = nxt
            if not frontier:
                break
        got = np.asarray(dist, np.int32)
        sd = st.space("g")
        for vid in range(200):
            d = sd.dense_id(vid)
            if d < 0:
                continue
            exp = want.get(vid, -1)
            assert got[d % 8, d // 8] == exp, (w, vid, exp,
                                               int(got[d % 8, d // 8]))


def test_non_identity_vid_decode(rt):
    """Spaces whose vids are NOT the dense ids must still decode through
    the d2v gather — guards the identity fast path in runtime._d2v
    (sequential-int-vid spaces skip the gather; scattered vids may not).
    Covers both the GO materializer and the MATCH frame decode."""
    from nebula_tpu.tpu.runtime import _d2v
    rng = random.Random(5)
    st = GraphStore()
    st.create_space("nid", partition_num=P, vid_type="INT64")
    st.catalog.create_tag("nid", "person", [PropDef("age", PropType.INT64)])
    st.catalog.create_edge("nid", "knows", [PropDef("w", PropType.INT64)])
    vids = [v * 13 + 1001 for v in range(80)]
    rng.shuffle(vids)
    for v in vids:
        st.insert_vertex("nid", v, "person", {"age": v % 90})
    for v in vids:
        for _ in range(rng.randint(0, 6)):
            st.insert_edge("nid", v, "knows", rng.choice(vids),
                           rng.randint(0, 2), {"w": rng.randint(0, 99)})
    snap = rt.pin(st, "nid").host
    _d2v(snap)
    assert not snap._d2v_identity

    sources = vids[:3]
    rows, _ = rt.traverse(st, "nid", sources, ["knows"], "out", 2)
    got = sorted(norm_edge(e) for (_, e, _) in rows)
    want = host_go(st, "nid", sources, ["knows"], "out", 2)
    assert got == want
    # every decoded endpoint is a real vid, not a dense id
    vidset = set(vids)
    for (sv, e, dv) in rows:
        assert sv in vidset and dv in vidset

    # fused-yield columnar path + MATCH frame decode, device vs host
    src_list = ", ".join(map(str, sources))
    for q in (f"GO 2 STEPS FROM {src_list} OVER knows "
              f"YIELD src(edge) AS s, dst(edge) AS d, knows.w AS w",
              f"MATCH (a:person)-[e:knows]->(b) WHERE id(a) == {sources[0]} "
              f"RETURN id(a), id(b), e.w"):
        out = []
        for tpu_rt in (None, rt):
            eng = QueryEngine(st, tpu_runtime=tpu_rt)
            s = eng.new_session()
            eng.execute(s, "USE nid")
            rs = eng.execute(s, q)
            assert rs.error is None, f"{q} -> {rs.error}"
            out.append(sorted(map(repr, rs.data.rows)))
        assert out[0] == out[1], q


def test_shared_runtime_two_stores_no_cache_collision(rt):
    """One TpuRuntime serving two DIFFERENT stores whose same-named
    spaces share an epoch value must not serve store A's pinned graph
    for store B's queries — the snapshot cache is keyed by space uid,
    not just (name, epoch)."""
    stores = [random_store(seed) for seed in (21, 22)]
    wants = [host_go(st, "g", [3, 17], ["knows"], "out", 2)
             for st in stores]
    assert wants[0] != wants[1]          # distinct graphs
    rows, _ = rt.traverse(stores[0], "g", [3, 17], ["knows"], "out", 2)
    assert sorted(norm_edge(e) for (_, e, _) in rows) == wants[0]
    # force the epoch COLLISION the uid guard exists for: store B's
    # same-named space reports the exact epoch store A was pinned at
    stores[1].space("g").epoch = stores[0].space("g").epoch
    assert stores[1].space("g").epoch == stores[0].space("g").epoch
    rows, _ = rt.traverse(stores[1], "g", [3, 17], ["knows"], "out", 2)
    assert sorted(norm_edge(e) for (_, e, _) in rows) == wants[1]


def _hubby_store(seed=2, n=120, extra=60):
    st = random_store(seed, n=n, avg_deg=5)
    rng = random.Random(9)
    for _ in range(extra):
        st.insert_edge("g", 7, "knows", rng.randrange(n),
                       rng.randint(0, 2),
                       {"w": rng.randint(0, 99), "f": 0.5, "tag": "ann"})
    return st


def test_degree_split_transform_layout():
    """degree_split preserves every (src, nbr, rank, props) tuple while
    spreading hub adjacency across parts as extra hub rows."""
    from nebula_tpu.graphstore.csr import build_snapshot, degree_split
    st = _hubby_store()
    snap = build_snapshot(st, "g")
    sp = degree_split(snap, threshold=16)
    assert sp.hub_dense is not None and len(sp.hub_dense) >= 1
    H = len(sp.hub_dense)
    vmax = snap.vmax
    for key in snap.blocks:
        b0, b1 = snap.blocks[key], sp.blocks[key]
        assert b1.indptr.shape == (P, vmax + H + 1)
        assert b0.total_edges() == b1.total_edges(), key

        def adj(b, hubs=None):
            out = {}
            nrows = vmax if hubs is None else vmax + len(hubs)
            for p in range(P):
                for r in range(nrows):
                    s, e = int(b.indptr[p, r]), int(b.indptr[p, r + 1])
                    if e <= s:
                        continue
                    dn = (r * P + p if r < vmax
                          else int(hubs[r - vmax]))
                    out.setdefault(dn, []).extend(
                        zip(b.nbr[p, s:e].tolist(),
                            b.rank[p, s:e].tolist(),
                            b.props["w"][p, s:e].tolist()))
            return {k: sorted(v) for k, v in out.items()}
        assert adj(b0) == adj(b1, sp.hub_dense), key


def test_degree_split_device_parity(rt):
    """GO / predicate / MATCH var-len / SHORTEST PATH / SUBGRAPH give
    identical rows with the supernode degree-split active (SURVEY §7
    hard-part #4's split option)."""
    from nebula_tpu.utils.config import get_config
    get_config().set_dynamic("tpu_degree_split_threshold", 8)
    try:
        st = _hubby_store()
        dev = rt.pin(st, "g", force=True)
        assert dev.host.hub_dense is not None \
            and len(dev.host.hub_dense) > 0
        for steps, direction in ((1, "out"), (2, "in"), (3, "both")):
            rows, _ = rt.traverse(st, "g", [3, 7, 44], ["knows"],
                                  direction, steps)
            got = sorted(norm_edge(e) for (_, e, _) in rows)
            assert got == host_go(st, "g", [3, 7, 44], ["knows"],
                                  direction, steps), (steps, direction)
        eng = QueryEngine(st, tpu_runtime=rt)
        s = eng.new_session()
        eng.execute(s, "USE g")
        plain = QueryEngine(st)
        sp = plain.new_session()
        plain.execute(sp, "USE g")
        for q in [
            "GO 2 STEPS FROM 7 OVER knows WHERE knows.w > 30 "
            "YIELD src(edge), dst(edge), knows.w",
            "MATCH (a:person)-[e:knows*1..2]->(b) WHERE id(a) == 7 "
            "RETURN count(*)",
            "FIND SHORTEST PATH FROM 7 TO 44 OVER knows YIELD path AS p",
            "GET SUBGRAPH 2 STEPS FROM 7 YIELD VERTICES AS nodes",
        ]:
            a, b = eng.execute(s, q), plain.execute(sp, q)
            assert a.error is None and b.error is None, \
                (q, a.error, b.error)
            assert sorted(map(repr, a.data.rows)) == \
                sorted(map(repr, b.data.rows)), q
    finally:
        get_config().set_dynamic("tpu_degree_split_threshold", 0)


def test_degree_split_bfs_parity(rt):
    """Device BFS distances with hubs == host level-synchronous BFS,
    on the sharded mesh AND the single-chip direction-optimizing
    variant (its bottom-up probes hub rows owned by other parts)."""
    import numpy as np
    from nebula_tpu.utils.config import get_config
    get_config().set_dynamic("tpu_degree_split_threshold", 8)
    try:
        st = _hubby_store(seed=4, n=150, extra=70)
        want = {3: 0}
        frontier = {3}
        for lvl in range(1, 6):
            nxt = set()
            for (sv, et, rank, dv, props, sgn) in st.get_neighbors(
                    "g", sorted(frontier), ["knows"], "out"):
                if dv not in want:
                    nxt.add(dv)
            for v in nxt:
                want[v] = lvl
            frontier = nxt
        sd = st.space("g")
        for runtime in (rt, TpuRuntime(make_mesh(1))):
            dev = runtime.pin(st, "g", force=True)
            assert dev.host.hub_dense is not None
            dist, _ = runtime.bfs(st, "g", [3], ["knows"], "out", 5)
            got = np.asarray(dist, np.int32)
            for vid in range(150):
                d = sd.dense_id(vid)
                if d < 0:
                    continue
                assert got[d % P, d // P] == want.get(vid, -1), vid
    finally:
        get_config().set_dynamic("tpu_degree_split_threshold", 0)


def test_degree_split_string_vids(rt):
    """Degree-split + FIXED_STRING vids: the d2v decode is an OBJECT
    array here (no identity fast path), and hub dense ids still map
    back to string vids exactly."""
    from nebula_tpu.utils.config import get_config
    get_config().set_dynamic("tpu_degree_split_threshold", 4)
    try:
        st = GraphStore()
        st.create_space("svh", partition_num=P,
                        vid_type="FIXED_STRING(16)")
        st.catalog.create_tag("svh", "person",
                              [PropDef("name", PropType.STRING)])
        st.catalog.create_edge("svh", "knows",
                               [PropDef("w", PropType.INT64)])
        rng = random.Random(3)
        vids = [f"v{i:03d}" for i in range(60)]
        for v in vids:
            st.insert_vertex("svh", v, "person", {"name": v})
        for v in vids:
            for _ in range(rng.randint(1, 4)):
                st.insert_edge("svh", v, "knows", rng.choice(vids), 0,
                               {"w": rng.randint(0, 9)})
        for i in range(25):
            st.insert_edge("svh", "v000", "knows", rng.choice(vids), i,
                           {"w": 1})
        dev = rt.pin(st, "svh", force=True)
        assert dev.host.hub_dense is not None
        rows, _ = rt.traverse(st, "svh", ["v000", "v005"], ["knows"],
                              "out", 2)
        got = sorted(norm_edge(e) for (_, e, _) in rows)
        want = host_go(st, "svh", ['"v000"', '"v005"'], ["knows"],
                       "out", 2)
        assert got == want
        for (sv, e, dv) in rows:
            assert isinstance(sv, str) and isinstance(dv, str)
    finally:
        get_config().set_dynamic("tpu_degree_split_threshold", 0)


def test_speculative_fetch_round_trips_and_undershoot(rt):
    """Repeat query shapes collapse the two-phase result fetch into ONE
    device_get (a tunnel round trip saved per query); an undershoot —
    the kept set growing past the speculated prefix — falls back to the
    exact refetch with identical rows."""
    from nebula_tpu.tpu import runtime as R
    st = GraphStore()
    st.create_space("sf", partition_num=P, vid_type="INT64")
    st.catalog.create_tag("sf", "person", [PropDef("a", PropType.INT64)])
    st.catalog.create_edge("sf", "knows", [PropDef("w", PropType.INT64)])
    for v in range(60):
        st.insert_vertex("sf", v, "person", {"a": v})
    st.insert_edge("sf", 1, "knows", 2, 0, {"w": 1})
    st.insert_edge("sf", 1, "knows", 3, 0, {"w": 2})
    for i in range(40):                    # supersized vertex 2
        st.insert_edge("sf", 2, "knows", (i * 7) % 60, i, {"w": i})

    calls = [0]
    orig = R.jax.device_get

    def counting(x):
        calls[0] += 1
        return orig(x)

    R.jax.device_get = counting
    try:
        rows, _ = rt.traverse(st, "sf", [1], ["knows"], "out", 1)
        calls[0] = 0
        rows, _ = rt.traverse(st, "sf", [1], ["knows"], "out", 1)
        assert calls[0] == 1, calls[0]     # speculation engaged
        assert sorted(norm_edge(e) for (_, e, _) in rows) == \
            host_go(st, "sf", [1], ["knows"], "out", 1)
        # same program shape, 20x the kept set: speculated prefix is
        # too small — exact refetch kicks in, rows still identical
        rows, _ = rt.traverse(st, "sf", [2], ["knows"], "out", 1)
        assert sorted(norm_edge(e) for (_, e, _) in rows) == \
            host_go(st, "sf", [2], ["knows"], "out", 1)
        calls[0] = 0
        rows, _ = rt.traverse(st, "sf", [2], ["knows"], "out", 1)
        assert calls[0] == 1               # re-armed at the larger size
        assert sorted(norm_edge(e) for (_, e, _) in rows) == \
            host_go(st, "sf", [2], ["knows"], "out", 1)
    finally:
        R.jax.device_get = orig


def test_over_all_direction_combos_parity(rt):
    """OVER * x REVERSELY/BIDIRECT x m-to-n: multi-block expansion in
    every direction matches the host engine row-for-row."""
    st = random_store(11, extra_edge_type=True)
    eng = QueryEngine(st, tpu_runtime=rt)
    s = eng.new_session()
    eng.execute(s, "USE g")
    plain = QueryEngine(st)
    sp = plain.new_session()
    plain.execute(sp, "USE g")
    for q in ["GO 2 STEPS FROM 3, 7 OVER * REVERSELY "
              "YIELD src(edge), dst(edge), rank(edge)",
              "GO 2 STEPS FROM 3, 7 OVER * BIDIRECT "
              "YIELD src(edge), dst(edge)",
              "GO 1 TO 3 STEPS FROM 3 OVER * YIELD dst(edge) AS d",
              "GO 2 STEPS FROM 3 OVER knows, likes REVERSELY "
              "YIELD type(edge), dst(edge)"]:
        a, b = eng.execute(s, q), plain.execute(sp, q)
        assert a.error is None and b.error is None, (q, a.error, b.error)
        assert sorted(map(repr, a.data.rows)) == \
            sorted(map(repr, b.data.rows)), q
