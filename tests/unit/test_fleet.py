"""Graphd fleet fault tolerance (ISSUE 20): cluster-coherent cache
epochs (write through ANY coordinator invalidates every coordinator's
cached results), client-side coordinator selection + transparent
failover with a strict retry-safety taxonomy, graceful drain that
sheds zero acked statements, fleet-wide KILL idempotency, and
per-tenant DWRR QoS with the cluster SHOW TENANTS view."""
import threading
import time

import pytest

from nebula_tpu.cluster.client import (GraphClient, _stmt_retryable)
from nebula_tpu.cluster.launcher import LocalCluster
from nebula_tpu.cluster.rpc import (RpcClient, RpcConnError, RpcError,
                                    RpcNeverSentError, reset_breakers)
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils.admission import admission
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.epochs import ClusterEpochs, EpochClock
from nebula_tpu.utils.stats import stats

_FLEET_FLAGS = (
    "result_cache_size", "result_cache_strict_epoch", "read_consistency",
    "max_running_queries", "admission_queue_capacity",
    "admission_tenant_weights",
)


def _pop_flags():
    for k in _FLEET_FLAGS:
        get_config().dynamic_layer.pop(k, None)


def _poll(pred, timeout=6.0, msg="condition"):
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        got = pred()
        if got:
            return got
        time.sleep(0.02)
    pytest.fail(f"timed out waiting for {msg}")


def _counter(name) -> float:
    return stats().snapshot().get(name, 0)


# -- ClusterEpochs / EpochClock (pure) --------------------------------------


def test_epoch_fold_monotonic_and_boot_change():
    ce = ClusterEpochs()
    assert ce.gen("s") == 0 and ce.gen(None) == 0
    assert ce.fold("s", "h1", "bootA", 3)
    g1 = ce.gen("s")
    assert g1 == 1
    # same boot, lower epoch: a stale out-of-order heartbeat must NOT
    # regress the vector or mint new keys
    assert not ce.fold("s", "h1", "bootA", 2)
    assert ce.gen("s") == g1
    # same boot, higher epoch: advance
    assert ce.fold("s", "h1", "bootA", 4)
    assert ce.gen("s") == g1 + 1
    # NEW boot with a LOWER epoch: a restart is always news — a plain
    # max() would mask the fresh host's low-but-advancing counter
    assert ce.fold("s", "h1", "bootB", 1)
    assert ce.gen("s") == g1 + 2
    # another host folds independently
    assert ce.fold("s", "h2", "bootX", 1)
    assert ce.gen("s") == g1 + 3


def test_epoch_fold_table_and_ack():
    ce = ClusterEpochs()
    n = ce.fold_table({"s": {"h1": ["b", 2, None], "h2": ["b", 1, None]},
                       "t": {"h1": ["b", 5, None]}})
    assert n == 3
    assert ce.gen("s") == 2 and ce.gen("t") == 1
    # replay of the same table: nothing advances
    assert ce.fold_table({"s": {"h1": ["b", 2, None]}}) == 0
    # malformed entries are skipped, not fatal
    assert ce.fold_table({"s": {"h3": "garbage", "h4": ["b"]}}) == 0
    assert ce.fold_table(None) == 0
    # write-ack leg: monotonic per space, bumps the generation so the
    # WRITING coordinator's caches turn over at ack time
    g = ce.gen("s")
    assert ce.note_ack("s", 7)
    assert ce.gen("s") == g + 1
    assert not ce.note_ack("s", 7)      # replayed ack: no new keys
    assert not ce.note_ack("s", 3)      # stale ack: no regression
    assert ce.gen("s") == g + 1
    assert not ce.note_ack("", 9) and not ce.note_ack("s", "x")


def test_epoch_clock_ts():
    ec = EpochClock()
    assert ec.ts_for("s", 1) is None
    ec.note("s", 3)
    ts = ec.ts_for("s", 3)
    assert ts is not None and ts <= time.time()
    # a different epoch carries no ts (fold without a lag sample)
    assert ec.ts_for("s", 4) is None
    ec.note("s", 2)                     # stale note: ignored
    assert ec.ts_for("s", 3) == ts


# -- client-side retry-safety taxonomy (pure) -------------------------------


def test_stmt_retry_taxonomy():
    for s in ("GO FROM 1 OVER e", "  MATCH (n) RETURN n",
              "FETCH PROP ON T 1 YIELD T.n", "LOOKUP ON T WHERE T.n > 1",
              "SHOW HOSTS", "DESCRIBE TAG T", "DESC TAG T", "USE s",
              "YIELD 1 AS x", "(GO FROM 1 OVER e)"):
        assert _stmt_retryable(s), s
    for s in ("INSERT VERTEX T(n) VALUES 1:(1)", "UPDATE VERTEX ON T 1 SET n=2",
              "DELETE VERTEX 1", "UPSERT VERTEX ON T 1 SET n=2",
              "CREATE TAG T(n int)", "DROP SPACE s",
              # EXPLAIN/PROFILE deliberately excluded: they EXECUTE
              "EXPLAIN INSERT VERTEX T(n) VALUES 1:(1)",
              "PROFILE GO FROM 1 OVER e", ""):
        assert not _stmt_retryable(s), s


def test_client_endpoint_forms():
    c = GraphClient(["a:1", "b:2"])
    assert c.endpoints == ["a:1", "b:2"] and c.addr == "a:1"
    assert GraphClient("a:1,b:2, c:3").endpoints == ["a:1", "b:2", "c:3"]
    assert GraphClient("h", 9669).endpoints == ["h:9669"]  # legacy pair
    with pytest.raises(ValueError):
        GraphClient([])


class _FakeRpc:
    """Scripted RpcClient stand-in: raises `err` for graph.execute, or
    answers with a canned success; records every method called."""

    def __init__(self, err=None):
        self.err = err
        self.calls = []

    def call(self, method, **kw):
        self.calls.append(method)
        if method == "graph.execute" and self.err is not None:
            raise self.err
        if method == "graph.adopt_session":
            return {"session_id": kw["session_id"], "space": None}
        return {"error": None, "space": None, "latency_us": 1,
                "data": None, "plan_desc": None}

    def close(self):
        pass


def _fleet_pair(err):
    """Client homed on a rigged coordinator `a:1` with healthy `b:2`."""
    c = GraphClient(["a:1", "b:2"])
    c.session_id = 1
    dead, good = _FakeRpc(err=err), _FakeRpc()
    c._rpcs = {"a:1": dead, "b:2": good}
    return c, dead, good


def test_failover_taxonomy_unknown_outcome_write_not_resent():
    """Mid-statement connection death: the outcome is UNKNOWN.  A write
    must come back as a structured E_COORDINATOR_LOST — never silently
    re-sent — while the session still re-homes for the next statement."""
    c, dead, good = _fleet_pair(RpcConnError("connection reset"))
    rs = c.execute("INSERT VERTEX T(n) VALUES 1:(1)")
    assert rs.error and "E_COORDINATOR_LOST" in rs.error
    assert "graph.execute" not in good.calls          # never re-sent
    assert "graph.adopt_session" in good.calls        # but re-homed
    assert c.addr == "b:2"
    rs = c.execute("INSERT VERTEX T(n) VALUES 2:(2)")
    assert rs.error is None                           # next stmt flows


def test_failover_taxonomy_read_retries():
    c, dead, good = _fleet_pair(RpcConnError("connection reset"))
    rs = c.execute("GO FROM 1 OVER e YIELD 1")
    assert rs.error is None
    assert good.calls.count("graph.execute") == 1 and c.addr == "b:2"


def test_failover_taxonomy_never_sent_retries_writes():
    """RpcNeverSentError is provably side-effect free — even a write
    retries safely on the sibling."""
    c, dead, good = _fleet_pair(RpcNeverSentError("connect refused"))
    rs = c.execute("INSERT VERTEX T(n) VALUES 1:(1)")
    assert rs.error is None
    assert good.calls.count("graph.execute") == 1 and c.addr == "b:2"


def test_failover_taxonomy_session_moved_retries_writes():
    """A drain refusal happens BEFORE execution: any statement —
    including a write — retries on the named sibling."""
    c, dead, good = _fleet_pair(
        RpcError("E_SESSION_MOVED: graphd a:1 draining; sibling=b:2"))
    rs = c.execute("INSERT VERTEX T(n) VALUES 1:(1)")
    assert rs.error is None
    assert good.calls.count("graph.execute") == 1 and c.addr == "b:2"


def test_single_endpoint_conn_death_still_raises():
    """Legacy single-endpoint clients keep the old contract: transport
    death surfaces as the raw exception, no failover machinery."""
    c = GraphClient("a:1")
    c.session_id = 1
    c._rpcs = {"a:1": _FakeRpc(err=RpcConnError("connection reset"))}
    with pytest.raises(RpcConnError):
        c.execute("GO FROM 1 OVER e")


# -- fleet cluster (module-scoped: non-destructive tests only) --------------


@pytest.fixture(scope="module")
def fleet():
    reset_breakers()
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=3)
    ca = c.client(graphd=0)

    def ok(client, q):
        r = client.execute(q)
        assert r.error is None, f"{q} -> {r.error}"
        return r

    ok(ca, "CREATE SPACE fs(partition_num=2, replica_factor=1, "
           "vid_type=INT64)")
    c.reconcile_storage()
    ok(ca, "USE fs")
    ok(ca, "CREATE TAG Person(name string, age int)")
    ok(ca, 'INSERT VERTEX Person(name, age) VALUES '
           '1:("ann",30), 2:("bob",25)')
    yield c, ca
    _pop_flags()
    ca.close()
    c.stop()


def _peer_client(fleet, graphd=1):
    c, _ = fleet
    cb = c.client(graphd=graphd)
    # catalog propagation is pull-through via metad; poll until this
    # graphd can resolve the space+tag before the test proper
    _poll(lambda: cb.execute("USE fs").error is None, msg="USE fs on peer")
    _poll(lambda: cb.execute(
        "FETCH PROP ON Person 1 YIELD Person.age AS a").error is None,
        msg="catalog on peer")
    return cb


def test_fleet_epochs_reach_metad_and_peers(fleet):
    c, ca = fleet
    # the storaged write epochs ride its heartbeat into metad's merged
    # table...
    meta = c.graphds[0].meta

    def table_has_fs():
        t = meta.cluster_epochs()
        return "fs" in t and t["fs"]
    _poll(table_has_fs, msg="metad cluster_epochs table")
    # ...and every heartbeat REPLY folds it into every graphd,
    # including ones that never served a statement for the space
    for i in range(3):
        _poll(lambda i=i: c.graphds[i].engine.cluster_epochs.gen("fs") > 0,
              msg=f"graphd {i} epoch fold")


def test_cross_coordinator_cache_invalidation(fleet):
    """The tentpole hole (PR 9): write through coordinator A, cached
    read through coordinator B.  Without cluster epochs B's cached rows
    would be stale FOREVER (its local write_epoch never moved); with
    them the fold mints a new key within the propagation window."""
    c, ca = fleet
    cb = _peer_client(fleet)
    get_config().set_dynamic("result_cache_size", 64)
    try:
        q = "FETCH PROP ON Person 1 YIELD Person.age AS a"
        hits0 = _counter("result_cache_hits")
        assert cb.execute(q).data.rows == [[30]]
        assert cb.execute(q).data.rows == [[30]]          # cached
        assert _counter("result_cache_hits") > hits0
        r = ca.execute("UPDATE VERTEX ON Person 1 SET age = 31")
        assert r.error is None, r.error
        folds0 = _counter("cluster_epoch_folds")
        _poll(lambda: cb.execute(q).data.rows == [[31]],
              msg="peer cache invalidation")
        # the fold that did it was measured: propagation lag samples
        # and the fold counter both moved
        snap = stats().snapshot()
        assert snap.get("cluster_epoch_folds", 0) >= folds0
        assert snap.get("epoch_propagation_lag_ms.count", 0) > 0
    finally:
        get_config().dynamic_layer.pop("result_cache_size", None)
        cb.close()


def test_write_coordinator_read_your_writes(fleet):
    """On the WRITE coordinator freshness is ack-latency, not
    heartbeat-latency: the storaged ack folds immediately (plus the
    PR 9 local write_epoch) — no poll needed."""
    c, ca = fleet
    get_config().set_dynamic("result_cache_size", 64)
    try:
        q = "FETCH PROP ON Person 2 YIELD Person.age AS a"
        assert ca.execute(q).data.rows == [[25]]
        assert ca.execute(q).data.rows == [[25]]          # cached
        assert ca.execute("UPDATE VERTEX ON Person 2 SET age = 26"
                          ).error is None
        assert ca.execute(q).data.rows == [[26]]          # immediately
    finally:
        get_config().dynamic_layer.pop("result_cache_size", None)


def test_strict_epoch_sync_hook(fleet):
    """`result_cache_strict_epoch`: a leader-consistency cached read
    pulls metad's merged table BEFORE forming the cache key — the
    engine calls the graphd's epoch_sync hook exactly when the flag is
    on."""
    c, ca = fleet
    cb = _peer_client(fleet)
    eng = c.graphds[1].engine
    calls = []
    orig = eng.epoch_sync
    eng.epoch_sync = lambda: (calls.append(1), orig())
    get_config().set_dynamic("result_cache_size", 64)
    try:
        q = "FETCH PROP ON Person 1 YIELD Person.age AS a"
        assert cb.execute(q).error is None
        assert not calls                                   # flag off
        get_config().set_dynamic("result_cache_strict_epoch", True)
        assert cb.execute(q).error is None
        assert calls                                       # flag on
    finally:
        eng.epoch_sync = orig
        get_config().dynamic_layer.pop("result_cache_strict_epoch", None)
        get_config().dynamic_layer.pop("result_cache_size", None)
        cb.close()


def test_cross_coordinator_read_your_writes_levels(fleet):
    """Write via A, read via B at every consistency level, cached and
    uncached, under a concurrent epoch-bumping writer: reads converge
    to the written value within the propagation window and never after
    serve the old value again (no cache resurrection)."""
    c, ca = fleet
    cb = _peer_client(fleet)
    assert ca.execute('INSERT VERTEX Person(name, age) VALUES '
                      '50:("rw",1)').error is None
    stop = threading.Event()

    def churn():
        # concurrent epoch bumps on an UNRELATED vertex: folds must
        # invalidate by space generation without corrupting results
        k = 0
        while not stop.is_set():
            ca.execute(f'INSERT VERTEX Person(name, age) VALUES '
                       f'60:("churn",{k % 90})')
            k += 1
            time.sleep(0.01)

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        val = 1
        for level in ("leader", "follower", "bounded_stale"):
            for cached in (False, True):
                get_config().set_dynamic("read_consistency", level)
                if cached:
                    get_config().set_dynamic("result_cache_size", 64)
                q = "FETCH PROP ON Person 50 YIELD Person.age AS a"
                cb.execute(q)                      # warm/cache
                val += 1
                r = ca.execute(f"UPDATE VERTEX ON Person 50 "
                               f"SET age = {val}")
                assert r.error is None, (level, cached, r.error)
                _poll(lambda: cb.execute(q).data.rows == [[val]],
                      msg=f"read-your-writes {level} cached={cached}")
                # once seen, the old value must never resurface
                assert cb.execute(q).data.rows == [[val]]
                get_config().dynamic_layer.pop("result_cache_size", None)
                get_config().dynamic_layer.pop("read_consistency", None)
    finally:
        stop.set()
        t.join(5)
        _pop_flags()
        cb.close()


def test_show_tenants_cluster_view(fleet):
    c, ca = fleet
    cb = _peer_client(fleet)
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 8)
    cfg.set_dynamic("admission_queue_capacity", 32)
    cfg.set_dynamic("admission_tenant_weights", "root:4")
    try:
        for _ in range(3):
            assert ca.execute("YIELD 1 AS x").error is None
            assert cb.execute("YIELD 1 AS x").error is None
        rs = ca.execute("SHOW TENANTS")
        assert rs.error is None, rs.error
        assert rs.data.column_names == ["Tenant", "Weight", "Running",
                                        "Queued", "Admitted", "Share",
                                        "Graphds"]
        row = next(r for r in rs.data.rows if r[0] == "root")
        assert row[1] == 4                       # weight from the flag
        assert row[4] >= 6                       # admissions summed
        assert row[6] >= 2                       # merged across graphds
        # LOCAL view: this coordinator's controller only (in-process
        # LocalCluster shares one controller, so the row still merges
        # to a single-graphd count)
        rs = ca.execute("SHOW LOCAL TENANTS")
        assert rs.error is None, rs.error
        row = next(r for r in rs.data.rows if r[0] == "root")
        assert row[6] == 1
    finally:
        _pop_flags()
        admission().reset()
        cb.close()


def test_kill_session_double_kill_idempotent(fleet):
    c, ca = fleet
    victim = c.client(graphd=1)
    sid = victim.session_id
    assert ca.execute(f"KILL SESSION {sid}").error is None
    # second kill: the sid is a metad TOMBSTONE — quiet success, the
    # goal state already holds (operator scripts re-run safely)
    assert ca.execute(f"KILL SESSION {sid}").error is None
    # a sid that NEVER existed still errors (typo protection)
    rs = ca.execute("KILL SESSION 987654321")
    assert rs.error is not None


def test_adopt_session_guards(fleet):
    """A sid alone must never be enough to steal a session: credentials
    and the session's recorded user are re-checked; unknown sids are
    refused."""
    c, ca = fleet
    addr_b = c.graph_addrs[1]
    rpc = RpcClient.from_addr(addr_b, timeout=3.0, retries=0)
    try:
        with pytest.raises(RpcError, match="E_SESSION_UNKNOWN"):
            rpc.call("graph.adopt_session", session_id=123456789,
                     user="root", password="nebula")
        with pytest.raises(RpcError, match="user mismatch"):
            rpc.call("graph.adopt_session", session_id=ca.session_id,
                     user="mallory", password="whatever")
        # the legitimate owner re-homes fine
        r = rpc.call("graph.adopt_session", session_id=ca.session_id,
                     user="root", password="nebula")
        assert r["session_id"] == ca.session_id
    finally:
        rpc.close()
        # re-home back so later tests keep using graphd 0
        ca.rpc.call("graph.adopt_session", session_id=ca.session_id,
                    user="root", password="nebula")


# -- KILL QUERY idempotency (engine level) ----------------------------------


def test_kill_query_double_kill_engine():
    eng = QueryEngine()
    s = eng.new_session()
    ev = threading.Event()
    s.queries[4242] = "stalled"
    s.running_kill[4242] = ev
    assert eng.kill_running(s.id, 4242)
    assert ev.is_set()
    # victim drained: registry empty now
    s.queries.pop(4242)
    s.running_kill.pop(4242)
    # second kill of the SAME qid: quiet success via the recent-kills
    # ledger, not "no running query matches"
    assert eng.kill_running(s.id, 4242)
    assert eng.kill_running(None, 4242)
    # a qid never killed and not running still misses
    assert not eng.kill_running(s.id, 999999)


# -- tenant DWRR (controller level) -----------------------------------------


def test_tenant_dwrr_shares_and_snapshot():
    """Outer DWRR rotation is per TENANT: with weights vip:3 / agg:1
    and both backlogged on one slot, admissions interleave ~3:1 — an
    aggressor tenant cannot starve the others no matter how many
    sessions or statements it piles on."""
    admission().reset()
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 100)
    cfg.set_dynamic("admission_tenant_weights", "vip:3,agg:1")
    order = []
    threads = []
    try:
        ctl = admission()
        seed = ctl.acquire(qid=1, session=1, kind="GO", user="vip")
        assert seed is not None and seed.mode == "admitted"

        def waiter(qid, user):
            t = ctl.acquire(qid=qid, session=qid, kind="GO", user=user)
            order.append(user)
            t.release()

        # aggressor enqueues FIRST and 2× as much — FIFO would give it
        # the whole head of the line
        qid = 100
        for u in ["agg"] * 12 + ["vip"] * 6:
            th = threading.Thread(target=waiter, args=(qid, u),
                                  daemon=True)
            th.start()
            threads.append(th)
            qid += 1
            _poll(lambda n=qid - 100: admission().snapshot()["queued"]
                  >= n, msg="waiter queued")
        seed.release()                      # open the floodgate
        for th in threads:
            th.join(10)
            assert not th.is_alive()
        head = order[:8]
        assert head.count("vip") >= 5, order
        assert head.count("agg") >= 1, order    # weighted, not starved
        rows = {r["tenant"]: r for r in ctl.tenant_snapshot()}
        assert rows["vip"]["weight"] == 3 and rows["agg"]["weight"] == 1
        assert rows["vip"]["admitted"] == 7 and rows["agg"]["admitted"] == 12
        assert abs(sum(r["share"] for r in rows.values()) - 1.0) < 0.01
    finally:
        _pop_flags()
        admission().reset()


def test_single_tenant_collapses_to_session_dwrr():
    """With ONE tenant the two-level scheme must reduce exactly to the
    PR 8 per-session DWRR — weights still honored inside the tenant."""
    admission().reset()
    cfg = get_config()
    cfg.set_dynamic("max_running_queries", 1)
    cfg.set_dynamic("admission_queue_capacity", 100)
    order = []
    threads = []
    try:
        ctl = admission()
        seed = ctl.acquire(qid=1, session=77, kind="GO")
        qid = 200
        for sess in [10, 10, 10, 20, 20, 20]:
            th = threading.Thread(
                target=lambda q=qid, s=sess: (
                    (t := ctl.acquire(qid=q, session=s, kind="GO")),
                    order.append(s), t.release()),
                daemon=True)
            th.start()
            threads.append(th)
            qid += 1
            _poll(lambda n=qid - 200: admission().snapshot()["queued"]
                  >= n, msg="waiter queued")
        seed.release()
        for th in threads:
            th.join(10)
        # equal weights: sessions alternate, neither side runs 3 deep
        # while the other waits
        assert order[:2].count(10) == 1 and order[:2].count(20) == 1, order
    finally:
        _pop_flags()
        admission().reset()


# -- drain / crash failover (own clusters: destructive) ---------------------


def test_drain_sheds_zero_acked_statements(tmp_path):
    """The satellite regression: a PLANNED restart through drain sheds
    ZERO statements — every refusal is an E_SESSION_MOVED the client
    transparently retries (writes included: refusal precedes
    execution), and every acked write survives."""
    reset_breakers()
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=2,
                     data_dir=str(tmp_path))
    try:
        fc = c.fleet_client()
        assert fc.execute("CREATE SPACE dr(partition_num=2, "
                          "replica_factor=1, vid_type=INT64)").error is None
        c.reconcile_storage()
        assert fc.execute("USE dr").error is None
        assert fc.execute("CREATE TAG T(n int)").error is None
        home = fc.addr
        idx = c.graph_addrs.index(home)
        sib = c.client(graphd=1 - idx)
        _poll(lambda: sib.execute("USE dr").error is None, msg="peer USE")
        _poll(lambda: sib.execute("DESCRIBE TAG T").error is None,
              msg="peer catalog")
        drains0 = _counter("graphd_drains")
        results = []

        def writer():
            for k in range(40):
                results.append(
                    fc.execute(f"INSERT VERTEX T(n) VALUES {k}:({k})"))

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        _poll(lambda: len(results) >= 5, msg="writer warm")
        c.drain_graphd(idx)
        t.join(30)
        assert not t.is_alive()
        errs = [r.error for r in results if r.error is not None]
        assert not errs, errs                      # ZERO shed statements
        assert fc.addr != home                     # re-homed
        assert _counter("graphd_drains") > drains0
        # every acked write is readable exactly where it should be
        for k in range(40):
            r = sib.execute(f"FETCH PROP ON T {k} YIELD T.n AS n")
            assert r.error is None and r.data.rows == [[k]], (k, r.error)
        sib.close()
        fc.close()
    finally:
        c.stop()


def test_crash_failover_and_owner_dead_kill(tmp_path):
    """Hard coordinator death: reads fail over transparently; an
    unknown-outcome write is either safely retried (provably never
    sent) or reported as structured E_COORDINATOR_LOST — NEVER silently
    re-sent; KILL of the dead coordinator's session/query succeeds
    idempotently (the victim provably isn't running)."""
    reset_breakers()
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=2,
                     data_dir=str(tmp_path))
    try:
        fc = c.fleet_client()
        assert fc.execute("CREATE SPACE cr(partition_num=2, "
                          "replica_factor=1, vid_type=INT64)").error is None
        c.reconcile_storage()
        assert fc.execute("USE cr").error is None
        assert fc.execute("CREATE TAG T(n int)").error is None
        home = fc.addr
        idx = c.graph_addrs.index(home)
        surv = c.client(graphd=1 - idx)
        _poll(lambda: surv.execute("USE cr").error is None, msg="peer USE")
        _poll(lambda: surv.execute("DESCRIBE TAG T").error is None,
              msg="peer catalog")
        assert fc.execute("INSERT VERTEX T(n) VALUES 1:(1)").error is None
        # a session owned by the soon-dead coordinator, for the KILLs
        doomed = c.client(graphd=idx)
        doomed_sid = doomed.session_id

        fails0 = _counter("coordinator_failovers")
        c.stop_graphd(idx)

        # write DURING the crash: exactly-once either way — retried
        # only when provably never sent, else structured + not applied
        rs = fc.execute("INSERT VERTEX T(n) VALUES 2:(2)")
        if rs.error is not None:
            assert "E_COORDINATOR_LOST" in rs.error, rs.error
            r2 = fc.execute("FETCH PROP ON T 2 YIELD T.n AS n")
            assert r2.error is None
            if not r2.data.rows:           # provably not applied: redo
                assert fc.execute(
                    "INSERT VERTEX T(n) VALUES 2:(2)").error is None
        assert fc.addr != home
        assert _counter("coordinator_failovers") > fails0

        # reads + writes flow on the survivor; acked-exactly-once holds
        r = fc.execute("FETCH PROP ON T 1 YIELD T.n AS n")
        assert r.error is None and r.data.rows == [[1]]
        r = fc.execute("FETCH PROP ON T 2 YIELD T.n AS n")
        assert r.error is None and r.data.rows == [[2]]

        # owner-dead KILL race: the owning graphd is gone — the query
        # provably isn't running, so KILL succeeds instead of erroring
        rs = surv.execute(f"KILL QUERY (session={doomed_sid}, plan=1)")
        assert rs.error is None, rs.error
        rs = surv.execute(f"KILL SESSION {doomed_sid}")
        assert rs.error is None, rs.error
        rs = surv.execute(f"KILL SESSION {doomed_sid}")   # double-kill
        assert rs.error is None, rs.error
        surv.close()
        fc.close()
    finally:
        c.stop()
