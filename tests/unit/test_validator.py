"""Validator layer with static type deduction (SURVEY §2 row 19;
VERDICT r1 'no separate validator layer or type deduction')."""
import pytest

from nebula_tpu.exec.engine import QueryEngine


@pytest.fixture
def eng():
    e = QueryEngine()
    s = e.new_session()
    for q in ["CREATE SPACE v(partition_num=2, vid_type=INT64)", "USE v",
              "CREATE TAG t(x int, name string)",
              "CREATE EDGE e(w int, tag string)"]:
        r = e.execute(s, q)
        assert r.error is None, (q, r.error)
    return e, s


REJECTED = [
    'YIELD 1 + "x"',
    "YIELD NOT 5",
    'YIELD "a" < 1',
    "YIELD true AND 3",
    'YIELD -"s"',
    'YIELD ("a" + "b") * 2',
    'YIELD CASE WHEN 3 THEN 1 END',
    'GO FROM 1 OVER e WHERE e.w + "s" > 2 YIELD dst(edge)',
    'GO FROM 1 OVER e WHERE e.tag < 5 YIELD dst(edge)',
    "GO FROM 1 OVER e WHERE e.nosuch > 1 YIELD dst(edge)",
    'GO FROM 1 OVER e YIELD e.w + "x"',
]

ACCEPTED = [
    'YIELD 1 + 2 AS s, "a" + "b" AS c, 1 < 2.5 AS d',
    "YIELD [1, 2] + [3] AS l",
    'GO FROM 1 OVER e WHERE e.tag CONTAINS "x" YIELD dst(edge)',
    "GO FROM 1 OVER e WHERE e.w > 2 AND e.w < 9 YIELD dst(edge)",
    'YIELD CASE WHEN 1 > 2 THEN "a" ELSE "b" END AS c',
    "YIELD size([1,2]) + 1 AS n",
    # dynamic/unknown stays runtime-checked (three-valued semantics)
    "YIELD coalesce(1, \"x\") AS mixed",
    "GO FROM 1 OVER e WHERE e.w + 0.5 > 1 YIELD dst(edge) AS d",
]


@pytest.mark.parametrize("q", REJECTED)
def test_type_errors_rejected_at_validation(eng, q):
    e, s = eng
    rs = e.execute(s, q)
    assert rs.error is not None and "SemanticError" in rs.error, (q, rs.error)


@pytest.mark.parametrize("q", ACCEPTED)
def test_valid_statements_pass(eng, q):
    e, s = eng
    rs = e.execute(s, q)
    assert rs.error is None, (q, rs.error)


def test_deduce_api():
    from nebula_tpu.query.parser import parse_expression
    from nebula_tpu.query.validator import Scope, deduce

    class _P:
        space = None
        catalog = None
    sc = Scope(_P())
    assert deduce(parse_expression("1 + 2"), sc) == "int"
    assert deduce(parse_expression("1 + 2.0"), sc) == "float"
    assert deduce(parse_expression('"a" + "b"'), sc) == "string"
    assert deduce(parse_expression("1 < 2"), sc) == "bool"
    assert deduce(parse_expression("upper(\"x\")"), sc) == "string"
    assert deduce(parse_expression("size([1])"), sc) == "int"
