"""Production telemetry plane (ISSUE 8): cluster-wide PROFILE cost
attribution, PROFILE parity + parallel schedule, PR5-path trace
coverage (retries / breaker transitions / dedup fast path), SLO burn
rates, metric federation, and the metric-catalogue lint."""
import json
import pathlib
import re
import time
import urllib.request

import pytest

from nebula_tpu.cluster.launcher import LocalCluster
from nebula_tpu.cluster.rpc import RpcClient, reset_breakers
from nebula_tpu.cluster.storage_client import StorageClient
from nebula_tpu.core.wire import to_wire
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils import trace
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import CostRecorder, stats, use_cost

REPO = pathlib.Path(__file__).resolve().parents[2]


@pytest.fixture()
def clean_faults():
    fail.reset()
    reset_breakers()
    yield
    fail.reset()
    reset_breakers()


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    client = c.client()

    def run(q, expect_ok=True):
        rs = client.execute(q)
        if expect_ok:
            assert rs.error is None, f"{q} -> {rs.error}"
        return rs

    run("CREATE SPACE tel(partition_num=4, replica_factor=2, "
        "vid_type=INT64)")
    c.reconcile_storage()
    run("USE tel")
    run("CREATE TAG Person(name string, age int)")
    run("CREATE EDGE KNOWS(w int)")
    run('INSERT VERTEX Person(name, age) VALUES '
        '1:("ann",30), 2:("bob",25), 3:("cid",41)')
    run("INSERT EDGE KNOWS(w) VALUES 1->2:(7), 1->3:(9), 2->3:(5)")
    c.run = run
    yield c
    c.stop()


# -- cost recorder unit surface ---------------------------------------------


def test_cost_recorder_merge_reply():
    cc = CostRecorder()
    cc.add("calls", 1)
    # "us" is the remote handler time in fixed-width decimal (reply
    # byte determinism); it maps to remote_us on merge
    cc.merge_reply({"us": "000001234", "rows": 10, "wal_fsyncs": 2})
    cc.merge_reply({"us": "000000766", "rows": 5})
    d = cc.as_dict()
    assert d["remote_us"] == 2000 and d["rows"] == 15
    assert d["wal_fsyncs"] == 2 and d["calls"] == 1
    assert bool(cc)


def test_cost_reply_envelope_fixed_width(cluster, clean_faults):
    """A cost-flagged request's reply carries a cost record whose `us`
    field is fixed-width — reply byte counts stay deterministic."""
    addr = cluster.storage_servers[0].addr
    cli = RpcClient.from_addr(addr)
    try:
        cc = CostRecorder()
        with use_cost(cc):
            cli.call("storage.part_stats", space="tel", part=0)
        d = cc.as_dict()
        assert d["calls"] == 1 and "remote_us" in d
        assert d["bytes_sent"] > 0 and d["bytes_recv"] > 0
    finally:
        cli.close()


# -- cluster-wide PROFILE ---------------------------------------------------


def test_profile_parity_cluster_rows_and_remote_cost(cluster,
                                                     clean_faults):
    """PROFILE returns byte-identical rows to the plain run AND its
    plan rows carry per-node remote cost (storaged µs / rows) from the
    reply envelopes — cluster-wide attribution, not graphd wall time."""
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d, KNOWS.w AS w"
    plain = cluster.run(q)
    prof = cluster.run("PROFILE " + q)
    assert sorted(map(tuple, prof.data.rows)) == \
        sorted(map(tuple, plain.data.rows))
    assert prof.plan_desc and "rows=" in prof.plan_desc
    assert "remote={" in prof.plan_desc, prof.plan_desc
    assert "remote_us=" in prof.plan_desc
    assert "calls=" in prof.plan_desc


def test_profile_write_carries_wal_fsyncs(cluster, clean_faults):
    rs = cluster.run('PROFILE INSERT VERTEX Person(name, age) '
                     'VALUES 9:("zed",1)')
    assert "wal_fsyncs=" in rs.plan_desc, rs.plan_desc


def test_forwarded_cost_records_carry_no_variable_width_timing(
        cluster, clean_faults):
    """Reply-envelope cost records must contain NO variable-width
    timing ints: the only timing field on the wire is the fixed-width
    `us` string — nested-hop remote_us would otherwise make reply byte
    counts timing-dependent and flake the wire-byte regression gate."""
    addr = cluster.storage_servers[0].addr
    cli = RpcClient.from_addr(addr)
    try:
        cc = CostRecorder()
        # raw reply inspection: monkey-scope via the recorder is not
        # enough, we need the on-wire record itself
        seen = {}
        orig = CostRecorder.merge_reply

        def spy(self, cost):
            seen.update(cost)
            return orig(self, cost)

        CostRecorder.merge_reply = spy
        try:
            with use_cost(cc):
                cli.call("storage.part_stats", space="tel", part=0)
        finally:
            CostRecorder.merge_reply = orig
        assert seen, "no cost record came back"
        for k, v in seen.items():
            if k == "us":
                assert isinstance(v, str) and len(v) == 9, (k, v)
            else:
                assert not k.endswith("_us"), \
                    f"variable-width timing field {k} on the wire"
    finally:
        cli.close()


def test_profile_uses_parallel_schedule():
    """The old `profile is None` gate is gone: a branchy profiled plan
    dispatches on the parallel ready-queue (recorded by the
    scheduler_parallel_plans counter)."""
    eng = QueryEngine()
    s = eng.new_session()
    for q in ['CREATE SPACE par(partition_num=2, vid_type=FIXED_STRING(8))',
              'USE par', 'CREATE EDGE e(w int)',
              'INSERT EDGE e(w) VALUES "a"->"b":(1), "b"->"c":(2)']:
        r = eng.execute(s, q)
        assert r.error is None, f"{q} -> {r.error}"
    q = ('GO FROM "a" OVER e YIELD dst(edge) AS d '
         'UNION GO FROM "b" OVER e YIELD dst(edge) AS d')
    plain = eng.execute(s, q)
    assert plain.error is None
    before = stats().snapshot().get("scheduler_parallel_plans", 0)
    prof = eng.execute(s, "PROFILE " + q)
    assert prof.error is None
    after = stats().snapshot().get("scheduler_parallel_plans", 0)
    assert after > before, \
        "profiled run fell back to the sequential scheduler"
    assert sorted(map(tuple, prof.data.rows)) == \
        sorted(map(tuple, plain.data.rows))


# -- PR5-path trace coverage ------------------------------------------------


def _spans_of(tid):
    entry = trace.trace_store().get(tid)
    assert entry is not None
    return entry["spans"]


def test_retry_attempts_traced_with_peer(clean_faults):
    """Every re-issued RPC attempt lands in the statement's trace tree
    as an `rpc:retry` leaf with the retried peer labeled."""
    cli = RpcClient("127.0.0.1", 1, timeout=0.2, retries=2)  # dead port
    try:
        with trace.start_trace("query:TestRetry", service="graphd") as tg:
            tid = tg.trace_id
            with pytest.raises(Exception):
                cli.call("storage.get_vertex", space="x", part=0)
        retries = [s for s in _spans_of(tid) if s["name"] == "rpc:retry"]
        assert len(retries) >= 2
        assert all(s["attrs"]["peer"] == "127.0.0.1:1" for s in retries)
        assert all("attempt" in s["attrs"] for s in retries)
    finally:
        cli.close()


def test_breaker_transitions_traced(clean_faults):
    get_config().set_dynamic("breaker_failure_threshold", 2)
    get_config().set_dynamic("breaker_reset_secs", 0.05)
    cli = RpcClient("127.0.0.1", 1, timeout=0.2, retries=0)
    try:
        with trace.start_trace("query:TestBreaker",
                               service="graphd") as tg:
            tid = tg.trace_id
            for _ in range(3):
                with pytest.raises(Exception):
                    cli.call("storage.get_vertex", space="x", part=0)
            time.sleep(0.08)
            # half-open probe admitted, fails, re-opens
            with pytest.raises(Exception):
                cli.call("storage.get_vertex", space="x", part=0)
        br_spans = [s for s in _spans_of(tid)
                    if s["name"] == "rpc:breaker"]
        states = [s["attrs"]["to"] for s in br_spans]
        assert "open" in states and "half_open" in states, states
        assert all(s["attrs"]["peer"] == "127.0.0.1:1" for s in br_spans)
    finally:
        cli.close()
        get_config().dynamic_layer.pop("breaker_failure_threshold", None)
        get_config().dynamic_layer.pop("breaker_reset_secs", None)


def test_dedup_fast_path_traced_and_costed(cluster, clean_faults):
    """A re-sent tokened write answered from the dedup window produces
    a `storage:dedup_hit` remote span in the caller's trace and a
    `dedup_hits` field in the reply cost record."""
    sc = StorageClient(cluster.meta_clients[0])
    pid = sc.part_of("tel", 1)
    params = {"cmds": [to_wire(["upd_vertex", 1, "Person",
                                {"age": 33}])],
              "cat_ver": cluster.meta_clients[0].version,
              "token": ["wtrace", 71]}
    sc._call_part("tel", pid, "storage.write", dict(params))
    cc = CostRecorder()
    with trace.start_trace("query:TestDedup", service="graphd") as tg:
        tid = tg.trace_id
        with use_cost(cc):
            sc._call_part("tel", pid, "storage.write", dict(params))
    hits = [s for s in _spans_of(tid)
            if s["name"] == "storage:dedup_hit"]
    assert hits and hits[0].get("remote"), \
        "dedup fast path did not land in the trace"
    assert hits[0]["attrs"]["writer"] == "wtrace"
    assert cc.as_dict().get("dedup_hits", 0) >= 1
    sc.close()


def test_profile_fused_pipeline_segments():
    """A fused TpuMatchPipeline node is no longer opaque: PROFILE shows
    each segment's own wall time / rows (and device µs where a segment
    dispatched)."""
    from test_tpu import P, random_store  # noqa: E402 — shared harness
    from nebula_tpu.tpu import TpuRuntime, make_mesh

    st = random_store(3, n=60, avg_deg=4)
    eng = QueryEngine(st, tpu_runtime=TpuRuntime(make_mesh(P)))
    s = eng.new_session()
    eng.execute(s, "USE g")
    q = ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2,3] "
         "WITH DISTINCT b MATCH (b)-[:knows]->(c:person) "
         "RETURN id(b) AS x, id(c) AS y ORDER BY x, y")
    plain = eng.execute(s, q)
    assert plain.error is None
    prof = eng.execute(s, "PROFILE " + q)
    assert prof.error is None
    if "TpuMatchPipeline" in (prof.plan_desc or ""):
        assert "segment:" in prof.plan_desc, prof.plan_desc
        assert "segment:result" in prof.plan_desc
    assert sorted(map(tuple, prof.data.rows)) == \
        sorted(map(tuple, plain.data.rows))


# -- SLO engine -------------------------------------------------------------


def test_show_slo_reports_burn_rates():
    from nebula_tpu.utils.slo import slo_engine
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "YIELD 1")
    eng.execute(s, "GOGO")            # syntax error → availability bad
    slo_engine().tick()
    r = eng.execute(s, "SHOW SLO")
    assert r.ok, r.error
    assert r.data.column_names == ["Objective", "Window", "Target",
                                   "Total", "Bad", "Bad Ratio",
                                   "Burn Rate"]
    rows = r.data.rows
    assert len(rows) == 6             # 2 objectives × 3 windows
    avail = [x for x in rows if x[0] == "availability"]
    assert len(avail) == 3 and all(x[6] >= 0 for x in avail)
    # the 6h window has seen at least one error by now → nonzero burn
    a6 = next(x for x in avail if x[1] == "6h")
    assert a6[3] > 0 and a6[6] > 0
    # gauges published for federation
    snap = stats().snapshot()
    assert "slo_burn_availability_1h" in snap
    assert "slo_burn_latency_6h" in snap


def test_slo_history_survives_subsecond_polling(monkeypatch):
    """Burst collapse must KEEP older snapshots, not replace them — a
    0.5s poller must still leave real window bases behind."""
    import nebula_tpu.utils.slo as slo_mod
    eng = slo_mod.SloEngine()
    clock = {"t": 1000.0}
    monkeypatch.setattr(slo_mod.time, "monotonic",
                        lambda: clock["t"])
    for i in range(20):               # 10s of 0.5s polls
        clock["t"] = 1000.0 + i * 0.5
        eng.tick()
    assert len(eng._snaps) >= 10, \
        "sub-second polling starved the snapshot history"
    ages = [clock["t"] - ts for ts, _ in eng._snaps]
    assert max(ages) >= 9.0, f"oldest base too fresh: {ages}"


def test_slo_endpoint():
    from nebula_tpu.cluster.webservice import WebService
    ws = WebService(role="graphd")
    ws.start()
    try:
        rows = json.loads(urllib.request.urlopen(
            f"http://{ws.addr}/slo").read())
        assert len(rows) == 6
        assert {r["window"] for r in rows} == {"5m", "1h", "6h"}
    finally:
        ws.stop()


# -- metric federation ------------------------------------------------------


def test_federation_scrapes_and_labels(cluster):
    from nebula_tpu.cluster.federation import MetricFederator
    from nebula_tpu.cluster.webservice import WebService
    ws_g = WebService(role="graphd")
    ws_s = WebService(role="storaged")
    ws_g.start()
    ws_s.start()
    try:
        # daemons report their webservice addr via the heartbeat
        graph_mc = cluster.meta_clients[-1]
        stor_mc = cluster.meta_clients[0]
        graph_mc.ws_addr = ws_g.addr
        stor_mc.ws_addr = ws_s.addr
        graph_mc.heartbeat_once()
        stor_mc.heartbeat_once()
        fed = MetricFederator(cluster.metads[0])
        targets = fed.targets()
        assert {t[2] for t in targets} >= {ws_g.addr, ws_s.addr}
        merged = fed.scrape_once()
        assert f'instance="{graph_mc.my_addr}"' in merged
        assert 'role="graphd"' in merged and 'role="storaged"' in merged
        # every sample line is labeled (federation invariant)
        for ln in merged.splitlines():
            if ln and not ln.startswith("#"):
                assert 'instance="' in ln, ln
        status = fed.scrape_status()
        assert all(s["ok"] for s in status.values())
        # dead target counts an error, does not break the merge
        ws_s.stop()
        fed.scrape_once()
        assert any(not s["ok"] for s in fed.scrape_status().values())
    finally:
        ws_g.stop()
        try:
            ws_s.stop()
        except Exception:  # noqa: BLE001 — already stopped above
            pass


def test_federation_label_injection_grammar():
    from nebula_tpu.cluster.federation import _inject_labels
    text = ('# TYPE a counter\na 3\n'
            'b{op="x",le="+Inf"} 7\nc_sum 1.5\n')
    out = _inject_labels(text, "1.2.3.4:9779", "storaged")
    assert 'a{instance="1.2.3.4:9779",role="storaged"} 3' in out
    assert 'b{op="x",le="+Inf",instance="1.2.3.4:9779",' \
           'role="storaged"} 7' in out


# -- metric catalogue lint --------------------------------------------------


def _emitted_metric_names():
    call_pat = re.compile(
        r'\.(?:inc|inc_labeled|observe|gauge_labeled|gauge|add_value)\(\s*'
        r'["\']([A-Za-z_][A-Za-z0-9_.]*)["\']')
    slo_pat = re.compile(r'["\'](slo_burn_[a-z0-9_]+)["\']')
    names = set()
    for p in (REPO / "nebula_tpu").rglob("*.py"):
        src = p.read_text()
        names.update(call_pat.findall(src))
        names.update(slo_pat.findall(src))
    # dynamically-composed names (prefix + suffix): verified here so
    # the allowlist can't outlive the code that emits them
    pushdown = (REPO / "nebula_tpu/cluster/pushdown.py").read_text()
    assert 'stats_prefix + "_scanned"' in pushdown
    assert 'stats_prefix + "_shipped"' in pushdown
    assert '"storage_pushdown"' in \
        (REPO / "nebula_tpu/cluster/storage_service.py").read_text()
    names.update({"storage_pushdown_scanned",
                  "storage_pushdown_shipped"})
    return names


def _catalogued_metric_names():
    doc = (REPO / "docs/OBSERVABILITY.md").read_text()
    section = doc.split("## Metric catalogue", 1)
    assert len(section) == 2, "OBSERVABILITY.md lost its catalogue"
    return set(re.findall(r"^- `([A-Za-z0-9_.]+)`", section[1],
                          re.MULTILINE))


@pytest.mark.lint
def test_metric_catalogue_lint():
    """Every metric the registries emit is documented, and every
    documented metric is emitted — the catalogue cannot drift."""
    emitted = _emitted_metric_names()
    documented = _catalogued_metric_names()
    undocumented = emitted - documented
    stale = documented - emitted
    assert not undocumented, \
        f"metrics missing from docs/OBSERVABILITY.md catalogue: " \
        f"{sorted(undocumented)}"
    assert not stale, \
        f"catalogued metrics no code emits: {sorted(stale)}"


# -- span catalogue lint (ISSUE 9 satellite) --------------------------------


def _emitted_span_names():
    """Every span / phase / root-trace name the source tree emits,
    with dynamic f-string segments (`{node.kind}`) normalized to `*`
    so `exec:{node.kind}` and the catalogue's `exec:*` compare equal."""
    pat = re.compile(
        r'(?:trace|_trace|_t)\.(?:span|record_phase|start_trace)\(\s*'
        r'(f?)["\']([^"\']+)["\']')
    names = set()
    for p in (REPO / "nebula_tpu").rglob("*.py"):
        for isf, name in pat.findall(p.read_text()):
            if isf:
                name = re.sub(r"\{[^}]*\}", "*", name)
            names.add(name)
    return names


def _catalogued_span_names():
    doc = (REPO / "docs/OBSERVABILITY.md").read_text()
    section = doc.split("## Span catalogue", 1)
    assert len(section) == 2, "OBSERVABILITY.md lost its span catalogue"
    body = section[1].split("\n## ", 1)[0]
    return set(re.findall(r"^- `([A-Za-z0-9_.:*]+)`", body,
                          re.MULTILINE))


@pytest.mark.lint
def test_span_catalogue_lint():
    """Every span/phase name the source emits is documented and every
    documented span name is emitted — so a renamed span cannot
    silently orphan dashboards or the Perfetto export."""
    emitted = _emitted_span_names()
    documented = _catalogued_span_names()
    assert emitted, "span scan found nothing — the regex rotted"
    undocumented = emitted - documented
    stale = documented - emitted
    assert not undocumented, \
        f"spans missing from docs/OBSERVABILITY.md span catalogue: " \
        f"{sorted(undocumented)}"
    assert not stale, \
        f"catalogued spans no code emits: {sorted(stale)}"
