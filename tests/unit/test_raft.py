"""Raft + WAL tests: in-process multi-node groups over LoopbackTransport
(the reference tests raftex the same way — multiple parts in one process;
SURVEY §4)."""
import threading
import time

import pytest

from nebula_tpu.cluster.raft import LEADER, LoopbackTransport, RaftPart
from nebula_tpu.cluster.wal import Wal


# ---------------------------------------------------------------------------
# WAL
# ---------------------------------------------------------------------------


def test_wal_roundtrip(tmp_path):
    w = Wal(str(tmp_path / "a.wal"))
    for i in range(1, 6):
        w.append(i, 1, f"e{i}".encode())
    assert w.last_index() == 5
    assert w.read(3) == (1, b"e3")
    assert list(w.read_range(2, 4)) == [(2, 1, b"e2"), (3, 1, b"e3"),
                                        (4, 1, b"e4")]
    w.close()
    # recovery
    w2 = Wal(str(tmp_path / "a.wal"))
    assert w2.last_index() == 5
    assert w2.read(5) == (1, b"e5")
    w2.close()


def test_wal_truncate_and_compact(tmp_path):
    w = Wal(str(tmp_path / "b.wal"))
    for i in range(1, 11):
        w.append(i, i % 3, str(i).encode())
    w.truncate_from(8)
    assert w.last_index() == 7
    w.append(8, 9, b"new8")
    assert w.read(8) == (9, b"new8")
    w.compact_to(5)
    assert w.first_index() == 6
    assert w.read(5) is None
    assert w.read(7) == (1, b"7")
    w.close()
    w2 = Wal(str(tmp_path / "b.wal"))
    assert w2.first_index() == 6
    assert w2.last_index() == 8
    w2.close()


def test_wal_torn_tail_recovery(tmp_path):
    p = str(tmp_path / "c.wal")
    w = Wal(p)
    w.append(1, 1, b"one")
    w.append(2, 1, b"two")
    w.close()
    with open(p, "ab") as f:
        f.write(b"\x01\x02garbage-partial-record")
    w2 = Wal(p)
    assert w2.last_index() == 2
    w2.append(3, 2, b"three")          # append after recovery works
    assert w2.read(3) == (2, b"three")
    w2.close()


# ---------------------------------------------------------------------------
# Raft
# ---------------------------------------------------------------------------


class Applied:
    def __init__(self):
        self.entries = []
        self.lock = threading.Lock()

    def cb(self, idx, data):
        with self.lock:
            self.entries.append((idx, data))

    def data(self):
        with self.lock:
            return [d for _, d in self.entries]


def make_cluster(tmp_path, n=3, group="g0", snapshot=False, **kw):
    tr = LoopbackTransport()
    nodes = [f"n{i}" for i in range(n)]
    parts, apps = [], []
    for i, nid in enumerate(nodes):
        app = Applied()
        state = {"log": []}
        snap_cb = rest_cb = None
        if snapshot:
            def snap_cb(a=app):
                return b"|".join(a.data())

            def rest_cb(b, a=app):
                with a.lock:
                    a.entries = [(0, d) for d in b.split(b"|") if d]
        part = RaftPart(group, nid, nodes, tr,
                        str(tmp_path / nid), app.cb,
                        snapshot_cb=snap_cb, restore_cb=rest_cb,
                        election_timeout=(0.05, 0.12),
                        heartbeat_interval=0.02, **kw)
        parts.append(part)
        apps.append(app)
    for p in parts:
        p.start()
    return tr, parts, apps


def wait_leader(parts, timeout=20.0):
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        leaders = [p for p in parts if p.is_leader() and p.alive]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.01)
    raise AssertionError("no unique leader elected")


def wait_applied(apps, want, timeout=20.0, exclude=()):
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        if all(a.data() == want for i, a in enumerate(apps)
               if i not in exclude):
            return
        time.sleep(0.01)
    got = [a.data() for a in apps]
    raise AssertionError(f"apply mismatch: want {want}, got {got}")


def stop_all(parts):
    for p in parts:
        p.stop()


def test_election_and_replication(tmp_path):
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        assert leader.propose(b"x=1")
        assert leader.propose(b"x=2")
        wait_applied(apps, [b"x=1", b"x=2"])
    finally:
        stop_all(parts)


def test_single_node_group(tmp_path):
    tr, parts, apps = make_cluster(tmp_path, n=1)
    try:
        leader = wait_leader(parts)
        assert leader.propose(b"solo")
        assert apps[0].data() == [b"solo"]
    finally:
        stop_all(parts)


def test_leader_failover_and_catchup(tmp_path):
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        # generous timeout: on a starved 2-core VM under full-suite
        # load a commit can exceed the 5s default while still in
        # flight — timing out would retry and double-apply
        assert leader.propose(b"a", timeout=20)
        wait_applied(apps, [b"a"])
        # kill the leader; a new one takes over and accepts writes
        dead = parts.index(leader)
        leader.alive = False
        rest = [p for p in parts if p is not leader]
        new_leader = wait_leader(rest)
        assert new_leader.propose(b"b", timeout=20)
        wait_applied(apps, [b"a", b"b"], exclude=(dead,))
        # old leader rejoins as follower and catches up
        parts[dead].state = "follower"
        parts[dead].alive = True
        parts[dead]._thread = threading.Thread(
            target=parts[dead]._run, daemon=True)
        parts[dead]._thread.start()
        wait_applied(apps, [b"a", b"b"])
        assert not parts[dead].is_leader() or parts[dead].current_term >= \
            new_leader.current_term
    finally:
        stop_all(parts)


def test_partition_minority_cannot_commit(tmp_path):
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        others = [p for p in parts if p is not leader]
        # isolate the leader from both followers
        for o in others:
            tr.partition(leader.node_id, o.node_id)
        assert leader.propose(b"lost", timeout=0.5) is None
        # Retry-against-current-leader like a real client: the first
        # majority-side leader can be deposed by a concurrent election
        # before the propose lands (propose contract: None -> retry).
        deadline = time.time() + 15
        while True:
            new_leader = wait_leader(others)
            if new_leader.propose(b"kept"):
                break
            assert time.time() < deadline, "majority never committed"
        tr.heal()
        wait_applied(apps, [b"kept"])
        # the isolated leader's uncommitted entry must be discarded
        assert apps[parts.index(leader)].data() == [b"kept"]
    finally:
        stop_all(parts)


def test_restart_replays_from_wal(tmp_path):
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        for i in range(5):
            # starved-VM tolerance: see test_leader_failover_and_catchup
            assert leader.propose(f"v{i}".encode(), timeout=20)
        want = [f"v{i}".encode() for i in range(5)]
        wait_applied(apps, want)
    finally:
        stop_all(parts)
    # restart node 0 from its WAL dir with a fresh state machine
    app = Applied()
    tr2 = LoopbackTransport()
    p0 = RaftPart("g0", "n0", ["n0"], tr2, str(tmp_path / "n0"), app.cb,
                  election_timeout=(0.05, 0.12), heartbeat_interval=0.02)
    p0.start()
    try:
        wait_leader([p0])
        assert p0.propose(b"after")
        assert app.data() == [f"v{i}".encode() for i in range(5)] + [b"after"]
    finally:
        p0.stop()


def test_full_group_restart_recommits(tmp_path):
    """After every replica restarts, the new leader's no-op entry must
    re-commit (and re-apply) the previous terms' entries without waiting
    for a new client write."""
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        # a CPU-starved election may depose the leader mid-loop under
        # full-suite load: follow the new leader instead of failing
        deadline = time.monotonic() + 30
        i = 0
        while i < 3:
            # long per-propose timeout: a timed-out-but-committed
            # propose would be retried here and double-apply, making
            # the exact wait_applied below unreachable
            if leader.propose(f"r{i}".encode(), timeout=20):
                i += 1
            else:
                assert time.monotonic() < deadline, "no stable leader"
                leader = wait_leader(parts)
        wait_applied(apps, [b"r0", b"r1", b"r2"])
    finally:
        stop_all(parts)
    # full restart: fresh state machines, same WAL dirs, NO new writes
    tr2 = LoopbackTransport()
    nodes = [f"n{i}" for i in range(3)]
    apps2 = [Applied() for _ in nodes]
    parts2 = [RaftPart("g0", nid, nodes, tr2, str(tmp_path / nid),
                       apps2[i].cb, election_timeout=(0.05, 0.12),
                       heartbeat_interval=0.02)
              for i, nid in enumerate(nodes)]
    for p in parts2:
        p.start()
    try:
        wait_leader(parts2)
        wait_applied(apps2, [b"r0", b"r1", b"r2"])
    finally:
        stop_all(parts2)


def test_snapshot_compaction_and_laggard_catchup(tmp_path):
    tr, parts, apps = make_cluster(tmp_path, snapshot=True,
                                   snapshot_threshold=10)
    try:
        leader = wait_leader(parts)
        lag = [p for p in parts if p is not leader][0]
        lag_i = parts.index(lag)
        # isolate the laggard from BOTH peers: it can neither receive
        # entries nor win an election.  A CPU-starved election may still
        # move leadership between the other two mid-loop (propose then
        # returns False) — follow the new leader instead of failing.
        for o in parts:
            if o is not lag:
                tr.partition(o.node_id, lag.node_id)
        n_entries = 25
        deadline = time.monotonic() + 15
        i = 0
        while i < n_entries:
            if leader.propose(f"s{i}".encode()):
                i += 1
            else:
                assert time.monotonic() < deadline, "no stable leader"
                leader = wait_leader([p for p in parts if p is not lag])
        want = [f"s{i}".encode() for i in range(n_entries)]
        wait_applied(apps, want, exclude=(lag_i,))
        # leader compacted its log past the laggard's position
        assert leader.wal.first_index() > 1
        tr.heal()
        dl = time.monotonic() + 5
        while time.monotonic() < dl:
            if apps[lag_i].data()[-1:] == [f"s{n_entries-1}".encode()]:
                break
            time.sleep(0.02)
        # laggard caught up via snapshot + tail entries
        assert apps[lag_i].data()[-1] == f"s{n_entries-1}".encode()
    finally:
        stop_all(parts)


# ---------------------------------------------------------------------------
# membership change + leadership transfer (the BALANCE primitives)
# ---------------------------------------------------------------------------


def test_transfer_leadership(tmp_path):
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        assert leader.propose(b"w1")
        target = next(p for p in parts if p is not leader)
        assert leader.transfer_leadership(target.node_id)
        # old leader stepped down instantly (lease honesty)
        assert not leader.is_leader()
        # under full-suite CPU load a starved election can beat the
        # TimeoutNow head start or depose the target right after it
        # wins — re-issue the transfer until the TARGET leads and has
        # committed a write of its own
        dl = time.monotonic() + 15
        done = False
        while not done:
            assert time.monotonic() < dl, "transfer never stabilized"
            if target.is_leader():
                done = target.propose(b"w2")
                continue
            cur = next((p for p in parts if p.is_leader()), None)
            if cur is not None and cur is not target:
                cur.transfer_leadership(target.node_id)
            time.sleep(0.02)
        wait_applied(apps, [b"w1", b"w2"])
    finally:
        stop_all(parts)


def test_update_peers_add_and_remove(tmp_path):
    """A new member joins an existing group via update_peers, catches up,
    then an old member is removed and its replicator stops."""
    tr, parts, apps = make_cluster(tmp_path, n=3)
    try:
        leader = wait_leader(parts)
        for i in range(5):
            assert leader.propose(f"e{i}".encode())
        # join n3
        app3 = Applied()
        n3 = RaftPart("g0", "n3", ["n0", "n1", "n2", "n3"], tr,
                      str(tmp_path / "n3"), app3.cb,
                      election_timeout=(0.05, 0.12),
                      heartbeat_interval=0.02)
        n3.start()
        for p in parts:
            p.update_peers(["n0", "n1", "n2", "n3"])
        wait_applied([app3], [f"e{i}".encode() for i in range(5)])
        # remove one original follower
        gone = next(p for p in parts if p is not leader)
        new_set = [n for n in ("n0", "n1", "n2", "n3")
                   if n != gone.node_id]
        for p in parts + [n3]:
            if p is not gone:
                p.update_peers(new_set)
        gone.stop()
        # the shrunk group still commits
        assert leader.propose(b"after")
        live_apps = [a for p, a in zip(parts + [n3], apps + [app3])
                     if p is not gone]
        wait_applied(live_apps, [f"e{i}".encode() for i in range(5)]
                     + [b"after"])
    finally:
        stop_all(parts)
        n3.stop()
