"""User management + PermissionManager (SURVEY §2 row 26; VERDICT r1
missing #6): catalog user CRUD, role grants, wire round-trip, engine
statements, and role-gated admission with enable_authorize on."""
import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore import schema_wire
from nebula_tpu.graphstore.schema import Catalog, SchemaError, hash_password
from nebula_tpu.utils.config import get_config


def mk_engine():
    eng = QueryEngine()
    root = eng.new_session()
    eng.execute(root, "CREATE SPACE s1(partition_num=2, vid_type=INT64)")
    eng.execute(root, "USE s1")
    eng.execute(root, "CREATE TAG t(x int)")
    return eng, root


# -- catalog layer ----------------------------------------------------------


def test_catalog_user_crud():
    c = Catalog()
    assert c.role_of("root", None) == "GOD"
    u = c.create_user("alice", "pw1")
    assert u.check_password("pw1") and not u.check_password("pw2")
    with pytest.raises(SchemaError):
        c.create_user("alice", "other")
    c.create_user("alice", "other", if_not_exists=True)   # no-op
    assert c.get_user("alice").check_password("pw1")
    c.alter_user("alice", "pw2")
    assert c.get_user("alice").check_password("pw2")
    c.change_password("alice", "pw2", "pw3")
    with pytest.raises(SchemaError):
        c.change_password("alice", "bad-old", "x")
    c.drop_user("alice")
    with pytest.raises(SchemaError):
        c.get_user("alice")
    with pytest.raises(SchemaError):
        c.drop_user("root")


def test_catalog_roles():
    c = Catalog()
    c.create_space("g", partition_num=2)
    c.create_user("bob", "pw")
    with pytest.raises(SchemaError):
        c.grant_role("bob", "g", "GOD")
    with pytest.raises(SchemaError):
        c.grant_role("bob", "nospace", "USER")
    c.grant_role("bob", "g", "dba")
    assert c.role_of("bob", "g") == "DBA"
    assert c.role_of("bob", "other") is None
    with pytest.raises(SchemaError):
        c.revoke_role("bob", "g", "ADMIN")    # role mismatch
    c.revoke_role("bob", "g", "DBA")
    assert c.role_of("bob", "g") is None
    # dropping a space clears grants on it
    c.grant_role("bob", "g", "USER")
    c.drop_space("g")
    assert "g" not in c.get_user("bob").roles


def test_users_wire_roundtrip():
    c = Catalog()
    c.create_space("g", partition_num=2)
    c.create_user("eve", "secret")
    c.grant_role("eve", "g", "ADMIN")
    c2 = schema_wire.from_jso(schema_wire.to_jso(c))
    assert c2.get_user("eve").check_password("secret")
    assert c2.role_of("eve", "g") == "ADMIN"
    assert c2.role_of("root", None) == "GOD"
    # pre-ACL payload (no users key) keeps the default root
    j = schema_wire.to_jso(Catalog())
    del j["users"]
    c3 = schema_wire.from_jso(j)
    assert c3.role_of("root", None) == "GOD"


def test_password_storage_is_hashed():
    c = Catalog()
    c.create_user("u", "plaintext")
    assert "plaintext" not in repr(c.get_user("u").pwd_hash)
    assert c.get_user("u").pwd_hash == hash_password("plaintext")


# -- engine statements ------------------------------------------------------


def test_user_statements():
    eng, root = mk_engine()
    for q in ['CREATE USER alice WITH PASSWORD "pw"',
              'CREATE USER IF NOT EXISTS alice WITH PASSWORD "zz"',
              'GRANT ROLE DBA ON s1 TO alice',
              'ALTER USER alice WITH PASSWORD "pw2"',
              'CHANGE PASSWORD alice FROM "pw2" TO "pw3"']:
        rs = eng.execute(root, q)
        assert rs.error is None, (q, rs.error)
    rs = eng.execute(root, "SHOW USERS")
    assert sorted(r[0] for r in rs.data.rows) == ["alice", "root"]
    rs = eng.execute(root, "SHOW ROLES IN s1")
    assert rs.data.rows == [["alice", "DBA"]]
    rs = eng.execute(root, "REVOKE ROLE DBA ON s1 FROM alice")
    assert rs.error is None
    rs = eng.execute(root, "SHOW ROLES IN s1")
    assert rs.data.rows == []
    rs = eng.execute(root, "DROP USER alice")
    assert rs.error is None
    rs = eng.execute(root, 'CREATE USER alice WITH PASSWORD')
    assert rs.error is not None and "SyntaxError" in rs.error


@pytest.fixture
def authz():
    get_config().set_dynamic("enable_authorize", True)
    yield
    get_config().set_dynamic("enable_authorize", False)


def test_permission_lattice(authz):
    eng, root = mk_engine()
    eng.execute(root, 'CREATE USER guest WITH PASSWORD "g"')
    eng.execute(root, 'CREATE USER writer WITH PASSWORD "w"')
    eng.execute(root, 'CREATE USER dba WITH PASSWORD "d"')
    eng.execute(root, 'CREATE USER admin WITH PASSWORD "a"')
    for u, r in (("guest", "GUEST"), ("writer", "USER"),
                 ("dba", "DBA"), ("admin", "ADMIN")):
        rs = eng.execute(root, f"GRANT ROLE {r} ON s1 TO {u}")
        assert rs.error is None, rs.error
    eng.execute(root, "INSERT VERTEX t(x) VALUES 1:(10)")

    def run(user, q):
        s = eng.new_session(user)
        eng.execute(s, "USE s1")
        return eng.execute(s, q)

    # GUEST: read yes, write no
    assert run("guest", "FETCH PROP ON t 1 YIELD t.x").error is None
    rs = run("guest", "INSERT VERTEX t(x) VALUES 2:(20)")
    assert rs.error and "PermissionError" in rs.error
    # USER: write yes, DDL no
    assert run("writer", "INSERT VERTEX t(x) VALUES 3:(30)").error is None
    rs = run("writer", "CREATE TAG t2(y int)")
    assert rs.error and "PermissionError" in rs.error
    # DBA: DDL yes, grant no
    assert run("dba", "CREATE TAG t3(y int)").error is None
    rs = run("dba", "GRANT ROLE GUEST ON s1 TO guest")
    assert rs.error and "PermissionError" in rs.error
    # ADMIN: grant yes, create space no
    assert run("admin", "GRANT ROLE GUEST ON s1 TO writer").error is None
    rs = run("admin", "CREATE SPACE other(partition_num=2, vid_type=INT64)")
    assert rs.error and "PermissionError" in rs.error
    # no role at all: even USE of the space is denied
    eng.execute(root, 'CREATE USER outsider WITH PASSWORD "o"')
    s = eng.new_session("outsider")
    rs = eng.execute(s, "USE s1")
    assert rs.error and "PermissionError" in rs.error


def test_change_own_password_allowed(authz):
    eng, root = mk_engine()
    eng.execute(root, 'CREATE USER me WITH PASSWORD "old"')
    eng.execute(root, "GRANT ROLE GUEST ON s1 TO me")
    s = eng.new_session("me")
    rs = eng.execute(s, 'CHANGE PASSWORD me FROM "old" TO "new"')
    assert rs.error is None, rs.error
    rs = eng.execute(s, 'CHANGE PASSWORD root FROM "nebula" TO "x"')
    assert rs.error and "PermissionError" in rs.error
    # GOD may change anyone's
    rs = eng.execute(root, 'ALTER USER me WITH PASSWORD "again"')
    assert rs.error is None


def test_show_users_needs_god(authz):
    eng, root = mk_engine()
    eng.execute(root, 'CREATE USER low WITH PASSWORD "l"')
    eng.execute(root, "GRANT ROLE ADMIN ON s1 TO low")
    s = eng.new_session("low")
    rs = eng.execute(s, "SHOW USERS")
    assert rs.error and "PermissionError" in rs.error
    assert eng.execute(root, "SHOW USERS").error is None


def test_cluster_user_auth(tmp_path):
    """Users created through graphd replicate via metad and gate
    authentication cluster-wide."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        root_client = c.client()
        rs = root_client.execute('CREATE USER carol WITH PASSWORD "pw"')
        assert rs.error is None, rs.error
        rs = root_client.execute("SHOW USERS")
        assert sorted(r[0] for r in rs.data.rows) == ["carol", "root"]
        get_config().set_dynamic("enable_authorize", True)
        try:
            ok = c.client(user="carol", password="pw")
            assert ok.execute("SHOW SPACES").error is None
            with pytest.raises(Exception):
                c.client(user="carol", password="wrong")
        finally:
            get_config().set_dynamic("enable_authorize", False)
    finally:
        c.stop()


def test_show_roles_needs_admin_on_target(authz):
    eng, root = mk_engine()
    eng.execute(root, "CREATE SPACE s2(partition_num=2, vid_type=INT64)")
    eng.execute(root, 'CREATE USER snoop WITH PASSWORD "s"')
    eng.execute(root, "GRANT ROLE ADMIN ON s1 TO snoop")
    s = eng.new_session("snoop")
    assert eng.execute(s, "SHOW ROLES IN s1").error is None
    rs = eng.execute(s, "SHOW ROLES IN s2")
    assert rs.error and "PermissionError" in rs.error


def test_password_rotation_invalidates_old(tmp_path):
    """graph_service must not fall back to the legacy static map for a
    catalog account — a rotated password's predecessor stays dead."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        root_client = c.client()
        rs = root_client.execute('ALTER USER root WITH PASSWORD "rotated"')
        assert rs.error is None, rs.error
        get_config().set_dynamic("enable_authorize", True)
        try:
            with pytest.raises(Exception):
                c.client(user="root", password="nebula")
            ok = c.client(user="root", password="rotated")
            assert ok.execute("SHOW SPACES").error is None
        finally:
            get_config().set_dynamic("enable_authorize", False)
    finally:
        c.stop()


def test_keyword_named_schema_objects():
    """Unreserved keywords (User, Role, password...) stay usable as
    case-preserved identifiers."""
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "CREATE SPACE kw(partition_num=2, vid_type=INT64)")
    eng.execute(s, "USE kw")
    rs = eng.execute(s, "CREATE TAG User(Role string, password int)")
    assert rs.error is None, rs.error
    rs = eng.execute(s, 'INSERT VERTEX User(Role, password) VALUES 1:("r", 5)')
    assert rs.error is None, rs.error
    rs = eng.execute(s, "FETCH PROP ON User 1 YIELD User.Role AS r, User.password AS p")
    assert rs.error is None and rs.data.rows == [["r", 5]]


def test_cross_pattern_edge_uniqueness():
    """Relationship isomorphism scopes to the whole MATCH clause."""
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "CREATE SPACE xp(partition_num=2, vid_type=INT64)")
    eng.execute(s, "USE xp")
    eng.execute(s, "CREATE TAG n(x int)")
    eng.execute(s, "CREATE EDGE r(w int)")
    eng.execute(s, "INSERT VERTEX n(x) VALUES 1:(1), 2:(2)")
    eng.execute(s, "INSERT EDGE r(w) VALUES 1->2:(7)")
    rs = eng.execute(
        s, "MATCH (a:n)-[e1:r]->(b), (c:n)-[e2:r]->(d) RETURN id(a), id(c)")
    assert rs.error is None, rs.error
    assert rs.data.rows == []     # only one edge exists; e1 == e2 forbidden


def test_kill_query_needs_god(authz):
    eng, root = mk_engine()
    eng.execute(root, 'CREATE USER pleb WITH PASSWORD "p"')
    eng.execute(root, "GRANT ROLE ADMIN ON s1 TO pleb")
    s = eng.new_session("pleb")
    rs = eng.execute(s, "KILL QUERY(session=1, plan=2)")
    assert rs.error and "PermissionError" in rs.error


def test_keyword_aliases_in_match():
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "CREATE SPACE ka(partition_num=2, vid_type=INT64)")
    eng.execute(s, "USE ka")
    eng.execute(s, "CREATE TAG n(x int)")
    eng.execute(s, "CREATE EDGE KNOWS(w int)")
    eng.execute(s, "INSERT VERTEX n(x) VALUES 1:(1), 2:(2)")
    eng.execute(s, "INSERT EDGE KNOWS(w) VALUES 1->2:(9)")
    rs = eng.execute(s, "MATCH (a:n)-[role:KNOWS]->(b) RETURN role.w AS w")
    assert rs.error is None and rs.data.rows == [[9]], rs.error
    rs = eng.execute(s, "MATCH user = (a:n)-[:KNOWS]->(b) RETURN length(user) AS l")
    assert rs.error is None and rs.data.rows == [[1]], rs.error
    rs = eng.execute(s, "YIELD [user IN [1, 2, 3] | user * 2] AS l")
    assert rs.error is None and rs.data.rows == [[[2, 4, 6]]], rs.error


def test_no_plaintext_passwords_in_meta_raft_log(tmp_path):
    """User credentials replicate through metad as hashes — the raft WAL
    on disk must never contain the plaintext."""
    from nebula_tpu.cluster.launcher import LocalCluster
    import os
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        rs = client.execute('CREATE USER vault WITH PASSWORD "s3cr3tpw"')
        assert rs.error is None, rs.error
        rs = client.execute(
            'CHANGE PASSWORD vault FROM "s3cr3tpw" TO "n3wpw"')
        assert rs.error is None, rs.error
        rs = client.execute('CHANGE PASSWORD vault FROM "wrong" TO "x"')
        assert rs.error is not None
        blob = b""
        for root, _dirs, files in os.walk(str(tmp_path)):
            for fn in files:
                with open(os.path.join(root, fn), "rb") as f:
                    blob += f.read()
        assert b"s3cr3tpw" not in blob and b"n3wpw" not in blob
    finally:
        c.stop()
