"""Metrics registry, config layers, webservice endpoints, SHOW/UPDATE
CONFIGS, PROFILE device fields — the SURVEY §5 aux-subsystem surface."""
import json
import urllib.request

import pytest

from nebula_tpu.cluster.webservice import WebService
from nebula_tpu.exec import QueryEngine
from nebula_tpu.utils.config import Config, ConfigError, get_config
from nebula_tpu.utils.stats import StatsManager, stats


def test_stats_counters_and_series():
    sm = StatsManager()
    sm.inc("q")
    sm.inc("q", 4)
    sm.gauge("hbm", 123.0)
    for v in (10, 20, 30, 40):
        sm.add_value("lat", v)
    snap = sm.snapshot()
    assert snap["q"] == 5 and snap["hbm"] == 123.0
    assert snap["lat.count"] == 4 and snap["lat.avg"] == 25
    assert snap["lat.p50"] == 30
    assert "lat=..." not in sm.to_text()


def test_config_layers(tmp_path, monkeypatch):
    c = Config()
    c.define("alpha", 10, "t")
    c.define("beta", "x")
    assert c.get("alpha") == 10
    f = tmp_path / "conf"
    f.write_text("# comment\n--alpha=20\nbeta = y\n")
    c.load_file(str(f))
    assert c.get("alpha") == 20 and c.get("beta") == "y"
    monkeypatch.setenv("NEBULA_ALPHA", "30")
    assert c.get("alpha") == 30
    c.set_dynamic("alpha", 40)
    assert c.get("alpha") == 40
    with pytest.raises(ConfigError):
        c.get("nope")
    with pytest.raises(ConfigError):
        c.set_dynamic("nope", 1)


def test_config_bad_file_flag(tmp_path):
    c = Config()
    c.define("a", 1)
    f = tmp_path / "conf"
    f.write_text("zzz=1\n")
    with pytest.raises(ConfigError):
        c.load_file(str(f))


def test_webservice_endpoints():
    stats().inc("ws_test_counter", 7)
    get_config().define("ws_test_flag", 1, "t")
    ws = WebService(role="graphd")
    ws.start()
    try:
        base = f"http://{ws.addr}"
        st = json.loads(urllib.request.urlopen(base + "/status").read())
        assert st == {"status": "running", "role": "graphd"}
        body = urllib.request.urlopen(base + "/stats").read().decode()
        assert "ws_test_counter=7" in body
        flags = json.loads(urllib.request.urlopen(
            base + "/flags?format=json").read())
        assert flags["ws_test_flag"] == 1
        req = urllib.request.Request(base + "/flags", method="PUT",
                                     data=b"ws_test_flag=42")
        assert urllib.request.urlopen(req).status == 200
        assert get_config().get("ws_test_flag") == 42
        req = urllib.request.Request(base + "/flags", method="PUT",
                                     data=b"nosuch=1")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req)
    finally:
        ws.stop()


def test_show_and_update_configs():
    eng = QueryEngine()
    s = eng.new_session()
    r = eng.execute(s, "SHOW CONFIGS")
    assert r.ok
    names = [row[1] for row in r.data.rows]
    assert "slow_query_threshold_us" in names
    r = eng.execute(s, "UPDATE CONFIGS slow_query_threshold_us = 123456")
    assert r.ok, r.error
    assert get_config().get("slow_query_threshold_us") == 123456
    get_config().dynamic_layer.pop("slow_query_threshold_us", None)
    r = eng.execute(s, "UPDATE CONFIGS nosuchflag = 1")
    assert not r.ok


def test_put_flags_is_atomic():
    get_config().define("ws_atom_a", 1)
    get_config().define("ws_atom_b", 2)
    ws = WebService(role="t")
    ws.start()
    try:
        req = urllib.request.Request(
            f"http://{ws.addr}/flags", method="PUT",
            data=b"ws_atom_a=9\nnosuchflag=1\nws_atom_b=9")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req)
        # nothing applied — 400 means NO change
        assert get_config().get("ws_atom_a") == 1
        assert get_config().get("ws_atom_b") == 2
    finally:
        ws.stop()


def test_live_config_affects_slow_log():
    eng = QueryEngine()
    s = eng.new_session()
    get_config().set_dynamic("slow_query_threshold_us", 0)
    try:
        eng.execute(s, "YIELD 1")
        assert eng.slow_log, "live threshold change must take effect"
    finally:
        get_config().dynamic_layer.pop("slow_query_threshold_us", None)


def test_error_queries_counted():
    eng = QueryEngine()
    s = eng.new_session()
    before = stats().snapshot().get("num_query_errors", 0)
    eng.execute(s, "GOGO")                   # syntax error
    eng.execute(s, "GO FROM 1 OVER nosuch")  # semantic error
    after = stats().snapshot()
    assert after["num_query_errors"] >= before + 2


def test_query_metrics_flow():
    before = stats().snapshot().get("num_queries", 0)
    eng = QueryEngine()
    s = eng.new_session()
    eng.execute(s, "YIELD 1")
    eng.execute(s, "YIELD 2")
    after = stats().snapshot()
    assert after["num_queries"] >= before + 2
    assert after["query_latency_us.count"] >= 2


def test_tpu_profiler_trace(tmp_path):
    """tpu_profiler_dir wraps kernel runs in a jax.profiler trace and
    leaves an xplane dump on disk (SURVEY §5 tracing)."""
    import os

    from nebula_tpu.exec import QueryEngine
    from nebula_tpu.tpu.device import make_mesh
    from nebula_tpu.tpu.runtime import TpuRuntime
    from nebula_tpu.utils.config import get_config

    get_config().set_dynamic("tpu_profiler_dir", str(tmp_path))
    try:
        eng = QueryEngine(tpu_runtime=TpuRuntime(make_mesh()))
        s = eng.new_session()
        for q in ["CREATE SPACE pf(partition_num=8, vid_type=INT64)",
                  "USE pf", "CREATE EDGE e(w int)",
                  "INSERT EDGE e(w) VALUES 1->2:(1), 2->3:(2), 1->3:(3)",
                  "GO 2 STEPS FROM 1 OVER e YIELD dst(edge) AS d"]:
            r = eng.execute(s, q)
            assert r.error is None, f"{q} -> {r.error}"
        assert eng.qctx.last_tpu_stats is not None
        dumped = [os.path.join(dp, f) for dp, _, fs in os.walk(tmp_path)
                  for f in fs]
        assert dumped, "profiler trace left no files"
    finally:
        get_config().set_dynamic("tpu_profiler_dir", "")
