"""Workload insights plane (ISSUE 16): statement fingerprints (golden
digests — a wire contract), the per-graphd StatementRegistry (triage,
exact merge, concurrent aggregation vs sequential truth), the
plan-history regression sentinel (forced plan flip), the fingerprint
join across flight recorder / slow log / SHOW QUERIES, the
insights_enabled off switch, and the cluster surfaces (SHOW STATEMENTS
federation without double counting, SHOW HOTSPOTS from heartbeat-ridden
partition heat)."""
import threading
import time

import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.query.parser import parse
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.flight import flight_recorder
from nebula_tpu.utils.insights import (PartHeatTable, StatementRegistry,
                                       bucket_quantile, fingerprint_of,
                                       merge_heat_snapshots,
                                       merge_statement_snapshots,
                                       normalize_statement,
                                       statement_columns)
from nebula_tpu.utils.stats import stats


@pytest.fixture()
def clean():
    fail.reset()
    yield
    fail.reset()
    for k in ("insights_enabled", "plan_regression_min_calls",
              "plan_regression_ratio", "slow_query_threshold_us",
              "insights_max_fingerprints"):
        get_config().dynamic_layer.pop(k, None)


def small_engine(n=30, deg=3, space="ins"):
    eng = QueryEngine()
    s = eng.new_session()
    for q in (f"CREATE SPACE {space}(partition_num=2, vid_type=INT64)",
              f"USE {space}", "CREATE TAG P(x int)",
              "CREATE EDGE E(w int)"):
        r = eng.execute(s, q)
        assert r.error is None, f"{q} -> {r.error}"
    vals = ", ".join(f"{v}:({v})" for v in range(n))
    assert eng.execute(s, f"INSERT VERTEX P(x) VALUES {vals}").ok
    edges = ", ".join(f"{v}->{(v * k + 1) % n}:({v + k})"
                      for v in range(n) for k in range(1, deg + 1))
    assert eng.execute(s, f"INSERT EDGE E(w) VALUES {edges}").ok
    return eng, s


def _wait_for(pred, timeout=5.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        got = pred()
        if got:
            return got
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {msg}")


# -- fingerprint goldens (lint marker: tools/ci_lint.sh runs these) ---------


@pytest.mark.lint
def test_fingerprint_literals_collapse():
    """Same shape, different literals — and different literal COUNTS in
    homogeneous lists — share one fingerprint."""
    a = parse("GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d")
    b = parse("GO 2 STEPS FROM 7, 8, 9 OVER E YIELD dst(edge) AS d")
    assert fingerprint_of(a, "g") == fingerprint_of(b, "g")
    m1 = parse("MATCH (a:P)-[:E]->(b) WHERE a.P.x > 5 RETURN b")
    m2 = parse("MATCH (a:P)-[:E]->(b) WHERE a.P.x > 99 RETURN b")
    assert fingerprint_of(m1, "g") == fingerprint_of(m2, "g")
    i1 = parse("INSERT VERTEX P(x) VALUES 1:(1), 2:(2)")
    i2 = parse("INSERT VERTEX P(x) VALUES 9:(9)")
    assert fingerprint_of(i1, "g") == fingerprint_of(i2, "g")


@pytest.mark.lint
def test_fingerprint_structure_distinguishes():
    """Structure is preserved: step counts, yields, tags, kinds, and
    the session space all key distinct fingerprints."""
    base = parse("GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d")
    assert fingerprint_of(base, "g") != fingerprint_of(
        parse("GO 3 STEPS FROM 1 OVER E YIELD dst(edge) AS d"), "g")
    assert fingerprint_of(base, "g") != fingerprint_of(
        parse("GO 2 STEPS FROM 1 OVER E YIELD src(edge) AS d"), "g")
    assert fingerprint_of(base, "g") != fingerprint_of(base, "h")
    assert fingerprint_of(
        parse("MATCH (a:P) RETURN a"), "g") != fingerprint_of(
        parse("MATCH (a:Q) RETURN a"), "g")


@pytest.mark.lint
def test_fingerprint_golden_digests():
    """The digest is a WIRE CONTRACT: dashboards and the federation
    merge key on it, so a normalizer change that silently re-keys
    every fingerprint must fail here, not in production.  If a change
    is intentional, update these goldens in the same PR and say so."""
    cases = {
        "GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d": "bae38f2d4c1d",
        "MATCH (a:P)-[:E]->(b) WHERE a.P.x > 5 RETURN b": "c737f903645c",
        "INSERT VERTEX P(x) VALUES 1:(1), 2:(2)": "cbc3fbfef00d",
    }
    for text, want in cases.items():
        got = fingerprint_of(parse(text), "g")
        assert got == want, (
            f"fingerprint of {text!r} drifted: {got} != golden {want}\n"
            f"preimage: {normalize_statement(parse(text), 'g')}")


@pytest.mark.lint
def test_fingerprint_stable_across_parses():
    """Two independent parses of the same text normalize identically —
    no id()/ordering leakage into the preimage."""
    text = "GO 2 STEPS FROM 3 OVER E WHERE E.w > 1 YIELD dst(edge) AS d"
    assert normalize_statement(parse(text), "g") == \
        normalize_statement(parse(text), "g")
    assert fingerprint_of(parse(text), "g") == \
        fingerprint_of(parse(text), "g")


# -- registry: triage, columns, exact merge ---------------------------------


def test_registry_triage_and_columns(clean):
    reg = StatementRegistry()
    fp = "aaaaaaaaaaaa"
    common = dict(fp=fp, text="GO ...", kind="Go", space="g")
    reg.record(latency_us=90, **common)
    reg.record(latency_us=90, error="SemanticError: boom", **common)
    reg.record(latency_us=90,
               error="ExecutionError: query was killed", **common)
    reg.record(latency_us=40_000, error="E_OVERLOAD: retry_after_ms=5 "
               "site=graphd full", **common)
    row = reg.get(fp)
    assert row["calls"] == 4
    assert (row["errors"], row["kills"], row["sheds"]) == (1, 1, 1)
    cols = statement_columns([row])[0]
    # [fp, sample, calls, errors, p50, p95, rows, share, plan, chg, reg]
    assert cols[0] == fp and cols[2] == 4
    assert cols[3] == 3, "Errors column is the triage total"
    assert cols[4] == 100 and cols[5] == 50_000  # bucket upper bounds


def test_registry_eviction_bounded(clean):
    get_config().set_dynamic("insights_max_fingerprints", 4)
    reg = StatementRegistry()
    for i in range(10):
        reg.record(fp=f"fp{i:010d}", text=f"q{i}", kind="Go", space="g",
                   latency_us=100)
    assert len(reg) == 4
    assert reg.get("fp0000000009") is not None   # newest survives
    assert reg.get("fp0000000000") is None       # oldest evicted


def test_merge_statement_snapshots_exact(clean):
    """Cross-host merge is an exact fold: counters and bucket counts
    sum, quantiles of the merged buckets equal quantiles of the union,
    regressed ORs."""
    a, b = StatementRegistry(), StatementRegistry()
    fp = "feedfacef00d"
    for us in (100, 400, 900):
        a.record(fp=fp, text="GO ...", kind="Go", space="g",
                 latency_us=us, rows=2)
    for us in (4000, 9000, 40_000):
        b.record(fp=fp, text="GO ...", kind="Go", space="g",
                 latency_us=us, rows=3, error="x")
    merged = merge_statement_snapshots([a.snapshot(), b.snapshot()])
    assert len(merged) == 1
    m = merged[0]
    assert m["calls"] == 6 and m["rows"] == 15 and m["errors"] == 3
    union = [0] * len(m["lat_buckets"])
    for snap in (a.snapshot(), b.snapshot()):
        for i, c in enumerate(snap[0]["lat_buckets"]):
            union[i] += c
    assert m["lat_buckets"] == union
    assert bucket_quantile(m["lat_buckets"], 0.5) == 1000


def test_concurrent_aggregation_matches_sequential_truth(clean):
    """N threads hammering one statement shape aggregate to exactly
    the sequential truth — same calls, same rows, same bucket total
    (the acceptance bar: correct under concurrent mixed load)."""
    eng, s = small_engine(n=40, deg=4)
    seeds = list(range(12))

    def stmt(v):
        return f"GO 2 STEPS FROM {v} OVER E YIELD dst(edge) AS d"

    # sequential truth
    eng.insights.clear()
    rows_expected = 0
    for v in seeds:
        rs = eng.execute(s, stmt(v))
        assert rs.error is None, rs.error
        rows_expected += len(rs.data.rows)
    fp = eng.insights.fingerprints.get(stmt(seeds[0]), "ins")
    assert fp, "fingerprint memo must be warm after execution"
    seq = eng.insights.get(fp)
    assert seq["calls"] == len(seeds)

    # concurrent re-run, one session per thread, mixed with MATCHes
    eng.insights.clear()
    errs = []

    def run(vs):
        try:
            sess = eng.new_session()
            assert eng.execute(sess, "USE ins").ok
            for v in vs:
                r = eng.execute(sess, stmt(v))
                if r.error is not None:
                    errs.append(r.error)
                r = eng.execute(
                    sess, f"MATCH (a:P) WHERE a.P.x == {v} RETURN a")
                if r.error is not None:
                    errs.append(r.error)
        except Exception as ex:  # noqa: BLE001
            errs.append(repr(ex))

    chunks = [seeds[i::4] for i in range(4)]
    ths = [threading.Thread(target=run, args=(c,)) for c in chunks]
    for t in ths:
        t.start()
    for t in ths:
        t.join(30)
    assert not errs, errs[:3]
    conc = eng.insights.get(fp)
    assert conc["calls"] == seq["calls"] == len(seeds)
    assert conc["rows"] == seq["rows"] == rows_expected
    assert sum(conc["lat_buckets"]) == len(seeds)
    # the MATCH shape aggregated separately (no cross-shape bleed)
    mfp = eng.insights.fingerprints.get(
        "MATCH (a:P) WHERE a.P.x == 0 RETURN a", "ins")
    assert mfp and mfp != fp
    assert eng.insights.get(mfp)["calls"] == len(seeds)


# -- plan history + regression sentinel -------------------------------------


def test_regression_sentinel_synthetic(clean):
    """Registry-level: a plan flip whose new p50 degrades past the
    ratio flags the row and fires plan_regressed once; a flip to a
    FASTER plan never flags."""
    get_config().set_dynamic("plan_regression_min_calls", 4)
    reg = StatementRegistry()
    fp = "deadbeef0000"

    def rec(plan, us, n):
        for _ in range(n):
            reg.record(fp=fp, text="GO ...", kind="Go", space="g",
                       latency_us=us, plan_hash=plan)

    before = sum(stats().labeled.get("plan_regressed", {}).values())
    rec("planA", 400, 6)             # old plan: p50 bucket 500
    rec("planB", 40_000, 6)          # new plan: p50 bucket 50000
    row = reg.get(fp)
    assert row["plan_changed"] == 1
    assert row["prev_plan"] == "planA" and row["active_plan"] == "planB"
    assert row["regressed"] is True
    after = sum(stats().labeled.get("plan_regressed", {}).values())
    assert after == before + 1, "sentinel fires once per transition"

    # a faster new plan is a win, not a regression
    reg2 = StatementRegistry()
    for plan, us in (("planA", 40_000), ("planB", 400)):
        for _ in range(6):
            reg2.record(fp=fp, text="GO ...", kind="Go", space="g",
                        latency_us=us, plan_hash=plan)
    assert reg2.get(fp)["regressed"] is False


def test_regression_sentinel_on_forced_engine_plan_flip(clean):
    """The acceptance shape: force a real plan flip (optimizer toggle +
    plan-cache clear) and slow the new plan down — the registry keeps
    both plans side by side and flags the regression."""
    get_config().set_dynamic("plan_regression_min_calls", 3)
    eng, s = small_engine()
    # the WHERE matters: filter pushdown is what the optimizer changes
    # about this shape, so toggling it off really flips the kind tree
    q = "GO 2 STEPS FROM 1 OVER E WHERE E.w > 0 YIELD dst(edge) AS d"
    for _ in range(3):
        assert eng.execute(s, q).error is None
    fp = eng.insights.fingerprints.get(q, "ins")
    row = eng.insights.get(fp)
    old_plan = row["active_plan"]
    assert old_plan and row["plan_changed"] == 0

    eng.enable_optimizer = False
    eng.plan_cache.clear()
    fail.arm_callable(
        "exec:node",
        lambda i, key: ("delay", 0.05) if key == "ExpandAll" else None)
    try:
        for _ in range(3):
            assert eng.execute(s, q).error is None
    finally:
        fail.reset()
    row = eng.insights.get(fp)
    assert row["plan_changed"] == 1
    assert row["prev_plan"] == old_plan
    assert row["active_plan"] != old_plan
    assert set(row["plans"]) == {old_plan, row["active_plan"]}
    assert row["regressed"] is True


# -- the fingerprint join: SHOW QUERIES / flight / slow log -----------------


def test_kill_query_fingerprint_joins_flight_and_registry(clean):
    """Kill an in-flight query and follow ONE fingerprint from its
    live SHOW QUERIES row to the flight-recorder post-mortem to the
    registry's kill triage."""
    eng, s = small_engine()
    fail.arm_callable(
        "exec:node",
        lambda i, key: ("delay", 0.1) if key == "ExpandAll" else None)
    box = {}
    q = "GO 3 STEPS FROM 2 OVER E YIELD dst(edge) AS d"
    t = threading.Thread(
        target=lambda: box.update(rs=eng.execute(s, q)), daemon=True)
    t.start()
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[3].startswith("GO 3 STEPS")), None),
        msg="victim in SHOW QUERIES")
    # row: [..., consistency, batch, fingerprint]
    live_fp = row[14]
    assert live_fp, "live row must carry the fingerprint"
    s2 = eng.new_session()
    rs = eng.execute(s2, f"KILL QUERY (session={s.id}, plan={row[1]})")
    assert rs.error is None, rs.error
    t.join(10)
    fail.reset()
    assert box["rs"].error == "ExecutionError: query was killed"
    ent = next(e for e in flight_recorder().list(limit=20)
               if e["stmt"].startswith("GO 3 STEPS"))
    assert ent["status"] == "killed"
    assert ent["fingerprint"] == live_fp
    reg_row = eng.insights.get(live_fp)
    assert reg_row is not None and reg_row["kills"] >= 1
    assert live_fp == eng.insights.fingerprints.get(q, "ins")


def test_slow_log_carries_fingerprint(clean):
    eng, s = small_engine()
    get_config().set_dynamic("slow_query_threshold_us", 1)
    q = "GO 2 STEPS FROM 5 OVER E YIELD dst(edge) AS d"
    assert eng.execute(s, q).error is None
    ent = next(e for e in eng.slow_log if e["stmt"] == q)
    assert ent["fingerprint"] == eng.insights.fingerprints.get(q, "ins")


def test_insights_disabled_reproduces_pre_plane_behavior(clean):
    """insights_enabled=false: statements run identically but nothing
    is fingerprinted and nothing is recorded."""
    eng, s = small_engine()
    eng.insights.clear()
    get_config().set_dynamic("insights_enabled", False)
    q = "GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d"
    rs = eng.execute(s, q)
    assert rs.error is None and len(rs.data.rows) > 0
    assert len(eng.insights) == 0
    assert eng.insights.fingerprints.get(q, "ins") is None
    rs = eng.execute(s, "SHOW STATEMENTS")
    assert rs.error is None and len(rs.data.rows) == 0
    get_config().dynamic_layer.pop("insights_enabled", None)
    assert eng.execute(s, q).error is None
    assert eng.insights.get(
        eng.insights.fingerprints.get(q, "ins"))["calls"] == 1


# -- partition heat ---------------------------------------------------------


def test_part_heat_table_scores_and_merge(clean):
    heat = PartHeatTable()
    for _ in range(10):
        heat.record_read("g", 0, rows=5, latency_us=100.0)
    for _ in range(3):
        heat.record_write("g", 1, rows=2, latency_us=500.0)
    snap = heat.snapshot()
    by_part = {r["part"]: r for r in snap}
    assert by_part[0]["reads"] == 10 and by_part[0]["read_rows"] == 50
    assert by_part[1]["writes"] == 3 and by_part[1]["write_rows"] == 6
    assert by_part[0]["read_qps"] > 0
    assert heat.heat_of("g", 0) > 0.0
    assert heat.heat_of("g", 99) == 0.0       # unknown part = cold
    # writes are double-weighted in the score
    w = PartHeatTable()
    r = PartHeatTable()
    for _ in range(10):
        w.record_write("g", 0)
        r.record_read("g", 0)
    w.snapshot(), r.snapshot()
    assert w.heat_of("g", 0) > r.heat_of("g", 0)
    merged = merge_heat_snapshots({"h1": snap, "h2": snap})
    m0 = next(m for m in merged if m["part"] == 0)
    assert m0["reads"] == 20 and m0["hosts"] == ["h1", "h2"]


# -- cluster surfaces -------------------------------------------------------


def test_cluster_statements_and_hotspots(clean, tmp_path):
    """Two graphds, one storaged: SHOW STATEMENTS merges both
    registries exactly (calls sum, no double counting), SHOW LOCAL
    STATEMENTS answers per graphd, and SHOW HOTSPOTS ranks the parts
    whose heat rode the storaged heartbeat."""
    from nebula_tpu.cluster.client import GraphClient
    from nebula_tpu.cluster.launcher import LocalCluster

    c = LocalCluster(n_meta=1, n_storage=1, n_graph=2,
                     data_dir=str(tmp_path))
    try:
        cl1 = c.client()
        assert cl1.execute("CREATE SPACE cw(partition_num=2, "
                           "vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ("USE cw", "CREATE TAG P(x int)",
                  "CREATE EDGE E(w int)"):
            assert cl1.execute(q).error is None, q
        verts = ", ".join(f"{v}:({v})" for v in range(20))
        assert cl1.execute(
            f"INSERT VERTEX P(x) VALUES {verts}").error is None
        edges = ", ".join(f"{v}->{(v + 1) % 20}:({v})"
                          for v in range(20))
        assert cl1.execute(
            f"INSERT EDGE E(w) VALUES {edges}").error is None

        host2, port2 = c.graph_servers[1].addr.rsplit(":", 1)
        cl2 = GraphClient(host2, int(port2))
        cl2.authenticate("root", "nebula")
        assert cl2.execute("USE cw").error is None

        def stmt(v):
            return f"GO 2 STEPS FROM {v} OVER E YIELD dst(edge) AS d"

        for v in range(4):
            assert cl1.execute(stmt(v)).error is None
        for v in range(3):
            assert cl2.execute(stmt(v)).error is None
        fp = fingerprint_of(parse(stmt(0)), "cw")

        rs = cl1.execute("SHOW STATEMENTS")
        assert rs.error is None, rs.error
        assert rs.data.column_names == [
            "Fingerprint", "Sample", "Calls", "Errors", "P50 Us",
            "P95 Us", "Rows", "DeviceShare", "PlanHash", "PlanChanged",
            "Regressed"]
        row = next(r for r in rs.data.rows if r[0] == fp)
        assert row[2] == 7, "cluster view must sum, never double count"

        rs = cl2.execute("SHOW LOCAL STATEMENTS")
        assert rs.error is None, rs.error
        row = next(r for r in rs.data.rows if r[0] == fp)
        assert row[2] == 3

        # heat rides the 0.2s heartbeat; counters are cumulative, so
        # wait for a beat that POSTDATES the reads above (the first
        # rows metad serves may still be from an inserts-era snapshot)
        def hotspots():
            rs = cl1.execute("SHOW HOTSPOTS")
            assert rs.error is None, rs.error
            rows = rs.data.rows
            if rows and sum(r[5] for r in rows) > 0:
                return rows
            return None

        rows = _wait_for(hotspots, timeout=5.0,
                         msg="read heat to ride a heartbeat to metad")
        assert all(r[0] == "cw" for r in rows)
        assert {r[1] for r in rows} <= {0, 1}
        assert sum(r[6] for r in rows) > 0, "writes recorded"
        for r in rows:
            assert r[11], "leader annotated from the part map"
            assert r[12], "replica set annotated"
    finally:
        c.stop()
