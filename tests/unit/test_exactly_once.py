"""Exactly-once storage writes (ISSUE 5 tentpole).

Every storage.write carries a (writer_id, seq) idempotency token; the
part keeps a raft-replicated dedup window of applied tokens.  A re-sent
request — the client walked replicas after a lost reply — returns its
recorded outcome instead of double-applying, which is what flips the
old mid-call abort (`... not retried (non-idempotent)`) into a safe
retry.
"""
import threading
import time
from collections import OrderedDict

import pytest

from nebula_tpu.cluster.launcher import LocalCluster
from nebula_tpu.cluster.rpc import reset_breakers
from nebula_tpu.cluster.storage_client import StorageClient, StorageError
from nebula_tpu.core.wire import to_wire
from nebula_tpu.graphstore.store import DEDUP_WINDOW, GraphStore
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import stats


@pytest.fixture()
def clean_faults():
    fail.reset()
    reset_breakers()
    stats().reset()
    yield
    fail.reset()
    reset_breakers()


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    client = c.client()

    def run(q, expect_ok=True):
        rs = client.execute(q)
        if expect_ok:
            assert rs.error is None, f"{q} -> {rs.error}"
        return rs

    run("CREATE SPACE eo(partition_num=4, replica_factor=2, "
        "vid_type=INT64)")
    c.reconcile_storage()
    run("USE eo")
    run("CREATE TAG Person(name string, age int)")
    run("CREATE EDGE KNOWS(w int)")
    run('INSERT VERTEX Person(name, age) VALUES 1:("ann",30), 2:("bob",25)')
    c.run = run
    yield c
    c.stop()


# -- store-level dedup window ----------------------------------------------


def test_dedup_window_record_seen_and_eviction():
    st = GraphStore()
    st.create_space("s", partition_num=1, vid_type="INT64")
    assert st.dedup_seen("s", 0, "w", 1) is None
    st.dedup_record("s", 0, "w", 1, {"n": 2, "err": None})
    assert st.dedup_seen("s", 0, "w", 1) == {"n": 2, "err": None}
    # overflow evicts in insertion order, deterministically
    for i in range(2, DEDUP_WINDOW + 2):
        st.dedup_record("s", 0, "w", i, {"n": 1, "err": None})
    assert st.dedup_seen("s", 0, "w", 1) is None          # evicted
    assert st.dedup_seen("s", 0, "w", DEDUP_WINDOW + 1) is not None


def test_dedup_window_rides_part_state_snapshot():
    st = GraphStore()
    st.create_space("s", partition_num=1, vid_type="INT64")
    st.dedup_record("s", 0, "w", 7, {"n": 3, "err": "boom"})
    payload = st.export_part_state("s", 0)
    st2 = GraphStore()
    st2.create_space("s", partition_num=1, vid_type="INT64")
    st2.install_part_state("s", 0, payload)
    assert st2.dedup_seen("s", 0, "w", 7) == {"n": 3, "err": "boom"}
    # window ORDER survives the roundtrip (eviction order is state)
    sd = st2.space("s")
    assert isinstance(sd.parts[0].applied_writes, OrderedDict)


# -- dbatch apply gate ------------------------------------------------------


def test_duplicate_dbatch_apply_skips(cluster, clean_faults):
    """The replicated apply gate: a second dbatch with the same
    (writer, seq) must NOT re-apply — proven by giving the duplicate a
    DIFFERENT payload and observing the original's effect survive."""
    sc = StorageClient(cluster.meta_clients[0])
    pid = sc.part_of("eo", 1)
    # apply on the storaged LEADING the part: leadership is election-
    # random, and the FETCH below reads through the leader — a side-
    # applied write on a lagged follower would be invisible to it
    sid = cluster.storageds[0].meta.catalog.get_space("eo").space_id
    ss = next(s for s in cluster.storageds
              if (sid, pid) in s.parts and s.parts[(sid, pid)].is_leader())
    ss._apply_dbatch("eo", pid, "wdup", 1,
                     [["upd_vertex", 1, "Person", {"age": 77}]])
    before = stats().snapshot().get("storage_write_dedup_apply_skips", 0)
    ss._apply_dbatch("eo", pid, "wdup", 1,
                     [["upd_vertex", 1, "Person", {"age": 78}]])
    after = stats().snapshot().get("storage_write_dedup_apply_skips", 0)
    assert after == before + 1
    assert ss.store.dedup_seen("eo", pid, "wdup", 1) == \
        {"n": 1, "err": None}
    rs = cluster.run("FETCH PROP ON Person 1 YIELD Person.age AS a")
    assert rs.data.rows == [[77]], "duplicate dbatch re-applied!"


def test_dbatch_records_error_outcome(cluster, clean_faults):
    ss = cluster.storageds[0]
    with pytest.raises(ValueError):
        ss._apply_dbatch("eo", 0, "werr", 1, [["no_such_op"]])
    rec = ss.store.dedup_seen("eo", 0, "werr", 1)
    assert rec is not None and "no_such_op" in rec["err"]


def test_duplicate_dbatch_reraises_recorded_error(cluster, clean_faults):
    """A duplicate of a FAILED dbatch must fail identically — a silent
    skip would ack the retry of a write whose original apply failed."""
    ss = cluster.storageds[0]
    with pytest.raises(ValueError, match="no_such_op"):
        ss._apply_dbatch("eo", 0, "werr2", 1, [["no_such_op"]])
    before = stats().snapshot().get("storage_write_dedup_apply_skips", 0)
    with pytest.raises(ValueError, match="no_such_op"):
        ss._apply_dbatch("eo", 0, "werr2", 1, [["no_such_op"]])
    after = stats().snapshot().get("storage_write_dedup_apply_skips", 0)
    assert after == before + 1      # skipped, not re-applied — but failed


# -- end-to-end: lost reply → replica-walk retry → dedup hit ---------------


def _arm_reply_loss_once(key="storage.write|ok"):
    """Kill the reply of the next SUCCESSFUL storage.write — the
    handler ran, the write committed, the ack is lost (killing an error
    reply would inject a different, weaker fault)."""
    state = {"fired": False}

    def decide(idx, k):
        if state["fired"] or k != key:
            return None
        state["fired"] = True
        return ("raise", "reply dropped")

    fail.arm_callable("rpc:server_reply", decide)
    return state


def test_acked_write_exactly_once_after_lost_reply(cluster, clean_faults):
    """The headline flip: the server applies a write, the reply is lost
    (connection killed post-dispatch), the client re-sends the SAME
    token — the statement still acks, the write lands exactly once."""
    state = _arm_reply_loss_once()
    rs = cluster.run('INSERT VERTEX Person(name, age) VALUES 50:("eve",8)')
    assert rs.error is None
    assert state["fired"], "failpoint never fired — test proved nothing"
    snap = stats().snapshot()
    dedup = snap.get("storage_write_dedup_hits", 0) + \
        snap.get("storage_write_dedup_apply_skips", 0)
    assert dedup >= 1, f"re-send was not deduplicated: {snap}"
    rs = cluster.run("FETCH PROP ON Person 50 YIELD Person.name AS n, "
                     "Person.age AS a")
    assert rs.data.rows == [["eve", 8]]


def test_update_not_lost_after_reply_loss(cluster, clean_faults):
    """Same flip for UPDATE: the acked new value survives the re-send
    (without dedup the duplicate would be invisible here — this guards
    the ack itself: the statement must succeed, not abort mid-call)."""
    cluster.run('INSERT VERTEX Person(name, age) VALUES 60:("fay",1)')
    _arm_reply_loss_once()
    rs = cluster.run("UPDATE VERTEX ON Person 60 SET age = age + 1")
    assert rs.error is None
    rs = cluster.run("FETCH PROP ON Person 60 YIELD Person.age AS a")
    assert rs.data.rows == [[2]]


def test_untokened_write_still_aborts_mid_call(cluster, clean_faults):
    """The at-least-once gate is unchanged for writes WITHOUT a dedup
    token (raw storage.write callers): a mid-call death must surface,
    not silently re-send."""
    sc = StorageClient(cluster.meta_clients[0])
    _arm_reply_loss_once()
    cmd = ["vertex", 70, "Person", 0, {"name": "gus", "age": 3}]
    with pytest.raises(StorageError, match="not retried"):
        sc._call_part("eo", sc.part_of("eo", 70), "storage.write",
                      {"cmds": [to_wire(cmd)],
                       "cat_ver": cluster.meta_clients[0].version})


def test_tokened_retry_survives_leader_restart_window(cluster,
                                                      clean_faults):
    """Reply loss + a racing second statement: both ack, both land,
    ordering preserved (the dedup window keys on (writer, seq) so the
    sibling write is untouched)."""
    state = _arm_reply_loss_once()
    done = {}

    def other():
        done["rs"] = cluster.run(
            'INSERT VERTEX Person(name, age) VALUES 81:("ian",4)')

    t = threading.Thread(target=other)
    t.start()
    rs = cluster.run('INSERT VERTEX Person(name, age) VALUES 80:("hal",2)')
    t.join()
    assert rs.error is None and done["rs"].error is None
    assert state["fired"]
    rows = cluster.run("FETCH PROP ON Person 80, 81 YIELD Person.name "
                       "AS n").data.rows
    assert sorted(r[0] for r in rows) == ["hal", "ian"]
