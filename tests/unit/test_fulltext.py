"""Full-text plane tests: analyzer/edit-distance primitives, listener
async maintenance on writes, all four text ops, rebuild, drop/resurrect
guard, durability, and cluster-mode text LOOKUP (SURVEY §2 row 10
Listener; reference: ES-backed LOOKUP [UNVERIFIED — empty mount])."""
import pytest

from nebula_tpu.exec import QueryEngine
from nebula_tpu.graphstore.fulltext import (FulltextIndexData, analyze,
                                            levenshtein_leq)


# ---- primitives -----------------------------------------------------------

def test_analyze():
    assert analyze("Boris Diaw-2010") == ["boris", "diaw", "2010"]
    assert analyze("") == []


def test_levenshtein_band():
    assert levenshtein_leq("kitten", "sitten", 1)
    assert not levenshtein_leq("kitten", "sitting", 2)
    assert levenshtein_leq("kitten", "sitting", 3)
    assert not levenshtein_leq("abc", "xyz", 2)
    assert levenshtein_leq("", "ab", 2)


def test_index_data_ops():
    ft = FulltextIndexData("f", "t", "name", False, 2, 1)
    ft.add(0, "Boris Diaw", 1)
    ft.add(1, "Bob", 2)
    ft.add(0, "Alice", 3)
    assert ft.search("PREFIX", "bo") == [1, 2]       # part order
    assert ft.search("WILDCARD", "*li*") == [3]
    assert ft.search("REGEXP", "^B.*w$") == [1]
    assert ft.search("FUZZY", "Alise") == [3]
    ft.remove(0, 1)
    assert ft.search("PREFIX", "bo") == [2]
    assert ft.count() == 2
    with pytest.raises(ValueError):
        ft.search("REGEXP", "(unclosed")


# ---- engine surface -------------------------------------------------------

@pytest.fixture()
def eng():
    e = QueryEngine()
    s = e.new_session()

    def run(q):
        r = e.execute(s, q)
        assert r.ok, f"{q} -> {r.error}"
        if "REBUILD" in q.upper():
            from nebula_tpu.exec.jobs import job_manager
            assert job_manager(e.qctx.store).wait()   # jobs are async (r4)
        return r

    run('CREATE SPACE fts(partition_num=4, vid_type=INT64)')
    run('USE fts')
    run('CREATE TAG player(name string, age int64)')
    run('CREATE EDGE follows(note string)')
    run('ADD LISTENER ELASTICSEARCH "127.0.0.1:9200"')
    run('CREATE FULLTEXT TAG INDEX ft_name ON player(name)')
    run('CREATE FULLTEXT EDGE INDEX ft_note ON follows(note)')
    run('INSERT VERTEX player(name, age) VALUES '
        '1:("Boris Diaw", 33), 2:("Bob Marley", 40), '
        '3:("Alice", 20), 4:("boxer", 25)')
    run('INSERT EDGE follows(note) VALUES '
        '1->2:("great singer"), 2->3:("old friend"), 3->4:("gym buddy")')
    e._run = run
    return e


def rows(eng, q):
    return eng._run(q).data.rows


def names(eng, q):
    return sorted(r[0] for r in rows(eng, q))


def test_show_fulltext_indexes_and_listener(eng):
    assert rows(eng, 'SHOW FULLTEXT INDEXES') == [
        ['ft_name', 'Tag', 'player', 'name'],
        ['ft_note', 'Edge', 'follows', 'note']]
    ls = rows(eng, 'SHOW LISTENER')
    assert ls[0][1] == 'ELASTICSEARCH' and ls[0][3] == 'ONLINE'


def test_prefix_wildcard_regexp_fuzzy(eng):
    assert names(eng, 'LOOKUP ON player WHERE PREFIX(player.name, "Bo") '
                      'YIELD player.name AS n') \
        == ['Bob Marley', 'Boris Diaw', 'boxer']
    assert names(eng, 'LOOKUP ON player WHERE WILDCARD(player.name, "*li*")'
                      ' YIELD player.name AS n') == ['Alice']
    assert names(eng, 'LOOKUP ON player WHERE REGEXP(player.name, '
                      '"^[AB].*e$") YIELD player.name AS n') == ['Alice']
    assert names(eng, 'LOOKUP ON player WHERE FUZZY(player.name, "Alise") '
                      'YIELD player.name AS n') == ['Alice']


def test_residual_filter_and_default_yield(eng):
    assert names(eng, 'LOOKUP ON player WHERE PREFIX(player.name, "Bo") '
                      'AND player.age > 35 YIELD player.name AS n') \
        == ['Bob Marley']
    # default yield: vertex ids
    assert sorted(r[0] for r in rows(
        eng, 'LOOKUP ON player WHERE PREFIX(player.name, "Bo")')) \
        == [1, 2, 4]


def test_edge_fulltext_with_props(eng):
    got = rows(eng, 'LOOKUP ON follows WHERE PREFIX(follows.note, "g") '
                    'YIELD src(edge) AS s, follows.note AS n')
    assert sorted(map(tuple, got)) == [(1, 'great singer'),
                                       (3, 'gym buddy')]


def test_listener_tracks_dml(eng):
    eng._run('DELETE VERTEX 2')
    assert names(eng, 'LOOKUP ON player WHERE PREFIX(player.name, "Bo") '
                      'YIELD player.name AS n') == ['Boris Diaw', 'boxer']
    eng._run('UPDATE VERTEX ON player 4 SET name = "Bobby"')
    assert names(eng, 'LOOKUP ON player WHERE PREFIX(player.name, "Bo") '
                      'YIELD player.name AS n') == ['Bobby', 'Boris Diaw']
    eng._run('INSERT VERTEX player(name, age) VALUES 9:("Border", 1)')
    assert names(eng, 'LOOKUP ON player WHERE PREFIX(player.name, "Bo") '
                      'YIELD player.name AS n') \
        == ['Bobby', 'Border', 'Boris Diaw']


def test_rebuild_and_drop_guard(eng):
    assert rows(eng, 'REBUILD FULLTEXT INDEX')[0][0] >= 0
    assert names(eng, 'LOOKUP ON player WHERE PREFIX(player.name, "b") '
                      'YIELD player.name AS n') \
        == ['Bob Marley', 'Boris Diaw', 'boxer']
    eng._run('DROP FULLTEXT INDEX ft_note')
    s = eng.new_session()
    eng.execute(s, 'USE fts')
    bad = eng.execute(s, 'LOOKUP ON follows WHERE '
                         'PREFIX(follows.note, "g") YIELD follows.note')
    assert bad.error is not None and 'fulltext' in bad.error
    # re-create with same name: must start EMPTY until rebuild
    eng._run('CREATE FULLTEXT EDGE INDEX ft_note ON follows(note)')
    assert rows(eng, 'LOOKUP ON follows WHERE PREFIX(follows.note, "g") '
                     'YIELD follows.note AS n') == []
    eng._run('REBUILD FULLTEXT INDEX ft_note')
    assert len(rows(eng, 'LOOKUP ON follows WHERE '
                         'PREFIX(follows.note, "g") '
                         'YIELD follows.note AS n')) == 2


def test_requires_string_prop(eng):
    bad = None
    s2 = eng.new_session()
    eng.execute(s2, 'USE fts')
    bad = eng.execute(s2, 'CREATE FULLTEXT TAG INDEX ft_age ON player(age)')
    assert bad.error is not None and 'string' in bad.error


def test_no_index_is_clean_error(eng):
    s2 = eng.new_session()
    eng.execute(s2, 'USE fts')
    bad = eng.execute(s2, 'LOOKUP ON player WHERE '
                          'PREFIX(player.age, "3") YIELD id(vertex)')
    assert bad.error is not None


def test_durable_recovery(tmp_path):
    """DDL + data replay through the journal; text search works after
    recovery (catalog mutators journaled via CATALOG_MUTATORS)."""
    from nebula_tpu.graphstore.store import GraphStore
    st = GraphStore(data_dir=str(tmp_path))
    e = QueryEngine(st)
    s = e.new_session()
    for q in ['CREATE SPACE d(partition_num=2, vid_type=INT64)', 'USE d',
              'CREATE TAG t(name string)',
              'CREATE FULLTEXT TAG INDEX ft ON t(name)',
              'INSERT VERTEX t(name) VALUES 1:("hello world"), 2:("help")']:
        r = e.execute(s, q)
        assert r.ok, f"{q} -> {r.error}"
    st.close()

    st2 = GraphStore(data_dir=str(tmp_path))
    e2 = QueryEngine(st2)
    s2 = e2.new_session()
    e2.execute(s2, 'USE d')
    r = e2.execute(s2, 'LOOKUP ON t WHERE PREFIX(t.name, "hel") '
                       'YIELD t.name AS n')
    assert r.ok, r.error
    assert sorted(x[0] for x in r.data.rows) == ['hello world', 'help']
    st2.close()


def test_cluster_fulltext():
    """Text LOOKUP in cluster mode: DDL via metad raft, per-part search
    fan-out over storaged, listener on each replica."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    try:
        sess = c.client()
        r = sess.execute('CREATE SPACE cf(partition_num=4, '
                         'replica_factor=1, vid_type=INT64)')
        assert r.error is None, r.error
        c.reconcile_storage()
        for q in ['USE cf',
                  'CREATE TAG song(title string)',
                  'CREATE FULLTEXT TAG INDEX ft_title ON song(title)',
                  'INSERT VERTEX song(title) VALUES 1:("Hey Jude"), '
                  '2:("Hey Ya"), 3:("Let It Be"), 4:("Yesterday")']:
            r = sess.execute(q)
            assert r.error is None, f"{q} -> {r.error}"
        r = sess.execute('LOOKUP ON song WHERE PREFIX(song.title, "Hey") '
                         'YIELD song.title AS t')
        assert r.error is None, r.error
        assert sorted(x[0] for x in r.data.rows) == ['Hey Jude', 'Hey Ya']
        r = sess.execute('LOOKUP ON song WHERE FUZZY(song.title, "Yesterdy")'
                         ' YIELD song.title AS t')
        assert r.error is None, r.error
        assert [x[0] for x in r.data.rows] == ['Yesterday']
        # DML keeps replica sinks fresh
        r = sess.execute('DELETE VERTEX 2')
        assert r.error is None, r.error
        r = sess.execute('LOOKUP ON song WHERE PREFIX(song.title, "Hey") '
                         'YIELD song.title AS t')
        assert [x[0] for x in r.data.rows] == ['Hey Jude']
    finally:
        c.stop()


def test_second_text_conjunct_evaluates_as_residual(eng):
    """Only one text predicate plans into the scan; others must still
    evaluate (host text functions), not crash."""
    got = names(eng, 'LOOKUP ON player WHERE PREFIX(player.name, "Bo") '
                     'AND WILDCARD(player.name, "*diaw*") '
                     'YIELD player.name AS n')
    assert got == ['Boris Diaw']


def test_concurrent_search_and_writes(eng):
    """Listener thread mutates while query threads scan — no
    'dictionary changed size during iteration'."""
    import threading
    errs = []

    def writer():
        for i in range(200):
            eng._run(f'INSERT VERTEX player(name, age) '
                     f'VALUES {100 + i}:("Bolt {i}", {i % 80 + 10})')

    def reader():
        try:
            for _ in range(60):
                rows(eng, 'LOOKUP ON player WHERE '
                          'PREFIX(player.name, "Bo") YIELD player.name')
                rows(eng, 'LOOKUP ON player WHERE '
                          'FUZZY(player.name, "Bolt") YIELD player.name')
        except Exception as ex:  # noqa: BLE001
            errs.append(ex)

    ts = [threading.Thread(target=writer)] + \
        [threading.Thread(target=reader) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


def test_drop_releases_corpus(eng):
    """DROP FULLTEXT INDEX must evict the store-side corpus (not strand
    it until a same-name re-CREATE)."""
    st = eng.qctx.store
    sd = st.space('fts')
    assert 'ft_note' in sd.ft_data
    eng._run('DROP FULLTEXT INDEX ft_note')
    # next write-path touch GCs the dropped incarnation
    eng._run('INSERT EDGE follows(note) VALUES 7->8:("x")')
    assert 'ft_note' not in sd.ft_data
    assert st.ft_listener.target('fts', 'ft_note') is None


def test_unindexed_text_conjunct_order_independent(eng):
    """An indexed text conjunct plans the scan regardless of conjunct
    order; the unindexed one evaluates residually."""
    eng._run('CREATE TAG multi(name string, nick string)')
    eng._run('CREATE FULLTEXT TAG INDEX ft_mname ON multi(name)')
    eng._run('INSERT VERTEX multi(name, nick) VALUES '
             '20:("anna", "ann"), 21:("arnold", "arny"), 22:("bo", "b")')
    for q in ['LOOKUP ON multi WHERE PREFIX(multi.nick, "a") AND '
              'PREFIX(multi.name, "a") YIELD multi.name AS n',
              'LOOKUP ON multi WHERE PREFIX(multi.name, "a") AND '
              'PREFIX(multi.nick, "a") YIELD multi.name AS n']:
        assert names(eng, q) == ['anna', 'arnold'], q


def test_bad_regexp_errors_in_both_placements(eng):
    s2 = eng.new_session()
    eng.execute(s2, 'USE fts')
    for q in ['LOOKUP ON player WHERE REGEXP(player.name, "(") '
              'YIELD player.name',
              'LOOKUP ON player WHERE PREFIX(player.name, "B") AND '
              'REGEXP(player.name, "(") YIELD player.name']:
        bad = eng.execute(s2, q)
        assert bad.error is not None and 'REGEXP' in bad.error, q
