"""Golden-plan tests: plan shapes + optimizer rules.

Mirrors the reference's validator/optimizer test pattern of asserting on
node-kind sequences (SURVEY §4).
"""
import pytest

from nebula_tpu.exec import QueryEngine
from nebula_tpu.query.optimizer import optimize
from nebula_tpu.query.parser import parse
from nebula_tpu.query.plan import ExecutionPlan
from nebula_tpu.query.planner import PlannerContext, _plan


@pytest.fixture()
def eng():
    e = QueryEngine()
    s = e.new_session()
    for q in ['CREATE SPACE t (partition_num=2)', 'USE t',
              'CREATE TAG person(name string, age int64)',
              'CREATE EDGE knows(since int64)',
              'CREATE TAG INDEX i_age ON person(age)']:
        r = e.execute(s, q)
        assert r.ok, r.error
    e._sess = s
    return e


def plan_of(eng, text, opt=True):
    pctx = PlannerContext(eng.qctx, "t")
    root = _plan(pctx, parse(text))
    p = ExecutionPlan(root, pctx.space)
    return optimize(p, enable=opt)


def test_go_plan_shape(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d', opt=False)
    assert p.root.kind_tree() == ["Project", "ExpandAll", "Start"]


def test_go_two_step_plan(eng):
    p = plan_of(eng, 'GO 2 STEPS FROM "a" OVER knows', opt=False)
    assert p.root.kind_tree() == [
        "Project", "ExpandAll", "Dedup", "Project", "ExpandAll", "Start"]


def test_go_m_to_n_union(eng):
    p = plan_of(eng, 'GO 1 TO 2 STEPS FROM "a" OVER knows', opt=False)
    kinds = p.root.kind_tree()
    assert kinds[0] == "Union"
    assert kinds.count("ExpandAll") == 3  # shared frontier chain + 2 branches... (1st reused)


def test_filter_pushdown_into_expand(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows WHERE knows.since > 5 YIELD dst(edge)')
    kinds = p.root.kind_tree()
    assert "Filter" not in kinds          # fully absorbed
    exp = p.root
    while exp.kind != "ExpandAll":
        exp = exp.dep()
    assert exp.args["edge_filter"] is not None


def test_filter_partial_pushdown(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows '
                     'WHERE knows.since > 5 AND $$.person.age > 10 YIELD dst(edge)')
    kinds = p.root.kind_tree()
    assert "Filter" in kinds              # dst-prop conjunct stays
    exp = p.root
    while exp.kind != "ExpandAll":
        exp = exp.dep()
    assert "since" in str(exp.args["edge_filter"])
    f = p.root
    while f.kind != "Filter":
        f = f.dep()
    assert "age" in str(f.args["condition"])


def test_topn_fusion(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d '
                     '| ORDER BY $-.d | LIMIT 3')
    assert p.root.kind == "TopN"
    assert p.root.args["count"] == 3


def test_match_plan_shape(eng):
    p = plan_of(eng, 'MATCH (v:person)-[e:knows]->(b) RETURN b', opt=False)
    kinds = p.root.kind_tree()
    assert kinds == ["Project", "AppendVertices", "Traverse", "Filter",
                     "ScanVertices"]


def test_match_edge_filter_pushdown(eng):
    p = plan_of(eng, 'MATCH (v:person)-[e:knows]->(b) WHERE e.since > 3 RETURN b')
    # the e.since conjunct must reach the Traverse node
    tv = None
    for k in p.root.kind_tree():
        pass
    node = p.root
    stack = [node]
    while stack:
        n = stack.pop()
        if n.kind == "Traverse":
            tv = n
        stack.extend(n.deps)
    assert tv is not None and tv.args.get("edge_filter") is not None


def test_match_seed_by_id(eng):
    p = plan_of(eng, 'MATCH (a)-[e:knows]->(b) WHERE id(a) == "x" RETURN b',
                opt=False)
    kinds = p.root.kind_tree()
    assert "GetVertices" in kinds and "ScanVertices" not in kinds


def test_lookup_plan(eng):
    p = plan_of(eng, 'LOOKUP ON person WHERE person.age > 1 YIELD id(vertex)',
                opt=False)
    assert p.root.kind_tree() == ["Project", "IndexScan"]


def test_explain_output_contains_args(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows WHERE knows.since > 5')
    desc = p.describe()
    assert "ExpandAll" in desc and "knows" in desc


# ---- round-2 optimizer rule family (golden shapes) ------------------------


def test_merge_adjacent_filters(eng):
    # MATCH ... WHERE lands one Filter; wrap another via $var? Simplest:
    # construct directly — Filter(Filter(x)) collapses to one node.
    from nebula_tpu.core.expr import Binary, InputProp, Literal
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start")
    f1 = PlanNode("Filter", deps=[base], col_names=["a"],
                  args={"condition": Binary(">", InputProp("a"), Literal(1))})
    f2 = PlanNode("Filter", deps=[f1], col_names=["a"],
                  args={"condition": Binary("<", InputProp("a"), Literal(9))})
    p = optimize(ExecutionPlan(f2, "t"))
    assert p.root.kind_tree() == ["Filter", "Start"]
    from nebula_tpu.core.expr import to_text
    assert "AND" in to_text(p.root.args["condition"])


def test_eliminate_true_filter(eng):
    from nebula_tpu.core.expr import Literal
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start")
    f = PlanNode("Filter", deps=[base], col_names=[],
                 args={"condition": Literal(True)})
    p = optimize(ExecutionPlan(f, "t"))
    assert p.root.kind_tree() == ["Start"]


def test_merge_adjacent_limits(eng):
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["x"])
    l1 = PlanNode("Limit", deps=[base], col_names=["x"],
                  args={"offset": 2, "count": 10})
    l2 = PlanNode("Limit", deps=[l1], col_names=["x"],
                  args={"offset": 3, "count": 4})
    p = optimize(ExecutionPlan(l2, "t"))
    assert p.root.kind_tree() == ["Limit", "Start"]
    assert p.root.args["offset"] == 5
    assert p.root.args["count"] == 4


def test_limit_semantics_after_merge(eng):
    """rows[2:12][3:7] == rows[5:9] — the merged bound is equivalent."""
    rows = list(range(20))
    assert rows[2:12][3:7] == rows[5:9]


def test_push_filter_through_dedup(eng):
    from nebula_tpu.core.expr import Binary, InputProp, Literal
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["a"])
    dd = PlanNode("Dedup", deps=[base], col_names=["a"])
    f = PlanNode("Filter", deps=[dd], col_names=["a"],
                 args={"condition": Binary(">", InputProp("a"), Literal(1))})
    p = optimize(ExecutionPlan(f, "t"))
    assert p.root.kind_tree() == ["Dedup", "Filter", "Start"]


def test_push_limit_down_project(eng):
    from nebula_tpu.core.expr import InputProp
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["a"])
    pj = PlanNode("Project", deps=[base], col_names=["b"],
                  args={"columns": [(InputProp("a"), "b")]})
    lm = PlanNode("Limit", deps=[pj], col_names=["b"],
                  args={"offset": 0, "count": 5})
    p = optimize(ExecutionPlan(lm, "t"))
    assert p.root.kind_tree() == ["Project", "Limit", "Start"]


def test_push_limit_down_index_scan(eng):
    p = plan_of(eng, "LOOKUP ON person WHERE person.age > 3 "
                     "YIELD person.name")
    from nebula_tpu.query.plan import PlanNode
    assert "IndexScan" in p.root.kind_tree()
    root = PlanNode("Limit", deps=[p.root], col_names=p.root.col_names,
                    args={"offset": 0, "count": 4})
    p2 = optimize(ExecutionPlan(root, "t"))
    # the bound landed on the IndexScan through the Project
    node = p2.root
    while node.kind != "IndexScan":
        node = node.dep()
    assert node.args.get("limit") == 4


def test_push_filter_into_join_sides(eng):
    from nebula_tpu.core.expr import Binary, InputProp, Literal, join_conjuncts
    from nebula_tpu.query.plan import PlanNode
    l = PlanNode("Start", col_names=["a"])
    r = PlanNode("Start", col_names=["b"])
    jn = PlanNode("HashInnerJoin", deps=[l, r], col_names=["a", "b"],
                  args={"hash_keys": [], "probe_keys": []})
    cond = join_conjuncts([
        Binary(">", InputProp("a"), Literal(1)),
        Binary("<", InputProp("b"), Literal(9)),
    ])
    f = PlanNode("Filter", deps=[jn], col_names=["a", "b"],
                 args={"condition": cond})
    p = optimize(ExecutionPlan(f, "t"))
    kinds = p.root.kind_tree()
    assert kinds == ["HashInnerJoin", "Filter", "Start", "Filter", "Start"]


def test_eliminate_noop_project(eng):
    from nebula_tpu.core.expr import InputProp
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["a", "b"])
    pj = PlanNode("Project", deps=[base], col_names=["a", "b"],
                  args={"columns": [(InputProp("a"), "a"),
                                    (InputProp("b"), "b")]})
    p = optimize(ExecutionPlan(pj, "t"))
    assert p.root.kind_tree() == ["Start"]


def test_push_limit_down_scan_plants_bound(eng):
    from nebula_tpu.query.plan import PlanNode
    sc = PlanNode("ScanVertices", col_names=["v"],
                  args={"space": "t", "tag": None})
    lm = PlanNode("Limit", deps=[sc], col_names=["v"],
                  args={"offset": 1, "count": 3})
    p = optimize(ExecutionPlan(lm, "t"))
    assert p.root.kind == "Limit"
    assert p.root.dep().args.get("limit") == 4


def test_push_filter_down_append_vertices(eng):
    p = plan_of(eng, "MATCH (a:person)-[e:knows]->(b) "
                     "WHERE b.person.age > 3 RETURN b")
    # the b-only conjunct must land on the AppendVertices node
    node = p.root
    found = None
    from nebula_tpu.query.plan import walk_plan
    for n in walk_plan(p.root):
        if n.kind == "AppendVertices" and n.args.get("filter") is not None:
            found = n
    assert found is not None


def test_eliminate_false_filter(eng):
    q = ('GO FROM "a" OVER knows YIELD dst(edge) AS d '
         '| YIELD $-.d AS d WHERE false')
    p = plan_of(eng, q)
    kinds = p.root.kind_tree()
    assert "Filter" not in kinds
    from nebula_tpu.query.plan import walk_plan
    assert any(n.args.get("empty") for n in walk_plan(p.root)
               if n.kind == "Project")
    # and it actually runs to an empty (not errored) result
    r = eng.execute(eng._sess, q)
    assert r.ok and r.data.rows == [] and r.data.column_names == ["d"]


def test_push_limit_down_fulltext_scan(eng):
    r = eng.execute(eng._sess,
                    'CREATE FULLTEXT TAG INDEX ft_n ON person(name)')
    assert r.ok, r.error
    p = plan_of(eng, 'LOOKUP ON person WHERE PREFIX(person.name, "a") '
                     'YIELD person.name | LIMIT 2')
    scan = p.root
    while scan.kind != "FulltextIndexScan":
        scan = scan.dep()
    assert scan.args.get("limit") == 2


def test_adjacent_sorts_merge_exactly(eng):
    """Sort is stable, so an inner ORDER BY is observable through ties
    of the outer keys — merge_consecutive_sorts must keep it observable
    by folding the inner keys in as SECONDARY factors of one Sort
    (ordering by (outer, inner) == stable outer pass over inner-sorted
    rows), never by dropping the inner sort."""
    q = ('GO FROM "a" OVER knows YIELD dst(edge) AS d '
         '| ORDER BY $-.d DESC | ORDER BY $-.d ASC')
    p = plan_of(eng, q)
    assert p.root.kind_tree().count("Sort") == 1
    # row parity with the optimizer off, ties included
    from nebula_tpu.exec import QueryEngine
    seed = eng.qctx.store
    s2 = eng._sess
    eng.execute(s2, 'INSERT VERTEX person(name, age) VALUES '
                '"a":("a", 1), "b":("b", 2), "c":("c", 3), "d":("d", 4)')
    eng.execute(s2, 'INSERT EDGE knows(since) VALUES "a"->"b":(7), '
                '"a"->"c":(7), "a"->"d":(5)')
    q2 = ('GO FROM "a" OVER knows YIELD dst(edge) AS d, '
          'knows.since AS s | ORDER BY $-.s DESC | ORDER BY $-.s ASC')
    plain = QueryEngine(seed, enable_optimizer=False)
    sp = plain.new_session()
    plain.execute(sp, "USE t")
    want = plain.execute(sp, q2)
    assert want.error is None, want.error
    got = eng.execute(s2, q2)
    assert got.error is None, got.error
    assert got.data.rows == want.data.rows    # IN ORDER, ties intact


def test_eliminate_limit_zero(eng):
    q = 'GO FROM "a" OVER knows YIELD dst(edge) AS d | LIMIT 0'
    p = plan_of(eng, q)
    from nebula_tpu.query.plan import walk_plan
    assert any(n.args.get("empty") for n in walk_plan(p.root)
               if n.kind == "Project")
    r = eng.execute(eng._sess, q)
    assert r.ok and r.data.rows == []


def test_eliminate_noop_limit(eng):
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["x"])
    lm = PlanNode("Limit", deps=[base], col_names=["x"],
                  args={"offset": 0, "count": -1})
    p = optimize(ExecutionPlan(lm, "t"))
    assert p.root.kind_tree() == ["Start"]


# ---- round-4 rules: memo/exploration + new pushdowns ----------------------


def _pctx(eng):
    return PlannerContext(eng.qctx, "t")


def test_index_seed_for_match_scan(eng):
    """Filter(ScanVertices) with an indexable tag-prop predicate is
    replaced by Filter(IndexScan) via the cost-model exploration."""
    pctx = _pctx(eng)
    root = _plan(pctx, parse(
        "MATCH (a:person) WHERE a.person.age > 21 RETURN id(a)"))
    p = optimize(ExecutionPlan(root, "t"), pctx=pctx)
    kinds = p.root.kind_tree()
    assert "IndexScan" in kinds and "ScanVertices" not in kinds
    from nebula_tpu.query.plan import walk_plan
    scan = next(n for n in walk_plan(p.root) if n.kind == "IndexScan")
    assert scan.args["index"] == "i_age"
    assert scan.args["range"] is not None


def test_index_seed_prefers_equality(eng):
    s = eng._sess
    assert eng.execute(s, "CREATE TAG INDEX i_name ON person(name)").ok
    pctx = _pctx(eng)
    root = _plan(pctx, parse(
        'MATCH (a:person) WHERE a.person.name == "x" AND '
        'a.person.age > 21 RETURN id(a)'))
    p = optimize(ExecutionPlan(root, "t"), pctx=pctx)
    from nebula_tpu.query.plan import walk_plan
    scan = next(n for n in walk_plan(p.root) if n.kind == "IndexScan")
    assert scan.args["index"] == "i_name"       # eq beats range in cost
    r = eng.execute(s, "DROP TAG INDEX i_name")
    assert r.ok


def test_scan_without_predicate_not_rewritten(eng):
    pctx = _pctx(eng)
    root = _plan(pctx, parse("MATCH (a:person) RETURN id(a)"))
    p = optimize(ExecutionPlan(root, "t"), pctx=pctx)
    assert "ScanVertices" in p.root.kind_tree()


def test_push_filter_into_index_scan(eng):
    pctx = _pctx(eng)
    root = _plan(pctx, parse(
        'LOOKUP ON person WHERE person.age > 21 AND '
        'person.name == "q" YIELD id(vertex) AS v'))
    p = optimize(ExecutionPlan(root, "t"), pctx=pctx)
    from nebula_tpu.query.plan import walk_plan
    kinds = p.root.kind_tree()
    assert "Filter" not in kinds
    scan = next(n for n in walk_plan(p.root) if n.kind == "IndexScan")
    assert scan.args.get("filter") is not None


def test_push_filter_down_set_op(eng):
    from nebula_tpu.core.expr import Binary, InputProp, Literal
    from nebula_tpu.query.plan import PlanNode
    l = PlanNode("Start", col_names=["v"])
    r = PlanNode("Start", col_names=["v"])
    u = PlanNode("Union", deps=[l, r], col_names=["v"],
                 args={"distinct": True})
    f = PlanNode("Filter", deps=[u], col_names=["v"],
                 args={"condition": Binary(">", InputProp("v"),
                                           Literal(2))})
    p = optimize(ExecutionPlan(f, "t"))
    assert p.root.kind == "Union"
    assert all(d.kind == "Filter" for d in p.root.deps)


def test_push_limit_into_union_all(eng):
    from nebula_tpu.query.plan import PlanNode
    l = PlanNode("Start", col_names=["v"])
    r = PlanNode("Start", col_names=["v"])
    u = PlanNode("Union", deps=[l, r], col_names=["v"],
                 args={"distinct": False})
    lm = PlanNode("Limit", deps=[u], col_names=["v"],
                  args={"offset": 1, "count": 3})
    p = optimize(ExecutionPlan(lm, "t"))
    assert p.root.kind == "Limit"
    assert p.root.dep().kind == "Union"
    assert all(d.kind == "Limit" and d.args["count"] == 4
               for d in p.root.dep().deps)


def test_push_topn_down_project(eng):
    from nebula_tpu.core.expr import InputProp
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["a", "b"])
    proj = PlanNode("Project", deps=[base], col_names=["x", "y"],
                    args={"columns": [(InputProp("a"), "x"),
                                      (InputProp("b"), "y")]})
    topn = PlanNode("TopN", deps=[proj], col_names=["x", "y"],
                    args={"factors": [("x", True)], "count": 5,
                          "offset": 0})
    p = optimize(ExecutionPlan(topn, "t"))
    assert p.root.kind == "Project"
    assert p.root.dep().kind == "TopN"
    assert p.root.dep().args["factors"] == [("a", True)]


def test_push_dedup_through_project(eng):
    from nebula_tpu.core.expr import InputProp
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["a", "b"])
    proj = PlanNode("Project", deps=[base], col_names=["x", "y"],
                    args={"columns": [(InputProp("b"), "x"),
                                      (InputProp("a"), "y")]})
    dd = PlanNode("Dedup", deps=[proj], col_names=["x", "y"], args={})
    p = optimize(ExecutionPlan(dd, "t"))
    assert p.root.kind == "Project"
    assert p.root.dep().kind == "Dedup"


def test_const_fold_filter(eng):
    from nebula_tpu.core.expr import Binary, Literal
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["v"])
    f = PlanNode("Filter", deps=[base], col_names=["v"],
                 args={"condition": Binary(">", Literal(1), Literal(2))})
    p = optimize(ExecutionPlan(f, "t"))
    # 1 > 2 folds to false; the false-filter eliminator empties the plan
    from nebula_tpu.query.plan import walk_plan
    assert all(n.kind != "Filter" for n in walk_plan(p.root))


def test_eliminate_dedup_after_unique_scan(eng):
    from nebula_tpu.query.plan import PlanNode
    scan = PlanNode("ScanVertices", deps=[], col_names=["a"],
                    args={"space": "t", "tag": "person", "as_col": "a"})
    dd = PlanNode("Dedup", deps=[scan], col_names=["a"], args={})
    p = optimize(ExecutionPlan(dd, "t"))
    assert p.root.kind == "ScanVertices"


def test_eliminate_empty_set_op_branch(eng):
    from nebula_tpu.query.plan import PlanNode
    live = PlanNode("Start", col_names=["v"])
    empty = PlanNode("Project", deps=[], col_names=["v"],
                     args={"empty": True})
    u = PlanNode("Union", deps=[empty, live], col_names=["v"],
                 args={"distinct": True})
    p = optimize(ExecutionPlan(u, "t"))
    assert p.root.kind == "Dedup" and p.root.dep().kind == "Start"
    i = PlanNode("Intersect", deps=[live, empty], col_names=["v"], args={})
    p = optimize(ExecutionPlan(i, "t"))
    assert p.root.args.get("empty")
    m = PlanNode("Minus", deps=[live, empty], col_names=["v"], args={})
    p = optimize(ExecutionPlan(m, "t"))
    assert p.root.kind == "Dedup"


def test_fold_constant_project_columns(eng):
    from nebula_tpu.core.expr import Binary, Literal
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=[])
    proj = PlanNode("Project", deps=[base], col_names=["x"],
                    args={"columns": [(Binary("+", Literal(2),
                                              Literal(3)), "x")]})
    p = optimize(ExecutionPlan(proj, "t"))
    e = p.root.args["columns"][0][0]
    assert e.kind == "literal" and e.value == 5


def test_push_filter_down_sort(eng):
    from nebula_tpu.core.expr import Binary, InputProp, Literal
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["v"])
    srt = PlanNode("Sort", deps=[base], col_names=["v"],
                   args={"factors": [("v", True)]})
    f = PlanNode("Filter", deps=[srt], col_names=["v"],
                 args={"condition": Binary(">", InputProp("v"),
                                           Literal(0))})
    p = optimize(ExecutionPlan(f, "t"))
    assert p.root.kind == "Sort" and p.root.dep().kind == "Filter"


def test_merge_limit_into_topn(eng):
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["v"])
    tn = PlanNode("TopN", deps=[base], col_names=["v"],
                  args={"factors": [("v", True)], "offset": 1,
                        "count": 10})
    lm = PlanNode("Limit", deps=[tn], col_names=["v"],
                  args={"offset": 2, "count": 4})
    p = optimize(ExecutionPlan(lm, "t"))
    assert p.root.kind == "TopN"
    assert p.root.args["offset"] == 3 and p.root.args["count"] == 4


def test_eliminate_dedup_after_aggregate(eng):
    from nebula_tpu.core.expr import InputProp
    from nebula_tpu.core.expr import AggExpr
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["v"])
    agg = PlanNode("Aggregate", deps=[base], col_names=["v", "c"],
                   args={"group_keys": [InputProp("v")],
                         "columns": [(InputProp("v"), "v"),
                                     (AggExpr("count", None), "c")]})
    dd = PlanNode("Dedup", deps=[agg], col_names=["v", "c"], args={})
    p = optimize(ExecutionPlan(dd, "t"))
    assert p.root.kind == "Aggregate"


def test_push_filter_down_left_join(eng):
    from nebula_tpu.core.expr import Binary, InputProp, Literal, to_text
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["a", "k"])
    l = PlanNode("Filter", deps=[base], col_names=["a", "k"],
                 args={"condition": Binary(">", InputProp("k"),
                                           Literal(0))})
    lvar = l.output_var
    r = PlanNode("Start", col_names=["k", "b"])
    jn = PlanNode("HashLeftJoin", deps=[l, r],
                  col_names=["a", "k", "b"], args={})
    cond = Binary("AND",
                  Binary(">", InputProp("a"), Literal(1)),
                  Binary(">", InputProp("b"), Literal(2)))
    f = PlanNode("Filter", deps=[jn], col_names=["a", "k", "b"],
                 args={"condition": cond})
    p = optimize(ExecutionPlan(f, "t"))
    # left-only conjunct merged into the EXISTING left Filter (same
    # node, same output_var — Argument.from_var linkage must survive);
    # right-side conjunct stays above
    assert p.root.kind == "Filter"
    assert "b" in to_text(p.root.args["condition"])
    jn2 = p.root.dep()
    assert jn2.kind == "HashLeftJoin"
    lf = jn2.dep(0)
    assert lf.kind == "Filter" and lf.output_var == lvar
    assert "($-.a > 1)" in to_text(lf.args["condition"])
    assert jn2.dep(1).kind == "Start"


def test_merge_project_into_aggregate(eng):
    from nebula_tpu.core.expr import AggExpr, InputProp
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["v"])
    agg = PlanNode("Aggregate", deps=[base], col_names=["v", "c"],
                   args={"group_keys": [InputProp("v")],
                         "columns": [(InputProp("v"), "v"),
                                     (AggExpr("count", None), "c")]})
    proj = PlanNode("Project", deps=[agg], col_names=["n"],
                    args={"columns": [(InputProp("c"), "n")]})
    p = optimize(ExecutionPlan(proj, "t"))
    assert p.root.kind == "Aggregate"
    assert p.root.col_names == ["n"]
    (e0, n0), = p.root.args["columns"]
    assert isinstance(e0, AggExpr) and n0 == "n"


def test_push_topn_into_union_all(eng):
    from nebula_tpu.query.plan import PlanNode
    l = PlanNode("Start", col_names=["v"])
    r = PlanNode("Start", col_names=["v"])
    u = PlanNode("Union", deps=[l, r], col_names=["v"],
                 args={"distinct": False})
    tn = PlanNode("TopN", deps=[u], col_names=["v"],
                  args={"factors": [("v", True)], "offset": 1,
                        "count": 3})
    p = optimize(ExecutionPlan(tn, "t"))
    assert p.root.kind == "TopN"
    assert p.root.dep().kind == "Union"
    assert all(d.kind == "TopN" and d.args["count"] == 4
               and d.args["offset"] == 0
               for d in p.root.dep().deps)


def test_push_filter_through_unwind(eng):
    from nebula_tpu.core.expr import Binary, InputProp, Literal, LabelExpr
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["row"])
    uw = PlanNode("Unwind", deps=[base], col_names=["row", "x"],
                  args={"alias": "x", "expr": InputProp("row")})
    cond = Binary("AND",
                  Binary(">", InputProp("row"), Literal(0)),
                  Binary(">", LabelExpr("x"), Literal(5)))
    f = PlanNode("Filter", deps=[uw], col_names=["row", "x"],
                 args={"condition": cond})
    p = optimize(ExecutionPlan(f, "t"))
    # row-level conjunct moved below the Unwind; alias conjunct stays
    assert p.root.kind == "Filter"
    uw2 = p.root.dep()
    assert uw2.kind == "Unwind"
    assert uw2.dep().kind == "Filter"


def test_planted_topn_not_replanted_through_project(eng):
    """push_topn_down_project rewrites a planted branch TopN into
    Project(TopN); the union-planting guard must see THROUGH that or it
    re-plants every fixpoint round (code-review r4)."""
    from nebula_tpu.core.expr import InputProp
    from nebula_tpu.query.plan import PlanNode, walk_plan
    mk = lambda: PlanNode(
        "Project",
        deps=[PlanNode("Start", col_names=["a"])],
        col_names=["v"], args={"columns": [(InputProp("a"), "v")]})
    u = PlanNode("Union", deps=[mk(), mk()], col_names=["v"],
                 args={"distinct": False})
    tn = PlanNode("TopN", deps=[u], col_names=["v"],
                  args={"factors": [("v", True)], "count": 2, "offset": 0})
    p = optimize(ExecutionPlan(tn, "t"))
    kinds = [n.kind for n in walk_plan(p.root)]
    # exactly one planted TopN per branch + the outer cut — no stacking
    assert kinds.count("TopN") == 3, kinds


def test_push_filter_through_aggregate(eng):
    """Group-key predicates move below the Aggregate (substituted back
    to the key expr); aggregate-output predicates stay above."""
    from nebula_tpu.core.expr import (AggExpr, Binary, InputProp, Literal)
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["k", "v"])
    agg = PlanNode("Aggregate", deps=[base], col_names=["k", "n"],
                   args={"group_keys": [InputProp("k")],
                         "columns": [(InputProp("k"), "k"),
                                     (AggExpr("count", InputProp("v")),
                                      "n")]})
    cond = Binary("AND",
                  Binary(">", InputProp("k"), Literal(3)),
                  Binary(">", InputProp("n"), Literal(1)))
    f = PlanNode("Filter", deps=[agg], col_names=["k", "n"],
                 args={"condition": cond})
    p = optimize(ExecutionPlan(f, "t"))
    # key conjunct below the Aggregate, count conjunct above
    assert p.root.kind == "Filter"
    agg2 = p.root.dep()
    assert agg2.kind == "Aggregate"
    assert agg2.dep().kind == "Filter"
    from nebula_tpu.core.expr import to_text
    assert "k" in to_text(agg2.dep().args["condition"])


def test_merge_consecutive_sorts(eng):
    """ORDER BY piped into ORDER BY = one stable sort on (outer, inner)
    keys."""
    rs = eng.execute(eng._sess, "EXPLAIN YIELD 3 AS a, 1 AS b "
                     "| ORDER BY $-.b | ORDER BY $-.a")
    desc = rs.data.rows[0][0]
    assert desc.count("Sort") == 1, desc
    assert "$-.a" in desc and "$-.b" in desc   # composite factors


def test_eliminate_dedup_under_dupfree_aggregate(eng):
    from nebula_tpu.core.expr import AggExpr, InputProp
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["k", "v"])
    for func, distinct, gone in (("min", False, True),
                                 ("collect_set", False, True),
                                 ("count", True, True),
                                 ("count", False, False),
                                 ("sum", False, False)):
        dd = PlanNode("Dedup", deps=[base], col_names=["k", "v"], args={})
        agg = PlanNode("Aggregate", deps=[dd], col_names=["k", "m"],
                       args={"group_keys": [InputProp("k")],
                             "columns": [(InputProp("k"), "k"),
                                         (AggExpr(func, InputProp("v"),
                                                  distinct), "m")]})
        p = optimize(ExecutionPlan(agg, "t"))
        kinds = p.root.kind_tree()
        if gone:
            assert "Dedup" not in kinds, (func, distinct, kinds)
        else:
            assert "Dedup" in kinds, (func, distinct, kinds)


def test_filter_through_aggregate_keeps_pushing(eng):
    """A partially-pushed group-key filter must keep commuting in later
    fixpoint passes (here: through the Dedup under the Aggregate) —
    the rule returns the mutated node so `changed` is recorded."""
    from nebula_tpu.core.expr import AggExpr, Binary, InputProp, Literal
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["k", "v"])
    dd = PlanNode("Dedup", deps=[base], col_names=["k", "v"], args={})
    agg = PlanNode("Aggregate", deps=[dd], col_names=["k", "n"],
                   args={"group_keys": [InputProp("k")],
                         "columns": [(InputProp("k"), "k"),
                                     (AggExpr("count", InputProp("v")),
                                      "n")]})
    cond = Binary("AND",
                  Binary(">", InputProp("k"), Literal(3)),
                  Binary(">", InputProp("n"), Literal(1)))
    f = PlanNode("Filter", deps=[agg], col_names=["k", "n"],
                 args={"condition": cond})
    p = optimize(ExecutionPlan(f, "t"))
    assert p.root.kind_tree() == \
        ["Filter", "Aggregate", "Dedup", "Filter", "Start"]


def test_filter_through_aggregate_skips_untraversable_exprs(eng):
    """A key-column reference nested inside an expr kind rewrite()
    cannot traverse (here: a slice) must NOT be pushed — the verbatim
    push would bind the name to a different input column (code-review
    r4: wrong-results repro)."""
    from nebula_tpu.exec import QueryEngine
    st = eng.qctx.store
    s = eng._sess
    eng.execute(s, 'INSERT VERTEX person(name, age) VALUES '
                '"a":("a", 1), "b":("b", 2)')
    eng.execute(s, 'INSERT EDGE knows(since) VALUES "a"->"b":(5), '
                '"b"->"a":(7)')
    q = ('GO FROM "a", "b" OVER knows YIELD knows.since AS s, [1,2] AS k '
         '| GROUP BY $-.s YIELD $-.s AS k, count(*) AS n '
         '| YIELD $-.k AS k WHERE size($-.k[0..1]) >= 1')
    plain = QueryEngine(st, enable_optimizer=False)
    sp = plain.new_session()
    plain.execute(sp, "USE t")
    want = plain.execute(sp, q)
    got = eng.execute(s, q)
    assert want.error is None and got.error is None, \
        (want.error, got.error)
    assert sorted(map(repr, got.data.rows)) == \
        sorted(map(repr, want.data.rows))


def test_eliminate_topn_zero(eng):
    from nebula_tpu.core.expr import InputProp
    from nebula_tpu.query.plan import PlanNode
    base = PlanNode("Start", col_names=["x"])
    tn = PlanNode("TopN", deps=[base], col_names=["x"],
                  args={"factors": [(InputProp("x"), True)],
                        "offset": 0, "count": 0})
    p = optimize(ExecutionPlan(tn, "t"))
    assert any(n.args.get("empty") for n in [p.root])


def test_eliminate_dedup_after_distinct_union(eng):
    from nebula_tpu.query.plan import PlanNode
    a = PlanNode("Start", col_names=["x"])
    b = PlanNode("Start", col_names=["x"])
    u = PlanNode("Union", deps=[a, b], col_names=["x"],
                 args={"distinct": True})
    dd = PlanNode("Dedup", deps=[u], col_names=["x"])
    p = optimize(ExecutionPlan(dd, "t"))
    assert p.root.kind == "Union"
    # UNION ALL keeps the Dedup (duplicates are possible)
    u2 = PlanNode("Union", deps=[PlanNode("Start", col_names=["x"]),
                                 PlanNode("Start", col_names=["x"])],
                  col_names=["x"], args={"distinct": False})
    dd2 = PlanNode("Dedup", deps=[u2], col_names=["x"])
    p2 = optimize(ExecutionPlan(dd2, "t"))
    assert p2.root.kind == "Dedup"
