"""Golden-plan tests: plan shapes + optimizer rules.

Mirrors the reference's validator/optimizer test pattern of asserting on
node-kind sequences (SURVEY §4).
"""
import pytest

from nebula_tpu.exec import QueryEngine
from nebula_tpu.query.optimizer import optimize
from nebula_tpu.query.parser import parse
from nebula_tpu.query.plan import ExecutionPlan
from nebula_tpu.query.planner import PlannerContext, _plan


@pytest.fixture()
def eng():
    e = QueryEngine()
    s = e.new_session()
    for q in ['CREATE SPACE t (partition_num=2)', 'USE t',
              'CREATE TAG person(name string, age int64)',
              'CREATE EDGE knows(since int64)',
              'CREATE TAG INDEX i_age ON person(age)']:
        r = e.execute(s, q)
        assert r.ok, r.error
    e._sess = s
    return e


def plan_of(eng, text, opt=True):
    pctx = PlannerContext(eng.qctx, "t")
    root = _plan(pctx, parse(text))
    p = ExecutionPlan(root, pctx.space)
    return optimize(p, enable=opt)


def test_go_plan_shape(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d', opt=False)
    assert p.root.kind_tree() == ["Project", "ExpandAll", "Start"]


def test_go_two_step_plan(eng):
    p = plan_of(eng, 'GO 2 STEPS FROM "a" OVER knows', opt=False)
    assert p.root.kind_tree() == [
        "Project", "ExpandAll", "Dedup", "Project", "ExpandAll", "Start"]


def test_go_m_to_n_union(eng):
    p = plan_of(eng, 'GO 1 TO 2 STEPS FROM "a" OVER knows', opt=False)
    kinds = p.root.kind_tree()
    assert kinds[0] == "Union"
    assert kinds.count("ExpandAll") == 3  # shared frontier chain + 2 branches... (1st reused)


def test_filter_pushdown_into_expand(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows WHERE knows.since > 5 YIELD dst(edge)')
    kinds = p.root.kind_tree()
    assert "Filter" not in kinds          # fully absorbed
    exp = p.root
    while exp.kind != "ExpandAll":
        exp = exp.dep()
    assert exp.args["edge_filter"] is not None


def test_filter_partial_pushdown(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows '
                     'WHERE knows.since > 5 AND $$.person.age > 10 YIELD dst(edge)')
    kinds = p.root.kind_tree()
    assert "Filter" in kinds              # dst-prop conjunct stays
    exp = p.root
    while exp.kind != "ExpandAll":
        exp = exp.dep()
    assert "since" in str(exp.args["edge_filter"])
    f = p.root
    while f.kind != "Filter":
        f = f.dep()
    assert "age" in str(f.args["condition"])


def test_topn_fusion(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d '
                     '| ORDER BY $-.d | LIMIT 3')
    assert p.root.kind == "TopN"
    assert p.root.args["count"] == 3


def test_match_plan_shape(eng):
    p = plan_of(eng, 'MATCH (v:person)-[e:knows]->(b) RETURN b', opt=False)
    kinds = p.root.kind_tree()
    assert kinds == ["Project", "AppendVertices", "Traverse", "Filter",
                     "ScanVertices"]


def test_match_edge_filter_pushdown(eng):
    p = plan_of(eng, 'MATCH (v:person)-[e:knows]->(b) WHERE e.since > 3 RETURN b')
    # the e.since conjunct must reach the Traverse node
    tv = None
    for k in p.root.kind_tree():
        pass
    node = p.root
    stack = [node]
    while stack:
        n = stack.pop()
        if n.kind == "Traverse":
            tv = n
        stack.extend(n.deps)
    assert tv is not None and tv.args.get("edge_filter") is not None


def test_match_seed_by_id(eng):
    p = plan_of(eng, 'MATCH (a)-[e:knows]->(b) WHERE id(a) == "x" RETURN b',
                opt=False)
    kinds = p.root.kind_tree()
    assert "GetVertices" in kinds and "ScanVertices" not in kinds


def test_lookup_plan(eng):
    p = plan_of(eng, 'LOOKUP ON person WHERE person.age > 1 YIELD id(vertex)',
                opt=False)
    assert p.root.kind_tree() == ["Project", "IndexScan"]


def test_explain_output_contains_args(eng):
    p = plan_of(eng, 'GO FROM "a" OVER knows WHERE knows.since > 5')
    desc = p.describe()
    assert "ExpandAll" in desc and "knows" in desc
