"""Standalone persistent engine (SURVEY §2 row 10; VERDICT r1 missing
#8): journal + checkpoint + compaction — a restarted store recovers
everything, not just what was explicitly snapshotted."""
import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.store import GraphStore


def _populate(store):
    eng = QueryEngine(store)
    s = eng.new_session()
    for q in [
        "CREATE SPACE d(partition_num=4, vid_type=INT64)",
        "USE d",
        "CREATE TAG person(name string, age int)",
        "CREATE EDGE knows(since int)",
        "CREATE TAG INDEX by_age ON person(age)",
        'INSERT VERTEX person(name, age) VALUES 1:("ann", 30), 2:("bob", 25), 3:("cat", 40)',
        "INSERT EDGE knows(since) VALUES 1->2:(2010), 2->3:(2015)",
        "REBUILD TAG INDEX by_age",
        "UPDATE VERTEX ON person 2 SET age = 26",
        "DELETE VERTEX 3 WITH EDGE",
        'CREATE USER u1 WITH PASSWORD "pw"',
    ]:
        rs = eng.execute(s, q)
        assert rs.error is None, (q, rs.error)
        if "REBUILD" in q:
            from nebula_tpu.exec.jobs import job_manager
            assert job_manager(store).wait()    # jobs are async (r4)
    return eng, s


def _verify(store, lookup_ids=(1,)):
    eng = QueryEngine(store)
    s = eng.new_session()
    eng.execute(s, "USE d")
    rs = eng.execute(s, "FETCH PROP ON person 1, 2 YIELD person.name AS n, "
                        "person.age AS a | ORDER BY $-.a")
    assert rs.error is None, rs.error
    assert rs.data.rows == [["ann", 30], ["bob", 26]] or \
        rs.data.rows == [["bob", 26], ["ann", 30]]
    rs = eng.execute(s, "FETCH PROP ON person 3 YIELD person.name")
    assert rs.data.rows == []
    rs = eng.execute(s, "GO FROM 1 OVER knows YIELD dst(edge) AS dd")
    assert [r[0] for r in rs.data.rows] == [2]
    rs = eng.execute(s, "LOOKUP ON person WHERE person.age > 27 "
                        "YIELD id(vertex) AS i")
    assert sorted(r[0] for r in rs.data.rows) == sorted(lookup_ids)
    rs = eng.execute(s, "SHOW USERS")
    assert sorted(r[0] for r in rs.data.rows) == ["root", "u1"]


def test_recovery_from_journal(tmp_path):
    store = GraphStore(data_dir=str(tmp_path / "db"))
    _populate(store)
    store.close()
    # reopen: everything recovered from journal alone (no compaction ran)
    store2 = GraphStore(data_dir=str(tmp_path / "db"))
    _verify(store2)
    store2.close()


def test_recovery_after_compaction(tmp_path):
    store = GraphStore(data_dir=str(tmp_path / "db"))
    eng, s = _populate(store)
    rs = eng.execute(s, "SUBMIT JOB COMPACT")
    assert rs.error is None
    from nebula_tpu.exec.jobs import job_manager
    assert job_manager(store).wait()        # jobs are async (r4)
    # post-compaction writes land in the fresh journal
    rs = eng.execute(s, 'INSERT VERTEX person(name, age) VALUES 9:("zed", 50)')
    assert rs.error is None
    store.close()

    store2 = GraphStore(data_dir=str(tmp_path / "db"))
    _verify(store2, lookup_ids=(1, 9))
    eng2 = QueryEngine(store2)
    s2 = eng2.new_session()
    eng2.execute(s2, "USE d")
    rs = eng2.execute(s2, "FETCH PROP ON person 9 YIELD person.name AS n")
    assert rs.data.rows == [["zed"]]
    # journal was truncated: it holds only the post-checkpoint tail
    assert store2._engine.journal.first_index() > 1
    store2.close()


def test_double_restart_idempotent(tmp_path):
    """Journal replay is idempotent — two recoveries in a row (or a
    mutation racing a compaction) cannot double-apply."""
    store = GraphStore(data_dir=str(tmp_path / "db"))
    _populate(store)
    store.close()
    for _ in range(2):
        st = GraphStore(data_dir=str(tmp_path / "db"))
        _verify(st)
        st.close()


def test_drop_space_recovers(tmp_path):
    store = GraphStore(data_dir=str(tmp_path / "db"))
    eng = QueryEngine(store)
    s = eng.new_session()
    eng.execute(s, "CREATE SPACE keepme(partition_num=2, vid_type=INT64)")
    eng.execute(s, "CREATE SPACE dropme(partition_num=2, vid_type=INT64)")
    eng.execute(s, "DROP SPACE dropme")
    store.close()
    store2 = GraphStore(data_dir=str(tmp_path / "db"))
    eng2 = QueryEngine(store2)
    s2 = eng2.new_session()
    rs = eng2.execute(s2, "SHOW SPACES")
    assert [r[0] for r in rs.data.rows] == ["keepme"]
    store2.close()


def test_clear_space_recovers(tmp_path):
    """CLEAR SPACE survives a restart: replay must wipe the data again
    while the schema (journaled DDL) stays."""
    store = GraphStore(data_dir=str(tmp_path / "db"))
    eng = QueryEngine(store)
    s = eng.new_session()
    eng.execute(s, "CREATE SPACE cs(partition_num=2, vid_type=INT64)")
    eng.execute(s, "USE cs")
    eng.execute(s, "CREATE TAG t(x int)")
    eng.execute(s, "INSERT VERTEX t(x) VALUES 1:(1), 2:(2)")
    rs = eng.execute(s, "CLEAR SPACE cs")
    assert rs.error is None, rs.error
    store.close()
    store2 = GraphStore(data_dir=str(tmp_path / "db"))
    eng2 = QueryEngine(store2)
    s2 = eng2.new_session()
    eng2.execute(s2, "USE cs")
    rs = eng2.execute(s2, "DESCRIBE TAG t")
    assert rs.error is None and rs.data.rows
    rs = eng2.execute(s2, "FETCH PROP ON t 1, 2 YIELD t.x")
    assert rs.error is None and rs.data.rows == []
    store2.close()


def test_memory_store_unaffected():
    store = GraphStore()
    assert store._engine is None
    assert store.compact_journal() == 0


def test_compact_crash_before_truncation(tmp_path, monkeypatch):
    """A crash after the checkpoint swap but before journal truncation
    must not double-apply the stale journal prefix on recovery."""
    store = GraphStore(data_dir=str(tmp_path / "db"))
    _populate(store)
    # simulate the crash: compaction runs but truncation never happens
    from nebula_tpu.cluster.wal import Wal
    monkeypatch.setattr(Wal, "compact_to", lambda self, idx: None)
    store.compact_journal()
    monkeypatch.undo()
    store.close()
    store2 = GraphStore(data_dir=str(tmp_path / "db"))   # must not raise
    _verify(store2)
    store2.close()


def test_compact_crash_between_renames(tmp_path, monkeypatch):
    """A crash with only checkpoint.old on disk recovers from it."""
    import os
    store = GraphStore(data_dir=str(tmp_path / "db"))
    _populate(store)
    store.compact_journal()
    store.close()
    ck = str(tmp_path / "db" / "checkpoint")
    os.rename(ck, ck + ".old")      # simulate dying mid-swap
    store2 = GraphStore(data_dir=str(tmp_path / "db"))
    _verify(store2)
    store2.close()


def test_no_plaintext_passwords_in_journal(tmp_path):
    store = GraphStore(data_dir=str(tmp_path / "db"))
    eng = QueryEngine(store)
    s = eng.new_session()
    eng.execute(s, 'CREATE USER sec WITH PASSWORD "hunter2"')
    eng.execute(s, 'CHANGE PASSWORD sec FROM "hunter2" TO "hunter3"')
    store.close()
    raw = (tmp_path / "db" / "journal.wal").read_bytes()
    assert b"hunter2" not in raw and b"hunter3" not in raw
    # and the hashed form still authenticates after recovery
    store2 = GraphStore(data_dir=str(tmp_path / "db"))
    assert store2.catalog.get_user("sec").check_password("hunter3")
    store2.close()


def test_ddl_logged_during_compaction_race_recovers(tmp_path):
    """DDL that lands in BOTH the checkpoint and the journal tail (a
    compact() race) must not make the store unopenable."""
    store = GraphStore(data_dir=str(tmp_path / "db"))
    _populate(store)
    store.compact_journal()
    # simulate the race: a DDL entry that survives truncation (idx >
    # upto) but whose effect is ALREADY in the checkpoint — exactly what
    # a mutation logged while compact() serialized the catalog looks like
    store._engine.log(("catalog", "create_edge", ["d", "knows", []], {}))
    store.close()
    store2 = GraphStore(data_dir=str(tmp_path / "db"))   # must not raise
    _verify(store2)
    store2.close()
