"""Expression interpreter semantics."""
import pytest

from nebula_tpu.core import (NULL, NULL_BAD_TYPE, Binary, Case, DictContext,
                             Edge, FunctionCall, InputProp, LabelExpr,
                             ListComprehension, ListExpr, Literal, MapExpr,
                             PredicateExpr, Reduce, Slice, SrcProp, Subscript,
                             Tag, TypeCast, Unary, VarExpr, Vertex, is_null,
                             split_conjuncts, to_text)
from nebula_tpu.core.expr import AggExpr, AttributeExpr, EdgeProp


def ev(e, **kw):
    return e.eval(DictContext(**kw))


def L(v):
    return Literal(v)


def test_arithmetic_tree():
    e = Binary("+", Binary("*", L(2), L(3)), L(4))
    assert ev(e) == 10
    assert to_text(e) == "((2 * 3) + 4)"


def test_relational():
    assert ev(Binary("<", L(1), L(2))) is True
    assert ev(Binary("==", L("a"), L("a"))) is True
    assert is_null(ev(Binary(">", L(1), Literal(NULL))))


def test_in_contains():
    assert ev(Binary("IN", L(2), ListExpr([L(1), L(2)]))) is True
    assert ev(Binary("IN", L(5), ListExpr([L(1), Literal(NULL)]))) is NULL
    assert ev(Binary("NOT IN", L(5), ListExpr([L(1)]))) is True
    assert ev(Binary("CONTAINS", L("hello"), L("ell"))) is True
    assert ev(Binary("STARTS WITH", L("hello"), L("he"))) is True
    assert ev(Binary("ENDS WITH", L("hello"), L("lo"))) is True
    assert ev(Binary("=~", L("abc123"), L("[a-z]+\\d+"))) is True


def test_short_circuit():
    # rhs would raise (unknown function) but must not be evaluated
    bad = FunctionCall("no_such_fn", [])
    assert ev(Binary("AND", L(False), bad)) is False
    assert ev(Binary("OR", L(True), bad)) is True


def test_props():
    ctx = DictContext(input_props={"x": 7},
                      src_props={"person": {"age": 30}},
                      edge_props={"since": 2010})
    assert InputProp("x").eval(ctx) == 7
    assert SrcProp("person", "age").eval(ctx) == 30
    assert EdgeProp("knows", "since").eval(ctx) == 2010
    assert is_null(InputProp("missing").eval(ctx))


def test_edge_reserved_props():
    e = Edge("a", "b", "knows", 3)
    ctx = DictContext(edge=e)
    assert EdgeProp("knows", "_src").eval(ctx) == "a"
    assert EdgeProp("knows", "_dst").eval(ctx) == "b"
    assert EdgeProp("knows", "_rank").eval(ctx) == 3
    assert EdgeProp("knows", "_type").eval(ctx) == "knows"


def test_subscript_slice():
    lst = ListExpr([L(10), L(20), L(30)])
    assert ev(Subscript(lst, L(1))) == 20
    assert ev(Subscript(lst, L(-1))) == 30
    assert is_null(ev(Subscript(lst, L(9))))
    assert ev(Slice(lst, L(1), None)) == [20, 30]
    m = MapExpr([("a", L(1))])
    assert ev(Subscript(m, L("a"))) == 1


def test_attribute():
    v = Vertex("a", [Tag("person", {"name": "Ann"})])
    ctx = DictContext(variables={"v": v})
    assert AttributeExpr(LabelExpr("v"), "name").eval(ctx) == "Ann"
    assert ev(AttributeExpr(MapExpr([("k", L(5))]), "k")) == 5


def test_case():
    e = Case([(Binary(">", InputProp("x"), L(0)), L("pos"))], L("neg"))
    assert ev(e, input_props={"x": 3}) == "pos"
    assert ev(e, input_props={"x": -3}) == "neg"
    e2 = Case([(L(1), L("one")), (L(2), L("two"))], L("other"), condition=InputProp("x"))
    assert ev(e2, input_props={"x": 2}) == "two"


def test_list_comprehension():
    e = ListComprehension("x", ListExpr([L(1), L(2), L(3), L(4)]),
                          where=Binary(">", LabelExpr("x"), L(2)),
                          mapping=Binary("*", LabelExpr("x"), L(10)))
    assert ev(e) == [30, 40]


def test_predicate():
    lst = ListExpr([L(1), L(2), L(3)])
    assert ev(PredicateExpr("all", "x", lst, Binary(">", LabelExpr("x"), L(0)))) is True
    assert ev(PredicateExpr("any", "x", lst, Binary(">", LabelExpr("x"), L(2)))) is True
    assert ev(PredicateExpr("none", "x", lst, Binary(">", LabelExpr("x"), L(5)))) is True
    assert ev(PredicateExpr("single", "x", lst, Binary("==", LabelExpr("x"), L(2)))) is True


def test_reduce():
    e = Reduce("acc", L(0), "x", ListExpr([L(1), L(2), L(3)]),
               Binary("+", LabelExpr("acc"), LabelExpr("x")))
    assert ev(e) == 6


def test_functions():
    assert ev(FunctionCall("abs", [L(-5)])) == 5
    assert ev(FunctionCall("upper", [L("ab")])) == "AB"
    assert ev(FunctionCall("size", [ListExpr([L(1), L(2)])])) == 2
    assert ev(FunctionCall("substr", [L("hello"), L(1), L(3)])) == "ell"
    assert ev(FunctionCall("coalesce", [Literal(NULL), L(3)])) == 3
    assert ev(FunctionCall("reverse", [L("abc")])) == "cba"
    assert ev(FunctionCall("reverse",
                           [ListExpr([L(1), L(2), L(3)])])) == [3, 2, 1]
    import math as _m
    assert ev(FunctionCall("atan2", [L(1.0), L(2.0)])) == _m.atan2(1.0, 2.0)
    assert ev(FunctionCall("split", [L("a,b"), L(",")])) == ["a", "b"]
    assert ev(FunctionCall("round", [L(2.5)])) == 3.0
    assert ev(FunctionCall("round", [L(-2.5)])) == -3.0


def test_cast():
    assert ev(TypeCast("int", L("42"))) == 42
    assert ev(TypeCast("string", L(4.0))) == "4.0"
    assert ev(TypeCast("float", L(3))) == 3.0
    assert ev(TypeCast("bool", L("true"))) is True


def test_graph_functions():
    v = Vertex("a", [Tag("person", {"name": "Ann"})])
    e = Edge("a", "b", "knows", 0, {"w": 1})
    ctx = DictContext(variables={"v": v, "e": e})
    assert FunctionCall("id", [LabelExpr("v")]).eval(ctx) == "a"
    assert FunctionCall("tags", [LabelExpr("v")]).eval(ctx) == ["person"]
    assert FunctionCall("type", [LabelExpr("e")]).eval(ctx) == "knows"
    assert FunctionCall("src", [LabelExpr("e")]).eval(ctx) == "a"
    assert FunctionCall("properties", [LabelExpr("e")]).eval(ctx) == {"w": 1}


def test_aggregate_apply():
    a = AggExpr("sum", InputProp("x"))
    assert a.apply([1, 2, NULL, 3]) == 6
    assert AggExpr("count", None).apply([1, NULL]) == 2  # count(*)
    assert AggExpr("count", InputProp("x")).apply([1, NULL]) == 1
    assert AggExpr("avg", InputProp("x")).apply([1, 2, 3]) == 2.0
    assert AggExpr("max", InputProp("x")).apply(["a", "c", "b"]) == "c"
    assert AggExpr("collect", InputProp("x")).apply([1, NULL, 2]) == [1, 2]
    assert AggExpr("sum", InputProp("x"), distinct=True).apply([1, 1, 2]) == 3


def test_split_conjuncts():
    e = Binary("AND", Binary("AND", L(1), L(2)), L(3))
    assert len(split_conjuncts(e)) == 3


def test_unary_is_null():
    assert ev(Unary("IS_NULL", Literal(NULL))) is True
    assert ev(Unary("IS_NOT_NULL", L(1))) is True
    assert ev(Unary("NOT", L(False))) is True
    assert ev(Unary("-", L(5))) == -5
