"""BALANCE DATA / BALANCE LEADER move real parts and leadership
(SURVEY §2 row 17; VERDICT r1 item 10): raft membership change +
snapshot catch-up on expansion, re-replication after a host death,
leader spreading — queries stay correct throughout."""
import time

import pytest

from nebula_tpu.utils.config import get_config

def _wait_jobs(cluster):
    """Admin jobs are async (r4): settle every graphd's manager."""
    from nebula_tpu.exec.jobs import job_manager
    for g in cluster.graphds:
        mgr = getattr(g.engine.qctx.store, "_job_manager", None)
        if mgr is not None:
            assert mgr.wait()



def _setup_space(client, cluster, parts=4, rf=1):
    rs = client.execute(
        f"CREATE SPACE bal(partition_num={parts}, replica_factor={rf}, "
        f"vid_type=INT64)")
    assert rs.error is None, rs.error
    cluster.reconcile_storage()
    for q in ["USE bal",
              "CREATE TAG item(x int)",
              "CREATE EDGE rel(w int)"]:
        rs = client.execute(q)
        assert rs.error is None, (q, rs.error)
    vals = ", ".join(f"{i}:({i * 10})" for i in range(40))
    rs = client.execute(f"INSERT VERTEX item(x) VALUES {vals}")
    assert rs.error is None, rs.error
    edges = ", ".join(f"{i}->{(i + 1) % 40}:({i})" for i in range(40))
    rs = client.execute(f"INSERT EDGE rel(w) VALUES {edges}")
    assert rs.error is None, rs.error


def _check_data(client):
    rs = client.execute("USE bal")
    assert rs.error is None, rs.error
    rs = client.execute(
        "FETCH PROP ON item 7, 23, 39 YIELD item.x AS x | ORDER BY $-.x")
    assert rs.error is None, rs.error
    assert rs.data.rows == [[70], [230], [390]]
    rs = client.execute("GO 2 STEPS FROM 5 OVER rel YIELD dst(edge) AS d")
    assert rs.error is None and rs.data.rows == [[7]]


def test_balance_data_expands_to_new_host(tmp_path):
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        _setup_space(client, c, parts=4, rf=1)
        _check_data(client)
        a_addr = c.storage_servers[0].addr

        ss_b = c.add_storaged()
        b_addr = ss_b.my_addr
        rs = client.execute("SUBMIT JOB BALANCE DATA")
        assert rs.error is None, rs.error
        _wait_jobs(c)

        # the part map now spreads over both hosts, 2 + 2
        meta = c.graphds[0].meta
        meta.refresh(force=True)
        pm = meta.parts_of("bal")
        hosts = [reps[0] for reps in pm]
        assert hosts.count(a_addr) == 2 and hosts.count(b_addr) == 2, pm
        # every replica list is singleton again (add-then-remove finished)
        assert all(len(reps) == 1 for reps in pm), pm

        # host B genuinely serves its parts: it holds part state now
        moved = [pid for pid, reps in enumerate(pm) if reps[0] == b_addr]
        total_b = sum(
            len(ss_b.store.space("bal").parts[pid].vertices)
            for pid in moved)
        assert total_b > 0
        # and host A released what moved away
        ss_a = c.storageds[0]
        released = sum(
            len(ss_a.store.space("bal").parts[pid].vertices)
            for pid in moved)
        assert released == 0

        _check_data(client)     # reads route to the new owners
        # writes land on the moved parts too
        rs = client.execute("INSERT VERTEX item(x) VALUES 100:(1000)")
        assert rs.error is None, rs.error
        rs = client.execute("FETCH PROP ON item 100 YIELD item.x AS x")
        assert rs.error is None and rs.data.rows == [[1000]]
    finally:
        c.stop()


def test_balance_data_heals_after_host_death(tmp_path):
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=3, n_graph=1,
                     data_dir=str(tmp_path))
    get_config().set_dynamic("host_hb_expire_secs", 0.6)
    try:
        client = c.client()
        _setup_space(client, c, parts=4, rf=2)
        _check_data(client)

        dead = c.storage_servers[2].addr
        c.stop_storaged(2)
        time.sleep(0.9)          # heartbeat horizon passes

        rs = client.execute("SUBMIT JOB BALANCE DATA")
        assert rs.error is None, rs.error
        _wait_jobs(c)

        meta = c.graphds[0].meta
        meta.refresh(force=True)
        pm = meta.parts_of("bal")
        for reps in pm:
            assert dead not in reps, pm
            assert len(reps) == 2, pm       # rf restored on survivors

        _check_data(client)
        rs = client.execute("INSERT VERTEX item(x) VALUES 200:(2000)")
        assert rs.error is None, rs.error
        rs = client.execute("FETCH PROP ON item 200 YIELD item.x AS x")
        assert rs.error is None and rs.data.rows == [[2000]]
    finally:
        get_config().set_dynamic("host_hb_expire_secs", 10.0)
        c.stop()


def test_balance_leader_spreads_leadership(tmp_path):
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        rs = client.execute(
            "CREATE SPACE bal(partition_num=4, replica_factor=2, "
            "vid_type=INT64)")
        assert rs.error is None, rs.error
        c.reconcile_storage()
        time.sleep(0.6)          # let every group elect

        rs = client.execute("SUBMIT JOB BALANCE LEADER")
        assert rs.error is None, rs.error
        _wait_jobs(c)

        # count actual raft leaders per host: 2 + 2.  Under full-suite
        # CPU load a starved election can undo a transfer right after
        # the one-shot job ran — re-submitting the (idempotent) job
        # inside the wait keeps the test about spreading, not timing.
        from collections import Counter
        counts = Counter()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            counts = Counter()
            for ss in c.storageds:
                for (sid, pid), part in ss.parts.items():
                    if part.is_leader():
                        counts[ss.my_addr] += 1
            if sorted(counts.values()) == [2, 2]:
                break
            time.sleep(0.3)
            client.execute("SUBMIT JOB BALANCE LEADER")
            _wait_jobs(c)
        assert sorted(counts.values()) == [2, 2], counts
    finally:
        c.stop()


def test_balance_heal_preserves_zone_isolation(tmp_path):
    """Healing after a host death re-replicates into an UNCOVERED zone,
    keeping the one-replica-per-zone invariant CREATE SPACE set up."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=4, n_graph=1,
                     data_dir=str(tmp_path))
    get_config().set_dynamic("host_hb_expire_secs", 0.6)
    try:
        client = c.client()
        addrs = [s.addr for s in c.storage_servers]
        client.execute(f'ADD HOSTS "{addrs[0]}", "{addrs[1]}" INTO ZONE za')
        client.execute(f'ADD HOSTS "{addrs[2]}", "{addrs[3]}" INTO ZONE zb')
        rs = client.execute(
            "CREATE SPACE zi(partition_num=4, replica_factor=2, "
            "vid_type=INT64)")
        assert rs.error is None, rs.error
        c.reconcile_storage()

        dead = addrs[2]
        idx = [s.addr for s in c.storage_servers].index(dead)
        c.stop_storaged(idx)
        import time
        time.sleep(0.9)

        rs = client.execute("SUBMIT JOB BALANCE DATA")
        assert rs.error is None, rs.error
        _wait_jobs(c)
        meta = c.graphds[0].meta
        meta.refresh(force=True)
        za, zb = set(addrs[:2]), {addrs[3]}     # zb minus the dead host
        for reps in meta.parts_of("zi"):
            assert dead not in reps, reps
            zones_hit = [("za" if r in za else "zb") for r in reps]
            # both replicas never collapse into one zone while the other
            # zone still has a live host
            assert sorted(zones_hit) == ["za", "zb"], reps
    finally:
        get_config().set_dynamic("host_hb_expire_secs", 10.0)
        c.stop()
