"""End-to-end query engine tests — the minimum E2E slice and beyond."""
import time

import pytest

from nebula_tpu.core import NULL, Path, Vertex, is_null
from nebula_tpu.exec import QueryEngine


@pytest.fixture()
def eng():
    e = QueryEngine()
    s = e.new_session()

    def run(q):
        r = e.execute(s, q)
        assert r.ok, f"{q} -> {r.error}"
        return r

    run('CREATE SPACE test (partition_num=4, vid_type=FIXED_STRING(20))')
    run('USE test')
    run('CREATE TAG person(name string, age int64)')
    run('CREATE TAG city(pop int64)')
    run('CREATE EDGE knows(since int64, weight double)')
    run('CREATE EDGE likes(level int64)')
    run('CREATE TAG INDEX i_person_age ON person(age)')
    run('CREATE EDGE INDEX i_knows_since ON knows(since)')
    run('INSERT VERTEX person(name, age) VALUES '
        '"a":("Ann",30), "b":("Bob",25), "c":("Cat",41), "d":("Dan",19), "e":("Eve",33)')
    run('INSERT EDGE knows(since, weight) VALUES '
        '"a"->"b":(2010,1.0), "a"->"c":(2012,0.5), "b"->"c":(2015,2.0), '
        '"c"->"d":(2018,1.5), "d"->"e":(2020,3.0), "e"->"a":(2021,0.1)')
    run('INSERT EDGE likes(level) VALUES "a"->"d":(5), "b"->"a":(3)')
    e._run = run
    e._sess = s
    return e


def rows(eng, q):
    return eng._run(q).data.rows


def test_go_one_step(eng):
    assert rows(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d') == [["b"], ["c"]]


def test_go_default_yield(eng):
    assert rows(eng, 'GO FROM "a" OVER knows') == [["b"], ["c"]]


def test_go_reversely(eng):
    assert rows(eng, 'GO FROM "a" OVER knows REVERSELY YIELD src(edge) AS s') == [["e"]]
    # dst(edge) under REVERSELY is the stored dst, i.e. "a" itself
    assert rows(eng, 'GO FROM "a" OVER knows REVERSELY YIELD dst(edge)') == [["a"]]


def test_go_bidirect(eng):
    got = sorted(r[0] for r in rows(
        eng, 'GO FROM "a" OVER knows BIDIRECT YIELD '
             'CASE WHEN dst(edge)=="a" THEN src(edge) ELSE dst(edge) END AS other'))
    assert got == ["b", "c", "e"]


def test_go_over_star(eng):
    got = sorted(r[0] for r in rows(eng, 'GO FROM "a" OVER * YIELD dst(edge) AS d'))
    assert got == ["b", "c", "d"]


def test_go_multi_step_with_filter(eng):
    got = rows(eng, 'GO 2 STEPS FROM "a" OVER knows '
                    'WHERE knows.since > 2012 AND $$.person.age > 20 '
                    'YIELD dst(edge) AS d, $^.person.name AS src_name')
    assert got == [["c", "Bob"]]


def test_go_m_to_n(eng):
    got = sorted((r[0], r[1]) for r in rows(
        eng, 'GO 1 TO 2 STEPS FROM "a" OVER knows YIELD dst(edge) AS d, knows.since AS y'))
    assert got == [("b", 2010), ("c", 2012), ("c", 2015), ("d", 2018)]


def test_go_src_dst_props(eng):
    got = rows(eng, 'GO FROM "b" OVER knows YIELD $^.person.age AS sa, '
                    '$$.person.age AS da, knows.weight AS w')
    assert got == [[25, 41, 2.0]]


def test_go_pipe_group_order_limit(eng):
    got = rows(eng, 'GO 1 TO 3 STEPS FROM "a" OVER knows YIELD dst(edge) AS d '
                    '| GROUP BY $-.d YIELD $-.d AS d, count(*) AS c '
                    '| ORDER BY $-.c DESC, $-.d | LIMIT 2')
    assert got == [["c", 2], ["d", 2]]


def test_go_from_pipe_input(eng):
    got = rows(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d '
                    '| GO FROM $-.d OVER knows YIELD $-.d AS via, dst(edge) AS d2')
    assert sorted(map(tuple, got)) == [("b", "c"), ("c", "d")]


def test_assignment_var(eng):
    eng._run('$v = GO FROM "a" OVER knows YIELD dst(edge) AS d')
    got = rows(eng, 'GO FROM $v.d OVER knows YIELD dst(edge) AS d2')
    assert sorted(r[0] for r in got) == ["c", "d"]
    assert rows(eng, 'YIELD $v.d AS d') == [["b"], ["c"]]


def test_go_distinct(eng):
    got = rows(eng, 'GO 2 STEPS FROM "a","b" OVER knows YIELD DISTINCT dst(edge) AS d')
    assert sorted(r[0] for r in got) == ["c", "d"]


def test_go_zero_neighbors(eng):
    eng._run('INSERT VERTEX person(name, age) VALUES "z":("Zoe", 50)')
    assert rows(eng, 'GO FROM "z" OVER knows') == []


def test_yield_standalone(eng):
    assert rows(eng, 'YIELD 1 + 2 AS x, "hi" AS s') == [[3, "hi"]]
    assert rows(eng, 'YIELD 1/0 AS d')[0][0].kind.value == "__DIV_BY_ZERO__"


def test_match_basic(eng):
    got = rows(eng, 'MATCH (v:person)-[e:knows]->(v2) WHERE v.person.age > 30 '
                    'RETURN v2.person.name AS n, e.since AS y ORDER BY n')
    assert got == [["Ann", 2021], ["Dan", 2018]]


def test_match_id_seed(eng):
    got = rows(eng, 'MATCH (a)-[e:knows]->(b) WHERE id(a) == "a" '
                    'RETURN b.person.name AS n ORDER BY n')
    assert got == [["Bob"], ["Cat"]]


def test_match_varlen(eng):
    got = rows(eng, 'MATCH p = (a)-[e:knows*1..2]->(b) WHERE id(a) == "a" '
                    'RETURN b.person.name AS n, length(p) AS l ORDER BY l, n')
    assert got == [["Bob", 1], ["Cat", 1], ["Cat", 2], ["Dan", 2]]


def test_match_incoming(eng):
    got = rows(eng, 'MATCH (a)<-[e:knows]-(b) WHERE id(a) == "c" '
                    'RETURN b.person.name AS n ORDER BY n')
    assert got == [["Ann"], ["Bob"]]


def test_match_both_direction(eng):
    got = rows(eng, 'MATCH (a)-[e:knows]-(b) WHERE id(a) == "a" '
                    'RETURN b.person.name AS n ORDER BY n')
    assert got == [["Bob"], ["Cat"], ["Eve"]]


def test_match_props_pattern(eng):
    got = rows(eng, 'MATCH (v:person{name:"Ann"})-[e:knows]->(b) '
                    'RETURN b.person.name AS n ORDER BY n')
    assert got == [["Bob"], ["Cat"]]


def test_match_return_aggregate(eng):
    got = rows(eng, 'MATCH (v:person)-[e:knows]->(b) '
                    'RETURN v.person.name AS n, count(*) AS c ORDER BY n')
    assert got == [["Ann", 2], ["Bob", 1], ["Cat", 1], ["Dan", 1], ["Eve", 1]]


def test_match_with_unwind(eng):
    got = rows(eng, 'MATCH (v:person) WITH v.person.age AS age WHERE age > 30 '
                    'RETURN age ORDER BY age')
    assert got == [[33], [41]]
    got2 = rows(eng, 'UNWIND [1,2,3] AS x RETURN x * 10 AS y')
    assert got2 == [[10], [20], [30]]


def test_match_optional(eng):
    got = rows(eng, 'MATCH (v:person{name:"Eve"}) '
                    'OPTIONAL MATCH (v)-[e:likes]->(o) RETURN v.person.name, o')
    assert len(got) == 1 and is_null(got[0][1])


def test_match_named_path(eng):
    got = rows(eng, 'MATCH p = (a)-[:knows]->(b) WHERE id(a) == "a" '
                    'RETURN nodes(p)[0] AS s ORDER BY id(s) LIMIT 1')
    assert isinstance(got[0][0], Vertex)
    assert got[0][0].vid == "a"


def test_find_shortest_path(eng):
    got = rows(eng, 'FIND SHORTEST PATH FROM "a" TO "e" OVER knows YIELD path AS p')
    assert len(got) == 1
    p = got[0][0]
    assert isinstance(p, Path)
    assert [v.vid for v in p.nodes()] == ["a", "c", "d", "e"]


def test_find_all_path(eng):
    got = rows(eng, 'FIND ALL PATH FROM "a" TO "c" OVER knows UPTO 3 STEPS YIELD path AS p')
    lens = sorted(r[0].length() for r in got)
    assert lens == [1, 2]   # a->c and a->b->c


def test_find_noloop_path(eng):
    got = rows(eng, 'FIND NOLOOP PATH FROM "a" TO "a" OVER knows UPTO 6 STEPS YIELD path AS p')
    assert got == []  # loop back to self excluded


def test_subgraph(eng):
    r = eng._run('GET SUBGRAPH 2 STEPS FROM "a" OUT knows YIELD VERTICES AS v, EDGES AS e')
    assert len(r.data.rows) >= 2
    all_vids = sorted({v.vid for row in r.data.rows for v in row[0]})
    assert all_vids == ["a", "b", "c", "d"]


def test_lookup(eng):
    got = rows(eng, 'LOOKUP ON person WHERE person.age > 30 '
                    'YIELD id(vertex) AS id, person.name AS name')
    assert sorted(map(tuple, got)) == [("c", "Cat"), ("e", "Eve")]
    got2 = rows(eng, 'LOOKUP ON knows WHERE knows.since >= 2018 YIELD src(edge) AS s')
    assert sorted(r[0] for r in got2) == ["c", "d", "e"]


def test_fetch(eng):
    got = rows(eng, 'FETCH PROP ON person "a" YIELD properties(vertex).name AS n, '
                    'properties(vertex).age AS a')
    assert got == [["Ann", 30]]
    got2 = rows(eng, 'FETCH PROP ON knows "a"->"b" YIELD properties(edge).since AS y')
    assert got2 == [[2010]]


def test_fetch_tag_prop_syntax(eng):
    # `person.name` in a FETCH yield is a tag-prop access on the fetched
    # vertex, not a variable lookup
    got = rows(eng, 'FETCH PROP ON person "a" YIELD person.name, person.age')
    assert got == [["Ann", 30]]
    got2 = rows(eng, 'FETCH PROP ON person "a", "c" YIELD person.name AS n')
    assert sorted(r[0] for r in got2) == ["Ann", "Cat"]


def test_update_and_fetch(eng):
    eng._run('UPDATE VERTEX ON person "a" SET age = age + 1')
    assert rows(eng, 'FETCH PROP ON person "a" YIELD properties(vertex).age AS a') == [[31]]
    eng._run('UPDATE EDGE ON knows "a"->"b" SET since = 2011')
    assert rows(eng, 'FETCH PROP ON knows "a"->"b" YIELD properties(edge).since') == [[2011]]


def test_upsert_creates(eng):
    eng._run('UPSERT VERTEX ON city "sf" SET pop = 800000')
    got = rows(eng, 'FETCH PROP ON city "sf" YIELD properties(vertex).pop AS p')
    assert got == [[800000]]


def test_delete(eng):
    eng._run('INSERT VERTEX person(name, age) VALUES "tmp":("Tmp", 1)')
    eng._run('INSERT EDGE knows(since, weight) VALUES "tmp"->"a":(2000, 0.0)')
    eng._run('DELETE VERTEX "tmp" WITH EDGE')
    assert rows(eng, 'GO FROM "a" OVER knows REVERSELY YIELD src(edge) AS s') == [["e"]]
    eng._run('DELETE EDGE likes "b"->"a"')
    assert rows(eng, 'GO FROM "b" OVER likes') == []


def test_union_intersect_minus(eng):
    got = rows(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d '
                    'UNION GO FROM "b" OVER knows YIELD dst(edge) AS d')
    assert sorted(r[0] for r in got) == ["b", "c"]
    got2 = rows(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d '
                     'INTERSECT GO FROM "b" OVER knows YIELD dst(edge) AS d')
    assert got2 == [["c"]]
    got3 = rows(eng, 'GO FROM "a" OVER knows YIELD dst(edge) AS d '
                     'MINUS GO FROM "b" OVER knows YIELD dst(edge) AS d')
    assert got3 == [["b"]]


def test_show_describe(eng):
    assert ["test"] in rows(eng, 'SHOW SPACES')
    assert sorted(r[0] for r in rows(eng, 'SHOW TAGS')) == ["city", "person"]
    assert sorted(r[0] for r in rows(eng, 'SHOW EDGES')) == ["knows", "likes"]
    d = rows(eng, 'DESCRIBE TAG person')
    assert d[0][:2] == ["name", "string"]


def test_explain_and_profile(eng):
    r = eng._run('EXPLAIN GO FROM "a" OVER knows')
    assert "ExpandAll" in r.data.rows[0][0]
    # PROFILE parity (ISSUE 8): data carries the QUERY's rows, the
    # per-node breakdown rides in plan_desc
    r2 = eng._run('PROFILE GO FROM "a" OVER knows')
    assert "rows=" in r2.plan_desc
    plain = eng._run('GO FROM "a" OVER knows')
    assert r2.data.rows == plain.data.rows


def test_index_ddl_and_jobs(eng):
    eng._run('CREATE TAG INDEX idx_age ON person(age)')
    assert ["idx_age", "person", ["age"]] in rows(eng, 'SHOW TAG INDEXES')
    r = eng._run('SUBMIT JOB STATS')
    jid = r.data.rows[0][0]
    jobs = rows(eng, 'SHOW JOBS')
    assert any(j[0] == jid and j[2] == "FINISHED" for j in jobs)


def test_errors_are_reported(eng):
    r = eng.execute(eng._sess, 'GO FROM "a" OVER nosuchedge')
    assert not r.ok and "nosuchedge" in r.error
    r2 = eng.execute(eng._sess, 'GOGO 1')
    assert not r2.ok and "SyntaxError" in r2.error
    r3 = eng.execute(eng._sess, 'GO FROM "a" OVER knows WHERE knows.nope > 1')
    assert not r3.ok and "nope" in r3.error


def test_aggregate_empty_group(eng):
    assert rows(eng, 'GO FROM "zzz" OVER knows YIELD dst(edge) AS d '
                     '| GROUP BY 1 YIELD count(*) AS c') == []
    got = rows(eng, 'MATCH (v:person{name:"NoOne"}) RETURN count(*) AS c')
    assert got == [[0]]


def test_case_insensitive_keywords(eng):
    assert rows(eng, 'go from "a" over knows yield dst(edge) as d') == [["b"], ["c"]]


# ---------------------------------------------------------------------------
# scheduler branch concurrency (SURVEY §2 row 24; VERDICT r1 weak #8)
# ---------------------------------------------------------------------------


def test_scheduler_runs_branches_concurrently():
    import time

    from nebula_tpu.exec.context import ExecutionContext, QueryContext
    from nebula_tpu.exec.executors import executor, EXECUTORS
    from nebula_tpu.exec.scheduler import Scheduler
    from nebula_tpu.query.plan import ExecutionPlan, PlanNode
    from nebula_tpu.core.value import DataSet

    spans = {}

    @executor("_SlowTest")
    def _slow(node, qctx, ectx, space):
        spans[node.args["v"]] = [time.perf_counter(), None]
        time.sleep(0.15)
        spans[node.args["v"]][1] = time.perf_counter()
        return DataSet(["x"], [[node.args["v"]]])

    @executor("_JoinTest")
    def _join(node, qctx, ectx, space):
        from nebula_tpu.exec.executors import _input
        a = _input(node, ectx, 0)
        b = _input(node, ectx, 1)
        return DataSet(["x"], a.rows + b.rows)

    try:
        left = PlanNode("_SlowTest", deps=[], args={"v": 1}, col_names=["x"])
        right = PlanNode("_SlowTest", deps=[], args={"v": 2}, col_names=["x"])
        root = PlanNode("_JoinTest", deps=[left, right], col_names=["x"])
        plan = ExecutionPlan(root, None)
        from nebula_tpu.graphstore.store import GraphStore
        qctx = QueryContext(GraphStore())
        ds = Scheduler(qctx).run(plan, ExecutionContext())
        assert sorted(r[0] for r in ds.rows) == [1, 2]
        # branches OVERLAPPED: each entered before the other exited
        # (wall-clock bounds flake on loaded machines; spans don't)
        (a0, a1), (b0, b1) = spans[1], spans[2]
        assert a0 < b1 and b0 < a1, spans
    finally:
        EXECUTORS.pop("_SlowTest", None)
        EXECUTORS.pop("_JoinTest", None)


def test_scheduler_sequential_when_disabled():
    from nebula_tpu.utils.config import get_config

    get_config().set_dynamic("scheduler_threads", 0)
    try:
        eng = QueryEngine()
        s = eng.new_session()
        eng.execute(s, "CREATE SPACE seq(partition_num=2, vid_type=INT64)")
        eng.execute(s, "USE seq")
        eng.execute(s, "CREATE TAG t(x int)")
        eng.execute(s, "INSERT VERTEX t(x) VALUES 1:(1), 2:(2)")
        rs = eng.execute(s, "MATCH (a:t), (b:t) RETURN id(a), id(b)")
        assert rs.error is None and len(rs.data.rows) == 4
    finally:
        get_config().set_dynamic("scheduler_threads", 4)


def test_recover_job_reruns_in_its_space():
    """RECOVER JOB re-runs a FAILED job with the space it was submitted
    in (ADVICE r4: recovery used the current session space, which is
    None inside the executor — jobs could never actually recover)."""
    eng = QueryEngine()
    s = eng.new_session()
    for t in ["CREATE SPACE rj(partition_num=2, vid_type=INT64)",
              "USE rj", "CREATE TAG P(a int)"]:
        assert eng.execute(s, t).error is None
    jid = eng.execute(s, "SUBMIT JOB STATS").data.rows[0][0]
    from nebula_tpu.exec.jobs import job_manager
    mgr = job_manager(eng.store)
    mgr.jobs[jid].status = "FAILED"
    rs = eng.execute(s, "RECOVER JOB")
    assert rs.error is None and rs.data.rows == [[1]]
    assert mgr.jobs[jid].status == "FINISHED"
    assert "error" not in (mgr.jobs[jid].result or {})


def test_kill_session_standalone():
    eng = QueryEngine()
    s1 = eng.new_session()
    s2 = eng.new_session()
    rs = eng.execute(s1, f"KILL SESSION {s2.id}")
    assert rs.error is None
    rs = eng.execute(s2, "SHOW SPACES")
    assert rs.error == "Session was killed"
    rs = eng.execute(s1, "KILL SESSION 999999")
    assert rs.error is not None


def test_get_configs_includes_session_params():
    """GET CONFIGS must agree with SHOW CONFIGS row-for-row, including
    the session-param module (ADVICE r4: the two had diverged)."""
    eng = QueryEngine(params={"my_session_knob": 7})
    s = eng.new_session()
    show = eng.execute(s, "SHOW CONFIGS")
    get = eng.execute(s, "GET CONFIGS")
    assert show.error is None and get.error is None
    assert sorted(map(repr, show.data.rows)) == \
        sorted(map(repr, get.data.rows))
    one = eng.execute(s, "GET CONFIGS my_session_knob")
    assert one.error is None and one.data.rows[0][0] == "session"


def test_kill_query_aborts_running_statement():
    """KILL QUERY (session=sid, plan=qid) from another session sets the
    running query's kill event; its scheduler aborts between nodes."""
    import threading
    from nebula_tpu.exec.executors import EXECUTORS, executor
    from nebula_tpu.core.value import DataSet as _DS

    eng = QueryEngine()
    victim = eng.new_session()
    killer = eng.new_session()
    started = threading.Event()

    @executor("_StallTest")
    def _stall(node, qctx, ectx, space):
        started.set()
        time.sleep(0.8)
        return _DS(["x"], [[1]])

    # a plan with a stalling node followed by another node: the kill
    # lands during the stall, the second node never runs
    from nebula_tpu.query.plan import ExecutionPlan, PlanNode
    from nebula_tpu.exec.context import ExecutionContext

    out = {}

    def run_victim():
        a = PlanNode("_StallTest", deps=[], col_names=["x"])
        b = PlanNode("_StallTest", deps=[a], col_names=["x"])
        plan = ExecutionPlan(b, None)
        # drive through the engine internals the way execute() does
        stmt_ectx = ExecutionContext()
        import nebula_tpu.exec.engine as em
        qid = next(em._query_ids)
        stmt_ectx.kill_event = threading.Event()
        victim.queries[qid] = "stall"
        victim.running_kill[qid] = stmt_ectx.kill_event
        out["qid"] = qid
        try:
            eng.scheduler.run(plan, stmt_ectx)
            out["err"] = None
        except Exception as ex:  # noqa: BLE001
            out["err"] = str(ex)
        finally:
            victim.queries.pop(qid, None)
            victim.running_kill.pop(qid, None)

    try:
        t = threading.Thread(target=run_victim)
        t.start()
        assert started.wait(5)
        assert "qid" in out, out       # registration precedes the stall
        rs = eng.execute(killer, "SHOW QUERIES")
        assert rs.error is None
        assert any(r[0] == victim.id and r[3] == "stall"
                   for r in rs.data.rows), \
            (rs.data.rows, victim.id, dict(victim.queries),
             list(eng.sessions), out)
        rs = eng.execute(
            killer,
            f"KILL QUERY (session={victim.id}, plan={out['qid']})")
        assert rs.error is None, rs.error
        t.join(timeout=5)
        assert out["err"] is not None and "killed" in out["err"]
    finally:
        EXECUTORS.pop("_StallTest", None)

    # killing a nonexistent query errors
    rs = eng.execute(killer, "KILL QUERY (session=999999, plan=1)")
    assert rs.error is not None


def test_admin_jobs_async_lifecycle():
    """The job manager is ASYNC (AdminTaskManager analog): SUBMIT
    returns immediately with the job QUEUE'd/RUNNING, the worker pool
    is bounded by max_concurrent_admin_jobs (throttling), STOP JOB
    cancels a QUEUE'd job outright, and RECOVER re-queues it."""
    import threading
    import time as _t

    from nebula_tpu.exec.jobs import JobManager, job_manager
    from nebula_tpu.graphstore.store import GraphStore
    from nebula_tpu.utils.config import get_config

    store = GraphStore()
    eng = QueryEngine(store)
    s = eng.new_session()
    for q in ["CREATE SPACE aj(partition_num=2, vid_type=INT64)",
              "USE aj", "CREATE TAG t(x int)"]:
        assert eng.execute(s, q).error is None

    mgr = job_manager(store)
    gate = threading.Event()
    orig_run = JobManager._run
    runs_per_job = {}

    def slow_run(self, qctx, command, space, job=None):
        if command == "stats":
            if job is not None:
                runs_per_job[job.job_id] =                     runs_per_job.get(job.job_id, 0) + 1
            assert gate.wait(10)
            if job is not None and job.cancel.is_set():
                from nebula_tpu.exec.jobs import JobStopped
                raise JobStopped()
        return orig_run(self, qctx, command, space, job)

    JobManager._run = slow_run
    try:
        get_config().set_dynamic("max_concurrent_admin_jobs", 1)
        rs = eng.execute(s, "SUBMIT JOB STATS")
        assert rs.error is None
        j1 = rs.data.rows[0][0]
        rs = eng.execute(s, "SUBMIT JOB STATS")
        j2 = rs.data.rows[0][0]
        rs = eng.execute(s, "SUBMIT JOB STATS")
        j3 = rs.data.rows[0][0]
        deadline = _t.time() + 5
        while _t.time() < deadline \
                and mgr.jobs[j1].status != "RUNNING":
            _t.sleep(0.01)
        # throttled: one RUNNING, the rest QUEUE'd
        assert mgr.jobs[j1].status == "RUNNING"
        assert mgr.jobs[j2].status == "QUEUE"
        assert mgr.jobs[j3].status == "QUEUE"
        # STOP a QUEUE'd job: cancelled outright, never runs
        rs = eng.execute(s, f"STOP JOB {j3}")
        assert rs.error is None
        assert mgr.jobs[j3].status == "STOPPED"
        # STOP the RUNNING job: aborts at its cancel point
        rs = eng.execute(s, f"STOP JOB {j1}")
        assert rs.error is None
        gate.set()
        assert mgr.wait(timeout=10)
        assert mgr.jobs[j1].status == "STOPPED"
        assert mgr.jobs[j2].status == "FINISHED"
        assert mgr.jobs[j3].status == "STOPPED"
        # RECOVER re-queues the stopped jobs and they finish
        rs = eng.execute(s, "RECOVER JOB")
        assert rs.error is None
        assert rs.data.rows[0][0] == 2
        assert mgr.wait(timeout=10)
        assert mgr.jobs[j1].status == "FINISHED"
        assert mgr.jobs[j3].status == "FINISHED"
        # STOP of the QUEUE'd j3 purged its queue entry: the RECOVER
        # re-queue must be its ONLY execution (a stale tuple would
        # double-dispatch — code-review r4)
        assert runs_per_job.get(j3, 0) == 1, runs_per_job
        assert runs_per_job[j2] == 1
    finally:
        JobManager._run = orig_run
        get_config().set_dynamic("max_concurrent_admin_jobs", 2)


def test_idle_sessions_reaped():
    """session_idle_timeout_secs: an idle session is dropped from the
    registry at the next new_session (the standalone reap path; the
    cluster reaps through metad TTL)."""
    from nebula_tpu.utils.config import get_config
    eng = QueryEngine()
    old = get_config().get("session_idle_timeout_secs")
    try:
        get_config().set_dynamic("session_idle_timeout_secs", 0)
        s1 = eng.new_session()
        import time as _t
        _t.sleep(0.05)
        s2 = eng.new_session()
        assert s1.id not in eng.sessions
        assert s2.id in eng.sessions
    finally:
        get_config().set_dynamic("session_idle_timeout_secs", old)
