"""TpuMatchPipeline: columnar multi-clause MATCH fusion (tpu/pipeline.py).

Parity contract (three-way): the fused columnar pipeline, the host row
executors, and a brute-force python oracle over the raw adjacency must
agree on result rows — including OPTIONAL MATCH null extension, 3VL
predicate corners over null-extended columns, and first-occurrence
dedup/group order.  When hypothesis is available the graph/seed space is
fuzzed; the seeded parametrize fallback keeps the suite running (and the
contract enforced) in environments without it.
"""
import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.stats import stats

from test_tpu import P, random_store  # noqa: E402

from nebula_tpu.tpu import TpuRuntime, make_mesh  # noqa: E402

try:
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as hst
    HAVE_HYPOTHESIS = True
except ImportError:                               # container without it:
    HAVE_HYPOTHESIS = False                       # seeded fallback below


@pytest.fixture(scope="module")
def rt():
    return TpuRuntime(make_mesh(P))


def _run(eng, s, q):
    r = eng.execute(s, q)
    assert r.error is None, f"{q} -> {r.error}"
    return [tuple(map(repr, row)) for row in r.data.rows]


def _engines(seed, rt, n=120, avg_deg=5):
    st = random_store(seed, n=n, avg_deg=avg_deg)
    host = QueryEngine(st)
    hs = host.new_session()
    host.execute(hs, "USE g")
    dev = QueryEngine(st, tpu_runtime=rt)
    ds = dev.new_session()
    dev.execute(ds, "USE g")
    return st, host, hs, dev, ds


# IC-shaped multi-clause pipelines: WITH DISTINCT → second MATCH →
# OPTIONAL MATCH → aggregate → ORDER BY, plus 3VL/edge-filter corners.
QUERIES = [
    # WITH DISTINCT then a second Argument-seeded MATCH
    ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2,3] "
     "WITH DISTINCT b MATCH (b)-[:knows]->(c:person) "
     "RETURN id(b) AS x, id(c) AS y ORDER BY x, y"),
    # OPTIONAL MATCH null extension (misses keep b, null-extend c)
    ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2,3,4] "
     "WITH DISTINCT b OPTIONAL MATCH (b)-[:knows]->(c:person) "
     "WHERE c.person.age > 60 "
     "RETURN id(b) AS x, id(c) AS y ORDER BY x, y"),
    # the full IC5 shape: OPTIONAL MATCH → grouped count → sort
    ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [0,1,2,3,4,5] "
     "WITH DISTINCT b OPTIONAL MATCH (b)-[:knows]->(c:person) "
     "WHERE c.person.age > 40 "
     "WITH b, count(c) AS cnt "
     "RETURN id(b) AS x, cnt ORDER BY cnt DESC, x ASC"),
    # device-compilable edge filter inside the second clause
    ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2,3] "
     "WITH DISTINCT b MATCH (b)-[e:knows]->(c) WHERE e.w > 50 "
     "RETURN id(b) AS x, id(c) AS y ORDER BY x, y"),
    # var-len first clause feeding the pipeline tail
    ("MATCH (a:person)-[:knows*1..2]->(b:person) WHERE id(a) IN [1,2] "
     "WITH DISTINCT b MATCH (b)-[:knows]->(c) "
     "RETURN count(*) AS n, count(DISTINCT id(c)) AS d"),
    # string-prop predicate + DISTINCT pair projection
    ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [0,5,6] "
     "AND b.person.name == \"ann\" "
     "WITH DISTINCT a, b MATCH (b)-[:knows]->(c) "
     "RETURN id(a) AS s, id(c) AS y ORDER BY s, y"),
    # 3VL: IS NULL over the null-extended optional column
    ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2,3,4] "
     "WITH DISTINCT b OPTIONAL MATCH (b)-[:knows]->(c:person) "
     "WHERE c.person.age > 70 "
     "RETURN id(b) AS x, id(c) IS NULL AS miss ORDER BY x, miss"),
    # LIMIT tail over the fused frame
    ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2,3] "
     "WITH DISTINCT b MATCH (b)-[:knows]->(c:person) "
     "RETURN id(b) AS x, id(c) AS y ORDER BY x, y LIMIT 7"),
]


def test_ic_shape_fuses(rt):
    _, _, _, dev, ds = _engines(3, rt)
    r = dev.execute(ds, "EXPLAIN " + QUERIES[2])
    txt = r.data.rows[0][0]
    assert "TpuMatchPipeline" in txt
    assert "HashLeftJoin" not in txt
    assert "Traverse" not in txt
    # counters move when the fused plan executes
    before = stats().snapshot().get("match_pipeline_fused", 0)
    r = dev.execute(ds, QUERIES[2])
    assert r.error is None
    assert stats().snapshot().get("match_pipeline_fused", 0) == before + 1


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_device_matches_host(rt, seed, qi):
    _, host, hs, dev, ds = _engines(seed, rt)
    q = QUERIES[qi]
    # ORDER BY queries compare in order; unordered ones as multisets
    dv, hv = _run(dev, ds, q), _run(host, hs, q)
    if "ORDER BY" in q:
        assert dv == hv, q
    else:
        assert sorted(dv) == sorted(hv), q


def _oracle_ic_shape(st, seeds, age_gt):
    """Brute-force python oracle for QUERIES[2]'s shape: seeds -knows->
    b (person), distinct b; per b count knows-edges to persons with
    age > age_gt; ORDER BY cnt DESC, id(b) ASC."""
    def nbrs(v):
        return list(st.get_neighbors("g", [v], ["knows"], "out"))

    def age(v):
        tv = st.get_vertex("g", v)
        return None if tv is None or "person" not in tv \
            else tv["person"].get("age")

    bs = []
    for s in seeds:
        if age(s) is None:
            continue
        for (_s, _et, _rk, other, _props, _sgn) in nbrs(s):
            if age(other) is not None and other not in bs:
                bs.append(other)
    rows = []
    for b in bs:
        cnt = 0
        for (_s, _et, _rk, c, _props, _sgn) in nbrs(b):
            a = age(c)
            if isinstance(a, int) and a > age_gt:
                cnt += 1
        rows.append((b, cnt))
    rows.sort(key=lambda t: (-t[1], t[0]))
    return [(str(b), str(c)) for b, c in rows]


@pytest.mark.parametrize("seed", [0, 4, 9])
def test_brute_force_oracle(rt, seed):
    st, host, hs, dev, ds = _engines(seed, rt)
    q = QUERIES[2]
    want = _oracle_ic_shape(st, [0, 1, 2, 3, 4, 5], 40)
    got_dev = [(x, c) for x, c in _run(dev, ds, q)]
    got_host = [(x, c) for x, c in _run(host, hs, q)]
    # ties on (cnt, x) are impossible (x unique), so full order compares
    assert got_dev == want
    assert got_host == want


def test_runtime_fallback_matches_host(rt):
    """tpu_match_device off: the fused node must execute its stashed
    subplan (host semantics), byte-identical to the host plane."""
    _, host, hs, dev, ds = _engines(5, rt)
    cfg = get_config()
    old = cfg.get("tpu_match_device")
    try:
        cfg.set_dynamic("tpu_match_device", False)
        before = {k: v for k, v in stats().snapshot().items()
                  if k.startswith("match_pipeline_fallback")}
        for q in QUERIES:
            assert _run(dev, ds, q) == _run(host, hs, q), q
        after = {k: v for k, v in stats().snapshot().items()
                 if k.startswith("match_pipeline_fallback")}
        assert sum(after.values()) > sum(before.values())
    finally:
        cfg.set_dynamic("tpu_match_device", old)


def test_pipeline_flag_off_keeps_plans_unfused(rt):
    _, host, hs, dev, ds = _engines(6, rt)
    cfg = get_config()
    old = cfg.get("tpu_match_pipeline")
    try:
        cfg.set_dynamic("tpu_match_pipeline", False)
        r = dev.execute(ds, "EXPLAIN " + QUERIES[2])
        assert "TpuMatchPipeline" not in r.data.rows[0][0]
        for q in QUERIES[:3]:
            assert _run(dev, ds, q) == _run(host, hs, q)
    finally:
        cfg.set_dynamic("tpu_match_pipeline", old)


def test_unfusable_tails_still_correct(rt):
    """Per-node bail-out: shapes the compiler refuses stay partially or
    wholly on row executors and still agree with the host plane."""
    _, host, hs, dev, ds = _engines(7, rt)
    qs = [
        # sum() aggregate — not a count: aggregate stays on rows
        ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2] "
         "WITH DISTINCT b MATCH (b)-[:knows]->(c:person) "
         "RETURN id(b) AS x, sum(c.person.age) AS s ORDER BY x"),
        # WITH ... WHERE over a projected count (val-column predicate)
        ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN [1,2,3] "
         "WITH DISTINCT b OPTIONAL MATCH (b)-[:knows]->(c) "
         "WITH b, count(c) AS cnt WHERE cnt > 1 "
         "RETURN id(b) AS x, cnt ORDER BY x"),
    ]
    for q in qs:
        assert _run(dev, ds, q) == _run(host, hs, q), q


def _parity_case(rt, seed, n, avg_deg):
    _, host, hs, dev, ds = _engines(seed, rt, n=n, avg_deg=avg_deg)
    for q in (QUERIES[1], QUERIES[2], QUERIES[4]):
        assert _run(dev, ds, q) == _run(host, hs, q), (seed, q)


if HAVE_HYPOTHESIS:
    @settings(max_examples=8, deadline=None,
              suppress_health_check=list(HealthCheck))
    @given(seed=hst.integers(min_value=0, max_value=10_000),
           n=hst.integers(min_value=20, max_value=160),
           avg_deg=hst.integers(min_value=1, max_value=7))
    def test_parity_fuzz(rt, seed, n, avg_deg):
        _parity_case(rt, seed, n, avg_deg)
else:
    @pytest.mark.parametrize("seed,n,avg_deg", [
        (11, 40, 2), (12, 80, 6), (13, 25, 7), (14, 160, 3)])
    def test_parity_fuzz(rt, seed, n, avg_deg):
        _parity_case(rt, seed, n, avg_deg)
