"""ISSUE 2 plan cache: repeated statements skip parse → validate →
plan → optimize; DDL (schema + index) bumps the schema epoch and makes
every stale plan unreachable."""
import pytest

from nebula_tpu.exec.engine import QueryEngine, quick_engine
from nebula_tpu.utils.stats import stats


def _counts():
    snap = stats().snapshot()
    return (snap.get("plan_cache_hits", 0),
            snap.get("plan_cache_misses", 0))


@pytest.fixture()
def eng_sess():
    eng, s = quick_engine()
    for q in ("CREATE SPACE pc(partition_num=2, vid_type=INT64)",
              "USE pc", "CREATE TAG Person(age int)",
              "CREATE EDGE KNOWS(w int)"):
        r = eng.execute(s, q)
        assert r.error is None, (q, r.error)
    r = eng.execute(s, "INSERT VERTEX Person(age) VALUES "
                       "1:(30), 2:(25), 3:(41), 4:(19)")
    assert r.error is None, r.error
    r = eng.execute(s, "INSERT EDGE KNOWS(w) VALUES 1->2:(5), 2->3:(50), "
                       "3->4:(9), 1->3:(80)")
    assert r.error is None, r.error
    return eng, s


def test_hit_skips_parse_and_plan(eng_sess, monkeypatch):
    eng, s = eng_sess
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d, KNOWS.w AS w"
    r1 = eng.execute(s, q)
    assert r1.error is None
    h0, _ = _counts()

    # a cache hit must not touch the parser or the planner at all
    import nebula_tpu.exec.engine as E

    def bomb(*a, **kw):
        raise AssertionError("parse() called on a plan-cache hit")

    monkeypatch.setattr(E, "parse", bomb)
    r2 = eng.execute(s, q)
    h1, _ = _counts()
    assert r2.error is None
    assert h1 == h0 + 1
    assert sorted(map(tuple, r2.data.rows)) == \
        sorted(map(tuple, r1.data.rows))


def test_ddl_bumps_epoch_and_invalidates(eng_sess):
    eng, s = eng_sess
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d"
    eng.execute(s, q)
    eng.execute(s, q)
    h0, _ = _counts()

    # ALTER TAG is DDL: schema epoch bumps, the cached plan goes stale
    ver0 = eng.qctx.catalog.version
    r = eng.execute(s, "ALTER TAG Person ADD (name string)")
    assert r.error is None
    assert eng.qctx.catalog.version > ver0
    eng.execute(s, q)                   # must be a MISS (replan)
    h1, _ = _counts()
    assert h1 == h0, "stale plan served after ALTER TAG"
    eng.execute(s, q)                   # fresh entry hits again
    h2, _ = _counts()
    assert h2 == h1 + 1

    # CREATE TAG and index DDL bump too
    for ddl in ("CREATE TAG Post(ts int)",
                "CREATE TAG INDEX i_age ON Person(age)",
                "REBUILD TAG INDEX i_age"):
        before = eng.qctx.catalog.version
        r = eng.execute(s, ddl)
        assert r.error is None, (ddl, r.error)
        if "REBUILD" not in ddl:
            assert eng.qctx.catalog.version > before, ddl
        eng.execute(s, q)               # miss after each DDL epoch bump
    h3, _ = _counts()
    assert h3 == h2 + 1                 # only the pre-CREATE hit above


def test_stale_plan_regression_index_ddl(eng_sess):
    """The stale-plan failure mode index DDL can cause: a LOOKUP planned
    before CREATE INDEX must not keep serving the index-less plan after
    the index exists — the epoch key forces a replan that picks the
    index up."""
    eng, s = eng_sess
    q = "MATCH (p:Person) WHERE p.Person.age > 24 " \
        "RETURN id(p) AS v ORDER BY v"
    r1 = eng.execute(s, q)
    assert r1.error is None
    key_before = [k for k in eng.plan_cache._map if k[0] == q]
    assert key_before, "read-only MATCH was not cached"
    plan_before = eng.plan_cache._map[key_before[0]][1]

    for ddl in ("CREATE TAG INDEX i_age2 ON Person(age)",
                "REBUILD TAG INDEX i_age2"):
        r = eng.execute(s, ddl)
        assert r.error is None, (ddl, r.error)
    r2 = eng.execute(s, q)
    assert r2.error is None
    assert r2.data.rows == r1.data.rows == [[1], [2], [3]]
    key_after = [k for k in eng.plan_cache._map if k[0] == q
                 and k not in key_before]
    assert key_after, "post-DDL execution did not create a fresh entry"
    plan_after = eng.plan_cache._map[key_after[0]][1]
    # the fresh plan uses the index the stale one could not know about
    assert "IndexScan" in plan_after.root.kind_tree()
    assert plan_after is not plan_before


def test_non_cacheable_statements(eng_sess):
    eng, s = eng_sess
    n0 = len(eng.plan_cache)
    # DML/DDL/compound/EXPLAIN never enter the cache
    assert eng.execute(
        s, "INSERT VERTEX Person(age) VALUES 9:(9)").error is None
    assert eng.execute(
        s, "EXPLAIN GO FROM 1 OVER KNOWS YIELD dst(edge)").error is None
    assert eng.execute(
        s, "YIELD 1 AS a; YIELD 2 AS b").error is None
    assert len(eng.plan_cache) == n0

    # $var sessions bypass the cache entirely (plans become
    # session-dependent the moment var state exists)
    r = eng.execute(s, "$v = GO FROM 1 OVER KNOWS YIELD dst(edge) AS d; "
                       "GO FROM $v.d OVER KNOWS YIELD dst(edge) AS d2")
    assert r.error is None
    assert s.var_cols
    h0, _ = _counts()
    q = "GO FROM 2 OVER KNOWS YIELD dst(edge) AS d"
    eng.execute(s, q)
    eng.execute(s, q)
    h1, _ = _counts()
    assert h1 == h0, "cached despite live $var session state"


def test_cache_disabled_by_flag(eng_sess, monkeypatch):
    from nebula_tpu.utils.config import get_config
    eng, s = eng_sess
    get_config().set_dynamic("plan_cache_size", 0)
    try:
        q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d"
        h0, _ = _counts()
        eng.execute(s, q)
        eng.execute(s, q)
        h1, _ = _counts()
        assert h1 == h0
        assert len(eng.plan_cache) == 0
    finally:
        get_config().set_dynamic("plan_cache_size", 128)


def test_space_isolation(eng_sess):
    """Same text in a different space must not hit the other space's
    plan (space is part of the key)."""
    eng, s = eng_sess
    q = "GO FROM 1 OVER KNOWS YIELD dst(edge) AS d"
    r1 = eng.execute(s, q)
    assert r1.error is None
    for ddl in ("CREATE SPACE pc2(partition_num=2, vid_type=INT64)",
                "USE pc2", "CREATE TAG Person(age int)",
                "CREATE EDGE KNOWS(w int)",
                "INSERT VERTEX Person(age) VALUES 1:(1), 7:(7)",
                "INSERT EDGE KNOWS(w) VALUES 1->7:(1)"):
        r = eng.execute(s, ddl)
        assert r.error is None, (ddl, r.error)
    r2 = eng.execute(s, q)
    assert r2.error is None
    assert sorted(r[0] for r in r2.data.rows) == [7]
