"""MetaClient leader-hint walk (ISSUE 5 satellite).

The hint grammar is "not leader; leader=<addr>".  A garbled or empty
hint (election in flight, truncated message) must clear the cached
leader and re-probe — never adopt free text as an address.  When every
metad is down the walk backs off with jittered exponential sleeps.
"""
import time

import pytest

from nebula_tpu.cluster.meta_client import MetaClient, MetaError
from nebula_tpu.cluster.rpc import RpcConnError, RpcError
from nebula_tpu.utils import cancel
from nebula_tpu.utils.stats import stats


class FakeRpc:
    """Scripted RpcClient stand-in: each call pops the next behavior
    (exception to raise, or value to return; the last repeats)."""

    def __init__(self, *script):
        self.script = list(script)
        self.calls = 0

    def call(self, method, **params):
        self.calls += 1
        b = self.script.pop(0) if len(self.script) > 1 else self.script[0]
        if isinstance(b, Exception):
            raise b
        return b


def _mc(fakes):
    mc = MetaClient(sorted(fakes), heartbeat_interval=1.0)
    mc._clients = dict(fakes)
    return mc


def test_leader_hint_followed():
    mc = _mc({"a:1": FakeRpc(RpcError("not leader; leader=c:3")),
              "b:2": FakeRpc(RpcError("not leader; leader=c:3")),
              "c:3": FakeRpc({"v": 1})})
    assert mc.call("meta.x") == {"v": 1}
    assert mc._leader == "c:3"
    # subsequent calls go straight to the cached leader
    mc.call("meta.x")
    assert mc._clients["c:3"].calls == 2


@pytest.mark.parametrize("reply", [
    "not leader",                      # no '=' at all (garbled)
    "not leader; leader=",             # empty hint (election in flight)
])
def test_garbled_hint_clears_cache_and_reprobes(reply):
    mc = _mc({"a:1": FakeRpc(RpcError(reply)),
              "b:2": FakeRpc({"v": 2}),
              "c:3": FakeRpc(RpcError(reply))})
    assert mc.call("meta.x") == {"v": 2}
    assert mc._leader == "b:2"
    # the old bug: split("=", 1)[-1] on a hint-less message adopted the
    # whole message text as an address; no such "client" may appear
    assert set(mc._clients) == {"a:1", "b:2", "c:3"}


def test_non_leader_error_is_not_hint():
    mc = _mc({"a:1": FakeRpc(RpcError("space `x' not found"))})
    with pytest.raises(MetaError, match="not found"):
        mc.call("meta.x")


def test_all_metads_down_backoff_timing():
    mc = _mc({"a:1": FakeRpc(RpcConnError("refused")),
              "b:2": FakeRpc(RpcConnError("refused"))})
    before = stats().snapshot().get("meta_leader_walk_retries", 0)
    t0 = time.monotonic()
    with pytest.raises(MetaError, match="no metad leader reachable"):
        mc.call("meta.x", _retries=3)
    elapsed = time.monotonic() - t0
    after = stats().snapshot().get("meta_leader_walk_retries", 0)
    assert after - before == 2          # sleeps BETWEEN attempts only
    # equal-jitter exponential, base 0.1: attempts 0,1 sleep at least
    # d/2 = 0.05 + 0.10, at most d = 0.10 + 0.20 (plus walk overhead)
    assert 0.14 <= elapsed <= 1.0, elapsed


def test_deadline_stops_the_walk():
    mc = _mc({"a:1": FakeRpc(RpcConnError("refused"))})
    with cancel.use_cancel(deadline=time.monotonic() - 0.001):
        t0 = time.monotonic()
        with pytest.raises(cancel.DeadlineExceeded):
            mc.call("meta.x")
        assert time.monotonic() - t0 < 0.5


def test_conn_error_clears_cached_leader():
    fakes = {"a:1": FakeRpc(RpcConnError("refused"), {"v": 3}),
             "b:2": FakeRpc({"v": 9})}
    mc = _mc(fakes)
    mc._leader = "a:1"
    assert mc.call("meta.x") == {"v": 9}
    assert mc._leader == "b:2"
