"""Deadline budgets + KILL QUERY responsiveness (ISSUE 5).

The statement timeout is an absolute deadline propagated (and
decremented) across every RPC hop; KILL QUERY lands between plan
nodes, between fused TPU pipeline segments, and inside the storage
fan-out wait — not just at row boundaries.
"""
import threading
import time

import pytest

from nebula_tpu.cluster.launcher import LocalCluster
from nebula_tpu.cluster.rpc import reset_breakers
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.stats import stats


@pytest.fixture()
def clean_faults():
    fail.reset()
    reset_breakers()
    yield
    fail.reset()
    reset_breakers()
    get_config().set_dynamic("query_timeout_secs", 300.0)


# -- engine-level deadline --------------------------------------------------


def test_statement_deadline_surfaces_e_query_timeout(clean_faults):
    eng = QueryEngine()
    s = eng.new_session()
    r = eng.execute(s, "CREATE SPACE dl(partition_num=1, vid_type=INT64)")
    assert r.error is None
    eng.execute(s, "USE dl")
    get_config().set_dynamic("query_timeout_secs", 1e-9)
    r = eng.execute(s, "YIELD 1 AS x")
    assert r.error is not None and r.error.startswith("E_QUERY_TIMEOUT"), \
        r.error
    assert stats().snapshot().get("query_deadline_exceeded", 0) >= 1
    # restoring the budget restores service
    get_config().set_dynamic("query_timeout_secs", 300.0)
    r = eng.execute(s, "YIELD 1 AS x")
    assert r.error is None and r.data.rows == [[1]]


def test_zero_timeout_disables_budget(clean_faults):
    eng = QueryEngine()
    s = eng.new_session()
    get_config().set_dynamic("query_timeout_secs", 0.0)
    r = eng.execute(s, "YIELD 1 AS x")
    assert r.error is None


# -- cluster: deadline crosses the RPC boundary -----------------------------


@pytest.fixture(scope="module")
def cluster():
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1)
    client = c.client()

    def run(q, expect_ok=True):
        rs = client.execute(q)
        if expect_ok:
            assert rs.error is None, f"{q} -> {rs.error}"
        return rs

    run("CREATE SPACE dk(partition_num=2, replica_factor=1, "
        "vid_type=INT64)")
    c.reconcile_storage()
    run("USE dk")
    run("CREATE TAG T(x int)")
    run("INSERT VERTEX T(x) VALUES 1:(1)")
    c.run = run
    yield c
    c.stop()


def test_fsync_stall_hits_deadline_not_rpc_timeout(cluster, clean_faults):
    """A stalled WAL fsync must surface E_QUERY_TIMEOUT within
    budget + grace — not hang for the full transport timeout."""
    get_config().set_dynamic("query_timeout_secs", 0.5)
    fail.arm("wal:pre_fsync", "-1*delay(1.0)")
    try:
        t0 = time.monotonic()
        rs = cluster.run("INSERT VERTEX T(x) VALUES 9:(9)",
                         expect_ok=False)
        elapsed = time.monotonic() - t0
    finally:
        fail.disarm("wal:pre_fsync")
        get_config().set_dynamic("query_timeout_secs", 300.0)
    assert rs.error is not None and "E_QUERY_TIMEOUT" in rs.error, rs.error
    # grace: budget 0.5s + one in-flight stall (1s) + walk overhead
    assert elapsed < 4.0, f"deadline overshot: {elapsed:.1f}s"


def test_clamped_timeout_does_not_kill_healthy_connection(clean_faults):
    """A deadline-clamped request can time out in milliseconds — that
    says nothing about the connection.  The silent-peer verdict is
    judged against the BASE transport window, so a sibling in-flight
    call on the shared pooled connection must survive and succeed."""
    from nebula_tpu.cluster.rpc import RpcClient, RpcConnError, RpcServer

    srv = RpcServer()
    srv.register("t.echo", lambda p: (time.sleep(p.get("s", 0)) or
                                      p["x"]))
    srv.start()
    cl = RpcClient(srv.host, srv.port, timeout=5.0, retries=0)
    try:
        assert cl.call("t.echo", x=1) == 1          # conn warm
        conn = cl._pick()
        # a request waiting only 50ms of its 5s base window times out —
        # alone.  (This is the shape a 50ms-of-budget statement's clamp
        # produces; driven via the conn to pin the timing.)
        with pytest.raises(RpcConnError, match="rpc timeout"):
            conn.request({"method": "t.echo",
                          "params": {"x": 3, "s": 1.0}}, 0.05)
        assert conn.dead is None, \
            "clamped timeout killed a healthy connection"
        assert cl.call("t.echo", x=2) == 2          # conn still serves
    finally:
        srv.stop()


def test_kill_query_lands_in_storage_fanout_wait(cluster, clean_faults):
    """KILL QUERY while every part write is stalled server-side: the
    fan-out wait polls the kill event and aborts promptly instead of
    riding out the RPC timeout."""
    from nebula_tpu.utils.failpoints import FaultSchedule
    # key-filtered to the STORAGE wal: a blanket arm would also stall
    # the metad's wal on the post-statement session touch, delaying the
    # (already-killed) reply by a full stall
    FaultSchedule(1, [{"fp": "wal:pre_fsync", "action": "delay",
                       "arg": 2.0, "p": 1.0, "key": "storage"}]).arm(fail)
    out = {}

    def victim():
        out["rs"] = cluster.run("INSERT VERTEX T(x) VALUES 10:(10)",
                                expect_ok=False)

    t = threading.Thread(target=victim)
    t0 = time.monotonic()
    t.start()
    try:
        time.sleep(0.4)                    # let the fan-out start + stall
        assert cluster.graphds[0].engine.kill_running(), \
            "no running query to kill"
        t.join(timeout=5.0)
        elapsed = time.monotonic() - t0
        assert not t.is_alive(), "statement did not return after kill"
    finally:
        fail.disarm("wal:pre_fsync")
        if t.is_alive():
            t.join()
    assert out["rs"].error is not None and "killed" in out["rs"].error, \
        out["rs"].error
    assert elapsed < 2.0, f"kill took {elapsed:.1f}s — rode out the stall"


def test_client_surfaces_clean_timeout_when_graphd_wedged(cluster,
                                                          clean_faults):
    """GraphClient satellite: a graphd that stops answering yields a
    clean E_QUERY_TIMEOUT result, not a raw RpcConnError traceback."""
    from nebula_tpu.cluster.client import GraphClient
    host, port = cluster.graph_addr.rsplit(":", 1)
    cl = GraphClient(host, int(port), timeout=1.0)
    cl.authenticate()
    state = {"fired": False}

    def decide(idx, key, _s=state):
        if _s["fired"] or key != "graph.execute":
            return None
        _s["fired"] = True
        return ("delay", 2.5)

    fail.arm_callable("rpc:server_dispatch", decide)
    try:
        t0 = time.monotonic()
        rs = cl.execute("YIELD 1 AS x")
        elapsed = time.monotonic() - t0
    finally:
        fail.disarm("rpc:server_dispatch")
        cl.close()
    assert rs.error is not None and \
        rs.error.startswith("E_QUERY_TIMEOUT"), rs.error
    assert 0.9 <= elapsed < 2.4


def test_client_honors_configured_statement_timeout():
    from nebula_tpu.cluster.client import (CLIENT_TIMEOUT_GRACE_S,
                                           GraphClient)
    get_config().set_dynamic("query_timeout_secs", 42.0)
    try:
        cl = GraphClient("127.0.0.1", 1)   # connects lazily — no I/O here
        assert cl.timeout == 42.0 + CLIENT_TIMEOUT_GRACE_S
        cl2 = GraphClient("127.0.0.1", 1, timeout=7.0)
        assert cl2.timeout == 7.0
    finally:
        get_config().set_dynamic("query_timeout_secs", 300.0)


def test_request_timeout_is_breaker_neutral(clean_faults):
    """A per-request timeout on an ALIVE connection carries no
    transport verdict: even `breaker_failure_threshold` consecutive
    slow requests must not trip the peer's circuit breaker (a slow-
    but-healthy follower must not get cut out of quorum)."""
    from nebula_tpu.cluster.rpc import (RpcClient, RpcConnError,
                                        RpcServer, breaker_for)

    srv = RpcServer()
    srv.register("t.echo", lambda p: (time.sleep(p.get("s", 0)) or
                                      p["x"]))
    srv.start()
    cl = RpcClient(srv.host, srv.port, timeout=5.0, retries=0)
    try:
        assert cl.call("t.echo", x=1) == 1          # conn warm
        for _ in range(6):                          # threshold is 5
            conn = cl._pick()
            with pytest.raises(RpcConnError, match="rpc timeout"):
                conn.request({"method": "t.echo",
                              "params": {"x": 3, "s": 1.0}}, 0.05)
        br = breaker_for(f"{srv.host}:{srv.port}")
        assert br.state == "closed", \
            f"slow requests tripped the breaker ({br.state})"
        assert cl.call("t.echo", x=2) == 2          # not short-circuited
    finally:
        srv.stop()


def test_kill_wakes_backoff_sleep(clean_faults):
    """KILL QUERY during a retry backoff sleep wakes it immediately —
    an unbudgeted statement (query_timeout_secs=0) must not ride out
    the full jittered backoff before noticing the kill."""
    from nebula_tpu.cluster.rpc import deadline_sleep
    from nebula_tpu.utils import cancel as _cancel

    kill = threading.Event()
    out = {}

    def sleeper():
        with _cancel.use_cancel(kill=kill):        # no deadline
            t0 = time.monotonic()
            deadline_sleep(5.0)
            out["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=sleeper)
    t.start()
    time.sleep(0.1)
    kill.set()
    t.join(timeout=3.0)
    assert not t.is_alive(), "backoff sleep ignored the kill event"
    assert out["elapsed"] < 1.0, \
        f"kill waited out the backoff: {out['elapsed']:.2f}s"


def test_remote_deadline_maps_to_deadline_exceeded(clean_faults):
    """A hop whose re-anchored budget expires FIRST replies with a
    deadline error; the RPC client maps it back to DeadlineExceeded so
    the engine boundary reports E_QUERY_TIMEOUT (and counts it)
    whichever side's clock wins the race."""
    from nebula_tpu.cluster.rpc import RpcClient, RpcServer
    from nebula_tpu.utils import cancel as _cancel

    def expired(p):
        with _cancel.use_cancel(deadline=time.monotonic() - 1.0):
            _cancel.check()                         # raises

    srv = RpcServer()
    srv.register("t.dl", expired)
    srv.start()
    cl = RpcClient(srv.host, srv.port, timeout=5.0, retries=0)
    try:
        with pytest.raises(_cancel.DeadlineExceeded):
            cl.call("t.dl")
    finally:
        srv.stop()


# -- fused TPU pipeline: kill between segments, dispatch-failure fallback ---


def _device_engines():
    from nebula_tpu.tpu import TpuRuntime, make_mesh
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_tpu import P, random_store
    st = random_store(3, n=120, avg_deg=5)
    rt = TpuRuntime(make_mesh(P))
    host = QueryEngine(st)
    hs = host.new_session()
    host.execute(hs, "USE g")
    dev = QueryEngine(st, tpu_runtime=rt)
    ds = dev.new_session()
    dev.execute(ds, "USE g")
    return host, hs, dev, ds


FUSED_QUERY = ("MATCH (a:person)-[:knows]->(b:person) WHERE id(a) IN "
               "[1,2,3] WITH DISTINCT b MATCH (b)-[:knows]->(c:person) "
               "RETURN id(b) AS x, id(c) AS y ORDER BY x, y")


def test_kill_query_between_pipeline_segments(clean_faults):
    """ISSUE 5 satellite: a kill DURING a fused pipeline takes effect
    at the next segment boundary — the statement dies, it does NOT
    fall back to the row plane and keep running."""
    host, hs, dev, ds = _device_engines()
    fired = {"n": 0}

    def decide(idx, key, _f=fired):
        # the decision runs ON the query thread mid-pipeline: set the
        # statement's kill event, fire nothing — the next segment
        # boundary's check must do the killing
        _f["n"] += 1
        dev.kill_running()
        return None

    fail.arm_callable("tpu:dispatch", decide)
    r = dev.execute(ds, FUSED_QUERY)
    assert fired["n"] >= 1, "pipeline never dispatched — nothing proven"
    assert r.error is not None and "killed" in r.error, r.error


def test_device_dispatch_failure_falls_back_to_host_rows(clean_faults):
    """Chaos schedule 5's unit form: an injected device-dispatch
    failure must produce the host plane's exact rows via the stashed
    subplan — never wrong, only absent."""
    host, hs, dev, ds = _device_engines()
    expect = host.execute(hs, FUSED_QUERY)
    assert expect.error is None
    before = stats().snapshot().get(
        "match_pipeline_fallback{reason=runtime:FailpointError,"
        "stage=execute}", 0)
    fail.arm("tpu:dispatch", "-1*raise(injected dispatch failure)")
    r = dev.execute(ds, FUSED_QUERY)
    assert r.error is None, r.error
    assert r.data.rows == expect.data.rows
    after = stats().snapshot().get(
        "match_pipeline_fallback{reason=runtime:FailpointError,"
        "stage=execute}", 0)
    assert after == before + 1
