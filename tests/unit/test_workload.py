"""Live workload plane (ISSUE 9): SHOW QUERIES/SESSIONS with live
per-operator progress, the stall watchdog (ring + forced flight
capture + /stalls), concurrent per-statement attribution (CostRecorder
/ flight entries / live rows, including under KILL QUERY), and the
federated /queries surface."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from nebula_tpu.cluster.webservice import WebService
from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.failpoints import fail
from nebula_tpu.utils.flight import flight_recorder
from nebula_tpu.utils.stats import WorkCounters, use_work
from nebula_tpu.utils.workload import (LiveQuery, StallWatchdog,
                                       dispatch_table, live_registry,
                                       stall_watchdog)


@pytest.fixture()
def clean():
    fail.reset()
    stall_watchdog().clear()
    yield
    fail.reset()
    stall_watchdog().clear()
    for k in ("stall_threshold_secs", "workload_plane_enabled",
              "flight_sample_rate", "stall_default_secs"):
        get_config().dynamic_layer.pop(k, None)


def small_engine(n=30, deg=3):
    eng = QueryEngine()
    s = eng.new_session()
    for q in ("CREATE SPACE wl(partition_num=2, vid_type=INT64)",
              "USE wl", "CREATE TAG P(x int)", "CREATE EDGE E(w int)"):
        r = eng.execute(s, q)
        assert r.error is None, f"{q} -> {r.error}"
    vals = ", ".join(f"{v}:({v})" for v in range(n))
    assert eng.execute(s, f"INSERT VERTEX P(x) VALUES {vals}").ok
    edges = ", ".join(f"{v}->{(v * k + 1) % n}:({v + k})"
                      for v in range(n) for k in range(1, deg + 1))
    assert eng.execute(s, f"INSERT EDGE E(w) VALUES {edges}").ok
    return eng, s


def _delay_nodes(kind, secs):
    """Delay only plan nodes of `kind` (GO plans carry ExpandAll; SHOW
    / KILL statements don't), so probing statements run undelayed."""
    fail.arm_callable(
        "exec:node",
        lambda i, key: ("delay", secs) if key == kind else None)


def _run_async(eng, sess, stmt):
    box = {}

    def run():
        box["rs"] = eng.execute(sess, stmt)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, box


def _wait_for(pred, timeout=5.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        got = pred()
        if got:
            return got
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {msg}")


# -- live progress ----------------------------------------------------------


def test_show_queries_live_progress(clean):
    """A second session sees the in-flight statement's current plan
    node, live duration and status — and the row disappears once the
    statement completes."""
    eng, s = small_engine()
    _delay_nodes("ExpandAll", 0.1)
    t, box = _run_async(eng, s, "GO 2 STEPS FROM 1 OVER E "
                                "YIELD dst(edge) AS d")
    s2 = eng.new_session()
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[3].startswith("GO 2 STEPS")), None),
        msg="GO statement in SHOW QUERIES")
    sid, qid, user, text, status, operator = row[:6]
    assert sid == s.id and status == "RUNNING" and user == "root"
    assert operator, "no live operator reported"
    assert row[7] > 0, "duration_us must be live"
    # the SHOW QUERIES statement surface carries the same row
    rs = eng.execute(s2, "SHOW QUERIES")
    assert rs.ok
    assert rs.data.column_names[:8] == [
        "SessionId", "ExecutionPlanId", "User", "Query", "Status",
        "Operator", "Rows", "DurationUs"]
    t.join(10)
    fail.reset()
    assert box["rs"].error is None
    assert not any(r[3].startswith("GO 2 STEPS")
                   for r in eng.list_running_queries())
    assert live_registry().get(qid) is None


def test_kill_query_lands_and_flight_records_killed(clean):
    eng, s = small_engine()
    _delay_nodes("ExpandAll", 0.1)
    t, box = _run_async(eng, s, "GO 3 STEPS FROM 2 OVER E "
                                "YIELD dst(edge) AS d")
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[3].startswith("GO 3 STEPS")), None),
        msg="victim in SHOW QUERIES")
    qid = row[1]
    s2 = eng.new_session()
    rs = eng.execute(s2, f"KILL QUERY (session={s.id}, plan={qid})")
    assert rs.error is None, rs.error
    # between the kill event and the next cancellation check the live
    # row reports KILLED (the victim is draining, not gone)
    lq = live_registry().get(qid)
    if lq is not None:
        assert lq.snapshot()["status"] == "KILLED"
    t.join(10)
    fail.reset()
    assert box["rs"].error == "ExecutionError: query was killed"
    ent = next(e for e in flight_recorder().list(limit=20)
               if e["stmt"].startswith("GO 3 STEPS"))
    assert ent["status"] == "killed"


def test_show_sessions_live_columns(clean):
    eng, s = small_engine()
    rs = eng.execute(s, "SHOW SESSIONS")
    assert rs.ok
    assert rs.data.column_names == [
        "SessionId", "UserName", "SpaceName", "CreateTime",
        "UpdateTime", "ActiveQueries", "GraphAddr"]
    mine = next(r for r in rs.data.rows if r[0] == s.id)
    assert mine[1] == "root" and mine[2] == "wl"
    assert mine[3] > 0 and mine[4] >= mine[3]
    # the probing session is itself mid-execute: one active query
    assert mine[5] == 1


def test_workload_plane_disabled_registers_nothing(clean):
    get_config().set_dynamic("workload_plane_enabled", False)
    eng, s = small_engine()
    _delay_nodes("ExpandAll", 0.1)
    t, box = _run_async(eng, s, "GO FROM 1 OVER E YIELD dst(edge)")
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[3].startswith("GO FROM 1")), None),
        msg="row with plane disabled")
    # identity columns still served; live columns blank
    assert row[4] == "RUNNING" and row[5] == "" and row[7] == 0
    assert live_registry().get(row[1]) is None
    t.join(10)
    fail.reset()
    assert box["rs"].error is None


# -- stall watchdog ---------------------------------------------------------


def test_stall_watchdog_statement_capture(clean):
    """A statement stuck past its threshold yields exactly ONE capture:
    thread stacks + dispatch table + kernel-ledger tail + live rows in
    the ring, a forced flight-recorder entry, SHOW STALLS row."""
    eng, s = small_engine()
    get_config().set_dynamic("stall_threshold_secs", 0.05)
    _delay_nodes("ExpandAll", 0.4)
    t, box = _run_async(eng, s, "GO 2 STEPS FROM 3 OVER E "
                                "YIELD dst(edge) AS d")
    _wait_for(lambda: len(live_registry()) > 0, msg="registration")
    time.sleep(0.15)
    # assert on RING CONTENTS, not scan_once()'s return: the engine's
    # background watchdog thread may legitimately win the capture race
    # — the contract is "captured exactly once", by whoever scans first
    stall_watchdog().scan_once()
    stmts = [e for e in stall_watchdog().list()
             if e["kind"] == "statement"]
    assert len(stmts) == 1, stmts
    # rescan: STILL exactly one capture (no duplicates)
    stall_watchdog().scan_once()
    stmts = [e for e in stall_watchdog().list()
             if e["kind"] == "statement"]
    assert len(stmts) == 1, stmts
    summ = stmts[0]
    assert summ["subject"]["stmt"].startswith("GO 2 STEPS")
    full = stall_watchdog().get(summ["id"])
    assert full["stacks"], "no thread stacks captured"
    assert any("delay" in ln or "sleep" in ln
               for frames in full["stacks"].values() for ln in frames), \
        "stacks must show the stalled frame"
    assert isinstance(full["dispatches"], list)
    assert isinstance(full["kernels"], list)
    assert full["live"] and full["live"][0]["stmt"].startswith("GO 2")
    # forced flight capture of the still-running statement
    ent = next(e for e in flight_recorder().list(limit=20)
               if e["status"] == "stalled")
    assert ent["stmt"].startswith("GO 2 STEPS")
    # SHOW STALLS surfaces the ring
    t.join(10)
    fail.reset()
    rs = eng.execute(s, "SHOW STALLS")
    assert rs.ok and rs.data.rows
    assert rs.data.rows[0][1] == "statement"
    # statement itself completed unharmed — pure observation
    assert box["rs"].error is None


def test_stall_watchdog_dispatch_capture(clean):
    """A device dispatch stuck in the table (queued or running) past
    the threshold is captured as kind=dispatch."""
    get_config().set_dynamic("stall_threshold_secs", 0.02)
    tok = dispatch_table().enter("traverse")
    try:
        time.sleep(0.05)
        stall_watchdog().scan_once()
        disp = [e for e in stall_watchdog().list()
                if e["kind"] == "dispatch"]
        assert len(disp) == 1, disp
        summ = disp[0]
        assert summ["subject"]["kernel"] == "traverse"
        assert summ["subject"]["state"] == "queued"
        # rescan while still in flight: no duplicate capture
        stall_watchdog().scan_once()
        assert len([e for e in stall_watchdog().list()
                    if e["kind"] == "dispatch"]) == 1
    finally:
        dispatch_table().exit(tok)


def test_stall_threshold_derivation(clean):
    """stall_threshold_secs=0 derives the threshold from the deadline
    budget (stall_deadline_fraction); unbudgeted statements use
    stall_default_secs; a flat threshold overrides both."""
    lq = LiveQuery(qid=1, session=1, user="u", stmt="x", kind="Go",
                   deadline=time.monotonic() + 10.0)
    thr = StallWatchdog.stmt_threshold_s(lq)
    assert 4.0 < thr < 6.0          # 0.5 × ~10 s budget
    lq2 = LiveQuery(qid=2, session=1, user="u", stmt="x", kind="Go")
    assert StallWatchdog.stmt_threshold_s(lq2) == pytest.approx(20.0)
    get_config().set_dynamic("stall_threshold_secs", 0.25)
    assert StallWatchdog.stmt_threshold_s(lq) == pytest.approx(0.25)
    assert StallWatchdog.stmt_threshold_s(lq2) == pytest.approx(0.25)


# -- concurrent attribution -------------------------------------------------


GO_TMPL = "GO 2 STEPS FROM {seed} OVER E YIELD dst(edge) AS d"


def _sequential_truth(eng, seeds):
    truth = {}
    for seed in seeds:
        s = eng.new_session()
        eng.execute(s, "USE wl")
        wc = WorkCounters()
        with use_work(wc):
            rs = eng.execute(s, GO_TMPL.format(seed=seed))
        assert rs.error is None
        truth[seed] = (sorted(map(repr, rs.data.rows)), wc.as_dict())
    return truth


def test_concurrent_attribution_no_bleed(clean):
    """N statements running simultaneously keep flight-recorder
    entries, work counters and rows strictly per-statement: each
    concurrent run's rows and deterministic work counts equal its own
    sequential run — no cross-query bleed (ISSUE 9 satellite)."""
    eng, _ = small_engine(n=40, deg=4)
    seeds = [1, 2, 3, 5, 7, 11]
    truth = _sequential_truth(eng, seeds)
    flight_recorder().clear()
    get_config().set_dynamic("flight_sample_rate", 1.0)

    results = {}
    counters = {}

    def run(seed):
        s = eng.new_session()
        eng.execute(s, "USE wl")
        wc = WorkCounters()
        with use_work(wc):
            rs = eng.execute(s, GO_TMPL.format(seed=seed))
        results[seed] = rs
        counters[seed] = wc.as_dict()

    ts = [threading.Thread(target=run, args=(seed,), daemon=True)
          for seed in seeds]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    for seed in seeds:
        rs = results[seed]
        assert rs.error is None, rs.error
        rows, work = truth[seed]
        assert sorted(map(repr, rs.data.rows)) == rows, \
            f"seed {seed}: rows bled across concurrent statements"
        assert counters[seed] == work, \
            f"seed {seed}: work counters bled across statements"
    # every concurrent statement left its OWN flight entry (rate 1.0),
    # whose recorded work matches the sequential truth
    ents = flight_recorder().list(limit=100)
    for seed in seeds:
        stmt = GO_TMPL.format(seed=seed)
        ent = next(e for e in ents if e["stmt"] == stmt[:120])
        full = flight_recorder().get(ent["id"])
        assert full["work"]["edges_traversed"] == \
            truth[seed][1]["edges_traversed"], \
            f"seed {seed}: flight work attribution bled"
        assert full["operators"], "per-operator breakdown missing"


def test_concurrent_attribution_under_kill(clean):
    """A KILL QUERY on one of N concurrent statements takes down only
    the victim: survivors' rows/attribution stay exact, the victim's
    flight entry is `killed`."""
    eng, _ = small_engine(n=40, deg=4)
    seeds = [2, 3, 5]
    truth = _sequential_truth(eng, seeds)
    flight_recorder().clear()
    get_config().set_dynamic("flight_sample_rate", 1.0)
    # only the victim's statement shape is delayed: survivors run clean
    victim_sess = eng.new_session()
    eng.execute(victim_sess, "USE wl")
    # every ExpandAll (victim AND survivors) is delayed — the victim
    # stays killable, the survivors' work counters are time-immune
    _delay_nodes("ExpandAll", 0.1)
    t_victim, box = _run_async(eng, victim_sess,
                               "GO 3 STEPS FROM 13 OVER E "
                               "YIELD dst(edge) AS d")
    row = _wait_for(
        lambda: next((r for r in eng.list_running_queries()
                      if r[3].startswith("GO 3 STEPS")), None),
        msg="victim visible")

    results = {}

    def run(seed):
        s = eng.new_session()
        eng.execute(s, "USE wl")
        results[seed] = eng.execute(s, GO_TMPL.format(seed=seed))

    ts = [threading.Thread(target=run, args=(seed,), daemon=True)
          for seed in seeds]
    for t in ts:
        t.start()
    killer = eng.new_session()
    rs = eng.execute(killer,
                     f"KILL QUERY (session={victim_sess.id}, "
                     f"plan={row[1]})")
    assert rs.error is None, rs.error
    for t in ts:
        t.join(30)
    t_victim.join(30)
    fail.reset()
    assert box["rs"].error == "ExecutionError: query was killed"
    for seed in seeds:
        assert results[seed].error is None
        assert sorted(map(repr, results[seed].data.rows)) == \
            truth[seed][0], f"survivor {seed} corrupted by the kill"
    ent = next(e for e in flight_recorder().list(limit=100)
               if e["stmt"].startswith("GO 3 STEPS"))
    assert ent["status"] == "killed"


def test_cluster_show_queries_live_and_kill(clean, tmp_path):
    """The acceptance shape (ISSUE 9) on a live cluster: SHOW QUERIES
    from a second session shows the in-flight statement's current
    operator and live duration/queue/device/host µs columns; KILL
    QUERY on it lands."""
    from nebula_tpu.cluster.launcher import LocalCluster

    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        cl = c.client()
        assert cl.execute("CREATE SPACE cw(partition_num=2, "
                          "vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ("USE cw", "CREATE TAG P(x int)",
                  "CREATE EDGE E(w int)"):
            assert cl.execute(q).error is None, q
        verts = ", ".join(f"{v}:({v})" for v in range(20))
        assert cl.execute(
            f"INSERT VERTEX P(x) VALUES {verts}").error is None
        edges = ", ".join(f"{v}->{(v + 1) % 20}:({v})"
                          for v in range(20))
        assert cl.execute(
            f"INSERT EDGE E(w) VALUES {edges}").error is None
        _delay_nodes("ExpandAll", 0.15)
        cl2 = c.client()
        cl2.execute("USE cw")
        box = {}
        t = threading.Thread(
            target=lambda: box.update(rs=cl.execute(
                "GO 3 STEPS FROM 1 OVER E YIELD dst(edge) AS d")),
            daemon=True)
        t.start()

        def probe():
            rs = cl2.execute("SHOW QUERIES")
            assert rs.error is None, rs.error
            return next((r for r in rs.data.rows
                         if str(r[3]).startswith("GO 3 STEPS")), None)

        row = _wait_for(probe, timeout=10.0,
                        msg="in-flight row via cluster SHOW QUERIES")
        # [sid, qid, user, text, status, operator, rows, duration_us,
        #  queue_us, device_us, host_us, memory_bytes, graph_addr]
        assert row[4] == "RUNNING"
        assert row[5], "no live operator over the cluster fan-out"
        assert row[7] > 0 and row[10] >= 0
        rs = cl2.execute(f"KILL QUERY (session={row[0]}, "
                         f"plan={row[1]})")
        assert rs.error is None, rs.error
        t.join(15)
        fail.reset()
        assert box["rs"].error == "ExecutionError: query was killed"
    finally:
        c.stop()


# -- batched attribution (ISSUE 15) -----------------------------------------


def test_batched_attribution_mixed_go_match(clean):
    """N concurrent mixed GO/MATCH statements with multi-lane batching
    ON produce rows byte-identical to batching OFF and to sequential
    truth, with exact per-statement WorkCounters and per-statement
    flight entries (the PR 7 attribution contract survives shared
    launches)."""
    pytest.importorskip("nebula_tpu.tpu")
    import random

    from nebula_tpu.graphstore.schema import PropDef, PropType
    from nebula_tpu.graphstore.store import GraphStore
    from nebula_tpu.tpu import TpuRuntime, make_mesh
    from nebula_tpu.tpu.batch import batch_former

    rng = random.Random(5)
    st = GraphStore()
    st.create_space("bw", partition_num=4, vid_type="INT64")
    st.catalog.create_tag("bw", "P", [PropDef("x", PropType.INT64)])
    st.catalog.create_edge("bw", "E", [PropDef("w", PropType.INT64)])
    for v in range(50):
        st.insert_vertex("bw", v, "P", {"x": v})
    for v in range(50):
        for _ in range(4):
            st.insert_edge("bw", v, "E", rng.randrange(50), 0, {"w": v})
    rt = TpuRuntime(make_mesh(1))
    eng = QueryEngine(st, tpu_runtime=rt)
    s0 = eng.new_session()
    assert eng.execute(s0, "USE bw").error is None

    def stmt_of(seed):
        if seed % 2:
            return (f"MATCH (a:P)-[e:E]->(b) WHERE id(a) == {seed} "
                    f"RETURN id(b)")
        return f"GO 2 STEPS FROM {seed} OVER E YIELD dst(edge) AS d"

    seeds = [1, 2, 3, 4, 5, 6]

    def run_set(concurrent: bool):
        results = {}

        def one(seed):
            s = eng.new_session()
            eng.execute(s, "USE bw")
            wc = WorkCounters()
            with use_work(wc):
                rs = eng.execute(s, stmt_of(seed))
            results[seed] = (rs, wc.as_dict())

        if concurrent:
            ts = [threading.Thread(target=one, args=(sd,), daemon=True)
                  for sd in seeds]
            for t in ts:
                t.start()
            for t in ts:
                t.join(60)
        else:
            for sd in seeds:
                one(sd)
        for sd in seeds:
            assert results[sd][0].error is None, results[sd][0].error
        return {sd: (sorted(map(repr, results[sd][0].data.rows)),
                     results[sd][1]) for sd in seeds}

    truth = run_set(concurrent=False)            # sequential, batching off
    off = run_set(concurrent=True)               # concurrent, batching off
    get_config().set_dynamic_many({"batch_max_lanes": 8,
                                   "batch_wait_us": 300_000})
    flight_recorder().clear()
    get_config().set_dynamic("flight_sample_rate", 1.0)
    try:
        on = run_set(concurrent=True)            # concurrent, batching ON
    finally:
        for k in ("batch_max_lanes", "batch_wait_us"):
            get_config().dynamic_layer.pop(k, None)
        batch_former().reset()
    for sd in seeds:
        assert on[sd][0] == truth[sd][0] == off[sd][0], \
            f"seed {sd}: rows differ across batching modes"
        assert on[sd][1] == truth[sd][1] == off[sd][1], \
            f"seed {sd}: work counters differ across batching modes"
    # every statement kept its OWN flight entry with its own work
    ents = flight_recorder().list(limit=100)
    for sd in seeds:
        stmt = stmt_of(sd)
        ent = next(e for e in ents if e["stmt"] == stmt[:120])
        full = flight_recorder().get(ent["id"])
        assert full["work"]["edges_traversed"] == \
            truth[sd][1]["edges_traversed"], \
            f"seed {sd}: flight work attribution bled across lanes"


# -- HTTP surfaces ----------------------------------------------------------


def test_queries_and_stalls_endpoints(clean):
    eng, s = small_engine()
    get_config().set_dynamic("stall_threshold_secs", 0.05)
    ws = WebService(role="graphd")
    ws.start()
    try:
        base = f"http://{ws.addr}"
        _delay_nodes("ExpandAll", 0.3)
        t, box = _run_async(eng, s, "GO 2 STEPS FROM 1 OVER E "
                                    "YIELD dst(edge) AS d")
        _wait_for(lambda: len(live_registry()) > 0, msg="registration")
        got = json.loads(urllib.request.urlopen(
            base + "/queries").read())
        assert got["queries"] and \
            got["queries"][0]["stmt"].startswith("GO 2 STEPS")
        assert got["queries"][0]["operator"]
        assert "dispatches" in got
        time.sleep(0.1)
        stall_watchdog().scan_once()
        stalls = json.loads(urllib.request.urlopen(
            base + "/stalls").read())
        assert stalls and stalls[0]["kind"] == "statement"
        full = json.loads(urllib.request.urlopen(
            base + f"/stalls?id={stalls[0]['id']}").read())
        assert full["stacks"]
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/stalls?id=99999")
        t.join(10)
        fail.reset()
        assert box["rs"].error is None
        got = json.loads(urllib.request.urlopen(
            base + "/queries").read())
        assert got["queries"] == []
    finally:
        ws.stop()


def test_federated_cluster_queries(clean):
    """metad's /cluster_queries view: the federator fans /queries out
    over the heartbeat-alive daemons and labels each instance."""
    from nebula_tpu.cluster.federation import MetricFederator

    eng, s = small_engine()
    ws = WebService(role="graphd")
    ws.start()
    try:
        class _Meta:
            my_addr = "meta:1"
            active_hosts = {"g1:9669": {"ws": ws.addr, "role": "graph",
                                        "last_hb": time.monotonic()}}

        fed = MetricFederator(_Meta(), self_ws="")
        _delay_nodes("ExpandAll", 0.3)
        t, box = _run_async(eng, s, "GO 2 STEPS FROM 1 OVER E "
                                    "YIELD dst(edge) AS d")
        _wait_for(lambda: len(live_registry()) > 0, msg="registration")
        got = fed.cluster_queries()
        assert got["g1:9669"]["ok"] and \
            got["g1:9669"]["role"] == "graphd"
        assert got["g1:9669"]["queries"][0]["stmt"].startswith("GO 2")
        t.join(10)
        fail.reset()
        assert box["rs"].error is None
    finally:
        ws.stop()
