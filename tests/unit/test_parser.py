"""Parser tests: statement → AST round-trips + error cases."""
import pytest

from nebula_tpu.core.expr import (AggExpr, AttributeExpr, Binary, InputProp,
                                  LabelExpr, Literal, SrcProp, to_text)
from nebula_tpu.query import ast as A
from nebula_tpu.query.parser import ParseError, parse


def test_go_basic():
    s = parse('GO FROM "a" OVER knows')
    assert isinstance(s, A.GoSentence)
    assert s.steps.m == 1 and s.steps.n == 1
    assert s.over.edges == ["knows"]
    assert s.from_.vids[0].value == "a"


def test_go_full():
    s = parse('GO 2 TO 4 STEPS FROM "a","b" OVER knows, likes REVERSELY '
              'WHERE knows.since > 2010 YIELD DISTINCT dst(edge) AS d, $$.person.age')
    assert s.steps.m == 2 and s.steps.n == 4
    assert s.over.direction == "in"
    assert s.over.edges == ["knows", "likes"]
    assert len(s.from_.vids) == 2
    assert s.yield_.distinct
    assert s.yield_.columns[0].alias == "d"
    assert to_text(s.where.filter) == "(knows.since > 2010)"


def test_go_over_star_pipe():
    s = parse('GO FROM "a" OVER * YIELD dst(edge) AS d | GO FROM $-.d OVER knows')
    assert isinstance(s, A.PipedSentence)
    assert s.left.over.is_all
    assert s.right.from_.ref is not None
    assert isinstance(s.right.from_.ref, InputProp)


def test_assignment_and_seq():
    s = parse('$var = GO FROM "a" OVER e YIELD dst(edge) AS d; YIELD $var.d')
    assert isinstance(s, A.SeqSentence)
    assert isinstance(s.stmts[0], A.AssignSentence)
    assert s.stmts[0].var == "var"


def test_ddl_space():
    s = parse("CREATE SPACE IF NOT EXISTS s1 (partition_num=4, replica_factor=1, "
              "vid_type=FIXED_STRING(20))")
    assert isinstance(s, A.CreateSpaceSentence)
    assert s.if_not_exists and s.partition_num == 4
    assert s.vid_type == "FIXED_STRING(20)"
    s2 = parse("DROP SPACE IF EXISTS s1")
    assert s2.if_exists


def test_ddl_tag():
    s = parse('CREATE TAG person(name string, age int64 NOT NULL DEFAULT 18, '
              'score double NULL)')
    assert isinstance(s, A.CreateSchemaSentence)
    assert not s.is_edge
    assert [p.name for p in s.props] == ["name", "age", "score"]
    assert s.props[1].nullable is False
    assert s.props[1].default.value == 18


def test_ddl_edge_and_index():
    s = parse("CREATE EDGE knows(since int64)")
    assert s.is_edge
    s2 = parse("CREATE TAG INDEX idx_name ON person(name)")
    assert isinstance(s2, A.CreateIndexSentence)
    assert s2.fields == ["name"]
    s3 = parse("REBUILD TAG INDEX idx_name")
    assert isinstance(s3, A.RebuildIndexSentence)


def test_alter():
    s = parse("ALTER TAG person ADD (city string), DROP (score)")
    assert s.adds[0].name == "city"
    assert s.drops == ["score"]


def test_insert_vertex():
    s = parse('INSERT VERTEX person(name, age) VALUES "a":("Ann", 30), "b":("Bob", 25)')
    assert isinstance(s, A.InsertVerticesSentence)
    assert len(s.rows) == 2
    assert s.rows[0].vid.value == "a"
    assert s.rows[1].values[1].value == 25


def test_insert_edge():
    s = parse('INSERT EDGE knows(since) VALUES "a"->"b"@3:(2010)')
    assert isinstance(s, A.InsertEdgesSentence)
    assert s.rows[0].rank == 3


def test_update_upsert():
    s = parse('UPDATE VERTEX ON person "a" SET age = age + 1 WHEN age > 10 YIELD name')
    assert isinstance(s, A.UpdateSentence)
    assert not s.insertable and s.when is not None
    s2 = parse('UPSERT EDGE ON knows "a"->"b" SET since = 2020')
    assert s2.insertable and s2.edge_key.rank == 0


def test_delete():
    s = parse('DELETE VERTEX "a", "b" WITH EDGE')
    assert isinstance(s, A.DeleteVerticesSentence) and s.with_edge
    s2 = parse('DELETE EDGE knows "a"->"b"@0, "b"->"c"')
    assert len(s2.keys) == 2
    s3 = parse('DELETE TAG person FROM "a"')
    assert s3.tags == ["person"]


def test_fetch():
    s = parse('FETCH PROP ON person "a", "b" YIELD properties(vertex)')
    assert isinstance(s, A.FetchVerticesSentence)
    assert s.tags == ["person"]
    s2 = parse('FETCH PROP ON * "a"')
    assert s2.tags == []
    s3 = parse('FETCH PROP ON knows "a"->"b" YIELD properties(edge)')
    assert isinstance(s3, A.FetchEdgesSentence)


def test_lookup():
    s = parse('LOOKUP ON person WHERE person.age > 20 YIELD id(vertex) AS id')
    assert isinstance(s, A.LookupSentence)
    assert s.schema_name == "person"


def test_match_basic():
    s = parse('MATCH (v:person{name:"Ann"})-[e:knows]->(v2) RETURN v2.person.age AS age')
    assert isinstance(s, A.MatchSentence)
    mc = s.clauses[0]
    assert isinstance(mc, A.MatchClauseAst)
    pat = mc.patterns[0]
    assert len(pat.nodes) == 2 and len(pat.edges) == 1
    assert pat.nodes[0].labels[0][0] == "person"
    assert pat.edges[0].types == ["knows"]
    assert pat.edges[0].direction == "out"


def test_match_varlen_and_direction():
    s = parse("MATCH p = (a)-[e:knows*1..3]->(b) WHERE id(a) == \"x\" "
              "RETURN p ORDER BY id(b) SKIP 1 LIMIT 5")
    pat = s.clauses[0].patterns[0]
    assert pat.alias == "p"
    assert pat.edges[0].min_hop == 1 and pat.edges[0].max_hop == 3
    assert s.return_.skip == 1 and s.return_.limit == 5
    s2 = parse("MATCH (a)<-[:knows]-(b) RETURN b")
    assert s2.clauses[0].patterns[0].edges[0].direction == "in"
    s3 = parse("MATCH (a)-[]-(b) RETURN b")
    assert s3.clauses[0].patterns[0].edges[0].direction == "both"


def test_match_with_unwind():
    s = parse("MATCH (v:person) WITH v.person.age AS age WHERE age > 10 "
              "UNWIND [1,2,3] AS x RETURN age, x")
    kinds = [type(c).__name__ for c in s.clauses]
    assert kinds == ["MatchClauseAst", "WithClauseAst", "UnwindClauseAst"]


def test_find_path():
    s = parse('FIND SHORTEST PATH FROM "a" TO "b" OVER * UPTO 4 STEPS YIELD path AS p')
    assert isinstance(s, A.FindPathSentence)
    assert s.kind == "shortest" and s.upto == 4
    s2 = parse('FIND ALL PATH WITH PROP FROM "a" TO "b","c" OVER knows')
    assert s2.kind == "all" and s2.with_prop


def test_subgraph():
    s = parse('GET SUBGRAPH WITH PROP 2 STEPS FROM "a" BOTH knows '
              'YIELD VERTICES AS nodes, EDGES AS relationships')
    assert isinstance(s, A.SubgraphSentence)
    assert s.steps == 2 and s.both_edges == ["knows"]


def test_yield_group_order_limit():
    s = parse('GO FROM "a" OVER e YIELD dst(edge) AS d, 1 AS one '
              '| GROUP BY $-.d YIELD $-.d, count(*) AS c '
              '| ORDER BY $-.c DESC | LIMIT 3, 10')
    seg = s
    assert isinstance(seg, A.PipedSentence)
    assert isinstance(seg.right, A.LimitSentence)
    assert seg.right.offset == 3 and seg.right.count == 10
    ob = seg.left.right
    assert isinstance(ob, A.OrderBySentence)
    assert not ob.factors[0].ascending
    gb = seg.left.left.right
    assert isinstance(gb, A.GroupBySentence)
    assert isinstance(gb.yield_.columns[1].expr, AggExpr)


def test_union():
    s = parse('GO FROM "a" OVER e UNION ALL GO FROM "b" OVER e')
    assert isinstance(s, A.SetOpSentence)
    assert s.op == "UNION ALL"


def test_explain_profile():
    s = parse('EXPLAIN GO FROM "a" OVER e')
    assert isinstance(s, A.ExplainSentence) and not s.profile
    s2 = parse('PROFILE GO FROM "a" OVER e')
    assert s2.profile


def test_show_describe():
    assert parse("SHOW SPACES").kind == "spaces"
    assert parse("SHOW TAGS").kind == "tags"
    assert parse("SHOW HOSTS").kind == "hosts"
    d = parse("DESCRIBE TAG person")
    assert d.kind == "tag" and d.name == "person"


def test_use():
    assert parse("USE nba").space == "nba"


def test_expr_precedence():
    s = parse("YIELD 1 + 2 * 3 == 7 AND NOT false AS x")
    e = s.yield_.columns[0].expr
    assert e.eval.__self__ is not None
    from nebula_tpu.core.expr import DictContext
    assert e.eval(DictContext()) is True


def test_complex_exprs():
    from nebula_tpu.core.expr import DictContext
    s = parse('YIELD [x IN range(1,5) WHERE x % 2 == 0 | x * 10] AS l, '
              'CASE WHEN 1 > 2 THEN "a" ELSE "b" END AS c, '
              'reduce(acc = 0, x IN [1,2,3] | acc + x) AS r')
    ctx = DictContext()
    cols = s.yield_.columns
    assert cols[0].expr.eval(ctx) == [20, 40]
    assert cols[1].expr.eval(ctx) == "b"
    assert cols[2].expr.eval(ctx) == 6


def test_errors():
    with pytest.raises(ParseError):
        parse("GO FROM")
    with pytest.raises(ParseError):
        parse("FROB 1")
    with pytest.raises(ParseError):
        parse('MATCH (a)-[e]->(b)')  # no RETURN
    with pytest.raises(ParseError):
        parse('GO FROM "a" OVER e YIELD')


def test_backquote_and_comments():
    s = parse('GO FROM "a" OVER `order` /* hi */ YIELD dst(edge) # trailing')
    assert s.over.edges == ["order"]


def test_src_dst_prop():
    s = parse('GO FROM "a" OVER e WHERE $^.person.age > $$.person.age')
    f = s.where.filter
    assert isinstance(f.lhs, SrcProp)
    assert to_text(f) == "($^.person.age > $$.person.age)"


def test_host_literal_and_zone_spellings():
    """Reference grammar spellings: "host":port two-token literals,
    quoted zone names, optional [INTO [NEW] ZONE], DIVIDE ZONE."""
    s = parse('ADD HOSTS "h1":9779, "h2:9779"')
    assert s.hosts == ["h1:9779", "h2:9779"] and s.zone == "default"
    s = parse('ADD HOSTS "h1":9779 INTO NEW ZONE "z1"')
    assert s.hosts == ["h1:9779"] and s.zone == "z1"
    s = parse('DROP HOSTS "h1":9779, "h2":9780')
    assert s.hosts == ["h1:9779", "h2:9780"]
    s = parse('DIVIDE ZONE "z" INTO "a" ("h1":1) "b" ("h2":2, "h3":3)')
    assert s.zone == "z"
    assert s.parts == [("a", ["h1:1"]), ("b", ["h2:2", "h3:3"])]
    s = parse('MERGE ZONE "a", b INTO "c"')
    assert s.zones == ["a", "b"] and s.into == "c"
    with pytest.raises(ParseError):
        parse('DIVIDE ZONE "z" INTO "a" ("h1":1)')   # needs >= 2 targets


def test_show_scope_spellings():
    for q, extra in [("SHOW LOCAL SESSIONS", "local"),
                     ("SHOW ALL SESSIONS", None),
                     ("SHOW LOCAL QUERIES", "local"),
                     ("SHOW ALL QUERIES", None)]:
        s = parse(q)
        assert s.kind in ("sessions", "queries") and s.extra == extra, q


def test_standalone_return():
    """RETURN as a statement head (VERDICT r4 item 3): MatchSentence with
    zero clauses; composes with UNION via the normal set-op grammar."""
    s = parse("RETURN 1 AS x, 2 + 3 AS y")
    assert isinstance(s, A.MatchSentence) and s.clauses == []
    assert [c.alias for c in s.return_.columns] == ["x", "y"]
    s = parse("RETURN 1 AS x UNION RETURN 2 AS x")
    assert isinstance(s, A.SetOpSentence)
    assert isinstance(s.left, A.MatchSentence) and s.left.clauses == []
    assert isinstance(s.right, A.MatchSentence)
    s = parse("RETURN DISTINCT 1 AS x ORDER BY x LIMIT 1")
    assert s.return_.distinct and s.return_.limit == 1


def test_pattern_predicate_parse():
    """(a)-[:knows]->() in expression position is a PatternPredExpr;
    parenthesized arithmetic backtracks to the expression read."""
    from nebula_tpu.core.expr import PatternPredExpr, Unary
    s = parse("MATCH (a:person) WHERE (a)-[:knows]->() RETURN id(a)")
    w = s.clauses[0].where
    assert isinstance(w, PatternPredExpr)
    assert w.text == "(a)-[:knows]->()"
    assert len(w.pattern.nodes) == 2 and len(w.pattern.edges) == 1
    # negated + incoming + var-len + both-direction spellings
    s = parse("MATCH (a) WHERE NOT (a)<-[:likes]-() RETURN id(a)")
    w = s.clauses[0].where
    assert isinstance(w, Unary) and w.op == "NOT"
    assert isinstance(w.operand, PatternPredExpr)
    assert w.operand.pattern.edges[0].direction == "in"
    s = parse("MATCH (a) WHERE (a)-[:e*2..4]->(:t{p: 1}) RETURN id(a)")
    ep = s.clauses[0].where.pattern.edges[0]
    assert (ep.min_hop, ep.max_hop) == (2, 4)
    assert s.clauses[0].where.text == "(a)-[:e*2..4]->(:t{p: 1})"
    s = parse("MATCH (a) WHERE (a)--(b) RETURN id(a)")
    assert s.clauses[0].where.pattern.edges[0].direction == "both"
    # exists() collapses to the bare pattern predicate
    s = parse("MATCH (a) WHERE exists((a)-[:knows]->()) RETURN id(a)")
    assert isinstance(s.clauses[0].where, PatternPredExpr)
    # arithmetic stays arithmetic
    s = parse("RETURN (1)-(2) AS d")
    e = s.return_.columns[0].expr
    assert isinstance(e, Binary) and e.op == "-"
    s = parse("MATCH (a) WHERE (a.person.age)-(1) > 0 RETURN id(a)")
    assert isinstance(s.clauses[0].where, Binary)


def test_bitwise_operators():
    """&/^ everywhere, | inside bracketed contexts only (it is the
    statement pipe / pattern-type separator elsewhere); reference/MySQL
    precedence: ^ above *, & above comparisons via additive, | lowest."""
    e = parse("RETURN 6 & 3 AS a").return_.columns[0].expr
    assert to_text(e) == "(6 & 3)"
    e = parse("RETURN (6 | 3) AS o").return_.columns[0].expr
    assert to_text(e) == "(6 | 3)"
    e = parse("RETURN 2 ^ 10 * 2 AS x").return_.columns[0].expr
    assert to_text(e) == "((2 ^ 10) * 2)"          # ^ binds above *
    e = parse("RETURN 1 + 2 & 3 AS x").return_.columns[0].expr
    assert to_text(e) == "((1 + 2) & 3)"           # & below additive
    e = parse("RETURN (1 | 2) == 3 AS c").return_.columns[0].expr
    assert to_text(e) == "((1 | 2) == 3)"
    # structural pipes survive: comprehension, reduce, statement pipe
    s = parse("RETURN [x IN [1,2] WHERE x > 0 | x * 2] AS l")
    assert s.return_.columns[0].alias == "l"
    s = parse("RETURN (reduce(acc = 0, x IN [1,2] | acc + x)) AS r")
    assert s.return_.columns[0].alias == "r"
    s = parse("YIELD 1 AS v | YIELD $-.v AS w")
    assert isinstance(s, A.PipedSentence)
    # multi-type patterns keep both spellings
    s = parse("MATCH (a)-[e:x|y]->(b) RETURN 1")
    assert s.clauses[0].patterns[0].edges[0].types == ["x", "y"]
    s = parse("MATCH (a)-[e:x|:y]->(b) RETURN 1")
    assert s.clauses[0].patterns[0].edges[0].types == ["x", "y"]


def test_unary_minus_xor_precedence():
    """Documented deviation (docs/COVERAGE.md): unary minus binds
    TIGHTER than `^` here — `-1 ^ 1` is `(-1) ^ 1` = -2, where the
    reference/MySQL precedence would give `-(1 ^ 1)` = 0.  This test
    pins the current behavior so any precedence change is deliberate."""
    from nebula_tpu.exec.engine import quick_engine
    eng, s = quick_engine()
    r = eng.execute(s, "YIELD -1 ^ 1")
    assert r.error is None and r.data.rows == [[-2]]
    # the parenthesized spelling recovers the reference meaning
    r = eng.execute(s, "YIELD -(1 ^ 1)")
    assert r.error is None and r.data.rows == [[0]]
