"""BACKUP / RESTORE surface (SURVEY §2 rows 16/18; the br-tool analog):
statement leg (CREATE/SHOW/DROP/RESTORE BACKUP), store-level restore,
durable round-trip, and the offline tool."""
import os
import tempfile

import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.schema import PropDef, PropType
from nebula_tpu.graphstore.store import GraphStore
from nebula_tpu.utils.config import get_config


@pytest.fixture()
def bdir(monkeypatch):
    d = tempfile.mkdtemp(prefix="nebula_bk_")
    monkeypatch.setenv("NEBULA_BACKUP_DIR", d)
    return d


def _seed(eng, s):
    for q in ["CREATE SPACE b(partition_num=4, vid_type=INT64)", "USE b",
              "CREATE TAG Person(age int)", "CREATE EDGE knows(w int)",
              "INSERT VERTEX Person(age) VALUES 1:(10), 2:(20), 3:(30)",
              "INSERT EDGE knows(w) VALUES 1->2:(7), 2->3:(8)"]:
        r = eng.execute(s, q)
        assert r.error is None, (q, r.error)


def _ages(eng, s):
    r = eng.execute(s, "MATCH (v:Person) RETURN id(v), v.Person.age")
    assert r.error is None, r.error
    return sorted(map(tuple, r.data.rows))


def test_backup_restore_statement_roundtrip(bdir):
    eng = QueryEngine()
    s = eng.new_session()
    _seed(eng, s)
    before = _ages(eng, s)

    r = eng.execute(s, "CREATE BACKUP AS bk1")
    assert r.error is None, r.error
    assert r.data.rows[0][0] == "bk1"

    r = eng.execute(s, "SHOW BACKUPS")
    assert r.error is None
    names = [row[0] for row in r.data.rows]
    assert "bk1" in names and r.data.rows[0][1] == "VALID"

    # mutate after the backup, then restore: the mutation must vanish
    for q in ["INSERT VERTEX Person(age) VALUES 9:(99)",
              "DELETE VERTEX 1"]:
        assert eng.execute(s, q).error is None
    assert _ages(eng, s) != before

    r = eng.execute(s, "RESTORE BACKUP bk1")
    assert r.error is None, r.error
    assert "b" in r.data.rows[0][0]
    assert _ages(eng, s) == before
    # index state is derived and rebuilt: a fresh CREATE+rebuild works
    r = eng.execute(s, "GO FROM 1 OVER knows YIELD dst(edge) AS d")
    assert r.error is None and [t[0] for t in r.data.rows] == [2]

    r = eng.execute(s, "DROP BACKUP bk1")
    assert r.error is None
    r = eng.execute(s, "SHOW BACKUPS")
    assert "bk1" not in [row[0] for row in r.data.rows]
    r = eng.execute(s, "RESTORE BACKUP bk1")
    assert r.error is not None


def test_backup_requires_god(bdir):
    eng = QueryEngine()
    s = eng.new_session()
    _seed(eng, s)
    for q in ["CREATE USER u1 WITH PASSWORD \"p\"",
              "GRANT ROLE ADMIN ON b TO u1"]:
        assert eng.execute(s, q).error is None
    get_config().set_dynamic("enable_authorize", True)
    try:
        u = eng.new_session("u1")
        r = eng.execute(u, "CREATE BACKUP AS nope")
        assert r.error is not None and "permission" in r.error.lower()
        r = eng.execute(u, "RESTORE BACKUP nope")
        assert r.error is not None and "permission" in r.error.lower()
    finally:
        get_config().set_dynamic("enable_authorize", False)


def test_restore_rebuilds_indexes_and_survives_restart(bdir):
    data = tempfile.mkdtemp(prefix="nebula_bkdur_")
    st = GraphStore(data_dir=data)
    st.create_space("g", partition_num=2, vid_type="INT64")
    st.catalog.create_tag("g", "person", [PropDef("age", PropType.INT64)])
    st.catalog.create_index("g", "iage", "person", ["age"], is_edge=False)
    for i in range(8):
        st.insert_vertex("g", i, "person", {"age": 20 + i})
    bpath = os.path.join(bdir, "dur1")
    st.checkpoint(bpath)
    # post-backup mutations to be rolled back
    for i in range(8, 12):
        st.insert_vertex("g", i, "person", {"age": 50 + i})
    assert len(st.index_scan("g", "iage", [], None)) == 12
    st.restore_backup(bpath)
    assert len(st.index_scan("g", "iage", [], None)) == 8
    st.close()
    # a restart boots the RESTORED world (restore compacted the journal)
    st2 = GraphStore(data_dir=data)
    assert len(st2.index_scan("g", "iage", [], None)) == 8
    assert st2.get_vertex("g", 9) is None
    assert st2.get_vertex("g", 3) == {"person": {"age": 23}}
    st2.close()


def test_offline_tool_roundtrip(bdir):
    from nebula_tpu.tools import backup as bk
    data = tempfile.mkdtemp(prefix="nebula_bktool_")
    st = GraphStore(data_dir=data)
    st.create_space("g", partition_num=2, vid_type="INT64")
    st.catalog.create_tag("g", "person", [PropDef("age", PropType.INT64)])
    st.insert_vertex("g", 1, "person", {"age": 41})
    st.close()
    out = os.path.join(bdir, "t1")
    assert bk.main(["create", "--data-dir", data, "--out", out]) == 0
    st = GraphStore(data_dir=data)
    st.insert_vertex("g", 2, "person", {"age": 52})
    st.close()
    assert bk.main(["list", "--dir", bdir]) == 0
    assert bk.main(["restore", "--data-dir", data, "--backup", out]) == 0
    st = GraphStore(data_dir=data)
    assert st.get_vertex("g", 2) is None
    assert st.get_vertex("g", 1) == {"person": {"age": 41}}
    st.close()


def test_backup_name_traversal_rejected(bdir):
    eng = QueryEngine()
    s = eng.new_session()
    _seed(eng, s)
    for q in ("DROP BACKUP `../../etc`", "RESTORE BACKUP `..`",
              "CREATE BACKUP AS `a/b`"):
        r = eng.execute(s, q)
        assert r.error is not None and "invalid backup name" in r.error, q


def test_corrupt_backup_rolls_back(bdir):
    st = GraphStore()
    st.create_space("g", partition_num=2, vid_type="INT64")
    st.catalog.create_tag("g", "person", [PropDef("age", PropType.INT64)])
    st.insert_vertex("g", 1, "person", {"age": 33})
    bpath = os.path.join(bdir, "c1")
    st.checkpoint(bpath)
    # corrupt one part file: restore must fail WITHOUT touching state
    target = None
    for root, _dirs, files in os.walk(bpath):
        for fn in files:
            if fn.startswith("part_"):
                target = os.path.join(root, fn)
    with open(target, "wb") as f:
        f.write(b"\x00garbage")
    st.insert_vertex("g", 2, "person", {"age": 44})
    with pytest.raises(Exception):
        st.restore_backup(bpath)
    assert st.get_vertex("g", 2) == {"person": {"age": 44}}
    assert st.get_vertex("g", 1) == {"person": {"age": 33}}


def test_restore_keeps_epochs_monotonic(bdir):
    st = GraphStore()
    st.create_space("g", partition_num=2, vid_type="INT64")
    st.catalog.create_tag("g", "person", [PropDef("age", PropType.INT64)])
    st.insert_vertex("g", 1, "person", {"age": 33})
    bpath = os.path.join(bdir, "e1")
    st.checkpoint(bpath)
    for i in range(2, 6):
        st.insert_vertex("g", i, "person", {"age": 30 + i})
    before = st.space("g").epoch
    st.restore_backup(bpath)
    assert st.space("g").epoch > before


def test_cluster_store_refuses_statement(bdir):
    class FakeClusterStore:
        pass
    from nebula_tpu.exec import jobs

    class Q:
        store = FakeClusterStore()
    with pytest.raises(ValueError, match="standalone"):
        jobs.create_backup(Q(), "x")
