"""Native (C++) kernel tests: every entry point against its Python/NumPy
fallback — the native path must be a pure speedup, never a semantic
change."""
import ctypes
import random

import numpy as np
import pytest

from nebula_tpu.native import available, get_lib
from nebula_tpu.native.kernels import (build_coo_csr, csv_ingest,
                                       dst_sort_key, fnv1a)

pytestmark = pytest.mark.skipif(not available(),
                                reason="native lib unavailable (no g++?)")


def random_coo(seed, n=500, P=8, nverts=64):
    rng = random.Random(seed)
    src = np.asarray([rng.randrange(nverts) for _ in range(n)], np.int64)
    dst = np.asarray([rng.randrange(nverts) for _ in range(n)], np.int64)
    rank = np.asarray([rng.randrange(3) for _ in range(n)], np.int64)
    vmax = (nverts + P - 1) // P
    return src, dst, rank, vmax


def numpy_reference(src, dst, rank, key, P, vmax):
    """Force the fallback by simulating lib absence via direct call of
    the fallback branch (build_coo_csr falls back only when the native
    call fails, so re-implement the reference ordering here)."""
    n = len(src)
    part = src % P
    local = src // P
    order = np.lexsort((np.arange(n), key, rank, local, part))
    counts = np.bincount(part, minlength=P)
    emax = max(1, int(counts.max()))
    indptr = np.zeros((P, vmax + 1), np.int32)
    nbr = np.full((P, emax), -1, np.int32)
    rk = np.zeros((P, emax), np.int32)
    perm = np.full((P, emax), -1, np.int64)
    pos = np.zeros(P, np.int64)
    for k in order:
        p = int(part[k])
        s = int(pos[p])
        pos[p] += 1
        perm[p, s] = k
        nbr[p, s] = dst[k]
        rk[p, s] = rank[k]
        indptr[p, local[k] + 1] += 1
    np.cumsum(indptr, axis=1, out=indptr)
    return indptr, nbr, rk, perm, emax


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_build_csr_matches_reference(seed):
    src, dst, rank, vmax = random_coo(seed)
    key = dst.copy()
    got = build_coo_csr(src, dst, rank, key, 8, vmax)
    want = numpy_reference(src, dst, rank, key, 8, vmax)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_build_csr_empty():
    indptr, nbr, rk, perm, emax = build_coo_csr(
        np.zeros(0, np.int64), np.zeros(0, np.int64),
        np.zeros(0, np.int64), np.zeros(0, np.int64), 4, 5)
    assert indptr.shape == (4, 6) and emax == 1


def test_dst_sort_key_strings():
    key = dst_sort_key(["bob", "ann", "bob", "cid"])
    assert key.tolist() == [1, 0, 1, 2]


def test_csv_ingest(tmp_path):
    f = tmp_path / "edges.csv"
    f.write_text("src,dst,w,city\n1,2,0.5,sf\n3,4,1.25,nyc\n5,6,-2.0,sf\n")
    cols = csv_ingest(str(f), ["int", "int", "float", "strhash"])
    assert cols is not None
    assert cols[0].tolist() == [1, 3, 5]
    assert cols[1].tolist() == [2, 4, 6]
    assert cols[2].tolist() == [0.5, 1.25, -2.0]
    assert cols[3][0] == cols[3][2] == fnv1a("sf")
    assert cols[3][1] == fnv1a("nyc")


def test_row_codec_roundtrip():
    from nebula_tpu.native.kernels import decode_row, encode_row
    props = [("int", 42), ("double", 2.5), ("bool", True),
             ("str", "héllo; world"), ("null", None)]
    blob = encode_row(7, props)
    assert blob is not None and isinstance(blob, bytes)
    ver, got = decode_row(blob)
    assert ver == 7
    assert got == props
    # malformed input → clean None, not a crash
    assert decode_row(b"\x01") is None
    assert decode_row(blob[:-3]) is None


def test_csv_ingest_rejects_malformed(tmp_path):
    short = tmp_path / "short.csv"
    short.write_text("a,b\n1,2\n3\n")          # short row
    with pytest.raises(ValueError):
        csv_ingest(str(short), ["int", "int"])
    bad = tmp_path / "bad.csv"
    bad.write_text("a,b\n1,xyz\n")             # non-numeric int field
    with pytest.raises(ValueError):
        csv_ingest(str(bad), ["int", "int"])


def test_csv_ingest_rejects_truncation(tmp_path):
    f = tmp_path / "big.csv"
    f.write_text("a\n" + "\n".join(str(i) for i in range(100)) + "\n")
    with pytest.raises(ValueError):
        csv_ingest(str(f), ["int"], max_rows=10)


def test_build_csr_rejects_out_of_range():
    lib = get_lib()
    # a dense id whose local index exceeds vmax must fail cleanly
    src = np.asarray([0, 8 * 100], np.int64)   # local 100 >= vmax 5
    dst = np.zeros(2, np.int64)
    rank = np.zeros(2, np.int64)
    indptr = np.zeros((8, 6), np.int32)
    nbr = np.full((8, 2), -1, np.int32)
    rk = np.zeros((8, 2), np.int32)
    perm = np.full((8, 2), -1, np.int64)
    import ctypes as C

    def p(a):
        return a.ctypes.data_as(C.c_void_p)
    got = lib.build_csr(2, 8, 5, p(src), p(dst), p(rank), p(dst), p(perm),
                        p(indptr), p(nbr), p(rk), 2)
    assert got == -1


def test_snapshot_uses_native_and_matches_host_order():
    """End-to-end: CSR built through the native kernel must match
    get_neighbors row order exactly (the parity contract)."""
    from nebula_tpu.graphstore.csr import build_snapshot
    from nebula_tpu.graphstore.schema import PropDef, PropType
    from nebula_tpu.graphstore.store import GraphStore
    rng = random.Random(3)
    st = GraphStore()
    st.create_space("n", partition_num=4, vid_type="INT64")
    st.catalog.create_edge("n", "e", [PropDef("w", PropType.INT64)])
    st.catalog.create_tag("n", "t", [])
    for i in range(40):
        st.insert_vertex("n", i, "t", {})
    for _ in range(200):
        st.insert_edge("n", rng.randrange(40), "e", rng.randrange(40),
                       rng.randrange(3), {"w": rng.randrange(100)})
    snap = build_snapshot(st, "n")
    blk = snap.block("e", "out")
    sd = st.space("n")
    for vid in range(40):
        d = sd.dense_id(vid)
        if d < 0:
            continue
        p, li = d % 4, d // 4
        lo, hi = int(blk.indptr[p, li]), int(blk.indptr[p, li + 1])
        got = [(int(blk.rank[p, i]),
                sd.dense_to_vid[int(blk.nbr[p, i])],
                int(blk.props["w"][p, i]))
               for i in range(lo, hi)]
        want = [(rank, dst, props["w"])
                for (_, _, rank, dst, props, _) in st.get_neighbors(
                    "n", [vid], ["e"], "out")]
        assert got == want, vid
