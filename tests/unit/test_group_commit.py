"""Group-commit write path (ISSUE 3): WAL batch append + group sync,
raft propose_batch under faults, bounded apply-error bookkeeping, the
raft_max_batch knob, and coalesced TOSS chains through a real cluster."""
import os
import threading
import time

import pytest

from nebula_tpu.cluster.raft import LoopbackTransport, RaftPart
from nebula_tpu.cluster.wal import Wal
from nebula_tpu.utils.stats import stats


# ---------------------------------------------------------------------------
# WAL: append_batch + single fsync + CRC recovery
# ---------------------------------------------------------------------------


def test_wal_append_batch_roundtrip_and_recovery(tmp_path):
    w = Wal(str(tmp_path / "b.wal"), sync=True)
    w.append_batch([(i, 1, f"e{i}".encode()) for i in range(1, 8)])
    assert w.last_index() == 7
    assert w.synced_index() == 7
    assert w.read(3) == (1, b"e3")
    # mixing single appends after a batch stays contiguous
    w.append(8, 2, b"e8")
    with pytest.raises(Exception):
        w.append_batch([(11, 2, b"gap")])
    w.close()
    w2 = Wal(str(tmp_path / "b.wal"), sync=True)
    assert w2.last_index() == 8
    assert [i for i, _, _ in w2.read_range(1, 8)] == list(range(1, 9))
    assert w2.synced_index() == 8       # recovered entries are durable
    w2.close()


def test_wal_append_batch_is_one_fsync(tmp_path, monkeypatch):
    calls = []
    real = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd),
                                                 real(fd))[1])
    w = Wal(str(tmp_path / "one.wal"), sync=True)
    w.append_batch([(i, 1, b"x" * 32) for i in range(1, 65)])
    assert len(calls) == 1              # 64 entries, ONE fsync
    for i in range(65, 69):
        w.append(i, 1, b"y")
    assert len(calls) == 5              # per-entry path: one each
    w.close()


def test_wal_torn_tail_mid_batch_crc_recovery(tmp_path):
    """Follower crash mid-batch-write: the CRC scan must keep the good
    prefix of the batch and drop the torn record."""
    p = str(tmp_path / "torn.wal")
    w = Wal(p, sync=True)
    w.append_batch([(i, 3, f"payload-{i}".encode() * 4)
                    for i in range(1, 6)])
    off4 = w._entries[3][2]             # file offset of entry 4
    w.close()
    with open(p, "r+b") as f:
        f.truncate(off4 + 9)            # sever entry 4 mid-record
    w2 = Wal(p, sync=True)
    assert w2.last_index() == 3
    assert w2.read(3) == (3, b"payload-3" * 4)
    w2.append(4, 4, b"new4")            # log continues past the scar
    assert w2.read(4) == (4, b"new4")
    w2.close()


# ---------------------------------------------------------------------------
# raft: propose_batch
# ---------------------------------------------------------------------------


class Applied:
    def __init__(self):
        self.entries = []
        self.lock = threading.Lock()

    def cb(self, idx, data):
        with self.lock:
            self.entries.append((idx, data))

    def data(self):
        with self.lock:
            return [d for _, d in self.entries]


def make_cluster(tmp_path, n=3, **kw):
    tr = LoopbackTransport()
    nodes = [f"n{i}" for i in range(n)]
    parts, apps = [], []
    for nid in nodes:
        app = Applied()
        parts.append(RaftPart("g0", nid, nodes, tr, str(tmp_path / nid),
                              app.cb, election_timeout=(0.05, 0.12),
                              heartbeat_interval=0.02, **kw))
        apps.append(app)
    for p in parts:
        p.start()
    return tr, parts, apps


def wait_leader(parts, timeout=20.0):
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        leaders = [p for p in parts if p.is_leader() and p.alive]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.01)
    raise AssertionError("no unique leader elected")


def wait_applied(apps, want, timeout=20.0, exclude=()):
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        if all(a.data() == want for i, a in enumerate(apps)
               if i not in exclude):
            return
        time.sleep(0.01)
    got = [a.data() for a in apps]
    raise AssertionError(f"apply mismatch: want {want}, got {got}")


def stop_all(parts):
    for p in parts:
        p.stop()


def _has_contig(got, batch):
    n = len(batch)
    return any(got[i:i + n] == batch
               for i in range(len(got) - n + 1))


def wait_contains_batch(apps, batch, timeout=20.0):
    """Every app's applied sequence contains `batch` contiguously.
    (Tolerates a None-but-committed retry duplicating a batch — the
    at-least-once client ambiguity the idempotent state machine
    absorbs — while still catching loss or interleaving.)"""
    dl = time.monotonic() + timeout
    while time.monotonic() < dl:
        if all(_has_contig(a.data(), batch) for a in apps):
            return
        time.sleep(0.01)
    raise AssertionError(
        f"batch never applied contiguously everywhere: "
        f"{[a.data() for a in apps]}")


def test_propose_batch_commits_all_in_order(tmp_path):
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        want = [f"b{i}".encode() for i in range(10)]
        # a CPU-starved election can depose the leader mid-propose —
        # retry against the current leader (the propose contract)
        deadline = time.monotonic() + 20
        idxs = None
        while idxs is None:
            idxs = leader.propose_batch(want, timeout=10)
            if idxs is None:
                assert time.monotonic() < deadline, "no stable leader"
                leader = wait_leader(parts)
        assert len(idxs) == 10
        assert idxs == list(range(idxs[0], idxs[0] + 10))   # contiguous
        wait_contains_batch(apps, want)
    finally:
        stop_all(parts)


def test_propose_batch_concurrent_callers_no_interleave_loss(tmp_path):
    """Concurrent batches coalesce (shared fsync / replication rounds)
    but every batch stays contiguous and nothing is lost."""
    tr, parts, apps = make_cluster(tmp_path)
    try:
        wait_leader(parts)
        results = {}

        def prop(k):
            batch = [f"c{k}-{j}".encode() for j in range(8)]
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                cur = next((p for p in parts if p.is_leader()), None)
                if cur is None:
                    time.sleep(0.02)
                    continue
                r = cur.propose_batch(batch, timeout=10)
                if r:
                    results[k] = (batch, r)
                    return
                time.sleep(0.05)

        ts = [threading.Thread(target=prop, args=(k,)) for k in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(results) == 6, sorted(results)
        for k, (batch, idxs) in results.items():
            # an acked batch's entries occupy contiguous indices ...
            assert idxs == list(range(idxs[0], idxs[0] + 8)), k
            # ... and land contiguously in apply order on every node
            wait_contains_batch(apps, batch)
        for a in apps:
            got = a.data()
            for k, (batch, _) in results.items():
                # no occurrence is ever torn by a sibling's entries
                for pos, x in enumerate(got):
                    if x == batch[0]:
                        assert got[pos:pos + 8] == batch, (k, pos)
    finally:
        stop_all(parts)


def test_acked_batch_survives_leader_loss(tmp_path):
    """No entry of an acked half-replicated batch may be lost: with one
    follower cut off, the batch commits on leader+f1; after the leader
    dies, the up-to-date follower must win and preserve every entry."""
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        others = [p for p in parts if p is not leader]
        f1, f2 = others
        tr.partition(leader.node_id, f2.node_id)
        tr.partition(f1.node_id, f2.node_id)    # f2 fully dark
        want = [f"k{i}".encode() for i in range(12)]
        # leadership may ping-pong between the two connected nodes
        # under CPU load — commit through whichever currently leads
        live = [leader, f1]
        deadline = time.monotonic() + 20
        idxs, committer = None, None
        while idxs is None:
            assert time.monotonic() < deadline, "majority never committed"
            committer = next((p for p in live if p.is_leader()), None)
            if committer is None:
                time.sleep(0.02)
                continue
            idxs = committer.propose_batch(want, timeout=10)
        # the committer dies; f2 heals — only the surviving live node
        # has the acked batch, and IT must win the election
        survivor = live[1 - live.index(committer)]
        dead = parts.index(committer)
        committer.alive = False
        tr.heal()
        new_leader = wait_leader([survivor, f2])
        # raft safety: whoever won already holds every acked entry (the
        # stale follower can only win AFTER catching up)
        assert new_leader.wal.term_of(idxs[-1]) is not None, \
            "election winner is missing acked batch entries"
        # the acked batch survives, followed by the new leader's write
        deadline = time.monotonic() + 20
        while not new_leader.propose(b"after", timeout=10):
            assert time.monotonic() < deadline, "survivor never committed"
            new_leader = wait_leader([survivor, f2])
        wait_contains_batch([a for i, a in enumerate(apps) if i != dead],
                            want)
        wait_contains_batch([a for i, a in enumerate(apps) if i != dead],
                            [b"after"])
    finally:
        stop_all(parts)


def test_unacked_batch_discarded_after_partition(tmp_path):
    """Leader change mid-batch: a batch proposed without quorum times
    out (NOT acked) and must be discarded wholesale — no partial apply
    surviving alongside the new leader's log."""
    tr, parts, apps = make_cluster(tmp_path)
    try:
        leader = wait_leader(parts)
        others = [p for p in parts if p is not leader]
        for o in others:
            tr.partition(leader.node_id, o.node_id)
        lost = [f"lost{i}".encode() for i in range(5)]
        assert leader.propose_batch(lost, timeout=0.5) is None
        deadline = time.time() + 15
        while True:
            nl = wait_leader(others)
            if nl.propose(b"kept"):
                break
            assert time.time() < deadline, "majority never committed"
        tr.heal()
        wait_applied(apps, [b"kept"])
        assert apps[parts.index(leader)].data() == [b"kept"]
    finally:
        stop_all(parts)


def test_raft_max_batch_knob_and_write_metrics(tmp_path):
    """raft_max_batch caps the replication round; the write-path
    metrics (fsync counters, batch/commit histograms) populate."""
    from nebula_tpu.utils.config import get_config
    before = stats().snapshot()
    get_config().set_dynamic("raft_max_batch", 8)
    try:
        tr, parts, apps = make_cluster(tmp_path)
        try:
            leader = wait_leader(parts)
            want = [f"m{i}".encode() for i in range(30)]
            assert leader.propose_batch(want, timeout=10)
            wait_applied(apps, want)
        finally:
            stop_all(parts)
    finally:
        get_config().set_dynamic("raft_max_batch", 64)
    after = stats().snapshot()

    def delta(k):
        return after.get(k, 0) - before.get(k, 0)

    assert delta("raft_propose_batches") >= 1
    assert delta("wal_fsync_total") >= 1
    assert delta("wal_fsync_batch_entries") >= 30
    assert delta("raft_commit_latency_ms.count") >= 1
    assert delta("raft_replication_batch_size.count") >= 1
    # and they export in prometheus form
    prom = stats().to_prometheus()
    assert "raft_replication_batch_size_bucket" in prom
    assert "raft_commit_latency_ms_bucket" in prom
    assert "wal_fsync_total" in prom


def test_bounded_error_map_evicts_oldest():
    """Regression for the _apply_errors leak: a propose that timed out
    never pops its later apply error — the map must stay bounded with
    insertion-order eviction, not grow forever."""
    from nebula_tpu.cluster.storage_service import BoundedErrorMap
    m = BoundedErrorMap(cap=64)
    for i in range(64 + 100):
        m.record(("g", i), f"err{i}")
    assert len(m) == 64
    assert ("g", 0) not in m and ("g", 99) not in m     # oldest evicted
    assert ("g", 100) in m and ("g", 163) in m
    assert m.pop(("g", 163)) == "err163"
    assert m.pop(("g", 163)) is None                    # pop-once
    assert len(m) == 63
    # re-recording a key refreshes its eviction position
    m2 = BoundedErrorMap(cap=2)
    m2.record(("g", 1), "a")
    m2.record(("g", 2), "b")
    m2.record(("g", 1), "a2")
    m2.record(("g", 3), "c")
    assert ("g", 2) not in m2 and m2.pop(("g", 1)) == "a2"


# ---------------------------------------------------------------------------
# cluster: coalesced writes + batched TOSS chains
# ---------------------------------------------------------------------------


def test_insert_if_not_exists_intra_statement_dup(tmp_path):
    """Batching defers writes past the existence checks — the executor
    must still suppress duplicates WITHIN one IF NOT EXISTS statement
    (first occurrence wins, as the per-row path naturally did)."""
    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.graphstore.store import GraphStore
    eng = QueryEngine(GraphStore())
    s = eng.new_session()
    for q in ("CREATE SPACE ine(partition_num=2, vid_type=INT64)",
              "USE ine", "CREATE TAG t(x int)", "CREATE EDGE e(w int)"):
        assert eng.execute(s, q).error is None, q
    rs = eng.execute(
        s, 'INSERT VERTEX IF NOT EXISTS t(x) VALUES 1:(10), 1:(99)')
    assert rs.error is None, rs.error
    rs = eng.execute(s, "FETCH PROP ON t 1 YIELD t.x AS x")
    assert rs.data.rows == [[10]], rs.data.rows       # first wins
    rs = eng.execute(
        s, "INSERT EDGE IF NOT EXISTS e(w) VALUES 1->2:(5), 1->2:(6)")
    assert rs.error is None, rs.error
    rs = eng.execute(s, "GO FROM 1 OVER e YIELD e.w AS w")
    assert rs.data.rows == [[5]], rs.data.rows        # first wins
    # plain INSERT keeps last-write-wins
    rs = eng.execute(s, "INSERT VERTEX t(x) VALUES 3:(1), 3:(2)")
    assert rs.error is None
    rs = eng.execute(s, "FETCH PROP ON t 3 YIELD t.x AS x")
    assert rs.data.rows == [[2]], rs.data.rows


def test_insert_statement_coalesces_proposals(tmp_path):
    """One INSERT statement ships one batched proposal per touched
    part (vertices) and 3 phases per (src_pid, dst_pid) pair (edges) —
    far fewer consensus rounds than rows."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        cl = c.client()
        r = cl.execute("CREATE SPACE gc(partition_num=4, vid_type=INT64)")
        assert r.error is None, r.error
        c.reconcile_storage()
        for q in ("USE gc", "CREATE TAG P(x int)", "CREATE EDGE E(w int)"):
            assert cl.execute(q).error is None, q
        before = stats().snapshot()
        n = 48
        vals = ", ".join(f"{i}:({i})" for i in range(n))
        assert cl.execute(f"INSERT VERTEX P(x) VALUES {vals}").error is None
        evals = ", ".join(f"{i}->{(i + 1) % n}:({i})" for i in range(n))
        assert cl.execute(f"INSERT EDGE E(w) VALUES {evals}").error is None
        after = stats().snapshot()
        batches = after.get("raft_propose_batches", 0) \
            - before.get("raft_propose_batches", 0)
        coalesced = after.get("toss_chains_coalesced", 0) \
            - before.get("toss_chains_coalesced", 0)
        # pre-group-commit this was ≥ 48 + 3*48 = 192 proposals; now:
        # ≤ 4 (vertices) + ≤ 16+4+4 (edge pairs by phase) + slack for
        # metad/heartbeat/janitor traffic
        assert batches <= 60, batches
        assert coalesced >= n - 16, coalesced
        # read-after-write oracle on both planes
        r = cl.execute("GO FROM 0 OVER E YIELD dst(edge) AS d")
        assert r.error is None and [x[0] for x in r.data.rows] == [1]
        r = cl.execute("GO FROM 1 OVER E REVERSELY YIELD src(edge) AS s")
        assert r.error is None and [x[0] for x in r.data.rows] == [0]
    finally:
        c.stop()


def test_batched_toss_chain_kill_and_resume(tmp_path):
    """A graphd that dies after the mark+out batch of a COALESCED chain
    (several edges, one journal entry) leaves the whole pair to the
    resume janitor: every edge's in-half must be re-driven, exactly
    once in effect (idempotent overwrite — no duplicate rows), and the
    journal retired everywhere."""
    from nebula_tpu.cluster.launcher import LocalCluster
    from nebula_tpu.cluster.storage_client import StorageClient
    from nebula_tpu.core.wire import to_wire
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        cl = c.client()
        r = cl.execute("CREATE SPACE bt(partition_num=4, vid_type=INT64)")
        assert r.error is None, r.error
        c.reconcile_storage()
        for q in ("USE bt", "CREATE TAG P()", "CREATE EDGE E(w int)"):
            assert cl.execute(q).error is None, q
        vids = list(range(1, 40))
        assert cl.execute("INSERT VERTEX P() VALUES "
                          + ", ".join(f"{v}:()" for v in vids)).error is None
        sc = StorageClient(c.meta_clients[0])
        src = 1
        src_pid = sc.part_of("bt", src)
        # two dst vids on the SAME part → one coalesced chain
        dst_pid, dsts = None, []
        for v in vids[1:]:
            p = sc.part_of("bt", v)
            if dst_pid is None:
                dst_pid, dsts = p, [v]
            elif p == dst_pid:
                dsts.append(v)
            if len(dsts) == 2:
                break
        d1, d2 = dsts
        ins = [["edge_half", src, "E", d, 0, {"w": 7}, "in"] for d in dsts]
        outs = [["edge_half", src, "E", d, 0, {"w": 7}, "out"] for d in dsts]
        # the crash window: mark + out-halves committed as ONE entry,
        # in-halves and chain_done never sent (graphd died)
        cmd = ("batch",
               [["chain_mark", src_pid, "orphan-b", dst_pid,
                 ["batch", ins], time.time() - 10]] + outs)
        sc._call_part("bt", src_pid, "storage.write",
                      {"cmds": [to_wire(list(cmd))]})
        # out-plane immediately visible
        rs = cl.execute("GO FROM 1 OVER E YIELD dst(edge) AS d")
        assert sorted(x[0] for x in rs.data.rows) == sorted(dsts)
        # janitor re-drives the batched in-half for EVERY edge
        deadline = time.time() + 12
        got = []
        while time.time() < deadline:
            rows = []
            for d in dsts:
                rs = cl.execute(f"GO FROM {d} OVER E REVERSELY "
                                f"YIELD src(edge) AS s, E.w AS w")
                rows.append([list(x) for x in rs.data.rows])
            if all(r == [[1, 7]] for r in rows):
                got = rows
                break
            time.sleep(0.3)
        assert got, "resume never completed the batched in-halves"
        # exactly-once in effect: single row per edge, no dupes
        assert all(r == [[1, 7]] for r in got), got
        # journal retired on every replica of the src part
        def journals():
            out = []
            for ss in c.storageds:
                sid = ss.meta.catalog.get_space("bt").space_id
                if (sid, src_pid) in ss.parts:
                    out.append(ss.store.pending_chains("bt", src_pid))
            return out
        deadline = time.time() + 8
        while time.time() < deadline and \
                any("orphan-b" in j for j in journals()):
            time.sleep(0.2)
        assert all("orphan-b" not in j for j in journals()), journals()
    finally:
        c.stop()
