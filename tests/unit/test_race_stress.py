"""Race-detection harness (SURVEY §5: the TSan/sanitizer-CI analog).

Two leg design (utils/racecheck.py):
  * lock-order watchdog: key component locks are created through
    make_lock(); with NEBULA_LOCKCHECK=1 every cross-lock acquisition
    edge is recorded and a cycle raises immediately.  These tests run
    the watchdog in-process (module reload with the env set) over the
    write path and the cluster planes, then assert the edge graph is
    acyclic.
  * interleaving amplification: concurrent engine/raft workloads run
    under a 10 µs switch interval so the scheduler preempts between
    nearly every bytecode — atomicity bugs that hide behind the
    default 5 ms quantum surface here.
"""
import threading

import pytest

from nebula_tpu.utils import racecheck


def _acyclic(edges):
    # Kahn over the observed order graph
    nodes = {a for a, _ in edges} | {b for _, b in edges}
    out = {n: set() for n in nodes}
    indeg = {n: 0 for n in nodes}
    for a, b in edges:
        if b not in out[a]:
            out[a].add(b)
            indeg[b] += 1
    q = [n for n in nodes if indeg[n] == 0]
    seen = 0
    while q:
        n = q.pop()
        seen += 1
        for m in out[n]:
            indeg[m] -= 1
            if indeg[m] == 0:
                q.append(m)
    return seen == len(nodes)


def test_lock_order_watchdog_detects_cycle():
    """The watchdog itself: an AB/BA interleave must raise."""
    racecheck.reset()
    a = racecheck.CheckedRLock("A")
    b = racecheck.CheckedRLock("B")
    with a:
        with b:
            pass
    with pytest.raises(racecheck.LockOrderError):
        with b:
            with a:
                pass
    racecheck.reset()


def test_lock_order_reentrant_ok():
    racecheck.reset()
    a = racecheck.CheckedRLock("A")
    with a:
        with a:
            pass
    assert racecheck.edges() == set()


def test_write_path_lock_order_acyclic(monkeypatch, tmp_path):
    """Durable write path holds space_data then journal (the documented
    order); run writes + compaction + recovery with CHECKED locks and
    assert no cycle was ever observed."""
    monkeypatch.setenv("NEBULA_LOCKCHECK", "1")
    monkeypatch.setattr(racecheck, "_enabled", True)
    racecheck.reset()
    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.graphstore.store import GraphStore

    store = GraphStore(data_dir=str(tmp_path / "db"))
    eng = QueryEngine(store)
    s = eng.new_session()
    for t in ["CREATE SPACE rs(partition_num=2, vid_type=INT64)",
              "USE rs", "CREATE TAG P(a int)", "CREATE EDGE E(w int)",
              "CREATE TAG INDEX pa ON P(a)"]:
        assert eng.execute(s, t).error is None

    def writer(base):
        s2 = eng.new_session()
        eng.execute(s2, "USE rs")
        for i in range(30):
            v = base + i
            eng.execute(s2, f"INSERT VERTEX P(a) VALUES {v}:({v})")
            eng.execute(s2, f"INSERT EDGE E(w) VALUES {v}->{base}:({i})")

    with racecheck.race_amplifier():
        ts = [threading.Thread(target=writer, args=(1000 * k,))
              for k in range(4)]
        for t in ts:
            t.start()
        store.compact_journal()
        for t in ts:
            t.join()
    store.close()
    assert _acyclic(racecheck.edges()), racecheck.edges()
    racecheck.reset()


def test_cluster_plane_lock_order_acyclic(monkeypatch, tmp_path):
    """Raft + meta + storage + graph planes under checked locks and an
    amplified scheduler: DDL, writes, reads, balance — then assert the
    global acquisition-order graph is acyclic."""
    monkeypatch.setenv("NEBULA_LOCKCHECK", "1")
    monkeypatch.setattr(racecheck, "_enabled", True)
    racecheck.reset()
    from nebula_tpu.cluster.launcher import LocalCluster

    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        rs = client.execute(
            "CREATE SPACE rc(partition_num=4, replica_factor=2, "
            "vid_type=INT64)")
        assert rs.error is None, rs.error
        for t in ["USE rc", "CREATE TAG P(a int)",
                  "CREATE EDGE E(w int)"]:
            assert client.execute(t).error is None

        errs = []

        def writer(base):
            try:
                cl = c.client()
                cl.execute("USE rc")
                for i in range(15):
                    v = base + i
                    cl.execute(f"INSERT VERTEX P(a) VALUES {v}:({v})")
                    cl.execute(
                        f"INSERT EDGE E(w) VALUES {v}->{base}:({i})")
            except Exception as ex:  # noqa: BLE001
                errs.append(ex)

        def reader():
            try:
                cl = c.client()
                cl.execute("USE rc")
                for _ in range(10):
                    cl.execute("GO 2 STEPS FROM 1000 OVER E "
                               "YIELD dst(edge)")
                    cl.execute("SHOW HOSTS")
            except Exception as ex:  # noqa: BLE001
                errs.append(ex)

        with racecheck.race_amplifier():
            ts = [threading.Thread(target=writer, args=(1000 * k,))
                  for k in range(3)] + [threading.Thread(target=reader)]
            for t in ts:
                t.start()
            client.execute("SUBMIT JOB BALANCE LEADER")
            for t in ts:
                t.join()
        assert not errs, errs
        assert _acyclic(racecheck.edges()), sorted(racecheck.edges())
    finally:
        c.stop()
        racecheck.reset()


def test_amplified_concurrent_sessions_consistent():
    """Many sessions hammering one store under the amplifier: final
    counts must be exact (no lost updates in the dict store write path)."""
    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.graphstore.store import GraphStore

    store = GraphStore()
    eng = QueryEngine(store)
    s = eng.new_session()
    for t in ["CREATE SPACE amp(partition_num=4, vid_type=INT64)",
              "USE amp", "CREATE TAG P(a int)"]:
        assert eng.execute(s, t).error is None
    n_threads, per = 6, 50

    def worker(k):
        s2 = eng.new_session()
        eng.execute(s2, "USE amp")
        for i in range(per):
            v = k * 10000 + i
            rs = eng.execute(s2, f"INSERT VERTEX P(a) VALUES {v}:({i})")
            assert rs.error is None, rs.error

    with racecheck.race_amplifier():
        ts = [threading.Thread(target=worker, args=(k,))
              for k in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    rs = eng.execute(s, "SUBMIT JOB STATS")
    assert rs.error is None
    det = store.stats_detail("amp")
    assert det["vertices"] == n_threads * per


def test_lock_order_nonadjacent_reentrant_ok():
    """Hold A, then B, then reacquire A: the thread owns A — no edge,
    no false cycle (ADVICE r4)."""
    racecheck.reset()
    a = racecheck.CheckedRLock("A")
    b = racecheck.CheckedRLock("B")
    with a:
        with b:
            with a:           # reentrant through another lock
                pass
    assert ("B", "A") not in racecheck.edges()
    # and the stack unwound correctly: a fresh B->A IS a cycle now
    with pytest.raises(racecheck.LockOrderError):
        with b:
            with a:
                pass
    racecheck.reset()


def test_repartition_under_concurrent_readers():
    """REPARTITION swaps the space layout while lock-free readers run:
    a racing query may transiently miss rows but must never crash, and
    after the swap settles every reader sees the full, correct graph."""
    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.graphstore.store import GraphStore

    store = GraphStore()
    eng = QueryEngine(store)
    s = eng.new_session()
    for t in ["CREATE SPACE rr(partition_num=2, vid_type=INT64)",
              "USE rr", "CREATE TAG P(a int)", "CREATE EDGE E(w int)"]:
        assert eng.execute(s, t).error is None
    for v in range(60):
        eng.execute(s, f"INSERT VERTEX P(a) VALUES {v}:({v})")
        eng.execute(s, f"INSERT EDGE E(w) VALUES {v}->{(v + 1) % 60}:(1)")
    rs = eng.execute(s, "GO 2 STEPS FROM 0 OVER E YIELD dst(edge) AS d")
    settled = sorted(map(repr, rs.data.rows))

    errs = []
    stop = threading.Event()

    def reader():
        s2 = eng.new_session()
        eng.execute(s2, "USE rr")
        while not stop.is_set():
            rs2 = eng.execute(
                s2, "GO 2 STEPS FROM 0 OVER E YIELD dst(edge) AS d")
            if rs2.error is not None:
                errs.append(rs2.error)
                return

    with racecheck.race_amplifier():
        ts = [threading.Thread(target=reader) for _ in range(3)]
        for t in ts:
            t.start()
        try:
            for n in (8, 3, 16, 2):
                moved = store.repartition("rr", n)
                assert moved == 60, moved
        finally:
            stop.set()         # a failing assert must not leave the
            for t in ts:       # non-daemon readers spinning forever
                t.join()
    assert not errs, errs
    rs = eng.execute(s, "GO 2 STEPS FROM 0 OVER E YIELD dst(edge) AS d")
    assert sorted(map(repr, rs.data.rows)) == settled


def test_amplified_job_manager_lifecycle():
    """Concurrent SUBMIT/STOP/RECOVER storms under the amplifier: the
    worker pool must never exceed its bound, no job may execute
    concurrently with itself, each job runs at most once per (re)queue,
    and every status converges to a terminal one."""
    from nebula_tpu.exec.engine import QueryEngine
    from nebula_tpu.exec.jobs import JobManager, job_manager
    from nebula_tpu.graphstore.store import GraphStore
    from nebula_tpu.utils.config import get_config

    store = GraphStore()
    eng = QueryEngine(store)
    s = eng.new_session()
    for t in ["CREATE SPACE jr(partition_num=2, vid_type=INT64)",
              "USE jr", "CREATE TAG P(a int)"]:
        assert eng.execute(s, t).error is None
    eng.execute(s, "INSERT VERTEX P(a) VALUES 1:(1)")

    import time

    mgr = job_manager(store)
    orig_run = JobManager._run
    live = {"n": 0, "max": 0, "per_job": {}, "concurrent_self": False}
    lk = threading.Lock()

    def counting_run(self, qctx, command, space, job=None):
        with lk:
            live["n"] += 1
            live["max"] = max(live["max"], live["n"])
            if job is not None:
                c = live["per_job"].get(job.job_id, 0) + 1
                live["per_job"][job.job_id] = c
                if getattr(job, "_in_run", False):
                    live["concurrent_self"] = True
                job._in_run = True
        try:
            time.sleep(0.001)
            return orig_run(self, qctx, command, space, job)
        finally:
            with lk:
                live["n"] -= 1
                if job is not None:
                    job._in_run = False

    JobManager._run = counting_run
    try:
        get_config().set_dynamic("max_concurrent_admin_jobs", 2)
        jids = []
        jl = threading.Lock()

        def submitter(k):
            s2 = eng.new_session()
            eng.execute(s2, "USE jr")
            for _ in range(10):
                rs = eng.execute(s2, "SUBMIT JOB STATS")
                assert rs.error is None
                with jl:
                    jids.append(rs.data.rows[0][0])

        def stopper():
            for _ in range(30):
                with jl:
                    pick = list(jids[-4:])
                for jid in pick:
                    eng.execute(s, f"STOP JOB {jid}")
                time.sleep(0.0005)

        def recoverer():
            for _ in range(10):
                eng.execute(s, "RECOVER JOB")
                time.sleep(0.002)

        with racecheck.race_amplifier():
            ts = ([threading.Thread(target=submitter, args=(k,))
                   for k in range(3)]
                  + [threading.Thread(target=stopper),
                     threading.Thread(target=recoverer)])
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        assert mgr.wait(timeout=30)
        assert not live["concurrent_self"], "job ran concurrently with itself"
        assert live["max"] <= 2, live["max"]
        for j in mgr.jobs.values():
            assert j.status in ("FINISHED", "STOPPED", "FAILED"), \
                (j.job_id, j.status)
    finally:
        JobManager._run = orig_run
        get_config().set_dynamic("max_concurrent_admin_jobs", 2)
