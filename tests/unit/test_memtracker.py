"""Memory tracker: per-query budget + kill-on-exceed (SURVEY §2 row 5)."""
import pytest

from nebula_tpu.exec.engine import QueryEngine
from nebula_tpu.graphstore.store import GraphStore
from nebula_tpu.utils.config import get_config
from nebula_tpu.utils.memtracker import MemoryExceeded, MemoryTracker


def _dense_graph(n=40):
    store = GraphStore()
    store.create_space("mt", partition_num=2, vid_type="INT64")
    store.catalog.create_tag("mt", "P", [])
    store.catalog.create_edge("mt", "E", [])
    for i in range(n):
        store.insert_vertex("mt", i, "P", {})
    # complete-ish digraph: variable-length MATCH explodes combinatorially
    for i in range(n):
        for j in range(n):
            if i != j:
                store.insert_edge("mt", i, "E", j, 0, {})
    return store


def test_tracker_charges_and_raises():
    tr = MemoryTracker(limit=1000)
    tr.charge(500)
    with pytest.raises(MemoryExceeded):
        tr.charge(600)


def test_runaway_match_killed_cleanly():
    store = _dense_graph(40)
    eng = QueryEngine(store)
    s = eng.new_session()
    eng.execute(s, "USE mt")
    cfg = get_config()
    old = cfg.get("query_memory_limit_bytes")
    cfg.set_dynamic("query_memory_limit_bytes", 2_000_000)
    try:
        rs = eng.execute(s, "MATCH (a:P)-[e:E*1..6]->(b) RETURN count(*)")
        assert rs.error is not None
        assert "memory exceeded" in rs.error
    finally:
        cfg.set_dynamic("query_memory_limit_bytes", old)


def test_normal_query_unaffected():
    store = _dense_graph(10)
    eng = QueryEngine(store)
    s = eng.new_session()
    eng.execute(s, "USE mt")
    rs = eng.execute(s, "GO FROM 1 OVER E YIELD dst(edge)")
    assert rs.error is None
    assert len(rs.data.rows) == 9
