"""Failpoint registry + retry/backoff/breaker primitives (ISSUE 5).

The deterministic fault-injection layer everything in tests/chaos/
stands on: spec parsing, action chains, seeded schedules, the cancel
context, equal-jitter backoff, and the per-peer circuit breaker.
"""
import random
import threading
import time

import pytest

from nebula_tpu.cluster.rpc import (CircuitBreaker, deadline_sleep,
                                    retry_backoff)
from nebula_tpu.utils import cancel
from nebula_tpu.utils.failpoints import (ConnectionKilled, FailpointError,
                                         FailpointRegistry, FaultSchedule,
                                         _parse_spec)


# -- spec parsing -----------------------------------------------------------


def test_parse_spec_chain():
    assert _parse_spec("2*off->1*raise(boom)") == \
        [[2, "off", None], [1, "raise", "boom"]]
    assert _parse_spec("delay(0.25)") == [[1, "delay", 0.25]]
    assert _parse_spec("delay") == [[1, "delay", 0.05]]
    assert _parse_spec("-1*kill_conn") == [[-1, "kill_conn", None]]


@pytest.mark.parametrize("bad", ["", "nope", "2*", "raise(", "3*frob"])
def test_parse_spec_rejects_garbage(bad):
    with pytest.raises(ValueError):
        _parse_spec(bad)


# -- registry behavior ------------------------------------------------------


def test_unarmed_hit_is_noop():
    reg = FailpointRegistry()
    reg.hit("never:armed")          # no raise, no counter
    assert reg.hit_count("never:armed") == 0


def test_chain_counts_and_exhaustion():
    reg = FailpointRegistry()
    reg.arm("x", "2*off->1*raise(boom)")
    reg.hit("x")
    reg.hit("x")                    # two skipped
    with pytest.raises(FailpointError, match="boom"):
        reg.hit("x")
    # chain exhausted → site disarmed, further hits are no-ops
    reg.hit("x")
    assert "x" not in reg.armed()
    assert reg.hit_count("x") == 3  # the post-disarm hit doesn't count


def test_forever_term_never_exhausts():
    reg = FailpointRegistry()
    reg.arm("x", "-1*raise")
    for _ in range(5):
        with pytest.raises(FailpointError):
            reg.hit("x")
    assert "x" in reg.armed()


def test_kill_conn_raises_connection_killed():
    reg = FailpointRegistry()
    reg.arm("x", "kill_conn")
    with pytest.raises(ConnectionKilled):
        reg.hit("x")


def test_delay_sleeps():
    reg = FailpointRegistry()
    reg.arm("x", "delay(0.05)")
    t0 = time.monotonic()
    reg.hit("x")
    assert time.monotonic() - t0 >= 0.04


def test_scoped_restores_armed_set():
    reg = FailpointRegistry()
    reg.arm("keep", "-1*off")
    with reg.scoped():
        reg.arm("temp", "-1*raise")
        reg.disarm("keep")
        assert reg.armed() == ["temp"]
    assert reg.armed() == ["keep"]


def test_env_arming(monkeypatch):
    monkeypatch.setenv("NEBULA_FAILPOINTS",
                       "a:b=raise(x); c:d=2*off->delay(0.1)")
    reg = FailpointRegistry()
    assert reg.armed() == ["a:b", "c:d"]
    with pytest.raises(FailpointError, match="x"):
        reg.hit("a:b")


# -- seeded schedules -------------------------------------------------------


def _fire_pattern(seed, hits=200, p=0.25):
    reg = FailpointRegistry()
    FaultSchedule(seed, [{"fp": "s", "action": "raise", "p": p}]).arm(reg)
    pat = []
    for _ in range(hits):
        try:
            reg.hit("s")
            pat.append(0)
        except FailpointError:
            pat.append(1)
    return pat


def test_schedule_is_deterministic_per_seed():
    a, b = _fire_pattern(7), _fire_pattern(7)
    assert a == b
    assert sum(a) > 0              # it does fire
    assert _fire_pattern(8) != a   # and the seed matters


def test_schedule_after_and_max():
    reg = FailpointRegistry()
    sched = FaultSchedule(1, [{"fp": "s", "action": "raise",
                               "p": 1.0, "after": 3, "max": 2}])
    sched.arm(reg)
    fired = 0
    for _ in range(10):
        try:
            reg.hit("s")
        except FailpointError:
            fired += 1
    assert fired == 2
    assert sched.fired == {"s": 2}


def test_schedule_key_filter():
    reg = FailpointRegistry()
    FaultSchedule(1, [{"fp": "s", "action": "raise", "p": 1.0,
                       "key": "meta"}]).arm(reg)
    reg.hit("s", key="storage/p3")          # filtered out
    with pytest.raises(FailpointError):
        reg.hit("s", key="meta")
    # the decision stream stays aligned with the hit index: the
    # filtered hit consumed draw #0, the firing one draw #1
    assert reg.hit_count("s") == 2


def test_schedule_disarm():
    reg = FailpointRegistry()
    sched = FaultSchedule(1, [{"fp": "s", "action": "raise", "p": 1.0}])
    sched.arm(reg)
    sched.disarm(reg)
    reg.hit("s")                    # disarmed: no raise


# -- backoff + deadline sleep -----------------------------------------------


def test_retry_backoff_equal_jitter_bounds():
    rng = random.Random(3)
    for attempt in range(8):
        d = min(2.0, 0.05 * (2 ** attempt))
        for _ in range(50):
            v = retry_backoff(attempt, rng=rng)
            assert d / 2 <= v <= d


def test_deadline_sleep_clamps_to_budget():
    with cancel.use_cancel(deadline=time.monotonic() + 0.05):
        t0 = time.monotonic()
        deadline_sleep(5.0)
        assert time.monotonic() - t0 < 0.5


# -- cancel context ---------------------------------------------------------


def test_cancel_check_noop_without_context():
    cancel.check()
    assert cancel.remaining() is None


def test_cancel_deadline_and_kill():
    with cancel.use_cancel(deadline=time.monotonic() - 1):
        with pytest.raises(cancel.DeadlineExceeded):
            cancel.check()
    ev = threading.Event()
    with cancel.use_cancel(kill=ev):
        cancel.check()
        ev.set()
        with pytest.raises(cancel.QueryKilled):
            cancel.check()


def test_cancel_nesting_inner_never_loosens():
    outer = time.monotonic() + 1.0
    with cancel.use_cancel(deadline=outer):
        with cancel.use_cancel(deadline=outer + 100):
            assert cancel.current_deadline() == outer
        with cancel.use_cancel(deadline=outer - 0.5):
            assert cancel.current_deadline() == outer - 0.5
        assert cancel.current_deadline() == outer
    assert cancel.current_deadline() is None


# -- circuit breaker --------------------------------------------------------


def test_breaker_trips_after_k_failures_and_half_opens():
    from nebula_tpu.utils.config import get_config
    get_config().set_dynamic("breaker_failure_threshold", 3)
    get_config().set_dynamic("breaker_reset_secs", 0.05)
    try:
        br = CircuitBreaker("peer")
        for _ in range(3):
            assert br.allow()
            br.record_failure()
        assert br.state == "open"
        assert not br.allow()               # short-circuit while open
        time.sleep(0.06)
        assert br.allow()                   # ONE half-open probe
        assert not br.allow()               # second caller short-circuits
        br.record_failure()                 # probe failed → re-open
        assert br.state == "open"
        time.sleep(0.06)
        assert br.allow()
        br.record_success()                 # probe ok → closed
        assert br.state == "closed" and br.failures == 0
        assert br.allow()
    finally:
        get_config().set_dynamic("breaker_failure_threshold", 5)
        get_config().set_dynamic("breaker_reset_secs", 2.0)


def test_breaker_success_resets_failure_streak():
    br = CircuitBreaker("peer")
    for _ in range(4):
        br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"     # streak broken: 1 < K


def test_breaker_abandoned_probe_releases_slot():
    """A half-open probe that exits via a non-transport path (killed
    statement, FrameTooLarge) must free the probe slot — a latched
    `_probing` would short-circuit the peer forever."""
    br = CircuitBreaker("peer")
    br.state, br.opened_at = "open", time.monotonic() - 10
    assert br.allow()               # admitted as THE probe
    assert not br.allow()           # slot taken
    br.release_probe()              # abandoned without a verdict
    assert br.state == "half_open"
    assert br.allow()               # fresh probe admitted
    br.record_success()
    assert br.state == "closed"


def test_breaker_short_circuit_does_not_record_failure():
    """A call denied by an open/probing breaker never left the process:
    it must not count as a peer failure (that would clear another
    thread's in-flight probe and re-trip the breaker on nothing)."""
    from nebula_tpu.cluster.rpc import (RpcClient, RpcNeverSentError,
                                        breaker_for, reset_breakers)
    from nebula_tpu.utils.config import get_config
    reset_breakers()
    get_config().set_dynamic("breaker_reset_secs", 0.01)
    try:
        cl = RpcClient("127.0.0.1", 9, retries=0)   # nothing listens
        br = breaker_for("127.0.0.1:9")
        br.state, br.opened_at = "open", time.monotonic() - 1.0
        assert br.allow()           # this thread holds the probe
        assert br.state == "half_open" and br._probing
        with pytest.raises(RpcNeverSentError, match="circuit open"):
            cl.call("meta.ready")   # denied: probe in flight
        # the in-flight probe and breaker state are untouched
        assert br.state == "half_open" and br._probing
    finally:
        reset_breakers()
        get_config().set_dynamic("breaker_reset_secs", 2.0)
