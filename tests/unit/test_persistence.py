"""Checkpoint/restore, TTL expiry (host + device parity + compaction),
and storaged restart from raft snapshot + WAL."""
import time

import pytest

from nebula_tpu.core.value import NULL
from nebula_tpu.exec import QueryEngine
from nebula_tpu.graphstore.schema import PropDef, PropType
from nebula_tpu.graphstore.store import GraphStore


def seeded_store():
    st = GraphStore()
    st.create_space("p", partition_num=4, vid_type="INT64")
    st.catalog.create_tag("p", "t", [PropDef("a", PropType.INT64)])
    st.catalog.create_edge("p", "e", [PropDef("w", PropType.INT64)])
    st.catalog.create_index("p", "i_a", "t", ["a"], is_edge=False)
    for i in range(20):
        st.insert_vertex("p", i, "t", {"a": i})
    for i in range(19):
        st.insert_edge("p", i, "e", i + 1, 0, {"w": i * 10})
    return st


def test_checkpoint_restore_roundtrip(tmp_path):
    st = seeded_store()
    st.checkpoint(str(tmp_path / "cp"))
    st2 = GraphStore.from_checkpoint(str(tmp_path / "cp"))
    assert st2.stats("p")["vertices"] == 20
    assert st2.stats("p")["edges"] == 19
    assert st2.get_vertex("p", 7) == {"t": {"a": 7}}
    assert st2.get_edge("p", 3, "e", 4) == {"w": 30}
    # dense ids survive (device-plane stability)
    sd1, sd2 = st.space("p"), st2.space("p")
    for v in range(20):
        assert sd1.dense_id(v) == sd2.dense_id(v)
    # derived index state rebuilt
    assert st2.index_scan("p", "i_a", [7]) == [7]
    # neighbors identical
    a = list(st.get_neighbors("p", list(range(20)), ["e"], "both"))
    b = list(st2.get_neighbors("p", list(range(20)), ["e"], "both"))
    assert a == b


def test_checkpoint_via_statement(tmp_path):
    from nebula_tpu.utils.config import get_config
    get_config().set_dynamic("snapshot_dir", str(tmp_path / "snaps"))
    try:
        eng = QueryEngine(seeded_store())
        s = eng.new_session()
        eng.execute(s, "USE p")
        r = eng.execute(s, "CREATE SNAPSHOT")
        assert r.ok, r.error
        name = r.data.rows[0][0]
        assert (tmp_path / "snaps" / name / "manifest.json").exists()
        r = eng.execute(s, "SHOW SNAPSHOTS")
        assert any(row[0] == name for row in r.data.rows)
        st2 = GraphStore.from_checkpoint(str(tmp_path / "snaps" / name))
        assert st2.stats("p")["edges"] == 19
        r = eng.execute(s, f"DROP SNAPSHOT {name}")
        assert r.ok, r.error
        assert not (tmp_path / "snaps" / name).exists()
    finally:
        get_config().dynamic_layer.pop("snapshot_dir", None)


def ttl_store():
    st = GraphStore()
    st.create_space("tt", partition_num=2, vid_type="INT64")
    st.catalog.create_tag("tt", "t", [PropDef("ts", PropType.INT64)],
                          ttl_col="ts", ttl_duration=100)
    st.catalog.create_edge("tt", "e", [PropDef("ts", PropType.INT64)],
                           ttl_col="ts", ttl_duration=100)
    now = int(time.time())
    st.insert_vertex("tt", 1, "t", {"ts": now})           # fresh
    st.insert_vertex("tt", 2, "t", {"ts": now - 1000})    # expired
    st.insert_vertex("tt", 3, "t", {"ts": NULL})          # never expires
    st.insert_edge("tt", 1, "e", 2, 0, {"ts": now})
    st.insert_edge("tt", 1, "e", 3, 0, {"ts": now - 1000})
    return st


def test_ttl_read_filtering():
    st = ttl_store()
    assert st.get_vertex("tt", 1) is not None
    assert st.get_vertex("tt", 2) is None          # expired → invisible
    assert st.get_vertex("tt", 3) is not None      # null ttl col
    nbrs = [(dst) for (_, _, _, dst, _, _) in
            st.get_neighbors("tt", [1], ["e"], "out")]
    assert nbrs == [2]
    assert st.get_edge("tt", 1, "e", 3) is None
    assert sorted(v for v, _, _ in st.scan_vertices("tt")) == [1, 3]


def test_ttl_device_parity():
    """The CSR snapshot must exclude expired rows like host reads do."""
    from nebula_tpu.graphstore.csr import build_snapshot
    st = ttl_store()
    snap = build_snapshot(st, "tt")
    blk = snap.block("e", "out")
    assert blk.total_edges() == 1
    tt = snap.tags["t"]
    assert int(tt.present.sum()) == 2


def test_ttl_compact_purges():
    st = ttl_store()
    removed = st.compact("tt")
    assert removed == 2                            # 1 vertex tag + 1 edge
    sd = st.space("tt")
    raw_vertices = sum(len(p.vertices) for p in sd.parts)
    assert raw_vertices == 2                       # vid 2 physically gone


def test_compact_job_statement():
    eng = QueryEngine(ttl_store())
    s = eng.new_session()
    eng.execute(s, "USE tt")
    r = eng.execute(s, "SUBMIT JOB COMPACT")
    assert r.ok, r.error
    assert eng.execute(s, "FETCH PROP ON t 2 YIELD t.ts").data.rows == []


def test_dropped_schema_rows_invisible_not_crashing():
    st = GraphStore()
    st.create_space("dx", partition_num=2, vid_type="INT64")
    st.catalog.create_tag("dx", "t", [PropDef("a", PropType.INT64)])
    st.catalog.create_tag("dx", "u", [PropDef("b", PropType.INT64)])
    st.catalog.create_edge("dx", "e", [])
    st.insert_vertex("dx", 1, "t", {"a": 1})
    st.insert_vertex("dx", 1, "u", {"b": 2})
    st.insert_edge("dx", 1, "e", 2, 0, {})
    st.catalog.drop_tag("dx", "t")
    st.catalog.drop_edge("dx", "e")
    # remaining tag still readable; dropped tag/edge rows invisible
    assert st.get_vertex("dx", 1) == {"u": {"b": 2}}
    assert list(st.scan_vertices("dx")) == [(1, "u", {"b": 2})]
    assert list(st.scan_edges("dx")) == []


def test_config_rejects_wrong_typed_values():
    from nebula_tpu.utils.config import ConfigError, get_config
    with pytest.raises(ConfigError):
        get_config().set_dynamic("slow_query_threshold_us", [1, 2])
    with pytest.raises(ConfigError):
        get_config().set_dynamic("enable_authorize", 3)


def test_storaged_restart_restores_from_wal(tmp_path):
    """Kill a storaged process-state; a fresh service over the same WAL
    dir must recover the part data (snapshot + replay)."""
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=1, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        cl = c.client()
        assert cl.execute(
            "CREATE SPACE rs(partition_num=2, vid_type=INT64)").error is None
        c.reconcile_storage()
        for q in ["USE rs", "CREATE TAG t(a int)",
                  "INSERT VERTEX t(a) VALUES 1:(11), 2:(22), 3:(33)"]:
            assert cl.execute(q).error is None
        ss = c.storageds[0]
        # simulate process death + restart: stop raft parts, wipe the
        # in-memory store, recreate parts from the same WAL dirs
        with ss.parts_lock:
            for p in ss.parts.values():
                p.stop()
            ss.parts.clear()
        from nebula_tpu.graphstore.store import GraphStore
        ss.store = GraphStore(catalog=ss.meta.catalog)
        ss.reconcile_parts()
        time.sleep(1.0)                  # re-election + replay
        rs = cl.execute("FETCH PROP ON t 2 YIELD t.a")
        assert rs.error is None and rs.data.rows == [[22]], \
            (rs.error, rs.data.rows)
    finally:
        c.stop()
