"""Minimal Gherkin-subset runner for the conformance features.

The reference's conformance gate is its TCK: Gherkin feature files
executed by pytest-bdd, comparing query results against expected tables
(tests/tck/features in the reference tree [UNVERIFIED — empty mount,
SURVEY §4]).  The reference's feature files could not be ported (mount
empty), so features/ holds a suite written from documented NebulaGraph
semantics, executed by this runner with the same step vocabulary:

    Feature: <name>
      Background:
        Given having executed:
          <triple-quoted statements>
      Scenario: <name>
        When executing query:
          <triple-quoted statement>
        Then the result should be, in any order:
          | col | col |
          | val | val |
        Then the result should be, in order: ...
        Then a SyntaxError should be raised
        Then a SemanticError should be raised
        Then an ExecutionError should be raised
        Then the result should be empty

Table cells are parsed as nGQL literal expressions (via YIELD); cells
that don't parse compare against the value's string form.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple


@dataclass
class Step:
    kind: str                     # exec | query | expect | error | empty
    text: str = ""
    table: Optional[List[List[str]]] = None
    ordered: bool = False
    error_kind: str = ""


@dataclass
class Scenario:
    feature: str
    name: str
    steps: List[Step] = field(default_factory=list)


def _parse_table(lines: List[str], i: int) -> Tuple[List[List[str]], int]:
    rows = []
    while i < len(lines) and lines[i].strip().startswith("|"):
        ln = lines[i].strip()
        cells = [c.strip() for c in ln.strip("|").split("|")]
        rows.append(cells)
        i += 1
    return rows, i


def _parse_docstring(lines: List[str], i: int) -> Tuple[str, int]:
    assert lines[i].strip() == '"""', f"expected docstring at line {i}"
    i += 1
    buf = []
    while lines[i].strip() != '"""':
        buf.append(lines[i])
        i += 1
    return "\n".join(buf).strip(), i + 1


def parse_feature(text: str) -> List[Scenario]:
    lines = text.splitlines()
    feature = ""
    background: List[Step] = []
    scenarios: List[Scenario] = []
    cur: Optional[Scenario] = None
    in_background = False
    i = 0
    while i < len(lines):
        ln = lines[i].strip()
        if not ln or ln.startswith("#"):
            i += 1
            continue
        if ln.startswith("Feature:"):
            feature = ln[len("Feature:"):].strip()
            i += 1
        elif ln.startswith("Background"):
            in_background = True
            i += 1
        elif ln.startswith("Scenario:"):
            in_background = False
            cur = Scenario(feature, ln[len("Scenario:"):].strip(),
                           list(background))
            scenarios.append(cur)
            i += 1
        elif re.match(r"(Given|And|When)\s+(having executed|executing query)",
                      ln):
            kind = "exec" if "having executed" in ln else "query"
            stext, i = _parse_docstring(lines, i + 1)
            step = Step(kind, stext)
            (background if in_background else cur.steps).append(step)
        elif ln.startswith("Then"):
            if "should be raised" in ln:
                m = re.search(r"an?\s+(\w+)\s+should be raised", ln)
                step = Step("error", error_kind=m.group(1))
                i += 1
            elif "should not be empty" in ln:
                step = Step("nonempty")
                i += 1
            elif "should contain" in ln:
                m = re.search(r'should contain\s+"([^"]+)"', ln)
                step = Step("contain", text=m.group(1))
                i += 1
            elif "should be empty" in ln:
                step = Step("empty")
                i += 1
            else:
                ordered = ", in order" in ln
                table, i = _parse_table(lines, i + 1)
                step = Step("expect", table=table, ordered=ordered)
            (background if in_background else cur.steps).append(step)
        else:
            raise ValueError(f"unparsed feature line {i}: {ln!r}")
    return scenarios


# -- execution --------------------------------------------------------------


_value_engine = None


def parse_cell(cell: str) -> Tuple[bool, Any]:
    """-> (parsed, value): literal-eval the cell through the engine's own
    expression pipeline; (False, None) if it isn't a literal."""
    global _value_engine
    from nebula_tpu.exec.engine import QueryEngine
    if _value_engine is None:
        _value_engine = QueryEngine()
        _value_engine._cell_sess = _value_engine.new_session()
    rs = _value_engine.execute(_value_engine._cell_sess, f"YIELD {cell}")
    if rs.error is None and len(rs.data.rows) == 1:
        return True, rs.data.rows[0][0]
    return False, None


def check_result(data, table: List[List[str]], ordered: bool) -> Optional[str]:
    """None if the DataSet matches the expected table, else a message."""
    from nebula_tpu.core.value import value_to_string, v_eq
    header, want_rows = table[0], table[1:]
    if list(data.column_names) != header:
        return f"columns {data.column_names} != {header}"
    if len(data.rows) != len(want_rows):
        return (f"row count {len(data.rows)} != {len(want_rows)}: "
                f"{data.rows!r}")

    def cell_match(want: str, got: Any) -> bool:
        ok, v = parse_cell(want)
        if ok and (v_eq(v, got) is True or repr(v) == repr(got)):
            return True
        # string-form fallback covers vertices/edges/paths/null kinds
        return value_to_string(got) == want

    def row_match(want, got) -> bool:
        return all(cell_match(w, g) for w, g in zip(want, got))

    if ordered:
        for w, g in zip(want_rows, data.rows):
            if not row_match(w, g):
                return f"row {g!r} != expected {w!r}"
        return None
    remaining = list(data.rows)
    for w in want_rows:
        hit = next((g for g in remaining if row_match(w, g)), None)
        if hit is None:
            return f"expected row {w!r} not found in {remaining!r}"
        remaining.remove(hit)
    return None


_JOB_STMT = re.compile(
    r"\b(SUBMIT\s+JOB|REBUILD\s+|BALANCE\b|RECOVER\s+JOB)", re.I)


def _settle_jobs(eng, sess) -> None:
    """Admin jobs are ASYNC (bounded worker pool, reference
    AdminTaskManager semantics); the reference TCK interleaves explicit
    'wait the job to finish' steps — this runner settles automatically
    after any job-submitting statement so scenarios stay declarative."""
    import time as _t
    qctx = getattr(eng, "qctx", None)
    if qctx is not None:
        mgr = getattr(qctx.store, "_job_manager", None)
        if mgr is not None:
            assert mgr.wait(timeout=60.0), "admin jobs did not settle"
        return
    # cluster client: poll the statement surface
    deadline = _t.time() + 60
    while _t.time() < deadline:
        rs = eng.execute(sess, "SHOW JOBS")
        if rs.error is not None or not any(
                r[2] in ("QUEUE", "RUNNING") for r in rs.data.rows):
            return
        _t.sleep(0.02)
    raise AssertionError("admin jobs did not settle (cluster)")


def run_scenario(scn: Scenario, make_engine) -> None:
    """Execute a scenario against a fresh engine; raises AssertionError
    with context on any mismatch."""
    eng, sess = make_engine()
    last = None
    for step in scn.steps:
        where = f"[{scn.feature} / {scn.name}]"
        if step.kind in ("exec", "query"):
            for stmt in [s for s in step.text.split(";") if s.strip()]:
                last = eng.execute(sess, stmt)
                if last.error is None and _JOB_STMT.search(stmt):
                    _settle_jobs(eng, sess)
                if step.kind == "exec":
                    assert last.error is None, \
                        f"{where} setup failed: {stmt!r}: {last.error}"
        elif step.kind == "error":
            assert last is not None and last.error is not None, \
                f"{where} expected {step.error_kind}, got success"
            assert step.error_kind.lower() in last.error.lower(), \
                f"{where} expected {step.error_kind}, got: {last.error}"
        elif step.kind == "empty":
            assert last.error is None, f"{where} error: {last.error}"
            assert last.data.rows == [], \
                f"{where} expected empty, got {last.data.rows!r}"
        elif step.kind == "nonempty":
            assert last.error is None, f"{where} error: {last.error}"
            assert last.data.rows, f"{where} expected non-empty result"
        elif step.kind == "contain":
            assert last.error is None, f"{where} error: {last.error}"
            assert any(step.text in str(c) for row in last.data.rows
                       for c in row), \
                f"{where} no cell contains {step.text!r}"
        elif step.kind == "expect":
            assert last.error is None, f"{where} error: {last.error}"
            msg = check_result(last.data, step.table, step.ordered)
            assert msg is None, f"{where} {msg}"
