"""Cluster-mode conformance: EVERY feature file runs against a real
multi-process-shaped LocalCluster — same assertions as the in-process
modes (VERDICT r1 item 9: cluster TCK must cover all features, not just
GO).

One cluster per feature file (startup is the expensive part); isolation
between scenarios is restored by dropping every space the scenario
created.  Spaces are created via the wrapped execute() below, which also
triggers storage part reconciliation the way the real deployment's
meta→storage heartbeat loop would.
"""
import glob
import os

import pytest

from .runner import parse_feature, run_scenario

_DIR = os.path.join(os.path.dirname(__file__), "features")
_FILES = sorted(glob.glob(os.path.join(_DIR, "*.feature")))


class _ClientEngine:
    """Adapts GraphClient to the (engine, session) protocol the runner
    drives."""

    def __init__(self, client, cluster):
        self.client = client
        self.cluster = cluster

    def execute(self, _session, stmt):
        rs = self.client.execute(stmt)
        if stmt.strip().upper().startswith("CREATE SPACE"):
            self.cluster.reconcile_storage()
        return rs


@pytest.mark.parametrize(
    "path", _FILES, ids=[os.path.basename(p).replace(".feature", "")
                         for p in _FILES])
def test_feature_on_cluster(path, tmp_path):
    with open(path) as f:
        scenarios = parse_feature(f.read())
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()
        eng = _ClientEngine(client, c)
        failures = []
        for scn in scenarios:
            if "[standalone]" in scn.name:
                # convention: scenarios needing per-scenario engine
                # state (deterministic job ids, standalone-only tasks)
                # run in the host/device modes only
                continue
            try:
                run_scenario(scn, lambda: (eng, None))
            except Exception as ex:     # noqa: BLE001 — aggregate, don't
                # abort the rest of the file on a non-assert failure
                failures.append(f"{scn.name}: {type(ex).__name__}: {ex}")
            finally:
                rs = client.execute("SHOW SPACES")
                if rs.error is None:
                    for (name,) in rs.data.rows:
                        client.execute(f"DROP SPACE IF EXISTS {name}")
                rs = client.execute("SHOW USERS")
                if rs.error is None:
                    for (name,) in rs.data.rows:
                        if name != "root":
                            client.execute(f"DROP USER IF EXISTS {name}")
        assert not failures, (
            f"{len(failures)}/{len(scenarios)} scenarios failed:\n"
            + "\n".join(failures))
    finally:
        c.stop()
