"""Cluster-mode conformance: the GO feature runs scenario-by-scenario
against a real multi-process-shaped LocalCluster (fresh cluster per
scenario for isolation) — same assertions as the in-process modes."""
import glob
import os

import pytest

from .runner import parse_feature, run_scenario

_DIR = os.path.join(os.path.dirname(__file__), "features")
with open(os.path.join(_DIR, "go.feature")) as _f:
    _SCN = parse_feature(_f.read())


class _ClientEngine:
    """Adapts GraphClient to the (engine, session) protocol the runner
    drives."""

    def __init__(self, client):
        self.client = client

    def execute(self, _session, stmt):
        return self.client.execute(stmt)


@pytest.mark.parametrize(
    "scn", _SCN, ids=[s.name.replace(" ", "_") for s in _SCN])
def test_go_feature_on_cluster(scn, tmp_path):
    from nebula_tpu.cluster.launcher import LocalCluster
    c = LocalCluster(n_meta=1, n_storage=2, n_graph=1,
                     data_dir=str(tmp_path))
    try:
        client = c.client()

        # cluster spaces need storage parts reconciled after CREATE SPACE;
        # wrap execute to trigger reconcile on DDL
        class _E(_ClientEngine):
            def execute(self, sess, stmt):
                rs = super().execute(sess, stmt)
                if stmt.strip().upper().startswith("CREATE SPACE"):
                    c.reconcile_storage()
                return rs

        run_scenario(scn, lambda: (_E(client), None))
    finally:
        c.stop()
