Feature: FETCH, LOOKUP, and index semantics

  Background:
    Given having executed:
      """
      CREATE SPACE fl(partition_num=4, vid_type=INT64);
      USE fl;
      CREATE TAG city(name string, pop int);
      CREATE EDGE road(len int);
      CREATE TAG INDEX i_pop ON city(pop);
      CREATE EDGE INDEX i_len ON road(len);
      INSERT VERTEX city(name, pop) VALUES 1:("sf", 800), 2:("la", 4000), 3:("ny", 8000);
      INSERT EDGE road(len) VALUES 1->2:(380), 2->3:(2800), 1->3:(2900)
      """

  Scenario: fetch vertex props
    When executing query:
      """
      FETCH PROP ON city 2 YIELD city.name AS n, city.pop AS p
      """
    Then the result should be, in any order:
      | n    | p    |
      | "la" | 4000 |

  Scenario: fetch missing vertex is empty
    When executing query:
      """
      FETCH PROP ON city 99 YIELD city.name
      """
    Then the result should be empty

  Scenario: fetch edge props
    When executing query:
      """
      FETCH PROP ON road 1->2 YIELD properties(edge).len AS l
      """
    Then the result should be, in any order:
      | l   |
      | 380 |

  Scenario: lookup range scan
    When executing query:
      """
      LOOKUP ON city WHERE city.pop >= 800 AND city.pop < 8000 YIELD id(vertex) AS id, city.name AS n
      """
    Then the result should be, in any order:
      | id | n    |
      | 1  | "sf" |
      | 2  | "la" |

  Scenario: lookup on edge index
    When executing query:
      """
      LOOKUP ON road WHERE road.len > 1000 YIELD src(edge) AS s, dst(edge) AS d
      """
    Then the result should be, in any order:
      | s | d |
      | 2 | 3 |
      | 1 | 3 |

  Scenario: lookup without any index errors
    When executing query:
      """
      LOOKUP ON road2 WHERE road2.x > 0
      """
    Then a SemanticError should be raised

  Scenario: update then fetch sees new value
    When executing query:
      """
      UPDATE VERTEX ON city 1 SET pop = 900;
      FETCH PROP ON city 1 YIELD city.pop AS p
      """
    Then the result should be, in any order:
      | p   |
      | 900 |

  Scenario: updated value visible through the index
    When executing query:
      """
      UPDATE VERTEX ON city 1 SET pop = 7777;
      LOOKUP ON city WHERE city.pop == 7777 YIELD id(vertex) AS id
      """
    Then the result should be, in any order:
      | id |
      | 1  |

  Scenario: delete removes from traversal and index
    When executing query:
      """
      DELETE VERTEX 3 WITH EDGE;
      LOOKUP ON city WHERE city.pop >= 8000 YIELD id(vertex)
      """
    Then the result should be empty

  Scenario: implicit aggregation in lookup yield
    When executing query:
      """
      LOOKUP ON city WHERE city.pop > 500 YIELD count(*) AS n
      """
    Then the result should be, in any order:
      | n |
      | 3 |

  Scenario: implicit grouped aggregation in lookup on edges
    When executing query:
      """
      LOOKUP ON road WHERE road.len > 1000 YIELD src(edge) AS s, count(*) AS n
      | ORDER BY $-.s
      """
    Then the result should be, in order:
      | s | n |
      | 1 | 1 |
      | 2 | 1 |
