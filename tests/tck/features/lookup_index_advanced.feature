Feature: Index scan boundaries and compound hints

  Background:
    Given having executed:
      """
      CREATE SPACE li(partition_num=4, vid_type=INT64);
      USE li;
      CREATE TAG person(city string, age int, score int);
      CREATE TAG INDEX i_city_age ON person(city, age);
      CREATE TAG INDEX i_score ON person(score);
      INSERT VERTEX person(city, age, score) VALUES
        1:("oslo", 20, 5), 2:("oslo", 30, 15), 3:("oslo", 40, 25),
        4:("bergen", 30, 35), 5:("bergen", 50, 45), 6:("tromso", 30, 55)
      """

  Scenario: exclusive lower bound
    When executing query:
      """
      LOOKUP ON person WHERE person.score > 25 YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 4 |
      | 5 |
      | 6 |

  Scenario: inclusive lower bound
    When executing query:
      """
      LOOKUP ON person WHERE person.score >= 25 YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 3 |
      | 4 |
      | 5 |
      | 6 |

  Scenario: two sided range
    When executing query:
      """
      LOOKUP ON person WHERE person.score > 5 AND person.score < 45
      YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 2 |
      | 3 |
      | 4 |

  Scenario: compound index equality prefix plus range
    When executing query:
      """
      LOOKUP ON person WHERE person.city == "oslo" AND person.age > 20
      YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 2 |
      | 3 |

  Scenario: equality prefix alone uses the compound index
    When executing query:
      """
      LOOKUP ON person WHERE person.city == "bergen"
      YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 4 |
      | 5 |

  Scenario: residual predicate filters index hits
    When executing query:
      """
      LOOKUP ON person WHERE person.city == "oslo" AND person.score > 10
      YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 2 |
      | 3 |

  Scenario: explain shows the chosen compound index
    When executing query:
      """
      EXPLAIN LOOKUP ON person WHERE person.city == "oslo" AND person.age > 20
      YIELD id(vertex) AS v
      """
    Then the result should contain "i_city_age"

  Scenario: yield indexed props without a filter
    When executing query:
      """
      LOOKUP ON person YIELD id(vertex) AS v, person.age AS a | ORDER BY $-.v | LIMIT 2
      """
    Then the result should be, in order:
      | v | a  |
      | 1 | 20 |
      | 2 | 30 |

  Scenario: index backfills existing rows on rebuild [standalone]
    When executing query:
      """
      CREATE TAG late(x int);
      INSERT VERTEX late(x) VALUES 7:(70), 8:(80);
      CREATE TAG INDEX i_late ON late(x);
      REBUILD TAG INDEX i_late;
      LOOKUP ON late WHERE late.x >= 70 YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 7 |
      | 8 |

  Scenario: a fresh index does not see pre-existing rows before rebuild
    When executing query:
      """
      CREATE TAG cold(x int);
      INSERT VERTEX cold(x) VALUES 9:(90);
      CREATE TAG INDEX i_cold ON cold(x);
      LOOKUP ON cold WHERE cold.x == 90 YIELD id(vertex) AS v
      """
    Then the result should be empty

  Scenario: writes after index creation are visible without rebuild
    When executing query:
      """
      CREATE TAG warm(x int);
      CREATE TAG INDEX i_warm ON warm(x);
      INSERT VERTEX warm(x) VALUES 10:(100);
      LOOKUP ON warm WHERE warm.x == 100 YIELD id(vertex) AS v
      """
    Then the result should be, in order:
      | v  |
      | 10 |

  Scenario: dropping the only index breaks lookup again
    When executing query:
      """
      DROP TAG INDEX i_score;
      DROP TAG INDEX i_city_age;
      LOOKUP ON person WHERE person.score > 0 YIELD id(vertex)
      """
    Then a SemanticError should be raised
