Feature: Per-statement semantic validation errors

  Background:
    Given having executed:
      """
      CREATE SPACE ve(partition_num=4, vid_type=INT64);
      USE ve;
      CREATE TAG person(name string, age int);
      CREATE EDGE knows(since int)
      """

  Scenario: go over an unknown edge type
    When executing query:
      """
      GO 1 STEPS FROM 1 OVER follows YIELD dst(edge)
      """
    Then a SemanticError should be raised

  Scenario: go with inverted step range
    When executing query:
      """
      GO 3 TO 1 STEPS FROM 1 OVER knows YIELD dst(edge)
      """
    Then a SemanticError should be raised

  Scenario: fetch prop on an unknown tag
    When executing query:
      """
      FETCH PROP ON animal 1 YIELD vertex AS v
      """
    Then a SemanticError should be raised

  Scenario: fetch prop on an unknown edge
    When executing query:
      """
      FETCH PROP ON likes 1->2 YIELD edge AS e
      """
    Then a SemanticError should be raised

  Scenario: lookup on an unknown schema
    When executing query:
      """
      LOOKUP ON nothing YIELD id(vertex)
      """
    Then a SemanticError should be raised

  Scenario: find path over an unknown edge
    When executing query:
      """
      FIND SHORTEST PATH FROM 1 TO 2 OVER follows UPTO 3 STEPS YIELD path AS p
      """
    Then a SemanticError should be raised

  Scenario: match with an unknown edge type
    When executing query:
      """
      MATCH (a)-[e:follows]->(b) RETURN e
      """
    Then a SemanticError should be raised

  Scenario: match with an unknown tag label
    When executing query:
      """
      MATCH (a:animal) RETURN a
      """
    Then a SemanticError should be raised

  Scenario: match with inverted hop bounds
    When executing query:
      """
      MATCH (a)-[e:knows*3..1]->(b) RETURN e
      """
    Then a SemanticError should be raised

  Scenario: insert vertex with an unknown property
    When executing query:
      """
      INSERT VERTEX person(name, height) VALUES 1:("Ann", 170)
      """
    Then a SemanticError should be raised

  Scenario: insert vertex with wrong value arity
    When executing query:
      """
      INSERT VERTEX person(name, age) VALUES 1:("Ann")
      """
    Then a SemanticError should be raised

  Scenario: insert edge with an unknown property
    When executing query:
      """
      INSERT EDGE knows(weight) VALUES 1->2:(5)
      """
    Then a SemanticError should be raised

  Scenario: insert edge with wrong value arity
    When executing query:
      """
      INSERT EDGE knows(since) VALUES 1->2:(2015, 7)
      """
    Then a SemanticError should be raised

  Scenario: update with an unknown property
    When executing query:
      """
      UPDATE VERTEX ON person 1 SET height = 170
      """
    Then a SemanticError should be raised

  Scenario: update on an unknown schema
    When executing query:
      """
      UPDATE VERTEX ON animal 1 SET age = 4
      """
    Then a SemanticError should be raised

  Scenario: create index on an unknown schema
    When executing query:
      """
      CREATE TAG INDEX ai ON animal(age)
      """
    Then a SemanticError should be raised

  Scenario: create index on an unknown property
    When executing query:
      """
      CREATE TAG INDEX hi ON person(height)
      """
    Then a SemanticError should be raised

  Scenario: create index with a duplicate field
    When executing query:
      """
      CREATE TAG INDEX di ON person(age, age)
      """
    Then a SemanticError should be raised

  Scenario: create tag with duplicate properties
    When executing query:
      """
      CREATE TAG t2(a int, a string)
      """
    Then a SemanticError should be raised

  Scenario: ttl column must exist
    When executing query:
      """
      CREATE TAG t3(a int) TTL_DURATION = 5, TTL_COL = "missing"
      """
    Then a SemanticError should be raised

  Scenario: ttl column must be integer typed
    When executing query:
      """
      CREATE TAG t4(a string) TTL_DURATION = 5, TTL_COL = "a"
      """
    Then a SemanticError should be raised

  Scenario: get subgraph over an unknown edge
    When executing query:
      """
      GET SUBGRAPH 2 STEPS FROM 1 OUT follows YIELD VERTICES AS v
      """
    Then a SemanticError should be raised

  Scenario: delete tag of an unknown tag
    When executing query:
      """
      DELETE TAG animal FROM 1
      """
    Then a SemanticError should be raised

  Scenario: boolean operator over a non-boolean operand
    When executing query:
      """
      GO 1 STEPS FROM 1 OVER knows WHERE knows.since AND true YIELD dst(edge)
      """
    Then a SemanticError should be raised

  Scenario: comparison between string and int literals
    When executing query:
      """
      YIELD 1 < "x" AS bad
      """
    Then a SemanticError should be raised

  Scenario: unary minus over a string
    When executing query:
      """
      YIELD -("abc") AS bad
      """
    Then a SemanticError should be raised

  Scenario: arithmetic plus between int and bool
    When executing query:
      """
      YIELD 1 + true AS bad
      """
    Then a SemanticError should be raised

  Scenario: case when condition must be boolean
    When executing query:
      """
      YIELD CASE WHEN 7 THEN 1 ELSE 2 END AS bad
      """
    Then a SemanticError should be raised
