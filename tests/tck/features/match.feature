Feature: MATCH patterns

  Background:
    Given having executed:
      """
      CREATE SPACE mm(partition_num=4, vid_type=FIXED_STRING(20));
      USE mm;
      CREATE TAG person(name string, age int);
      CREATE EDGE knows(since int);
      INSERT VERTEX person(name, age) VALUES "a":("Ann", 30), "b":("Bob", 25), "c":("Cat", 41), "d":("Dan", 19);
      INSERT EDGE knows(since) VALUES "a"->"b":(2010), "b"->"c":(2015), "c"->"d":(2018), "a"->"c":(2012)
      """

  Scenario: node scan with label filter
    When executing query:
      """
      MATCH (v:person) WHERE v.person.age > 28 RETURN v.person.name AS n ORDER BY n
      """
    Then the result should be, in order:
      | n     |
      | "Ann" |
      | "Cat" |

  Scenario: one hop pattern with edge filter
    When executing query:
      """
      MATCH (a:person)-[e:knows]->(b) WHERE e.since >= 2012 RETURN a.person.name AS s, b.person.name AS d
      """
    Then the result should be, in any order:
      | s     | d     |
      | "Bob" | "Cat" |
      | "Cat" | "Dan" |
      | "Ann" | "Cat" |

  Scenario: variable length path
    When executing query:
      """
      MATCH (a:person)-[e:knows*1..2]->(b) WHERE id(a) == "a" RETURN id(b) AS d
      """
    Then the result should be, in any order:
      | d   |
      | "b" |
      | "c" |
      | "c" |
      | "d" |

  Scenario: aggregation with grouping
    When executing query:
      """
      MATCH (a:person)-[e:knows]->(b) RETURN a.person.name AS s, count(*) AS c ORDER BY s
      """
    Then the result should be, in order:
      | s     | c |
      | "Ann" | 2 |
      | "Bob" | 1 |
      | "Cat" | 1 |

  Scenario: limit and skip
    When executing query:
      """
      MATCH (v:person) RETURN v.person.name AS n ORDER BY n SKIP 1 LIMIT 2
      """
    Then the result should be, in order:
      | n     |
      | "Bob" |
      | "Cat" |

  Scenario: optional-style missing property is null
    When executing query:
      """
      MATCH (v:person) WHERE v.person.name == "Ann" RETURN v.person.nosuch AS x
      """
    Then the result should be, in order:
      | x               |
      | __UNKNOWN_PROP__ |
