Feature: Path finding and subgraph advanced

  Background:
    Given having executed:
      """
      CREATE SPACE pa(partition_num=4, vid_type=FIXED_STRING(8));
      USE pa;
      CREATE TAG spot(name string);
      CREATE EDGE road(len int);
      CREATE EDGE rail(speed int);
      INSERT VERTEX spot(name) VALUES "a":("A"), "b":("B"), "c":("C"), "d":("D"), "e":("E"), "f":("F");
      INSERT EDGE road(len) VALUES "a"->"b":(1), "b"->"c":(1), "a"->"c":(5), "c"->"d":(1), "d"->"a":(1), "b"->"e":(2);
      INSERT EDGE rail(speed) VALUES "a"->"d":(300), "d"->"e":(200)
      """

  Scenario: shortest path length
    When executing query:
      """
      FIND SHORTEST PATH FROM "a" TO "d" OVER road YIELD path AS p | YIELD length($-.p) AS l
      """
    Then the result should be, in order:
      | l |
      | 2 |

  Scenario: shortest path over multiple edge types
    When executing query:
      """
      FIND SHORTEST PATH FROM "a" TO "e" OVER road, rail YIELD path AS p | YIELD length($-.p) AS l
      """
    Then the result should be, in any order:
      | l |
      | 2 |
      | 2 |

  Scenario: all paths up to 3 steps
    When executing query:
      """
      FIND ALL PATH FROM "a" TO "c" OVER road UPTO 3 STEPS YIELD path AS p | YIELD length($-.p) AS l | ORDER BY $-.l
      """
    Then the result should be, in order:
      | l |
      | 1 |
      | 2 |

  Scenario: noloop path excludes cycles back through start
    When executing query:
      """
      FIND NOLOOP PATH FROM "a" TO "d" OVER road UPTO 5 STEPS YIELD path AS p | YIELD length($-.p) AS l | ORDER BY $-.l
      """
    Then the result should be, in order:
      | l |
      | 2 |
      | 3 |

  Scenario: path to unreachable target is empty
    When executing query:
      """
      FIND SHORTEST PATH FROM "e" TO "a" OVER road YIELD path AS p
      """
    Then the result should be empty

  Scenario: shortest path to multiple targets
    When executing query:
      """
      FIND SHORTEST PATH FROM "a" TO "c", "e" OVER road YIELD path AS p | YIELD length($-.p) AS l | ORDER BY $-.l
      """
    Then the result should be, in order:
      | l |
      | 1 |
      | 2 |

  Scenario: bidirect shortest path
    When executing query:
      """
      FIND SHORTEST PATH FROM "e" TO "a" OVER road BIDIRECT YIELD path AS p | YIELD length($-.p) AS l
      """
    Then the result should be, in order:
      | l |
      | 2 |

  Scenario: subgraph one step vertices
    When executing query:
      """
      GET SUBGRAPH 1 STEPS FROM "a" OUT road YIELD vertices AS nodes | YIELD size($-.nodes) AS n
      """
    Then the result should be, in order:
      | n |
      | 1 |
      | 2 |

  Scenario: subgraph with edges yield
    When executing query:
      """
      GET SUBGRAPH 1 STEPS FROM "a" OUT road YIELD vertices AS nodes, edges AS rels | YIELD size($-.rels) AS r
      """
    Then the result should be, in order:
      | r |
      | 2 |
      | 1 |

  Scenario: subgraph both directions includes incoming
    When executing query:
      """
      GET SUBGRAPH 1 STEPS FROM "a" BOTH road YIELD vertices AS nodes | YIELD size($-.nodes) AS n
      """
    Then the result should be, in order:
      | n |
      | 1 |
      | 3 |

  Scenario: subgraph zero steps is just the seed
    When executing query:
      """
      GET SUBGRAPH 0 STEPS FROM "a" YIELD vertices AS nodes | YIELD size($-.nodes) AS n
      """
    Then the result should be, in order:
      | n |
      | 1 |

  Scenario: path nodes and relationships functions
    When executing query:
      """
      FIND SHORTEST PATH FROM "a" TO "c" OVER road YIELD path AS p | YIELD size(nodes($-.p)) AS n, size(relationships($-.p)) AS r
      """
    Then the result should be, in order:
      | n | r |
      | 2 | 1 |
  Scenario: all path with where on edge property
    When executing query:
      """
      FIND ALL PATH FROM "a" TO "c" OVER road WHERE road.len < 5 UPTO 3 STEPS YIELD path AS p | YIELD length($-.p) AS l
      """
    Then the result should be, in order:
      | l |
      | 2 |
