Feature: Schema DDL and admin statements

  Background:
    Given having executed:
      """
      CREATE SPACE sa(partition_num=4, vid_type=INT64);
      USE sa;
      CREATE TAG person(name string, age int);
      CREATE EDGE knows(since int)
      """

  Scenario: show tags and edges
    When executing query:
      """
      SHOW TAGS
      """
    Then the result should be, in any order:
      | Name     |
      | "person" |

  Scenario: describe tag lists fields
    When executing query:
      """
      DESCRIBE TAG person
      """
    Then the result should be, in any order:
      | Field  | Type     | Null  | Default |
      | "name" | "string" | "YES" | NULL    |
      | "age"  | "int64"  | "YES" | NULL    |

  Scenario: alter tag add column
    When executing query:
      """
      ALTER TAG person ADD (city string);
      INSERT VERTEX person(name, age, city) VALUES 1:("Ann", 30, "Oslo");
      FETCH PROP ON person 1 YIELD person.city AS c
      """
    Then the result should be, in order:
      | c      |
      | "Oslo" |

  Scenario: alter tag drop column
    When executing query:
      """
      ALTER TAG person DROP (age);
      INSERT VERTEX person(name) VALUES 2:("Bob");
      FETCH PROP ON person 2 YIELD person.name AS n
      """
    Then the result should be, in order:
      | n     |
      | "Bob" |

  Scenario: create tag if not exists is idempotent
    When executing query:
      """
      CREATE TAG IF NOT EXISTS person(name string);
      SHOW TAGS
      """
    Then the result should be, in any order:
      | Name     |
      | "person" |

  Scenario: duplicate create tag errors
    When executing query:
      """
      CREATE TAG person(x int)
      """
    Then an ExecutionError should be raised

  Scenario: drop tag removes it
    When executing query:
      """
      CREATE TAG tmp(x int);
      DROP TAG tmp;
      SHOW TAGS
      """
    Then the result should be, in any order:
      | Name     |
      | "person" |

  Scenario: create and show index
    When executing query:
      """
      CREATE TAG INDEX person_age ON person(age);
      SHOW TAG INDEXES
      """
    Then the result should be, in any order:
      | Index Name   | By Tag   | Columns |
      | "person_age" | "person" | ["age"] |

  Scenario: lookup via index after rebuild
    When executing query:
      """
      CREATE TAG INDEX person_age2 ON person(age);
      INSERT VERTEX person(name, age) VALUES 5:("Eve", 33), 6:("Fox", 20);
      REBUILD TAG INDEX person_age2;
      LOOKUP ON person WHERE person.age > 25 YIELD id(vertex) AS i
      """
    Then the result should be, in any order:
      | i |
      | 5 |

  Scenario: show spaces contains the space
    When executing query:
      """
      SHOW SPACES
      """
    Then the result should be, in any order:
      | Name |
      | "sa" |

  Scenario: describe edge
    When executing query:
      """
      DESCRIBE EDGE knows
      """
    Then the result should be, in any order:
      | Field   | Type    | Null  | Default |
      | "since" | "int64" | "YES" | NULL    |

  Scenario: show create tag roundtrip
    When executing query:
      """
      SHOW CREATE TAG person
      """
    Then the result should be, in any order:
      | Tag      | Create Tag                                                   |
      | "person" | "CREATE TAG `person` (`name` string NULL, `age` int64 NULL)" |

  Scenario: ttl on tag expires rows
    When executing query:
      """
      CREATE TAG session_t(started timestamp) TTL_DURATION = 1, TTL_COL = "started";
      SHOW TAGS
      """
    Then the result should be, in any order:
      | Name        |
      | "person"    |
      | "session_t" |

  Scenario: unknown space errors
    When executing query:
      """
      USE nosuchspace
      """
    Then a SemanticError should be raised

  Scenario: drop space removes it
    When executing query:
      """
      CREATE SPACE scratch(partition_num=2, vid_type=INT64);
      DROP SPACE scratch;
      SHOW SPACES
      """
    Then the result should be, in any order:
      | Name |
      | "sa" |

  Scenario: show stats lists per-tag and per-edge counts
    Given having executed:
      """
      CREATE SPACE stat2(partition_num=2, vid_type=INT64);
      USE stat2;
      CREATE TAG a();
      CREATE TAG b();
      CREATE EDGE e1();
      INSERT VERTEX a() VALUES 1:(), 2:();
      INSERT VERTEX b() VALUES 3:();
      INSERT EDGE e1() VALUES 1->2:(), 2->3:();
      SUBMIT JOB STATS
      """
    When executing query:
      """
      SHOW STATS
      """
    Then the result should be, in any order:
      | Type    | Name       | Count |
      | "Tag"   | "a"        | 2     |
      | "Tag"   | "b"        | 1     |
      | "Edge"  | "e1"       | 2     |
      | "Space" | "vertices" | 3     |
      | "Space" | "edges"    | 2     |
