Feature: Observability surface

  Background:
    Given having executed:
      """
      CREATE SPACE ob(partition_num=2, vid_type=INT64);
      USE ob;
      CREATE TAG P(a int);
      CREATE EDGE E(w int);
      INSERT VERTEX P(a) VALUES 1:(1), 2:(2), 3:(3);
      INSERT EDGE E(w) VALUES 1->2:(5), 2->3:(7)
      """

  Scenario: explain row format carries the yield expression
    When executing query:
      """
      EXPLAIN GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d
      """
    Then the result should contain "dst(edge)"

  Scenario: explain dot format emits a digraph
    When executing query:
      """
      EXPLAIN FORMAT="dot" GO FROM 1 OVER E YIELD dst(edge)
      """
    Then the result should contain "digraph"

  Scenario: explain of an unknown format errors
    When executing query:
      """
      EXPLAIN FORMAT="svg" GO FROM 1 OVER E YIELD dst(edge)
      """
    Then a SemanticError should be raised

  Scenario: show stats reflects deletes after a stats job
    When executing query:
      """
      DELETE VERTEX 3 WITH EDGE;
      SUBMIT JOB STATS;
      SHOW STATS
      """
    Then the result should be, in any order:
      | Type    | Name       | Count |
      | "Tag"   | "P"        | 2     |
      | "Edge"  | "E"        | 1     |
      | "Space" | "vertices" | 2     |
      | "Space" | "edges"    | 1     |

  Scenario: update configs takes effect live and reads back
    When executing query:
      """
      UPDATE CONFIGS minloglevel = 1;
      GET CONFIGS minloglevel
      """
    Then the result should be, in order:
      | Module  | Name          | Type  | Mode      | Value |
      | "graph" | "minloglevel" | "int" | "MUTABLE" | "1"   |

  Scenario: reset the flag for later scenarios
    When executing query:
      """
      UPDATE CONFIGS minloglevel = 0;
      GET CONFIGS minloglevel
      """
    Then the result should be, in order:
      | Module  | Name          | Type  | Mode      | Value |
      | "graph" | "minloglevel" | "int" | "MUTABLE" | "0"   |

  Scenario: updating an unknown config errors
    When executing query:
      """
      UPDATE CONFIGS never_a_flag = 1
      """
    Then an ExecutionError should be raised

  Scenario: show traces surfaces the per-statement trace store
    When executing query:
      """
      GO FROM 1 OVER E YIELD dst(edge) AS d;
      SHOW TRACES
      """
    Then the result should contain "query:Go"

  Scenario: traces carry executor span counts
    When executing query:
      """
      GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d;
      SHOW TRACES
      """
    Then the result should not be empty

  Scenario: show charset and collation answer
    When executing query:
      """
      SHOW CHARSET
      """
    Then the result should be, in order:
      | Charset | Description     | Default collation | Maxlen |
      | "utf8"  | "UTF-8 Unicode" | "utf8_bin"        | 4      |

  Scenario: describe space reports its shape
    When executing query:
      """
      DESCRIBE SPACE ob
      """
    Then the result should not be empty

  Scenario: show parts lists every partition
    When executing query:
      """
      SHOW PARTS
      """
    Then the result should not be empty
