Feature: Job manager and repartition task

  Background:
    Given having executed:
      """
      CREATE SPACE ja(partition_num=2, vid_type=INT64);
      USE ja;
      CREATE TAG P(a int);
      CREATE EDGE E(w int);
      CREATE TAG INDEX pa ON P(a);
      INSERT VERTEX P(a) VALUES 1:(10), 2:(20), 3:(30), 4:(40), 5:(50);
      INSERT EDGE E(w) VALUES 1->2:(1), 2->3:(2), 3->4:(3), 4->5:(4), 5->1:(5)
      """

  Scenario: stats job reports counts
    When executing query:
      """
      SUBMIT JOB STATS;
      SHOW STATS
      """
    Then the result should be, in any order:
      | Type    | Name       | Count |
      | "Tag"   | "P"        | 5     |
      | "Edge"  | "E"        | 5     |
      | "Space" | "vertices" | 5     |
      | "Space" | "edges"    | 5     |

  Scenario: compact job finishes [standalone]
    When executing query:
      """
      SUBMIT JOB COMPACT;
      SHOW JOB 1
      """
    Then the result should be, in order:
      | Job Id | Command   | Status     |
      | 1      | "compact" | "FINISHED" |

  Scenario: flush job finishes [standalone]
    When executing query:
      """
      SUBMIT JOB FLUSH;
      SHOW JOB 1
      """
    Then the result should be, in order:
      | Job Id | Command | Status     |
      | 1      | "flush" | "FINISHED" |

  Scenario: repartition keeps traversal results identical [standalone]
    When executing query:
      """
      SUBMIT JOB REPARTITION 8;
      GO 2 STEPS FROM 1 OVER E YIELD dst(edge) AS d
      """
    Then the result should be, in order:
      | d |
      | 3 |

  Scenario: repartition keeps index lookups working [standalone]
    When executing query:
      """
      SUBMIT JOB REPARTITION 4;
      LOOKUP ON P WHERE P.a > 25 YIELD id(vertex) AS v | ORDER BY $-.v
      """
    Then the result should be, in order:
      | v |
      | 3 |
      | 4 |
      | 5 |

  Scenario: repartition job records its result [standalone]
    When executing query:
      """
      SUBMIT JOB REPARTITION 8;
      SHOW JOB 1
      """
    Then the result should be, in order:
      | Job Id | Command         | Status     |
      | 1      | "repartition 8" | "FINISHED" |

  Scenario: unknown job command fails the job [standalone]
    When executing query:
      """
      SUBMIT JOB NO_SUCH_THING;
      SHOW JOB 1
      """
    Then the result should be, in order:
      | Job Id | Command         | Status   |
      | 1      | "no_such_thing" | "FAILED" |

  Scenario: show jobs lists every submitted job [standalone]
    When executing query:
      """
      SUBMIT JOB STATS;
      SUBMIT JOB COMPACT;
      SHOW JOBS
      """
    Then the result should be, in any order:
      | Job Id | Command   | Status     |
      | 1      | "stats"   | "FINISHED" |
      | 2      | "compact" | "FINISHED" |
