Feature: FIND PATH variants — WITH PROP, multi endpoints, direction

  Background:
    Given having executed:
      """
      CREATE SPACE pc(partition_num=2, vid_type=INT64);
      USE pc;
      CREATE TAG p(x int);
      CREATE EDGE r(w int);
      INSERT VERTEX p(x) VALUES 1:(10), 2:(20), 3:(30), 4:(40);
      INSERT EDGE r(w) VALUES 1->2:(5), 2->3:(7), 1->3:(9), 3->4:(1)
      """

  Scenario: shortest path with prop carries vertex properties
    When executing query:
      """
      FIND SHORTEST PATH WITH PROP FROM 1 TO 3 OVER r YIELD path AS p
      """
    Then the result should contain "x"

  Scenario: multi source and destination shortest paths
    When executing query:
      """
      FIND SHORTEST PATH FROM 1, 2 TO 3, 4 OVER r YIELD path AS p
      """
    Then the result should not be empty

  Scenario: reversed shortest path walks incoming edges
    When executing query:
      """
      FIND SHORTEST PATH FROM 3 TO 1 OVER r REVERSELY YIELD path AS p
      """
    Then the result should not be empty

  Scenario: reversed shortest path in the wrong direction is empty
    When executing query:
      """
      FIND SHORTEST PATH FROM 1 TO 3 OVER r REVERSELY YIELD path AS p
      """
    Then the result should be empty

  Scenario: bidirect shortest path ignores edge orientation
    When executing query:
      """
      FIND SHORTEST PATH FROM 1 TO 4 OVER r BIDIRECT YIELD path AS p
      """
    Then the result should not be empty

  Scenario: zero step subgraph is the source itself
    When executing query:
      """
      GET SUBGRAPH 0 STEPS FROM 1 YIELD VERTICES AS nodes
      """
    Then the result should not be empty
